"""Unified observability spine: metrics registry + per-task metrics +
structured event journal (ISSUE 1 tentpole).

The reference library explains *why* a query was slow through three
disconnected surfaces — the CUPTI profiler stream, the NVML monitor,
and RmmSpark's per-task retry/blocked-time accounting.  This package is
the spine that connects our analogs of those islands:

  * ``METRICS``  — process-wide :class:`MetricsRegistry` (counters,
    gauges, histograms; Prometheus text + JSON exposition);
  * ``TASKS``    — :class:`TaskMetricsTable` keyed by the task ids the
    OOM runtime tracks (memory/rmm_spark.py registrations feed it);
  * ``JOURNAL``  — ring-buffered :class:`EventJournal` for OOM
    retry/split/block events, shuffle writes/merges, and exchange
    capacity-doublings.

Everything is OFF by default; ``enable()`` (or env
``SPARK_RAPIDS_TPU_METRICS=1`` at import) flips one shared bool that
every hook reads first, so the disabled op path costs a single
attribute check.  Instrumented layers (utils/profiler.py op_range,
shuffle/kudo.py, parallel/exchange.py, memory/) call the ``record_*``
helpers below; they must never import back into those layers.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from spark_rapids_tpu.observability import flight_recorder as _fr
from spark_rapids_tpu.observability import slo as _slo
from spark_rapids_tpu.observability import stats as _stats
from spark_rapids_tpu.observability import timeseries as _ts
from spark_rapids_tpu.observability.dumpio import dump_via
from spark_rapids_tpu.observability.journal import EventJournal
from spark_rapids_tpu.observability.profile import (  # noqa: F401
    QueryProfiler, diff_profiles, merge_profiles)
from spark_rapids_tpu.observability.registry import (
    DEFAULT_LATENCY_BUCKETS_NS, MetricsRegistry)
from spark_rapids_tpu.observability.task_metrics import (
    UNATTRIBUTED, TaskMetricsTable)
from spark_rapids_tpu.observability.tracing import (  # noqa: F401
    NOOP_SPAN, SpanContext, Tracer)

# process start anchors: snapshots carry wall-clock + uptime so offline
# consumers (srt-doctor, Perfetto export) can place a dump in real time
# instead of guessing from per-process monotonic stamps
_START_MONO = time.monotonic()
_START_UNIX = time.time()


class _Switch:
    """The one shared enable flag (an object so the journal and task
    table can hold a reference instead of importing this module)."""

    __slots__ = ("enabled",)

    def __init__(self):
        self.enabled = False


_SWITCH = _Switch()

METRICS = MetricsRegistry(enabled=False)
JOURNAL = EventJournal(capacity=8192, enabled_ref=_SWITCH,
                       on_drop=lambda n: JOURNAL_DROPPED_TOTAL.inc(n))
TASKS = TaskMetricsTable(enabled_ref=_SWITCH)


def enable() -> None:
    METRICS.enabled = True
    _SWITCH.enabled = True


def disable() -> None:
    METRICS.enabled = False
    _SWITCH.enabled = False


def is_enabled() -> bool:
    return _SWITCH.enabled


def enable_tracing() -> None:
    """Turn on structured span tracing (independent of the metrics
    switch: spans cost more than counters, so a metrics-on run does not
    silently pay for them).  Span->journal and span->histogram fan-out
    additionally requires the metrics switch."""
    TRACER.enabled = True


def disable_tracing() -> None:
    TRACER.enabled = False


def is_tracing_enabled() -> bool:
    return TRACER.enabled


def reset() -> None:
    """Zero all registry series, journal records, task rows, and
    finished spans (the families and instrument handles stay valid).
    Parked OOM block-episode spans are discarded too: a stale span
    ended by a post-reset unblock would otherwise record a pre-reset
    trace_id and a bogus multi-run duration into the fresh ring."""
    global _LAST_ATTRIBUTION
    _LAST_ATTRIBUTION = None
    METRICS.reset()
    JOURNAL.clear()
    TASKS.reset()
    with _BLOCK_SPANS_LOCK:
        _BLOCK_SPANS.clear()
    TRACER.reset()
    PROFILER.reset()
    TIMESERIES.reset()
    SLO.reset()
    STATS.reset()


# --------------------------------------------------------------- instruments
# Named families created once at import; mutators on them are no-ops
# while the registry is disabled.

OP_LATENCY = METRICS.histogram(
    "srt_op_latency_ns", "Host-side op bracket latency (op_range)",
    labels=("op",), buckets=DEFAULT_LATENCY_BUCKETS_NS, max_series=256)
SHUFFLE_WRITE_BYTES = METRICS.counter(
    "srt_shuffle_write_bytes_total", "Kudo shuffle bytes serialized")
SHUFFLE_WRITE_TIME = METRICS.counter(
    "srt_shuffle_write_time_ns_total", "Kudo shuffle write copy time")
SHUFFLE_MERGE_ROWS = METRICS.counter(
    "srt_shuffle_merge_rows_total", "Rows concatenated by kudo merges")
SHUFFLE_MERGE_TIME = METRICS.counter(
    "srt_shuffle_merge_time_ns_total",
    "Kudo merge parse+concat time")
SHUFFLE_LINK_BYTES = METRICS.counter(
    "srt_shuffle_link_bytes_total",
    "Kudo shuffle bytes crossing a process-boundary link, by "
    "direction (send/recv) and peer rank", labels=("direction", "peer"),
    max_series=256)
SHUFFLE_LINK_MSGS = METRICS.counter(
    "srt_shuffle_link_msgs_total",
    "Shuffle messages delivered per link (acked sends / verified "
    "receives)", labels=("direction", "peer"), max_series=256)
SHUFFLE_LINK_RETRIES = METRICS.counter(
    "srt_shuffle_link_retries_total",
    "Shuffle link send attempts retried (NAK from the peer verifier, "
    "reconnects, ack timeouts)", labels=("peer", "reason"),
    max_series=256)
OOM_RETRY = METRICS.counter(
    "srt_oom_retry_total", "GpuRetryOOM/CpuRetryOOM throws",
    labels=("device",))
OOM_SPLIT_RETRY = METRICS.counter(
    "srt_oom_split_retry_total",
    "GpuSplitAndRetryOOM/CpuSplitAndRetryOOM throws", labels=("device",))
THREAD_BLOCKED_TIME = METRICS.counter(
    "srt_thread_blocked_time_ns_total",
    "Time threads spent BLOCKED/BUFN in the OOM state machine")
DEVICE_MEM_ALLOCATED = METRICS.gauge(
    "srt_device_memory_allocated_bytes",
    "Device bytes currently reserved through the adaptor")
HBM_BYTES_IN_USE = METRICS.gauge(
    "srt_hbm_bytes_in_use", "Backend-reported HBM bytes in use",
    labels=("device",), max_series=128)
EXCHANGE_DOUBLINGS = METRICS.counter(
    "srt_exchange_capacity_doublings_total",
    "ICI exchange capacity-retry doublings")
JOURNAL_DROPPED_TOTAL = METRICS.counter(
    "srt_journal_dropped_total",
    "Journal events overwritten by ring wrap-around (counted at emit)")
RETRY_EPISODES = METRICS.counter(
    "srt_retry_episodes_total",
    "Retry-driver episodes that saw at least one failure, by outcome",
    labels=("outcome",))
RETRY_ATTEMPTS = METRICS.counter(
    "srt_retry_attempts_total",
    "Attempts started by retry-driver episodes that saw a failure")
RETRY_SPLITS = METRICS.counter(
    "srt_retry_splits_total",
    "Batch halvings performed by split-and-retry drivers")
RETRY_TIME_LOST = METRICS.counter(
    "srt_retry_time_lost_ns_total",
    "Compute time burned by failed retry-driver attempts")
KUDO_CORRUPT = METRICS.counter(
    "srt_kudo_corrupt_total",
    "Kudo stream integrity events by kind (crc = trailer mismatch, "
    "resync = skip-to-next-magic recovery)",
    labels=("reason",))
KUDO_RESYNC_BYTES = METRICS.counter(
    "srt_kudo_resync_skipped_bytes_total",
    "Bytes skipped while resyncing corrupted kudo streams to the "
    "next magic")
SPILL_BYTES = METRICS.counter(
    "srt_spill_bytes_total",
    "Device bytes spilled through the tiered store (memory/spill.py) "
    "by stage and destination tier",
    labels=("stage", "tier"), max_series=256)
SPILL_RESTORES = METRICS.counter(
    "srt_spill_restores_total",
    "Spilled batches streamed back to the device by stage and source "
    "tier", labels=("stage", "tier"), max_series=256)
SPILL_TIME = METRICS.counter(
    "srt_spill_ns_total",
    "Wall nanoseconds inside spill-store work by stage and direction "
    "(spill = serialize+release, restore = re-acquire+deserialize)",
    labels=("stage", "dir"), max_series=256)
SPILL_CORRUPT = METRICS.counter(
    "srt_spill_corrupt_total",
    "Spill payloads failing CRC/parse on read-back (recomputed = "
    "rebuilt from source, failed = escalated)",
    labels=("outcome",))
JIT_CACHE_HITS = METRICS.counter(
    "srt_jit_cache_hits_total",
    "Kernel compile-cache hits (perf/jit_cache.py)", labels=("kernel",))
JIT_CACHE_MISSES = METRICS.counter(
    "srt_jit_cache_misses_total",
    "Kernel compile-cache misses (each one compiled an executable)",
    labels=("kernel",))
JIT_CACHE_EVICTIONS = METRICS.counter(
    "srt_jit_cache_evictions_total",
    "Kernel compile-cache LRU evictions (entry/byte budget)",
    labels=("kernel",))
JIT_COMPILE_TIME = METRICS.histogram(
    "srt_jit_compile_ns",
    "Kernel lower+compile wall time on compile-cache misses",
    labels=("kernel",), buckets=DEFAULT_LATENCY_BUCKETS_NS,
    max_series=128)
RESULT_CACHE_HITS = METRICS.counter(
    "srt_result_cache_hits_total",
    "Semantic result/subplan cache hits (perf/result_cache.py) by "
    "scope (result/stage/subplan) and tenant (result scope only)",
    labels=("scope", "tenant"), max_series=256)
RESULT_CACHE_MISSES = METRICS.counter(
    "srt_result_cache_misses_total",
    "Semantic result/subplan cache misses by scope and tenant",
    labels=("scope", "tenant"), max_series=256)
RESULT_CACHE_EVICTIONS = METRICS.counter(
    "srt_result_cache_evictions_total",
    "Result-cache LRU evictions (entry/byte budget; SpillStore "
    "pressure demotions are spill metrics, not evictions)",
    labels=("scope",))
RESULT_CACHE_BYTES = METRICS.counter(
    "srt_result_cache_bytes_total",
    "Payload bytes admitted into the result cache by scope",
    labels=("scope",))
RESULT_CACHE_FOLDS = METRICS.counter(
    "srt_result_cache_incremental_folds_total",
    "Arriving batches folded into resident partial-aggregate states "
    "(the O(delta) increments) by query", labels=("query",),
    max_series=128)
KERNEL_PATH = METRICS.counter(
    "srt_kernel_path_total",
    "Executions per op by the kernel path actually taken "
    "(calibrated join / JSON engines)", labels=("op", "path"),
    max_series=128)
STAGE_FUSION = METRICS.counter(
    "srt_stage_fusion_total",
    "Whole-stage executions by stage and outcome (fused = one AOT "
    "executable, unfused = op-by-op walk, compile = a fused "
    "executable was built this run)", labels=("stage", "outcome"),
    max_series=128)
FLEET_EPOCH = METRICS.gauge(
    "srt_fleet_epoch",
    "Elastic-fleet membership epoch on this worker (bumps on every "
    "observed leave/join; stale-epoch frames are fenced)")
FLEET_REBALANCES = METRICS.counter(
    "srt_fleet_rebalances_total",
    "Membership changes that moved shard ownership (peer death -> "
    "survivors inherit)", labels=("change",))
FLEET_DEATHS = METRICS.counter(
    "srt_fleet_deaths_total",
    "Peer ranks observed dead by this worker", labels=("peer",),
    max_series=128)
FLEET_SPECULATIONS = METRICS.counter(
    "srt_fleet_speculations_total",
    "Speculative re-executions of a straggler's partition, by "
    "outcome (won = the speculated copy merged first, lost = the "
    "original arrived first, cancelled = the original arrived "
    "mid-compute and the speculative task was cancelled)",
    labels=("outcome",))
FLEET_RESPLITS = METRICS.counter(
    "srt_fleet_resplits_total",
    "Hot partitions re-split into per-rank sub-partitions for a "
    "second exchange round")
FLEET_STALE_NAKS = METRICS.counter(
    "srt_fleet_stale_naks_total",
    "Elastic frames fenced for carrying a stale membership epoch "
    "(answered E, never merged)", labels=("peer",), max_series=128)
SHUFFLE_DUP_DROPPED = METRICS.counter(
    "srt_shuffle_dup_dropped_total",
    "Duplicate (op, partition) deliveries dropped after the byte "
    "compare (speculation losers, rebalance replays)",
    labels=("peer",), max_series=128)
INCIDENTS_TOTAL = METRICS.counter(
    "srt_incidents_total",
    "Flight-recorder incident bundles written, by trigger kind",
    labels=("trigger",))
INCIDENTS_SUPPRESSED = METRICS.counter(
    "srt_incidents_suppressed_total",
    "Flight-recorder triggers suppressed (rate_limit, byte_budget, "
    "error)", labels=("reason",))
MEMORY_LEAK_EVENTS = METRICS.counter(
    "srt_memory_leak_total",
    "Tasks that finished still holding device memory")
MEMORY_LEAKED_BYTES = METRICS.counter(
    "srt_memory_leaked_bytes_total",
    "Device bytes still held when their task finished")
SPAN_DURATION = METRICS.histogram(
    "srt_span_duration_ns", "Span durations by span kind and name",
    labels=("span_kind", "name"),
    buckets=DEFAULT_LATENCY_BUCKETS_NS, max_series=512)
SPANS_FINISHED = METRICS.counter(
    "srt_spans_finished_total", "Spans finished", labels=("span_kind",))
SERVER_ADMITTED = METRICS.counter(
    "srt_server_admitted_total",
    "Query-server submissions admitted, by tenant", labels=("tenant",),
    max_series=128)
SERVER_REJECTED = METRICS.counter(
    "srt_server_rejected_total",
    "Query-server submissions rejected with a typed ServerOverloaded "
    "(queue_full, tenant_inflight, tenant_bytes, shutdown)",
    labels=("tenant", "reason"), max_series=256)
SERVER_COMPLETED = METRICS.counter(
    "srt_server_completed_total",
    "Query-server jobs finished, by tenant and outcome "
    "(success, failed, cancelled, shed)",
    labels=("tenant", "outcome"), max_series=256)
SERVER_REQUEUED = METRICS.counter(
    "srt_server_requeued_total",
    "Jobs re-queued at lower priority by the load-shedding path "
    "(an attempt OOMed against quota instead of killing neighbors)",
    labels=("tenant", "reason"), max_series=128)
SERVER_QUEUED = METRICS.gauge(
    "srt_server_queued", "Queued (admitted, not yet running) jobs",
    labels=("tenant",), max_series=128)
SERVER_RUNNING = METRICS.gauge(
    "srt_server_running", "Jobs currently executing on pool threads",
    labels=("tenant",), max_series=128)
SERVER_TENANT_BYTES = METRICS.gauge(
    "srt_server_tenant_device_bytes",
    "Device bytes currently attributed to a tenant's live tasks "
    "(memory-ledger fold)", labels=("tenant",), max_series=128)
SERVER_FAIR_DEFICIT = METRICS.gauge(
    "srt_server_fair_share_deficit",
    "Weighted service a tenant is behind the most-served tenant "
    "(scheduler vruntime delta, seconds)", labels=("tenant",),
    max_series=128)
SERVER_QUEUE_WAIT = METRICS.histogram(
    "srt_server_queue_wait_ns",
    "Admission-to-dispatch queue wait per tenant",
    labels=("tenant",), buckets=DEFAULT_LATENCY_BUCKETS_NS,
    max_series=128)
SERVER_WATCHDOG = METRICS.counter(
    "srt_server_watchdog_total",
    "Query-lifeguard watchdog interventions (deadline_cancel, "
    "deadline_expired_queued, hang_release)", labels=("action",))
SERVER_QUARANTINE = METRICS.counter(
    "srt_server_quarantine_total",
    "Poison-query circuit-breaker transitions (opened, reopened, "
    "probe, closed, rejected)", labels=("event",))
SERVER_DRAIN = METRICS.counter(
    "srt_server_drain_total",
    "Query-server graceful-drain lifecycle markers (begin, end)",
    labels=("phase",))
IO_READ_BYTES = METRICS.counter(
    "srt_io_read_bytes_total",
    "Bytes fetched by storage range reads (io/fileio.read_range)")
IO_READ_TIME = METRICS.histogram(
    "srt_io_read_ns", "Storage range-read latency",
    buckets=DEFAULT_LATENCY_BUCKETS_NS)
IO_FILES = METRICS.counter(
    "srt_io_files_total",
    "Parquet files fully decoded by io/parquet_reader")
IO_PAGES = METRICS.counter(
    "srt_io_pages_total", "Parquet pages decoded")
IO_ROWS = METRICS.counter(
    "srt_io_rows_total", "Rows materialized from parquet files")
IO_DECODE_TIME = METRICS.counter(
    "srt_io_decode_ns_total",
    "Wall time decoding parquet pages into device columns")
LOCKDEP_CYCLES = METRICS.counter(
    "srt_lockdep_cycles_total",
    "Lock-acquisition-order cycles detected by the lockdep runtime "
    "(ABBA deadlock potential — the deadlock need not fire)")
LOCKDEP_BLOCKING = METRICS.counter(
    "srt_lockdep_blocking_total",
    "Instrumented locks observed held across a known blocking call "
    "(socket send/recv, storage range read)", labels=("op",))
PROFILE_QUERIES = METRICS.counter(
    "srt_profile_queries_total",
    "Per-query profiles assembled at query end (EXPLAIN ANALYZE "
    "artifacts), by tenant", labels=("tenant",), max_series=128)
PROFILE_ASSEMBLY = METRICS.histogram(
    "srt_profile_assembly_ns",
    "Wall time spent assembling one query profile at query end "
    "(the cost the profiling switch buys)",
    buckets=DEFAULT_LATENCY_BUCKETS_NS)
PROFILE_DROPPED = METRICS.counter(
    "srt_profile_dropped_total",
    "Profile sessions dropped instead of assembled (nested begin, "
    "stage record with no session, assembly error)",
    labels=("reason",))
TIMESERIES_WINDOWS = METRICS.counter(
    "srt_timeseries_windows_total",
    "Telemetry windows appended to the timeseries ring")
TIMESERIES_TICK = METRICS.histogram(
    "srt_timeseries_tick_ns",
    "Wall time of one timeseries tick (registry snapshot + delta "
    "fold) — the cost the sampler switch buys",
    buckets=DEFAULT_LATENCY_BUCKETS_NS)
TIMESERIES_MERGE = METRICS.counter(
    "srt_timeseries_merge_total",
    "Per-rank timeseries snapshots offered to the fleet merger, by "
    "outcome (merged, dup = no new windows, stale_epoch = fenced)",
    labels=("outcome",))
MONITOR_SAMPLE_AGE = METRICS.gauge(
    "srt_monitor_last_sample_age_s",
    "Seconds since the Monitor thread last sampled — computed at "
    "exposition time, so a dead sampler shows a growing age instead "
    "of a frozen healthy-looking gauge")
SLO_BURN_RATE = METRICS.gauge(
    "srt_slo_burn_rate",
    "Per-tenant error-budget burn rate (bad fraction / budget) over "
    "the fast and slow windows; 1.0 = spending exactly as "
    "provisioned", labels=("tenant", "window"), max_series=256)
SLO_ATTAINMENT = METRICS.gauge(
    "srt_slo_attainment_ratio",
    "Per-tenant lifetime fraction of budget-consuming completions "
    "that met the SLO (success within the latency target)",
    labels=("tenant",), max_series=128)
SLO_BREACHES = METRICS.counter(
    "srt_slo_breaches_total",
    "slo_burn alerts fired (both burn windows over threshold, "
    "cooldown-filtered), by tenant", labels=("tenant",),
    max_series=128)
SHUFFLE_WIRE_TIME = METRICS.counter(
    "srt_shuffle_wire_ns_total",
    "Query-thread wall spent serializing and sending shuffle frames "
    "(the wire half of an exchange; peers' ACKs included)")
SHUFFLE_WAIT_TIME = METRICS.counter(
    "srt_shuffle_wait_ns_total",
    "Query-thread wall spent idle waiting on peers' shuffle frames, "
    "by cause (inbox = ordinary exchange wait, speculation = gather "
    "idle attributable to parts with a live speculation decision)",
    labels=("cause",))
ATTRIBUTION_TIME = METRICS.counter(
    "srt_attribution_ns_total",
    "Per-query wall nanoseconds classified by attribution bucket "
    "(queue_wait/compile/compute_*/shuffle_*/oom_blocked/retry_lost/"
    "other), by tenant — fed at query end when attribution is armed",
    labels=("tenant", "bucket"), max_series=512)
ATTRIBUTION_QUERIES = METRICS.counter(
    "srt_attribution_queries_total",
    "Attribution ledgers built at query end, by conservation verdict "
    "(true = buckets summed to the wall within tolerance)",
    labels=("conserved",))
STATS_OBSERVATIONS = METRICS.counter(
    "srt_stats_observations_total",
    "Per-node row-count observations folded into the data-statistics "
    "plane (observability/stats.py), by stage", labels=("stage",),
    max_series=128)
STATS_MISESTIMATE = METRICS.counter(
    "srt_stats_misestimate_total",
    "Cardinality misestimates detected (actual vs estimate divergence "
    "past SPARK_RAPIDS_TPU_STATS_MISEST_RATIO), by stage and plan "
    "node", labels=("stage", "node"), max_series=512)
STATS_ROWS = METRICS.counter(
    "srt_stats_rows_total",
    "Result rows returned to tenants by completed server jobs (the "
    "rows/s feed behind srt-top)", labels=("tenant",), max_series=128)
STATS_SKETCH_NS = METRICS.histogram(
    "srt_stats_sketch_ns",
    "Wall time of one column sketch pass (KMV + heavy hitters + "
    "histogram; memoized per stage/input/ingest-epoch)",
    buckets=DEFAULT_LATENCY_BUCKETS_NS)


# ------------------------------------------------------------------ tracer
# Built AFTER the instrument families: the finish hook folds span
# durations into SPAN_DURATION and appends span records to the journal
# so one JSONL dump carries events AND spans on one timeline.


def _on_span_finish(rec: dict) -> None:
    # flight-recorder feed first (independent switch: the straggler
    # detector watches stage spans whether or not metrics are on)
    if FLIGHT.enabled:
        FLIGHT.observe_span(rec)
    if not _SWITCH.enabled:
        return
    SPAN_DURATION.observe(rec["dur_ns"],
                          labels=(rec["span_kind"], rec["name"]))
    SPANS_FINISHED.inc(labels=(rec["span_kind"],))
    # the span record keeps its own start t_ns (emit's now-stamp is
    # overridden by the explicit field)
    JOURNAL.emit("span", **{k: v for k, v in rec.items() if k != "kind"})


TRACER = Tracer(capacity=65536,
                task_lookup=lambda: TASKS.tasks_for(),
                on_finish=_on_span_finish)


# ------------------------------------------------------- query profiler
# EXPLAIN ANALYZE for every query (ISSUE 13 tentpole): per-query
# artifacts assembled at query end from the rings above.  Independent
# switch with the tracer's noop discipline — profiling off costs one
# attribute read per hook.


def _on_profile(profile: dict, assembly_ns: int) -> None:
    # attribution rides the profile-end hook (its own switch): the
    # ledger lands INSIDE the artifact, so retention, bundles and
    # srt-explain all carry it without new plumbing
    if ATTRIBUTION.enabled:
        _note_attribution(profile)
    if not _SWITCH.enabled:
        return
    PROFILE_QUERIES.inc(labels=(profile.get("tenant") or "-",))
    PROFILE_ASSEMBLY.observe(assembly_ns)
    JOURNAL.emit("query_profile", query_id=profile.get("query_id"),
                 tenant=profile.get("tenant"),
                 query=profile.get("query"),
                 wall_ns=profile.get("wall_ns"),
                 stages=len(profile.get("stages") or ()),
                 hot_stage=profile.get("hot_stage"))


def _profile_keep() -> int:
    try:
        return int(os.environ.get("SPARK_RAPIDS_TPU_PROFILE_KEEP", "")
                   or 16)
    except ValueError:
        return 16


PROFILER = QueryProfiler(
    journal=JOURNAL, tasks=TASKS, tracer=TRACER, registry=METRICS,
    keep=_profile_keep(), on_profile=_on_profile,
    on_drop=lambda reason: PROFILE_DROPPED.inc(labels=(reason,)))


def enable_profiling() -> None:
    """Turn on per-query profile assembly (independent of the metrics
    and tracing switches; profile counters additionally require the
    metrics switch, trace-scoped span stats require tracing)."""
    PROFILER.enabled = True


def disable_profiling() -> None:
    PROFILER.enabled = False


def is_profiling_enabled() -> bool:
    return PROFILER.enabled


def cache_hit_profile(tenant: str, query: str, query_id: str,
                      lookup_ns: int) -> Optional[dict]:
    """Assemble + retain the profile artifact for a warm result-cache
    hit (ISSUE 19).  A hit never executes, so there is no session to
    fold — the artifact is the lookup itself: wall == cache.lookup_ns,
    no stages, a ``cache`` section with the one hit.  Returns None
    when profiling is off."""
    if not PROFILER.enabled:
        return None
    from spark_rapids_tpu.observability.profile import PROFILE_VERSION
    profile = {
        "profile_version": PROFILE_VERSION,
        "query_id": query_id,
        "tenant": tenant,
        "query": query,
        "rank": 0,
        "world": 1,
        "trace_id": None,
        "t_unix_ms": int(time.time() * 1000),
        "wall_ns": int(lookup_ns),
        "queue_wait_ns": 0,
        "stages": [],
        "hot_stage": None,
        "cache": {"hits": 1, "misses": 0, "puts": 0, "evictions": 0,
                  "folds": 0, "lookup_ns": int(lookup_ns),
                  "bytes": 0},
    }
    return PROFILER.note_external(profile)


# ----------------------------------------------------- time attribution
# Where did the time go (ISSUE 17 tentpole): at profile end the wall is
# classified into exhaustive non-overlapping buckets with a
# conservation contract.  Independent switch; with it off the only
# cost is ONE attribute read inside the profile-end hook (and nothing
# at all when profiling itself is off).

ATTRIBUTION = _Switch()
_LAST_ATTRIBUTION: Optional[dict] = None


def _attribution_tolerance() -> float:
    try:
        return float(os.environ.get(
            "SPARK_RAPIDS_TPU_ATTRIBUTION_TOLERANCE", "") or 0.25)
    except ValueError:
        return 0.25


def _note_attribution(profile: dict) -> None:
    global _LAST_ATTRIBUTION
    try:
        from spark_rapids_tpu.observability.attribution import (
            attribute_profile)
        ledger = attribute_profile(
            profile, tolerance=_attribution_tolerance())
    except Exception:
        return  # a ledger must never fail the query it describes
    profile["attribution"] = ledger
    _LAST_ATTRIBUTION = ledger
    if not _SWITCH.enabled:
        return
    tenant = ledger.get("tenant") or "-"
    for bucket, ns in ledger.get("buckets", {}).items():
        if ns > 0:
            ATTRIBUTION_TIME.inc(int(ns), labels=(tenant, bucket))
    ATTRIBUTION_QUERIES.inc(
        labels=("true" if ledger.get("conserved") else "false",))


def enable_attribution() -> None:
    """Turn on per-query time-attribution ledgers (rides the profiler:
    arming attribution without profiling yields no ledgers; counters
    additionally require the metrics switch)."""
    ATTRIBUTION.enabled = True


def disable_attribution() -> None:
    ATTRIBUTION.enabled = False


def is_attribution_enabled() -> bool:
    return ATTRIBUTION.enabled


def attribution_last() -> Optional[dict]:
    """The most recently built ledger (what a flight-recorder bundle
    freezes as ``attribution.json``)."""
    return _LAST_ATTRIBUTION


# -------------------------------------------------------- flight recorder
# The black box (ISSUE 5 tentpole): anomaly detectors fed by the
# record helpers below, freezing the rings above into incident bundles.
# Independent switch — always-on capture is cheap, bundle dumps are
# not, so the recorder arms separately from metrics/tracing.

FLIGHT = _fr.FlightRecorder.from_env()


def enable_flight_recorder(out_dir: Optional[str] = None,
                           max_bytes: Optional[int] = None,
                           min_interval_s: Optional[float] = None
                           ) -> None:
    FLIGHT.configure(out_dir=out_dir, max_bytes=max_bytes,
                     min_interval_s=min_interval_s)
    FLIGHT.enabled = True


def disable_flight_recorder() -> None:
    FLIGHT.enabled = False


def is_flight_recorder_enabled() -> bool:
    return FLIGHT.enabled


def trigger_incident(kind: str, cause: Optional[BaseException] = None,
                     severity: str = "error", **detail) -> Optional[str]:
    """Explicit incident trigger for the instrumented layers
    (RetryExhausted in robustness/retry.py, KudoCorruptException in
    shuffle/kudo.py, task-end leaks in the OOM state machine).  One
    attribute read when the recorder is off."""
    if not FLIGHT.enabled:
        return None
    # bundle dumps take real wall time on the calling thread — beat
    # before and after so the hung-worker watchdog never mistakes a
    # worker busy FREEZING an incident for the incident itself
    hook = _HEARTBEAT_HOOK
    if hook is not None:
        hook(f"incident:{kind}")
    try:
        return FLIGHT.trigger(kind, cause=cause, severity=severity,
                              **detail)
    finally:
        if hook is not None:
            hook(f"incident:{kind}")


# ------------------------------------------------------- telemetry plane
# Windowed time-series + per-tenant SLO burn monitoring (ISSUE 16
# tentpole).  Independent switches with the usual noop discipline:
# the Monitor thread calls record_monitor_sample() every period, and
# with both switches off that costs two attribute reads.


def _on_timeseries_tick(elapsed_ns: int) -> None:
    if not _SWITCH.enabled:
        return
    TIMESERIES_WINDOWS.inc()
    TIMESERIES_TICK.observe(elapsed_ns)


def _env_num(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


TIMESERIES = _ts.TimeseriesSampler(
    METRICS,
    window_s=_env_num("SPARK_RAPIDS_TPU_TIMESERIES_WINDOW_S", 5.0),
    capacity=int(_env_num("SPARK_RAPIDS_TPU_TIMESERIES_CAPACITY", 120)),
    on_tick=_on_timeseries_tick)


def _on_slo_burn(tenant: str, alert: dict) -> None:
    """One multi-window burn alert: journal + breach counter, then the
    slo_burn incident bundle freezing the ring tail + the offending
    tenant's SLO snapshot next to the usual evidence (PR-13's last
    profile rides along via the recorder's own bundle assembly)."""
    detail = {k: v for k, v in alert.items() if k != "tenant"}
    if _SWITCH.enabled:
        SLO_BREACHES.inc(labels=(tenant,))
        JOURNAL.emit("slo_burn", tenant=tenant, **detail)
    trigger_incident(
        "slo_burn", severity="error", tenant=tenant, **detail,
        tenant_slo=SLO.status().get(tenant, {}),
        timeseries_tail=TIMESERIES.windows(4))


try:
    SLO = _slo.SloMonitor.from_env(on_burn=_on_slo_burn)
except Exception as _e:  # malformed SLO_CONFIG: warn loudly, run bare
    import sys as _sys
    print(f"spark_rapids_tpu: ignoring bad SPARK_RAPIDS_TPU_SLO_* "
          f"config: {_e}", file=_sys.stderr)
    SLO = _slo.SloMonitor(on_burn=_on_slo_burn)

# last Monitor sample, monotonic — the liveness source behind
# srt_monitor_last_sample_age_s (set at exposition, never by the
# sampler itself: a dead thread must show a GROWING age)
_LAST_MONITOR_SAMPLE: Optional[float] = None


def enable_timeseries(window_s: Optional[float] = None,
                      capacity: Optional[int] = None) -> None:
    """Arm the windowed sampler (independent switch; pair with the
    metrics switch — with the registry disabled every delta is
    zero)."""
    if window_s is not None:
        TIMESERIES.window_s = float(window_s)
    if capacity is not None:
        from collections import deque as _dq
        TIMESERIES.capacity = int(capacity)
        TIMESERIES._windows = _dq(TIMESERIES._windows,
                                  maxlen=int(capacity))
    TIMESERIES.enabled = True


def disable_timeseries() -> None:
    TIMESERIES.enabled = False


def is_timeseries_enabled() -> bool:
    return TIMESERIES.enabled


def enable_slo() -> None:
    SLO.enabled = True


def disable_slo() -> None:
    SLO.enabled = False


def is_slo_enabled() -> bool:
    return SLO.enabled


def _apply_slo_gauges() -> None:
    if not _SWITCH.enabled:
        return
    for tenant, st in SLO.status().items():
        SLO_BURN_RATE.set(st["burn_fast"], labels=(tenant, "fast"))
        SLO_BURN_RATE.set(st["burn_slow"], labels=(tenant, "slow"))
        SLO_ATTAINMENT.set(st["attainment"], labels=(tenant,))


# --------------------------------------------------------- data statistics
# The cardinality & statistics observatory (ISSUE 20 tentpole): per-node
# observed row counts tapped out of fused stages, column sketches, and
# the est-vs-actual misestimate sentinel.  Independent switch with the
# usual discipline — stats off costs the compiler ONE attribute read
# (``STATS.enabled``) per stage run.


def _on_stats_observation(stage: str, nodes: list,
                          misestimates: list) -> None:
    if not _SWITCH.enabled:
        return
    STATS_OBSERVATIONS.inc(len(nodes), labels=(stage,))
    JOURNAL.emit("node_stats", stage=stage, nodes=len(nodes),
                 misestimates=len(misestimates),
                 thread=threading.get_ident())


def _on_stats_misestimate(stage: str, node: str, est: int, actual: int,
                          ratio: float, first: bool) -> None:
    """One detected cardinality misestimate: metric + journal on every
    detection, the flight-recorder bundle only on the FIRST detection
    of a (stage, node) pair — a misestimate repeats on every run of
    the stage and one bundle is the evidence, fifty are noise."""
    if _SWITCH.enabled:
        STATS_MISESTIMATE.inc(labels=(stage, node))
        JOURNAL.emit("cardinality_misestimate", stage=stage, node=node,
                     est=int(est), actual=int(actual),
                     ratio=float(ratio),
                     thread=threading.get_ident())
    if first:
        trigger_incident(
            "cardinality_misestimate", severity="warn", stage=stage,
            node=node, est=int(est), actual=int(actual),
            ratio=float(ratio), stage_stats=STATS.last(stage))


def _on_stats_sketch(ns: int) -> None:
    if not _SWITCH.enabled:
        return
    STATS_SKETCH_NS.observe(int(ns))


STATS = _stats.StatsCollector(
    on_observation=_on_stats_observation,
    on_misestimate=_on_stats_misestimate,
    on_sketch=_on_stats_sketch)


def enable_stats() -> None:
    """Arm the data-statistics plane (independent switch; the
    srt_stats_* counters additionally require the metrics switch)."""
    STATS.enabled = True


def disable_stats() -> None:
    STATS.enabled = False


def is_stats_enabled() -> bool:
    return STATS.enabled


def record_tenant_rows(tenant: str, rows: int) -> None:
    """Server job-completion hook: result rows delivered to one
    tenant (the rows/s column in srt-top)."""
    if not _SWITCH.enabled:
        return
    STATS_ROWS.inc(int(rows), labels=(str(tenant) or "-",))


def evaluate_slo(now: Optional[float] = None) -> list:
    """Force one burn-rate evaluation + gauge refresh; returns the
    alerts that fired (each already routed through the slo_burn
    incident path).  Tests and the smoke drive this with synthetic
    clocks; production rides record_monitor_sample."""
    fired = SLO.evaluate(now)
    _apply_slo_gauges()
    return fired


def record_monitor_sample(now: Optional[float] = None) -> None:
    """utils/telemetry.Monitor loop hook: stamps sampler liveness and
    drives the telemetry plane at window granularity (maybe_tick /
    maybe_evaluate are no-ops until a window has elapsed)."""
    global _LAST_MONITOR_SAMPLE
    _LAST_MONITOR_SAMPLE = time.monotonic() if now is None else now
    if TIMESERIES.enabled:
        TIMESERIES.maybe_tick()
    if SLO.enabled:
        fired = SLO.maybe_evaluate()
        if fired is not None:
            _apply_slo_gauges()


def _refresh_liveness(now: Optional[float] = None) -> None:
    """Exposition-time liveness: every snapshot/health/expose path
    recomputes the sampler age so a stalled Monitor thread cannot
    freeze a healthy-looking value into dumps and bundles."""
    if not _SWITCH.enabled or _LAST_MONITOR_SAMPLE is None:
        return
    now = time.monotonic() if now is None else now
    MONITOR_SAMPLE_AGE.set(
        round(max(0.0, now - _LAST_MONITOR_SAMPLE), 3))


def timeseries_snapshot(rank: int = 0, epoch: int = 0) -> dict:
    """One publishable per-rank snapshot: the ring dump tagged with
    fleet identity (+ the SLO status when armed) — the unit workers
    send over CTRL frames / dump to ``timeseries_rank{r}.json`` and
    ``FleetTimeseries.offer`` merges."""
    snap = TIMESERIES.snapshot()
    snap["rank"] = int(rank)
    snap["epoch"] = int(epoch)
    if SLO.enabled:
        snap["slo"] = SLO.status()
    return snap


def record_timeseries_merge(outcome: str, rank: int) -> None:
    """Rank 0's fleet-merge hook: one offered per-rank snapshot, by
    outcome ('merged', 'dup', 'stale_epoch')."""
    if not _SWITCH.enabled:
        return
    TIMESERIES_MERGE.inc(labels=(outcome,))
    JOURNAL.emit("timeseries_merge", outcome=outcome, rank=int(rank),
                 thread=threading.get_ident())


# ------------------------------------------------------------ record helpers
# Called from the instrumented layers.  Each starts with the switch
# check so a disabled run pays one attribute read.

# hung-worker heartbeat seam: the lifeguard (robustness/lifeguard.py)
# installs a callback here so every finished op bracket counts as a
# sign of life.  A separate hook — NOT the metrics switch — because
# hang detection must work with metrics off, and the layering rule
# forbids this package importing robustness.
_HEARTBEAT_HOOK: Optional[Callable[[str], None]] = None


def set_heartbeat_hook(fn: Optional[Callable[[str], None]]) -> None:
    global _HEARTBEAT_HOOK
    _HEARTBEAT_HOOK = fn


def record_op(op: str, dur_ns: int) -> None:
    """utils/profiler.op_range close hook."""
    hook = _HEARTBEAT_HOOK
    if hook is not None:
        hook(op)
    if not _SWITCH.enabled:
        return
    OP_LATENCY.observe(dur_ns, labels=(op,))
    TASKS.note_op(op, dur_ns)


def record_shuffle_write(num_bytes: int, dur_ns: int, rows: int) -> None:
    if not _SWITCH.enabled:
        return
    SHUFFLE_WRITE_BYTES.inc(num_bytes)
    SHUFFLE_WRITE_TIME.inc(dur_ns)
    TASKS.note_shuffle_write(num_bytes, dur_ns)
    JOURNAL.emit("shuffle_write", bytes=num_bytes, rows=rows,
                 dur_ns=dur_ns, thread=threading.get_ident())


def record_shuffle_merge(rows: int, parse_ns: int, concat_ns: int,
                         tables: int) -> None:
    if not _SWITCH.enabled:
        return
    SHUFFLE_MERGE_ROWS.inc(rows)
    SHUFFLE_MERGE_TIME.inc(parse_ns + concat_ns)
    TASKS.note_shuffle_merge(rows, parse_ns + concat_ns)
    JOURNAL.emit("shuffle_merge", rows=rows, tables=tables,
                 parse_ns=parse_ns, concat_ns=concat_ns,
                 thread=threading.get_ident())


def record_shuffle_link(direction: str, peer: str, nbytes: int,
                        op_id: int = 0) -> None:
    """Distributed shuffle link hook (distributed/transport.py):
    ``direction`` is 'send' (payload acked by the peer) or 'recv'
    (payload received AND CRC-verified)."""
    if not _SWITCH.enabled:
        return
    peer = str(peer)
    SHUFFLE_LINK_BYTES.inc(nbytes, labels=(direction, peer))
    SHUFFLE_LINK_MSGS.inc(labels=(direction, peer))
    JOURNAL.emit("shuffle_link", direction=direction, peer=peer,
                 bytes=nbytes, op=op_id,
                 thread=threading.get_ident())


def record_shuffle_link_retry(peer: str, reason: str) -> None:
    """One failed shuffle-link send attempt about to be retried
    (reason: 'nak' = peer's CRC verifier refused the payload,
    'link' = connect/send/ack transport error)."""
    if not _SWITCH.enabled:
        return
    peer = str(peer)
    SHUFFLE_LINK_RETRIES.inc(labels=(peer, reason))
    JOURNAL.emit("shuffle_link_retry", peer=peer, reason=reason,
                 thread=threading.get_ident())


def record_shuffle_wire(op_id: int, wire_ns: int) -> None:
    """The wire half of one exchange on the query thread: serialize +
    concurrent per-peer sends, ACKs included (distributed/service.py).
    Thread-stamped so the per-query profile claims it."""
    if not _SWITCH.enabled:
        return
    wire_ns = int(wire_ns)
    SHUFFLE_WIRE_TIME.inc(wire_ns)
    JOURNAL.emit("shuffle_wire", op=int(op_id), wire_ns=wire_ns,
                 thread=threading.get_ident())


def record_shuffle_wait(op_id: int, wait_ns: int,
                        spec_ns: int = 0) -> None:
    """The idle half of one exchange/gather: blocked on peers' frames
    (``wait_ns``), with the slice attributable to parts under a live
    speculation decision split out as ``spec_ns`` — a straggler's
    story, not the wire's."""
    if not _SWITCH.enabled:
        return
    wait_ns, spec_ns = int(wait_ns), int(spec_ns)
    if wait_ns > 0:
        SHUFFLE_WAIT_TIME.inc(wait_ns, labels=("inbox",))
    if spec_ns > 0:
        SHUFFLE_WAIT_TIME.inc(spec_ns, labels=("speculation",))
    JOURNAL.emit("shuffle_wait", op=int(op_id), wait_ns=wait_ns,
                 spec_ns=spec_ns, thread=threading.get_ident())


def set_fleet_epoch(epoch: int) -> None:
    """Elastic-fleet membership epoch on this worker
    (robustness/fleet.py)."""
    if not _SWITCH.enabled:
        return
    FLEET_EPOCH.set(int(epoch))


def record_fleet_membership(change: str, *, dead, epoch: int, live,
                            moved=None, joined=None) -> None:
    """One membership transition: ``change`` 'death' (ranks left,
    shards moved to survivors) or 'join' (a worker (re)joined the
    live set).  The journal event is the rebalance evidence the
    elastic-smoke gate and srt-doctor read."""
    if not _SWITCH.enabled:
        return
    FLEET_EPOCH.set(int(epoch))
    if moved:
        FLEET_REBALANCES.inc(labels=(change,))
    for r in dead or ():
        FLEET_DEATHS.inc(labels=(str(r),))
    JOURNAL.emit("fleet_membership", change=change,
                 dead=[int(r) for r in dead or ()],
                 joined=joined, epoch=int(epoch),
                 live=[int(r) for r in live],
                 moved={str(k): int(v)
                        for k, v in (moved or {}).items()},
                 thread=threading.get_ident())


def record_fleet_speculation(op_id: int, part: int, owner: int,
                             by: int, outcome: str,
                             evidence: Optional[dict] = None) -> None:
    """One speculative re-execution decision resolved: ``outcome`` in
    {'won', 'lost', 'cancelled'} — won means the speculated copy
    merged first (the straggling owner's late frames dedup-drop),
    lost/cancelled mean the original beat the speculation."""
    if not _SWITCH.enabled:
        return
    FLEET_SPECULATIONS.inc(labels=(outcome,))
    JOURNAL.emit("fleet_speculation", op=int(op_id), part=int(part),
                 owner=int(owner), by=int(by), outcome=outcome,
                 evidence=evidence or {},
                 thread=threading.get_ident())


def record_fleet_resplit(op_id: int, part: int, nsub: int,
                         nbytes: int,
                         evidence: Optional[dict] = None) -> None:
    """A hot partition re-split into ``nsub`` sub-partitions for a
    second exchange round (skew evidence from the live link-byte
    deltas rides in ``evidence``)."""
    if not _SWITCH.enabled:
        return
    FLEET_RESPLITS.inc()
    JOURNAL.emit("fleet_resplit", op=int(op_id), part=int(part),
                 nsub=int(nsub), bytes=int(nbytes),
                 evidence=evidence or {},
                 thread=threading.get_ident())


def record_fleet_stale_nak(peer, frame_epoch: int,
                           local_epoch: int) -> None:
    """An elastic frame arrived carrying an epoch older than this
    worker's view: fenced with the E verdict, never merged."""
    if not _SWITCH.enabled:
        return
    FLEET_STALE_NAKS.inc(labels=(str(peer),))
    JOURNAL.emit("fleet_stale_nak", peer=str(peer),
                 frame_epoch=int(frame_epoch),
                 local_epoch=int(local_epoch),
                 thread=threading.get_ident())


def record_shuffle_dup_dropped(peer, op_id: int, part: int,
                               identical: Optional[bool]) -> None:
    """A duplicate (op, partition) delivery was dropped: the first
    verified copy won; this one (a speculation loser or a rebalance
    replay) is byte-compared and discarded.  ``identical=False`` is
    recorded loudly — deterministic recomputes must produce the same
    bytes, so a mismatch is corruption-grade evidence.
    ``identical=None`` means the compare was inapplicable: the
    winning copy was stitched from re-split sub-frames, so the same
    rows carry different framing bytes."""
    if not _SWITCH.enabled:
        return
    peer = str(peer)
    SHUFFLE_DUP_DROPPED.inc(labels=(peer,))
    JOURNAL.emit("shuffle_dup_dropped", peer=peer, op=int(op_id),
                 part=int(part),
                 identical=(None if identical is None
                            else bool(identical)),
                 thread=threading.get_ident())


# open OOM block-episode spans keyed by thread id (blocked/unblocked
# arrive as separate hook calls on the same thread; attach=False keeps
# them off the context stack so an out-of-order unblock cannot corrupt
# span nesting)
_BLOCK_SPANS: dict = {}
_BLOCK_SPANS_LOCK = threading.Lock()


def record_oom_event(kind: str, *, thread_id: int,
                     task_id: Optional[int], is_cpu: bool = False,
                     injected: bool = False, **extra) -> None:
    """OOM state machine hook: kind in {'oom_retry', 'oom_split_retry',
    'thread_blocked', 'thread_unblocked', 'thread_removed'}."""
    # the unblock/removed kinds must reach the span layer even with
    # tracing off: a block-episode span opened while tracing was on
    # would otherwise leak open in _BLOCK_SPANS forever
    if TRACER.enabled or kind in ("thread_unblocked", "thread_removed"):
        _record_oom_span(kind, thread_id, task_id, is_cpu, injected)
    if not _SWITCH.enabled:
        return
    device = "cpu" if is_cpu else "device"
    if kind == "oom_retry":
        OOM_RETRY.inc(labels=(device,))
    elif kind == "oom_split_retry":
        OOM_SPLIT_RETRY.inc(labels=(device,))
    elif kind == "thread_unblocked":
        THREAD_BLOCKED_TIME.inc(extra.get("blocked_ns", 0))
    TASKS.note_event(thread_id)
    JOURNAL.emit(kind, thread=thread_id,
                 task=task_id if task_id is not None else UNATTRIBUTED,
                 injected=injected, device=device, **extra)


def _record_oom_span(kind: str, thread_id: int, task_id, is_cpu: bool,
                     injected: bool) -> None:
    """Memory-runtime span emission: retry/split throws become instant
    spans; a blocked->unblocked episode becomes one span covering the
    whole wait."""
    attrs = {"device": "cpu" if is_cpu else "device",
             "injected": injected}
    if task_id is not None:
        attrs["task_id"] = task_id
    if kind in ("oom_retry", "oom_split_retry"):
        TRACER.start_span(kind, kind="oom", attrs=attrs,
                          attach=False).end()
    elif kind == "thread_blocked":
        span = TRACER.start_span("oom_blocked", kind="oom", attrs=attrs,
                                 attach=False)
        with _BLOCK_SPANS_LOCK:
            _BLOCK_SPANS[thread_id] = span
    elif kind in ("thread_unblocked", "thread_removed"):
        with _BLOCK_SPANS_LOCK:
            span = _BLOCK_SPANS.pop(thread_id, None)
        if span is not None:
            span.end()


def record_retry_episode(name: str, *, attempts: int, retries: int,
                         splits: int, max_split_depth: int,
                         lost_ns: int, outcome: str,
                         errors=()) -> None:
    """Retry-driver episode hook (robustness/retry.py) — called only
    for episodes that saw at least one failure."""
    if FLIGHT.enabled:
        FLIGHT.observe_retry_episode(name, outcome)
    if not _SWITCH.enabled:
        return
    RETRY_EPISODES.inc(labels=(outcome,))
    RETRY_ATTEMPTS.inc(attempts)
    RETRY_SPLITS.inc(splits)
    RETRY_TIME_LOST.inc(lost_ns)
    JOURNAL.emit("retry_episode", name=name, attempts=attempts,
                 retries=retries, splits=splits,
                 max_split_depth=max_split_depth, lost_ns=lost_ns,
                 outcome=outcome, errors=list(errors)[:16],
                 thread=threading.get_ident())


def record_kudo_corruption(reason: str, *, skipped_bytes: int = 0,
                           detail: str = "") -> None:
    """Kudo stream integrity hook: reason 'crc' for a trailer
    mismatch at the verify site, 'resync' for a skip-to-next-magic
    recovery (skipped_bytes > 0)."""
    if not _SWITCH.enabled:
        return
    KUDO_CORRUPT.inc(labels=(reason,))
    if skipped_bytes:
        KUDO_RESYNC_BYTES.inc(skipped_bytes)
    JOURNAL.emit("kudo_corrupt", reason=reason,
                 skipped_bytes=skipped_bytes, detail=detail[:200],
                 thread=threading.get_ident())


def record_spill(*, stage: str, tier: str, nbytes: int, ns: int,
                 task=None, name: str = "", generation: int = 0) -> None:
    """Tiered-store spill hook (memory/spill.py): one registered
    batch moved DOWN a tier (device->host or host->disk), freeing
    ``nbytes`` of the source tier."""
    if not _SWITCH.enabled:
        return
    st = stage or "-"
    SPILL_BYTES.inc(nbytes, labels=(st, tier))
    SPILL_TIME.inc(ns, labels=(st, "spill"))
    JOURNAL.emit("spill", stage=st, tier=tier, bytes=nbytes, ns=ns,
                 task=task, name=name, generation=generation,
                 thread=threading.get_ident())


def record_spill_restore(*, stage: str, tier: str, nbytes: int,
                         ns: int, task=None, name: str = "") -> None:
    """A spilled batch streamed back to the device from ``tier``."""
    if not _SWITCH.enabled:
        return
    st = stage or "-"
    SPILL_RESTORES.inc(labels=(st, tier))
    SPILL_TIME.inc(ns, labels=(st, "restore"))
    JOURNAL.emit("spill_restore", stage=st, tier=tier, bytes=nbytes,
                 ns=ns, task=task, name=name,
                 thread=threading.get_ident())


def record_spill_wait(ns: int, *, stage: str = "") -> None:
    """Synchronous wall time a query thread spent waiting on spill-
    store work (ensure_headroom victims, restore round trips) — the
    PR-16 ``spill_wait`` attribution bucket's journal source."""
    if not _SWITCH.enabled or ns <= 0:
        return
    JOURNAL.emit("spill_wait", stage=stage or "-", ns=ns,
                 thread=threading.get_ident())


def record_spill_corrupt(outcome: str, *, path: str = "",
                         generation: int = 0, name: str = "",
                         stage: str = "", task=None) -> None:
    """A spill payload failed CRC/parse verification on read-back:
    outcome 'recomputed' (rebuilt from source) or 'failed'."""
    if not _SWITCH.enabled:
        return
    SPILL_CORRUPT.inc(labels=(outcome,))
    JOURNAL.emit("spill_corrupt", outcome=outcome, path=path[:200],
                 generation=generation, name=name, stage=stage or "-",
                 task=task, thread=threading.get_ident())


def record_jit_cache(event: str, kernel: str, *,
                     compile_ns: int = 0) -> None:
    """Compile-cache hook (perf/jit_cache.py): event in
    {'hit', 'miss', 'eviction', 'compile_begin'}.  Misses carry the
    lower+compile wall time observed for the new executable;
    ``compile_begin`` marks the start of a compile and exists purely
    as a heartbeat edge (no counter)."""
    hook = _HEARTBEAT_HOOK
    if hook is not None:
        # both edges of a compile are signs of life (a long lower+
        # compile is the classic slow-but-alive window)
        hook(f"jit:{kernel}")
    if not _SWITCH.enabled:
        return
    if event == "hit":
        JIT_CACHE_HITS.inc(labels=(kernel,))
    elif event == "miss":
        JIT_CACHE_MISSES.inc(labels=(kernel,))
        JIT_COMPILE_TIME.observe(compile_ns, labels=(kernel,))
    elif event == "eviction":
        JIT_CACHE_EVICTIONS.inc(labels=(kernel,))


def record_result_cache(event: str, scope: str, *, tenant: str = "",
                        query: str = "", nbytes: int = 0,
                        ns: int = 0) -> None:
    """Semantic-cache hook (perf/result_cache.py): event in
    {'hit', 'miss', 'eviction', 'put', 'fold'}.  Result-scope events
    carry the tenant (per-tenant hit attribution); folds carry the
    query whose resident state absorbed an arriving batch."""
    if not _SWITCH.enabled:
        return
    tn = tenant or "-"
    if event == "hit":
        RESULT_CACHE_HITS.inc(labels=(scope, tn))
    elif event == "miss":
        RESULT_CACHE_MISSES.inc(labels=(scope, tn))
    elif event == "eviction":
        RESULT_CACHE_EVICTIONS.inc(labels=(scope,))
    elif event == "put":
        RESULT_CACHE_BYTES.inc(nbytes, labels=(scope,))
    elif event == "fold":
        RESULT_CACHE_FOLDS.inc(labels=(query or "-",))
    JOURNAL.emit("result_cache", event=event, scope=scope, tenant=tn,
                 query=query, bytes=nbytes, ns=ns,
                 thread=threading.get_ident())


def record_kernel_path(op: str, path: str, rows: int = 0) -> None:
    """One execution of ``op`` took ``path`` (calibrated kernel
    routing — joins, get_json_object, from_json, raw map).  Rows are
    journal-only color; the counter is the contract surface the
    metrics_report "kernel paths" table renders."""
    if not _SWITCH.enabled:
        return
    KERNEL_PATH.inc(labels=(op, path))
    JOURNAL.emit("kernel_path", op=op, path=path, rows=int(rows),
                 thread=threading.get_ident())


def record_stage_fusion(stage: str, outcome: str, *, digest: str = "",
                        wall_ns: int = 0, nodes: int = 0,
                        compiled: bool = False) -> None:
    """Whole-stage fusion hook (plan/compiler.py): one execution of
    ``stage`` took ``outcome`` ('fused' = one AOT executable,
    'unfused' = the op-by-op walk).  ``compiled`` marks runs that
    built a new fused executable (cache-hit runs don't); ``nodes`` is
    the dispatch count the unfused walk would pay.  The journal event
    feeds the metrics_report "stages" table."""
    if not _SWITCH.enabled:
        return
    STAGE_FUSION.inc(labels=(stage, outcome))
    if compiled:
        STAGE_FUSION.inc(labels=(stage, "compile"))
    JOURNAL.emit("stage_fusion", stage=stage, outcome=outcome,
                 digest=digest, wall_ns=int(wall_ns), nodes=int(nodes),
                 compiled=bool(compiled),
                 thread=threading.get_ident())


def record_lockdep(kind: str, *, cycle=(), op: str = "", held=(),
                   evidence: Optional[dict] = None) -> None:
    """Lockdep evidence hook (analysis/lockdep.py): ``kind`` is
    'cycle' (an acquisition-order cycle between lock classes — ABBA
    deadlock potential) or 'blocking' (a lock held across a known
    blocking call).  A cycle additionally freezes a ``lockdep_cycle``
    incident bundle when the recorder is armed, carrying the
    acquisition stacks of both directions — srt-doctor renders it as
    a ranked finding."""
    if kind == "cycle" and FLIGHT.enabled:
        trigger_incident("lockdep_cycle", severity="warn",
                         cycle=list(cycle),
                         evidence=evidence or {})
    if not _SWITCH.enabled:
        return
    if kind == "cycle":
        LOCKDEP_CYCLES.inc()
        JOURNAL.emit("lockdep", event="cycle", cycle=list(cycle),
                     thread=threading.get_ident())
    elif kind == "blocking":
        LOCKDEP_BLOCKING.inc(labels=(op,))
        JOURNAL.emit("lockdep", event="blocking", op=op,
                     held=list(held),
                     thread=threading.get_ident())


def record_exchange_doubling(from_capacity: int, to_capacity: int,
                             attempt: int) -> None:
    if not _SWITCH.enabled:
        return
    EXCHANGE_DOUBLINGS.inc()
    JOURNAL.emit("exchange_capacity_doubling", from_capacity=from_capacity,
                 to_capacity=to_capacity, attempt=attempt)


def record_device_memory(allocated_bytes: int) -> None:
    if not _SWITCH.enabled:
        return
    DEVICE_MEM_ALLOCATED.set(allocated_bytes)


def record_hbm_sample(device_index: int, bytes_in_use: int) -> None:
    if FLIGHT.enabled:
        FLIGHT.observe_hbm(device_index, bytes_in_use)
    if not _SWITCH.enabled:
        return
    HBM_BYTES_IN_USE.set(bytes_in_use, labels=(str(device_index),))


def record_task_leak(task_id: int, leaked_bytes: int,
                     holders=()) -> None:
    """Memory-ledger leak hook: ``task_done`` saw device bytes still
    attributed to the finishing task (the leak detector's feed, and a
    journal event so a later bundle still shows the history)."""
    if FLIGHT.enabled:
        FLIGHT.observe_task_leak(task_id, leaked_bytes, holders)
    if not _SWITCH.enabled:
        return
    MEMORY_LEAK_EVENTS.inc()
    MEMORY_LEAKED_BYTES.inc(leaked_bytes)
    JOURNAL.emit("memory_leak", task=task_id,
                 leaked_bytes=leaked_bytes,
                 holders=list(holders)[:8])


# ------------------------------------------------------------- ingest hooks
# (io/ calls these; per the layering rule io imports this package,
# never the reverse)


def record_io_read(source: str, nbytes: int, dur_ns: int) -> None:
    """Range-read hook (io/fileio.read_range): bytes fetched from
    storage and the fetch latency."""
    if not _SWITCH.enabled:
        return
    IO_READ_BYTES.inc(nbytes)
    IO_READ_TIME.observe(dur_ns)
    JOURNAL.emit("io_read", source=str(source)[-120:], bytes=nbytes,
                 dur_ns=dur_ns, thread=threading.get_ident())


def record_io_file(source: str, *, columns: int, pages: int, rows: int,
                   read_bytes: int, decode_ns: int) -> None:
    """Whole-file decode hook (io/parquet_reader.read_table): one
    journal record + the srt_io_* counters per materialized file."""
    if not _SWITCH.enabled:
        return
    IO_FILES.inc()
    IO_PAGES.inc(pages)
    IO_ROWS.inc(rows)
    IO_DECODE_TIME.inc(decode_ns)
    JOURNAL.emit("io_file", source=str(source)[-120:], columns=columns,
                 pages=pages, rows=rows, read_bytes=read_bytes,
                 decode_ns=decode_ns, thread=threading.get_ident())


# ------------------------------------------------------- query server hooks
# (server/ calls these; per the layering rule the server imports this
# package, never the reverse)


def record_server_admit(tenant: str, query: str, query_id: str,
                        queue_depth: int) -> None:
    if not _SWITCH.enabled:
        return
    SERVER_ADMITTED.inc(labels=(tenant,))
    JOURNAL.emit("server_admit", tenant=tenant, query=query,
                 query_id=query_id, queue_depth=queue_depth)


def record_server_reject(tenant: str, query: str, reason: str,
                         retry_after_s: float = 0.0) -> None:
    if not _SWITCH.enabled:
        return
    SERVER_REJECTED.inc(labels=(tenant, reason))
    JOURNAL.emit("server_reject", tenant=tenant, query=query,
                 reason=reason, retry_after_s=retry_after_s)


def record_server_dequeue(tenant: str, query_id: str,
                          wait_ns: int) -> None:
    if not _SWITCH.enabled:
        return
    SERVER_QUEUE_WAIT.observe(wait_ns, labels=(tenant,))
    JOURNAL.emit("server_dequeue", tenant=tenant, query_id=query_id,
                 wait_ns=wait_ns)


def record_server_requeue(tenant: str, query_id: str, reason: str,
                          demotions: int) -> None:
    if not _SWITCH.enabled:
        return
    SERVER_REQUEUED.inc(labels=(tenant, reason))
    JOURNAL.emit("server_requeue", tenant=tenant, query_id=query_id,
                 reason=reason, demotions=demotions)


def record_server_complete(tenant: str, query: str, query_id: str,
                           outcome: str, dur_ns: int,
                           wait_ns: int) -> None:
    # SLO feed first (independent switch): one SLI event per
    # completion, latency = what the caller experienced end to end
    if SLO.enabled:
        SLO.observe(tenant, outcome, int(wait_ns) + int(dur_ns))
    if not _SWITCH.enabled:
        return
    SERVER_COMPLETED.inc(labels=(tenant, outcome))
    JOURNAL.emit("server_complete", tenant=tenant, query=query,
                 query_id=query_id, outcome=outcome, dur_ns=dur_ns,
                 wait_ns=wait_ns)


def record_server_watchdog(action: str, tenant: str, query_id: str,
                           **extra) -> None:
    """Lifeguard watchdog intervention: ``deadline_cancel`` (the
    cooperative flag was fired), ``deadline_expired_queued`` (a queued
    job's deadline passed before dispatch), ``hang_release`` (a silent
    worker's task was force-released and the worker orphaned)."""
    if not _SWITCH.enabled:
        return
    SERVER_WATCHDOG.inc(labels=(action,))
    JOURNAL.emit("server_watchdog", action=action, tenant=tenant,
                 query_id=query_id, **extra)


def record_server_quarantine(event: str, tenant: str, query: str,
                             signature: str, **extra) -> None:
    """Poison-query circuit-breaker transition: event in {'opened',
    'reopened', 'probe', 'closed', 'rejected'}."""
    if not _SWITCH.enabled:
        return
    SERVER_QUARANTINE.inc(labels=(event,))
    JOURNAL.emit("server_quarantine", event=event, tenant=tenant,
                 query=query, signature=signature, **extra)


def record_server_drain(phase: str, **extra) -> None:
    """Graceful-drain lifecycle marker: phase in {'begin', 'end'}."""
    if not _SWITCH.enabled:
        return
    SERVER_DRAIN.inc(labels=(phase,))
    JOURNAL.emit("server_drain", phase=phase, **extra)


def set_server_tenant_gauges(queued: dict, running: dict,
                             deficit: dict, device_bytes: dict) -> None:
    """Per-tenant gauge refresh (the server calls this after every
    state transition with its current per-tenant snapshot)."""
    if not _SWITCH.enabled:
        return
    for tenant, v in queued.items():
        SERVER_QUEUED.set(v, labels=(tenant,))
    for tenant, v in running.items():
        SERVER_RUNNING.set(v, labels=(tenant,))
    for tenant, v in deficit.items():
        SERVER_FAIR_DEFICIT.set(round(float(v), 6), labels=(tenant,))
    for tenant, v in device_bytes.items():
        SERVER_TENANT_BYTES.set(int(v), labels=(tenant,))


# ------------------------------------------------------------------- dumping


def expose_text() -> str:
    """Prometheus text exposition of the process registry."""
    _refresh_liveness()
    return METRICS.expose_text()


def snapshot() -> dict:
    """JSON-able state: registry + per-task rollup + journal stats.
    Wall-clock anchored (``snapshot_unix_ms`` + ``uptime_s``): offline
    consumers place the per-process monotonic stamps in real time."""
    _refresh_liveness()
    return {
        "snapshot_unix_ms": int(time.time() * 1000),
        "uptime_s": round(time.monotonic() - _START_MONO, 3),
        "registry": METRICS.snapshot(),
        "tasks": {str(t): d for t, d in TASKS.rollup().items()},
        "journal": {"events": len(JOURNAL),
                    "dropped": JOURNAL.dropped,
                    "by_kind": JOURNAL.counts_by_kind()},
    }


def health() -> dict:
    """One-call process health rollup for the JVM shim's
    ``health_json``: switches, ring fill/drops, recorder stats, and a
    memory-ledger summary when the OOM runtime is installed."""
    _refresh_liveness()
    h = {
        "snapshot_unix_ms": int(time.time() * 1000),
        "start_unix_ms": int(_START_UNIX * 1000),
        "uptime_s": round(time.monotonic() - _START_MONO, 3),
        "pid": os.getpid(),
        "metrics_enabled": _SWITCH.enabled,
        "tracing_enabled": TRACER.enabled,
        "journal": {"events": len(JOURNAL), "dropped": JOURNAL.dropped},
        "spans": {"finished": len(TRACER), "dropped": TRACER.dropped},
        "flight_recorder": FLIGHT.stats(),
        "profiler": PROFILER.stats(),
        "monitor": {
            "last_sample_age_s": (
                None if _LAST_MONITOR_SAMPLE is None else
                round(max(0.0,
                          time.monotonic() - _LAST_MONITOR_SAMPLE), 3)),
            "timeseries_enabled": TIMESERIES.enabled,
            "timeseries_windows": len(TIMESERIES.windows()),
            "slo_enabled": SLO.enabled,
            "attribution_enabled": ATTRIBUTION.enabled,
        },
    }
    try:
        from spark_rapids_tpu.memory import rmm_spark
        from spark_rapids_tpu.memory import spark_resource_adaptor as sra
        adaptor = rmm_spark.installed_adaptor()
        if adaptor is not None:
            states = adaptor.thread_state_dump()
            h["memory"] = {
                "allocated_bytes": adaptor.gpu_memory_allocated_bytes,
                "threads": len(states),
                "blocked_threads": sum(
                    1 for s in states
                    if s["state"] in (sra.THREAD_BLOCKED,
                                      sra.THREAD_BUFN)),
            }
    except Exception:
        pass
    return h


def dump_spans_jsonl(path_or_file) -> int:
    """Finished-span ring as JSON Lines — one process's input file for
    ``tools/trace_export.py``.  Returns records written."""
    return TRACER.dump_jsonl(path_or_file)


def dump_journal_jsonl(path_or_file) -> int:
    """Journal ring + one ``task_rollup`` record per task + one
    ``registry_snapshot`` record, as JSON Lines — the input format of
    tools/metrics_report.py (and accepted by tools/profile_converter).
    Path writes are atomic (tmp + rename via dumpio): a crash mid-dump
    never leaves a truncated JSONL.  Returns records written."""
    import json as _json

    recs = JOURNAL.records()

    def _write(f):
        n = len(recs)
        for r in recs:
            f.write(_json.dumps(r) + "\n")
        for task_id, d in TASKS.rollup().items():
            f.write(_json.dumps(
                {"kind": "task_rollup", "task": task_id, **d}) + "\n")
            n += 1
        f.write(_json.dumps({"kind": "registry_snapshot",
                             "registry": METRICS.snapshot()}) + "\n")
        n += 1
        # the telemetry plane rides the same dump: the metrics report's
        # --window mode and srt-top's dump-dir tier read these records
        if TIMESERIES.enabled:
            f.write(_json.dumps({"kind": "timeseries_snapshot",
                                 **timeseries_snapshot()}) + "\n")
            n += 1
        if SLO.enabled:
            f.write(_json.dumps({"kind": "slo_status",
                                 "slo": SLO.status()}) + "\n")
            n += 1
        return n

    return dump_via(path_or_file, _write)


if os.environ.get("SPARK_RAPIDS_TPU_METRICS", "") not in ("", "0"):
    enable()
if os.environ.get("SPARK_RAPIDS_TPU_TRACE", "") not in ("", "0"):
    enable_tracing()
if os.environ.get("SPARK_RAPIDS_TPU_PROFILE", "") not in ("", "0"):
    enable_profiling()
if os.environ.get("SPARK_RAPIDS_TPU_TIMESERIES", "") not in ("", "0"):
    enable_timeseries()
if os.environ.get("SPARK_RAPIDS_TPU_SLO", "") not in ("", "0"):
    enable_slo()
if os.environ.get("SPARK_RAPIDS_TPU_ATTRIBUTION", "") not in ("", "0"):
    enable_attribution()
if os.environ.get("SPARK_RAPIDS_TPU_STATS", "") not in ("", "0"):
    enable_stats()
