"""Per-task metrics accumulation keyed by the Spark task ids the OOM
runtime already tracks.

The reference rolls numbers up per Spark task through RmmSpark's
getAndReset* surface (task threads register via
setCurrentThreadAsTask / poolThreadWorkingOnTasks, and the native
adaptor checkpoints per-thread metrics into per-task buckets —
SparkResourceAdaptorJni.cpp).  This table is the cross-subsystem
generalization: the SAME thread→task binding (fed by
memory/rmm_spark.py registration wrappers) attributes op latencies,
shuffle bytes, and journal events to tasks, and the OOM state
machine's own per-task counters are folded in when a task finishes.

Threads with no task binding accumulate under task id -1 so driver-side
/ test-harness activity still shows up in reports instead of vanishing.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Set

UNATTRIBUTED = -1


class TaskMetrics:
    """One task's accumulated numbers (observability-wide superset of
    memory.spark_resource_adaptor.TaskMetrics, which stays the OOM state
    machine's internal type)."""

    __slots__ = ("op_calls", "op_time_ns", "shuffle_write_bytes",
                 "shuffle_write_time_ns", "shuffle_merge_rows",
                 "shuffle_merge_time_ns", "retry_oom", "split_retry_oom",
                 "blocked_time_ns", "lost_time_ns", "max_device_memory",
                 "events")

    def __init__(self):
        self.op_calls: Dict[str, int] = {}
        self.op_time_ns: Dict[str, int] = {}
        self.shuffle_write_bytes = 0
        self.shuffle_write_time_ns = 0
        self.shuffle_merge_rows = 0
        self.shuffle_merge_time_ns = 0
        self.retry_oom = 0
        self.split_retry_oom = 0
        self.blocked_time_ns = 0
        self.lost_time_ns = 0
        self.max_device_memory = 0
        self.events = 0

    def as_dict(self) -> dict:
        return {
            "ops": {op: {"calls": self.op_calls[op],
                         "time_ns": self.op_time_ns.get(op, 0)}
                    for op in sorted(self.op_calls)},
            "shuffle_write_bytes": self.shuffle_write_bytes,
            "shuffle_write_time_ns": self.shuffle_write_time_ns,
            "shuffle_merge_rows": self.shuffle_merge_rows,
            "shuffle_merge_time_ns": self.shuffle_merge_time_ns,
            "retry_oom": self.retry_oom,
            "split_retry_oom": self.split_retry_oom,
            "blocked_time_ns": self.blocked_time_ns,
            "lost_time_ns": self.lost_time_ns,
            "max_device_memory": self.max_device_memory,
            "events": self.events,
        }


class TaskMetricsTable:
    """Thread→task binding plus per-task accumulators.

    Bindings mirror the RmmSpark registration calls 1:1 (dedicated task
    threads bind to one task, pool/shuffle threads to a set); the
    adaptor's remove-thread callback unbinds, so the two maps cannot
    drift."""

    def __init__(self, enabled_ref=None):
        self._enabled_ref = enabled_ref
        self._lock = threading.Lock()
        self._thread_tasks: Dict[int, Set[int]] = {}
        self._tasks: Dict[int, TaskMetrics] = {}

    def _on(self) -> bool:
        ref = self._enabled_ref
        return ref is None or ref.enabled

    # --------------------------------------------------------- bindings

    # Bindings are NOT gated on the enabled switch: they must mirror the
    # RmmSpark registration calls even while metrics are off, or an
    # off-window unbind is lost and a reused thread ident misattributes
    # later work to a finished task.  They are rare (per task, not per
    # op), so the always-on cost is a dict op at task registration.

    def bind_thread(self, thread_id: int, task_ids: Iterable[int]):
        with self._lock:
            self._thread_tasks.setdefault(thread_id, set()).update(task_ids)

    def unbind_thread(self, thread_id: int,
                      task_ids: Optional[Iterable[int]] = None):
        with self._lock:
            cur = self._thread_tasks.get(thread_id)
            if cur is None:
                return
            if task_ids is None:
                del self._thread_tasks[thread_id]
            else:
                cur.difference_update(task_ids)
                if not cur:
                    del self._thread_tasks[thread_id]

    def tasks_for(self, thread_id: Optional[int] = None) -> List[int]:
        if thread_id is None:
            thread_id = threading.get_ident()
        with self._lock:
            ids = self._thread_tasks.get(thread_id)
            return sorted(ids) if ids else [UNATTRIBUTED]

    # ------------------------------------------------------ accumulation

    def _targets(self, thread_id: Optional[int]) -> List[TaskMetrics]:
        if thread_id is None:
            thread_id = threading.get_ident()
        ids = self._thread_tasks.get(thread_id) or (UNATTRIBUTED,)
        return [self._tasks.setdefault(t, TaskMetrics()) for t in ids]

    def note_op(self, op: str, dur_ns: int,
                thread_id: Optional[int] = None):
        if not self._on():
            return
        with self._lock:
            for tm in self._targets(thread_id):
                tm.op_calls[op] = tm.op_calls.get(op, 0) + 1
                tm.op_time_ns[op] = tm.op_time_ns.get(op, 0) + dur_ns

    def note_shuffle_write(self, num_bytes: int, dur_ns: int,
                           thread_id: Optional[int] = None):
        if not self._on():
            return
        with self._lock:
            for tm in self._targets(thread_id):
                tm.shuffle_write_bytes += num_bytes
                tm.shuffle_write_time_ns += dur_ns

    def note_shuffle_merge(self, rows: int, dur_ns: int,
                           thread_id: Optional[int] = None):
        if not self._on():
            return
        with self._lock:
            for tm in self._targets(thread_id):
                tm.shuffle_merge_rows += rows
                tm.shuffle_merge_time_ns += dur_ns

    def note_event(self, thread_id: Optional[int] = None):
        if not self._on():
            return
        with self._lock:
            for tm in self._targets(thread_id):
                tm.events += 1

    def fold_rmm_task(self, task_id: int, *, retry_oom: int = 0,
                      split_retry_oom: int = 0, blocked_time_ns: int = 0,
                      lost_time_ns: int = 0, max_device_memory: int = 0):
        """Fold the OOM state machine's per-task counters (the
        getAndResetNumRetryThrow / getTotalBlockedOrLostTime analogs)
        into this task's row — called at task_done."""
        if not self._on():
            return
        with self._lock:
            tm = self._tasks.setdefault(task_id, TaskMetrics())
            tm.retry_oom += retry_oom
            tm.split_retry_oom += split_retry_oom
            tm.blocked_time_ns += blocked_time_ns
            tm.lost_time_ns += lost_time_ns
            tm.max_device_memory = max(tm.max_device_memory,
                                       max_device_memory)

    # ------------------------------------------------------------ report

    def rollup(self) -> Dict[int, dict]:
        with self._lock:
            return {t: tm.as_dict() for t, tm in sorted(self._tasks.items())}

    def bound_threads(self) -> Dict[int, List[int]]:
        with self._lock:
            return {tid: sorted(ts)
                    for tid, ts in sorted(self._thread_tasks.items())}

    def reset(self):
        with self._lock:
            self._thread_tasks.clear()
            self._tasks.clear()
