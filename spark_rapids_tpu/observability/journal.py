"""Structured event journal: append-only, ring-buffered, JSONL-dumpable.

The reference's explain-why-it-was-slow surface is event-shaped, not
gauge-shaped: RmmSpark logs every OOM retry/split/block transition to a
CSV state log, the CUPTI profiler streams activity records, kudo counts
writes/merges.  This journal is the unified host for those discrete
events here: OOM retry/split/block/remove, shuffle writes/merges,
exchange capacity-doublings, task completion rollups.

Records are plain dicts with the same ``kind``/``t_ns`` envelope as the
profiler's DataWriter records (utils/profiler.py), so
tools/profile_converter.py can interleave a journal dump with a
profiler stream on one timeline.  The buffer is a bounded ring — a
long-lived executor can emit forever; readers get the most recent
`capacity` events plus a count of how many were overwritten.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional


class EventJournal:
    def __init__(self, capacity: int = 8192, enabled_ref=None,
                 on_drop=None):
        """`enabled_ref`: object with a truthy `.enabled` attribute
        consulted on every emit (the shared observability switch);
        None means always-on (tests).  `on_drop(n)`: called (outside
        the ring lock) each time `n` events are overwritten by ring
        wrap-around — observability points it at the
        ``srt_journal_dropped_total`` counter so drops are no longer
        silent."""
        self.capacity = capacity
        self._enabled_ref = enabled_ref
        self._on_drop = on_drop
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0

    # ------------------------------------------------------------- write

    def emit(self, kind: str, **fields) -> None:
        """Append one event.  Near-zero cost when the shared switch is
        off: a single attribute read and return."""
        ref = self._enabled_ref
        if ref is not None and not ref.enabled:
            return
        rec = {"kind": kind, "t_ns": time.monotonic_ns(), **fields}
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            dropping = len(self._ring) == self._ring.maxlen
            self._ring.append(rec)
        if dropping and self._on_drop is not None:
            try:
                self._on_drop(1)
            except Exception:
                pass  # accounting must never break the emitting layer

    # -------------------------------------------------------------- read

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def total_emitted(self) -> int:
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        """Events overwritten by ring wrap-around."""
        with self._lock:
            return self._seq - len(self._ring)

    def records(self, kind: Optional[str] = None) -> List[Dict]:
        with self._lock:
            recs = list(self._ring)
        if kind is not None:
            recs = [r for r in recs if r.get("kind") == kind]
        return recs

    def counts_by_kind(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in self.records():
            k = r.get("kind", "?")
            out[k] = out.get(k, 0) + 1
        return out

    # -------------------------------------------------------------- dump

    def dump_jsonl(self, path_or_file) -> int:
        """Write the current ring as JSON Lines; returns record count.
        Accepts a path or an open text file object; path writes are
        atomic (tmp + rename) so a crash mid-dump never leaves a
        truncated file."""
        from spark_rapids_tpu.observability.dumpio import dump_via

        recs = self.records()

        def _write(f):
            for r in recs:
                f.write(json.dumps(r) + "\n")
            return len(recs)

        return dump_via(path_or_file, _write)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = 0
