"""Cross-rank critical-path solver over the stitched fleet trace
(ISSUE 17 tentpole, half two).

The merged fleet profile says WHICH stage was slow per rank; this
module says which CHAIN of spans — across ranks — the query wall
actually waited on.  Input is the per-rank span dumps the distributed
runner already writes (``spans_rank{r}.jsonl``): rank-local span
records with monotonic ``t_ns`` starts, plus the kudo KTRX ``links``
(merge span -> writer spans) that are the only physical cross-rank
ordering evidence.

Clock normalization: each rank's monotonic clock has an arbitrary
epoch, so raw cross-rank gaps are meaningless and can even be negative
(skew "time travel").  For every rank pair with link edges in BOTH
directions the true one-way gaps are unknowable, but their SUM is
skew-free — so the midpoint rule ``o = (min_gap_ba - min_gap_ab) / 2``
exactly cancels the skew term and never fabricates a negative edge.
One-directional pairs get the weaker min-gap-zero correction (only
applied when the raw minimum is negative, so honest wire latency on a
well-behaved clock survives).  Offsets propagate from the lowest rank
by BFS; any residual negative edge after normalization is clamped to
zero and COUNTED (``clamped_edges``) — the smoke and the skew test
gate on zero.

The DAG: leaf spans (containers — process/query roots and any span
that encloses another selected span on its own rank+thread — are
dropped) are nodes; consecutive leaves on one (rank, thread) lane are
sequential edges whose gap is lane idle time; KTRX links are exchange
edges whose gap is wire + peer wait.  The longest path by covered time
(sum of span durations plus edge gaps) is the critical path; exchange
edges are ALSO emitted as a ranked list, largest gap first — under an
injected ``slow:dst:ms`` link fault the slowed link's edge ranks
first, which is exactly the evidence the smoke gates on.

Pure functions over span dicts: no singletons, no clocks, no I/O —
tools and tests feed it loaded JSONL records.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# span kinds that are pure containers: they enclose the spans that do
# the work, so they never become path nodes themselves
_CONTAINER_KINDS = ("process", "query")

# span kind -> attribution bucket for path segments (the ledger's
# vocabulary, so --where and --critical-path tell one story)
_KIND_BUCKET = {
    "compile": "compile",
    "shuffle_write": "shuffle_wire",
    "shuffle_merge": "shuffle_wire",
    "shuffle_send": "shuffle_wire",
    "stage": "compute",
    "op": "compute",
    "io": "compute",
}

# backstop against pathological dumps: per-rank span cap (the solver
# is O(n^2) per lane for containment) — excess spans are dropped
# LOUDLY via the result's ``truncated_ranks``
_MAX_SPANS_PER_RANK = 20_000


def _span_rows(records: List[dict], rank: int) -> List[dict]:
    rows = []
    for r in records:
        if r.get("kind") != "span":
            continue
        try:
            rows.append({
                "rank": rank,
                "name": str(r.get("name", "?")),
                "span_kind": str(r.get("span_kind", "?")),
                "span_id": r.get("span_id"),
                "thread": r.get("thread", 0),
                "t_ns": int(r.get("t_ns", 0)),
                "dur_ns": max(int(r.get("dur_ns", 0)), 0),
                "links": [l.get("span_id")
                          for l in (r.get("links") or [])
                          if isinstance(l, dict)],
            })
        except (TypeError, ValueError):
            continue  # a torn record must not sink the whole solve
    return rows


def _link_edges(spans: List[dict]) -> List[Tuple[dict, dict]]:
    """(writer_span, linking_span) pairs resolved through the KTRX
    ``links`` extension.  Links to span ids the dump never saw (a
    truncated ring) are skipped — absence of evidence, not negative
    evidence."""
    by_id = {s["span_id"]: s for s in spans
             if s.get("span_id") is not None}
    out = []
    for s in spans:
        for lid in s["links"]:
            src = by_id.get(lid)
            if src is not None and src is not s:
                out.append((src, s))
    return out


def normalize_clocks(spans_by_rank: Dict[int, List[dict]],
                     links: List[Tuple[dict, dict]]
                     ) -> Dict[int, int]:
    """Per-rank additive clock offsets (ns) from the cross-rank link
    evidence.  The lowest rank anchors at zero; pairs connected in
    both directions use the skew-cancelling midpoint rule, one-way
    pairs the min-gap-zero floor; unconnected ranks stay at zero
    (nothing orders them, so nothing can mis-order them either)."""
    ranks = sorted(spans_by_rank)
    offsets = {r: 0 for r in ranks}
    if len(ranks) < 2:
        return offsets
    # min raw gap per ordered pair (src_rank -> dst_rank)
    min_gap: Dict[Tuple[int, int], int] = {}
    for src, dst in links:
        a, b = src["rank"], dst["rank"]
        if a == b:
            continue
        gap = dst["t_ns"] - (src["t_ns"] + src["dur_ns"])
        key = (a, b)
        if key not in min_gap or gap < min_gap[key]:
            min_gap[key] = gap
    # pair deltas: d[(a, b)] = offset(b) - offset(a)
    deltas: Dict[Tuple[int, int], int] = {}
    for (a, b), g_ab in min_gap.items():
        if (b, a) in deltas or (a, b) in deltas:
            continue
        g_ba = min_gap.get((b, a))
        if g_ba is not None:
            # both directions: midpoint exactly cancels the skew
            deltas[(a, b)] = (g_ba - g_ab) // 2
        else:
            # one way: only repair a negative minimum
            deltas[(a, b)] = max(0, -g_ab)
    # BFS from the lowest connected rank; first assignment wins
    adj: Dict[int, List[Tuple[int, int]]] = {}
    for (a, b), d in deltas.items():
        adj.setdefault(a, []).append((b, d))
        adj.setdefault(b, []).append((a, -d))
    seen = set()
    for root in ranks:
        if root in seen or root not in adj:
            continue
        seen.add(root)
        frontier = [root]
        while frontier:
            nxt = []
            for a in frontier:
                for b, d in adj.get(a, ()):
                    if b in seen:
                        continue
                    seen.add(b)
                    offsets[b] = offsets[a] + d
                    nxt.append(b)
            frontier = nxt
    return offsets


def _leaves(spans: List[dict]) -> List[dict]:
    """Drop containers: declared container kinds, plus any span that
    encloses another surviving span on its own (rank, thread) lane."""
    cands = [s for s in spans
             if s["span_kind"] not in _CONTAINER_KINDS]
    lanes: Dict[Tuple[int, int], List[dict]] = {}
    for s in cands:
        lanes.setdefault((s["rank"], s["thread"]), []).append(s)
    out = []
    for lane in lanes.values():
        lane.sort(key=lambda s: (s["t_ns"], -s["dur_ns"]))
        for i, s in enumerate(lane):
            end = s["t_ns"] + s["dur_ns"]
            contains = False
            for o in lane[i + 1:]:
                if o["t_ns"] >= end:
                    break
                if o["t_ns"] + o["dur_ns"] <= end \
                        and o is not s:
                    contains = True
                    break
            if not contains:
                out.append(s)
    return out


def critical_path(spans_by_rank: Dict[int, List[dict]],
                  *, top_edges: int = 8) -> dict:
    """Solve the cross-rank critical path.  ``spans_by_rank`` maps
    rank -> raw tracer records (span and non-span kinds mixed is
    fine).  Returns the ranked path, the exchange-edge leaderboard,
    the clock offsets and the clamp count."""
    truncated = []
    spans: List[dict] = []
    per_rank: Dict[int, List[dict]] = {}
    for rank in sorted(spans_by_rank):
        rows = _span_rows(spans_by_rank[rank], int(rank))
        if len(rows) > _MAX_SPANS_PER_RANK:
            rows = rows[:_MAX_SPANS_PER_RANK]
            truncated.append(int(rank))
        per_rank[int(rank)] = rows
        spans.extend(rows)
    if not spans:
        return {"path": [], "exchange_edges": [],
                "clock_offsets": {}, "clamped_edges": 0,
                "total_ns": 0, "truncated_ranks": truncated}

    links = _link_edges(spans)
    offsets = normalize_clocks(per_rank, links)
    for s in spans:
        s["n_start"] = s["t_ns"] + offsets[s["rank"]]
        s["n_end"] = s["n_start"] + s["dur_ns"]

    nodes = _leaves(spans)
    node_ids = {id(s) for s in nodes}
    # edges: (src, dst, gap, kind)
    clamped = 0
    edges: List[Tuple[dict, dict, int, str]] = []
    lanes: Dict[Tuple[int, int], List[dict]] = {}
    for s in nodes:
        lanes.setdefault((s["rank"], s["thread"]), []).append(s)
    for lane in lanes.values():
        lane.sort(key=lambda s: s["n_start"])
        for a, b in zip(lane, lane[1:]):
            gap = b["n_start"] - a["n_end"]
            if gap < 0:
                gap, clamped = 0, clamped + 1
            edges.append((a, b, gap, "sequential"))
    exchange_edges = []
    for src, dst in links:
        # a link may point at a container (the write span survived
        # but the merge got folded): lift to whichever side is a node
        if id(src) not in node_ids or id(dst) not in node_ids:
            continue
        gap = dst["n_start"] - src["n_end"]
        if gap < 0:
            gap, clamped = 0, clamped + 1
        edges.append((src, dst, gap, "exchange"))
        exchange_edges.append({
            "kind": "exchange_edge",
            "from_rank": src["rank"], "to_rank": dst["rank"],
            "from": src["name"], "to": dst["name"],
            "gap_ns": gap,
        })
    exchange_edges.sort(key=lambda e: -e["gap_ns"])

    # longest covered-time path: DP in normalized-start order (every
    # edge points forward in normalized time once gaps are clamped)
    nodes.sort(key=lambda s: (s["n_start"], s["n_end"]))
    index = {id(s): i for i, s in enumerate(nodes)}
    incoming: Dict[int, List[Tuple[int, int, str]]] = {}
    for a, b, gap, kind in edges:
        incoming.setdefault(index[id(b)], []).append(
            (index[id(a)], gap, kind))
    score = [0] * len(nodes)
    best_pred: List[Optional[Tuple[int, int, str]]] = \
        [None] * len(nodes)
    for i, s in enumerate(nodes):
        base = 0
        for j, gap, kind in incoming.get(i, ()):
            if j >= i:
                continue  # clamp artifact: never walk backwards
            cand = score[j] + gap
            if cand > base:
                base = cand
                best_pred[i] = (j, gap, kind)
        score[i] = base + s["dur_ns"]
    if not nodes:
        return {"path": [], "exchange_edges": exchange_edges,
                "clock_offsets": {str(r): o
                                  for r, o in offsets.items()},
                "clamped_edges": clamped, "total_ns": 0,
                "truncated_ranks": truncated}
    tail = max(range(len(nodes)), key=lambda i: score[i])
    chain: List[Tuple[int, int, str]] = []  # (node, gap_in, kind_in)
    i: Optional[int] = tail
    gap_in, kind_in = 0, "start"
    while i is not None:
        chain.append((i, gap_in, kind_in))
        pred = best_pred[i]
        if pred is None:
            break
        i, gap_in, kind_in = pred
    chain.reverse()
    t0 = nodes[chain[0][0]]["n_start"] if chain else 0
    path = []
    for i, gap, kind in chain:
        s = nodes[i]
        path.append({
            "rank": s["rank"],
            "thread": s["thread"],
            "name": s["name"],
            "span_kind": s["span_kind"],
            "bucket": _KIND_BUCKET.get(s["span_kind"], "other"),
            "start_ns": s["n_start"] - t0,
            "dur_ns": s["dur_ns"],
            "gap_in_ns": gap,
            "edge_in": kind,
        })
    # the path's own exchange hops get flagged on the leaderboard
    on_path = set()
    for seg in path:
        if seg["edge_in"] == "exchange":
            on_path.add((seg["rank"], seg["name"]))
    for e in exchange_edges:
        e["on_path"] = (e["to_rank"], e["to"]) in on_path
    return {
        "path": path,
        "exchange_edges": exchange_edges[:max(top_edges, 0)],
        "clock_offsets": {str(r): o for r, o in offsets.items()},
        "clamped_edges": clamped,
        "total_ns": score[tail],
        "truncated_ranks": truncated,
    }
