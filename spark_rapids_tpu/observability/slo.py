"""Per-tenant SLO definitions + multi-window burn-rate monitoring
(ISSUE 16 tentpole, subsystem 2 of 3).

An SLO here is the serving-system contract the Presto-on-GPUs line of
work is judged on: *objective* fraction of a tenant's queries must
succeed within a *latency target*, end to end (admission wait +
execution — the number a caller actually experiences).  Every server
completion becomes one SLI event:

    good  :=  outcome == "success"  AND  latency_ns <= target

The monitor tracks the bad fraction over TWO sliding windows — a fast
one (default 60 s) for responsiveness and a slow one (default 600 s)
to suppress blips — and converts each to a *burn rate*: the observed
bad fraction divided by the error budget (1 - objective).  Burn 1.0
means the budget is being spent exactly as provisioned; the alert
fires only when BOTH windows exceed the threshold (the classic
multi-window multi-burn rule), which rides the ``slo_burn``
flight-recorder trigger so the incident bundle freezes the timeseries
ring tail + the offending tenant's snapshot alongside the usual
evidence.

Everything takes an injectable clock so tests and the CI smoke drive
minutes of burn in milliseconds, and ``observe()`` is a deque append —
safe on the server completion path.  One attribute read when the
monitor is disabled (same switch discipline as every other hook).

Configuration (``SloMonitor.from_env``):

  SPARK_RAPIDS_TPU_SLO                enable ("1")
  SPARK_RAPIDS_TPU_SLO_CONFIG         inline JSON or @/path/to/file:
      {"*":       {"latency_ms": 250, "objective": 0.99},
       "tenantA": {"latency_ms": 50,  "objective": 0.999}}
      ("*" is the default applied to tenants without their own entry;
      with no config at all every tenant gets the built-in default)
  SPARK_RAPIDS_TPU_SLO_FAST_S         fast burn window (default 60)
  SPARK_RAPIDS_TPU_SLO_SLOW_S         slow burn window (default 600)
  SPARK_RAPIDS_TPU_SLO_BURN_THRESHOLD fire when both windows exceed
                                      this burn rate (default 4.0)
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

DEFAULT_LATENCY_MS = 250.0
DEFAULT_OBJECTIVE = 0.99
DEFAULT_FAST_S = 60.0
DEFAULT_SLOW_S = 600.0
DEFAULT_BURN_THRESHOLD = 4.0

# outcomes that do not consume error budget: the tenant asked for the
# cancel, and a shed/rejected query never ran — admission-control
# pushback is reported by the server stats, not double-counted as an
# SLO miss (deadline/failed/hung DO burn budget).  cache_hit is
# neutral in BOTH directions: a free warm answer must not count as a
# latency win either, or a cache-heavy replay would mask a burning
# tenant (ISSUE 19)
_NEUTRAL_OUTCOMES = frozenset({"cancelled", "rejected", "shed",
                               "requeued", "admitted", "cache_hit"})


@dataclass(frozen=True)
class SloConfig:
    """One tenant's objective: latency target + success-ratio goal."""

    latency_target_ns: int = int(DEFAULT_LATENCY_MS * 1e6)
    objective: float = DEFAULT_OBJECTIVE

    def __post_init__(self):
        if not 0.0 < self.objective < 1.0:
            raise ValueError(f"slo objective must be in (0,1): "
                             f"{self.objective}")

    @property
    def error_budget(self) -> float:
        return max(1.0 - self.objective, 1e-9)

    def to_dict(self) -> dict:
        return {"latency_ms": self.latency_target_ns / 1e6,
                "objective": self.objective}

    @classmethod
    def from_dict(cls, d: dict) -> "SloConfig":
        ms = float(d.get("latency_ms", DEFAULT_LATENCY_MS))
        obj = float(d.get("objective", DEFAULT_OBJECTIVE))
        if not 0.0 < obj < 1.0:
            raise ValueError(f"slo objective must be in (0,1): {obj}")
        return cls(latency_target_ns=int(ms * 1e6), objective=obj)


def parse_slo_config(spec: str) -> Dict[str, SloConfig]:
    """``SPARK_RAPIDS_TPU_SLO_CONFIG`` parser: inline JSON object or
    ``@path`` indirection.  Malformed config raises — a serving fleet
    silently monitoring the wrong objective is worse than failing to
    boot."""
    spec = spec.strip()
    if not spec:
        return {}
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            spec = f.read()
    obj = json.loads(spec)
    if not isinstance(obj, dict):
        raise ValueError("slo config must be a JSON object "
                         "keyed by tenant")
    return {str(t): SloConfig.from_dict(d) for t, d in obj.items()}


class _TenantState:
    __slots__ = ("config", "events", "good_total", "bad_total",
                 "breaches", "last_fire", "burn_fast", "burn_slow")

    def __init__(self, config: SloConfig):
        self.config = config
        # (t_mono, good) — pruned to the slow window on evaluate
        self.events: deque = deque()
        self.good_total = 0
        self.bad_total = 0
        self.breaches = 0
        self.last_fire: Optional[float] = None
        self.burn_fast = 0.0
        self.burn_slow = 0.0


class SloMonitor:
    """Multi-window burn-rate evaluator over server completion events.

    ``observe()`` runs on the completion path (cheap); ``evaluate()``
    runs at window granularity off the Monitor thread and returns the
    list of tenants whose burn alert fired this round (already
    cooldown-filtered) — the observability wiring turns each into one
    ``slo_burn`` incident."""

    def __init__(self, configs: Optional[Dict[str, SloConfig]] = None,
                 *, fast_s: float = DEFAULT_FAST_S,
                 slow_s: float = DEFAULT_SLOW_S,
                 threshold: float = DEFAULT_BURN_THRESHOLD,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_burn: Optional[Callable[[str, dict], None]] = None,
                 max_tenants: int = 256):
        self.enabled = False
        self.configs = dict(configs or {})
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.threshold = float(threshold)
        # one alert per tenant per slow window by default: the CI smoke
        # asserts EXACTLY one bundle for the injected-slow tenant
        self.cooldown_s = float(cooldown_s if cooldown_s is not None
                                else slow_s)
        self.on_burn = on_burn
        self.max_tenants = int(max_tenants)
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: Dict[str, _TenantState] = {}
        self._last_eval: Optional[float] = None

    @classmethod
    def from_env(cls, environ=os.environ, **kw) -> "SloMonitor":
        configs = parse_slo_config(
            environ.get("SPARK_RAPIDS_TPU_SLO_CONFIG", ""))

        def _f(name, default):
            raw = environ.get(name, "")
            return float(raw) if raw else default

        return cls(configs,
                   fast_s=_f("SPARK_RAPIDS_TPU_SLO_FAST_S",
                             DEFAULT_FAST_S),
                   slow_s=_f("SPARK_RAPIDS_TPU_SLO_SLOW_S",
                             DEFAULT_SLOW_S),
                   threshold=_f("SPARK_RAPIDS_TPU_SLO_BURN_THRESHOLD",
                                DEFAULT_BURN_THRESHOLD),
                   **kw)

    # -------------------------------------------------------- ingest

    def _config_for(self, tenant: str) -> SloConfig:
        return self.configs.get(tenant) \
            or self.configs.get("*") \
            or SloConfig()

    def observe(self, tenant: str, outcome: str, latency_ns: int,
                now: Optional[float] = None) -> None:
        """One SLI event from the server completion hook.  Neutral
        outcomes (tenant-initiated cancels, admission pushback) are
        ignored — they spend no error budget."""
        if not self.enabled:
            return
        if outcome in _NEUTRAL_OUTCOMES:
            return
        now = self._clock() if now is None else now
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                if len(self._tenants) >= self.max_tenants:
                    return  # bounded like every per-tenant table
                st = self._tenants[tenant] = _TenantState(
                    self._config_for(tenant))
            good = (outcome == "success"
                    and latency_ns <= st.config.latency_target_ns)
            st.events.append((now, good))
            if good:
                st.good_total += 1
            else:
                st.bad_total += 1

    # ------------------------------------------------------ evaluate

    @staticmethod
    def _bad_fraction(events, cutoff: float) -> Optional[float]:
        good = bad = 0
        for t, g in events:
            if t < cutoff:
                continue
            if g:
                good += 1
            else:
                bad += 1
        n = good + bad
        return (bad / n) if n else None

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Recompute every tenant's burn rates; returns the alerts that
        fired this round as ``[{"tenant", "burn_fast", "burn_slow",
        ...}]`` (cooldown already applied).  Also invokes ``on_burn``
        per alert when set."""
        if not self.enabled:
            return []
        now = self._clock() if now is None else now
        fired: List[dict] = []
        with self._lock:
            for tenant, st in self._tenants.items():
                while st.events and st.events[0][0] < now - self.slow_s:
                    st.events.popleft()
                bf = self._bad_fraction(st.events, now - self.fast_s)
                bs = self._bad_fraction(st.events, now - self.slow_s)
                budget = st.config.error_budget
                st.burn_fast = (bf / budget) if bf is not None else 0.0
                st.burn_slow = (bs / budget) if bs is not None else 0.0
                if st.burn_fast >= self.threshold \
                        and st.burn_slow >= self.threshold:
                    if st.last_fire is not None \
                            and now - st.last_fire < self.cooldown_s:
                        continue
                    st.last_fire = now
                    st.breaches += 1
                    fired.append({
                        "tenant": tenant,
                        "burn_fast": round(st.burn_fast, 3),
                        "burn_slow": round(st.burn_slow, 3),
                        "fast_window_s": self.fast_s,
                        "slow_window_s": self.slow_s,
                        "threshold": self.threshold,
                        "objective": st.config.objective,
                        "latency_target_ms":
                            st.config.latency_target_ns / 1e6,
                        "attainment": self._attainment_locked(st),
                    })
        if self.on_burn is not None:
            for alert in fired:
                self.on_burn(alert["tenant"], alert)
        return fired

    def maybe_evaluate(self, now: Optional[float] = None
                       ) -> Optional[List[dict]]:
        """Throttled evaluate for the Monitor-thread drive path: runs
        at most every fast_s/10 (>= 0.5 s) so a fast sample period
        does not re-scan every tenant's event deque each tick.
        Returns None when throttled, else the fired alerts."""
        if not self.enabled:
            return None
        now = self._clock() if now is None else now
        period = max(self.fast_s / 10.0, 0.5)
        if self._last_eval is not None \
                and now - self._last_eval < period:
            return None
        self._last_eval = now
        return self.evaluate(now)

    # -------------------------------------------------------- status

    @staticmethod
    def _attainment_locked(st: _TenantState) -> float:
        n = st.good_total + st.bad_total
        return (st.good_total / n) if n else 1.0

    def attainment(self, tenant: str) -> float:
        """Lifetime good fraction for one tenant (1.0 when it has no
        budget-consuming events yet)."""
        with self._lock:
            st = self._tenants.get(tenant)
            return self._attainment_locked(st) if st else 1.0

    def status(self) -> Dict[str, dict]:
        """JSON-able per-tenant SLO view — embedded in server stats,
        timeseries snapshots and the metrics-report "slo" section."""
        with self._lock:
            out = {}
            for tenant in sorted(self._tenants):
                st = self._tenants[tenant]
                out[tenant] = {
                    "latency_target_ms":
                        st.config.latency_target_ns / 1e6,
                    "objective": st.config.objective,
                    "events": st.good_total + st.bad_total,
                    "attainment": round(self._attainment_locked(st), 6),
                    "burn_fast": round(st.burn_fast, 3),
                    "burn_slow": round(st.burn_slow, 3),
                    "breaches": st.breaches,
                }
            return out

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    def reset(self) -> None:
        with self._lock:
            self._tenants.clear()
            self._last_eval = None
