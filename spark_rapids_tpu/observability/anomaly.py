"""Anomaly detectors feeding the flight recorder.

Canopy-style trigger model (PAPERS.md): the always-on spine records
everything into bounded rings; these detectors watch the streams the
spine already produces and decide the MOMENT something is wrong, so the
flight recorder can freeze the rings into an incident bundle while the
evidence is still in them.

Four detectors, one contract: ``observe(...)`` is called from the hot
record helpers, costs a few dict/deque ops, and returns ``None`` on
the quiet path or a JSON-able dict describing the anomaly when one
fires.  Each detector self-arms with a cooldown (per key where it has
keys) so a sustained condition produces ONE fire, not a firehose — the
recorder's own rate limiting is the backstop, not the primary valve.

Clocks are injectable everywhere (``clock()`` returning seconds) so
tests drive the windows synthetically.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional


def robust_z(value: float, samples) -> float:
    """Robust z-score of ``value`` against ``samples`` via median/MAD
    (consistent-estimator scaling 1.4826).  The MAD is floored at 5%
    of the median (and at 1.0) so a near-constant sample set cannot
    turn ordinary jitter into an infinite score."""
    xs = sorted(samples)
    n = len(xs)
    if n == 0:
        return 0.0
    med = (xs[n // 2] if n % 2 else (xs[n // 2 - 1] + xs[n // 2]) / 2.0)
    devs = sorted(abs(x - med) for x in xs)
    mad = (devs[n // 2] if n % 2
           else (devs[n // 2 - 1] + devs[n // 2]) / 2.0)
    scale = max(1.4826 * mad, 0.05 * abs(med), 1.0)
    return (value - med) / scale


class StragglerDetector:
    """Per-stage task-duration outliers: a new duration whose robust
    z-score against the stage's recent window exceeds ``threshold``
    fires (the "stage exchange.step p99 9.8x p50" class of finding).
    Needs ``min_samples`` prior observations per stage before it can
    judge — a cold stage never fires on its first slow task."""

    def __init__(self, threshold: float = 6.0, min_samples: int = 8,
                 window: int = 128, cooldown_s: float = 60.0,
                 clock=time.monotonic):
        self.threshold = float(threshold)
        self.min_samples = int(min_samples)
        self.window = int(window)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._durations: Dict[str, deque] = {}
        self._last_fire: Dict[str, float] = {}

    def observe(self, stage: str, dur_ns: int,
                task=None) -> Optional[dict]:
        with self._lock:
            win = self._durations.get(stage)
            if win is None:
                win = self._durations[stage] = deque(maxlen=self.window)
            fired = None
            if len(win) >= self.min_samples:
                z = robust_z(float(dur_ns), win)
                if z >= self.threshold:
                    now = self.clock()
                    last = self._last_fire.get(stage)
                    if last is None or now - last >= self.cooldown_s:
                        self._last_fire[stage] = now
                        xs = sorted(win)
                        fired = {
                            "stage": stage,
                            "task": task,
                            "dur_ns": int(dur_ns),
                            "median_ns": int(xs[len(xs) // 2]),
                            "robust_z": round(z, 2),
                            "samples": len(win),
                        }
            win.append(float(dur_ns))
            return fired


class RetryStormDetector:
    """Retry-episode rate over a sliding window: more than
    ``threshold`` failed episodes inside ``window_s`` seconds fires.
    One storm = one fire (cooldown)."""

    def __init__(self, threshold: int = 10, window_s: float = 10.0,
                 cooldown_s: float = 60.0, clock=time.monotonic):
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._times: deque = deque()
        self._sections: deque = deque(maxlen=16)
        self._last_fire: Optional[float] = None

    def observe(self, section: str = "?") -> Optional[dict]:
        now = self.clock()
        with self._lock:
            self._times.append(now)
            self._sections.append(section)
            cutoff = now - self.window_s
            while self._times and self._times[0] < cutoff:
                self._times.popleft()
            if len(self._times) < self.threshold:
                return None
            if self._last_fire is not None and \
                    now - self._last_fire < self.cooldown_s:
                return None
            self._last_fire = now
            return {
                "episodes_in_window": len(self._times),
                "window_s": self.window_s,
                "recent_sections": sorted(set(self._sections)),
            }


class HbmPressureDetector:
    """Sustained HBM pressure: a device whose ``bytes_in_use`` stays at
    or above ``threshold_bytes`` for ``sustain_s`` seconds fires.  A
    ``threshold_bytes`` of None disarms the detector (the library
    cannot guess a chip's capacity; the operator sets the knob)."""

    def __init__(self, threshold_bytes: Optional[int] = None,
                 sustain_s: float = 5.0, cooldown_s: float = 60.0,
                 clock=time.monotonic):
        self.threshold_bytes = (None if threshold_bytes is None
                                else int(threshold_bytes))
        self.sustain_s = float(sustain_s)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._above_since: Dict[str, float] = {}
        self._last_fire: Dict[str, float] = {}

    def observe(self, device: str, bytes_in_use: int) -> Optional[dict]:
        if self.threshold_bytes is None:
            return None
        device = str(device)
        now = self.clock()
        with self._lock:
            if bytes_in_use < self.threshold_bytes:
                self._above_since.pop(device, None)
                return None
            since = self._above_since.setdefault(device, now)
            if now - since < self.sustain_s:
                return None
            last = self._last_fire.get(device)
            if last is not None and now - last < self.cooldown_s:
                return None
            self._last_fire[device] = now
            return {
                "device": device,
                "bytes_in_use": int(bytes_in_use),
                "threshold_bytes": self.threshold_bytes,
                "sustained_s": round(now - since, 3),
            }


DEFAULT_LEAK_FLOOR_BYTES = 64 << 10


class LeakDetector:
    """Task-end leak check: ``task_done`` saw unreleased device bytes
    still attributed to the finishing task.  Fires per event when the
    leak is at least ``min_bytes`` (pool threads working for several
    tasks attribute their held bytes to every finishing task, so small
    residues can be shared accounting noise — the 64 KiB default floor
    filters those; the journal still records every positive leak)."""

    def __init__(self, min_bytes: int = DEFAULT_LEAK_FLOOR_BYTES):
        self.min_bytes = int(min_bytes)

    def observe(self, task_id: int, leaked_bytes: int,
                holders=()) -> Optional[dict]:
        if leaked_bytes < self.min_bytes:
            return None
        return {
            "task": task_id,
            "leaked_bytes": int(leaked_bytes),
            "holders": list(holders)[:8],
        }
