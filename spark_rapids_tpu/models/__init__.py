"""Named query catalog — the model pipelines as a serving surface.

The query server (``spark_rapids_tpu/server/``) admits work as
``(tenant, query_name, params)`` triples; this module is the registry
that turns a name into a runnable pipeline.  Every built-in runner is a
pure function of its ``params`` dict (data generated from a seed,
pipeline compiled once per parameter signature and cached), so a query
executed interleaved with seven neighbors returns bytes identical to
the same query executed alone — the property the server soak gate
(`make server-smoke`) asserts.

Runners receive an optional :class:`QueryContext` carrying tenant /
query-id attribution and a cooperative cancel flag; the built-in
pipelines are single jitted programs (not interruptible mid-dispatch),
so they check the flag at the recompute boundary only.  Custom runners
registered via :func:`register_query` can poll ``ctx.check_cancel()``
wherever they like.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from spark_rapids_tpu.robustness import lifeguard as _lifeguard


class QueryCancelled(Exception):
    """Raised by a runner that observed its cancel flag (the server
    folds it into a 'cancelled' outcome, never an error)."""


class QueryDeadlineExceeded(QueryCancelled):
    """Raised by a cooperative checkpoint once the query's deadline
    has passed (subclass of :class:`QueryCancelled` so existing
    runners unwind unchanged; the server reports a distinct
    ``deadline`` outcome)."""


class QueryContext:
    """Per-execution attribution + cooperative cancellation/deadline
    handle.  Every ``check_cancel`` poll doubles as a lifeguard
    heartbeat — a runner that checkpoints is "slow", never "hung"."""

    __slots__ = ("query_id", "tenant", "_cancel", "deadline_ns")

    def __init__(self, query_id: str = "", tenant: str = "",
                 cancel_event: Optional[threading.Event] = None,
                 deadline_ns: Optional[int] = None):
        self.query_id = query_id
        self.tenant = tenant
        self._cancel = cancel_event
        self.deadline_ns = deadline_ns

    def cancelled(self) -> bool:
        return self._cancel is not None and self._cancel.is_set()

    def remaining_s(self) -> Optional[float]:
        """Seconds until the deadline (negative once past), or None
        when the query has no deadline."""
        if self.deadline_ns is None:
            return None
        return (self.deadline_ns - time.monotonic_ns()) / 1e9

    def check_cancel(self) -> None:
        _lifeguard.beat(f"ctx:{self.query_id or 'query'}")
        # an explicit cancel wins over the deadline: the server keys
        # the outcome off its cancel_reason, so a user-cancelled job
        # whose deadline ALSO lapsed reports "cancelled", not a bogus
        # deadline failure (which would count as a quarantine death)
        if self.cancelled():
            raise QueryCancelled(self.query_id or "query")
        if self.deadline_ns is not None \
                and time.monotonic_ns() > self.deadline_ns:
            raise QueryDeadlineExceeded(self.query_id or "query")


class UnknownQueryError(KeyError):
    """Submitted name is not in the catalog (typed so the server front
    door can map it to a clean error response)."""


# name -> fn(params: dict, ctx: QueryContext) -> JSON-able result
_CATALOG: Dict[str, Callable] = {}
_CATALOG_LOCK = threading.Lock()
# compiled pipelines keyed by (name, param signature): concurrent
# tenants share one executable per shape (the jit_cache story at the
# pipeline level), and serial-vs-interleaved runs execute the SAME
# program — the byte-identity precondition.  LRU-bounded: the
# signature includes tenant-supplied params (join_capacity, stores,
# ...), so an adversarial tenant varying them must recycle cache
# slots, not grow the process without limit.
_PIPELINES: Dict[tuple, Any] = {}
_PIPELINES_LOCK = threading.Lock()
_PIPELINES_MAX = 32


def register_query(name: str, fn: Callable) -> None:
    """Register (or replace) a catalog entry.  ``fn(params, ctx)``
    must be safe to call from multiple pool threads at once."""
    with _CATALOG_LOCK:
        _CATALOG[name] = fn


def unregister_query(name: str) -> None:
    with _CATALOG_LOCK:
        _CATALOG.pop(name, None)


def catalog_queries() -> List[str]:
    with _CATALOG_LOCK:
        return sorted(_CATALOG)


def has_query(name: str) -> bool:
    with _CATALOG_LOCK:
        return name in _CATALOG


def run_catalog_query(name: str, params: Optional[dict] = None,
                      ctx: Optional[QueryContext] = None):
    """Resolve ``name`` and run it — the server's execution entry, and
    equally usable standalone (the soak's serial baseline)."""
    with _CATALOG_LOCK:
        fn = _CATALOG.get(name)
    if fn is None:
        raise UnknownQueryError(name)
    return fn(dict(params or {}), ctx or QueryContext())


def _pipeline(key: tuple, build: Callable):
    with _PIPELINES_LOCK:
        fn = _PIPELINES.pop(key, None)
        if fn is not None:
            _PIPELINES[key] = fn      # re-insert at the LRU tail
            return fn
    # build OUTSIDE the lock: a first-touch signature must not stall
    # every other pool thread's cache hit behind its construction.
    # Racing builders are pure and rare; the first published wins so
    # all callers share ONE program per shape.
    fn = build()
    with _PIPELINES_LOCK:
        fn = _PIPELINES.pop(key, fn)  # keep an earlier publisher
        _PIPELINES[key] = fn
        while len(_PIPELINES) > _PIPELINES_MAX:
            _PIPELINES.pop(next(iter(_PIPELINES)))
        return fn


def _rows(*arrays) -> List[list]:
    """Host-materialize pipeline outputs as plain nested lists (ints
    and floats only) — JSON-able across the socket front door and
    directly comparable for byte-identity."""
    import numpy as np
    cols = [np.asarray(a).reshape(-1) for a in arrays]
    out = []
    for row in zip(*cols):
        out.append([float(v) if isinstance(v, np.floating) else int(v)
                    for v in row])
    return out


# ------------------------------------------------------- built-in runners
# (each: seeded data + cached pipeline + overflow check + host rows)


def _run_q5(params: dict, ctx: QueryContext):
    import numpy as np

    from spark_rapids_tpu.models import tpcds
    ctx.check_cancel()
    rows = int(params.get("rows", 2048))
    stores = int(params.get("stores", 8))
    seed = int(params.get("seed", 5))
    cap = int(params.get("join_capacity", 1 << 12))
    d = tpcds.gen_q5(rows=rows, stores=stores, days=60, seed=seed)
    q = _pipeline(("q5", stores, cap),
                  lambda: tpcds.make_q5(stores, join_capacity=cap))
    k, sales, rets, profit, of = q(d)
    if bool(np.asarray(of)):
        raise RuntimeError("q5 join capacity overflow")
    return _rows(k, sales, rets, profit)


def _run_q9(params: dict, ctx: QueryContext):
    from spark_rapids_tpu.models import tpcds
    ctx.check_cancel()
    rows = int(params.get("rows", 4096))
    seed = int(params.get("seed", 9))
    data = tpcds.gen_q9(rows=rows, seed=seed)
    counts, avg_p, avg_n = tpcds.run_q9(*data)
    return _rows(counts, avg_p, avg_n)


def _run_q72(params: dict, ctx: QueryContext):
    import numpy as np

    from spark_rapids_tpu.models import tpcds
    ctx.check_cancel()
    rows = int(params.get("rows", 2048))
    items = int(params.get("items", 64))
    max_week = int(params.get("max_week", 16))
    seed = int(params.get("seed", 72))
    cap = int(params.get("join_capacity", 1 << 17))
    week0 = 11_000 // 7
    d = tpcds.gen_q72(cs_rows=rows, inv_rows=rows // 2, items=items,
                      days=35, seed=seed)
    q = _pipeline(("q72", items, max_week, cap),
                  lambda: tpcds.make_q72(items, max_week,
                                         join_capacity=cap,
                                         week0=week0))
    i, w, c, of = q(d)
    if bool(np.asarray(of)):
        raise RuntimeError("q72 join capacity overflow")
    return _rows(i, w, c)


def _run_q3(params: dict, ctx: QueryContext):
    from spark_rapids_tpu.models import tpcds
    ctx.check_cancel()
    rows = int(params.get("rows", 2048))
    items = int(params.get("items", 128))
    brands = int(params.get("brands", 16))
    manufact = int(params.get("manufact", 3))
    seed = int(params.get("seed", 3))
    base = 10_957
    d = tpcds.gen_q3(rows=rows, items=items, days=730, brands=brands,
                     seed=seed)
    q = _pipeline(("q3", base, brands, manufact),
                  lambda: tpcds.make_q3(base, years=2, brands=brands,
                                        manufact=manufact))
    year, brand, sums, total = q(d)
    return _rows(year, brand, sums) + [[int(total)]]


def _run_q7(params: dict, ctx: QueryContext):
    from spark_rapids_tpu.models import tpcds
    ctx.check_cancel()
    rows = int(params.get("rows", 2048))
    items = int(params.get("items", 64))
    seed = int(params.get("seed", 7))
    d = tpcds.gen_q7(rows=rows, items=items, demos=256, promos=32,
                     seed=seed)
    q = _pipeline(("q7", items), lambda: tpcds.make_q7(items))
    return _rows(*q(d))


# stage-IR variants (plan/catalog.py, ISSUE 13): the SAME queries
# compiled through the whole-stage fusion compiler — byte-identical
# to the hand-fused twins by the PR-11 contract, but every execution
# reports typed per-stage records to the query profiler, so a server
# tenant submitting these gets a real EXPLAIN ANALYZE plan tree.
# (The hand-fused entries stay untouched as the byte-identity
# oracles; the compiler memoizes CompiledStage per plan digest, so no
# _pipeline cache layer is needed here.)


def _run_q5_fused(params: dict, ctx: QueryContext):
    import numpy as np

    from spark_rapids_tpu.models import tpcds
    from spark_rapids_tpu.plan import catalog as plan_catalog
    ctx.check_cancel()
    rows = int(params.get("rows", 2048))
    stores = int(params.get("stores", 8))
    seed = int(params.get("seed", 5))
    cap = int(params.get("join_capacity", 1 << 12))
    d = tpcds.gen_q5(rows=rows, stores=stores, days=60, seed=seed)
    k, sales, rets, profit, of = plan_catalog.run_q5(d, stores, cap)
    if bool(np.asarray(of)):
        raise RuntimeError("q5 join capacity overflow")
    return _rows(k, sales, rets, profit)


def _run_q3_fused(params: dict, ctx: QueryContext):
    from spark_rapids_tpu.models import tpcds
    from spark_rapids_tpu.plan import catalog as plan_catalog
    ctx.check_cancel()
    rows = int(params.get("rows", 2048))
    items = int(params.get("items", 128))
    brands = int(params.get("brands", 16))
    manufact = int(params.get("manufact", 3))
    seed = int(params.get("seed", 3))
    d = tpcds.gen_q3(rows=rows, items=items, days=730, brands=brands,
                     seed=seed)
    year, brand, sums, total = plan_catalog.run_q3(
        d, 10_957, years=2, brands=brands, manufact=manufact)
    return _rows(year, brand, sums) + [[int(total)]]


def _run_q72_fused(params: dict, ctx: QueryContext):
    import numpy as np

    from spark_rapids_tpu.models import tpcds
    from spark_rapids_tpu.plan import catalog as plan_catalog
    ctx.check_cancel()
    rows = int(params.get("rows", 2048))
    items = int(params.get("items", 64))
    max_week = int(params.get("max_week", 16))
    seed = int(params.get("seed", 72))
    cap = int(params.get("join_capacity", 1 << 17))
    d = tpcds.gen_q72(cs_rows=rows, inv_rows=rows // 2, items=items,
                      days=35, seed=seed)
    i, w, c, of = plan_catalog.run_q72(d, items, max_week, cap,
                                       week0=11_000 // 7)
    if bool(np.asarray(of)):
        raise RuntimeError("q72 join capacity overflow")
    return _rows(i, w, c)


# file-backed variants (models/filesource.py): same seeded data via a
# parquet round trip through io/parquet_reader, same cached pipeline,
# byte-identical rows — registered thin so pyarrow loads on first use
def _run_q3_file(params: dict, ctx: QueryContext):
    from spark_rapids_tpu.models import filesource
    return filesource.run_q3_file(params, ctx)


# ------------------------------------------------ incremental runners
# (ISSUE 19): the q5/q72 partials/finish split as an INCREMENTAL mode.
# The stream source's ingest epoch says how many batches have arrived;
# only batches past the resident partial-aggregate state's watermark
# run the map side, each folding into the state via the exact-int64
# merge property (segment sums are additive across batches, overflow
# flags OR) — then one finish pass.  With the cache off (or cold)
# every batch recomputes, which IS the differential baseline: the two
# paths share this body, so byte-identity is structural.


def _run_q5_incremental(params: dict, ctx: QueryContext):
    import numpy as np

    from spark_rapids_tpu.models import tpcds
    from spark_rapids_tpu.perf import result_cache as _rc
    from spark_rapids_tpu.plan import catalog as _cat
    ctx.check_cancel()
    rows = int(params.get("rows", 2048))
    stores = int(params.get("stores", 8))
    seed = int(params.get("seed", 5))
    cap = int(params.get("join_capacity", 1 << 12))
    source = str(params.get("source", "q5_stream"))
    # epoch N means N batches ARRIVED after the initial one:
    # a fresh stream (epoch 0) still has its base batch
    batches = _rc.ingest_epoch(source) + 1
    key = ("q5_state", rows, stores, seed, source)
    state, upto = None, 0
    if _rc.cache_enabled():
        got = _rc.CACHE.get_subplan(key)
        if got is not None:
            meta, arrays = got
            w = int(meta.get("upto", 0))
            if 0 < w <= batches:     # a shrunk stream can't rewind
                state, upto = list(arrays), w
                cap = max(cap, int(meta.get("cap", cap)))
    for b in range(upto, batches):
        ctx.check_cancel()
        d = tpcds.gen_q5(rows=rows, stores=stores, days=60,
                         seed=seed + 7919 * b)
        outs, cap = _cat.run_q5_partials(
            (d.s_date, d.s_store, d.s_price, d.s_profit,
             d.r_date, d.r_store, d.r_amt, d.r_loss, d.d_date),
            stores, cap, ctx=ctx)
        delta = [np.asarray(o) for o in outs]
        if state is None:
            state = delta
        else:
            state = _rc.fold_partials(state, delta, or_indices=(4,))
            _rc.CACHE.record_fold("tpcds_q5_incremental")
    if _rc.cache_enabled() and batches > upto:
        _rc.CACHE.put_subplan(key, state,
                              {"upto": batches, "cap": cap})
    # dimension labels come from the BASE batch (st_id is a seeded
    # permutation; partials are keyed by store INDEX, so the labels
    # must not drift with the arriving batches)
    d0 = tpcds.gen_q5(rows=stores, stores=stores, days=60, seed=seed)
    k, sales, rets, profit, g_of = _cat.run_q5_finish(
        state[0], state[1], state[2], state[3], state[4],
        d0.st_id, stores)
    if bool(np.asarray(g_of)):
        raise RuntimeError("q5 join capacity overflow")
    return _rows(k, sales, rets, profit)


def _run_q72_incremental(params: dict, ctx: QueryContext):
    import numpy as np

    from spark_rapids_tpu.models import tpcds
    from spark_rapids_tpu.perf import result_cache as _rc
    from spark_rapids_tpu.plan import catalog as _cat
    ctx.check_cancel()
    rows = int(params.get("rows", 2048))
    items = int(params.get("items", 64))
    max_week = int(params.get("max_week", 16))
    seed = int(params.get("seed", 72))
    cap = int(params.get("join_capacity", 1 << 17))
    limit = int(params.get("limit", 100))
    week0 = 11_000 // 7
    source = str(params.get("source", "q72_stream"))
    # epoch N means N batches ARRIVED after the initial one:
    # a fresh stream (epoch 0) still has its base batch
    batches = _rc.ingest_epoch(source) + 1
    key = ("q72_state", rows, items, max_week, seed, source)
    state, upto = None, 0
    if _rc.cache_enabled():
        got = _rc.CACHE.get_subplan(key)
        if got is not None:
            meta, arrays = got
            w = int(meta.get("upto", 0))
            if 0 < w <= batches:
                state, upto = list(arrays), w
                cap = max(cap, int(meta.get("cap", cap)))
    for b in range(upto, batches):
        ctx.check_cancel()
        d = tpcds.gen_q72(cs_rows=rows, inv_rows=rows // 2,
                          items=items, days=35,
                          seed=seed + 7919 * b)
        outs, cap = _cat.run_q72_partials(
            (d.cs_item, d.cs_date, d.cs_qty,
             d.inv_item, d.inv_date, d.inv_qty, d.item_id),
            items, max_week, cap, week0)
        delta = [np.asarray(o) for o in outs]
        if state is None:
            state = delta
        else:
            state = _rc.fold_partials(state, delta, or_indices=(1,))
            _rc.CACHE.record_fold("tpcds_q72_incremental")
    if _rc.cache_enabled() and batches > upto:
        _rc.CACHE.put_subplan(key, state,
                              {"upto": batches, "cap": cap})
    i, w, c, g_of = _cat.run_q72_finish(state[0], state[1], items,
                                        max_week, limit, week0)
    if bool(np.asarray(g_of)):
        raise RuntimeError("q72 join capacity overflow")
    return _rows(i, w, c)


def _run_q7_file(params: dict, ctx: QueryContext):
    from spark_rapids_tpu.models import filesource
    return filesource.run_q7_file(params, ctx)


def _run_q9_file(params: dict, ctx: QueryContext):
    from spark_rapids_tpu.models import filesource
    return filesource.run_q9_file(params, ctx)


register_query("tpcds_q3", _run_q3)
register_query("tpcds_q5", _run_q5)
register_query("tpcds_q7", _run_q7)
register_query("tpcds_q9", _run_q9)
register_query("tpcds_q72", _run_q72)
register_query("tpcds_q3_fused", _run_q3_fused)
register_query("tpcds_q5_fused", _run_q5_fused)
register_query("tpcds_q72_fused", _run_q72_fused)
register_query("tpcds_q3_file", _run_q3_file)
register_query("tpcds_q7_file", _run_q7_file)
register_query("tpcds_q9_file", _run_q9_file)
register_query("tpcds_q5_incremental", _run_q5_incremental)
register_query("tpcds_q72_incremental", _run_q72_incremental)

# result-cache specs (ISSUE 19): the generator-backed catalog queries
# are pure functions of their parameter binding (seeded synthetic
# data, no external reads), so their results are shareable across
# tenants — the safety gate's "identical digests over shared sources"
# case.  The incremental queries additionally key on their stream
# source's ingest epoch (source_param lets a binding name its own
# stream).  The _file queries read operator-supplied paths and are
# deliberately NOT registered: an unregistered query is uncacheable.
from spark_rapids_tpu.perf.result_cache import \
    register_cache_spec as _reg_spec  # noqa: E402

for _q in ("tpcds_q3", "tpcds_q5", "tpcds_q7", "tpcds_q9",
           "tpcds_q72", "tpcds_q3_fused", "tpcds_q5_fused",
           "tpcds_q72_fused"):
    _reg_spec(_q, shared=True)
_reg_spec("tpcds_q5_incremental", shared=True,
          sources=("q5_stream",), source_param="source")
_reg_spec("tpcds_q72_incremental", shared=True,
          sources=("q72_stream",), source_param="source")
