"""Flagship query pipelines — the "model" of this framework (BASELINE.json
configs: join + group-by aggregate shapes from TPC-DS q5/q9/q72).

Two forms:
  * simple_star_join_agg: eager composition of the real op kernels
    (hash join -> gather -> group-by aggregate) — the single-chip
    end-to-end slice.
  * distributed_hash_aggregate: the multi-chip step — murmur hash
    partitioning + all-to-all ICI exchange + on-device bucketed partial
    aggregation, all inside one jitted shard_map (the analog of the
    reference's executor-parallel shuffle+agg, SURVEY.md §2.2 checklist).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from spark_rapids_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.ops import copying, groupby, joins
from spark_rapids_tpu.ops import hash as H
from spark_rapids_tpu.parallel import exchange as ex


def simple_star_join_agg(fact: Table, dim: Table,
                         fact_key: int = 0, fact_value: int = 1,
                         dim_key: int = 0, dim_attr: int = 1) -> Table:
    """SELECT d.attr, sum(f.value), count(*) FROM fact f JOIN dim d
    ON f.key = d.key GROUP BY d.attr — the minimum end-to-end slice."""
    from spark_rapids_tpu.robustness import retry as _retry

    def _run():
        li, ri = joins.hash_inner_join(
            Table([fact.columns[fact_key]]),
            Table([dim.columns[dim_key]]))
        value = copying.gather(fact.columns[fact_value], li)
        attr = copying.gather(dim.columns[dim_attr], ri)
        return groupby.groupby_aggregate(
            Table([attr], names=["attr"]), [value, value],
            [groupby.SUM, groupby.COUNT])

    # query-root span: the eagerly composed op kernels below each open
    # child op spans under it, so a trace export shows the whole query
    # as one tree; the retry driver recomputes the (pure) composition
    # on a mid-query OOM
    with _obs.TRACER.span("simple_star_join_agg", kind="query"):
        return _retry.with_retry(_run, name="simple_star_join_agg")


def make_distributed_hash_aggregate(mesh: Mesh, n_parts: int,
                                    num_buckets: int, capacity: int):
    """Jitted multi-chip step: per-shard murmur partition -> all-to-all ->
    per-device bucketed sums/counts.  Returns (step_fn, sharding).

    The returned step takes (keys int64 shard, vals float32 shard) and
    yields per-device (bucket_sums, bucket_counts, send_counts) — callers
    check max(send_counts) <= capacity per the exchange contract."""

    def local(keys, vals):
        h = H.murmur3_32(
            [Column(dtypes.INT64, keys.shape[0], data=keys)], 42).data
        part = (h.astype(jnp.uint32) % jnp.uint32(n_parts)).astype(
            jnp.int32)
        (rk, rv), valid, _total, send_counts = ex.exchange(
            [keys, vals], part, "data", n_parts, capacity)
        bucket = (rk.astype(jnp.uint64)
                  % jnp.uint64(num_buckets)).astype(jnp.int32)
        bucket = jnp.where(valid, bucket, num_buckets)  # dropped lane
        sums = jax.ops.segment_sum(
            jnp.where(valid, rv, 0.0), bucket, num_buckets + 1)
        counts = jax.ops.segment_sum(
            valid.astype(jnp.int32), bucket, num_buckets + 1)
        return sums[:num_buckets], counts[:num_buckets], send_counts

    jitted = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P("data"), P("data")),
        out_specs=(P("data"), P("data"), P("data"))))

    from spark_rapids_tpu.robustness import retry as _retry

    def step(keys, vals):
        # stage-level span around the jitted multi-chip step (the
        # exchange itself runs inside XLA; the span brackets dispatch);
        # retry driver: a mid-dispatch OOM re-runs the pure step
        with _obs.TRACER.span("distributed_hash_aggregate",
                              kind="stage"):
            return _retry.with_retry(
                jitted, keys, vals, name="distributed_hash_aggregate")

    return step, NamedSharding(mesh, P("data"))
