"""TPC-DS-shaped flagship pipelines (BASELINE.json configs[4] /
north_star: "TPC-DS SF100 q5/q9/q72 end-to-end"; q3 and q7 shapes
extend toward the q1-q10 target).

Each pipeline is ONE jitted program over device arrays — scan ->
join(s) -> filter -> group-by -> order-by — with the shapes the real
queries have:

  * q9-shape : CASE-WHEN bucketed aggregates over store_sales
               (5 quantity ranges; count/avg per range) — pure
               elementwise + masked reductions.
  * q5-shape : sales & returns facts joined to a date-filtered
               date_dim and to a store dim, grouped by store with
               decimal sums, ordered by store — join -> join ->
               group-by -> order-by.
  * q72-shape: catalog_sales joined to inventory on item (fact-fact),
               week-offset filter through date lookups, inventory
               shortage filter, item dim join, group by (item, week),
               count, order by count desc with a LIMIT — the long
               multi-join chain.

TPU-first design decisions (vs the reference's row-iterator operators):
  * joins are the jittable padded-capacity inner join
    (ops/device_join.inner_join_device): static shapes, validity
    masks, int64 overflow accounting — XLA sees one fused program.
  * group-bys ride jax.ops.segment_sum over dictionary-encoded keys
    (dimension keys ARE small dictionaries after the dim join, the
    same reason Spark dictionary-encodes parquet strings).
  * order-by is lax.sort over the padded group table with sentinel
    keys for invalid slots.
  * strings never enter the jitted program: dimension attributes are
    dictionary ids inside compute and materialize back to strings at
    the presentation boundary (models/__init__ callers) — the
    scan-side dictionary encode is where the reference pays its
    string cost too.
  * decimal sums are exact int64 scaled arithmetic (decimal64 cents),
    promoted to f64 only for the avg presentation.

The numpy oracles (oracle_q5/q9/q72) define correctness; tests drive
both single-chip jit and the 8-device mesh variants against them.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.ops.device_join import inner_join_device


def _note_gen(source: str, **args) -> None:
    """Catalog data generators feed the result-cache ingest-epoch
    registry (ISSUE 19): a regeneration with CHANGED arguments is new
    data over that source (the epoch bumps and stale cached results
    miss); an identical regeneration is not an ingest."""
    try:
        from spark_rapids_tpu.perf.result_cache import note_ingest
        note_ingest(source, ",".join(
            f"{k}={v}" for k, v in sorted(args.items())))
    except Exception:
        pass


def _traced_query(name: str, fn):
    """Wrap a pipeline's jitted run fn in a query-root span AND the
    task-level retry driver: every eager op bracket, shuffle span, and
    OOM episode recorded while the query executes parents under this
    root, and a GpuRetryOOM / GpuSplitAndRetryOOM / CudfException
    raised mid-query (real or injected — the driver polls the forced-
    OOM and fault-injector hooks under the query's name at every
    attempt) recomputes the pipeline instead of killing it.  The
    pipelines are pure functions of their argument arrays, so the
    recompute needs no checkpoint and a "split" degrades soundly to a
    full re-run."""
    from spark_rapids_tpu.robustness import retry as _retry

    @functools.wraps(fn)
    def run(*args, **kwargs):
        with _obs.TRACER.span(name, kind="query"):
            # close over the call instead of forwarding kwargs: a
            # pipeline kwarg named like a driver control parameter
            # (policy, checkpoint, ...) must reach fn, not the driver
            return _retry.with_retry(lambda: fn(*args, **kwargs),
                                     name=name)

    return run

# ------------------------------------------------------------------ data


class Q5Data(NamedTuple):
    # store_sales-like fact
    s_date: jnp.ndarray     # i32 days since epoch
    s_store: jnp.ndarray    # i32 store key
    s_price: jnp.ndarray    # i64 decimal64(2) cents
    s_profit: jnp.ndarray   # i64 decimal64(2) cents
    # store_returns-like fact
    r_date: jnp.ndarray
    r_store: jnp.ndarray
    r_amt: jnp.ndarray
    r_loss: jnp.ndarray
    # date_dim filtered to the 14-day window, store dim (dense keys:
    # store key k's attributes live at index k)
    d_date: jnp.ndarray     # i32 days (pre-filtered window)
    st_id: jnp.ndarray      # i32 dictionary id of s_store_id


def gen_q5(rows: int = 50_000, stores: int = 32, days: int = 120,
           seed: int = 5) -> Q5Data:
    _note_gen("tpcds:gen_q5", rows=rows, stores=stores, days=days,
              seed=seed)
    rng = np.random.default_rng(seed)
    base = 11_000  # ~2000-02-14 in days-since-epoch
    win0 = base + 40

    def fact(n):
        return (
            jnp.asarray(rng.integers(base, base + days, n)
                        .astype(np.int32)),
            jnp.asarray(rng.integers(0, stores, n).astype(np.int32)),
            jnp.asarray(rng.integers(100, 100_000, n)
                        .astype(np.int64)),
            jnp.asarray(rng.integers(-20_000, 50_000, n)
                        .astype(np.int64)),
        )

    s = fact(rows)
    r = fact(rows // 8)
    d_date = jnp.asarray(np.arange(win0, win0 + 14, dtype=np.int32))
    perm = rng.permutation(stores).astype(np.int32)
    return Q5Data(*s, *r, d_date=d_date, st_id=jnp.asarray(perm))


def _q5_partials(stores: int, join_capacity: int):
    """The map side of q5: per-shard partial group table (per-store
    sales / returns / profit / seen) + overflow flag.  Shared by the
    single-chip jit, the mesh shard bodies, AND the multi-process
    distributed runner (distributed/runner.py) — the partial vectors
    are exact int64 sums, so any reduction order (psum over ICI or a
    kudo reduce-scatter over sockets) yields byte-identical totals."""

    def compute(s_date, s_store, s_price, s_profit,
                r_date, r_store, r_amt, r_loss, d_date):
        def channel(date, store, amt_a, amt_b):
            """fact JOIN date_window -> per-store (sum a, sum b)."""
            pairs = inner_join_device(date, d_date, join_capacity)
            li = pairs.left_indices
            ok = pairs.valid
            st = jnp.where(ok, store[li], 0)
            sum_a = jax.ops.segment_sum(
                jnp.where(ok, amt_a[li], 0), st, num_segments=stores)
            sum_b = jax.ops.segment_sum(
                jnp.where(ok, amt_b[li], 0), st, num_segments=stores)
            seen = jax.ops.segment_sum(ok.astype(jnp.int64), st,
                                       num_segments=stores)
            return sum_a, sum_b, seen, pairs.total > join_capacity

        s_sales, s_profit_s, s_seen, of1 = channel(
            s_date, s_store, s_price, s_profit)
        r_amt_s, r_loss_s, r_seen, of2 = channel(
            r_date, r_store, r_amt, r_loss)
        return (s_sales, r_amt_s, s_profit_s - r_loss_s,
                s_seen + r_seen, of1 | of2)

    return compute


def _q5_finish(stores: int):
    """The reduce side of q5: ORDER BY s_store_id over the GLOBAL
    group table (post-reduction) — one implementation for every
    execution mode, so the distributed run's presentation cannot
    drift from the single-process one."""

    def fin(sales, rets, profit, seen, st_id):
        # ORDER BY s_store_id: sort the group table by dictionary id
        # (store dim join is a dense-key index; a sparse dim would
        # ride the same inner join)
        sentinel = jnp.int32(2**31 - 1)
        key = jnp.where(seen > 0, st_id, sentinel)
        key_s, sales_s, ret_s, profit_s = lax.sort(
            (key, sales, rets, profit), num_keys=1)
        return key_s, sales_s, ret_s, profit_s

    return fin


def _q5_kernel(stores: int, join_capacity: int, reduce_sum,
               reduce_any):
    """Shared per-shard q5 pipeline body (single-chip: identity
    reduces; mesh: lax.psum reduces — ONE implementation so the two
    variants cannot drift).  Composed from _q5_partials (map side) and
    _q5_finish (order-by) with the caller's reduction in between."""
    partials = _q5_partials(stores, join_capacity)
    fin = _q5_finish(stores)

    def compute(s_date, s_store, s_price, s_profit,
                r_date, r_store, r_amt, r_loss, d_date, st_id):
        s_sales, r_amt_s, profit, seen, of = partials(
            s_date, s_store, s_price, s_profit,
            r_date, r_store, r_amt, r_loss, d_date)
        # global group table (mesh: one psum rides ICI)
        s_sales = reduce_sum(s_sales)
        r_amt_s = reduce_sum(r_amt_s)
        profit = reduce_sum(profit)
        seen = reduce_sum(seen)
        key_s, sales_s, ret_s, profit_s = fin(
            s_sales, r_amt_s, profit, seen, st_id)
        return key_s, sales_s, ret_s, profit_s, reduce_any(of)

    return compute


def make_q5(stores: int, join_capacity: int):
    """q5-shape single-jit pipeline.  Returns fn(Q5Data) ->
    (store_ids i32, sales i64, returns i64, profit i64, overflow
    bool) with one output row per store id, ordered by store id
    (invalid stores hold sentinel id 2^31-1)."""
    kernel = _q5_kernel(stores, join_capacity,
                        lambda x: x, lambda b: b)

    @jax.jit
    def run(d: Q5Data):
        return kernel(*d)

    return _traced_query("tpcds_q5", run)


def oracle_q5(d: Q5Data, stores: int):
    # one host materialization per column up front: per-element jnp
    # indexing would pay a device round-trip per row
    h = Q5Data(*(np.asarray(x) for x in d))
    dd = set(h.d_date.tolist())
    out = {}
    for i in range(len(h.s_date)):
        if int(h.s_date[i]) in dd:
            e = out.setdefault(int(h.s_store[i]), [0, 0, 0])
            e[0] += int(h.s_price[i])
            e[2] += int(h.s_profit[i])
    for i in range(len(h.r_date)):
        if int(h.r_date[i]) in dd:
            e = out.setdefault(int(h.r_store[i]), [0, 0, 0])
            e[1] += int(h.r_amt[i])
            e[2] -= int(h.r_loss[i])
    rows = sorted((int(h.st_id[st]), a, b, c)
                  for st, (a, b, c) in out.items())
    return rows


# ------------------------------------------------------------------- q9


def gen_q9(rows: int = 100_000, seed: int = 9):
    _note_gen("tpcds:gen_q9", rows=rows, seed=seed)
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.integers(1, 101, rows).astype(np.int32)),
            jnp.asarray(rng.integers(100, 30_000, rows)
                        .astype(np.int64)),
            jnp.asarray(rng.integers(-5_000, 20_000, rows)
                        .astype(np.int64)))


_Q9_BUCKETS = ((1, 20), (21, 40), (41, 60), (61, 80), (81, 100))


@jax.jit
def _run_q9_jit(quantity: jnp.ndarray, price: jnp.ndarray,
                profit: jnp.ndarray):
    counts, avg_p, avg_n = [], [], []
    for lo, hi in _Q9_BUCKETS:
        m = (quantity >= lo) & (quantity <= hi)
        c = jnp.sum(m.astype(jnp.int64))
        sp = jnp.sum(jnp.where(m, price, 0))
        sn = jnp.sum(jnp.where(m, profit, 0))
        counts.append(c)
        avg_p.append(sp.astype(jnp.float64)
                     / jnp.maximum(c, 1).astype(jnp.float64))
        avg_n.append(sn.astype(jnp.float64)
                     / jnp.maximum(c, 1).astype(jnp.float64))
    return (jnp.stack(counts), jnp.stack(avg_p), jnp.stack(avg_n))


# q9-shape: per-bucket count / avg(price) / avg(profit); avgs in f64
# at the presentation edge, sums exact in int64.  Same query-root
# span + retry contract as every other pipeline.
run_q9 = _traced_query("tpcds_q9", _run_q9_jit)


def make_q9_multichip(mesh: Mesh):
    """q9-shape on the mesh: rows sharded, the five bucket reductions
    psum'd — sums cross ICI, the avg divide happens on the global
    sums (a mean of shard means would be wrong)."""
    from spark_rapids_tpu.utils.jax_compat import shard_map as smap

    axis = mesh.axis_names[0]

    def shard_fn(quantity, price, profit):
        counts, sp, sn = [], [], []
        for lo, hi in _Q9_BUCKETS:
            m = (quantity >= lo) & (quantity <= hi)
            counts.append(lax.psum(jnp.sum(m.astype(jnp.int64)),
                                   axis))
            sp.append(lax.psum(jnp.sum(jnp.where(m, price, 0)),
                               axis))
            sn.append(lax.psum(jnp.sum(jnp.where(m, profit, 0)),
                               axis))
        c = jnp.stack(counts)
        denom = jnp.maximum(c, 1).astype(jnp.float64)
        return (c, jnp.stack(sp).astype(jnp.float64) / denom,
                jnp.stack(sn).astype(jnp.float64) / denom)

    shard = P(axis)
    rep = P()
    fn = smap(shard_fn, mesh=mesh, in_specs=(shard, shard, shard),
              out_specs=(rep, rep, rep))
    return _traced_query("tpcds_q9_multichip", jax.jit(fn))


def oracle_q9(quantity, price, profit):
    q = np.asarray(quantity)
    p = np.asarray(price)
    n = np.asarray(profit)
    out = []
    for lo, hi in _Q9_BUCKETS:
        m = (q >= lo) & (q <= hi)
        c = int(m.sum())
        out.append((c, p[m].sum() / max(c, 1), n[m].sum() / max(c, 1)))
    return out


# ------------------------------------------------------------------ q72


class Q72Data(NamedTuple):
    cs_item: jnp.ndarray      # i32 item key
    cs_date: jnp.ndarray      # i32 order date (days)
    cs_qty: jnp.ndarray       # i32
    inv_item: jnp.ndarray     # i32
    inv_date: jnp.ndarray     # i32 inventory date (days)
    inv_qty: jnp.ndarray      # i32
    item_id: jnp.ndarray      # i32 dictionary id per item key (dense)


def gen_q72(cs_rows: int = 30_000, inv_rows: int = 30_000,
            items: int = 512, days: int = 70, seed: int = 72
            ) -> Q72Data:
    _note_gen("tpcds:gen_q72", cs_rows=cs_rows, inv_rows=inv_rows,
              items=items, days=days, seed=seed)
    rng = np.random.default_rng(seed)
    base = 11_000
    return Q72Data(
        jnp.asarray(rng.integers(0, items, cs_rows).astype(np.int32)),
        jnp.asarray(rng.integers(base, base + days, cs_rows)
                    .astype(np.int32)),
        jnp.asarray(rng.integers(1, 100, cs_rows).astype(np.int32)),
        jnp.asarray(rng.integers(0, items, inv_rows).astype(np.int32)),
        jnp.asarray(rng.integers(base, base + days, inv_rows)
                    .astype(np.int32)),
        jnp.asarray(rng.integers(1, 60, inv_rows).astype(np.int32)),
        jnp.asarray(rng.permutation(items).astype(np.int32)),
    )


def _q72_partials(items: int, max_week: int, join_capacity: int,
                  week0: int):
    """Map side of q72: per-shard partial (item, week) count vector +
    overflow flag (see _q5_partials — shared with the distributed
    runner, exact int64 partials)."""
    n_groups = items * max_week

    def compute(cs_item, cs_date, cs_qty, inv_item, inv_date,
                inv_qty, item_id):
        pairs = inner_join_device(cs_item, inv_item, join_capacity)
        li, ri, ok = (pairs.left_indices, pairs.right_indices,
                      pairs.valid)
        order_week = cs_date[li] // 7
        inv_week = inv_date[ri] // 7
        week = order_week - week0
        keep = (ok & (inv_week == order_week + 1)
                & (inv_qty[ri] < cs_qty[li])
                & (week >= 0) & (week < max_week))
        iid = item_id[cs_item[li]]
        gid = jnp.where(keep, iid * max_week + week, 0)
        # masked rows land on gid 0 but add 0 (the summand is `keep`)
        counts = jax.ops.segment_sum(keep.astype(jnp.int64), gid,
                                     num_segments=n_groups)
        return counts, pairs.total > join_capacity

    return compute


def _q72_finish(items: int, max_week: int, limit: int, week0: int):
    """Reduce side of q72: top-k over the GLOBAL count vector (see
    _q5_finish)."""
    n_groups = items * max_week

    def fin(counts):
        # ORDER BY count DESC, item ASC LIMIT k over the group table
        gidx = jnp.arange(n_groups, dtype=jnp.int64)
        sort_key = jnp.where(counts > 0, -counts, jnp.int64(2**62))
        _k, gid_s, cnt_s = lax.sort((sort_key, gidx, counts),
                                    num_keys=2)
        return (gid_s[:limit] // max_week,
                gid_s[:limit] % max_week + week0, cnt_s[:limit])

    return fin


def _q72_kernel(items: int, max_week: int, join_capacity: int,
                limit: int, week0: int, reduce_sum, reduce_any):
    """Shared per-shard q72 pipeline body (see _q5_kernel)."""
    partials = _q72_partials(items, max_week, join_capacity, week0)
    fin = _q72_finish(items, max_week, limit, week0)

    def compute(cs_item, cs_date, cs_qty, inv_item, inv_date,
                inv_qty, item_id):
        counts, of = partials(cs_item, cs_date, cs_qty, inv_item,
                              inv_date, inv_qty, item_id)
        counts = reduce_sum(counts)
        item, week, cnt = fin(counts)
        return item, week, cnt, reduce_any(of)

    return compute


def make_q72(items: int, max_week: int, join_capacity: int,
             limit: int = 100, week0: int = 0):
    """q72-shape single-jit pipeline: cs JOIN inv ON item (fact-fact)
    with inv_week == order_week + 1 and inv_qty < cs_qty filters,
    item-dim join for the dictionary id, GROUP BY (item, week) COUNT,
    ORDER BY count DESC, item_id ASC LIMIT `limit`.  The group space
    is items x max_week with weeks rebased to week0 (the date_dim
    window's first week) — the group table stays proportional to the
    QUERY's domain, not the calendar's."""
    kernel = _q72_kernel(items, max_week, join_capacity, limit,
                         week0, lambda x: x, lambda b: b)

    @jax.jit
    def run(d: Q72Data):
        return kernel(*d)

    return _traced_query("tpcds_q72", run)


def oracle_q72(d: Q72Data, items: int, max_week: int,
               limit: int = 100, week0: int = 0):
    from collections import Counter, defaultdict
    inv_by_item = defaultdict(list)
    inv_item = np.asarray(d.inv_item)
    inv_date = np.asarray(d.inv_date)
    inv_qty = np.asarray(d.inv_qty)
    for j in range(len(inv_item)):
        inv_by_item[int(inv_item[j])].append(j)
    counts: Counter = Counter()
    cs_item = np.asarray(d.cs_item)
    cs_date = np.asarray(d.cs_date)
    cs_qty = np.asarray(d.cs_qty)
    item_id = np.asarray(d.item_id)
    for i in range(len(cs_item)):
        ow = int(cs_date[i]) // 7
        for j in inv_by_item.get(int(cs_item[i]), ()):
            if (int(inv_date[j]) // 7 == ow + 1
                    and int(inv_qty[j]) < int(cs_qty[i])
                    and 0 <= ow - week0 < max_week):
                counts[(int(item_id[cs_item[i]]), ow - week0)] += 1
    rows = sorted(((-c, iid * max_week + wk)
                   for (iid, wk), c in counts.items()))
    return [(g // max_week, g % max_week + week0, -negc)
            for negc, g in rows[:limit]]


# ----------------------------------------------------------- multichip


def make_q5_multichip(mesh: Mesh, stores: int, join_capacity: int):
    """q5-shape on the mesh: facts sharded over the 'data' axis
    (row-parallel scan), the date window and store dim replicated
    (broadcast join — dims fit HBM, the same plan GpuBroadcastHashJoin
    picks), per-shard partial group-by via the SHARED _q5_kernel, ONE
    psum over ICI for the global group table, order-by replicated.
    The whole step is a single jitted shard_map program."""
    from spark_rapids_tpu.utils.jax_compat import shard_map as smap

    axis = mesh.axis_names[0]
    kernel = _q5_kernel(
        stores, join_capacity,
        lambda x: lax.psum(x, axis),
        lambda b: lax.psum(b.astype(jnp.int32), axis) > 0)
    shard = P(axis)
    rep = P()
    fn = smap(kernel, mesh=mesh,
              in_specs=(shard, shard, shard, shard,
                        shard, shard, shard, shard, rep, rep),
              out_specs=(rep, rep, rep, rep, rep))
    return _traced_query("tpcds_q5_multichip", jax.jit(fn))


def make_q72_multichip(mesh: Mesh, items: int, max_week: int,
                       join_capacity: int, limit: int = 100,
                       week0: int = 0):
    """q72-shape on the mesh: catalog_sales sharded row-parallel,
    inventory + item dim replicated (broadcast), per-shard join +
    filters + partial (item, week) counts via the SHARED _q72_kernel,
    psum for the global group table, top-k replicated."""
    from spark_rapids_tpu.utils.jax_compat import shard_map as smap

    axis = mesh.axis_names[0]
    kernel = _q72_kernel(
        items, max_week, join_capacity, limit, week0,
        lambda x: lax.psum(x, axis),
        lambda b: lax.psum(b.astype(jnp.int32), axis) > 0)
    shard = P(axis)
    rep = P()
    fn = smap(kernel, mesh=mesh,
              in_specs=(shard, shard, shard, rep, rep, rep, rep),
              out_specs=(rep, rep, rep, rep))
    return _traced_query("tpcds_q72_multichip", jax.jit(fn))


# ------------------------------------------------------------------- q3


class Q3Data(NamedTuple):
    s_date: jnp.ndarray    # i32 days (fact)
    s_item: jnp.ndarray    # i32 item key
    s_price: jnp.ndarray   # i64 decimal64(2) cents
    d_moy: jnp.ndarray     # i32 month-of-year per day index (dense
    #                         date dim: day d's row lives at d - base)
    d_year: jnp.ndarray    # i32 year per day index
    i_brand: jnp.ndarray   # i32 brand id per item key (dense item dim)
    i_manufact: jnp.ndarray  # i32 manufacturer id per item key


def gen_q3(rows: int = 50_000, items: int = 256, days: int = 730,
           brands: int = 32, seed: int = 3) -> Q3Data:
    _note_gen("tpcds:gen_q3", rows=rows, items=items, days=days,
              brands=brands, seed=seed)
    rng = np.random.default_rng(seed)
    base = 10_957  # 2000-01-01
    day_idx = np.arange(days)
    return Q3Data(
        jnp.asarray(rng.integers(base, base + days, rows)
                    .astype(np.int32)),
        jnp.asarray(rng.integers(0, items, rows).astype(np.int32)),
        jnp.asarray(rng.integers(100, 50_000, rows).astype(np.int64)),
        jnp.asarray(((day_idx // 30) % 12 + 1).astype(np.int32)),
        jnp.asarray((2000 + day_idx // 365).astype(np.int32)),
        jnp.asarray(rng.integers(0, brands, items).astype(np.int32)),
        jnp.asarray(rng.integers(0, 8, items).astype(np.int32)),
    )


def make_q3(base: int, years: int, brands: int, manufact: int,
            month: int = 11, limit: int = 100):
    """q3-shape single-jit pipeline: store_sales JOIN date_dim (dense
    lookup, d_moy filter) JOIN item (dense lookup, manufacturer
    filter) GROUP BY (d_year, brand) SUM(price) ORDER BY year ASC,
    sum DESC, brand ASC LIMIT `limit`.  Rows outside the `years`-wide
    window starting at d_year[0] are filtered (the date-dim join scope);
    dead output slots carry the 2^31-1 year sentinel."""
    kernel = _q3_kernel(base, years, brands, manufact, month, limit,
                        lambda x: x)

    @jax.jit
    def run(d: Q3Data):
        return kernel(*d)

    return _traced_query("tpcds_q3", run)


def _q3_kernel(base, years, brands, manufact, month, limit,
               reduce_sum):
    """Shared per-shard q3 body (see _q5_kernel)."""
    n_groups = years * brands

    def compute(s_date, s_item, s_price, d_moy, d_year, i_brand,
                i_manufact):
        di = s_date - base
        year_idx = d_year[di] - d_year[0]
        keep = ((d_moy[di] == month)
                & (i_manufact[s_item] == manufact)
                & (year_idx >= 0) & (year_idx < years))
        brand = i_brand[s_item]
        gid = jnp.where(keep, year_idx * brands + brand, 0)
        amt = jnp.where(keep, s_price, 0)
        sums = reduce_sum(jax.ops.segment_sum(
            amt, gid, num_segments=n_groups))
        cnts = reduce_sum(jax.ops.segment_sum(
            keep.astype(jnp.int64), gid, num_segments=n_groups))
        gidx = jnp.arange(n_groups, dtype=jnp.int64)
        year_of_g = gidx // brands
        brand_of_g = gidx % brands
        sentinel = jnp.int64(2**62)
        k1 = jnp.where(cnts > 0, year_of_g, sentinel)
        # ORDER BY year, sum DESC, brand
        _a, _b, _c, g_s, sum_s, cnt_s = lax.sort(
            (k1, jnp.where(cnts > 0, -sums, sentinel), brand_of_g,
             gidx, sums, cnts), num_keys=3)
        live = cnt_s[:limit] > 0
        # dead slots sentinel their year like q5/q7 (a zero-sum group
        # is otherwise indistinguishable from padding)
        return (jnp.where(live, g_s[:limit] // brands + d_year[0],
                          jnp.int64(2**31 - 1)),
                g_s[:limit] % brands, sum_s[:limit], jnp.sum(cnts))

    return compute


def make_q3_multichip(mesh: Mesh, base: int, years: int, brands: int,
                      manufact: int, month: int = 11,
                      limit: int = 100):
    """q3-shape on the mesh: fact sharded row-parallel, dense date and
    item dims replicated, partial group tables psum'd over ICI."""
    from spark_rapids_tpu.utils.jax_compat import shard_map as smap

    axis = mesh.axis_names[0]
    kernel = _q3_kernel(base, years, brands, manufact, month, limit,
                        lambda x: lax.psum(x, axis))
    shard = P(axis)
    rep = P()
    fn = smap(kernel, mesh=mesh,
              in_specs=(shard, shard, shard, rep, rep, rep, rep),
              out_specs=(rep, rep, rep, rep))
    return _traced_query("tpcds_q3_multichip", jax.jit(fn))


def oracle_q3(d: Q3Data, base: int, brands: int, manufact: int,
              month: int = 11, limit: int = 100):
    h = Q3Data(*(np.asarray(x) for x in d))
    agg = {}
    for i in range(len(h.s_date)):
        di = int(h.s_date[i]) - base
        if int(h.d_moy[di]) != month:
            continue
        item = int(h.s_item[i])
        if int(h.i_manufact[item]) != manufact:
            continue
        key = (int(h.d_year[di]), int(h.i_brand[item]))
        agg[key] = agg.get(key, 0) + int(h.s_price[i])
    rows = sorted(((y, -s, b) for (y, b), s in agg.items()))
    return [(y, b, -negs) for y, negs, b in rows[:limit]]


# ------------------------------------------------------------------- q7


class Q7Data(NamedTuple):
    s_item: jnp.ndarray     # i32
    s_cdemo: jnp.ndarray    # i32 customer-demographics key
    s_promo: jnp.ndarray    # i32 promotion key
    s_qty: jnp.ndarray      # i64
    s_list: jnp.ndarray     # i64 decimal64(2)
    s_coupon: jnp.ndarray   # i64 decimal64(2)
    s_sales: jnp.ndarray    # i64 decimal64(2)
    cd_match: jnp.ndarray   # bool per cdemo key (gender/marital/edu)
    p_match: jnp.ndarray    # bool per promo key (no email/event)
    item_id: jnp.ndarray    # i32 dictionary id per item key


def gen_q7(rows: int = 40_000, items: int = 128, demos: int = 512,
           promos: int = 64, seed: int = 7) -> Q7Data:
    rng = np.random.default_rng(seed)
    return Q7Data(
        jnp.asarray(rng.integers(0, items, rows).astype(np.int32)),
        jnp.asarray(rng.integers(0, demos, rows).astype(np.int32)),
        jnp.asarray(rng.integers(0, promos, rows).astype(np.int32)),
        jnp.asarray(rng.integers(1, 100, rows).astype(np.int64)),
        jnp.asarray(rng.integers(100, 20_000, rows).astype(np.int64)),
        jnp.asarray(rng.integers(0, 5_000, rows).astype(np.int64)),
        jnp.asarray(rng.integers(100, 18_000, rows).astype(np.int64)),
        jnp.asarray((rng.random(demos) < 0.2)),
        jnp.asarray((rng.random(promos) < 0.5)),
        jnp.asarray(rng.permutation(items).astype(np.int32)),
    )


def make_q7(items: int, limit: int = 100):
    """q7-shape single-jit pipeline: sales JOIN customer_demographics
    (selective filter) JOIN promotion (filter) JOIN item; four AVGs
    GROUP BY item dictionary id, ORDER BY item id LIMIT `limit` —
    averages as exact int64 sums with one f64 divide at the edge."""

    kernel = _q7_kernel(items, limit, lambda x: x)

    @jax.jit
    def run(d: Q7Data):
        return kernel(*d)

    return _traced_query("tpcds_q7", run)


def _q7_kernel(items, limit, reduce_sum):
    """Shared per-shard q7 body (see _q5_kernel)."""

    def compute(s_item, s_cdemo, s_promo, s_qty, s_list, s_coupon,
                s_sales, cd_match, p_match, item_id):
        keep = cd_match[s_cdemo] & p_match[s_promo]
        iid = item_id[s_item]
        gid = jnp.where(keep, iid, 0)
        cnt = reduce_sum(jax.ops.segment_sum(
            keep.astype(jnp.int64), gid, num_segments=items))
        sums = [reduce_sum(jax.ops.segment_sum(
            jnp.where(keep, v, 0), gid, num_segments=items))
            for v in (s_qty, s_list, s_coupon, s_sales)]
        denom = jnp.maximum(cnt, 1).astype(jnp.float64)
        avgs = [s.astype(jnp.float64) / denom for s in sums]
        sentinel = jnp.int64(2**62)
        key = jnp.where(cnt > 0, jnp.arange(items, dtype=jnp.int64),
                        sentinel)
        key_s, c_s, a0, a1, a2, a3 = lax.sort(
            (key, cnt, *avgs), num_keys=1)
        return (key_s[:limit], c_s[:limit], a0[:limit], a1[:limit],
                a2[:limit], a3[:limit])

    return compute


def make_q7_multichip(mesh: Mesh, items: int, limit: int = 100):
    """q7-shape on the mesh: facts row-sharded, filter/dictionary dims
    replicated, partial counts/sums psum'd BEFORE the avg divide (a
    mean of shard means would be wrong)."""
    from spark_rapids_tpu.utils.jax_compat import shard_map as smap

    axis = mesh.axis_names[0]
    kernel = _q7_kernel(items, limit, lambda x: lax.psum(x, axis))
    shard = P(axis)
    rep = P()
    fn = smap(kernel, mesh=mesh,
              in_specs=(shard, shard, shard, shard, shard, shard,
                        shard, rep, rep, rep),
              out_specs=(rep,) * 6)
    return _traced_query("tpcds_q7_multichip", jax.jit(fn))


def oracle_q7(d: Q7Data, items: int, limit: int = 100):
    h = Q7Data(*(np.asarray(x) for x in d))
    agg = {}
    for i in range(len(h.s_item)):
        if not (h.cd_match[h.s_cdemo[i]] and h.p_match[h.s_promo[i]]):
            continue
        iid = int(h.item_id[h.s_item[i]])
        e = agg.setdefault(iid, [0, 0, 0, 0, 0])
        e[0] += 1
        e[1] += int(h.s_qty[i])
        e[2] += int(h.s_list[i])
        e[3] += int(h.s_coupon[i])
        e[4] += int(h.s_sales[i])
    out = []
    for iid in sorted(agg)[:limit]:
        c, q, l, cp, sl = agg[iid]
        out.append((iid, c, q / c, l / c, cp / c, sl / c))
    return out


# ------------------------------------- q67 / q89 (stage-IR shapes)
# These two shapes have NO hand-fused kernel: they exist because the
# stage IR (plan/) makes new operators cheap — rollup/cube grouping
# sets and window functions are IR nodes, and the pipelines live in
# plan/catalog.py.  The seeded generators and numpy oracles below are
# their golden contract.


class Q67Data(NamedTuple):
    cat: jnp.ndarray     # i32 category key
    cls: jnp.ndarray     # i32 class key
    sales: jnp.ndarray   # i64 decimal64(2) cents


def gen_q67(rows: int = 20_000, ncat: int = 8, ncls: int = 16,
            seed: int = 67) -> Q67Data:
    rng = np.random.default_rng(seed)
    return Q67Data(
        jnp.asarray(rng.integers(0, ncat, rows).astype(np.int32)),
        jnp.asarray(rng.integers(0, ncls, rows).astype(np.int32)),
        jnp.asarray(rng.integers(100, 50_000, rows).astype(np.int64)),
    )


def oracle_q67(d: Q67Data, ncat: int, ncls: int):
    """q67-shape oracle: finest-level rows as
    [(cat, cls, sum, rank)] ordered by (cat, rank) — rank within
    category by sum DESC, ties by (cat, cls) id ASC — plus the
    per-category rollup sums and the grand total."""
    h = Q67Data(*(np.asarray(x) for x in d))
    agg: dict = {}
    for i in range(len(h.cat)):
        key = (int(h.cat[i]), int(h.cls[i]))
        agg[key] = agg.get(key, 0) + int(h.sales[i])
    rows = []
    for cat in sorted({k[0] for k in agg}):
        grp = sorted(((-s, cls) for (c, cls), s in agg.items()
                      if c == cat))
        for rank, (negs, cls) in enumerate(grp):
            rows.append((cat, cls, -negs, rank))
    sum1 = [sum(s for (c, _cls), s in agg.items() if c == cat)
            for cat in range(ncat)]
    return rows, sum1, sum(agg.values())


def oracle_cube(d: Q67Data, ncat: int, ncls: int):
    """All four grouping sets of CUBE(cat, cls) as dense vectors."""
    h = Q67Data(*(np.asarray(x) for x in d))
    sum0 = np.zeros(ncat * ncls, np.int64)
    cnt0 = np.zeros(ncat * ncls, np.int64)
    for i in range(len(h.cat)):
        g = int(h.cat[i]) * ncls + int(h.cls[i])
        sum0[g] += int(h.sales[i])
        cnt0[g] += 1
    s2 = sum0.reshape(ncat, ncls)
    c2 = cnt0.reshape(ncat, ncls)
    return (sum0, cnt0, s2.sum(axis=1), c2.sum(axis=1),
            int(sum0.sum()), int(cnt0.sum()),
            s2.sum(axis=0), c2.sum(axis=0))


class Q89Data(NamedTuple):
    store: jnp.ndarray   # i32 store key
    item: jnp.ndarray    # i32 item key
    sales: jnp.ndarray   # i64 decimal64(2) cents


def gen_q89(rows: int = 20_000, stores: int = 8, items: int = 32,
            seed: int = 89) -> Q89Data:
    rng = np.random.default_rng(seed)
    return Q89Data(
        jnp.asarray(rng.integers(0, stores, rows).astype(np.int32)),
        jnp.asarray(rng.integers(0, items, rows).astype(np.int32)),
        jnp.asarray(rng.integers(100, 30_000, rows).astype(np.int64)),
    )


def oracle_q89(d: Q89Data, stores: int, items: int):
    """q89-shape oracle: live (store, item) groups ordered by
    (store, item) with each group's sales, its store's total (the
    sum-over-partition window), and the group row count."""
    h = Q89Data(*(np.asarray(x) for x in d))
    agg: dict = {}
    tot = [0] * stores
    for i in range(len(h.store)):
        key = (int(h.store[i]), int(h.item[i]))
        e = agg.setdefault(key, [0, 0])
        e[0] += int(h.sales[i])
        e[1] += 1
        tot[key[0]] += int(h.sales[i])
    return [(st, it, s, tot[st], c)
            for (st, it), (s, c) in sorted(agg.items())]


# --------------------------------------------------- capacity retry


def run_with_capacity_retry(build, args, capacity: int,
                            max_doublings: int = 16):
    """Eager driver for the fixed-capacity pipelines: delegates to the
    CENTRALIZED overflow-retry (parallel/exchange.with_capacity_retry
    — per-capacity step memoization, typed CapacityExceeded, any-shape
    overflow indicators).  The pipelines report overflow as their LAST
    output.  Returns (outputs, capacity_used)."""
    from spark_rapids_tpu.parallel.exchange import with_capacity_retry
    return with_capacity_retry(build, capacity,
                               max_doublings=max_doublings)(*args)


def q5_mesh_data(rows: int, stores: int, n_devices: int,
                 days: int = 60) -> Q5Data:
    """Seeded q5 data shaped for an n-device mesh (row counts rounded
    to shard evenly) — shared by the JVM-driven mesh entry and its
    emission-time oracle so the two cannot drift."""
    rows = max(int(rows) // n_devices, 1) * n_devices
    d = gen_q5(rows=rows, stores=stores, days=days)
    rrows = max(len(np.asarray(d.r_date)) // n_devices, 1) * n_devices
    return d._replace(r_date=d.r_date[:rrows],
                      r_store=d.r_store[:rrows],
                      r_amt=d.r_amt[:rrows], r_loss=d.r_loss[:rrows])


def q72_mesh_data(cs_rows: int, items: int, n_devices: int,
                  days: int = 35) -> Q72Data:
    """Seeded q72 data shaped for an n-device mesh (cs rows rounded to
    shard evenly; inventory replicated) — shared by the JVM mesh entry
    and its emission-time oracle."""
    cs_rows = max(int(cs_rows) // n_devices, 1) * n_devices
    return gen_q72(cs_rows=cs_rows, inv_rows=64, items=items,
                   days=days)


# ----------------------------------------------------- presentation


def present_q5(outs, store_ids: "Sequence[str]"):
    """Decode q5 outputs at the presentation boundary: dictionary ids
    map back to store id STRINGS here — strings never entered the
    jitted program (module docstring).  Returns
    [(store_id_str, sales, returns, profit), ...] for live rows."""
    key_s, sales, rets, profit, _overflow = outs
    key = np.asarray(key_s)
    live = key != 2**31 - 1
    return [(store_ids[int(k)], int(a), int(b), int(c))
            for k, a, b, c in zip(key[live], np.asarray(sales)[live],
                                  np.asarray(rets)[live],
                                  np.asarray(profit)[live])]


def present_q72(outs, item_ids: "Sequence[str]"):
    """Decode q72 outputs: item dictionary ids -> item id strings."""
    items, weeks, cnts, _overflow = outs
    cnts_np = np.asarray(cnts)
    live = cnts_np > 0
    return [(item_ids[int(i)], int(w), int(c))
            for i, w, c in zip(np.asarray(items)[live],
                               np.asarray(weeks)[live],
                               cnts_np[live])]
