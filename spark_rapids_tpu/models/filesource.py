"""File-backed TPC-DS runners: the catalog's storage-to-shuffle path.

The in-memory runners (models/__init__) generate device arrays from a
seed; the ``*_file`` variants here write the SAME seeded data to
parquet ONCE per session per parameter signature, then run every
query file -> ``io/parquet_reader`` -> device columns -> the SAME
cached pipeline (same ``_pipeline`` key, so both variants execute one
shared jitted program).  Because the parquet round trip of int32 /
int64 / bool values is exact, a file-backed query is byte-identical
to its in-memory twin — the property `make ingest-smoke` gates.

Layout per query (projection pushdown exercised on every read):

  q3: store_sales(ss_sold_date_sk, ss_item_sk, ss_ext_sales_price),
      date_dim(d_moy, d_year), item(i_brand_id, i_manufact_id)
  q7: store_sales(7 cols), customer_demographics(cd_match),
      promotion(p_match), item(i_item_id)
  q9: store_sales(ss_quantity, ss_ext_list_price, ss_net_profit)

Knobs: ``SPARK_RAPIDS_TPU_INGEST_DIR`` pins the dataset directory
(default: one mkdtemp per process), ``SPARK_RAPIDS_TPU_INGEST_COMPRESSION``
picks the writer codec (default NONE — byte-stable fixtures; the
reader handles anything pyarrow's codecs do).
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Dict, Optional

import numpy as np

_LOCK = threading.Lock()
_DIR: Optional[str] = None
_WRITTEN: Dict[str, bool] = {}


def data_dir() -> str:
    """The session's parquet dataset directory (created on first use;
    ``SPARK_RAPIDS_TPU_INGEST_DIR`` overrides for shared fixtures)."""
    global _DIR
    with _LOCK:
        if _DIR is None:
            _DIR = os.environ.get("SPARK_RAPIDS_TPU_INGEST_DIR") or \
                tempfile.mkdtemp(prefix="srt-ingest-")
        os.makedirs(_DIR, exist_ok=True)
        return _DIR


def reset_dir() -> None:
    """Forget the cached directory + written set (tests repoint the
    env knob between cases)."""
    global _DIR
    with _LOCK:
        _DIR = None
        _WRITTEN.clear()


def _write_once(name: str, build) -> str:
    """Write ``build()`` (a pyarrow Table) to ``<dir>/<name>.parquet``
    exactly once per signature: atomic tmp+rename, so concurrent pool
    threads (or processes sharing INGEST_DIR) race benignly."""
    path = os.path.join(data_dir(), name + ".parquet")
    with _LOCK:
        if _WRITTEN.get(path) or os.path.exists(path):
            _WRITTEN[path] = True
            return path
    import pyarrow.parquet as pq
    table = build()
    codec = os.environ.get("SPARK_RAPIDS_TPU_INGEST_COMPRESSION",
                           "NONE")
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    pq.write_table(table, tmp, compression=codec)
    os.replace(tmp, path)
    with _LOCK:
        _WRITTEN[path] = True
    return path


def _pa_table(cols: Dict[str, np.ndarray]):
    import pyarrow as pa
    return pa.table({k: pa.array(np.asarray(v)) for k, v in cols.items()})


def _read(path: str, columns):
    from spark_rapids_tpu.io.parquet_reader import read_table
    return read_table(path, columns=list(columns))


def _jnp_bool(col):
    import jax.numpy as jnp
    # BOOL8 columns decode as uint8; the in-memory generators hand the
    # pipelines bool arrays, and sharing their cached executable needs
    # the same dtype
    return col.data.astype(jnp.bool_) if col.data.dtype != jnp.bool_ \
        else col.data


# ------------------------------------------------------------------ q3


def q3_paths(rows: int, items: int, days: int, brands: int,
             seed: int) -> Dict[str, str]:
    from spark_rapids_tpu.models import tpcds
    sig = f"q3_r{rows}_i{items}_d{days}_b{brands}_s{seed}"
    d = [None]

    def gen():
        if d[0] is None:
            d[0] = tpcds.gen_q3(rows=rows, items=items, days=days,
                                brands=brands, seed=seed)
        return d[0]

    return {
        "store_sales": _write_once(sig + "_store_sales", lambda: _pa_table({
            "ss_sold_date_sk": gen().s_date,
            "ss_item_sk": gen().s_item,
            "ss_ext_sales_price": gen().s_price})),
        "date_dim": _write_once(sig + "_date_dim", lambda: _pa_table({
            "d_moy": gen().d_moy, "d_year": gen().d_year})),
        "item": _write_once(sig + "_item", lambda: _pa_table({
            "i_brand_id": gen().i_brand,
            "i_manufact_id": gen().i_manufact})),
    }


def run_q3_file(params: dict, ctx):
    from spark_rapids_tpu import models
    from spark_rapids_tpu.models import tpcds
    ctx.check_cancel()
    rows = int(params.get("rows", 2048))
    items = int(params.get("items", 128))
    brands = int(params.get("brands", 16))
    manufact = int(params.get("manufact", 3))
    seed = int(params.get("seed", 3))
    base = 10_957
    paths = q3_paths(rows, items, 730, brands, seed)
    ss = _read(paths["store_sales"],
               ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"])
    dd = _read(paths["date_dim"], ["d_moy", "d_year"])
    it = _read(paths["item"], ["i_brand_id", "i_manufact_id"])
    ctx.check_cancel()
    d = tpcds.Q3Data(ss["ss_sold_date_sk"].data, ss["ss_item_sk"].data,
                     ss["ss_ext_sales_price"].data, dd["d_moy"].data,
                     dd["d_year"].data, it["i_brand_id"].data,
                     it["i_manufact_id"].data)
    # SAME pipeline key as the in-memory runner: one shared executable
    q = models._pipeline(("q3", base, brands, manufact),
                         lambda: tpcds.make_q3(base, years=2,
                                               brands=brands,
                                               manufact=manufact))
    year, brand, sums, total = q(d)
    return models._rows(year, brand, sums) + [[int(total)]]


# ------------------------------------------------------------------ q7


def q7_paths(rows: int, items: int, demos: int, promos: int,
             seed: int) -> Dict[str, str]:
    from spark_rapids_tpu.models import tpcds
    sig = f"q7_r{rows}_i{items}_cd{demos}_p{promos}_s{seed}"
    d = [None]

    def gen():
        if d[0] is None:
            d[0] = tpcds.gen_q7(rows=rows, items=items, demos=demos,
                                promos=promos, seed=seed)
        return d[0]

    return {
        "store_sales": _write_once(sig + "_store_sales", lambda: _pa_table({
            "ss_item_sk": gen().s_item, "ss_cdemo_sk": gen().s_cdemo,
            "ss_promo_sk": gen().s_promo, "ss_quantity": gen().s_qty,
            "ss_list_price": gen().s_list,
            "ss_coupon_amt": gen().s_coupon,
            "ss_sales_price": gen().s_sales})),
        "customer_demographics": _write_once(sig + "_cd", lambda: _pa_table({
            "cd_match": gen().cd_match})),
        "promotion": _write_once(sig + "_promotion", lambda: _pa_table({
            "p_match": gen().p_match})),
        "item": _write_once(sig + "_item", lambda: _pa_table({
            "i_item_id": gen().item_id})),
    }


def run_q7_file(params: dict, ctx):
    from spark_rapids_tpu import models
    from spark_rapids_tpu.models import tpcds
    ctx.check_cancel()
    rows = int(params.get("rows", 2048))
    items = int(params.get("items", 64))
    seed = int(params.get("seed", 7))
    paths = q7_paths(rows, items, 256, 32, seed)
    ss = _read(paths["store_sales"],
               ["ss_item_sk", "ss_cdemo_sk", "ss_promo_sk",
                "ss_quantity", "ss_list_price", "ss_coupon_amt",
                "ss_sales_price"])
    cd = _read(paths["customer_demographics"], ["cd_match"])
    pr = _read(paths["promotion"], ["p_match"])
    it = _read(paths["item"], ["i_item_id"])
    ctx.check_cancel()
    d = tpcds.Q7Data(ss["ss_item_sk"].data, ss["ss_cdemo_sk"].data,
                     ss["ss_promo_sk"].data, ss["ss_quantity"].data,
                     ss["ss_list_price"].data,
                     ss["ss_coupon_amt"].data,
                     ss["ss_sales_price"].data,
                     _jnp_bool(cd["cd_match"]),
                     _jnp_bool(pr["p_match"]), it["i_item_id"].data)
    q = models._pipeline(("q7", items), lambda: tpcds.make_q7(items))
    return models._rows(*q(d))


# ------------------------------------------------------------------ q9


def q9_path(rows: int, seed: int) -> str:
    from spark_rapids_tpu.models import tpcds
    sig = f"q9_r{rows}_s{seed}"

    def build():
        qty, price, profit = tpcds.gen_q9(rows=rows, seed=seed)
        return _pa_table({"ss_quantity": qty,
                          "ss_ext_list_price": price,
                          "ss_net_profit": profit})

    return _write_once(sig + "_store_sales", build)


def run_q9_file(params: dict, ctx):
    from spark_rapids_tpu import models
    from spark_rapids_tpu.models import tpcds
    ctx.check_cancel()
    rows = int(params.get("rows", 4096))
    seed = int(params.get("seed", 9))
    path = q9_path(rows, seed)
    ss = _read(path, ["ss_quantity", "ss_ext_list_price",
                      "ss_net_profit"])
    ctx.check_cancel()
    counts, avg_p, avg_n = tpcds.run_q9(
        ss["ss_quantity"].data, ss["ss_ext_list_price"].data,
        ss["ss_net_profit"].data)
    return models._rows(counts, avg_p, avg_n)
