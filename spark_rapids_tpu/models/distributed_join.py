"""Distributed inner join: the shuffle-join pipeline the reference's
kudo shuffle + join_primitives serve in Spark (KudoSerializer.java
write/merge + JoinPrimitives sort-merge), re-designed TPU-first as ONE
jitted SPMD program: hash-partition both sides by key, exchange rows
over ICI with `jax.lax.all_to_all`, then run the fixed-capacity device
join locally on every chip.  No serialization, no host hops — the wire
format between chips is just sharded arrays (docs/tpu_design.md §6).

Overflow anywhere (a partition outgrowing its exchange slots, or local
pairs outgrowing the join capacity) is *detected*, not silently dropped:
true counts travel with the data, mirroring the retry-with-larger-budget
contract the reference's OOM machinery enforces on the JVM side.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spark_rapids_tpu.ops.device_join import inner_join_device
from spark_rapids_tpu.utils.jax_compat import shard_map
from spark_rapids_tpu.parallel.exchange import exchange


def _local_step(lk, lv, rk, rv, *, axis_name, n_parts, exch_cap,
                pair_cap):
    """Per-shard body (runs under shard_map): partition, exchange both
    sides, join locally, return joined (key, lval, rval) slots."""
    lk = lk.reshape(-1)
    lv = lv.reshape(-1)
    rk = rk.reshape(-1)
    rv = rv.reshape(-1)
    part_l = (lk % n_parts).astype(jnp.int32)
    part_r = (rk % n_parts).astype(jnp.int32)
    (lk_r, lv_r), l_valid, _, l_sends = exchange(
        [lk, lv], part_l, axis_name, n_parts, exch_cap)
    (rk_r, rv_r), r_valid, _, r_sends = exchange(
        [rk, rv], part_r, axis_name, n_parts, exch_cap)
    pairs = inner_join_device(lk_r, rk_r, pair_cap,
                              left_valid=l_valid, right_valid=r_valid)
    out_k = jnp.where(pairs.valid, lk_r[pairs.left_indices], 0)
    out_lv = jnp.where(pairs.valid, lv_r[pairs.left_indices], 0)
    out_rv = jnp.where(pairs.valid, rv_r[pairs.right_indices], 0)
    overflow = (jnp.max(jnp.maximum(l_sends, r_sends)) > exch_cap) \
        | (pairs.total > pair_cap)
    return (out_k[None], out_lv[None], out_rv[None],
            pairs.valid[None], pairs.total[None], overflow[None])


def make_distributed_join(mesh: Mesh, exch_cap: int, pair_cap: int):
    """Build the jitted all-chip join step over `mesh` (axis 'x').

    Returns fn(left_keys, left_vals, right_keys, right_vals) ->
    (keys, lvals, rvals, valid, per_shard_totals, overflow_flags), all
    sharded (n_dev, ...) — slot layout per shard, true counts alongside.
    The mesh's first axis name is used for the collectives.
    """
    n = mesh.devices.size
    ax = mesh.axis_names[0]
    body = partial(_local_step, axis_name=ax, n_parts=n,
                   exch_cap=exch_cap, pair_cap=pair_cap)
    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(ax), P(ax), P(ax), P(ax)),
        out_specs=(P(ax), P(ax), P(ax), P(ax), P(ax), P(ax)))

    sharding = NamedSharding(mesh, P(ax))

    @jax.jit
    def step(lk, lv, rk, rv):
        lk = jax.lax.with_sharding_constraint(lk, sharding)
        rk = jax.lax.with_sharding_constraint(rk, sharding)
        return mapped(lk, lv, rk, rv)

    return step


def make_distributed_join_auto(mesh: Mesh, exch_cap: int = 256,
                               pair_cap: int = 512, *,
                               max_doublings: int = 6):
    """Budget-learning variant: the centralized overflow retry
    (parallel/exchange.with_capacity_retry) re-runs with doubled
    exchange/pair capacities until nothing is dropped — callers never
    hand-check send_counts.

    Returns run(lk, lv, rk, rv) -> ((keys, lvals, rvals, valid, totals,
    overflow), (exch_cap_used, pair_cap_used))."""
    from spark_rapids_tpu.parallel.exchange import with_capacity_retry

    def make_step(cap):
        # pair capacity scales with the exchange budget so one knob
        # drives the doubling loop
        scale = cap / exch_cap
        return make_distributed_join(mesh, cap,
                                     max(1, int(pair_cap * scale)))

    inner = with_capacity_retry(make_step, exch_cap,
                                max_doublings=max_doublings,
                                overflow_index=5)

    def run(lk, lv, rk, rv):
        out, cap_used = inner(lk, lv, rk, rv)
        scale = cap_used / exch_cap
        return out, (cap_used, max(1, int(pair_cap * scale)))

    return run
