"""ICI fast-path shuffle: hash-partition exchange over a jax mesh axis.

The reference's data-parallel story delegates cross-node movement to
Spark's byte-blob shuffle (SURVEY.md §2.2 checklist).  On TPU, chips in a
slice are directly connected (ICI), so the idiomatic exchange is NOT bytes
through the host: columns stay arrays and move with jax.lax.all_to_all
inside shard_map, with XLA scheduling the collective.

Because XLA collectives need static shapes, partitions are exchanged in
fixed-capacity slots: each device sends an (n_parts, capacity, ...) padded
block per column plus true counts; receivers get (n_parts*capacity, ...)
padded rows and a validity mask.  Capacity is the caller's budget — the
same memory-budgeted-chunking philosophy as the reference's
get_json_object batching (SURVEY.md §3.4).  Rows beyond capacity are
dropped from the padded slots, but true per-destination sizes travel
alongside the data, so overflow is detectable, never silent.

Overflow handling is CENTRALIZED in `with_capacity_retry` below: wrap a
capacity-parameterized program factory and the driver re-runs with a
doubled budget whenever the program reports overflow — the same
retry-with-larger-budget loop the reference's OOM machinery enforces on
the JVM side (SparkResourceAdaptor split-and-retry).  Callers no longer
hand-roll the check.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import observability as _obs

_I32 = jnp.int32


def build_padded_sends(arrays: Sequence[jnp.ndarray], part: jnp.ndarray,
                       n_parts: int, capacity: int):
    """Pack rows into per-destination padded slots.

    arrays: per-column row-major arrays (rows, ...) sharing axis 0.
    part:   (rows,) int32 destination partition per row.
    Returns (sends, counts): sends[i] has shape (n_parts, capacity, ...);
    counts is (n_parts,) true row counts (may exceed capacity — caller
    checks)."""
    rows = part.shape[0]
    order = jnp.argsort(part)
    p_sorted = part[order]
    counts = jnp.bincount(part, length=n_parts).astype(_I32)
    starts = jnp.concatenate(
        [jnp.zeros(1, _I32), jnp.cumsum(counts)[:-1].astype(_I32)])
    rank = jnp.arange(rows, dtype=_I32) - starts[p_sorted]
    slot = jnp.where(rank < capacity, rank, capacity)  # overflow -> dropped
    sends = []
    for a in arrays:
        buf = jnp.zeros((n_parts, capacity) + a.shape[1:], a.dtype)
        sends.append(buf.at[p_sorted, slot].set(a[order], mode="drop"))
    return sends, counts


def exchange(arrays: Sequence[jnp.ndarray], part: jnp.ndarray,
             axis_name: str, n_parts: int, capacity: int):
    """All-to-all hash exchange inside shard_map.

    Each device keeps rows with part == its own index after the exchange.
    Returns (received arrays each (n_parts*capacity, ...), valid mask
    (n_parts*capacity,), total_received (int32 scalar), send_counts
    (n_parts,) int32 — the TRUE outbound sizes; any entry > capacity means
    rows were dropped and the caller must retry with a larger budget)."""
    sends, send_counts = build_padded_sends(arrays, part, n_parts, capacity)
    recv_counts = jax.lax.all_to_all(
        send_counts.reshape(n_parts, 1), axis_name, split_axis=0,
        concat_axis=0).reshape(n_parts)
    recv_counts = jnp.minimum(recv_counts, capacity)
    received = []
    for s in sends:
        r = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)
        received.append(r.reshape((n_parts * capacity,) + s.shape[2:]))
    slot_idx = jnp.arange(n_parts * capacity, dtype=_I32) % capacity
    src_idx = jnp.arange(n_parts * capacity, dtype=_I32) // capacity
    valid = slot_idx < recv_counts[src_idx]
    return received, valid, jnp.sum(recv_counts).astype(_I32), send_counts


class CapacityExceeded(RuntimeError):
    """Raised when a budgeted SPMD program still overflows at the retry
    ceiling (the analog of GpuSplitAndRetryOOM escaping the retries)."""

    def __init__(self, capacity: int, doublings: int):
        super().__init__(
            f"exchange capacity {capacity} still overflowed after "
            f"{doublings} doublings")
        self.capacity = capacity


def with_capacity_retry(make_step: Callable[[int], Callable],
                        initial_capacity: int, *,
                        max_doublings: int = 6,
                        overflow_index: int = -1):
    """Centralized overflow retry for fixed-capacity SPMD programs.

    make_step(capacity) must return a callable whose output tuple
    carries a boolean overflow indicator at `overflow_index` (any shape;
    any True element means rows were dropped).  The wrapper runs the
    program, checks the indicator on the host, and re-builds at double
    the capacity until clean — compilation per capacity is cached by
    jit, so steady-state workloads pay the retry only while the budget
    is learning.

    Returns run(*args) -> (outputs, capacity_used)."""
    steps = {}

    def run(*args):
        # stage-level span: one per driver invocation, covering every
        # capacity attempt (per-attempt sub-spans would double-count
        # the final successful run's time)
        with _obs.TRACER.span("exchange_capacity_retry",
                              kind="stage") as sp:
            cap = int(initial_capacity)
            for attempt in range(max_doublings + 1):
                if cap not in steps:
                    steps[cap] = make_step(cap)
                out = steps[cap](*args)
                if not bool(np.any(np.asarray(out[overflow_index]))):
                    sp.set_attr("capacity", cap)
                    sp.set_attr("attempts", attempt + 1)
                    return out, cap
                if attempt < max_doublings:
                    _obs.record_exchange_doubling(cap, cap * 2, attempt)
                    cap *= 2
            sp.set_attr("capacity", cap)
            sp.set_attr("overflowed", True)
            _obs.JOURNAL.emit("exchange_capacity_exceeded", capacity=cap,
                              doublings=max_doublings)
            raise CapacityExceeded(cap, max_doublings)

    return run
