"""ICI fast-path shuffle: hash-partition exchange over a jax mesh axis.

The reference's data-parallel story delegates cross-node movement to
Spark's byte-blob shuffle (SURVEY.md §2.2 checklist).  On TPU, chips in a
slice are directly connected (ICI), so the idiomatic exchange is NOT bytes
through the host: columns stay arrays and move with jax.lax.all_to_all
inside shard_map, with XLA scheduling the collective.

Because XLA collectives need static shapes, partitions are exchanged in
fixed-capacity slots: each device sends an (n_parts, capacity, ...) padded
block per column plus true counts; receivers get (n_parts*capacity, ...)
padded rows and a validity mask.  Capacity is the caller's budget — the
same memory-budgeted-chunking philosophy as the reference's
get_json_object batching (SURVEY.md §3.4).  Rows beyond capacity are
dropped from the padded slots, but true per-destination sizes travel
alongside the data, so overflow is detectable, never silent.

Overflow handling is CENTRALIZED in `with_capacity_retry` below: wrap a
capacity-parameterized program factory and the driver re-runs with a
doubled budget whenever the program reports overflow — the same
retry-with-larger-budget loop the reference's OOM machinery enforces on
the JVM side (SparkResourceAdaptor split-and-retry).  Callers no longer
hand-roll the check.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import observability as _obs

_I32 = jnp.int32

# counting-sort rank working-set cap: the (rows, n_parts) int32 cumsum
# beyond this falls back to the stable-argsort layout
_COUNTING_SORT_MAX_BYTES = 64 << 20


def build_padded_sends(arrays: Sequence[jnp.ndarray], part: jnp.ndarray,
                       n_parts: int, capacity: int):
    """Pack rows into per-destination padded slots.

    arrays: per-column row-major arrays (rows, ...) sharing axis 0.
    part:   (rows,) int32 destination partition per row.
    Returns (sends, counts): sends[i] has shape (n_parts, capacity, ...);
    counts is (n_parts,) true row counts (may exceed capacity — caller
    checks)."""
    # stable counting sort (ISSUE 9 satellite): partition ids are small
    # ints, so the within-partition rank is one (rows, n_parts) one-hot
    # cumsum — O(n * n_parts) elementwise work instead of the
    # O(n log n) comparator sort jnp.argsort paid on every exchange.
    # No explicit reorder is even needed: (partition, rank) slots are
    # unique, so each row scatters straight to its padded slot, and the
    # receive-side (src, slot) order is byte-identical to the old
    # argsort layout (rank == stable sorted position within partition).
    # The (rows, n_parts) int32 cumsum is the working set; past a
    # budget it would dwarf the row data, so huge shards keep the
    # argsort layout (identical (partition, rank) slots either way).
    pi = part.astype(_I32)
    rows = int(pi.shape[0])
    if rows * max(n_parts, 1) * 4 <= _COUNTING_SORT_MAX_BYTES:
        onehot = pi[:, None] == jnp.arange(n_parts, dtype=_I32)[None, :]
        rank = jnp.take_along_axis(
            jnp.cumsum(onehot.astype(_I32), axis=0),
            jnp.clip(pi, 0, n_parts - 1)[:, None], axis=1)[:, 0] - 1
        counts = jnp.sum(onehot, axis=0, dtype=_I32)
    else:
        order = jnp.argsort(pi)          # jnp.argsort is stable
        p_sorted = pi[order]
        counts = jnp.bincount(pi, length=n_parts).astype(_I32)
        starts = jnp.concatenate(
            [jnp.zeros(1, _I32), jnp.cumsum(counts)[:-1].astype(_I32)])
        rank_sorted = jnp.arange(rows, dtype=_I32) - starts[p_sorted]
        rank = jnp.zeros(rows, _I32).at[order].set(rank_sorted)
    slot = jnp.where(rank < capacity, rank, capacity)  # overflow -> dropped
    sends = []
    for a in arrays:
        buf = jnp.zeros((n_parts, capacity) + a.shape[1:], a.dtype)
        sends.append(buf.at[pi, slot].set(a, mode="drop"))
    return sends, counts


def exchange(arrays: Sequence[jnp.ndarray], part: jnp.ndarray,
             axis_name: str, n_parts: int, capacity: int):
    """All-to-all hash exchange inside shard_map.

    Each device keeps rows with part == its own index after the exchange.
    Returns (received arrays each (n_parts*capacity, ...), valid mask
    (n_parts*capacity,), total_received (int32 scalar), send_counts
    (n_parts,) int32 — the TRUE outbound sizes; any entry > capacity means
    rows were dropped and the caller must retry with a larger budget)."""
    sends, send_counts = build_padded_sends(arrays, part, n_parts, capacity)
    recv_counts = jax.lax.all_to_all(
        send_counts.reshape(n_parts, 1), axis_name, split_axis=0,
        concat_axis=0).reshape(n_parts)
    recv_counts = jnp.minimum(recv_counts, capacity)
    received = []
    for s in sends:
        r = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)
        received.append(r.reshape((n_parts * capacity,) + s.shape[2:]))
    slot_idx = jnp.arange(n_parts * capacity, dtype=_I32) % capacity
    src_idx = jnp.arange(n_parts * capacity, dtype=_I32) // capacity
    valid = slot_idx < recv_counts[src_idx]
    return received, valid, jnp.sum(recv_counts).astype(_I32), send_counts


class CapacityExceeded(RuntimeError):
    """Raised when a budgeted SPMD program still overflows at the retry
    ceiling (the analog of GpuSplitAndRetryOOM escaping the retries).

    ``send_counts`` carries the observed overflow indicator from the
    last attempt — for the raw exchange that is the TRUE per-destination
    row counts, so the caller (and the journal) can see HOW FAR over
    budget the exchange was, not just that it overflowed."""

    def __init__(self, capacity: int, doublings: int,
                 send_counts=None, reason: str = "overflowed"):
        super().__init__(
            f"exchange capacity {capacity} still {reason} after "
            f"{doublings} doublings"
            + (f" (observed counts {send_counts})"
               if send_counts is not None else ""))
        self.capacity = capacity
        self.doublings = doublings
        self.send_counts = send_counts


def _observed_counts(indicator: np.ndarray):
    """The true per-destination sizes carried into CapacityExceeded
    when the caller opted into a counts indicator (bounded: the first
    64 entries)."""
    if indicator.size:
        return [int(x) for x in indicator.reshape(-1)[:64]]
    return None


# ------------------------------------------------- pluggable transport
# The SPMD exchange above moves arrays over ICI inside one process.
# Table-granularity exchanges (the kudo shuffle) go through a pluggable
# TRANSPORT instead: by default an in-process loopback that still
# round-trips the real wire bytes (partition -> kudo write -> kudo
# read/merge), and — when the distributed runtime installs its
# ShuffleService (spark_rapids_tpu/distributed/) — TCP/unix-socket
# links between worker processes.  Callers write against
# ``exchange_tables`` and never know which side of a process boundary
# their peers live on.


class InProcessKudoTransport:
    """Single-process loopback transport: every destination is this
    process.  Partitions still serialize through the kudo wire format
    and merge back through ``read_tables``/``merge_to_table``, so the
    byte path (KTRX trace context, KCRC trailers included) is
    identical to the socket transport's — only the socket is elided."""

    rank = 0
    world = 1

    def exchange(self, op_id: int, tables_by_dest, fields=None):
        import io

        from spark_rapids_tpu.shuffle import kudo as _kudo
        from spark_rapids_tpu.shuffle.schema import schema_of_table
        if len(tables_by_dest) != 1:
            raise ValueError(
                "in-process transport has world=1; got "
                f"{len(tables_by_dest)} destinations (install a "
                "distributed transport via set_table_transport)")
        table = tables_by_dest[0]
        if fields is None:
            fields = schema_of_table(table)
        buf = io.BytesIO()
        _kudo.write_to_stream_with_metrics(
            table.columns, buf, 0, table.num_rows)
        buf.seek(0)
        return _kudo.merge_to_table(_kudo.read_tables(buf), fields)

    def allgather(self, op_id: int, table, fields=None):
        return self.exchange(op_id, [table], fields)


_TABLE_TRANSPORT = [None]


def set_table_transport(transport) -> object:
    """Install the process's table transport (the distributed runtime
    registers its ShuffleService here; ``None`` restores the
    in-process loopback).  Returns the prior transport."""
    prior = _TABLE_TRANSPORT[0]
    _TABLE_TRANSPORT[0] = transport
    return prior


def table_transport():
    """The installed transport, or the in-process loopback default."""
    t = _TABLE_TRANSPORT[0]
    if t is None:
        t = _TABLE_TRANSPORT[0] = InProcessKudoTransport()
    return t


def exchange_tables(op_id: int, tables_by_dest, fields=None):
    """All-to-all at table granularity over the installed transport:
    ``tables_by_dest[d]`` goes to rank ``d``; returns the merged Table
    of everything addressed to THIS rank, partitions concatenated in
    source-rank order (deterministic merge — the property the
    byte-identity gates assert)."""
    return table_transport().exchange(op_id, tables_by_dest, fields)


def allgather_table(op_id: int, table, fields=None):
    """Every rank contributes ``table``; every rank receives the
    rank-ordered concatenation of all contributions."""
    return table_transport().allgather(op_id, table, fields)


def with_capacity_retry(make_step: Callable[[int], Callable],
                        initial_capacity: int, *,
                        max_doublings: int = 6,
                        overflow_index: int = -1,
                        policy=None,
                        counts_indicator: bool = False,
                        check: Optional[Callable[[], None]] = None):
    """Centralized overflow retry for fixed-capacity SPMD programs.

    make_step(capacity) must return a callable whose output tuple
    carries an overflow indicator at `overflow_index`.  By default it
    is a truthiness flag (any shape; any true/non-zero element means
    rows were dropped).  With ``counts_indicator=True`` the indicator
    is instead the RAW send_counts array: the driver compares it
    against the current capacity itself, and a terminal
    CapacityExceeded reports the true per-destination sizes.  (The
    interpretation is an explicit opt-in — an integer 0/1 flag under
    the default stays a flag.)  The wrapper runs the program, checks
    the indicator on the host, and re-builds at double the capacity
    until clean — compilation per capacity is cached by jit, so
    steady-state workloads pay the retry only while the budget is
    learning.

    The attempt loop rides the SAME RetryPolicy the task-level retry
    drivers use (robustness/retry.py): `policy` bounds attempts
    (default ``max_doublings + 1``), applies its backoff between
    rebuilds, and its wall-clock deadline — a deadline hit raises
    CapacityExceeded early instead of compiling ever-larger programs.

    ``check`` (optional) runs at the top of EVERY capacity attempt —
    the elastic fleet passes ``QueryContext.check_cancel`` here so a
    speculative re-execution whose original arrived mid-retry unwinds
    through the cooperative cancel machinery instead of compiling the
    next doubling for a result nobody wants.

    Returns run(*args) -> (outputs, capacity_used)."""
    from spark_rapids_tpu.perf import jit_cache as _jc
    from spark_rapids_tpu.robustness.retry import RetryPolicy
    steps = {}
    pol = policy or RetryPolicy(max_attempts=max_doublings + 1,
                                base_backoff_s=0.0)

    def _step_for(cap: int):
        """Capacity-parameterized programs live in the process compile
        cache (perf/jit_cache.py): one entry per (factory, capacity),
        so steady-state budgets survive across driver instances, show
        up in srt_jit_cache_* stats, and participate in LRU eviction.
        The factory object itself is the entry owner — identity-checked
        on hits, so a recycled id() can never resurrect a stale step."""
        if not _jc.CACHE.enabled():
            if cap not in steps:
                steps[cap] = make_step(cap)
            return steps[cap]
        return _jc.CACHE.get_or_build(
            "exchange.step", f"factory@{id(make_step)}", cap,
            lambda: make_step(cap), owner=make_step,
            counts_compile=False)

    def run(*args):
        # stage-level span: one per driver invocation, covering every
        # capacity attempt (per-attempt sub-spans would double-count
        # the final successful run's time)
        with _obs.TRACER.span("exchange_capacity_retry",
                              kind="stage") as sp:
            cap = int(initial_capacity)
            t0 = pol.clock()
            attempt = 0
            lost_ns = 0
            prev_backoff = 0.0
            while True:
                if check is not None:
                    check()
                attempt_t0 = time.monotonic_ns()
                out = _step_for(cap)(*args)
                indicator = np.asarray(out[overflow_index])
                if counts_indicator:
                    overflowed = bool(np.any(indicator > cap))
                else:
                    overflowed = bool(np.any(indicator))
                if not overflowed:
                    sp.set_attr("capacity", cap)
                    sp.set_attr("attempts", attempt + 1)
                    if attempt:
                        _obs.record_retry_episode(
                            "exchange_capacity", attempts=attempt + 1,
                            retries=attempt, splits=0,
                            max_split_depth=0, lost_ns=lost_ns,
                            outcome="success",
                            errors=["CapacityOverflow"] * attempt)
                    return out, cap
                attempt += 1
                lost_ns += time.monotonic_ns() - attempt_t0
                deadline_hit = (pol.deadline_s is not None
                                and pol.clock() - t0 >= pol.deadline_s)
                if attempt >= pol.max_attempts or deadline_hit:
                    counts = (_observed_counts(indicator)
                              if counts_indicator else None)
                    sp.set_attr("capacity", cap)
                    sp.set_attr("overflowed", True)
                    _obs.JOURNAL.emit("exchange_capacity_exceeded",
                                      capacity=cap,
                                      doublings=attempt - 1,
                                      send_counts=counts)
                    _obs.record_retry_episode(
                        "exchange_capacity", attempts=attempt,
                        retries=attempt, splits=0, max_split_depth=0,
                        lost_ns=lost_ns, outcome="exhausted:deadline"
                        if deadline_hit else "exhausted:attempts",
                        errors=["CapacityOverflow"] * attempt)
                    raise CapacityExceeded(
                        cap, attempt - 1, send_counts=counts,
                        reason="over deadline" if deadline_hit
                        else "overflowed")
                _obs.record_exchange_doubling(cap, cap * 2, attempt - 1)
                # thread the previous pause through so jittered
                # policies get true decorrelated backoff (retry.py)
                backoff = pol.backoff_for(attempt, prev_backoff)
                prev_backoff = backoff
                if backoff > 0:
                    pol.sleep(backoff)
                cap *= 2

    return run
