"""Resource-management + precondition helpers (reference Arms.java:31-100,
Preconditions.java:28-70, Pair.java:39).  The Java originals exist
because cudf-java handles are manually closed; the Python counterparts
serve the same role for Column/Table handle registries and file streams
in the shim layer."""

from __future__ import annotations

from typing import (Callable, Iterable, NamedTuple, Optional, TypeVar)

R = TypeVar("R")
T = TypeVar("T")
K = TypeVar("K")
V = TypeVar("V")


def close_if_exception(resource: R, fn: Callable[[R], T]) -> T:
    """Run fn(resource); close the resource ONLY if fn raises
    (Arms.java:31 closeIfException)."""
    try:
        return fn(resource)
    except BaseException:
        try:
            if resource is not None:
                resource.close()
        except Exception:
            pass  # suppressed, as the reference adds it as suppressed
        raise


def close_all(resources: Iterable) -> None:
    """Close every resource, remembering the first failure and raising
    it after all closes were attempted (Arms.java:53-90)."""
    first: Optional[BaseException] = None
    for r in resources:
        if r is None:
            continue
        try:
            r.close()
        # srt-lint: disable=SRT007 mirror of Arms.closeAll: the first failure is remembered and raised after every close was attempted
        except BaseException as e:  # noqa: BLE001 - mirror closeAll
            if first is None:
                first = e
    if first is not None:
        raise first


def with_resources(resources, fn):
    """Run fn(resources), closing all of them afterwards
    (Arms.java:93 withResource)."""
    try:
        return fn(resources)
    finally:
        close_all(resources)


# ------------------------------------------------------- preconditions

def ensure(condition: bool, message) -> None:
    """Raise ValueError unless condition (Preconditions.java:28-44;
    message may be a string or a zero-arg callable)."""
    if not condition:
        raise ValueError(message() if callable(message) else message)


def ensure_non_negative(value: int, name: str) -> int:
    """Raise ValueError when value < 0 (Preconditions.java:50-70)."""
    if value < 0:
        raise ValueError(f"{name} must be non-negative, but was {value}")
    return value


class Pair(NamedTuple):
    """Immutable 2-tuple with named accessors (Pair.java:39)."""
    left: object
    right: object

    @staticmethod
    def of(left, right) -> "Pair":
        return Pair(left, right)
