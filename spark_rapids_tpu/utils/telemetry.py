"""Device telemetry (reference NVMLJni.cpp + nvml/*.java: device info,
utilization, memory, periodic NVMLMonitor with callback interface).

TPU mapping: per-device info from jax.devices() metadata and
device.memory_stats() (libtpu-provided HBM counters); the periodic
monitor mirrors NVMLMonitor.java:49's start/stop + listener shape."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax

from spark_rapids_tpu import observability as _obs


@dataclass
class DeviceInfo:
    index: int
    kind: str
    platform: str
    process_index: int
    memory_stats: Dict[str, int] = field(default_factory=dict)


def get_device_count() -> int:
    return len(jax.devices())


def get_device_info(index: int = 0) -> DeviceInfo:
    d = jax.devices()[index]
    stats: Dict[str, int] = {}
    try:
        raw = d.memory_stats()
        if raw:
            stats = {k: int(v) for k, v in raw.items()}
    except Exception:
        pass
    return DeviceInfo(index=index, kind=d.device_kind,
                      platform=d.platform,
                      process_index=d.process_index,
                      memory_stats=stats)


def get_memory_info(index: int = 0) -> Dict[str, int]:
    """{'total': .., 'used': ..} when the backend exposes it (the NVML
    memory query analog)."""
    stats = get_device_info(index).memory_stats
    out = {}
    if "bytes_limit" in stats:
        out["total"] = stats["bytes_limit"]
    if "bytes_in_use" in stats:
        out["used"] = stats["bytes_in_use"]
        if "total" in out:
            out["free"] = out["total"] - out["used"]
    return out


class TelemetryNotSupported(RuntimeError):
    """Explicit NVML_ERROR_NOT_SUPPORTED analog: queries the current
    backend/platform cannot answer raise instead of returning
    plausible-looking zeros."""


def get_device_utilization(index: int = 0) -> float:
    """Device duty-cycle analog of nvmlDeviceGetUtilizationRates.

    libtpu exposes no utilization counter through jax today; HBM
    occupancy is the closest proxy and is reported as `used/total`.
    Raises TelemetryNotSupported when the backend has no memory stats
    (e.g. the CPU backend)."""
    mem = get_memory_info(index)
    if "total" not in mem or "used" not in mem or not mem["total"]:
        raise TelemetryNotSupported(
            "device utilization: backend exposes no HBM counters")
    return mem["used"] / mem["total"]


def get_power_usage_watts(index: int = 0) -> float:
    """nvmlDeviceGetPowerUsage analog — no public libtpu counter; kept
    as an explicit unsupported surface so callers can distinguish
    'no data' from 'zero watts'."""
    raise TelemetryNotSupported("power telemetry not exposed by libtpu")


def get_clock_mhz(index: int = 0) -> float:
    """nvmlDeviceGetClockInfo analog — same explicit-unsupported story
    as power."""
    raise TelemetryNotSupported("clock telemetry not exposed by libtpu")


def get_host_cpu_times() -> Dict[str, int]:
    """Host CPU jiffies from /proc/stat (user/system/idle/iowait) —
    sample twice and diff for utilization."""
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()
    except OSError as e:
        raise TelemetryNotSupported(f"/proc/stat unreadable: {e}")
    v = [int(x) for x in parts[1:8]]
    if not any(v):
        # gVisor-style sandboxes expose /proc/stat with every jiffy
        # counter zero; that carries no signal, same as no counters
        raise TelemetryNotSupported("/proc/stat reports zero jiffies")
    return {"user": v[0] + v[1], "system": v[2], "idle": v[3],
            "iowait": v[4]}


def get_host_memory_info() -> Dict[str, int]:
    """Host RAM from /proc/meminfo (the NVML host-side counterpart the
    RmmSpark host-alloc hooks budget against)."""
    out: Dict[str, int] = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                k, rest = line.split(":", 1)
                if k in ("MemTotal", "MemAvailable", "MemFree"):
                    out[k] = int(rest.strip().split()[0]) * 1024
    except OSError as e:
        raise TelemetryNotSupported(f"/proc/meminfo unreadable: {e}")
    return out


class Monitor:
    """Periodic sampler with listener callback (NVMLMonitor.java:49).

    Samples carry device info plus host CPU/memory; sampling or
    listener errors are surfaced through `on_error` (and counted in
    `error_count`) rather than swallowed — the NVMLMonitor error-path
    parity the r3 review flagged as missing."""

    def __init__(self, period_millis: int,
                 listener: Callable[[List[DeviceInfo]], None],
                 on_error: Optional[Callable[[Exception], None]] = None):
        self.period = period_millis / 1000.0
        self.listener = listener
        self.on_error = on_error
        self.error_count = 0
        self.sample_count = 0
        self.last_host_cpu: Optional[Dict[str, int]] = None
        self.last_cpu_utilization: Optional[float] = None
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._lifecycle = threading.Lock()

    def start(self):
        with self._lifecycle:
            if self._running:
                return
            self._running = True
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def stop(self, timeout: Optional[float] = None):
        """Idempotent shutdown: safe to call repeatedly, concurrently,
        before start, and even from the listener callback (the sampler
        thread never joins itself).  Joins with a bounded timeout so a
        wedged backend query can never hang the caller."""
        with self._lifecycle:
            self._running = False
            t, self._thread = self._thread, None
        if t is not None and t is not threading.current_thread():
            t.join(timeout if timeout is not None
                   else self.period * 4 + 1)

    def _report(self, exc: Exception):
        self.error_count += 1
        if self.on_error is not None:
            try:
                self.on_error(exc)
            except Exception:
                pass  # an error-handler bug must not kill the monitor

    def _loop(self):
        # `me` check: stop() clears _thread before (maybe) joining, so a
        # stop/start pair that beats this loop's next _running read still
        # terminates the old sampler — only the thread start() installed
        # may keep looping, never two at once
        me = threading.current_thread()
        while self._running and self._thread is me:
            try:
                infos = [get_device_info(i)
                         for i in range(get_device_count())]
            except Exception as e:  # device sampling failure
                self._report(e)
                time.sleep(self.period)
                continue
            # HBM occupancy -> observability gauge (NVML-monitor role in
            # the reference's metrics pipeline); no-op when disabled
            for info in infos:
                b = info.memory_stats.get("bytes_in_use")
                if b is not None:
                    _obs.record_hbm_sample(info.index, b)
            try:
                # host CPU is best-effort: an unreadable /proc/stat
                # (non-Linux) must not starve the device listener
                cpu = get_host_cpu_times()
                if self.last_host_cpu is not None:
                    busy = (cpu["user"] + cpu["system"]
                            - self.last_host_cpu["user"]
                            - self.last_host_cpu["system"])
                    total = busy + (cpu["idle"] + cpu["iowait"]
                                    - self.last_host_cpu["idle"]
                                    - self.last_host_cpu["iowait"])
                    if total > 0:
                        self.last_cpu_utilization = busy / total
                self.last_host_cpu = cpu
            except Exception as e:
                self._report(e)
            self.sample_count += 1
            # liveness stamp + telemetry-plane drive (windowed ticks /
            # SLO burn evaluation run at window granularity off THIS
            # thread; two attribute reads when the plane is off)
            _obs.record_monitor_sample()
            try:
                self.listener(infos)
            except Exception as e:
                self._report(e)
            time.sleep(self.period)
