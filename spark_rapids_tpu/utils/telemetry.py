"""Device telemetry (reference NVMLJni.cpp + nvml/*.java: device info,
utilization, memory, periodic NVMLMonitor with callback interface).

TPU mapping: per-device info from jax.devices() metadata and
device.memory_stats() (libtpu-provided HBM counters); the periodic
monitor mirrors NVMLMonitor.java:49's start/stop + listener shape."""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax


@dataclass
class DeviceInfo:
    index: int
    kind: str
    platform: str
    process_index: int
    memory_stats: Dict[str, int] = field(default_factory=dict)


def get_device_count() -> int:
    return len(jax.devices())


def get_device_info(index: int = 0) -> DeviceInfo:
    d = jax.devices()[index]
    stats: Dict[str, int] = {}
    try:
        raw = d.memory_stats()
        if raw:
            stats = {k: int(v) for k, v in raw.items()}
    except Exception:
        pass
    return DeviceInfo(index=index, kind=d.device_kind,
                      platform=d.platform,
                      process_index=d.process_index,
                      memory_stats=stats)


def get_memory_info(index: int = 0) -> Dict[str, int]:
    """{'total': .., 'used': ..} when the backend exposes it (the NVML
    memory query analog)."""
    stats = get_device_info(index).memory_stats
    out = {}
    if "bytes_limit" in stats:
        out["total"] = stats["bytes_limit"]
    if "bytes_in_use" in stats:
        out["used"] = stats["bytes_in_use"]
        if "total" in out:
            out["free"] = out["total"] - out["used"]
    return out


class Monitor:
    """Periodic sampler with listener callback (NVMLMonitor.java:49)."""

    def __init__(self, period_millis: int,
                 listener: Callable[[List[DeviceInfo]], None]):
        self.period = period_millis / 1000.0
        self.listener = listener
        self._running = False
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._running = False
        if self._thread is not None:
            self._thread.join(self.period * 4 + 1)
            self._thread = None

    def _loop(self):
        while self._running:
            infos = [get_device_info(i)
                     for i in range(get_device_count())]
            try:
                self.listener(infos)
            except Exception:
                pass  # listener bugs must not kill the monitor
            time.sleep(self.period)
