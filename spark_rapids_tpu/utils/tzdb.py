"""Timezone transition database from the system tzdata (TZif files).

The reference builds its device timezone table from JVM ZoneRules
(GpuTimeZoneDB.loadData:262-398: LIST<STRUCT<utcInstant, localInstant,
offset>>).  Here the equivalent table is parsed directly from
/usr/share/zoneinfo TZif v2+ binaries (RFC 8536): per zone, sorted arrays
of (transition instant UTC seconds, UTC offset seconds after transition),
cached per process.  Kernels binary-search these arrays, exactly like the
reference's device binary search (timezones.cu).
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Dict, Tuple

import numpy as np

TZDIR = os.environ.get("TZDIR", "/usr/share/zoneinfo")

_info_cache: Dict[str, "ZoneInfoRecord"] = {}
_lock = threading.Lock()


class ZoneInfoRecord:
    """Full TZif parse: transitions (with -inf sentinel row), UTC offsets,
    per-row DST flags, and the v2+ POSIX TZ footer string."""

    __slots__ = ("trans", "offs", "isdst", "footer")

    def __init__(self, trans, offs, isdst, footer):
        self.trans = trans
        self.offs = offs
        self.isdst = isdst
        self.footer = footer


def _parse_tzif(path: str) -> ZoneInfoRecord:
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != b"TZif":
        raise ValueError(f"not a TZif file: {path}")
    version = data[4:5]

    def header(off):
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt,
         charcnt) = struct.unpack(">6i", data[off + 20: off + 44])
        return isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt

    off = 0
    isutcnt, isstdcnt, leapcnt, timecnt, typecnt, charcnt = header(0)
    v1_size = (44 + timecnt * 5 + typecnt * 6 + charcnt + leapcnt * 8
               + isstdcnt + isutcnt)
    footer = ""
    if version >= b"2":
        # skip v1 block; parse the 64-bit second block
        off = v1_size
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt,
         charcnt) = header(off)
        p = off + 44
        times = np.frombuffer(data, ">i8", timecnt, p)
        p += timecnt * 8
        idx = np.frombuffer(data, np.uint8, timecnt, p)
        p += timecnt
        ttinfos = [struct.unpack(">ibB", data[p + i * 6: p + i * 6 + 6])
                   for i in range(typecnt)]
        p += typecnt * 6 + charcnt + leapcnt * 12 + isstdcnt + isutcnt
        # RFC 8536 §3.3: NL, TZ string, NL
        tail = data[p:]
        if tail[:1] == b"\n":
            end = tail.find(b"\n", 1)
            if end > 0:
                footer = tail[1:end].decode("ascii", "replace")
    else:
        p = 44
        times = np.frombuffer(data, ">i4", timecnt, p).astype(np.int64)
        p += timecnt * 4
        idx = np.frombuffer(data, np.uint8, timecnt, p)
        p += timecnt
        ttinfos = [struct.unpack(">ibB", data[p + i * 6: p + i * 6 + 6])
                   for i in range(typecnt)]
    offsets = np.array([ttinfos[i][0] for i in idx], np.int64) if timecnt \
        else np.zeros(0, np.int64)
    dstflags = np.array([ttinfos[i][1] for i in idx], np.int64) if timecnt \
        else np.zeros(0, np.int64)
    # offset before the first transition: the first non-DST type, falling
    # back to type 0 (RFC 8536 §3.2 guidance)
    base = 0
    if ttinfos:
        base = ttinfos[0][0]
        for utoff, isdst, _ in ttinfos:
            if not isdst:
                base = utoff
                break
    trans = np.concatenate([np.array([-(2**62)], np.int64),
                            times.astype(np.int64)])
    offs = np.concatenate([np.array([base], np.int64), offsets])
    isdst = np.concatenate([np.array([0], np.int64), dstflags])
    return ZoneInfoRecord(trans, offs, isdst, footer)


def _zone_path(zone_id: str) -> str:
    path = os.path.realpath(os.path.join(TZDIR, zone_id))
    tzroot = os.path.realpath(TZDIR)
    if not path.startswith(tzroot + os.sep):
        raise ValueError(f"invalid zone id {zone_id!r}")
    if not os.path.exists(path):
        raise ValueError(f"unknown timezone {zone_id!r}")
    return path


def get_zone_info(zone_id: str) -> ZoneInfoRecord:
    """Full zone record incl. DST flags and POSIX footer (cached)."""
    with _lock:
        if zone_id in _info_cache:
            return _info_cache[zone_id]
    rec = _parse_tzif(_zone_path(zone_id))
    with _lock:
        _info_cache[zone_id] = rec
    return rec


def get_transitions(zone_id: str) -> Tuple[np.ndarray, np.ndarray]:
    """(transition UTC seconds (ascending, starts with -inf sentinel),
    UTC offset seconds in effect from that instant)."""
    rec = get_zone_info(zone_id)
    return rec.trans, rec.offs
