"""In-process profiler (reference profiler/ 4.9k LoC: CUPTI activity ->
flatbuffers -> JVM DataWriter callback, Profiler.java:36-120 control
surface + NVTX ranges in every op).

TPU mapping (SURVEY.md §5): device tracing goes through jax.profiler
(XPlane/TensorBoard, the Nsight analog — the converter role is played by
TensorBoard's trace viewer); the in-process activity stream (op ranges,
allocations) is recorded here and pushed to a DataWriter callback as
length-prefixed JSON records (the flatbuffers analog; self-describing so
the Java shim can decode without a schema compiler)."""

from __future__ import annotations

import json
import struct
import sys
import threading
import time
from contextlib import contextmanager
from typing import Callable, List, Optional

import jax

from spark_rapids_tpu import observability as _obs


class Config:
    """Profiler.Config.Builder analog (Profiler.java:133-145)."""

    def __init__(self, write_buffer_size: int = 1 << 20,
                 flush_period_millis: int = 0,
                 alloc_capture: bool = False,
                 device_trace_dir: Optional[str] = None):
        self.write_buffer_size = write_buffer_size
        self.flush_period_millis = flush_period_millis
        self.alloc_capture = alloc_capture
        self.device_trace_dir = device_trace_dir


class Profiler:
    """Singleton-style control surface: init/start/stop/shutdown."""

    _instance: Optional["Profiler"] = None

    def __init__(self, data_writer: Callable[[bytes], None],
                 config: Config):
        self.writer = data_writer
        self.config = config
        self._buffer: List[bytes] = []
        self._buffered_bytes = 0
        self._lock = threading.Lock()
        self._running = False
        self._device_tracing = False
        self._flusher: Optional[threading.Thread] = None

    # --------------------------------------------------------- lifecycle

    @classmethod
    def init(cls, data_writer, config: Optional[Config] = None
             ) -> "Profiler":
        if cls._instance is not None:
            raise RuntimeError("profiler already initialized")
        cls._instance = Profiler(data_writer, config or Config())
        return cls._instance

    @classmethod
    def get(cls) -> Optional["Profiler"]:
        return cls._instance

    @classmethod
    def shutdown(cls):
        inst = cls._instance
        if inst is not None:
            inst.stop()
            inst.flush()
            cls._instance = None
            # optional sink teardown (file-backed DataWriters set
            # sink_close so EVERY shutdown path releases the file)
            closer = getattr(inst, "sink_close", None)
            if closer is not None:
                try:
                    closer()
                except Exception:
                    pass

    def start(self):
        if self._running:
            return
        self._running = True
        if self.config.device_trace_dir:
            try:
                jax.profiler.start_trace(self.config.device_trace_dir)
                self._device_tracing = True
            except Exception:  # backend may not support tracing
                self._device_tracing = False
        if self.config.flush_period_millis > 0:
            self._flusher = threading.Thread(target=self._flush_loop,
                                             daemon=True)
            self._flusher.start()
        self.record("profiler_start", {})

    def stop(self):
        if not self._running:
            return
        self.record("profiler_stop", {})
        self._running = False
        if self._flusher is not None:
            self._flusher.join(
                self.config.flush_period_millis / 1000.0 * 4 + 1)
            self._flusher = None
        if self._device_tracing:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_tracing = False
        self.flush()

    # --------------------------------------------------------- recording

    def record(self, kind: str, payload: dict):
        """Append one activity record (KernelActivity/ApiActivity/... in
        the reference fbs schema, profiler.fbs:136-287)."""
        if not self._running and kind not in ("profiler_stop",):
            return
        rec = json.dumps({"kind": kind, "t_ns": time.monotonic_ns(),
                          **payload}).encode()
        framed = struct.pack("<I", len(rec)) + rec
        blob = None
        with self._lock:
            self._buffer.append(framed)
            self._buffered_bytes += len(framed)
            if self._buffered_bytes >= self.config.write_buffer_size:
                blob = self._take_locked()
        if blob:
            self.writer(blob)  # outside the lock: writer may re-enter

    def flush(self):
        with self._lock:
            blob = self._take_locked()
        if blob:
            self.writer(blob)

    def _take_locked(self) -> bytes:
        blob = b"".join(self._buffer)
        self._buffer = []
        self._buffered_bytes = 0
        return blob

    def _flush_loop(self):
        period = self.config.flush_period_millis / 1000.0
        while self._running:
            time.sleep(period)
            self.flush()


@contextmanager
def op_range(name: str, **attrs):
    """NVTX3_FUNC_RANGE analog (nvtx_ranges.hpp): wraps an op in a
    jax.profiler annotation, emits a range record to the in-process
    profiler when one is running, and opens a child span on the process
    tracer when tracing is enabled (the span parents under the
    innermost open query/stage/op span on this thread).

    Every bracket records its own range/span — the old same-name-
    nesting suppression is gone because its only source (the shim's
    bracket plus the op layer's `traced` wrapper around ONE logical
    call) is now skipped at the `traced` layer, keyed by the owning
    frame; a genuinely recursive op call is a real nested range and is
    recorded as such."""
    owner = sys._getframe(2)  # frame containing the `with` statement
    stack = _bracket_stack()
    stack.append((name, id(owner)))
    prof = Profiler.get()
    tracer = _obs.TRACER
    span = (tracer.start_span(name, kind="op", attrs=attrs or None)
            if tracer.enabled else None)
    t0 = time.monotonic_ns()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        stack.pop()
        dur_ns = time.monotonic_ns() - t0
        if span is not None:
            span.end()
        if prof is not None:
            prof.record("op_range",
                        {"name": name,
                         "dur_ns": dur_ns,
                         "thread": threading.get_ident(),
                         **attrs})
        # observability spine: per-op latency histogram + per-task
        # attribution (no-op behind one bool when disabled)
        _obs.record_op(name, dur_ns)


_active_ranges = threading.local()


def _bracket_stack() -> list:
    """Thread-local stack of (op name, owner frame id) for brackets
    currently open on this thread."""
    s = getattr(_active_ranges, "stack", None)
    if s is None:
        s = []
        _active_ranges.stack = s
    return s


def active_op_names() -> set:
    """Op names currently inside an op_range on this thread."""
    return {n for n, _ in _bracket_stack()}


def bracket_owned_by(name: str, frame_id: int) -> bool:
    """True when an open bracket for `name` on this thread was entered
    by the frame with id `frame_id` — i.e. the caller asking IS the
    code lexically inside that bracket's `with` statement.  This is the
    shim-over-op double-bracket signature `utils/tracing.traced` must
    suppress (and the ONLY thing it suppresses: a recursive call to the
    same op from a different frame brackets normally)."""
    for n, fid in _bracket_stack():
        if n == name and fid == frame_id:
            return True
    return False


def iter_records(blob: bytes):
    """Decode a DataWriter blob back into record dicts (the
    spark_rapids_profile_converter role for tests/tools)."""
    pos = 0
    while pos < len(blob):
        (n,) = struct.unpack_from("<I", blob, pos)
        pos += 4
        yield json.loads(blob[pos:pos + n])
        pos += n

def record_alloc(kind: str, num_bytes: int) -> None:
    """Allocator hook (reference alloc-capture activity records,
    profiler.fbs AllocActivity): called by the memory adaptor on every
    device alloc/free; no-op unless a running profiler asked for
    alloc_capture."""
    prof = Profiler.get()
    if prof is not None and prof.config.alloc_capture:
        prof.record(kind, {"bytes": int(num_bytes),
                           "thread": threading.get_ident()})
