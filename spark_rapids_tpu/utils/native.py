"""Loader for the native runtime kernels (native/columnar_native.cpp).

The reference's runtime around the device compute path is C++
(SparkResourceAdaptorJni, kudo merge, join prep); here the native library
is compiled on first use with the system g++ and bound through ctypes
(no pybind11 in this image).  Everything has a pure-Python fallback —
set SPARK_RAPIDS_TPU_DISABLE_NATIVE=1 to force it."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libcolumnar_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if os.environ.get("SPARK_RAPIDS_TPU_DISABLE_NATIVE") == "1":
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        src = os.path.join(_NATIVE_DIR, "columnar_native.cpp")
        try:
            if not os.path.exists(_LIB_PATH) or (
                    os.path.exists(src)
                    and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)):
                # compile to a temp name and rename: atomic against
                # concurrent builders (multi-process executors)
                tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                     "-o", tmp, src],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, _LIB_PATH)
            lib = ctypes.CDLL(_LIB_PATH)
            lib.rank_strings.restype = ctypes.c_int64
            lib.rank_strings.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p]
            _lib = lib
        except (OSError, subprocess.SubprocessError):
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def rank_strings(chars: np.ndarray, offsets: np.ndarray
                 ) -> Optional[np.ndarray]:
    """Dense lexicographic ranks for an Arrow string buffer; None when the
    native library is unavailable (caller falls back to np.unique)."""
    lib = _load()
    if lib is None:
        return None
    n = len(offsets) - 1
    chars = np.ascontiguousarray(chars, dtype=np.uint8)
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    out = np.empty(n, np.int64)
    lib.rank_strings(
        chars.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(n),
        out.ctypes.data_as(ctypes.c_void_p))
    return out



