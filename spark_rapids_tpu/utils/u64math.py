"""Shared u64 lane arithmetic for the device numeric engines
(ftos_device Ryu, stod_device Eisel-Lemire, hllpp registers): 128-bit
products from 32-bit limbs and branchless count-leading-zeros — the
integer substrate this backend's f64-as-raw-bits convention runs on."""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

_U64 = jnp.uint64
_I32 = jnp.int32


def umul128(a, b) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(lo, hi) of the 128-bit product of two u64 lanes."""
    mask = _U64(0xFFFFFFFF)
    a_lo, a_hi = a & mask, a >> _U64(32)
    b_lo, b_hi = b & mask, b >> _U64(32)
    p_ll = a_lo * b_lo
    p_lh = a_lo * b_hi
    p_hl = a_hi * b_lo
    mid = (p_ll >> _U64(32)) + (p_lh & mask) + (p_hl & mask)
    lo = (p_ll & mask) | (mid << _U64(32))
    hi = a_hi * b_hi + (p_lh >> _U64(32)) + (p_hl >> _U64(32)) \
        + (mid >> _U64(32))
    return lo, hi


def clz64(x) -> jnp.ndarray:
    """countl_zero on u64 lanes (binary steps, no float rounding)."""
    out = jnp.zeros(x.shape, _I32)
    v = x
    for bits in (32, 16, 8, 4, 2, 1):
        m = v < (_U64(1) << _U64(64 - bits))
        out = jnp.where(m, out + bits, out)
        v = jnp.where(m, v << _U64(bits), v)
    return jnp.where(x == 0, 64, out)
