"""Spark platform/version predicates + device attributes + file IO SPI
(reference version.hpp / SparkPlatformType.java, DeviceAttr.java,
fileio/RapidsFileIO.java)."""

from __future__ import annotations

import io
import os
from dataclasses import dataclass
from typing import BinaryIO

import jax

# SparkPlatformType.java:17-37 (enum kept in sync with version.hpp)
VANILLA_SPARK = 0
DATABRICKS = 1
CLOUDERA = 2


@dataclass(frozen=True)
class SparkSystem:
    """version.hpp spark_system: platform + version predicates passed to
    kernels whose semantics differ per Spark distro."""

    platform: int
    major: int
    minor: int
    patch: int = 0

    def is_vanilla_320(self) -> bool:
        return (self.platform == VANILLA_SPARK
                and (self.major, self.minor) == (3, 2))

    def is_databricks_14_3_or_later(self) -> bool:
        return (self.platform == DATABRICKS
                and (self.major, self.minor) >= (14, 3))

    def is_vanilla(self) -> bool:
        return self.platform == VANILLA_SPARK


def is_integrated_gpu() -> bool:
    """DeviceAttr.isIntegratedGPU analog: TPUs are discrete accelerators;
    True only for the CPU fallback backend (shares host memory)."""
    return jax.default_backend() == "cpu"


# ----------------------------------------------------- file IO SPI
# (fileio/RapidsFileIO.java:28 — pluggable storage for e.g. parquet
# footers; local-file default, other schemes plug in via subclassing)


class SeekableInputStream(io.BufferedReader):
    """SeekableInputStream contract: read/seek/tell over any storage."""


class RapidsInputFile:
    def __init__(self, path: str):
        self._path = path

    def get_length(self) -> int:
        return os.path.getsize(self._path)

    def open(self) -> "SeekableInputStream":
        return SeekableInputStream(open(self._path, "rb", buffering=0))


class RapidsFileIO:
    """Default local-filesystem implementation of the SPI."""

    def open_input_file(self, path: str) -> RapidsInputFile:
        return RapidsInputFile(path)
