"""Op-layer tracing/fault-injection instrumentation.

The reference wraps every native op in an NVTX range at its definition
(nvtx_ranges.hpp NVTX3_FUNC_RANGE in each .cu entry point) and the
fault-injection tool intercepts at the driver boundary, so EVERY caller
— plugin, tests, tools — is covered.  Round 1 only wrapped the
shim/jni_api.py surface; models/ and direct op calls bypassed the
sidecars.  This module fixes that: `traced` is applied to the op-layer
entry points themselves (via `instrument` from ops/__init__), so any
call path hits the same maybe_inject + op_range bracket.
"""

from __future__ import annotations

import functools
import sys
from typing import Iterable, Optional

from spark_rapids_tpu.utils.fault_injection import maybe_inject
from spark_rapids_tpu.utils.profiler import op_range

_WRAPPED_FLAG = "__srt_traced__"


def traced(fn=None, *, name: Optional[str] = None):
    """Decorator: fault-injection point + profiler/NVTX-style range
    around an eager op entry point.  Idempotent (re-wrapping is a
    no-op).  Do NOT apply to functions called inside jit traces — the
    bracket is a host-side, per-eager-call construct.

    Double-bracket suppression is keyed by FRAME, not by name: the only
    duplicate to suppress is the shim-over-op shape, where jni_api opens
    ``with op_range("x")`` and calls the traced op from that same frame
    — one logical call, two brackets.  A name-keyed guard (the old
    ``active_op_names`` check) also swallowed genuinely recursive calls
    to the same op (e.g. a join entry point composing another join),
    hiding the inner call from injection and the profiler entirely."""

    def deco(f):
        if getattr(f, _WRAPPED_FLAG, False):
            return f
        opname = name or f.__name__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            from spark_rapids_tpu.utils.profiler import bracket_owned_by

            if bracket_owned_by(opname, id(sys._getframe(1))):
                # the CALLER's frame opened an op_range for this very
                # op (the shim bracketing the op it is about to call):
                # same logical call — don't inject or record twice
                return f(*args, **kwargs)
            maybe_inject(opname)
            with op_range(opname):
                return f(*args, **kwargs)

        setattr(wrapper, _WRAPPED_FLAG, True)
        wrapper.__wrapped__ = f
        return wrapper

    return deco(fn) if fn is not None else deco


def instrument(module_name: str, names: Iterable[str]) -> None:
    """Wrap the named functions of an already-imported module in
    `traced`, rebinding them on the module so subsequent imports and
    attribute calls are covered."""
    mod = sys.modules[module_name]
    for n in names:
        f = getattr(mod, n)
        if callable(f):
            setattr(mod, n, traced(f, name=n))
