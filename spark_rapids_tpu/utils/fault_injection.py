"""Fault injection at the shim boundary (reference faultinj/faultinj.cu:
libcufaultinj.so loaded via CUDA_INJECTION64_PATH, JSON config from
FAULT_INJECTOR_CONFIG_PATH with hot reload, matching driver/runtime
callbacks by function name or '*' with probability and repeat counts).

TPU mapping: there is no CUPTI; the interception point is the op shim —
ops (or the Java bindings layer) call `maybe_inject(op_name)` before
dispatch.  Config schema mirrors the reference:

    {"seed": 42,                       # optional deterministic seed
     "faults": [
        {"match": "murmur3_32",        # exact op name or "*"
         "probability": 0.5,           # 0..1 (default 1.0)
         "repeat": 3,                  # max hits, -1 = unlimited
         "exception": "CudfException"} # or "GpuRetryOOM", ...
     ]}

The config file is watched by mtime and hot-reloaded, like the
reference's dynamicReconfig watcher thread (faultinj.cu:88)."""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.memory import exceptions as exc

CONFIG_ENV = "FAULT_INJECTOR_CONFIG_PATH"

_EXCEPTIONS = {
    "CudfException": exc.CudfException,
    "GpuRetryOOM": exc.GpuRetryOOM,
    "GpuSplitAndRetryOOM": exc.GpuSplitAndRetryOOM,
    "CpuRetryOOM": exc.CpuRetryOOM,
    "CpuSplitAndRetryOOM": exc.CpuSplitAndRetryOOM,
    "GpuOOM": exc.GpuOOM,
}


class _Rule:
    def __init__(self, spec: dict):
        self.match = spec.get("match", "*")
        self.probability = float(spec.get("probability", 1.0))
        self.remaining = int(spec.get("repeat", -1))
        self.exception = _EXCEPTIONS.get(spec.get("exception",
                                                  "CudfException"),
                                         exc.CudfException)

    def applies(self, op_name: str) -> bool:
        return self.match == "*" or self.match == op_name


INTERVAL_ENV = "FAULT_INJECTOR_INTERVAL_MS"
DEFAULT_INTERVAL_MS = 200


class FaultInjector:
    def __init__(self, config_path: Optional[str] = None,
                 watch: bool = False,
                 interval_ms: Optional[int] = None):
        """A missing/unreadable/garbled config is TOLERATED (empty rule
        set) — the watcher keeps polling and picks the file up when it
        appears or heals, matching the reference injector's dynamic-
        reconfig behavior.  ``interval_ms`` tunes the watch poll
        (default 200ms, env ``FAULT_INJECTOR_INTERVAL_MS``)."""
        self.config_path = config_path or os.environ.get(CONFIG_ENV)
        if interval_ms is None:
            try:
                env = int(os.environ.get(INTERVAL_ENV, ""))
            except ValueError:
                env = 0      # unset/garbled env: tolerant, like the
            #                  config itself — fall to the default
            interval_ms = env if env > 0 else DEFAULT_INTERVAL_MS
        self.interval_ms = max(int(interval_ms), 1)
        self._rules: List[_Rule] = []
        self._rng = random.Random()
        self._lock = threading.Lock()
        self._mtime = 0.0
        self._watching = False
        if self.config_path:
            self.reload()
            if watch:
                self._watching = True
                threading.Thread(target=self._watch_loop,
                                 daemon=True).start()

    def reload(self) -> bool:
        """Load/refresh the rule set; returns True when a config was
        applied.  A missing or unreadable file clears the rules (and
        returns False) instead of raising — the watcher retries, so a
        config that appears later still takes effect; a file that
        exists but holds bad JSON keeps the CURRENT rules (a partial
        write must not drop live rules)."""
        # stat BEFORE reading: a write landing between read and stat must
        # still trigger another reload on the next watcher poll
        try:
            mtime = os.stat(self.config_path).st_mtime
        except OSError:
            mtime = self._mtime
        try:
            with open(self.config_path) as f:
                spec = json.load(f)
        except OSError:
            with self._lock:
                self._rules = []
                # forget the applied mtime: a config restored with a
                # PRESERVED mtime (mv of a backup) must still reload
                self._mtime = 0.0
            return False
        except (json.JSONDecodeError, ValueError):
            # keep the CURRENT rules and the OLD mtime: a bad read is
            # usually a non-atomic write in flight, and recording its
            # mtime could skip the completed write when it lands in
            # the same mtime granule — re-parse every poll instead
            return False
        try:
            # build OUTSIDE the lock and tolerantly: valid JSON with a
            # garbled rule spec (bad probability, non-dict entry) must
            # keep the current rules, like any other bad write
            rules = [_Rule(r) for r in spec.get("faults", [])]
            seed = spec.get("seed")
        except (TypeError, ValueError, AttributeError, KeyError):
            return False    # garbled rule spec: same contract as a
        #                     bad write — keep rules, keep re-parsing
        with self._lock:
            if seed is not None:
                self._rng = random.Random(seed)
            self._rules = rules
            self._mtime = mtime
        return True

    def _watch_loop(self):
        while self._watching:
            time.sleep(self.interval_ms / 1000.0)
            try:
                m = os.stat(self.config_path).st_mtime
            except OSError:
                # config deleted: drop any live rules ONCE (deleting
                # the file is the operator's off switch, same contract
                # as reload on a missing file); keep polling for it
                with self._lock:
                    had_rules = bool(self._rules)
                if had_rules:
                    self.reload()
                continue
            if m != self._mtime:
                self.reload()   # tolerant: see reload's contract

    def stop(self):
        self._watching = False

    def active_rules(self) -> List[dict]:
        """Snapshot of the live rule set (shim/CLI introspection and
        the chaos harness's hot-reload assertion)."""
        with self._lock:
            return [{"match": r.match, "probability": r.probability,
                     "remaining": r.remaining,
                     "exception": r.exception.__name__}
                    for r in self._rules]

    def maybe_inject(self, op_name: str):
        """Raise the configured exception for this op, honoring
        probability and repeat count."""
        with self._lock:
            for rule in self._rules:
                if not rule.applies(op_name):
                    continue
                if rule.remaining == 0:
                    continue
                if rule.probability < 1.0 and \
                        self._rng.random() >= rule.probability:
                    continue
                if rule.remaining > 0:
                    rule.remaining -= 1
                raise rule.exception(
                    f"injected fault in {op_name}")


_global: Optional[FaultInjector] = None


def install(config_path: Optional[str] = None,
            watch: bool = True,
            interval_ms: Optional[int] = None) -> FaultInjector:
    """Process-global injector (the CUDA_INJECTION64_PATH load analog).
    Replacing an installed injector stops its watcher first."""
    global _global
    if _global is not None:
        _global.stop()
    _global = FaultInjector(config_path, watch=watch,
                            interval_ms=interval_ms)
    return _global


def installed() -> Optional[FaultInjector]:
    return _global


def uninstall():
    global _global
    if _global is not None:
        _global.stop()
    _global = None


def maybe_inject(op_name: str):
    if _global is not None:
        _global.maybe_inject(op_name)
