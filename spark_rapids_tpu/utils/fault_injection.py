"""Fault injection at the shim boundary (reference faultinj/faultinj.cu:
libcufaultinj.so loaded via CUDA_INJECTION64_PATH, JSON config from
FAULT_INJECTOR_CONFIG_PATH with hot reload, matching driver/runtime
callbacks by function name or '*' with probability and repeat counts).

TPU mapping: there is no CUPTI; the interception point is the op shim —
ops (or the Java bindings layer) call `maybe_inject(op_name)` before
dispatch.  Config schema mirrors the reference:

    {"seed": 42,                       # optional deterministic seed
     "faults": [
        {"match": "murmur3_32",        # exact op name or "*"
         "probability": 0.5,           # 0..1 (default 1.0)
         "repeat": 3,                  # max hits, -1 = unlimited
         "exception": "CudfException"} # or "GpuRetryOOM", ...
     ]}

The config file is watched by mtime and hot-reloaded, like the
reference's dynamicReconfig watcher thread (faultinj.cu:88)."""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Dict, List, Optional

from spark_rapids_tpu.memory import exceptions as exc

CONFIG_ENV = "FAULT_INJECTOR_CONFIG_PATH"

_EXCEPTIONS = {
    "CudfException": exc.CudfException,
    "GpuRetryOOM": exc.GpuRetryOOM,
    "GpuSplitAndRetryOOM": exc.GpuSplitAndRetryOOM,
    "CpuRetryOOM": exc.CpuRetryOOM,
    "CpuSplitAndRetryOOM": exc.CpuSplitAndRetryOOM,
    "GpuOOM": exc.GpuOOM,
}


class _Rule:
    def __init__(self, spec: dict):
        self.match = spec.get("match", "*")
        self.probability = float(spec.get("probability", 1.0))
        self.remaining = int(spec.get("repeat", -1))
        self.exception = _EXCEPTIONS.get(spec.get("exception",
                                                  "CudfException"),
                                         exc.CudfException)

    def applies(self, op_name: str) -> bool:
        return self.match == "*" or self.match == op_name


class FaultInjector:
    def __init__(self, config_path: Optional[str] = None,
                 watch: bool = False):
        self.config_path = config_path or os.environ.get(CONFIG_ENV)
        self._rules: List[_Rule] = []
        self._rng = random.Random()
        self._lock = threading.Lock()
        self._mtime = 0.0
        self._watching = False
        if self.config_path:
            self.reload()
            if watch:
                self._watching = True
                threading.Thread(target=self._watch_loop,
                                 daemon=True).start()

    def reload(self):
        # stat BEFORE reading: a write landing between read and stat must
        # still trigger another reload on the next watcher poll
        try:
            mtime = os.stat(self.config_path).st_mtime
        except OSError:
            mtime = self._mtime
        with open(self.config_path) as f:
            spec = json.load(f)
        with self._lock:
            if "seed" in spec:
                self._rng = random.Random(spec["seed"])
            self._rules = [_Rule(r) for r in spec.get("faults", [])]
            self._mtime = mtime

    def _watch_loop(self):
        while self._watching:
            time.sleep(0.2)
            try:
                m = os.stat(self.config_path).st_mtime
            except OSError:
                continue
            if m != self._mtime:
                try:
                    self.reload()
                except (json.JSONDecodeError, OSError):
                    pass  # keep the old config on a bad write

    def stop(self):
        self._watching = False

    def maybe_inject(self, op_name: str):
        """Raise the configured exception for this op, honoring
        probability and repeat count."""
        with self._lock:
            for rule in self._rules:
                if not rule.applies(op_name):
                    continue
                if rule.remaining == 0:
                    continue
                if rule.probability < 1.0 and \
                        self._rng.random() >= rule.probability:
                    continue
                if rule.remaining > 0:
                    rule.remaining -= 1
                raise rule.exception(
                    f"injected fault in {op_name}")


_global: Optional[FaultInjector] = None


def install(config_path: Optional[str] = None,
            watch: bool = True) -> FaultInjector:
    """Process-global injector (the CUDA_INJECTION64_PATH load analog).
    Replacing an installed injector stops its watcher first."""
    global _global
    if _global is not None:
        _global.stop()
    _global = FaultInjector(config_path, watch=watch)
    return _global


def uninstall():
    global _global
    if _global is not None:
        _global.stop()
    _global = None


def maybe_inject(op_name: str):
    if _global is not None:
        _global.maybe_inject(op_name)
