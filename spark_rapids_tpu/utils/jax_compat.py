"""Version-compatibility shims for the jax API surface this library
uses across the jax versions it runs on.

``shard_map`` moved from ``jax.experimental.shard_map`` to a top-level
``jax.shard_map`` export; importing through here works on both sides of
that move (this image ships 0.4.37, where only the experimental path
exists).

``ensure_partitionable_threefry`` pins the partitionable threefry
implementation, which newer jax enables by default and which this
library's generators rely on: with it, drawing N rows then the first
M < N rows from the same seed yields the same prefix (ops/uuid_gen.py's
deterministic-per-seed contract).  Classic threefry pairs counters by
splitting the flat range in half, so the prefix property does not hold
there.
"""

from __future__ import annotations

def ensure_partitionable_threefry() -> None:
    """Make seeded draws shape-prefix-stable on every jax version.

    jax >= 0.4.36 defaults ``jax_threefry_partitionable`` on (and much
    later removes the option entirely, partitionable being the only
    implementation); 0.4.37 in this image still defaults it off."""
    import jax
    try:
        if not jax.config.jax_threefry_partitionable:
            jax.config.update("jax_threefry_partitionable", True)
    except AttributeError:
        pass    # option gone: partitionable is the only implementation


try:                                    # jax >= 0.4.38 top-level export
    from jax import shard_map           # type: ignore[attr-defined]
except ImportError:                     # jax <= 0.4.37
    import functools

    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, *args, check_rep: bool = False, **kwargs):
        # check_rep defaults OFF: 0.4.37's replication checker lacks
        # rules for several collectives these programs use (and the
        # top-level export dropped the argument entirely)
        return _shard_map(f, *args, check_rep=check_rep, **kwargs)
