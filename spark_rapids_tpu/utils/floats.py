"""Float64 handling on a backend with no native f64.

TPUs lower 64-bit floats by demoting to f32 (observed on this backend:
1e300 -> inf under jit) and cannot lower f64<->u64 bitcasts at all.  Spark
DOUBLE semantics need exact IEEE754 bit behavior, so FLOAT64 Columns store
raw bits in uint64 lanes (columns/column.py) and ops choose explicitly:

  * bit-exact paths (hash, comparisons via total-order transform, casts,
    min/max, sort keys) — pure integer ops on the bits; exact everywhere.
  * arithmetic paths (sum/avg/mul) — decode to the best available float
    compute.  On CPU that's true f64; on TPU it's f32 (documented precision
    loss) until a double-double Pallas path lands.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_U64 = jnp.uint64
_I64 = jnp.int64

F64_SIGN = 0x8000000000000000
F64_EXP_MASK = 0x7FF0000000000000
F64_FRAC_MASK = 0x000FFFFFFFFFFFFF
F64_QNAN = 0x7FF8000000000000
F64_INF = 0x7FF0000000000000


def is_nan_bits(bits: jnp.ndarray) -> jnp.ndarray:
    return (bits & _U64(0x7FFFFFFFFFFFFFFF)) > _U64(F64_INF)


def is_inf_bits(bits: jnp.ndarray) -> jnp.ndarray:
    return (bits & _U64(0x7FFFFFFFFFFFFFFF)) == _U64(F64_INF)


def is_neg_bits(bits: jnp.ndarray) -> jnp.ndarray:
    return (bits >> _U64(63)) != _U64(0)


def total_order_key(bits: jnp.ndarray) -> jnp.ndarray:
    """Monotone int64 key: orders like IEEE754 totalOrder (negatives
    reversed).  NaNs sort above +inf (Spark sort semantics for NaN-last is
    layered on top by callers)."""
    b = bits.astype(_U64)
    flipped = jnp.where(is_neg_bits(b),
                        ~b, b | _U64(F64_SIGN))
    return flipped.astype(_I64) + jnp.int64(-2**63)


def bits_to_f64_compute(bits: jnp.ndarray) -> jnp.ndarray:
    """Decode raw bits to a float array for arithmetic.

    On backends with real f64 (CPU) this is an exact bitcast.  On TPU it
    decodes mantissa/exponent arithmetically into whatever f64 lowering the
    backend has (effectively f32 precision) — callers that need exactness
    must use a bit-path instead.
    """
    if jax.default_backend() == "cpu":
        return lax.bitcast_convert_type(bits.astype(_U64), jnp.float64)
    b = bits.astype(_U64)
    sign = jnp.where(is_neg_bits(b), -1.0, 1.0)
    exp = ((b & _U64(F64_EXP_MASK)) >> _U64(52)).astype(jnp.int32)
    frac = (b & _U64(F64_FRAC_MASK)).astype(jnp.float64)
    normal_m = 1.0 + frac * (2.0 ** -52)
    subnormal_m = frac * (2.0 ** -52)
    m = jnp.where(exp == 0, subnormal_m, normal_m)
    e = jnp.where(exp == 0, -1022, exp - 1023)
    val = sign * m * jnp.exp2(e.astype(jnp.float64))
    val = jnp.where(is_inf_bits(b), sign * jnp.inf, val)
    val = jnp.where(is_nan_bits(b), jnp.nan, val)
    return val


def f64_compute_to_bits(x: jnp.ndarray,
                        force_f32_path: bool = False) -> jnp.ndarray:
    """Inverse of bits_to_f64_compute for storing results.  Exact on CPU;
    on TPU routes through the f32-precision encoder."""
    if jax.default_backend() == "cpu" and not force_f32_path:
        return lax.bitcast_convert_type(x.astype(jnp.float64), _U64)
    # Encode via f32: bitcast f32->u32 is supported on TPU.
    f32 = x.astype(jnp.float32)
    u32 = lax.bitcast_convert_type(f32, jnp.uint32).astype(_U64)
    sign = (u32 >> _U64(31)) & _U64(1)
    exp32 = (u32 >> _U64(23)) & _U64(0xFF)
    frac32 = u32 & _U64(0x7FFFFF)
    # remap f32 fields into f64 fields
    is_nan = exp32 == _U64(0xFF)
    is_zero = (u32 & _U64(0x7FFFFFFF)) == _U64(0)
    exp64 = jnp.where(exp32 == _U64(0xFF), _U64(0x7FF),
                      exp32 - _U64(127) + _U64(1023))
    frac64 = frac32 << _U64(29)
    # f32 subnormals (exp32==0, frac!=0) have no implicit leading 1: the
    # value is frac32 * 2^-149, always normalizable in f64.  Normalize by
    # converting the integer frac32 through f32 (exact to 2^23) and reading
    # its exponent/mantissa fields.
    zf = frac32.astype(jnp.float32)
    zu = lax.bitcast_convert_type(zf, jnp.uint32).astype(_U64)
    sub_exp64 = ((zu >> _U64(23)) & _U64(0xFF)) - _U64(127) - _U64(149) \
        + _U64(1023)
    sub_frac64 = (zu & _U64(0x7FFFFF)) << _U64(29)
    is_subnormal = (exp32 == _U64(0)) & (frac32 != _U64(0))
    exp64 = jnp.where(is_subnormal, sub_exp64, exp64)
    frac64 = jnp.where(is_subnormal, sub_frac64, frac64)
    bits = (sign << _U64(63)) | (exp64 << _U64(52)) | frac64
    bits = jnp.where(is_zero, sign << _U64(63), bits)
    bits = jnp.where(is_nan & (frac32 != 0), _U64(F64_QNAN), bits)
    return bits
