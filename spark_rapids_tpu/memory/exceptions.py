"""OOM exception taxonomy (reference: 7 Java classes thrown from native via
class lookup, SparkResourceAdaptorJni.cpp:49-54).  The retry framework above
this library catches these by type."""


class RetryOOMBase(Exception):
    """A rollback-to-spillable-and-retry is requested."""


class SplitAndRetryOOMBase(Exception):
    """A split-input-and-retry is requested."""


class GpuRetryOOM(RetryOOMBase):
    def __init__(self, msg="GPU OutOfMemory"):
        super().__init__(msg)


class GpuSplitAndRetryOOM(SplitAndRetryOOMBase):
    def __init__(self, msg="GPU OutOfMemory"):
        super().__init__(msg)


class CpuRetryOOM(RetryOOMBase):
    def __init__(self, msg="CPU OutOfMemory"):
        super().__init__(msg)


class CpuSplitAndRetryOOM(SplitAndRetryOOMBase):
    def __init__(self, msg="CPU OutOfMemory"):
        super().__init__(msg)


class GpuOOM(Exception):
    """Unrecoverable device OOM (e.g. retry limit exceeded)."""


class OffHeapOOM(Exception):
    """Unrecoverable host (off-heap) OOM."""


class CudfException(Exception):
    """Generic engine exception (reference CudfException) — used by fault
    injection to simulate kernel errors."""


class ThreadRemovedException(RuntimeError):
    """Thread was unregistered while blocked (THREAD_REMOVE_THROW)."""
