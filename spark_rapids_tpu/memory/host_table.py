"""HostTable: move a whole device Table into one contiguous host buffer and
back (reference HostTable.java:46 fromTableAsync / toDeviceColumnViews,
host_table_view.hpp) — the primitive behind host-spill of tables.

Layout: a metadata header (python-side description of the column tree) +
one contiguous bytes buffer holding every device buffer (data, validity,
offsets, children depth-first), each 8-byte aligned — matching the
contiguous-split single-buffer idea the reference builds on.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType
from spark_rapids_tpu.columns.table import Table

_ALIGN = 8


class _BufMeta:
    __slots__ = ("offset", "nbytes", "np_dtype", "shape")

    def __init__(self, offset, nbytes, np_dtype, shape):
        self.offset = offset
        self.nbytes = nbytes
        self.np_dtype = np_dtype
        self.shape = shape


class _ColMeta:
    __slots__ = ("dtype", "length", "data", "validity", "offsets",
                 "children")

    def __init__(self, dtype: DType, length: int, data, validity, offsets,
                 children):
        self.dtype = dtype
        self.length = length
        self.data = data
        self.validity = validity
        self.offsets = offsets
        self.children = children


class HostTable:
    """A spilled Table: metadata + one contiguous host buffer."""

    def __init__(self, buffer: bytes, columns: List[_ColMeta],
                 names: Optional[List[str]]):
        self.buffer = buffer
        self._columns = columns
        self.names = names

    @property
    def size_bytes(self) -> int:
        return len(self.buffer)

    @staticmethod
    def from_table(table: Table) -> "HostTable":
        chunks: List[bytes] = []
        pos = 0

        def put(arr: Optional[jnp.ndarray]) -> Optional[_BufMeta]:
            nonlocal pos
            if arr is None:
                return None
            host = np.asarray(arr)
            raw = host.tobytes()
            meta = _BufMeta(pos, len(raw), host.dtype, host.shape)
            chunks.append(raw)
            pos += len(raw)
            pad = (-pos) % _ALIGN
            if pad:
                chunks.append(b"\0" * pad)
                pos += pad
            return meta

        def walk(c: Column) -> _ColMeta:
            return _ColMeta(c.dtype, c.length, put(c.data), put(c.validity),
                            put(c.offsets),
                            [walk(ch) for ch in c.children])

        cols = [walk(c) for c in table.columns]
        return HostTable(b"".join(chunks), cols, table.names)

    def to_table(self) -> Table:
        buf = self.buffer

        def get(meta: Optional[_BufMeta]) -> Optional[jnp.ndarray]:
            if meta is None:
                return None
            host = np.frombuffer(
                buf, dtype=meta.np_dtype,
                count=int(np.prod(meta.shape)) if meta.shape else 1,
                offset=meta.offset).reshape(meta.shape)
            return jax.device_put(host)

        def rebuild(m: _ColMeta) -> Column:
            return Column(m.dtype, m.length, data=get(m.data),
                          validity=get(m.validity), offsets=get(m.offsets),
                          children=tuple(rebuild(ch) for ch in m.children))

        return Table([rebuild(m) for m in self._columns], self.names)
