"""Memory resources: reservation-tracking allocators for HBM budget control.

JAX/XLA owns physical HBM; what the Spark runtime needs from "RMM" here is
*reservation accounting* — a strict budget that allocations check against so
the OOM state machine can block/retry/split tasks before XLA ever hits a
real OOM (SURVEY.md §7.2: explicit reservation at the shim boundary).  The
resource stack mirrors RMM's composable adaptors: a base resource with a
byte limit, wrapped by the SparkResourceAdaptor state machine.
"""

from __future__ import annotations

import threading
from typing import Optional


class AllocationFailed(MemoryError):
    """Internal signal that a reservation does not fit (rmm::out_of_memory
    equivalent) — callers above the adaptor never see this."""

    def __init__(self, nbytes: int):
        super().__init__(f"allocation of {nbytes} bytes failed")
        self.nbytes = nbytes


class MemoryResource:
    """Abstract reservation resource."""

    def allocate(self, nbytes: int) -> int:
        raise NotImplementedError

    def deallocate(self, nbytes: int) -> None:
        raise NotImplementedError


class LimitingMemoryResource(MemoryResource):
    """Strict byte-budget resource (rmm limiting_resource_adaptor analog)."""

    def __init__(self, limit_bytes: int):
        self.limit = int(limit_bytes)
        self._used = 0
        self._lock = threading.Lock()

    @property
    def used(self) -> int:
        return self._used

    def allocate(self, nbytes: int) -> int:
        if nbytes < 0:
            raise ValueError("negative allocation")
        with self._lock:
            if self._used + nbytes > self.limit:
                raise AllocationFailed(nbytes)
            self._used += nbytes
        return nbytes

    def deallocate(self, nbytes: int) -> None:
        with self._lock:
            self._used -= nbytes
