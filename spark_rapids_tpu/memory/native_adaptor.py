"""ctypes binding for the native C++ SparkResourceAdaptor
(native/spark_resource_adaptor.cpp) — same public surface as the Python
SparkResourceAdaptor so the deterministic RmmSparkTest-style suite runs
differentially against both implementations."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional

from spark_rapids_tpu.memory import exceptions as exc
from spark_rapids_tpu.memory.spark_resource_adaptor import (
    CPU, CPU_OR_GPU, GPU, THREAD_ALLOC, THREAD_ALLOC_FREE, THREAD_BLOCKED,
    THREAD_BUFN, THREAD_BUFN_THROW, THREAD_BUFN_WAIT, THREAD_REMOVE_THROW,
    THREAD_RUNNING, THREAD_SPLIT_THROW, UNKNOWN)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsra_native.so")
_SRC = os.path.join(_NATIVE_DIR, "spark_resource_adaptor.cpp")

_STATE_NAMES = {
    -1: UNKNOWN, 0: THREAD_RUNNING, 1: THREAD_ALLOC, 2: THREAD_ALLOC_FREE,
    3: THREAD_BLOCKED, 4: THREAD_BUFN_THROW, 5: THREAD_BUFN_WAIT,
    6: THREAD_BUFN, 7: THREAD_SPLIT_THROW, 8: THREAD_REMOVE_THROW,
}
_FILTERS = {CPU_OR_GPU: 0, CPU: 1, GPU: 2}

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if os.environ.get("SPARK_RAPIDS_TPU_DISABLE_NATIVE") == "1":
        return None
    with _lock:
        if _tried:
            return _lib
    # Build + bind OUTSIDE the lock (srt-lint SRT006): the g++ compile
    # can run for minutes and a mutex held across it wedges every
    # first-touch caller behind an invisible subprocess.  A rare
    # concurrent first touch compiles twice into pid-unique tmp files;
    # os.replace is atomic and both artifacts are identical, so the
    # first publisher wins and the duplicate work is bounded.
    lib: Optional[ctypes.CDLL] = None
    try:
        if not os.path.exists(_LIB_PATH) or (
                os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)):
            # pid+thread unique: two same-process first-touch
            # threads must not share a tmp inode
            tmp = (f"{_LIB_PATH}.{os.getpid()}"
                   f".{threading.get_ident()}.tmp")
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                 "-pthread", "-o", tmp, _SRC],
                check=True, capture_output=True, timeout=180)
            os.replace(tmp, _LIB_PATH)
        lib = ctypes.CDLL(_LIB_PATH)
        for name, res, args in [
            ("sra_create", ctypes.c_long, [ctypes.c_long]),
            ("sra_destroy", None, [ctypes.c_long]),
            ("sra_start_dedicated_task_thread", ctypes.c_int,
             [ctypes.c_long] * 3),
            ("sra_pool_thread_working_on_tasks", ctypes.c_int,
             [ctypes.c_long, ctypes.c_long, ctypes.c_int,
              ctypes.c_void_p, ctypes.c_long]),
            ("sra_remove_thread_association", ctypes.c_int,
             [ctypes.c_long] * 3),
            ("sra_task_done", ctypes.c_int, [ctypes.c_long] * 2),
            ("sra_alloc", ctypes.c_int, [ctypes.c_long] * 3),
            ("sra_dealloc", ctypes.c_int, [ctypes.c_long] * 3),
            ("sra_cpu_prealloc", ctypes.c_int,
             [ctypes.c_long, ctypes.c_long, ctypes.c_int]),
            ("sra_post_cpu_alloc_success", ctypes.c_int,
             [ctypes.c_long, ctypes.c_long, ctypes.c_long,
              ctypes.c_int]),
            ("sra_post_cpu_alloc_failed", ctypes.c_int,
             [ctypes.c_long, ctypes.c_long, ctypes.c_int,
              ctypes.c_int, ctypes.c_int]),
            ("sra_cpu_dealloc", ctypes.c_int, [ctypes.c_long] * 3),
            ("sra_block_thread_until_ready", ctypes.c_int,
             [ctypes.c_long] * 2),
            ("sra_force_retry_oom", ctypes.c_int,
             [ctypes.c_long, ctypes.c_long, ctypes.c_long,
              ctypes.c_int, ctypes.c_long]),
            ("sra_force_split_and_retry_oom", ctypes.c_int,
             [ctypes.c_long, ctypes.c_long, ctypes.c_long,
              ctypes.c_int, ctypes.c_long]),
            ("sra_force_cudf_exception", ctypes.c_int,
             [ctypes.c_long] * 3),
            ("sra_get_state", ctypes.c_int, [ctypes.c_long] * 2),
            ("sra_used", ctypes.c_long, [ctypes.c_long]),
            ("sra_gpu_allocated", ctypes.c_long, [ctypes.c_long]),
            ("sra_thread_waiting_on_pool", ctypes.c_int,
             [ctypes.c_long, ctypes.c_long, ctypes.c_int]),
            ("sra_check_and_break_deadlocks", ctypes.c_int,
             [ctypes.c_long]),
            ("sra_get_and_reset_metric", ctypes.c_long,
             [ctypes.c_long, ctypes.c_long, ctypes.c_int,
              ctypes.c_int]),
            ("sra_remove_task_metrics", None,
             [ctypes.c_long] * 2),
            ("sra_log_count", ctypes.c_long, [ctypes.c_long]),
            ("sra_log_line", ctypes.c_long,
             [ctypes.c_long, ctypes.c_long, ctypes.c_char_p,
              ctypes.c_long]),
        ]:
            fn = getattr(lib, name)
            fn.restype = res
            fn.argtypes = args
    except (OSError, subprocess.SubprocessError):
        lib = None
    with _lock:
        if not _tried:
            _lib, _tried = lib, True
        return _lib


def available() -> bool:
    return _load() is not None


def _raise_for(status: int, ctx: str = ""):
    if status == 0:
        return
    if status == -1:
        raise exc.GpuRetryOOM()
    if status == -2:
        raise exc.GpuSplitAndRetryOOM()
    if status == -3:
        raise exc.CudfException("injected CudfException")
    if status == -4:
        raise exc.GpuOOM("GPU OutOfMemory")
    if status == -5:
        raise exc.ThreadRemovedException("thread removed while blocked")
    if status == -7:
        raise exc.CpuRetryOOM()   # injected OR real CPU backpressure
    if status == -8:
        raise exc.CpuSplitAndRetryOOM()
    if status == -6:
        # same exception type as the Python adaptor's invalid-state path
        raise RuntimeError(f"Internal error: invalid adaptor state {ctx}")
    raise ValueError(f"native adaptor error {status} {ctx}")


class _ResourceView:
    def __init__(self, adaptor: "NativeSparkResourceAdaptor"):
        self._a = adaptor

    @property
    def used(self) -> int:
        return self._a._lib.sra_used(self._a._h)


class NativeSparkResourceAdaptor:
    """Drop-in for SparkResourceAdaptor backed by the C++ library."""

    def __init__(self, limit_bytes: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native adaptor library unavailable")
        self._lib = lib
        self._h = lib.sra_create(limit_bytes)
        self.resource = _ResourceView(self)

    # lifecycle -----------------------------------------------------

    def shutdown(self):
        if self._h:
            self._lib.sra_destroy(self._h)
            self._h = 0

    # registration --------------------------------------------------

    def start_dedicated_task_thread(self, thread_id: int, task_id: int):
        _raise_for(self._lib.sra_start_dedicated_task_thread(
            self._h, thread_id, task_id))

    def pool_thread_working_on_tasks(self, is_for_shuffle: bool,
                                     thread_id: int, task_ids):
        ids = list(task_ids)
        arr = (ctypes.c_long * len(ids))(*ids)
        _raise_for(self._lib.sra_pool_thread_working_on_tasks(
            self._h, thread_id, 1 if is_for_shuffle else 0,
            ctypes.cast(arr, ctypes.c_void_p), len(ids)))

    def remove_thread_association(self, thread_id: int, task_id: int = -1):
        _raise_for(self._lib.sra_remove_thread_association(
            self._h, thread_id, task_id))

    def task_done(self, task_id: int):
        _raise_for(self._lib.sra_task_done(self._h, task_id))

    # injection -----------------------------------------------------

    def force_retry_oom(self, thread_id: int, num_ooms: int,
                        oom_filter: str = GPU, skip_count: int = 0):
        _raise_for(self._lib.sra_force_retry_oom(
            self._h, thread_id, num_ooms, _FILTERS[oom_filter],
            skip_count), "force_retry_oom")

    def force_split_and_retry_oom(self, thread_id: int, num_ooms: int,
                                  oom_filter: str = GPU,
                                  skip_count: int = 0):
        _raise_for(self._lib.sra_force_split_and_retry_oom(
            self._h, thread_id, num_ooms, _FILTERS[oom_filter],
            skip_count), "force_split_and_retry_oom")

    def force_cudf_exception(self, thread_id: int, num_times: int):
        _raise_for(self._lib.sra_force_cudf_exception(
            self._h, thread_id, num_times), "force_cudf_exception")

    # queries -------------------------------------------------------

    def get_state_of(self, thread_id: int) -> str:
        return _STATE_NAMES.get(
            self._lib.sra_get_state(self._h, thread_id), UNKNOWN)

    @property
    def gpu_memory_allocated_bytes(self) -> int:
        return self._lib.sra_gpu_allocated(self._h)

    # alloc ---------------------------------------------------------

    def allocate(self, num_bytes: int) -> int:
        tid = threading.get_ident()
        _raise_for(self._lib.sra_alloc(self._h, tid, num_bytes))
        return num_bytes

    def deallocate(self, num_bytes: int):
        tid = threading.get_ident()
        _raise_for(self._lib.sra_dealloc(self._h, tid, num_bytes))

    def cpu_prealloc(self, num_bytes: int, blocking: bool) -> bool:
        """Host-alloc bracket (RmmSpark.preCpuAlloc :790): returns
        was_recursive."""
        tid = threading.get_ident()
        rc = self._lib.sra_cpu_prealloc(self._h, tid, int(blocking))
        _raise_for(rc if rc < 0 else 0)
        return rc == 1

    def post_cpu_alloc_success(self, num_bytes: int, blocking: bool,
                               was_recursive: bool):
        tid = threading.get_ident()
        _raise_for(self._lib.sra_post_cpu_alloc_success(
            self._h, tid, num_bytes, int(was_recursive)))

    def post_cpu_alloc_failed(self, was_oom: bool, blocking: bool,
                              was_recursive: bool) -> bool:
        tid = threading.get_ident()
        rc = self._lib.sra_post_cpu_alloc_failed(
            self._h, tid, int(was_oom), int(blocking),
            int(was_recursive))
        _raise_for(rc if rc < 0 else 0)
        return rc == 1

    def cpu_deallocate(self, num_bytes: int):
        tid = threading.get_ident()
        _raise_for(self._lib.sra_cpu_dealloc(self._h, tid, num_bytes))

    def block_thread_until_ready(self, thread_id: Optional[int] = None):
        if thread_id is None:
            thread_id = threading.get_ident()
        _raise_for(self._lib.sra_block_thread_until_ready(
            self._h, thread_id))

    def thread_waiting_on_pool(self, thread_id: Optional[int] = None):
        if thread_id is None:
            thread_id = threading.get_ident()
        _raise_for(self._lib.sra_thread_waiting_on_pool(
            self._h, thread_id, 1))

    def thread_done_waiting_on_pool(self,
                                    thread_id: Optional[int] = None):
        if thread_id is None:
            thread_id = threading.get_ident()
        _raise_for(self._lib.sra_thread_waiting_on_pool(
            self._h, thread_id, 0))

    def check_and_break_deadlocks(self):
        _raise_for(self._lib.sra_check_and_break_deadlocks(self._h))

    # metrics -------------------------------------------------------

    def _metric(self, task_id: int, kind: int, reset: bool = True) -> int:
        return self._lib.sra_get_and_reset_metric(
            self._h, task_id, kind, 1 if reset else 0)

    def get_and_reset_num_retry_throw(self, task_id: int) -> int:
        return self._metric(task_id, 0)

    def get_and_reset_num_split_retry_throw(self, task_id: int) -> int:
        return self._metric(task_id, 1)

    def get_and_reset_block_time(self, task_id: int) -> int:
        return self._metric(task_id, 2)

    def get_and_reset_compute_time_lost_to_retry(self,
                                                 task_id: int) -> int:
        return self._metric(task_id, 3)

    def get_and_reset_gpu_max_memory_allocated(self, task_id: int) -> int:
        return self._metric(task_id, 4)

    def get_max_gpu_task_memory(self, task_id: int) -> int:
        return self._metric(task_id, 5, reset=False)

    def remove_task_metrics(self, task_id: int):
        self._lib.sra_remove_task_metrics(self._h, task_id)

    # log -----------------------------------------------------------

    def get_log(self) -> List[str]:
        n = self._lib.sra_log_count(self._h)
        buf = ctypes.create_string_buffer(256)
        out = ["time,op,current thread,op thread,op task,from state,"
               "to state,notes"]
        for i in range(n):
            self._lib.sra_log_line(self._h, i, buf, 256)
            parts = buf.value.decode().split(",")
            if parts and parts[0] == "TRANSITION" and len(parts) >= 5:
                frm = _STATE_NAMES.get(int(parts[3]), UNKNOWN)
                to = _STATE_NAMES.get(int(parts[4]), UNKNOWN)
                rest = parts[5] if len(parts) > 5 else ""
                out.append(f"0,TRANSITION,{parts[1]},{parts[1]},"
                           f"{parts[2]},{frm},{to},{rest}")
            else:
                out.append(buf.value.decode())
        return out
