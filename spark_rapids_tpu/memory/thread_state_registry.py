"""ThreadStateRegistry: the JVM-side thread map the native OOM machine
calls back into (reference ThreadStateRegistry.java:44-53 +
SparkResourceAdaptorJni.cpp:55-80 — native looks up/removes JVM threads
by native id when associations end).

Here the adaptor (memory/spark_resource_adaptor.py) plays "native" and
this registry plays the JVM side: RmmSpark registration adds threads,
and the adaptor's remove-association path invokes the registered
callback so the registry drops its entry — the same
native-calls-back-into-managed shape, exercised end-to-end through the
JNI binding's RmmSpark surface."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class ThreadStateRegistry:
    def __init__(self):
        self._threads: Dict[int, Optional[object]] = {}
        self._lock = threading.Lock()

    def add_thread(self, native_id: int,
                   thread: Optional[object] = None) -> None:
        """ThreadStateRegistry.addThread:44."""
        with self._lock:
            self._threads[native_id] = thread

    def remove_thread(self, native_id: int) -> None:
        """Called by the adaptor when a thread's task association ends
        (SparkResourceAdaptorJni.cpp:66-80 removeThread callback)."""
        with self._lock:
            self._threads.pop(native_id, None)

    def known_threads(self) -> List[int]:
        with self._lock:
            return sorted(self._threads)

    def blocked_thread_ids(self, adaptor) -> List[int]:
        """ThreadStateRegistry.blockedThreadIds:53 — registered threads
        currently blocked in the state machine."""
        out = []
        with self._lock:
            ids = list(self._threads)
        for tid in ids:
            try:
                state = adaptor.get_state_of(tid)
            except Exception:
                continue
            if "BLOCKED" in state or "BUFN" in state:
                out.append(tid)
        return sorted(out)


REGISTRY = ThreadStateRegistry()
