"""RmmSpark facade — process-global installation of the SparkResourceAdaptor
(reference RmmSpark.java:85-111 setEventHandler / setCurrentThreadAsTask
surface, adapted to Python naming).  All module functions operate on the
installed adaptor; `current_thread_id()` mirrors RmmSpark.getCurrentThreadId.
"""

from __future__ import annotations

import threading
from typing import Optional

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.memory.resource import LimitingMemoryResource
from spark_rapids_tpu.memory.spark_resource_adaptor import (
    GPU, CPU, CPU_OR_GPU, SparkResourceAdaptor)

_adaptor: Optional[SparkResourceAdaptor] = None
_install_lock = threading.Lock()


def set_event_handler(limit_bytes: int,
                      log_path: Optional[str] = None) -> SparkResourceAdaptor:
    """Install the adaptor over a fresh limiting resource (RmmSpark
    setEventHandler equivalent)."""
    global _adaptor
    with _install_lock:
        if _adaptor is not None:
            raise RuntimeError("event handler already installed")
        _adaptor = SparkResourceAdaptor(LimitingMemoryResource(limit_bytes),
                                        log_path=log_path)
        # native-side adaptor -> managed-side thread registry callback
        # (reference SparkResourceAdaptorJni.cpp:66-80 removeThread);
        # the observability task table unbinds on the same signal so the
        # two thread->task maps cannot drift
        from spark_rapids_tpu.memory.thread_state_registry import \
            REGISTRY as _TSR

        def _on_removed(thread_id: int):
            _TSR.remove_thread(thread_id)
            _obs.TASKS.unbind_thread(thread_id)

        _adaptor.on_thread_removed = _on_removed
        return _adaptor


def clear_event_handler():
    global _adaptor
    with _install_lock:
        if _adaptor is not None:
            _adaptor.shutdown()
        _adaptor = None


def get_adaptor() -> SparkResourceAdaptor:
    if _adaptor is None:
        raise RuntimeError("RmmSpark event handler is not installed")
    return _adaptor


def installed_adaptor() -> Optional[SparkResourceAdaptor]:
    """The installed adaptor or None — the retry drivers
    (robustness/retry.py) poll this on every attempt and must stay
    cheap and exception-free when no memory runtime exists."""
    return _adaptor


def current_thread_id() -> int:
    return threading.get_ident()


# thin delegating wrappers (RmmSpark.java public surface)

def start_dedicated_task_thread(thread_id: int, task_id: int):
    from spark_rapids_tpu.memory.thread_state_registry import REGISTRY
    # register BEFORE the adaptor start so a concurrent task_done's
    # remove_thread callback can never race a not-yet-added id into a
    # permanently stale entry; roll back on a failed start so it does
    # not leave one either (ADVICE r4)
    adaptor = get_adaptor()
    REGISTRY.add_thread(thread_id)
    try:
        adaptor.start_dedicated_task_thread(thread_id, task_id)
    except BaseException:
        REGISTRY.remove_thread(thread_id)
        raise
    _obs.TASKS.bind_thread(thread_id, (task_id,))


def current_thread_is_dedicated_to_task(task_id: int):
    # same validate-then-register contract as start_dedicated_task_thread
    start_dedicated_task_thread(current_thread_id(), task_id)


def shuffle_thread_working_on_tasks(task_ids):
    pool_thread_working_on_tasks(True, current_thread_id(), task_ids)


def pool_thread_working_on_tasks(is_for_shuffle: bool, thread_id: int,
                                 task_ids):
    get_adaptor().pool_thread_working_on_tasks(is_for_shuffle, thread_id,
                                               task_ids)
    _obs.TASKS.bind_thread(thread_id, task_ids)


def pool_thread_finished_for_tasks(thread_id: int, task_ids):
    get_adaptor().pool_thread_finished_for_tasks(thread_id, task_ids)
    _obs.TASKS.unbind_thread(thread_id, task_ids)


def remove_current_thread_association():
    get_adaptor().remove_thread_association(current_thread_id(), -1)
    _obs.TASKS.unbind_thread(current_thread_id())


def task_done(task_id: int):
    adaptor = get_adaptor()
    ret = adaptor.task_done(task_id)
    if _obs.is_enabled():
        # pull the state machine's per-task counters (the
        # getAndResetNumRetryThrow / getTotalBlockedOrLostTime analogs)
        # into the observability rollup, then release the bookkeeping
        _obs.TASKS.fold_rmm_task(
            task_id,
            retry_oom=adaptor.get_and_reset_num_retry_throw(task_id),
            split_retry_oom=adaptor.get_and_reset_num_split_retry_throw(
                task_id),
            blocked_time_ns=adaptor.get_and_reset_block_time(task_id),
            lost_time_ns=adaptor.get_and_reset_compute_time_lost_to_retry(
                task_id),
            max_device_memory=adaptor.get_and_reset_gpu_max_memory_allocated(
                task_id))
        adaptor.remove_task_metrics(task_id)
        _obs.JOURNAL.emit("task_done", task=task_id)
    return ret


def force_release_task(task_id: int) -> dict:
    """Lifeguard entry: forcibly unwind a hung task's associations
    (``SparkResourceAdaptor.force_release_task``) and fold its
    counters into the observability rollup like a normal
    ``task_done`` would."""
    adaptor = get_adaptor()
    info = adaptor.force_release_task(task_id)
    if _obs.is_enabled():
        _obs.TASKS.fold_rmm_task(
            task_id,
            retry_oom=adaptor.get_and_reset_num_retry_throw(task_id),
            split_retry_oom=adaptor.get_and_reset_num_split_retry_throw(
                task_id),
            blocked_time_ns=adaptor.get_and_reset_block_time(task_id),
            lost_time_ns=adaptor.get_and_reset_compute_time_lost_to_retry(
                task_id),
            max_device_memory=adaptor.get_and_reset_gpu_max_memory_allocated(
                task_id))
        adaptor.remove_task_metrics(task_id)
        _obs.JOURNAL.emit("task_force_released", task=task_id,
                          threads=info.get("threads", []),
                          held_bytes=info.get("held_bytes", 0))
    return info


def force_retry_oom(thread_id: int, num_ooms: int = 1,
                    oom_filter: str = GPU, skip_count: int = 0):
    get_adaptor().force_retry_oom(thread_id, num_ooms, oom_filter,
                                  skip_count)


def force_split_and_retry_oom(thread_id: int, num_ooms: int = 1,
                              oom_filter: str = GPU, skip_count: int = 0):
    get_adaptor().force_split_and_retry_oom(thread_id, num_ooms, oom_filter,
                                            skip_count)


def force_cudf_exception(thread_id: int, num_times: int = 1):
    get_adaptor().force_cudf_exception(thread_id, num_times)


def block_thread_until_ready():
    get_adaptor().block_thread_until_ready(current_thread_id())


def get_state_of(thread_id: int) -> str:
    return get_adaptor().get_state_of(thread_id)
