"""Memory runtime: HBM reservation resources, the RmmSpark OOM retry/split
state machine, and host-spill table movement (reference SURVEY.md §2.1)."""

from spark_rapids_tpu.memory.exceptions import (  # noqa: F401
    GpuRetryOOM, GpuSplitAndRetryOOM, CpuRetryOOM, CpuSplitAndRetryOOM,
    GpuOOM, OffHeapOOM, CudfException, ThreadRemovedException)
from spark_rapids_tpu.memory.resource import (  # noqa: F401
    MemoryResource, LimitingMemoryResource, AllocationFailed)
from spark_rapids_tpu.memory.spark_resource_adaptor import (  # noqa: F401
    SparkResourceAdaptor)
from spark_rapids_tpu.memory.host_table import HostTable  # noqa: F401
