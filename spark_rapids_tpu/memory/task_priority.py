"""Global task-attempt priority registry (reference TaskPriority.java:33
and TaskPriorityJni.cpp:25-60): earlier-registered attempts get higher
priority, the special attempt id -1 always gets the maximum, and
`task_done` releases an attempt's entry.  Used by the shuffle path to
order task work; the OOM deadlock breaker derives its own priority from
(task, thread) ids independently (spark_resource_adaptor.py).

Re-registration semantics (load-bearing for the query server's
load-shedding path, server/server.py): priorities are handed out from
a strictly DECREASING counter and an attempt's value is forgotten at
``task_done`` — so an attempt id that is re-registered after its
``task_done`` receives a *newer, strictly lower* priority than it held
before, and lower than every attempt that registered in between.  That
is intentional: "done then back again" means the attempt lost its
place in line (the server demotes an OOM-shed query exactly this way).
Callers that need a stable priority across retries must simply NOT
call ``task_done`` between attempts — the first ``get_task_priority``
pins the value until release.

``stats()`` exposes the registry's live view (entry count, next value
to be issued, cumulative register/release counts) — the query server's
``stats`` endpoint carries it as fair-share evidence.
"""

from __future__ import annotations

import threading

_MAX_LONG = (1 << 63) - 1


class TaskPriorityRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = _MAX_LONG - 1
        self._priorities: dict = {}
        self._registered_total = 0
        self._released_total = 0

    def get_task_priority(self, attempt_id: int) -> int:
        if attempt_id == -1:
            return _MAX_LONG  # special case: always highest
        with self._lock:
            if attempt_id in self._priorities:
                return self._priorities[attempt_id]
            priority = self._next
            self._next -= 1
            self._registered_total += 1
            self._priorities[attempt_id] = priority
            return priority

    def task_done(self, attempt_id: int) -> None:
        if attempt_id == -1:
            return
        with self._lock:
            if self._priorities.pop(attempt_id, None) is not None:
                self._released_total += 1

    def stats(self) -> dict:
        """Snapshot for the server ``stats`` endpoint: live entries
        (with their priorities, lowest first = most recently
        registered first), the next value to be issued, and the
        cumulative churn counters."""
        with self._lock:
            live = dict(self._priorities)
            return {
                "live_entries": len(live),
                "next_value": self._next,
                "registered_total": self._registered_total,
                "released_total": self._released_total,
                # bounded: the newest 64 attempts (lowest priorities)
                # — enough for fair-share evidence without letting a
                # leaky caller bloat every stats pull
                "live": {str(a): p for a, p in
                         sorted(live.items(),
                                key=lambda kv: kv[1])[:64]},
            }


_global = TaskPriorityRegistry()


def get_task_priority(attempt_id: int) -> int:
    return _global.get_task_priority(attempt_id)


def task_done(attempt_id: int) -> None:
    _global.task_done(attempt_id)


def stats() -> dict:
    return _global.stats()
