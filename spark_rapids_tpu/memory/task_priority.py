"""Global task-attempt priority registry (reference TaskPriority.java:33
and TaskPriorityJni.cpp:25-60): earlier-registered attempts get higher
priority, the special attempt id -1 always gets the maximum, and
`task_done` releases an attempt's entry.  Used by the shuffle path to
order task work; the OOM deadlock breaker derives its own priority from
(task, thread) ids independently (spark_resource_adaptor.py)."""

from __future__ import annotations

import threading

_MAX_LONG = (1 << 63) - 1


class TaskPriorityRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = _MAX_LONG - 1
        self._priorities: dict = {}

    def get_task_priority(self, attempt_id: int) -> int:
        if attempt_id == -1:
            return _MAX_LONG  # special case: always highest
        with self._lock:
            if attempt_id in self._priorities:
                return self._priorities[attempt_id]
            priority = self._next
            self._next -= 1
            self._priorities[attempt_id] = priority
            return priority

    def task_done(self, attempt_id: int) -> None:
        if attempt_id == -1:
            return
        with self._lock:
            self._priorities.pop(attempt_id, None)


_global = TaskPriorityRegistry()


def get_task_priority(attempt_id: int) -> int:
    return _global.get_task_priority(attempt_id)


def task_done(attempt_id: int) -> None:
    _global.task_done(attempt_id)
