"""Tiered spill store (ISSUE 18 tentpole): run THROUGH memory
pressure instead of around it.

The OOM state machine (memory/spark_resource_adaptor.py) can today
only roll a blocked thread back (BUFN -> GpuRetryOOM) or make it
split its input toward a one-element floor.  The reference's L3b
design pairs that machinery with a spill framework: device buffers
registered as SPILLABLE move down a tier ladder under pressure and
stream back on demand, so an over-memory join completes out-of-core
instead of shedding.  This module is that framework:

  device tier   the registered column batch, resident; its bytes are
                reserved through the installed SparkResourceAdaptor
  host tier     the batch serialized as ONE kudo table (KTRX trace
                context + a FORCED KCRC trailer — spilled bytes are
                corruption-checked and trace-carrying on read-back)
                held in host memory, device reservation released
  disk tier     the same kudo bytes in a file under
                ``SPARK_RAPIDS_TPU_SPILL_DIR``, demoted when host
                bytes exceed ``SPARK_RAPIDS_TPU_SPILL_HOST_LIMIT_BYTES``

Victim selection is driven by the PR-5 memory ledger: candidates are
ranked (lowest task priority first, largest resident-task bytes
first, largest handle first) — the same ordering the adaptor's
deadlock breaker uses to pick who rolls back, so the store spills
exactly the data whose owner would otherwise be BUFN'd.

``ensure_headroom(bytes)`` is the synchronous hook the state machine
calls BEFORE escalating a blocked thread to BUFN/retry-split (see
SparkResourceAdaptor.allocate / _check_and_update_for_bufn).  All
device-side releases/re-acquisitions run inside
``spill_range_start/done`` so the adaptor's existing recursive-
allocation path recognizes them as spill-side work and keeps task
footprints honest.

A corrupt spill file (CRC mismatch on read-back) surfaces *file path
+ spill generation* in :class:`KudoCorruptException` and — when the
handle registered a ``recompute`` callback — triggers recompute-from-
source instead of query failure, counted ``srt_spill_corrupt_total``.
"""

from __future__ import annotations

import io
import os
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from spark_rapids_tpu import observability as _obs

TIER_DEVICE = "device"
TIER_HOST = "host"
TIER_DISK = "disk"
TIER_FREED = "freed"

_MAX_PRIORITY = 2**63 - 1


def task_priority(task_id: Optional[int]) -> int:
    """The adaptor's thread-priority formula (larger = higher
    priority = spilled LAST): pool/shuffle data (no task) outranks
    every task; among tasks, lower task ids are older and keep their
    memory longer."""
    if task_id is None:
        return _MAX_PRIORITY
    return _MAX_PRIORITY - (int(task_id) + 1)


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name, "")
    if not v:
        return None
    try:
        return int(v)
    except ValueError:
        return None


# the disabled path (no budget configured) sits on every out-of-core
# entry and is gated <1us by scripts/spill_smoke.py: on CPython/posix
# read the env through its raw backing dict (~0.07us vs ~1us for
# os.environ.get's per-call key encode) — it IS os.environ's store,
# so putenv/delenv stay visible — and cache the int parse on the raw
# value.  ``_data`` is a CPython implementation detail (bytes-keyed on
# posix), so any other interpreter takes the portable os.environ.get
# path.
_BUDGET_KEY = b"SPARK_RAPIDS_TPU_DEVICE_BUDGET_BYTES"
_ENV_DATA = (getattr(os.environ, "_data", None)
             if os.name == "posix"
             and sys.implementation.name == "cpython"
             else None)
if not isinstance(_ENV_DATA, dict):
    _ENV_DATA = None
_budget_parse: tuple = (None, None)       # (raw bytes, parsed int)


def device_budget_bytes() -> Optional[int]:
    """``SPARK_RAPIDS_TPU_DEVICE_BUDGET_BYTES`` — the build-side
    budget past which ops/out_of_core partitions and spills (None =
    unlimited, the disabled path).  Dynamic read, one dict hit."""
    global _budget_parse
    if _ENV_DATA is not None:
        raw = _ENV_DATA.get(_BUDGET_KEY)
    else:
        s = os.environ.get("SPARK_RAPIDS_TPU_DEVICE_BUDGET_BYTES")
        raw = s.encode() if s is not None else None
    if not raw:
        return None
    cached_raw, parsed = _budget_parse
    if raw != cached_raw:
        try:
            parsed = int(raw)
        except ValueError:
            parsed = None
        _budget_parse = (raw, parsed)
    return parsed


def columns_nbytes(columns: Sequence) -> int:
    """Conservative byte estimate for a column batch (data + validity
    + offsets + children), used as the handle's device reservation
    size when the caller doesn't pass one."""
    import numpy as np
    total = 0
    for c in columns:
        for buf in (getattr(c, "data", None), getattr(c, "validity", None),
                    getattr(c, "offsets", None)):
            if buf is not None:
                total += int(np.asarray(buf).nbytes)
        total += columns_nbytes(getattr(c, "children", ()))
    return total


class SpillHandle:
    """One registered spillable column batch.  State transitions are
    owned by the store; callers hold the handle and use :meth:`get`
    (restore-on-demand) and :meth:`close`."""

    __slots__ = ("store", "handle_id", "name", "task_id", "stage",
                 "device_bytes", "columns", "fields", "payload", "path",
                 "tier", "generation", "closed", "busy", "pins",
                 "recompute", "_priority", "spill_seq", "disk_nbytes")

    def __init__(self, store: "SpillStore", handle_id: int, name: str,
                 columns, device_bytes: int, task_id: Optional[int],
                 stage: str, priority: Optional[int],
                 recompute: Optional[Callable[[], Sequence]]):
        self.store = store
        self.handle_id = handle_id
        self.name = name
        self.task_id = task_id
        self.stage = stage
        self.device_bytes = int(device_bytes)
        self.columns = list(columns)
        self.fields = None          # captured at first spill
        self.payload: Optional[bytes] = None
        self.path: Optional[str] = None
        self.tier = TIER_DEVICE
        self.generation = 0         # bumps on every device->host spill
        self.closed = False
        self.busy = False           # a restore/demotion is in flight
        self.pins = 0               # callers computing on the columns
        self.recompute = recompute
        self._priority = priority
        self.spill_seq = 0          # FIFO order for host->disk demotion
        self.disk_nbytes = 0        # bytes on disk (accounting, locked)

    @property
    def priority(self) -> int:
        return (self._priority if self._priority is not None
                else task_priority(self.task_id))

    def get(self):
        """The batch's columns, restoring from host/disk when spilled.
        Synchronous; the restore-side device reservation runs inside a
        spill range so the OOM machinery sees it as spill-path work.

        NOTE: the returned columns are NOT protected from a concurrent
        ``ensure_headroom`` — the handle stays victim-eligible and its
        device reservation may be released while the caller computes.
        Callers that hold the columns across further allocations must
        use :meth:`pin` instead."""
        return self.store._materialize(self)

    def pin(self) -> "_Pin":
        """Context manager: materialize AND pin.  While entered, the
        handle is excluded from victim selection (``ensure_headroom``
        will not spill it), so its device reservation is guaranteed to
        cover the returned columns for the caller's whole compute:

            with handle.pin() as cols:
                ...  # cols stay resident here
        """
        return _Pin(self)

    def spill(self) -> int:
        """Force this handle down one tier (device->host, host->disk);
        returns device bytes freed (0 if it wasn't resident)."""
        return self.store._spill_handle(self)

    def close(self) -> None:
        self.store._close_handle(self)


class _Pin:
    """Materialize-and-pin guard (see :meth:`SpillHandle.pin`): the
    pin count is taken under the store lock at restore commit, so from
    the moment ``__enter__`` returns until ``__exit__`` the handle is
    invisible to ``_victims``/``spillable_bytes`` and its reservation
    stays backing the returned columns."""

    __slots__ = ("handle",)

    def __init__(self, handle: SpillHandle):
        self.handle = handle

    def __enter__(self):
        return self.handle.store._materialize(self.handle, pin=True)

    def __exit__(self, *exc) -> None:
        self.handle.store._unpin(self.handle)


class SpillStore:
    """Registry of spillable handles + the tier ladder + the
    ``ensure_headroom`` hook.  Thread-safe; blocking or slow calls —
    restore's device re-acquisition, spill's kudo serialization, the
    adaptor-side release — run OUTSIDE the store lock, so a blocked
    restore can never wedge a concurrent spill and an adaptor-lock
    holder probing ``spillable_bytes()`` never waits on store I/O
    (the lock-order discipline that prevents an ABBA deadlock with
    ``SparkResourceAdaptor._check_and_update_for_bufn``)."""

    def __init__(self, *, spill_dir: Optional[str] = None,
                 host_limit_bytes: Optional[int] = None):
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._handles: Dict[int, SpillHandle] = {}
        self._next_id = 1
        self._spill_seq = 1
        self._dir = spill_dir
        self._host_limit = host_limit_bytes
        self._host_bytes = 0
        self._disk_bytes = 0
        self.spill_count = {TIER_HOST: 0, TIER_DISK: 0}
        self.restore_count = 0
        self.corrupt_count = 0
        self.recompute_count = 0

    # ------------------------------------------------------------- config

    def spill_dir(self) -> str:
        d = self._dir or os.environ.get("SPARK_RAPIDS_TPU_SPILL_DIR", "")
        if not d:
            d = os.path.join(tempfile.gettempdir(),
                             f"srt_spill_{os.getpid()}")
        os.makedirs(d, exist_ok=True)
        return d

    def host_limit_bytes(self) -> Optional[int]:
        if self._host_limit is not None:
            return self._host_limit
        return _env_int("SPARK_RAPIDS_TPU_SPILL_HOST_LIMIT_BYTES")

    # ----------------------------------------------------------- registry

    def register(self, columns, *, device_bytes: Optional[int] = None,
                 name: str = "", task_id: Optional[int] = None,
                 stage: str = "", priority: Optional[int] = None,
                 recompute: Optional[Callable[[], Sequence]] = None
                 ) -> SpillHandle:
        """Register a resident device column batch as spillable.  The
        caller already holds the device reservation; the store releases
        it on spill and re-acquires it on restore (both through the
        installed adaptor, inside a spill range)."""
        nbytes = (int(device_bytes) if device_bytes is not None
                  else columns_nbytes(columns))
        with self._lock:
            hid = self._next_id
            self._next_id += 1
            h = SpillHandle(self, hid, name or f"spill-{hid}", columns,
                            nbytes, task_id, stage, priority, recompute)
            self._handles[hid] = h
            return h

    def _close_handle(self, h: SpillHandle) -> None:
        with self._lock:
            if h.closed:
                return
            h.closed = True
            self._handles.pop(h.handle_id, None)
            if h.busy:
                # an in-flight restore/demotion owns the payload and
                # file right now; it observes ``closed`` at commit and
                # performs this cleanup itself (nothing leaks, and the
                # racing reader still gets its columns)
                return
            h.columns = None
            if h.payload is not None:
                self._host_bytes -= len(h.payload)
                h.payload = None
            path, h.path = h.path, None
            self._disk_bytes -= h.disk_nbytes   # accounting under the
            h.disk_nbytes = 0                   # lock; unlink outside
            h.tier = TIER_FREED
        if path:
            try:
                os.unlink(path)
            except OSError:
                pass

    def close(self) -> None:
        """Drop every handle and its spill files."""
        with self._lock:
            handles = list(self._handles.values())
        for h in handles:
            self._close_handle(h)

    # -------------------------------------------------------- adaptor glue

    def _adaptor(self):
        from spark_rapids_tpu.memory import rmm_spark
        return rmm_spark.installed_adaptor()

    def _release_device(self, nbytes: int) -> None:
        ad = self._adaptor()
        if ad is None or nbytes <= 0:
            return
        ad.spill_range_start()
        try:
            ad.deallocate(nbytes)
        finally:
            ad.spill_range_done()

    def _acquire_device(self, nbytes: int) -> None:
        ad = self._adaptor()
        if ad is None or nbytes <= 0:
            return
        ad.spill_range_start()
        try:
            ad.allocate(nbytes)
        finally:
            ad.spill_range_done()

    # ------------------------------------------------------------ spilling

    def _unpin(self, h: SpillHandle) -> None:
        with self._lock:
            if h.pins > 0:
                h.pins -= 1

    def spillable_bytes(self) -> int:
        """Device bytes the store could free right now — the OOM state
        machine's pre-BUFN probe.  Lock-cheap: no I/O or adaptor calls
        happen under the store lock, so this is safe to call while
        holding the adaptor lock."""
        with self._lock:
            return sum(h.device_bytes for h in self._handles.values()
                       if h.tier == TIER_DEVICE and not h.busy
                       and h.pins == 0)

    def _victims(self) -> List[SpillHandle]:
        """Device-tier handles in spill order: lowest task priority
        first, then largest resident-task bytes (the PR-5 ledger),
        then largest handle.  Pinned handles (a caller is computing on
        their columns) are not candidates."""
        resident: Dict[Optional[int], int] = {}
        ad = self._adaptor()
        if ad is not None:
            try:
                for tid, row in (ad.memory_ledger(timeline=0)
                                 .get("tasks") or {}).items():
                    resident[int(tid)] = int(row.get("active_bytes", 0))
            except Exception:
                resident = {}
        with self._lock:
            cands = [h for h in self._handles.values()
                     if h.tier == TIER_DEVICE and not h.busy
                     and h.pins == 0]
        cands.sort(key=lambda h: (h.priority,
                                  -resident.get(h.task_id, 0),
                                  -h.device_bytes, h.handle_id))
        return cands

    def ensure_headroom(self, nbytes: int) -> int:
        """Synchronously spill victims until ``nbytes`` of device
        memory have been freed (or nothing spillable remains);
        returns the bytes actually freed.  Called by the adaptor's
        alloc-failure path BEFORE a blocked thread escalates to
        BUFN/retry-split, and by the server's shed path as a last
        try before demoting a job."""
        t0 = time.monotonic_ns()
        freed = 0
        for h in self._victims():
            if freed >= nbytes:
                break
            freed += self._spill_handle(h)
        if freed > 0:
            _obs.record_spill_wait(time.monotonic_ns() - t0,
                                   stage="ensure_headroom")
        return freed

    def _serialize(self, h: SpillHandle, cols: Sequence) -> bytes:
        from spark_rapids_tpu.columns.table import Table
        from spark_rapids_tpu.shuffle import kudo
        from spark_rapids_tpu.shuffle.schema import schema_of_table
        cols = list(cols)
        if h.fields is None:
            h.fields = schema_of_table(Table(cols))
        buf = io.BytesIO()
        rows = int(cols[0].length) if cols else 0
        # CRC forced ON per table: spilled bytes are always
        # corruption-checked on read-back, whatever the wire default
        kudo.write_to_stream(cols, buf, 0, rows, crc=True)
        return buf.getvalue()

    def _spill_handle(self, h: SpillHandle) -> int:
        """device->host (and maybe host->disk under the host budget).
        Returns device bytes freed."""
        t0 = time.monotonic_ns()
        with self._lock:
            if (h.closed or h.busy or h.pins > 0
                    or h.tier != TIER_DEVICE):
                return 0
            h.busy = True
            cols = h.columns
        # serialize OUTSIDE the store lock: a long kudo write must not
        # stall spillable_bytes() probes, which run under the adaptor
        # lock (ABBA otherwise); ``busy`` keeps the handle ours
        try:
            payload = self._serialize(h, cols)
        except BaseException:
            with self._cv:
                h.busy = False
                self._cv.notify_all()
                if h.closed:
                    h.columns = None
                    h.tier = TIER_FREED
            raise
        with self._cv:
            h.busy = False
            self._cv.notify_all()
            if h.closed:
                # closed while serializing: drop the payload, finish
                # the deferred cleanup close() left to the busy owner
                h.columns = None
                h.tier = TIER_FREED
                return 0
            h.payload = payload
            h.columns = None
            h.tier = TIER_HOST
            h.generation += 1
            h.spill_seq = self._spill_seq
            self._spill_seq += 1
            self._host_bytes += len(payload)
            self.spill_count[TIER_HOST] += 1
        # release OUTSIDE the lock: deallocation wakes blocked threads
        self._release_device(h.device_bytes)
        _obs.record_spill(stage=h.stage, tier=TIER_HOST,
                          nbytes=h.device_bytes,
                          ns=time.monotonic_ns() - t0, task=h.task_id,
                          name=h.name, generation=h.generation)
        self._enforce_host_limit()
        return h.device_bytes

    def _enforce_host_limit(self) -> None:
        limit = self.host_limit_bytes()
        if limit is None:
            return
        while True:
            with self._lock:
                if self._host_bytes <= limit:
                    return
                hosted = [h for h in self._handles.values()
                          if h.tier == TIER_HOST and not h.busy]
                if not hosted:
                    return
                h = min(hosted, key=lambda x: x.spill_seq)  # oldest
            self._demote_to_disk(h)

    def _demote_to_disk(self, h: SpillHandle) -> None:
        t0 = time.monotonic_ns()
        with self._lock:
            if h.closed or h.busy or h.tier != TIER_HOST:
                return
            payload = h.payload
            path = os.path.join(
                self.spill_dir(),
                f"{h.name}.g{h.generation}.kudo")
            h.busy = True
        try:
            with open(path, "wb") as f:
                f.write(payload)
        except OSError:
            with self._cv:
                h.busy = False
                self._cv.notify_all()
                if h.closed:
                    # closed while the failed write was in flight:
                    # same deferred cleanup as the success path, or
                    # the host payload leaks with tier still HOST
                    if h.payload is not None:
                        self._host_bytes -= len(h.payload)
                        h.payload = None
                    h.columns = None
                    h.tier = TIER_FREED
            try:
                os.unlink(path)            # any partial write
            except OSError:
                pass
            return
        closed = False
        with self._cv:
            h.busy = False
            self._cv.notify_all()
            if h.closed:
                # closed while the file write was in flight: finish
                # the deferred cleanup close() left to us
                if h.payload is not None:
                    self._host_bytes -= len(h.payload)
                    h.payload = None
                h.columns = None
                h.tier = TIER_FREED
                closed = True
            else:
                self._host_bytes -= len(payload)
                self._disk_bytes += len(payload)
                h.payload = None
                h.path = path
                h.disk_nbytes = len(payload)
                h.tier = TIER_DISK
                self.spill_count[TIER_DISK] += 1
        if closed:
            try:
                os.unlink(path)
            except OSError:
                pass
            return
        _obs.record_spill(stage=h.stage, tier=TIER_DISK,
                          nbytes=len(payload),
                          ns=time.monotonic_ns() - t0, task=h.task_id,
                          name=h.name, generation=h.generation)

    # ------------------------------------------------------------- restore

    def _drop_spilled_payload_locked(self, h: SpillHandle,
                                     charge_host: bool = True
                                     ) -> Optional[str]:
        """Drop a handle's host payload and disk accounting (caller
        holds the store lock); returns the file path the CALLER must
        unlink AFTER releasing the lock (filesystem work never runs
        under the store lock)."""
        if h.payload is not None:
            if charge_host:
                self._host_bytes -= len(h.payload)
            h.payload = None
        self._disk_bytes -= h.disk_nbytes
        h.disk_nbytes = 0
        path, h.path = h.path, None
        return path

    def _materialize(self, h: SpillHandle, pin: bool = False):
        with self._cv:
            while h.busy:
                self._cv.wait()
            if h.closed:
                raise ValueError(
                    f"spill handle {h.name!r} is closed")
            if h.tier == TIER_DEVICE:
                if pin:
                    h.pins += 1
                return h.columns
            h.busy = True
            src_tier = h.tier
            payload = h.payload
            path = h.path
            gen = h.generation
            fields = h.fields
        t0 = time.monotonic_ns()
        acquired = False
        try:
            # blocking device re-acquisition OUTSIDE the lock (it may
            # itself trigger ensure_headroom on other handles)
            self._acquire_device(h.device_bytes)
            acquired = True
            cols = self._deserialize(h, src_tier, payload, path, gen,
                                     fields)
            ns = time.monotonic_ns() - t0
            release_owed = 0
            unlink_path = None
            with self._cv:
                h.busy = False
                self._cv.notify_all()
                if h.closed:
                    # restore-under-concurrent-free race: the caller
                    # still gets its data; the reservation and the
                    # handle's tiers are released, nothing leaks.
                    # close() deferred payload/file cleanup to us.
                    unlink_path = self._drop_spilled_payload_locked(h)
                    h.columns = None
                    h.tier = TIER_FREED
                    # the release runs AFTER the lock is dropped:
                    # deallocate takes the adaptor lock, whose holder
                    # may be probing our spillable_bytes() (ABBA
                    # deadlock if we called it here)
                    release_owed = h.device_bytes
                else:
                    unlink_path = self._drop_spilled_payload_locked(
                        h, charge_host=(src_tier == TIER_HOST))
                    h.columns = list(cols)
                    h.tier = TIER_DEVICE
                    if pin:
                        h.pins += 1
                    self.restore_count += 1
            if unlink_path:
                try:
                    os.unlink(unlink_path)
                except OSError:
                    pass
            if release_owed:
                self._release_device(release_owed)
                return cols
            _obs.record_spill_restore(stage=h.stage, tier=src_tier,
                                      nbytes=h.device_bytes, ns=ns,
                                      task=h.task_id, name=h.name)
            _obs.record_spill_wait(ns, stage=h.stage or "restore")
            return cols
        except BaseException:
            unlink_path = None
            with self._cv:
                h.busy = False
                self._cv.notify_all()
                if h.closed:
                    # deferred close cleanup (see _close_handle)
                    unlink_path = self._drop_spilled_payload_locked(h)
                    h.columns = None
                    h.tier = TIER_FREED
            if unlink_path:
                try:
                    os.unlink(unlink_path)
                except OSError:
                    pass
            if acquired:
                self._release_device(h.device_bytes)
            raise

    def _deserialize(self, h: SpillHandle, src_tier: str,
                     payload: Optional[bytes], path: Optional[str],
                     generation: int, fields):
        from spark_rapids_tpu.shuffle import kudo
        if src_tier == TIER_DISK:
            try:
                with open(path, "rb") as f:
                    payload = f.read()
            except OSError as e:
                return self._corrupt(h, kudo.KudoCorruptException(
                    f"unreadable spill file: {e}", reason="truncated",
                    path=path, generation=generation), path, generation)
        try:
            kts = kudo.read_tables(io.BytesIO(payload))
            table = kudo.merge_to_table(kts, fields)
            return list(table.columns)
        except (kudo.KudoCorruptException, EOFError, ValueError) as e:
            if not isinstance(e, kudo.KudoCorruptException):
                e = kudo.KudoCorruptException(str(e), reason="truncated")
            if e.path is None and path is not None:
                e = kudo.annotate_spill_corruption(e, path, generation)
            return self._corrupt(h, e, path, generation)

    def _corrupt(self, h: SpillHandle, err, path, generation):
        """A spill payload failed verification on read-back.  With a
        ``recompute`` callback the batch is rebuilt from source
        (counted srt_spill_corrupt_total{outcome=recomputed}) instead
        of failing the query; without one the annotated error (file
        path + spill generation) escalates."""
        self.corrupt_count += 1
        if h.recompute is not None:
            _obs.record_spill_corrupt(
                "recomputed", path=path or "", generation=generation,
                name=h.name, stage=h.stage, task=h.task_id)
            self.recompute_count += 1
            return list(h.recompute())
        _obs.record_spill_corrupt(
            "failed", path=path or "", generation=generation,
            name=h.name, stage=h.stage, task=h.task_id)
        raise err

    # --------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            tiers: Dict[str, dict] = {
                TIER_DEVICE: {"handles": 0, "bytes": 0},
                TIER_HOST: {"handles": 0,
                            "bytes": int(self._host_bytes)},
                TIER_DISK: {"handles": 0,
                            "bytes": int(self._disk_bytes)},
            }
            for h in self._handles.values():
                row = tiers.get(h.tier)
                if row is not None:
                    row["handles"] += 1
                    if h.tier == TIER_DEVICE:
                        row["bytes"] += h.device_bytes
            return {
                "handles": len(self._handles),
                "tiers": tiers,
                "spills_host": self.spill_count[TIER_HOST],
                "spills_disk": self.spill_count[TIER_DISK],
                "restores": self.restore_count,
                "corrupt": self.corrupt_count,
                "recomputes": self.recompute_count,
                "spillable_bytes": sum(
                    h.device_bytes for h in self._handles.values()
                    if h.tier == TIER_DEVICE and not h.busy
                    and h.pins == 0),
            }


# ------------------------------------------------------- global install

_store: Optional[SpillStore] = None
_install_lock = threading.Lock()


def install(store: Optional[SpillStore] = None) -> SpillStore:
    """Install the process spill store and wire it into the installed
    adaptor's OOM state machine (idempotent; a fresh store replaces
    the prior one)."""
    global _store
    with _install_lock:
        if store is None:
            store = SpillStore()
        _store = store
        from spark_rapids_tpu.memory import rmm_spark
        ad = rmm_spark.installed_adaptor()
        if ad is not None:
            ad.set_spill_hook(store)
        return store


def uninstall() -> None:
    global _store
    with _install_lock:
        store, _store = _store, None
        from spark_rapids_tpu.memory import rmm_spark
        ad = rmm_spark.installed_adaptor()
        if ad is not None:
            ad.set_spill_hook(None)
        if store is not None:
            store.close()


def installed_store() -> Optional[SpillStore]:
    return _store


def ensure_store() -> SpillStore:
    """The installed store, installing a default one on first use
    (the out-of-core operators' entry)."""
    return _store if _store is not None else install()
