"""The OOM retry/split/BUFN thread state machine.

Re-implements the semantics of the reference SparkResourceAdaptorJni.cpp
(2,903 LoC; design doc docs/memory_management.md) over the reservation
resources in memory/resource.py:

  * 9 thread states (SparkResourceAdaptorJni.cpp:91-104): RUNNING, ALLOC,
    ALLOC_FREE, BLOCKED, BUFN_THROW, BUFN_WAIT, BUFN, SPLIT_THROW,
    REMOVE_THROW.
  * alloc flow (allocate() loop, :2115-2140): pre_alloc -> resource ->
    post_alloc_success / post_alloc_failed; failed+OOM blocks the thread;
    frees flip other ALLOC threads to ALLOC_FREE and wake the highest
    priority BLOCKED thread.
  * deadlock detection (is_in_deadlock :1789): a task is blocked if any
    dedicated thread is blocked and ALL pool threads working for it are
    blocked; all tasks blocked => pick the lowest-priority BLOCKED thread
    to roll back (BUFN_THROW -> GpuRetryOOM), unless it is the only blocked
    thread, in which case it retries once first (is_retry_alloc_before_bufn,
    :1962-1975); all tasks BUFN => pick the highest-priority BUFN thread to
    split (SPLIT_THROW -> GpuSplitAndRetryOOM).
  * thread priority (:349-396): task_priority = MAX_LONG - (task_id + 1),
    pool/shuffle threads (no task) highest; thread id breaks ties.
  * forced-OOM injection hooks (force_retry_oom etc. :955-991) and the
    watchdog entry check_and_break_deadlocks (:1119) — the contract the
    reference test suite (RmmSparkTest.java) drives.
  * CSV transition log with the reference's header/format (:125-200).

This runtime layer is host-side control logic (it never touches device
data); a C++ port behind the same API is planned for the JNI shim.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Set

from spark_rapids_tpu import observability as _obs
from spark_rapids_tpu.memory import exceptions as exc
from spark_rapids_tpu.memory.resource import (AllocationFailed,
                                              MemoryResource)

MAX_LONG = (1 << 63) - 1

# thread states
UNKNOWN = "UNKNOWN"
THREAD_RUNNING = "THREAD_RUNNING"
THREAD_ALLOC = "THREAD_ALLOC"
THREAD_ALLOC_FREE = "THREAD_ALLOC_FREE"
THREAD_BLOCKED = "THREAD_BLOCKED"
THREAD_BUFN_THROW = "THREAD_BUFN_THROW"
THREAD_BUFN_WAIT = "THREAD_BUFN_WAIT"
THREAD_BUFN = "THREAD_BUFN"
THREAD_SPLIT_THROW = "THREAD_SPLIT_THROW"
THREAD_REMOVE_THROW = "THREAD_REMOVE_THROW"

# oom injection filters (RmmSpark.OomInjectionType)
CPU_OR_GPU = "CPU_OR_GPU"
CPU = "CPU"
GPU = "GPU"

RETRY_LIMIT = 500  # check_before_oom livelock watchdog (:1290)


class _Injection:
    __slots__ = ("hit_count", "skip_count", "filter")

    def __init__(self):
        self.hit_count = 0
        self.skip_count = 0
        self.filter = GPU

    def matches(self, is_for_cpu: bool) -> bool:
        if self.hit_count <= 0 and self.skip_count <= 0:
            return False
        if self.filter == CPU_OR_GPU:
            return True
        return (self.filter == CPU) == is_for_cpu


class TaskMetrics:
    __slots__ = ("num_times_retry_throw", "num_times_split_retry_throw",
                 "time_blocked_nanos", "time_lost_nanos",
                 "gpu_max_memory_allocated", "gpu_memory_active_footprint",
                 "gpu_memory_max_footprint")

    def __init__(self):
        self.num_times_retry_throw = 0
        self.num_times_split_retry_throw = 0
        self.time_blocked_nanos = 0
        self.time_lost_nanos = 0
        self.gpu_max_memory_allocated = 0
        self.gpu_memory_active_footprint = 0
        self.gpu_memory_max_footprint = 0

    def add(self, other: "TaskMetrics"):
        self.num_times_retry_throw += other.num_times_retry_throw
        self.num_times_split_retry_throw += other.num_times_split_retry_throw
        self.time_blocked_nanos += other.time_blocked_nanos
        self.time_lost_nanos += other.time_lost_nanos
        self.gpu_max_memory_allocated = max(self.gpu_max_memory_allocated,
                                            other.gpu_max_memory_allocated)
        # active footprint SUMS across checkpoints: bytes a removed
        # thread still held must survive into the task bucket, or the
        # task_done leak detector goes blind to exactly the leaks that
        # matter (thread died holding memory)
        self.gpu_memory_active_footprint += other.gpu_memory_active_footprint
        self.gpu_memory_max_footprint = max(self.gpu_memory_max_footprint,
                                            other.gpu_memory_max_footprint)


class _ThreadState:
    def __init__(self, thread_id: int, task_id: Optional[int], lock,
                 is_for_shuffle: bool = False):
        self.thread_id = thread_id
        self.task_id = task_id          # None => pool/shuffle thread
        self.pool_task_ids: Set[int] = set()
        self.is_for_shuffle = is_for_shuffle
        self.state = THREAD_RUNNING
        self.is_cpu_alloc = False
        self.pool_blocked = False
        self.is_retry_alloc_before_bufn = False
        self.is_in_spilling = False
        self.num_times_retried = 0
        self.retry_oom = _Injection()
        self.split_and_retry_oom = _Injection()
        self.cudf_exception_injected = 0
        self.metrics = TaskMetrics()
        # ledger counters (survive metric checkpointing: they describe
        # the THREAD, not the task)
        self.alloc_count = 0
        self.dealloc_count = 0
        self.wake = threading.Condition(lock)
        self._block_start: Optional[float] = None
        self._retry_point: float = time.monotonic()

    def priority(self):
        """Sortable priority; larger sorts as higher priority."""
        if self.task_id is None:
            tp = MAX_LONG
        else:
            tp = MAX_LONG - (self.task_id + 1)
        return (tp, self.thread_id)

    def before_block(self):
        self._block_start = time.monotonic()
        _obs.record_oom_event("thread_blocked", thread_id=self.thread_id,
                              task_id=self.task_id,
                              is_cpu=self.is_cpu_alloc)

    def after_block(self):
        if self._block_start is not None:
            blocked_ns = int((time.monotonic() - self._block_start) * 1e9)
            self.metrics.time_blocked_nanos += blocked_ns
            self._block_start = None
            _obs.record_oom_event("thread_unblocked",
                                  thread_id=self.thread_id,
                                  task_id=self.task_id,
                                  blocked_ns=blocked_ns)

    def record_failed_retry_time(self):
        now = time.monotonic()
        self.metrics.time_lost_nanos += int((now - self._retry_point) * 1e9)
        self._retry_point = now

    def record_progress(self):
        self._retry_point = time.monotonic()


class SparkResourceAdaptor:
    """State-machine resource adaptor (one per executor process)."""

    def __init__(self, resource: MemoryResource,
                 log_path: Optional[str] = None):
        self.resource = resource
        self._lock = threading.Lock()
        self._threads: Dict[int, _ThreadState] = {}
        self._checkpointed: Dict[int, TaskMetrics] = {}
        self.gpu_memory_allocated_bytes = 0
        # bounded ring when no file sink: long-lived executors must not
        # accumulate log strings forever
        self._log_rows = collections.deque(maxlen=100_000)
        self._log_file = open(log_path, "w") if log_path else None
        # ThreadStateRegistry callback: the reference's native adaptor
        # calls ThreadStateRegistry.removeThread when an association
        # ends (SparkResourceAdaptorJni.cpp:66-80); set this to the
        # registry's remove_thread to mirror that shape
        self.on_thread_removed = None
        # spill hook (memory/spill.py SpillStore): ensure_headroom(n)
        # frees device bytes synchronously; spillable_bytes() is the
        # cheap probe the deadlock breaker consults before BUFN
        self._spill_hook = None
        self._log("time,op,current thread,op thread,op task,from state,"
                  "to state,notes", raw=True)

    # ------------------------------------------------------------- logging

    def _log(self, row: str, raw: bool = False):
        line = row if raw else f"{time.monotonic():.6f},{row}"
        self._log_rows.append(line)
        if self._log_file:
            self._log_file.write(line + "\n")
            self._log_file.flush()

    def _log_transition(self, t: _ThreadState, to_state: str, notes: str = ""):
        tid = threading.get_ident()
        task = t.task_id if t.task_id is not None else -1
        self._log(f"TRANSITION,{tid},{t.thread_id},{task},{t.state},"
                  f"{to_state},{notes}")

    def _log_status(self, op: str, thread_id: int, task_id, state: str,
                    notes: str = ""):
        tid = threading.get_ident()
        task = task_id if task_id is not None else -1
        self._log(f"{op},{tid},{thread_id},{task},{state},,{notes}")

    def get_log(self) -> List[str]:
        return list(self._log_rows)

    # --------------------------------------------------------- transitions

    def _transition(self, t: _ThreadState, to_state: str, notes: str = ""):
        self._log_transition(t, to_state, notes)
        t.state = to_state

    # ------------------------------------------------------- registration

    def start_dedicated_task_thread(self, thread_id: int, task_id: int):
        with self._lock:
            t = self._threads.get(thread_id)
            if t is not None:
                if t.task_id != task_id:
                    raise ValueError(
                        f"thread {thread_id} already registered to task "
                        f"{t.task_id}")
                return
            t = _ThreadState(thread_id, task_id, self._lock)
            self._threads[thread_id] = t
            self._log_transition(t, THREAD_RUNNING, "dedicated task thread")

    def pool_thread_working_on_tasks(self, is_for_shuffle: bool,
                                     thread_id: int, task_ids):
        with self._lock:
            t = self._threads.get(thread_id)
            if t is None:
                t = _ThreadState(thread_id, None, self._lock,
                                 is_for_shuffle=is_for_shuffle)
                self._threads[thread_id] = t
                self._log_transition(
                    t, THREAD_RUNNING,
                    "shuffle thread" if is_for_shuffle else "pool thread")
            elif t.task_id is not None:
                raise ValueError(
                    f"thread {thread_id} is a dedicated task thread")
            t.pool_task_ids.update(task_ids)

    def pool_thread_finished_for_tasks(self, thread_id: int, task_ids):
        with self._lock:
            for task_id in list(task_ids):
                self._remove_thread_association(thread_id, task_id)

    def remove_thread_association(self, thread_id: int,
                                  task_id: int = -1):
        with self._lock:
            self._remove_thread_association(thread_id, task_id)

    def _remove_thread_association(self, thread_id: int, remove_task_id: int):
        t = self._threads.get(thread_id)
        if t is None:
            return False
        self._checkpoint_metrics(t)
        remove = False
        if remove_task_id < 0:
            remove = True
        elif t.task_id is not None:
            if t.task_id == remove_task_id:
                remove = True
        else:
            t.pool_task_ids.discard(remove_task_id)
            if not t.pool_task_ids:
                remove = True
        ret = False
        if remove:
            if t.state in (THREAD_BLOCKED, THREAD_BUFN):
                self._transition(t, THREAD_REMOVE_THROW)
                t.wake.notify_all()
            else:
                if t.state == THREAD_RUNNING:
                    ret = True
                self._log_transition(t, UNKNOWN)
                del self._threads[thread_id]
                if self.on_thread_removed is not None:
                    try:
                        self.on_thread_removed(thread_id)
                    except Exception:
                        pass  # registry bugs must not corrupt the SM
        return ret

    def task_done(self, task_id: int):
        leaked = 0
        holders: List[dict] = []
        with self._lock:
            # leak detection BEFORE the associations unwind: device
            # bytes still attributed to the finishing task are exactly
            # the evidence the flight recorder wants frozen.  The sum
            # includes NEGATIVE footprints — a checkpointed +X whose
            # frees landed on the live thread after the checkpoint
            # shows up as thread -X, and only the net is a leak.
            # Pool threads serving several tasks still attribute their
            # held bytes to each finishing task (shared-accounting
            # noise the leak detector's byte floor filters).
            cp = self._checkpointed.get(task_id)
            if cp is not None and cp.gpu_memory_active_footprint != 0:
                leaked += cp.gpu_memory_active_footprint
                if cp.gpu_memory_active_footprint > 0:
                    holders.append({
                        "thread": -1, "state": "CHECKPOINTED",
                        "bytes":
                        int(cp.gpu_memory_active_footprint)})
            for t in self._threads.values():
                if (t.task_id == task_id
                        or task_id in t.pool_task_ids) \
                        and t.metrics.gpu_memory_active_footprint != 0:
                    leaked += t.metrics.gpu_memory_active_footprint
                    if t.metrics.gpu_memory_active_footprint > 0:
                        holders.append({
                            "thread": t.thread_id, "state": t.state,
                            "bytes":
                            int(t.metrics.gpu_memory_active_footprint)})
            woke_any = False
            for thread_id in list(self._threads.keys()):
                t = self._threads.get(thread_id)
                if t is None:
                    continue
                associated = (t.task_id == task_id
                              or task_id in t.pool_task_ids)
                if associated:
                    if self._remove_thread_association(thread_id, task_id):
                        woke_any = True
            self._wake_up_threads_after_task_finishes()
        if leaked > 0:
            # outside the lock: the leak hook may freeze a bundle,
            # which reads this adaptor's ledger (non-reentrant lock)
            _obs.record_task_leak(task_id, int(leaked), holders)
        return woke_any

    def force_release_task(self, task_id: int) -> dict:
        """Watchdog entry (query lifeguard, ISSUE 7): forcibly unwind
        a HUNG task's thread associations so its held accounting and
        blocked neighbors unblock without waiting for the wedged
        thread to cooperate.  Semantically ``task_done`` — blocked
        associated threads get ``THREAD_REMOVE_THROW`` (they raise
        ``ThreadRemovedException`` if they ever wake), running ones
        are disassociated, waiters are woken — plus a FORCE_RELEASE
        row in the OOM-state log so the transition timeline shows the
        eviction was deliberate.  Returns the affected thread ids and
        the device bytes the task still held."""
        with self._lock:
            affected = []
            held = 0
            for t in self._threads.values():
                if t.task_id == task_id or task_id in t.pool_task_ids:
                    affected.append(t.thread_id)
                    held += int(t.metrics.gpu_memory_active_footprint)
            cp = self._checkpointed.get(task_id)
            if cp is not None:
                held += int(cp.gpu_memory_active_footprint)
            self._log_status(
                "FORCE_RELEASE", affected[0] if affected else -1,
                task_id, "WATCHDOG",
                notes=f"threads={len(affected)} held={held}")
        woke = self.task_done(task_id)
        return {"task": task_id, "threads": affected,
                "held_bytes": held, "woke_any": woke}

    def _checkpoint_metrics(self, t: _ThreadState):
        """Merge a thread's metrics into its task-level checkpoints."""
        task_ids = ([t.task_id] if t.task_id is not None
                    else list(t.pool_task_ids))
        for task_id in task_ids:
            self._checkpointed.setdefault(task_id, TaskMetrics()).add(
                t.metrics)
        t.metrics = TaskMetrics()

    # ------------------------------------------------------ oom injection

    def force_retry_oom(self, thread_id: int, num_ooms: int,
                        oom_filter: str = GPU, skip_count: int = 0):
        self._force(thread_id, "retry_oom", num_ooms, oom_filter, skip_count)

    def force_split_and_retry_oom(self, thread_id: int, num_ooms: int,
                                  oom_filter: str = GPU,
                                  skip_count: int = 0):
        self._force(thread_id, "split_and_retry_oom", num_ooms, oom_filter,
                    skip_count)

    def _force(self, thread_id, which, num_ooms, oom_filter, skip_count):
        with self._lock:
            t = self._threads.get(thread_id)
            if t is None:
                raise ValueError(f"thread {thread_id} is not registered")
            inj = getattr(t, which)
            inj.hit_count = num_ooms
            inj.skip_count = skip_count
            inj.filter = oom_filter

    def force_cudf_exception(self, thread_id: int, num_times: int):
        with self._lock:
            t = self._threads.get(thread_id)
            if t is None:
                raise ValueError(f"thread {thread_id} is not registered")
            t.cudf_exception_injected = num_times

    # ------------------------------------------------------------ queries

    def get_state_of(self, thread_id: int) -> str:
        with self._lock:
            t = self._threads.get(thread_id)
            return t.state if t is not None else UNKNOWN

    # ------------------------------------------------------------ metrics

    def _collect_metric(self, task_id: int, attr: str, reset: bool):
        total = 0
        is_max = attr in ("gpu_max_memory_allocated",
                          "gpu_memory_max_footprint")
        cp = self._checkpointed.get(task_id)
        if cp is not None:
            v = getattr(cp, attr)
            total = max(total, v) if is_max else total + v
            if reset:
                setattr(cp, attr, 0)
        for t in self._threads.values():
            if t.task_id == task_id or task_id in t.pool_task_ids:
                v = getattr(t.metrics, attr)
                total = max(total, v) if is_max else total + v
                if reset:
                    setattr(t.metrics, attr, 0)
        return total

    def get_and_reset_num_retry_throw(self, task_id: int) -> int:
        with self._lock:
            return self._collect_metric(task_id, "num_times_retry_throw",
                                        True)

    def get_and_reset_num_split_retry_throw(self, task_id: int) -> int:
        with self._lock:
            return self._collect_metric(
                task_id, "num_times_split_retry_throw", True)

    def get_and_reset_block_time(self, task_id: int) -> int:
        with self._lock:
            return self._collect_metric(task_id, "time_blocked_nanos", True)

    def get_and_reset_compute_time_lost_to_retry(self, task_id: int) -> int:
        with self._lock:
            return self._collect_metric(task_id, "time_lost_nanos", True)

    def get_and_reset_gpu_max_memory_allocated(self, task_id: int) -> int:
        with self._lock:
            return self._collect_metric(task_id,
                                        "gpu_max_memory_allocated", True)

    def get_max_gpu_task_memory(self, task_id: int) -> int:
        with self._lock:
            return self._collect_metric(task_id, "gpu_memory_max_footprint",
                                        False)

    def remove_task_metrics(self, task_id: int):
        """Drop checkpointed metrics for a finished task (reference
        removeTaskMetrics / SparkResourceAdaptorJni.cpp:1057) — callers pull
        get_and_reset_* first, then release the bookkeeping."""
        with self._lock:
            self._checkpointed.pop(task_id, None)

    # ------------------------------------------------------ memory ledger

    def memory_ledger(self, timeline: int = 200) -> dict:
        """Flight-recorder export (reference: RmmSpark's thread-state
        dump): per-thread and per-task allocation totals and
        watermarks, plus the tail of the OOM-state transition log.
        Per-task rows fold live threads AND the checkpointed buckets
        of threads that already unwound, so a task's held bytes are
        visible even after its threads died."""
        with self._lock:
            threads: Dict[str, dict] = {}
            tasks: Dict[int, dict] = {}

            def task_row(task_id: int) -> dict:
                return tasks.setdefault(task_id, {
                    "active_bytes": 0, "watermark_bytes": 0,
                    "max_allocated_bytes": 0, "retry_oom": 0,
                    "split_retry_oom": 0, "blocked_ns": 0,
                    "lost_ns": 0, "threads": []})

            def fold(row: dict, m: TaskMetrics):
                row["active_bytes"] += int(m.gpu_memory_active_footprint)
                row["watermark_bytes"] = max(
                    row["watermark_bytes"],
                    int(m.gpu_memory_max_footprint))
                row["max_allocated_bytes"] = max(
                    row["max_allocated_bytes"],
                    int(m.gpu_max_memory_allocated))
                row["retry_oom"] += m.num_times_retry_throw
                row["split_retry_oom"] += m.num_times_split_retry_throw
                row["blocked_ns"] += m.time_blocked_nanos
                row["lost_ns"] += m.time_lost_nanos

            for t in self._threads.values():
                m = t.metrics
                threads[str(t.thread_id)] = {
                    "task": t.task_id,
                    "pool_tasks": sorted(t.pool_task_ids),
                    "state": t.state,
                    "shuffle": t.is_for_shuffle,
                    "active_bytes": int(m.gpu_memory_active_footprint),
                    "watermark_bytes": int(m.gpu_memory_max_footprint),
                    "max_allocated_bytes":
                        int(m.gpu_max_memory_allocated),
                    "allocs": t.alloc_count,
                    "frees": t.dealloc_count,
                    "retry_oom": m.num_times_retry_throw,
                    "split_retry_oom": m.num_times_split_retry_throw,
                    "blocked_ns": m.time_blocked_nanos,
                    "lost_ns": m.time_lost_nanos,
                }
                task_ids = ([t.task_id] if t.task_id is not None
                            else sorted(t.pool_task_ids))
                for task_id in task_ids:
                    row = task_row(task_id)
                    fold(row, m)
                    row["threads"].append(t.thread_id)
            for task_id, cp in self._checkpointed.items():
                fold(task_row(task_id), cp)
            limit = getattr(self.resource, "limit", None)
            return {
                "allocated_bytes": int(self.gpu_memory_allocated_bytes),
                "limit_bytes": int(limit) if limit is not None else None,
                "threads": threads,
                "tasks": {str(k): v for k, v in sorted(tasks.items())},
                "oom_state_timeline": (list(self._log_rows)[-timeline:]
                                       if timeline else []),
            }

    def thread_state_dump(self) -> List[dict]:
        """Flat per-thread state list (the RmmSpark state-dump shape
        the incident bundle's threads.json carries)."""
        with self._lock:
            return [{"thread": t.thread_id, "task": t.task_id,
                     "pool_tasks": sorted(t.pool_task_ids),
                     "state": t.state, "shuffle": t.is_for_shuffle,
                     "active_bytes":
                         int(t.metrics.gpu_memory_active_footprint)}
                    for t in self._threads.values()]

    # ----------------------------------------------------------- spilling

    def thread_waiting_on_pool(self, thread_id: Optional[int] = None):
        """Mark a thread as blocked waiting on a pool-thread result
        (reference waiting_on_pool_status_changed :1246).  Such a thread
        counts as BUFN-or-above for deadlock detection, so a producer/
        consumer stall can still be broken."""
        if thread_id is None:
            thread_id = threading.get_ident()
        with self._lock:
            t = self._threads.get(thread_id)
            if t is not None:
                t.pool_blocked = True
                self._check_and_update_for_bufn(None)

    def thread_done_waiting_on_pool(self, thread_id: Optional[int] = None):
        if thread_id is None:
            thread_id = threading.get_ident()
        with self._lock:
            t = self._threads.get(thread_id)
            if t is not None:
                t.pool_blocked = False

    def set_spill_hook(self, hook):
        """Install (or clear, with None) the spill store hook.  The
        hook must expose ``ensure_headroom(nbytes) -> freed_bytes``
        (synchronous, may call back into allocate/deallocate — so it
        is ALWAYS invoked outside the adaptor lock) and
        ``spillable_bytes() -> int`` (lock-cheap probe, safe under the
        adaptor lock)."""
        with self._lock:
            self._spill_hook = hook

    def _spill_for_headroom(self, num_bytes: int) -> int:
        """Run the spill hook for a failed allocation.  Called WITHOUT
        the adaptor lock: the store calls deallocate() per victim,
        which needs the lock to wake blocked threads.  The release
        side runs inside spill_range_start/done (the store brackets
        it), so the recursive-allocation path recognizes the work as
        spill-side and keeps task footprints honest."""
        hook = self._spill_hook
        if hook is None:
            return 0
        try:
            return int(hook.ensure_headroom(num_bytes))
        except Exception:
            # a broken spill hook must never turn an OOM into a crash;
            # the state machine's BUFN/split ladder still applies
            return 0

    def spill_range_start(self):
        with self._lock:
            t = self._threads.get(threading.get_ident())
            if t is not None:
                t.is_in_spilling = True

    def spill_range_done(self):
        with self._lock:
            t = self._threads.get(threading.get_ident())
            if t is not None:
                t.is_in_spilling = False

    # --------------------------------------------------- blocking machinery

    def _is_blocked(self, state: str) -> bool:
        return state in (THREAD_BLOCKED, THREAD_BUFN)

    def _throw_retry_oom(self, t: _ThreadState):
        t.metrics.num_times_retry_throw += 1
        _obs.record_oom_event("oom_retry", thread_id=t.thread_id,
                              task_id=t.task_id, is_cpu=t.is_cpu_alloc)
        self._check_before_oom(t)
        t.record_failed_retry_time()
        if t.is_cpu_alloc:
            raise exc.CpuRetryOOM()
        raise exc.GpuRetryOOM()

    def _throw_split_and_retry_oom(self, t: _ThreadState):
        t.metrics.num_times_split_retry_throw += 1
        _obs.record_oom_event("oom_split_retry", thread_id=t.thread_id,
                              task_id=t.task_id, is_cpu=t.is_cpu_alloc)
        self._check_before_oom(t)
        t.record_failed_retry_time()
        if t.is_cpu_alloc:
            raise exc.CpuSplitAndRetryOOM()
        raise exc.GpuSplitAndRetryOOM()

    def _check_before_oom(self, t: _ThreadState):
        if t.num_times_retried + 1 > RETRY_LIMIT:
            t.record_failed_retry_time()
            raise exc.GpuOOM("GPU OutOfMemory: retry limit exceeded")
        t.num_times_retried += 1

    def block_thread_until_ready(self, thread_id: Optional[int] = None):
        if thread_id is None:
            thread_id = threading.get_ident()
        with self._lock:
            self._block_thread_until_ready(thread_id)

    def _block_thread_until_ready(self, thread_id: int):
        done = False
        first_time = True
        while not done:
            t = self._threads.get(thread_id)
            if t is None:
                return
            state = t.state
            if state in (THREAD_BLOCKED, THREAD_BUFN):
                self._log_status("WAITING", thread_id, t.task_id, state)
                t.before_block()
                while True:
                    t.wake.wait()
                    t = self._threads.get(thread_id)
                    if t is None or not self._is_blocked(t.state):
                        break
                if t is not None:
                    t.after_block()
            elif state == THREAD_BUFN_THROW:
                self._transition(t, THREAD_BUFN_WAIT)
                t.record_failed_retry_time()
                self._throw_retry_oom(t)
            elif state == THREAD_BUFN_WAIT:
                self._transition(t, THREAD_BUFN)
                self._check_and_update_for_bufn(None)
                if self._is_blocked(t.state):
                    self._log_status("WAITING", thread_id, t.task_id,
                                     t.state)
                    t.before_block()
                    while True:
                        t.wake.wait()
                        t = self._threads.get(thread_id)
                        if t is None or not self._is_blocked(t.state):
                            break
                    if t is not None:
                        t.after_block()
            elif state == THREAD_SPLIT_THROW:
                self._transition(t, THREAD_RUNNING)
                t.record_failed_retry_time()
                self._throw_split_and_retry_oom(t)
            elif state == THREAD_REMOVE_THROW:
                self._log_transition(t, UNKNOWN)
                del self._threads[thread_id]
                if self.on_thread_removed is not None:
                    try:  # registry callback fires on BOTH removal
                        self.on_thread_removed(thread_id)  # paths
                    except Exception:
                        pass
                raise exc.ThreadRemovedException(
                    "thread removed while blocked")
            else:
                if not first_time:
                    self._log_status("DONE WAITING", thread_id, t.task_id,
                                     t.state)
                done = True
            first_time = False

    def _wake_up_threads_after_task_finishes(self):
        any_blocked = False
        for t in self._threads.values():
            if t.state == THREAD_BLOCKED:
                self._transition(t, THREAD_RUNNING)
                t.wake.notify_all()
                any_blocked = True
        if not any_blocked:
            for t in self._threads.values():
                if t.state in (THREAD_BUFN, THREAD_BUFN_THROW,
                               THREAD_BUFN_WAIT):
                    self._transition(t, THREAD_RUNNING)
                    t.wake.notify_all()

    def _wake_next_highest_priority_blocked(self, is_for_cpu: bool):
        best = None
        for t in self._threads.values():
            if t.state == THREAD_BLOCKED and t.is_cpu_alloc == is_for_cpu:
                if best is None or t.priority() > best.priority():
                    best = t
        if best is not None:
            self._transition(best, THREAD_RUNNING)
            best.wake.notify_all()

    # -------------------------------------------------- deadlock handling

    def _is_thread_bufn_or_above(self, t: _ThreadState) -> bool:
        if t.pool_blocked:
            return True
        if t.state == THREAD_BLOCKED:
            return False
        return t.state == THREAD_BUFN

    def _deadlock_sets(self):
        all_task_ids: Set[int] = set()
        blocked_task_ids: Set[int] = set()
        bufn_task_ids: Set[int] = set()
        pool_task_thread_count: Dict[int, int] = {}
        pool_bufn_task_thread_count: Dict[int, int] = {}
        for t in self._threads.values():
            if t.task_id is not None:
                all_task_ids.add(t.task_id)
                bufn_plus = self._is_thread_bufn_or_above(t)
                if bufn_plus:
                    bufn_task_ids.add(t.task_id)
                if bufn_plus or t.state == THREAD_BLOCKED:
                    blocked_task_ids.add(t.task_id)
        for t in self._threads.values():
            if t.task_id is None:
                for task_id in t.pool_task_ids:
                    pool_task_thread_count[task_id] = \
                        pool_task_thread_count.get(task_id, 0) + 1
                bufn_plus = self._is_thread_bufn_or_above(t)
                if bufn_plus:
                    for task_id in t.pool_task_ids:
                        pool_bufn_task_thread_count[task_id] = \
                            pool_bufn_task_thread_count.get(task_id, 0) + 1
                if not bufn_plus and t.state != THREAD_BLOCKED:
                    for task_id in t.pool_task_ids:
                        blocked_task_ids.discard(task_id)
        # blocked_task_ids is a subset of all_task_ids, so size equality
        # means every task is blocked (reference :1866)
        deadlocked = (len(all_task_ids) > 0
                      and len(blocked_task_ids) == len(all_task_ids))
        return (deadlocked, all_task_ids, bufn_task_ids,
                pool_task_thread_count, pool_bufn_task_thread_count)

    def check_and_break_deadlocks(self):
        """Watchdog entry (RmmSpark java watchdog -> :1119)."""
        with self._lock:
            self._check_and_update_for_bufn(None)

    def _check_and_update_for_bufn(self, java_blocked):
        (deadlocked, all_task_ids, bufn_task_ids, pool_task_thread_count,
         pool_bufn_task_thread_count) = self._deadlock_sets()
        if not deadlocked:
            return
        # pick lowest-priority BLOCKED thread to roll back
        to_bufn = None
        blocked_count = 0
        for t in self._threads.values():
            if t.state == THREAD_BLOCKED:
                blocked_count += 1
                if to_bufn is None or t.priority() < to_bufn.priority():
                    to_bufn = t
        if to_bufn is not None:
            spillable = 0
            if self._spill_hook is not None:
                try:
                    spillable = int(self._spill_hook.spillable_bytes())
                except Exception:
                    spillable = 0
            if blocked_count == 1 or spillable > 0:
                # last blocked thread: retry the alloc once before BUFN —
                # spillable data may have been freed already (:1962).
                # Same wake when the spill store still holds device
                # bytes: the woken thread's alloc-failure path runs
                # ensure_headroom synchronously (outside the lock)
                # BEFORE any BUFN/retry-split escalation, so registered
                # batches spill instead of the query rolling back.
                to_bufn.is_retry_alloc_before_bufn = True
                self._transition(to_bufn, THREAD_RUNNING)
            else:
                self._transition(to_bufn, THREAD_BUFN_THROW)
            to_bufn.wake.notify_all()
        # tasks whose pool threads are all BUFN count as BUFN tasks
        for task_id, bufn_count in pool_bufn_task_thread_count.items():
            total = pool_task_thread_count.get(task_id)
            if total is not None and total <= bufn_count:
                bufn_task_ids.add(task_id)
        if all_task_ids and len(bufn_task_ids) == len(all_task_ids):
            # all tasks BUFN: highest-priority BUFN thread splits its input
            to_split = None
            for t in self._threads.values():
                if t.state == THREAD_BUFN:
                    if to_split is None or t.priority() > to_split.priority():
                        to_split = t
            if to_split is not None:
                self._transition(to_split, THREAD_SPLIT_THROW)
                to_split.wake.notify_all()

    # ---------------------------------------------------------- alloc flow

    def check_injected_oom(self, thread_id: Optional[int] = None):
        """Consume pending forced-OOM / CudfException injections for a
        thread OUTSIDE the alloc path — the retry drivers
        (robustness/retry.py) poll this at every attempt start, so
        ``force_retry_oom``/``force_split_and_retry_oom`` fire even
        for compute-only sections that never allocate (reference
        RmmSpark.forceRetryOOM semantics).  Device-filtered
        injections are consumed first, then STRICTLY-CPU-filtered
        ones (a compute-only section has no alloc flavor of its own;
        at most ONE injection fires per call since consumption
        raises, and the CPU pass skips CPU_OR_GPU injections — the
        device pass already serviced them, including their
        skip_count).  No-op for unregistered threads."""
        if thread_id is None:
            thread_id = threading.get_ident()
        with self._lock:
            t = self._threads.get(thread_id)
            if t is None:
                return
            self._consume_injected_oom(t, thread_id, False)
            self._consume_injected_oom(t, thread_id, True,
                                       skip_unfiltered=True)

    def _pre_alloc_core(self, thread_id: int, is_for_cpu: bool,
                        blocking: bool) -> bool:
        t = self._threads.get(thread_id)
        if t is None:
            return False
        if t.state in (THREAD_ALLOC, THREAD_ALLOC_FREE):
            if is_for_cpu and blocking:
                raise ValueError(
                    f"thread {thread_id} is trying to do a blocking "
                    f"allocate while already in the state {t.state}")
            return True  # recursive allocation (spill path)
        self._consume_injected_oom(t, thread_id, is_for_cpu)
        if blocking:
            self._block_thread_until_ready(thread_id)
        t = self._threads.get(thread_id)
        if t is None:
            return False
        if t.state == THREAD_RUNNING:
            self._transition(t, THREAD_ALLOC)
            t.is_cpu_alloc = is_for_cpu
        else:
            raise ValueError(
                f"thread {thread_id} in unexpected state pre alloc "
                f"{t.state}")
        return False

    def _consume_injected_oom(self, t: _ThreadState, thread_id: int,
                              is_for_cpu: bool,
                              skip_unfiltered: bool = False):
        """The forced-injection consumption shared by the alloc
        bracket and the retry drivers' check hook (caller holds the
        lock).  Order matches the reference: retry OOM, then
        CudfException, then split-and-retry OOM.  ``skip_unfiltered``
        limits the pass to injections whose filter REQUIRES this
        flavor (check_injected_oom's second pass — a CPU_OR_GPU
        injection must not burn a second skip in one poll)."""
        if t.retry_oom.matches(is_for_cpu) and not (
                skip_unfiltered and t.retry_oom.filter == CPU_OR_GPU):
            if t.retry_oom.skip_count > 0:
                t.retry_oom.skip_count -= 1
            elif t.retry_oom.hit_count > 0:
                t.retry_oom.hit_count -= 1
                t.metrics.num_times_retry_throw += 1
                self._log_status(
                    "INJECTED_RETRY_OOM_" + ("CPU" if is_for_cpu else "GPU"),
                    thread_id, t.task_id, t.state)
                _obs.record_oom_event("oom_retry", thread_id=thread_id,
                                      task_id=t.task_id, is_cpu=is_for_cpu,
                                      injected=True)
                t.record_failed_retry_time()
                raise (exc.CpuRetryOOM("injected RetryOOM") if is_for_cpu
                       else exc.GpuRetryOOM("injected RetryOOM"))
        if t.cudf_exception_injected > 0 and not skip_unfiltered:
            t.cudf_exception_injected -= 1
            self._log_status("INJECTED_CUDF_EXCEPTION", thread_id,
                             t.task_id, t.state)
            t.record_failed_retry_time()
            raise exc.CudfException("injected CudfException")
        if t.split_and_retry_oom.matches(is_for_cpu) and not (
                skip_unfiltered
                and t.split_and_retry_oom.filter == CPU_OR_GPU):
            if t.split_and_retry_oom.skip_count > 0:
                t.split_and_retry_oom.skip_count -= 1
            elif t.split_and_retry_oom.hit_count > 0:
                t.split_and_retry_oom.hit_count -= 1
                t.metrics.num_times_split_retry_throw += 1
                self._log_status(
                    "INJECTED_SPLIT_AND_RETRY_OOM_"
                    + ("CPU" if is_for_cpu else "GPU"),
                    thread_id, t.task_id, t.state)
                _obs.record_oom_event("oom_split_retry",
                                      thread_id=thread_id,
                                      task_id=t.task_id, is_cpu=is_for_cpu,
                                      injected=True)
                t.record_failed_retry_time()
                raise (exc.CpuSplitAndRetryOOM("injected SplitAndRetryOOM")
                       if is_for_cpu
                       else exc.GpuSplitAndRetryOOM(
                           "injected SplitAndRetryOOM"))

    def _post_alloc_success_core(self, thread_id: int, is_for_cpu: bool,
                                 was_recursive: bool, num_bytes: int):
        t = self._threads.get(thread_id)
        if was_recursive or t is None:
            return
        t.is_retry_alloc_before_bufn = False
        if t.state in (THREAD_ALLOC, THREAD_ALLOC_FREE):
            if t.is_cpu_alloc != is_for_cpu:
                raise ValueError(
                    f"thread {thread_id} has a mismatch on CPU vs GPU post "
                    f"alloc {t.state}")
            self._transition(t, THREAD_RUNNING)
            t.is_cpu_alloc = False
            t.record_progress()
            if not is_for_cpu:
                t.alloc_count += 1
                if not t.is_in_spilling:
                    t.metrics.gpu_memory_active_footprint += num_bytes
                    t.metrics.gpu_memory_max_footprint = max(
                        t.metrics.gpu_memory_max_footprint,
                        t.metrics.gpu_memory_active_footprint)
                self.gpu_memory_allocated_bytes += num_bytes
                t.metrics.gpu_max_memory_allocated = max(
                    t.metrics.gpu_max_memory_allocated,
                    self.gpu_memory_allocated_bytes)
                _obs.record_device_memory(self.gpu_memory_allocated_bytes)
        self._wake_next_highest_priority_blocked(is_for_cpu)

    def _post_alloc_failed_core(self, thread_id: int, is_for_cpu: bool,
                                is_oom: bool, blocking: bool,
                                was_recursive: bool) -> bool:
        t = self._threads.get(thread_id)
        if was_recursive or t is None:
            self._check_and_update_for_bufn(None)
            return False
        if t.is_cpu_alloc != is_for_cpu:
            raise ValueError(
                f"thread {thread_id} has a mismatch on CPU vs GPU post "
                f"alloc {t.state}")
        if t.state == THREAD_ALLOC_FREE:
            self._transition(t, THREAD_RUNNING)
        elif t.state == THREAD_ALLOC:
            if is_oom and t.is_retry_alloc_before_bufn:
                t.is_retry_alloc_before_bufn = False
                self._transition(t, THREAD_BUFN_THROW)
                t.wake.notify_all()
            elif is_oom and blocking:
                self._transition(t, THREAD_BLOCKED)
            else:
                self._transition(t, THREAD_RUNNING)
        else:
            raise RuntimeError(
                f"Internal error: unexpected state after alloc failed "
                f"{thread_id} {t.state}")
        self._check_and_update_for_bufn(None)
        return True

    def _dealloc_core(self, is_for_cpu: bool, num_bytes: int):
        tid = threading.get_ident()
        t = self._threads.get(tid)
        if t is not None:
            self._log_status("DEALLOC", tid, t.task_id, t.state)
            if not is_for_cpu:
                t.dealloc_count += 1
                if not t.is_in_spilling:
                    t.metrics.gpu_memory_active_footprint -= num_bytes
                self.gpu_memory_allocated_bytes -= num_bytes
                _obs.record_device_memory(self.gpu_memory_allocated_bytes)
        for other in self._threads.values():
            if other.thread_id != tid and other.state == THREAD_ALLOC \
                    and other.is_cpu_alloc == is_for_cpu:
                self._transition(other, THREAD_ALLOC_FREE)
        self._wake_next_highest_priority_blocked(is_for_cpu)

    # -------------------------------------------------------- public alloc

    def allocate(self, num_bytes: int) -> int:
        """Device reservation with full retry semantics (reference
        allocate() :2115).  Returns num_bytes on success."""
        tid = threading.get_ident()
        while True:
            with self._lock:
                likely_spill = self._pre_alloc_core(tid, False, True)
            try:
                self.resource.allocate(num_bytes)
                with self._lock:
                    self._post_alloc_success_core(tid, False, likely_spill,
                                                  num_bytes)
                from spark_rapids_tpu.utils.profiler import record_alloc
                record_alloc("alloc", num_bytes)
                return num_bytes
            except AllocationFailed:
                # synchronous spill BEFORE escalation: free registered
                # spillable batches and retry cleanly (no BLOCKED/BUFN
                # transition) while the store still has device bytes.
                # Runs outside the lock — the store deallocates per
                # victim, bracketed by spill_range_start/done.
                freed = self._spill_for_headroom(num_bytes)
                if freed > 0:
                    with self._lock:
                        t = self._threads.get(tid)
                        if t is not None:
                            t.is_retry_alloc_before_bufn = False
                        self._post_alloc_failed_core(
                            tid, False, True, False, likely_spill)
                    continue
                with self._lock:
                    retry = self._post_alloc_failed_core(
                        tid, False, True, True, likely_spill)
                if not retry:
                    raise exc.GpuOOM("GPU OutOfMemory")
            except (exc.RetryOOMBase, exc.SplitAndRetryOOMBase,
                    exc.CudfException):
                raise
            except Exception:
                with self._lock:
                    self._post_alloc_failed_core(tid, False, False, True,
                                                 likely_spill)
                raise

    def deallocate(self, num_bytes: int):
        self.resource.deallocate(num_bytes)
        with self._lock:
            self._dealloc_core(False, num_bytes)
        from spark_rapids_tpu.utils.profiler import record_alloc
        record_alloc("free", num_bytes)

    # ------------------------------------------------------ cpu alloc hooks

    def cpu_prealloc(self, num_bytes: int, blocking: bool) -> bool:
        """Host-alloc bracket (RmmSpark.preCpuAlloc :790): returns
        was_recursive."""
        tid = threading.get_ident()
        with self._lock:
            return self._pre_alloc_core(tid, True, blocking)

    def post_cpu_alloc_success(self, num_bytes: int, blocking: bool,
                               was_recursive: bool):
        tid = threading.get_ident()
        with self._lock:
            self._post_alloc_success_core(tid, True, was_recursive,
                                          num_bytes)

    def post_cpu_alloc_failed(self, was_oom: bool, blocking: bool,
                              was_recursive: bool) -> bool:
        tid = threading.get_ident()
        with self._lock:
            return self._post_alloc_failed_core(tid, True, was_oom,
                                                blocking, was_recursive)

    def cpu_deallocate(self, num_bytes: int):
        with self._lock:
            self._dealloc_core(True, num_bytes)

    # ------------------------------------------------------------ shutdown

    def shutdown(self):
        with self._lock:
            for t in list(self._threads.values()):
                if t.state in (THREAD_BLOCKED, THREAD_BUFN):
                    self._transition(t, THREAD_REMOVE_THROW)
                    t.wake.notify_all()
            # registry teardown: still-registered (RUNNING) threads
            # must not outlive the adaptor in the ThreadStateRegistry
            # (removeThread parity holds across non-clean teardowns)
            if self.on_thread_removed is not None:
                for thread_id in list(self._threads):
                    try:
                        self.on_thread_removed(thread_id)
                    except Exception:
                        pass
            # detach the sink under the lock so woken threads can't race a
            # write against close(); close after releasing the lock
            log_file, self._log_file = self._log_file, None
        if log_file:
            log_file.close()
