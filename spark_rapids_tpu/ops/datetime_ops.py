"""Datetime ops: timezone conversion, Julian<->Gregorian rebase, truncate
(reference timezones.cu/timezones.hpp, datetime_rebase.cu,
datetime_truncate.cu, GpuTimeZoneDB.java / DateTimeRebase.java /
DateTimeUtils.java).

All date math is vectorized civil-calendar arithmetic (Howard Hinnant
style days<->ymd formulas) on device arrays; timezone offsets come from
binary search over the tzdb transition table (utils/tzdb.py), matching
the reference's device binary search over its ZoneRules-derived table.
"""

from __future__ import annotations

from typing import Union

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.utils import tzdb

_I64 = jnp.int64
_I32 = jnp.int32

MICROS_PER_SEC = 1_000_000
SECS_PER_DAY = 86400


# ----------------------------------------------------- civil date helpers

def civil_days_scalar(y: int, m: int, d: int) -> int:
    """Scalar Hinnant days-from-civil (shared by host-loop parsers)."""
    y -= m <= 2
    era = (y if y >= 0 else y - 399) // 400
    yoe = y - era * 400
    mp = (m - 3) % 12
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468



def _days_to_ymd(z):
    """Vectorized proleptic-Gregorian days-since-epoch -> (y, m, d)."""
    z = z + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _ymd_to_days(y, m, d):
    y = jnp.where(m <= 2, y - 1, y)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _julian_ymd_to_days(y, m, d):
    """Julian-calendar Y/M/D -> days since 1970-01-01 (via JDN)."""
    a = (14 - m) // 12
    yy = y + 4800 - a
    mm = m + 12 * a - 3
    jdn = d + (153 * mm + 2) // 5 + 365 * yy + yy // 4 - 32083
    return jdn - 2440588


def _days_to_julian_ymd(z):
    """days since epoch -> Julian-calendar (y, m, d)."""
    jdn = z + 2440588
    c = jdn + 32082
    d_ = (4 * c + 3) // 1461
    e = c - (1461 * d_) // 4
    m_ = (5 * e + 2) // 153
    day = e - (153 * m_ + 2) // 5 + 1
    month = m_ + 3 - 12 * (m_ // 10)
    year = d_ - 4800 + m_ // 10
    return year, month, day


# -------------------------------------------------------------- timezone

def _offsets_at(instants_sec: jnp.ndarray, zone_id: str,
                wall_time: bool) -> jnp.ndarray:
    """UTC offset (seconds) at each instant.  wall_time=True treats the
    input as local wall seconds.  Wall boundaries use the offset BEFORE
    each transition (GpuTimeZoneDB.java:336-339 localInstant), so
    fall-back overlaps resolve to the earlier offset and spring-forward
    gaps to the later one — java.time ZoneRules semantics."""
    trans, offs = tzdb.get_transitions(zone_id)
    if wall_time:
        offs_before = np.concatenate([offs[:1], offs[:-1]])
        bounds = jnp.asarray(trans + offs_before)
    else:
        bounds = jnp.asarray(trans)
    idx = jnp.searchsorted(bounds, instants_sec, side="right") - 1
    idx = jnp.clip(idx, 0, len(offs) - 1)
    return jnp.asarray(offs)[idx]


def _floor_div(a, b):
    return a // b  # jnp int division is floor for int64


def convert_timestamp_to_utc(col: Column, zone_id: str) -> Column:
    """Wall-clock micros in `zone_id` -> UTC micros
    (timezones.hpp:2 convert_timestamp_to_utc)."""
    assert col.dtype.kind == Kind.TIMESTAMP_MICROS
    micros = col.data.astype(_I64)
    secs = _floor_div(micros, MICROS_PER_SEC)
    off = _offsets_at(secs, zone_id, wall_time=True)
    return Column(col.dtype, col.length,
                  data=micros - off * MICROS_PER_SEC,
                  validity=col.validity)


def convert_utc_timestamp_to_timezone(col: Column, zone_id: str) -> Column:
    """UTC micros -> wall-clock micros in `zone_id`
    (timezones.hpp convert_utc_timestamp_to_timezone)."""
    assert col.dtype.kind == Kind.TIMESTAMP_MICROS
    micros = col.data.astype(_I64)
    secs = _floor_div(micros, MICROS_PER_SEC)
    off = _offsets_at(secs, zone_id, wall_time=False)
    return Column(col.dtype, col.length,
                  data=micros + off * MICROS_PER_SEC,
                  validity=col.validity)


# ---------------------------------------------------------------- rebase

_GREG_START_DAYS = -141427  # 1582-10-15


def rebase_gregorian_to_julian(col: Column) -> Column:
    """Proleptic-Gregorian -> hybrid Julian/Gregorian calendar
    (datetime_rebase.cu; Spark rebaseGregorianToJulianDays/Micros).
    Dates on/after 1582-10-15 are unchanged; earlier dates keep their
    Y/M/D field values reinterpreted in the Julian calendar."""
    if col.dtype.kind == Kind.TIMESTAMP_DAYS:
        out = _rebase_days_g2j(col.data.astype(_I64))
        return Column(col.dtype, col.length, data=out.astype(_I32),
                      validity=col.validity)
    if col.dtype.kind == Kind.TIMESTAMP_MICROS:
        micros = col.data.astype(_I64)
        days = _floor_div(micros, MICROS_PER_SEC * SECS_PER_DAY)
        tod = micros - days * MICROS_PER_SEC * SECS_PER_DAY
        out_days = _rebase_days_g2j(days)
        return Column(col.dtype, col.length,
                      data=out_days * MICROS_PER_SEC * SECS_PER_DAY + tod,
                      validity=col.validity)
    raise ValueError("date or timestamp column required")


def _rebase_days_g2j(days: jnp.ndarray) -> jnp.ndarray:
    """Shared day computation for both rebase branches.  Dates INSIDE
    the cutover gap (1582-10-05..14) do not exist in the hybrid
    calendar: Spark clamps them to the Gregorian start day
    (datetime_rebase.cu:86-89); earlier dates reinterpret their Y/M/D
    in the Julian calendar; later dates are unchanged."""
    y, m, d = _days_to_ymd(days)
    jd = _julian_ymd_to_days(y, m, d)
    in_gap = (days >= _GREG_START_DAYS - 10) & (days < _GREG_START_DAYS)
    return jnp.where(days >= _GREG_START_DAYS, days,
                     jnp.where(in_gap, jnp.int64(_GREG_START_DAYS), jd))


def rebase_julian_to_gregorian(col: Column) -> Column:
    """Inverse rebase (datetime_rebase.cu)."""
    if col.dtype.kind == Kind.TIMESTAMP_DAYS:
        days = col.data.astype(_I64)
        y, m, d = _days_to_julian_ymd(days)
        gd = _ymd_to_days(y, m, d)
        out = jnp.where(days >= _GREG_START_DAYS, days, gd)
        return Column(col.dtype, col.length, data=out.astype(_I32),
                      validity=col.validity)
    if col.dtype.kind == Kind.TIMESTAMP_MICROS:
        micros = col.data.astype(_I64)
        days = _floor_div(micros, MICROS_PER_SEC * SECS_PER_DAY)
        tod = micros - days * MICROS_PER_SEC * SECS_PER_DAY
        y, m, d = _days_to_julian_ymd(days)
        gd = _ymd_to_days(y, m, d)
        out_days = jnp.where(days >= _GREG_START_DAYS, days, gd)
        return Column(col.dtype, col.length,
                      data=out_days * MICROS_PER_SEC * SECS_PER_DAY + tod,
                      validity=col.validity)
    raise ValueError("date or timestamp column required")


# -------------------------------------------------------------- truncate

_COMPONENTS = {
    "YEAR": "year", "YYYY": "year", "YY": "year",
    "QUARTER": "quarter",
    "MONTH": "month", "MON": "month", "MM": "month",
    "WEEK": "week",
    "DAY": "day", "DD": "day",
    "HOUR": "hour",
    "MINUTE": "minute",
    "SECOND": "second",
    "MILLISECOND": "millisecond",
    "MICROSECOND": "microsecond",
}


def truncate(col: Column, component: Union[str, Column]) -> Column:
    """Spark date_trunc / trunc (datetime_truncate.cu, DateTimeUtils.java:
    truncate).  Invalid components null the row; scalar or per-row
    component column."""
    if isinstance(component, Column):
        host_parts = [c if c in _COMPONENTS else None
                      for c in (None if v is None else str(v).upper()
                                for v in component.to_pylist())]
        mask = np.zeros(col.length, np.uint8)
        # one vectorized pass per distinct component
        result = np.zeros(col.length, np.int64)
        base_valid = np.asarray(col.valid_mask())
        for comp in set(c for c in host_parts if c):
            sel = np.array([c == comp for c in host_parts])
            sub = truncate(col, comp)
            result = np.where(sel, np.asarray(sub.data, dtype=np.int64),
                              result)
            mask = np.where(sel & base_valid, 1, mask).astype(np.uint8)
        np_dt = col.dtype.np_dtype
        return Column(col.dtype, col.length,
                      data=jnp.asarray(result.astype(np_dt)),
                      validity=jnp.asarray(mask))

    comp = _COMPONENTS.get(component.upper())
    if comp is None:
        raise ValueError(f"unsupported truncation component {component}")
    is_date = col.dtype.kind == Kind.TIMESTAMP_DAYS
    if is_date:
        days = col.data.astype(_I64)
        tod = jnp.zeros_like(days)
    else:
        micros = col.data.astype(_I64)
        day_us = MICROS_PER_SEC * SECS_PER_DAY
        days = _floor_div(micros, day_us)
        tod = micros - days * day_us

    if comp in ("year", "quarter", "month", "week"):
        y, m, d = _days_to_ymd(days)
        if comp == "year":
            nd = _ymd_to_days(y, jnp.ones_like(m), jnp.ones_like(m))
        elif comp == "quarter":
            qm = (m - 1) // 3 * 3 + 1
            nd = _ymd_to_days(y, qm, jnp.ones_like(m))
        elif comp == "month":
            nd = _ymd_to_days(y, m, jnp.ones_like(m))
        else:  # week: Monday
            dow = (days + 3) % 7  # 1970-01-01 is a Thursday
            nd = days - dow
        out_days, out_tod = nd, jnp.zeros_like(tod)
    else:
        unit = {"day": MICROS_PER_SEC * SECS_PER_DAY,
                "hour": MICROS_PER_SEC * 3600,
                "minute": MICROS_PER_SEC * 60,
                "second": MICROS_PER_SEC,
                "millisecond": 1000,
                "microsecond": 1}[comp]
        if is_date:
            out_days, out_tod = days, tod
        else:
            out_days = days
            out_tod = tod // unit * unit

    if is_date:
        return Column(col.dtype, col.length,
                      data=out_days.astype(_I32), validity=col.validity)
    day_us = MICROS_PER_SEC * SECS_PER_DAY
    return Column(col.dtype, col.length,
                  data=out_days * day_us + out_tod,
                  validity=col.validity)


def convert_orc_timezones(col: Column, writer_zone: str,
                          reader_zone: str) -> Column:
    """ORC timestamp rectification (timezones.hpp:24-31
    convert_orc_timezones, OrcTimezoneInfo.java): ORC stores wall-clock
    values in the writer's zone; shift each instant by the difference of
    the writer/reader offsets in effect at that instant so the reader's
    interpretation matches the writer's wall clock."""
    assert col.dtype.kind == Kind.TIMESTAMP_MICROS
    micros = col.data.astype(_I64)
    secs = _floor_div(micros, MICROS_PER_SEC)
    w_off = _offsets_at(secs, writer_zone, wall_time=False)
    r_off = _offsets_at(secs, reader_zone, wall_time=False)
    adjusted = micros + (w_off - r_off) * MICROS_PER_SEC
    # second reader lookup AT the adjusted instant: shifts landing across
    # a reader DST transition must use the post-shift offset
    # (timezones.cu convert_timestamp_between_timezones :340-348)
    r_off2 = _offsets_at(_floor_div(adjusted, MICROS_PER_SEC),
                         reader_zone, wall_time=False)
    return Column(col.dtype, col.length,
                  data=micros + (w_off - r_off2) * MICROS_PER_SEC,
                  validity=col.validity)
