"""Arithmetic ops: ANSI/TRY multiply with overflow, Spark round()
(reference multiply.cu/multiply.hpp, round_float.cu/round_float.hpp,
Arithmetic.java:45-185)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.ops.exceptions import ExceptionWithRowIndex
from spark_rapids_tpu.utils import floats

_I64 = jnp.int64

HALF_UP = "HALF_UP"
HALF_EVEN = "HALF_EVEN"


def _combined_validity(a: Column, b: Column):
    if a.validity is None and b.validity is None:
        return None
    return (a.valid_mask() & b.valid_mask()).astype(jnp.uint8)


def multiply(lhs: Column, rhs: Column, is_ansi_mode: bool = False,
             is_try_mode: bool = False) -> Column:
    """Element-wise multiply with Spark overflow semantics (multiply.hpp):
    regular mode wraps, TRY nulls overflow rows, ANSI throws
    ExceptionWithRowIndex at the first overflow row."""
    if is_ansi_mode and is_try_mode:
        raise ValueError("ANSI and TRY mode cannot both be enabled")
    if lhs.dtype != rhs.dtype:
        raise ValueError("multiply requires matching dtypes")
    kind = lhs.dtype.kind
    validity = _combined_validity(lhs, rhs)
    if kind in (Kind.FLOAT32, Kind.FLOAT64):
        if kind == Kind.FLOAT64:
            a = floats.bits_to_f64_compute(lhs.data)
            b = floats.bits_to_f64_compute(rhs.data)
            out = floats.f64_compute_to_bits(a * b)
        else:
            out = lhs.data * rhs.data
        return Column(lhs.dtype, lhs.length, data=out, validity=validity)
    # integral: compute wrapped product + overflow detection via division
    a = lhs.data.astype(_I64)
    b = rhs.data.astype(_I64)
    if kind == Kind.INT64:
        r = a * b  # wraps
        minv = jnp.int64(-2**63)
        ovf = ((a == -1) & (b == minv)) | ((b == -1) & (a == minv)) | \
            ((a != 0) & (lax.div(r, jnp.where(a == 0, jnp.int64(1), a))
                         != b))
        out = r
    else:
        info = np.iinfo(lhs.dtype.np_dtype)
        r = a * b  # exact in int64 for <=32-bit operands
        ovf = (r < info.min) | (r > info.max)
        out = r.astype(lhs.dtype.np_dtype)
    base_valid = (jnp.ones(lhs.length, jnp.bool_) if validity is None
                  else validity.astype(jnp.bool_))
    if is_ansi_mode:
        bad = np.asarray(base_valid & ovf)
        if bad.any():
            raise ExceptionWithRowIndex(int(np.argmax(bad)),
                                        "multiplication overflow")
        return Column(lhs.dtype, lhs.length, data=out, validity=validity)
    if is_try_mode:
        new_valid = (base_valid & ~ovf).astype(jnp.uint8)
        return Column(lhs.dtype, lhs.length, data=out, validity=new_valid)
    return Column(lhs.dtype, lhs.length, data=out, validity=validity)


def round_column(col: Column, decimal_places: int = 0,
                 method: str = HALF_UP) -> Column:
    """Spark round()/bround() (round_float.hpp): integers, floats,
    decimal32/64 (negated scale == decimal_places)."""
    if method not in (HALF_UP, HALF_EVEN):
        # unvalidated strings must not silently round the wrong way
        # (JNI callers pass the mode through verbatim)
        raise ValueError(f"unknown rounding method {method!r}; "
                         f"expected {HALF_UP!r} or {HALF_EVEN!r}")
    kind = col.dtype.kind
    if kind in (Kind.INT8, Kind.INT16, Kind.INT32, Kind.INT64):
        if decimal_places >= 0:
            return Column(col.dtype, col.length, data=col.data,
                          validity=col.validity)
        if -decimal_places > 18:  # 10^19 > int64 range: everything -> 0
            return Column(col.dtype, col.length,
                          data=jnp.zeros(col.length, col.dtype.np_dtype),
                          validity=col.validity)
        f = 10 ** (-decimal_places)
        v = col.data.astype(_I64)
        q = lax.div(v, _I64(f))
        rem = lax.rem(v, _I64(f))
        half = f // 2
        if method == HALF_UP:
            bump = (jnp.abs(rem) >= half).astype(_I64) * \
                jnp.where(v < 0, -1, 1)
        else:  # HALF_EVEN
            absr = jnp.abs(rem)
            tie = absr * 2 == f
            up = (absr * 2 > f) | (tie & (lax.rem(q, _I64(2)) != 0))
            bump = up.astype(_I64) * jnp.where(v < 0, -1, 1)
        out = ((q + bump) * f).astype(col.dtype.np_dtype)
        return Column(col.dtype, col.length, data=out,
                      validity=col.validity)
    if kind in (Kind.DECIMAL32, Kind.DECIMAL64):
        # rounding the unscaled value to the requested scale
        cur_places = -col.dtype.scale
        shift = cur_places - decimal_places
        if shift <= 0:
            return Column(col.dtype, col.length, data=col.data,
                          validity=col.validity)
        if shift > 18:  # beyond int64 unscaled range: everything -> 0
            return Column(col.dtype, col.length,
                          data=jnp.zeros(col.length, col.dtype.np_dtype),
                          validity=col.validity)
        f = 10 ** shift
        v = col.data.astype(_I64)
        q = lax.div(v, _I64(f))
        rem = lax.rem(v, _I64(f))
        half = f // 2
        if method == HALF_UP:
            up = jnp.abs(rem) >= half
        else:
            absr = jnp.abs(rem)
            tie = absr * 2 == f
            up = (absr * 2 > f) | (tie & (lax.rem(q, _I64(2)) != 0))
        bump = up.astype(_I64) * jnp.where(v < 0, -1, 1)
        out = ((q + bump) * f).astype(col.dtype.np_dtype)
        return Column(col.dtype, col.length, data=out,
                      validity=col.validity)
    if kind in (Kind.FLOAT32, Kind.FLOAT64):
        if kind == Kind.FLOAT64:
            x = floats.bits_to_f64_compute(col.data)
        else:
            x = col.data
        f = np.float64(10.0 ** decimal_places)
        scaled = x * f
        if method == HALF_UP:
            r = jnp.trunc(scaled + jnp.where(scaled >= 0, 0.5, -0.5))
        else:
            r = jnp.round(scaled)  # round-half-even
        out = r / f
        out = jnp.where(jnp.isfinite(x), out, x)
        if kind == Kind.FLOAT64:
            out = floats.f64_compute_to_bits(out)
        else:
            out = out.astype(jnp.float32)
        return Column(col.dtype, col.length, data=out,
                      validity=col.validity)
    raise NotImplementedError(f"round of {kind}")
