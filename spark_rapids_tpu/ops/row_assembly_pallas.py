"""Single-pass Pallas row-assembly kernel for JCUDF conversion.

The default `_assemble_fixed_words` path (row_conversion.py) composes
each output u32 word as an OR of shifted column vectors and relies on
XLA's `jnp.stack(words, axis=1)` to materialize the (rows, W) matrix —
measured ~59 GB/s of output on one v5e chip, a few x below the HBM
ceiling because the stack's strided stores pass through HBM.

This kernel instead builds each (BLOCK_ROWS, W) tile in VMEM: column
blocks stream in once in their NATIVE widths (u8/u16/u32 — the narrow
converts and shifts happen in-register), the word-stack transpose
happens in VMEM, and the tile is stored once.  The only pre-pass is
splitting 8-byte columns into u32 lo/hi halves (TPU vectors are 32-bit;
see docs/tpu_design.md §2 for why (rows, 2) u32 bitcasts are not safe
on this backend's tiling).

Reference counterpart: row_conversion.cu:591 copy_to_rows (shared-memory
tiled memcpy); the TPU shape is word-composition, not memcpy.

Opt-in until profiled on real hardware: set
SPARK_RAPIDS_TPU_PALLAS_ROWCONV=1 (row_conversion picks it up), or call
directly.  `interpret=True` runs anywhere (tests use the CPU backend).
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu.columns.column import Column

_U32 = jnp.uint32


def assemble_rows_pallas(inputs: Sequence[jnp.ndarray],
                         plan: Sequence[Tuple[int, int]],
                         rows: int, n_words: int,
                         block_rows: int = 512,
                         interpret: bool = False) -> jnp.ndarray:
    """Run the tile kernel; returns flat packed u32 LE words
    (rows * n_words,), same contract as _assemble_fixed_words."""
    import jax.experimental.pallas as pl

    br = min(block_rows, max(8, rows))

    def kernel(*refs):
        out_ref = refs[-1]
        words = [None] * n_words
        for r, (w, sh) in zip(refs[:-1], plan):
            v = r[:]
            if v.dtype != _U32:
                v = v.astype(_U32)
            if sh:
                v = v << _U32(sh)
            words[w] = v if words[w] is None else (words[w] | v)
        zeros = jnp.zeros((br,), _U32)
        tile = jnp.stack([w if w is not None else zeros
                          for w in words], axis=1)
        out_ref[:, :] = tile

    grid = (pl.cdiv(rows, br),)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((br,), lambda i: (i,)) for _ in inputs],
        out_specs=pl.BlockSpec((br, n_words), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, n_words), _U32),
        interpret=interpret,
    )(*inputs)
    return out.reshape(-1)


def assemble_fixed_words_pallas(cols, starts, validity_offset, row_size,
                                block_rows: int = 512,
                                interpret: bool = False) -> jnp.ndarray:
    """Drop-in replacement for row_conversion._assemble_fixed_words."""
    from spark_rapids_tpu.ops.row_conversion import build_plan

    rows = cols[0].length
    n_words = row_size // 4
    inputs, plan = build_plan(cols, starts, validity_offset, n_words)
    return assemble_rows_pallas(inputs, plan, rows, n_words,
                                block_rows=block_rows,
                                interpret=interpret)
