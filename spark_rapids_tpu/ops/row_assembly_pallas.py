"""Single-pass Pallas row-assembly kernel for JCUDF conversion.

The default `_assemble_fixed_words` path (row_conversion.py) composes
each output u32 word as an OR of shifted column vectors and relies on
XLA's `jnp.stack(words, axis=1)` to materialize the (rows, W) matrix —
measured ~59 GB/s of output on one v5e chip, a few x below the HBM
ceiling because the stack's strided stores pass through HBM.

This kernel instead builds each (BLOCK_ROWS, W) tile in VMEM: column
blocks stream in once in their NATIVE widths (u8/u16/u32 — the narrow
converts and shifts happen in-register), the word-stack transpose
happens in VMEM, and the tile is stored once.  The only pre-pass is
splitting 8-byte columns into u32 lo/hi halves (TPU vectors are 32-bit;
see docs/tpu_design.md §2 for why (rows, 2) u32 bitcasts are not safe
on this backend's tiling).

Reference counterpart: row_conversion.cu:591 copy_to_rows (shared-memory
tiled memcpy); the TPU shape is word-composition, not memcpy.

Both directions live here (r5): `assemble_rows_pallas` builds row
tiles (copy_to_rows), `disassemble_rows_pallas` streams the packed row
matrix through VMEM once and slices every column field out in-register
(copy_from_rows), and `paste_strings_pallas` gathers string payloads
into row tiles (the string variants, row_conversion.cu:71-73) instead
of scattering across the whole HBM matrix.

Opt-in until profiled on real hardware: set
SPARK_RAPIDS_TPU_PALLAS_ROWCONV=1 (row_conversion routes to-rows,
from-rows, and the string paste through these kernels), or call
directly.  `interpret=True` runs anywhere (tests use the CPU backend).
"""

from __future__ import annotations

from functools import partial
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu.columns.column import Column

_U32 = jnp.uint32


def assemble_rows_pallas(inputs: Sequence[jnp.ndarray],
                         plan: Sequence[Tuple[int, int]],
                         rows: int, n_words: int,
                         block_rows: int = 512,
                         interpret: bool = False) -> jnp.ndarray:
    """Run the tile kernel; returns flat packed u32 LE words
    (rows * n_words,), same contract as _assemble_fixed_words."""
    import jax.experimental.pallas as pl

    br = min(block_rows, max(8, rows))

    def kernel(*refs):
        out_ref = refs[-1]
        words = [None] * n_words
        for r, (w, sh) in zip(refs[:-1], plan):
            v = r[:]
            if v.dtype != _U32:
                v = v.astype(_U32)
            if sh:
                v = v << _U32(sh)
            words[w] = v if words[w] is None else (words[w] | v)
        zeros = jnp.zeros((br,), _U32)
        tile = jnp.stack([w if w is not None else zeros
                          for w in words], axis=1)
        out_ref[:, :] = tile

    grid = (pl.cdiv(rows, br),)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((br,), lambda i: (i,)) for _ in inputs],
        out_specs=pl.BlockSpec((br, n_words), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, n_words), _U32),
        interpret=interpret,
    )(*inputs)
    return out.reshape(-1)


def assemble_fixed_words_pallas(cols, starts, validity_offset, row_size,
                                block_rows: int = 512,
                                interpret: bool = False) -> jnp.ndarray:
    """Drop-in replacement for row_conversion._assemble_fixed_words.

    Routes through the process compile cache (perf/jit_cache.py) when
    enabled: column operands pad to the power-of-two row bucket,
    build_plan + the tile kernel trace once per (schema digest, bucket)
    and later batches in the same bucket reuse the executable."""
    from spark_rapids_tpu.ops.row_conversion import build_plan
    from spark_rapids_tpu.perf import jit_cache as _jc

    rows = cols[0].length
    n_words = row_size // 4
    traced = any(isinstance(c.data, jax.core.Tracer) for c in cols)
    if not _jc.cache_enabled() or rows == 0 or traced:
        inputs, plan = build_plan(cols, starts, validity_offset, n_words)
        return assemble_rows_pallas(inputs, plan, rows, n_words,
                                    block_rows=block_rows,
                                    interpret=interpret)

    from spark_rapids_tpu.columns.column import Column as _Col
    nullable = tuple(c.validity is not None for c in cols)
    schema_t = tuple(c.dtype for c in cols)
    starts_t = tuple(starts)
    digest = _jc.schema_digest(
        schema_t, nullable,
        extra=f"pallas_to:{row_size}:{block_rows}:{int(interpret)}")
    bucket = _jc.bucket_rows(rows)
    datas = tuple(_jc.pad_axis0(c.data, bucket) for c in cols)
    valids = tuple(None if c.validity is None
                   else _jc.pad_axis0(c.validity, bucket) for c in cols)

    def kernel(datas, valids):
        kcols = [_Col(dt, bucket, data=d, validity=v)
                 for dt, d, v in zip(schema_t, datas, valids)]
        inputs, plan = build_plan(kcols, starts_t, validity_offset,
                                  n_words)
        return assemble_rows_pallas(inputs, plan, bucket, n_words,
                                    block_rows=block_rows,
                                    interpret=interpret)

    out = _jc.CACHE.cached_call("pallas.to_rows", digest, kernel,
                                (datas, valids), bucket=bucket,
                                donate_argnums=(0,))
    return out[: rows * n_words]


# ------------------------------------------------- from-rows direction


def disassemble_rows_pallas(words: jnp.ndarray,
                            extract_plan: Sequence[Tuple[int, int, int]],
                            block_rows: int = 512,
                            interpret: bool = False):
    """Inverse tile kernel (row_conversion.cu:591 copy_from_rows
    counterpart): the (rows, W) packed word matrix streams through
    VMEM once per row tile and every extraction — (word, shift, nbits)
    — slices its field out in-register.  Returns one (rows,) u32 array
    per plan entry.

    One HBM read of the row matrix feeds ALL column extractions (the
    default gather path reads the byte buffer once per column)."""
    import jax.experimental.pallas as pl

    rows, n_words = words.shape
    br = min(block_rows, max(8, rows))

    def kernel(in_ref, *out_refs):
        tile = in_ref[:, :]
        for ref, (w, sh, nbits) in zip(out_refs, extract_plan):
            v = tile[:, w]
            if sh:
                v = v >> _U32(sh)
            if nbits < 32:
                v = v & _U32((1 << nbits) - 1)
            ref[:] = v

    grid = (pl.cdiv(rows, br),)
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((br, n_words), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((br,), lambda i: (i,))
                   for _ in extract_plan],
        out_shape=[jax.ShapeDtypeStruct((rows,), _U32)
                   for _ in extract_plan],
        interpret=interpret,
    )(words)
    return outs


def build_extract_plan(schema, starts, validity_offset, n_words):
    """Per-logical-field (word, shift, nbits) extraction entries for
    a fixed-width JCUDF schema + per-column validity entries.  Field
    coordinates come from row_conversion.field_word_slots — the SAME
    layout source the assembly direction consumes."""
    from spark_rapids_tpu.ops.row_conversion import field_word_slots

    plan: List[Tuple[int, int, int]] = []
    col_entries: List[List[int]] = []
    for dt, st in zip(schema, starts):
        entries = []
        for slot in field_word_slots(dt, st):
            entries.append(len(plan))
            plan.append(slot)
        col_entries.append(entries)
    valid_entries: List[int] = []
    for ci in range(len(schema)):
        off = validity_offset + ci // 8
        valid_entries.append(len(plan))
        plan.append((off // 4, (off % 4) * 8 + (ci % 8), 1))
    assert all(w < n_words for w, _sh, _nb in plan)
    return plan, col_entries, valid_entries


def convert_from_rows_pallas(list_col: Column, schema,
                             block_rows: int = 512,
                             interpret: bool = False):
    """Fixed-width-schema from-rows over the tile kernel; returns a
    Table matching row_conversion.convert_from_rows bit-for-bit.
    Requires uniform row sizes (fixed-width schemas have them)."""
    from spark_rapids_tpu.columns.dtypes import Kind
    from spark_rapids_tpu.columns.table import Table
    from spark_rapids_tpu.ops.row_conversion import (
        _col_byte_size, compute_layout, _round_up, JCUDF_ROW_ALIGNMENT)

    rows = list_col.length
    starts, validity_offset, fixed_size = compute_layout(schema)
    row_size = _round_up(fixed_size, JCUDF_ROW_ALIGNMENT)
    n_words = row_size // 4
    child = list_col.children[0]
    words = child.data
    assert words.dtype == _U32, "packed u32 word buffer expected"
    if int(words.size) != rows * n_words:
        raise ValueError(
            f"row buffer holds {int(words.size)} words, schema needs "
            f"{rows}x{n_words} uniform rows")
    mat = words.reshape(rows, n_words)
    plan, col_entries, valid_entries = build_extract_plan(
        schema, starts, validity_offset, n_words)
    from spark_rapids_tpu.perf import jit_cache as _jc
    if (_jc.cache_enabled() and rows > 0
            and not isinstance(mat, jax.core.Tracer)):
        # bucketed + compile-cached tile disassembly: pad the row
        # matrix (padded rows decode to garbage sliced off below)
        bucket = _jc.bucket_rows(rows)
        mat_p = _jc.pad_axis0(mat, bucket)
        digest = _jc.schema_digest(
            schema,
            extra=f"pallas_from:{row_size}:{block_rows}:{int(interpret)}")

        def kernel(mat_p):
            return tuple(disassemble_rows_pallas(
                mat_p, plan, block_rows=block_rows, interpret=interpret))

        pieces_b = _jc.CACHE.cached_call(
            "pallas.from_rows", digest, kernel, (mat_p,),
            bucket=bucket, donate_argnums=(0,))
        pieces = [p[:rows] for p in pieces_b]
    else:
        pieces = disassemble_rows_pallas(mat, plan,
                                         block_rows=block_rows,
                                         interpret=interpret)
    out_cols = []
    for ci, dt in enumerate(schema):
        es = [pieces[e] for e in col_entries[ci]]
        kind = dt.kind
        size = _col_byte_size(dt)
        if kind == Kind.DECIMAL128:
            data = lax.bitcast_convert_type(
                jnp.stack(es, axis=1), jnp.int32)
        elif size == 8:
            u = (es[0].astype(jnp.uint64)
                 | (es[1].astype(jnp.uint64) << jnp.uint64(32)))
            # FLOAT64 stays raw-bits u64 (columns convention)
            data = (u if kind == Kind.FLOAT64
                    else lax.bitcast_convert_type(
                        u, jnp.dtype(dt.np_dtype)))
        elif size == 4:
            data = lax.bitcast_convert_type(es[0],
                                            jnp.dtype(dt.np_dtype))
        elif size == 2:
            data = lax.bitcast_convert_type(
                es[0].astype(jnp.uint16), jnp.dtype(dt.np_dtype))
        else:
            data = lax.bitcast_convert_type(
                es[0].astype(jnp.uint8), jnp.dtype(dt.np_dtype))
        valid = pieces[valid_entries[ci]].astype(jnp.uint8)
        out_cols.append(Column(dt, rows, data=data, validity=valid))
    return Table(out_cols)


# ------------------------------------------- string payload tiling


def paste_strings_pallas(mat: jnp.ndarray, chars: jnp.ndarray,
                         vstart: jnp.ndarray, lens: jnp.ndarray,
                         block_rows: int = 256,
                         interpret: bool = False) -> jnp.ndarray:
    """Tile-resident string-payload paste for the variable-width
    to-rows path (row_conversion.cu:71-73 string copy counterpart):
    for each output byte position p of a row tile, the value is
    chars[r, p - vstart[r]] when p falls in the row's payload span,
    else the existing fixed-section byte.  The gather happens in VMEM
    per tile — the XLA fallback (_masked_row_scatter) materializes a
    scatter over the whole (rows, max_row) matrix in HBM."""
    import jax.experimental.pallas as pl

    rows, max_row = mat.shape
    pad = chars.shape[1]
    br = min(block_rows, max(8, rows))

    def kernel(mat_ref, ch_ref, vs_ref, ln_ref, out_ref):
        base = mat_ref[:, :]
        ch = ch_ref[:, :]
        vs = vs_ref[:]
        ln = ln_ref[:]
        p = lax.broadcasted_iota(jnp.int32, (br, max_row), 1)
        src = p - vs[:, None]
        in_span = (src >= 0) & (src < ln[:, None]) & (src < pad)
        gathered = jnp.take_along_axis(
            ch, jnp.clip(src, 0, pad - 1), axis=1)
        out_ref[:, :] = jnp.where(in_span, gathered, base)

    grid = (pl.cdiv(rows, br),)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((br, max_row), lambda i: (i, 0)),
                  pl.BlockSpec((br, pad), lambda i: (i, 0)),
                  pl.BlockSpec((br,), lambda i: (i,)),
                  pl.BlockSpec((br,), lambda i: (i,))],
        out_specs=pl.BlockSpec((br, max_row), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, max_row), mat.dtype),
        interpret=interpret,
    )(mat, chars, vstart.astype(jnp.int32), lens.astype(jnp.int32))
