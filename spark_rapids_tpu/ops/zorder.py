"""Z-order and Hilbert clustering indexes for Delta/Iceberg OPTIMIZE
(reference zorder.cu/zorder.hpp, ZOrder.java).

interleave_bits: rows of N same-typed fixed-width columns -> per-row byte
blob of bit-interleaved values, MSB of column 0 first (zorder.cu kernel
:160-190 bit ordering).  hilbert_index: N INT32 columns -> INT64 Hilbert
curve index via the Skilling transform (zorder.cu:92-150).

TPU design: both are pure bit-shuffles — expressed as (rows, bits)
boolean tensors reshaped/packed with static index maps, fully fused by
XLA; the Skilling loops are static python loops of vector ops.
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind

_U8 = jnp.uint8
_U32 = jnp.uint32
_U64 = jnp.uint64
_I32 = jnp.int32
_I64 = jnp.int64


def _value_bits_msb(col: Column) -> jnp.ndarray:
    """(rows, 8*size) bool bits, most significant first; null rows are 0."""
    kind = col.dtype.kind
    if kind == Kind.FLOAT32:
        from jax import lax
        u = lax.bitcast_convert_type(col.data, _U32).astype(_U64)
        nbits = 32
    elif kind == Kind.FLOAT64:
        u = col.data.astype(_U64)  # raw bits representation
        nbits = 64
    else:
        size = col.dtype.size_bytes
        nbits = 8 * size
        u = col.data.astype(jnp.int64).astype(_U64)
        if nbits < 64:
            u = u & _U64((1 << nbits) - 1)
    if col.validity is not None:
        u = jnp.where(col.validity.astype(jnp.bool_), u, _U64(0))
    shifts = jnp.arange(nbits - 1, -1, -1, dtype=_U64)
    return ((u[:, None] >> shifts[None, :]) & _U64(1)).astype(jnp.bool_)


def interleave_bits(cols: Sequence[Column]) -> Column:
    """LIST<UINT8> column: per-row interleaved bits of all columns
    (ZOrder.interleaveBits)."""
    if not cols:
        raise ValueError("The input table must have at least one column.")
    t0 = cols[0].dtype
    if not t0.is_fixed_width:
        raise ValueError("Only fixed width columns can be used")
    if any(c.dtype != t0 for c in cols):
        raise ValueError("All columns of the input table must be the same "
                         "type.")
    rows = cols[0].length
    nc = len(cols)
    bits = jnp.stack([_value_bits_msb(c) for c in cols], axis=1)
    # (rows, nc, B) -> output bit b*nc + c = bits[:, c, b]
    inter = jnp.transpose(bits, (0, 2, 1)).reshape(rows, -1)
    # pack MSB-first into bytes
    nbytes = inter.shape[1] // 8
    grouped = inter.reshape(rows, nbytes, 8).astype(_U8)
    weights = (_U8(1) << jnp.arange(7, -1, -1, dtype=_U8))[None, None, :]
    packed = (grouped * weights).sum(axis=2, dtype=jnp.uint32).astype(_U8)
    data = packed.reshape(-1)
    offsets = jnp.arange(rows + 1, dtype=_I32) * _I32(nbytes)
    return Column.make_list_from_parts(offsets, data)


def hilbert_index(num_bits: int, cols: Sequence[Column]) -> Column:
    """INT64 Hilbert index of N INT32 coordinate columns (zorder.hpp:34;
    Skilling transform per zorder.cu)."""
    if not cols:
        raise ValueError("at least one column is required.")
    if any(c.dtype.kind != Kind.INT32 for c in cols):
        raise ValueError("All columns of the input table must be INT32.")
    if not 0 < num_bits <= 32:
        raise ValueError("the number of bits must be >0 and <= 32")
    if num_bits * len(cols) > 64:
        raise ValueError("num_bits * num_columns must be <= 64")
    n = len(cols)
    mask_val = _U32((1 << num_bits) - 1)
    x: List[jnp.ndarray] = []
    for c in cols:
        u = c.data.astype(_U32) & mask_val
        if c.validity is not None:
            u = jnp.where(c.validity.astype(jnp.bool_), u, _U32(0))
        x.append(u)

    m = 1 << (num_bits - 1)
    # Inverse undo (zorder.cu:104-115)
    q = m
    while q > 1:
        p = _U32(q - 1)
        for i in range(n):
            cond = (x[i] & _U32(q)) != 0
            t = (x[0] ^ x[i]) & p
            new_x0 = jnp.where(cond, x[0] ^ p, x[0] ^ t)
            new_xi = jnp.where(cond, x[i], x[i] ^ t)
            x[0] = new_x0
            x[i] = new_xi if i != 0 else x[0]
        q >>= 1
    # Gray encode
    for i in range(1, n):
        x[i] = x[i] ^ x[i - 1]
    t = jnp.zeros_like(x[0])
    q = m
    while q > 1:
        t = jnp.where((x[n - 1] & _U32(q)) != 0, t ^ _U32(q - 1), t)
        q >>= 1
    for i in range(n):
        x[i] = x[i] ^ t

    # interleave transposed bits (to_hilbert_index zorder.cu:58-73)
    out = jnp.zeros(cols[0].length, _U64)
    b_index = num_bits * n - 1
    mask = 1 << (num_bits - 1)
    for _ in range(num_bits):
        for j in range(n):
            bit = ((x[j] & _U32(mask)) != 0).astype(_U64)
            out = out | (bit << _U64(b_index))
            b_index -= 1
        mask >>= 1
    return Column(dtypes.INT64, cols[0].length, data=out.astype(_I64))
