"""SHA-2 family hashes with null preservation + host CRC32.

Reference: src/main/cpp/src/hash/sha.cpp (sha224/256/384/512_nulls_preserved
— hex-digest string output, input nulls preserved as nulls) and
HashJni.cpp:134-157 (hostCrc32 — zlib crc32 over a host buffer, used for
shuffle block checksums).

TPU note: per-row messages are independent, so SHA vectorizes as one
lane per row — ops/sha_device.py runs the block compression for every
row simultaneously with a lax.scan over message blocks.  Columns at or
above DEVICE_MIN_ROWS route there (override with SPARK_RAPIDS_TPU_SHA=
host|device); tiny columns use the hashlib host path, which doubles as
the differential oracle.  CRC32 stays host zlib — the same decision the
reference makes (HashJni.cpp hostCrc32).
"""

from __future__ import annotations

import hashlib
import os
import zlib
from typing import Optional, Union

import numpy as np

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind

DEVICE_MIN_ROWS = int(os.environ.get("SPARK_RAPIDS_TPU_SHA_MIN_ROWS", 32))


def _use_device(col: Column) -> bool:
    mode = os.environ.get("SPARK_RAPIDS_TPU_SHA", "auto")
    if mode == "host":
        return False
    if not (col.dtype.is_string or col.dtype.is_fixed_width):
        return False
    return mode == "device" or col.length >= DEVICE_MIN_ROWS


def _row_bytes(col: Column):
    """Yield per-row byte strings (None for null rows)."""
    mask = (np.ones(col.length, bool) if col.validity is None
            else np.asarray(col.validity).astype(bool)[: col.length])
    if col.dtype.is_string:
        chars = np.asarray(col.data).tobytes() if col.data is not None else b""
        offs = np.asarray(col.offsets)
        for i in range(col.length):
            yield chars[offs[i]: offs[i + 1]] if mask[i] else None
    elif col.dtype.is_fixed_width:
        host = np.asarray(col.data)
        for i in range(col.length):
            yield host[i].tobytes() if mask[i] else None
    else:
        raise NotImplementedError(f"sha of {col.dtype.kind}")


def _sha_impl(algo_name: str, col: Column) -> Column:
    out = []
    for b in _row_bytes(col):
        out.append(None if b is None
                   else hashlib.new(algo_name, b).hexdigest())
    return Column.from_strings(out)


def _sha(algo_name: str, bits: int, col: Column) -> Column:
    if _use_device(col):
        from spark_rapids_tpu.ops import sha_device
        return sha_device._sha_device(col, bits)
    return _sha_impl(algo_name, col)


def sha224_nulls_preserved(col: Column) -> Column:
    return _sha("sha224", 224, col)


def sha256_nulls_preserved(col: Column) -> Column:
    return _sha("sha256", 256, col)


def sha384_nulls_preserved(col: Column) -> Column:
    return _sha("sha384", 384, col)


def sha512_nulls_preserved(col: Column) -> Column:
    return _sha("sha512", 512, col)


def host_crc32(crc: int, buffer: Optional[Union[bytes, np.ndarray]],
               length: Optional[int] = None) -> int:
    """zlib CRC32 over a host buffer (reference Hash.hostCrc32).  `buffer`
    may be None only when length is 0."""
    if buffer is None:
        if length not in (0, None):
            raise ValueError("len is not zero for empty buffer")
        return crc & 0xFFFFFFFF
    # raw buffer bytes, like the reference's unsigned char* + len
    data = buffer.tobytes() if isinstance(buffer, np.ndarray) else \
        bytes(buffer)
    if length is not None:
        data = data[:length]
    return zlib.crc32(data, crc) & 0xFFFFFFFF
