"""SHA-2 family hashes with null preservation + host CRC32.

Reference: src/main/cpp/src/hash/sha.cpp (sha224/256/384/512_nulls_preserved
— hex-digest string output, input nulls preserved as nulls) and
HashJni.cpp:134-157 (hostCrc32 — zlib crc32 over a host buffer, used for
shuffle block checksums).

TPU note: SHA is a bit-serial algorithm with no vector parallelism per
message; per-row messages are independent, so a Pallas lane-per-row SHA-256
is feasible but low-value (Spark uses sha for checksumming, not joins).
This implementation computes digests on host via hashlib — the same
host-path decision the reference makes for CRC32.
"""

from __future__ import annotations

import hashlib
import zlib
from typing import Optional, Union

import numpy as np

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind


def _row_bytes(col: Column):
    """Yield per-row byte strings (None for null rows)."""
    mask = (np.ones(col.length, bool) if col.validity is None
            else np.asarray(col.validity).astype(bool)[: col.length])
    if col.dtype.is_string:
        chars = np.asarray(col.data).tobytes() if col.data is not None else b""
        offs = np.asarray(col.offsets)
        for i in range(col.length):
            yield chars[offs[i]: offs[i + 1]] if mask[i] else None
    elif col.dtype.is_fixed_width:
        host = np.asarray(col.data)
        for i in range(col.length):
            yield host[i].tobytes() if mask[i] else None
    else:
        raise NotImplementedError(f"sha of {col.dtype.kind}")


def _sha_impl(algo_name: str, col: Column) -> Column:
    out = []
    for b in _row_bytes(col):
        out.append(None if b is None
                   else hashlib.new(algo_name, b).hexdigest())
    return Column.from_strings(out)


def sha224_nulls_preserved(col: Column) -> Column:
    return _sha_impl("sha224", col)


def sha256_nulls_preserved(col: Column) -> Column:
    return _sha_impl("sha256", col)


def sha384_nulls_preserved(col: Column) -> Column:
    return _sha_impl("sha384", col)


def sha512_nulls_preserved(col: Column) -> Column:
    return _sha_impl("sha512", col)


def host_crc32(crc: int, buffer: Optional[Union[bytes, np.ndarray]],
               length: Optional[int] = None) -> int:
    """zlib CRC32 over a host buffer (reference Hash.hostCrc32).  `buffer`
    may be None only when length is 0."""
    if buffer is None:
        if length not in (0, None):
            raise ValueError("len is not zero for empty buffer")
        return crc & 0xFFFFFFFF
    # raw buffer bytes, like the reference's unsigned char* + len
    data = buffer.tobytes() if isinstance(buffer, np.ndarray) else \
        bytes(buffer)
    if length is not None:
        data = data[:length]
    return zlib.crc32(data, crc) & 0xFFFFFFFF
