"""Device protobuf decoder: vectorized wire-format parse on TPU.

Reference: src/main/cpp/src/protobuf/protobuf_kernels.cu:1-1062 (+
protobuf.cu 1,361, protobuf_builders.cu 623) — thread-per-row varint /
wire-type parsing kernels feeding struct builders.  The TPU design
replaces thread-per-row pointer chasing with ONE field-step loop over
all rows simultaneously (the masked-scan shape this repo uses for stod /
ftos / SHA / JSON / kudo):

  * every row carries a cursor into its padded byte lane;
  * each `lax.while_loop` step consumes exactly one tag+payload record
    per active row: two bounded varint reads (10-byte gather windows,
    lane-masked shifts — no data-dependent loops), a wire-type dispatch
    for the next cursor, and unrolled per-schema-field capture selects
    (proto3 last-value-wins);
  * steps run until every row is done or malformed — the trip count is
    the max field count per message, not the byte length.

Scope of the device path (router below): scalar
bool/int32/int64/float32/float64/string fields, DEFAULT/FIXED/ZIGZAG
encodings, optional/required, defaults (string included), and arbitrarily
NESTED messages — a nested message is a LEN capture whose payload
spans become a child binary column the decode recurses on, the
masked-scan re-design of the reference's nested_field_descriptor
walk (protobuf.hpp:26-67) — and REPEATED
scalar/string fields: every occurrence lands in a per-row register
bank (unpacked records one per step; PACKED payloads via a cursor
state machine consuming one element per step), with rows exceeding
the occurrence capacity falling back whole-column.  Repeated
MESSAGES recurse too: occurrence spans flatten into one child binary
column, decode once, and wrap back as LIST<STRUCT>.  String defaults
splice into unseen rows at finalize.  The host oracle
(ops/protobuf.py) is the differential reference for everything here.

Divergence note (shared with json_device): STRING payloads pass raw
bytes through on device while the host oracle substitutes U+FFFD for
invalid UTF-8 — Spark strings are UTF-8, so this is out of contract.

Spark semantics parity with the host decoder:
  * unknown fields / wire-type mismatches are skipped by wire type;
  * truncated varints (no terminator in-row or within 10 bytes),
    truncated payloads, and group/invalid wire types null the row;
  * missing required fields null the row (proto2);
  * missing optional fields take the schema default, else null.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind

_I32 = jnp.int32
_U8 = jnp.uint8
_U32 = jnp.uint32
_U64 = jnp.uint64
_B = jnp.bool_

DEVICE_ROW_CHUNK = 1 << 17

# wire types (protobuf encoding spec)
_VARINT, _I64BIT, _LEN, _I32BIT = 0, 1, 2, 5


def supported_schema(fields) -> bool:
    """True when the device engine can decode this schema: scalar
    leaves (repeated included — packed or unpacked), strings, and
    arbitrarily nested messages INCLUDING repeated ones — a nested
    message is a LEN span (banked per occurrence when repeated) that
    becomes a child binary column the decode recurses on
    (protobuf.hpp:26-67 nested_field_descriptor re-designed for the
    masked-scan engine)."""
    from spark_rapids_tpu.ops.protobuf import DEFAULT, FIXED, ZIGZAG
    for f in fields:
        if f.field_number <= 0 or f.field_number >= (1 << 29):
            return False
        if f.is_message:
            if not supported_schema(f.children):
                return False
            continue
        if f.dtype.kind not in (Kind.BOOL8, Kind.INT32, Kind.INT64,
                                Kind.FLOAT32, Kind.FLOAT64,
                                Kind.STRING):
            return False
        if f.encoding not in (DEFAULT, FIXED, ZIGZAG):
            return False
    return True


# repeated-scalar occurrence capacity per row: rows exceeding it make
# the whole decode fall back to the host oracle (rare; configurable)
def _repeat_cap() -> int:
    return int(os.environ.get(
        "SPARK_RAPIDS_TPU_PROTOBUF_REPEAT_CAP", "32"))


def _expected_wire(f) -> int:
    from spark_rapids_tpu.ops.protobuf import FIXED
    if f.is_message:
        return _LEN
    kind = f.dtype.kind
    if kind == Kind.STRING:
        return _LEN
    if f.encoding == FIXED:
        return _I64BIT if kind in (Kind.INT64, Kind.FLOAT64) else _I32BIT
    if kind == Kind.FLOAT64:
        return _I64BIT
    if kind == Kind.FLOAT32:
        return _I32BIT
    return _VARINT


# lane shift table for varint assembly: lane i contributes bits
# (b & 0x7f) << 7i, masked to 64 bits (lane 9 only bit 63 survives —
# same wrap the host decoder applies)
_V_SHIFTS = tuple(min(7 * i, 63) for i in range(10))
_V_MASKS = tuple(0x7F if 7 * i <= 56 else (1 << (64 - 7 * i)) - 1
                 for i in range(9)) + (0x01,)


def _read_varint_at(chars: jnp.ndarray, pos: jnp.ndarray,
                    row_len: jnp.ndarray):
    """Vectorized varint read for every row at `pos` (row-relative).

    Returns (value u64, nbytes i32, ok bool).  ok=False when the varint
    has no terminator within 10 bytes or runs past the row end."""
    L = chars.shape[1]
    idx = pos[:, None] + jnp.arange(10, dtype=_I32)[None, :]
    win = jnp.take_along_axis(
        chars, jnp.clip(idx, 0, L - 1), axis=1)          # (R, 10)
    win = jnp.where(idx < row_len[:, None], win, _U8(0))  # OOB: treat
    is_term = (win & _U8(0x80)) == 0                      # as 0x00
    has_term = jnp.any(is_term, axis=1)
    nbytes = jnp.argmax(is_term, axis=1).astype(_I32) + 1
    lane = jnp.arange(10, dtype=_I32)[None, :]
    used = lane < nbytes[:, None]
    contrib = jnp.zeros(chars.shape[0], _U64)
    w64 = win.astype(_U64)
    for i in range(10):
        part = (w64[:, i] & _U64(_V_MASKS[i])) << _U64(_V_SHIFTS[i])
        contrib = contrib | jnp.where(used[:, i], part, _U64(0))
    ok = has_term & (pos + nbytes <= row_len) & (pos >= 0)
    return contrib, nbytes, ok


def _read_fixed(chars: jnp.ndarray, pos: jnp.ndarray,
                row_len: jnp.ndarray, nbytes: int):
    """Little-endian fixed32/64 load per row -> u64 (zero-extended)."""
    L = chars.shape[1]
    idx = pos[:, None] + jnp.arange(nbytes, dtype=_I32)[None, :]
    win = jnp.take_along_axis(chars, jnp.clip(idx, 0, L - 1), axis=1)
    win = jnp.where(idx < row_len[:, None], win, _U8(0))
    val = jnp.zeros(chars.shape[0], _U64)
    for i in range(nbytes):
        val = val | (win[:, i].astype(_U64) << _U64(8 * i))
    return val


def _decode_chunk(chars: jnp.ndarray, lens: jnp.ndarray, specs):
    """One jitted decode over a (R, L) padded byte chunk.

    specs: static tuple of (field_number, expected_wire, strict,
    repeated, cap) per field.  strict fields (nested messages) malform
    the row on a wire mismatch; repeated fields capture EVERY
    occurrence into a (R, cap) register bank — unpacked records one
    per step, PACKED payloads via a cursor state machine that consumes
    one element per step inside the payload span (the host's
    `while pos < end` loop, including its tolerated last-element
    overrun).  Returns (malformed, per-field last-value captures,
    seen, per-repeated-field counts, per-repeated-field value banks).
    """
    R = chars.shape[0]
    L = chars.shape[1]
    F = len(specs)
    rep_idx = [k for k, sp in enumerate(specs) if sp[3]]
    any_rep = bool(rep_idx)
    # packed varint elements can be 1 byte each: bound steps by L
    max_steps = (L + 2) if any_rep else (L // 2 + 2)
    cap = max([specs[k][4] for k in rep_idx], default=1)
    lane = jnp.arange(cap, dtype=_I32)[None, :]

    def cond(state):
        i, c, malformed = state[0], state[1], state[2]
        active = (~malformed) & (c < lens)
        return (i < max_steps) & jnp.any(active)

    def body(state):
        (i, c, malformed, packed_end, packed_k, vals, seen, rcnt,
         rvals) = state
        active = (~malformed) & (c < lens)
        packed_now = active & (packed_end > 0)
        norm = active & ~packed_now

        # ---- packed-mode element read at c ----
        pv_e, pn_e, pok_e = _read_varint_at(chars, c, lens)
        f64_e = _read_fixed(chars, c, lens, 8)
        f32_e = _read_fixed(chars, c, lens, 4)
        elem_val = jnp.zeros(R, _U64)
        elem_bytes = jnp.zeros(R, _I32)
        elem_ok = jnp.zeros(R, _B)
        for k in rep_idx:
            ewire = specs[k][1]
            if ewire == _LEN:
                continue          # strings are never packed
            mk = packed_now & (packed_k == k)
            if ewire == _VARINT:
                v, nb, ok = pv_e, pn_e, pok_e
            elif ewire == _I64BIT:
                v, nb, ok = f64_e, jnp.full(R, 8, _I32), c + 8 <= lens
            else:
                v, nb, ok = f32_e, jnp.full(R, 4, _I32), c + 4 <= lens
            elem_val = jnp.where(mk, v, elem_val)
            elem_bytes = jnp.where(mk, nb, elem_bytes)
            elem_ok = jnp.where(mk, ok, elem_ok)
        packed_c_new = c + elem_bytes
        packed_exit = packed_now & (packed_c_new >= packed_end)
        new_malformed = malformed | (packed_now & ~elem_ok)

        # ---- normal tag parse (non-packed rows) ----
        tag, tlen, tag_ok = _read_varint_at(chars, c, lens)
        wire = (tag & _U64(7)).astype(_I32)
        num = (tag >> _U64(3)).astype(_I32)
        s = c + tlen

        pval, plen, p_ok = _read_varint_at(chars, s, lens)
        plen_bytes = jnp.minimum(pval, _U64(1 << 30)).astype(_I32)

        nxt = jnp.where(
            wire == _VARINT, s + plen,
            jnp.where(wire == _I64BIT, s + 8,
                      jnp.where(wire == _I32BIT, s + 4,
                                s + plen + plen_bytes)))
        wire_ok = ((wire == _VARINT) | (wire == _I64BIT)
                   | (wire == _I32BIT) | (wire == _LEN))
        need_pv = (wire == _VARINT) | (wire == _LEN)
        step_ok = (tag_ok & wire_ok & (~need_pv | p_ok)
                   & (nxt <= lens))

        new_malformed = new_malformed | (norm & ~step_ok)
        capture = norm & step_ok

        f64 = _read_fixed(chars, s, lens, 8)
        f32 = _read_fixed(chars, s, lens, 4)
        str_pack = ((s + plen).astype(_U64) << _U64(32)) | \
            jnp.minimum(pval, _U64(0xFFFFFFFF))

        new_vals = list(vals)
        new_seen = list(seen)
        new_rcnt = list(rcnt)
        new_rvals = list(rvals)
        new_packed_end = jnp.where(packed_exit, 0, packed_end)
        new_packed_k = packed_k
        c_norm = jnp.where(capture, jnp.maximum(nxt, c + 1), c)
        for k, (fnum, ewire, strict, repeated, _cap) in \
                enumerate(specs):
            if strict:
                # message fields: wire mismatch malforms the row
                new_malformed = new_malformed | (
                    capture & (num == fnum) & (wire != ewire))
            if ewire == _VARINT:
                v = pval
            elif ewire == _I64BIT:
                v = f64
            elif ewire == _I32BIT:
                v = f32
            else:
                v = str_pack
            if not repeated:
                match = capture & (num == fnum) & (wire == ewire)
                new_vals[k] = jnp.where(match, v, vals[k])
                new_seen[k] = seen[k] | match
                continue
            r = rep_idx.index(k)
            # occurrence capture: unpacked record OR packed element
            rec = capture & (num == fnum) & (wire == ewire)
            pel = packed_now & (packed_k == k) & elem_ok
            occ = rec | pel
            val = jnp.where(pel, elem_val, v)
            write = (occ[:, None]
                     & (lane == new_rcnt[r][:, None]))
            new_rvals[r] = jnp.where(write, val[:, None],
                                     new_rvals[r])
            new_rcnt[r] = new_rcnt[r] + occ.astype(_I32)
            new_seen[k] = seen[k] | occ
            if ewire != _LEN:
                # packed-record entry: step into the payload.  An
                # EMPTY packed payload still marks the field seen
                # (host: out.setdefault(num, []) runs for n=0), it
                # just never enters the element state machine.
                packed_rec = (capture & (num == fnum)
                              & (wire == _LEN))
                enter = packed_rec & (plen_bytes > 0)
                new_packed_end = jnp.where(enter,
                                           s + plen + plen_bytes,
                                           new_packed_end)
                new_packed_k = jnp.where(enter, k, new_packed_k)
                c_norm = jnp.where(enter, s + plen, c_norm)
                new_seen[k] = new_seen[k] | packed_rec

        c_new = jnp.where(packed_now, packed_c_new, c_norm)
        return (i + 1, c_new, new_malformed, new_packed_end,
                new_packed_k, tuple(new_vals), tuple(new_seen),
                tuple(new_rcnt), tuple(new_rvals))

    state0 = (jnp.int32(0), jnp.zeros(R, _I32), jnp.zeros(R, _B),
              jnp.zeros(R, _I32), jnp.zeros(R, _I32),
              tuple(jnp.zeros(R, _U64) for _ in range(F)),
              tuple(jnp.zeros(R, _B) for _ in range(F)),
              tuple(jnp.zeros(R, _I32) for _ in rep_idx),
              tuple(jnp.zeros((R, cap), _U64) for _ in rep_idx))
    (_i, c, malformed, _pe, _pk, vals, seen, rcnt,
     rvals) = lax.while_loop(cond, body, state0)
    # a row that stopped before its end without being flagged is
    # impossible (cursor advances or malforms), but guard anyway
    malformed = malformed | (c < lens)
    return malformed, vals, seen, rcnt, rvals


_ENGINE_CACHE = {}


def _engine(specs):
    if specs not in _ENGINE_CACHE:
        _ENGINE_CACHE[specs] = jax.jit(
            lambda ch, ln: _decode_chunk(ch, ln, specs))
    return _ENGINE_CACHE[specs]


def _convert_scalar_values(f, raw: np.ndarray) -> np.ndarray:
    """Raw u64 captures -> typed numpy values (zigzag/width/sign rules
    shared by the last-value and repeated finalizers)."""
    from spark_rapids_tpu.ops.protobuf import ZIGZAG
    kind = f.dtype.kind
    v = raw.astype(np.uint64)
    if f.encoding == ZIGZAG:
        v = (v >> np.uint64(1)) ^ (np.uint64(0) - (v & np.uint64(1)))
    if kind == Kind.BOOL8:
        return (v != 0).astype(np.uint8)
    if kind == Kind.INT32:
        return (v & np.uint64(0xFFFFFFFF)).astype(np.uint32) \
            .view(np.int32)
    if kind == Kind.INT64:
        return v.view(np.int64)
    if kind == Kind.FLOAT32:        # payload is a 4-byte LE float
        return (v & np.uint64(0xFFFFFFFF)).astype(np.uint32) \
            .view(np.float32)
    if kind == Kind.FLOAT64:
        return v.view(np.float64)
    raise AssertionError(kind)


def _finalize_numeric(f, raw: np.ndarray, seen: np.ndarray,
                      rownull: np.ndarray) -> Column:
    """Raw u64 capture -> typed column with defaults/validity."""
    kind = f.dtype.kind
    out = _convert_scalar_values(f, raw)

    has_default = f.default is not None
    if has_default:
        fill = f.default
        if kind == Kind.BOOL8:
            fill = int(bool(fill))
        out = np.where(seen, out, np.asarray(fill, out.dtype))
    validity = (seen | has_default) & ~rownull
    return Column.from_numpy(
        out, validity=None if validity.all() else
        validity.astype(np.uint8), dtype=f.dtype)


def _finalize_string(chars: np.ndarray, lens: np.ndarray,
                     raw: np.ndarray, seen: np.ndarray,
                     rownull: np.ndarray,
                     default_rows: "np.ndarray | None" = None,
                     default: "str | None" = None) -> Column:
    from spark_rapids_tpu.columns.strbuild import build_string_column
    starts = (raw >> np.uint64(32)).astype(np.int64)
    slens = (raw & np.uint64(0xFFFFFFFF)).astype(np.int64)
    L = chars.shape[1]
    rows_idx = np.arange(len(starts))
    # missing optional field with a schema default: the constant
    # default tiles into unseen (non-null) rows — vectorized, no
    # per-row Python even when most of the column is defaulted
    return build_string_column(
        chars.reshape(-1), rows_idx * L + starts, slens,
        seen & ~rownull,
        fill_rows=default_rows if default is not None else None,
        fill_text=default)


def decode_protobuf_to_struct_device(col: Column,
                                     fields) -> Optional[Column]:
    """Flat-schema device decode; None when the schema needs the host
    path (router: ops/protobuf.py decode_protobuf_to_struct)."""
    if not supported_schema(fields):
        return None
    rows = col.length
    if rows == 0:
        return None
    if col.dtype.kind == Kind.LIST:     # binary LIST<UINT8>: same
        col = Column(dtypes.STRING, rows,  # layout as a string column
                     data=col.children[0].data,
                     validity=col.validity, offsets=col.offsets)
    elif not col.dtype.is_string:
        return None

    cap = _repeat_cap()
    specs = tuple((f.field_number, _expected_wire(f), f.is_message,
                   f.repeated, cap)
                  for f in fields)
    rep_idx = [k for k, f in enumerate(fields) if f.repeated]
    engine = _engine(specs)

    in_null = (np.zeros(rows, bool) if col.validity is None
               else ~np.asarray(col.validity).astype(bool))

    mal_parts: List[np.ndarray] = []
    val_parts: List[List[np.ndarray]] = []
    seen_parts: List[List[np.ndarray]] = []
    rcnt_parts: List[List[np.ndarray]] = []
    rval_parts: List[List[np.ndarray]] = []
    char_parts: List[np.ndarray] = []
    len_parts: List[np.ndarray] = []
    for c0 in range(0, rows, DEVICE_ROW_CHUNK):
        c1 = min(rows, c0 + DEVICE_ROW_CHUNK)
        sub = Column(col.dtype, c1 - c0, data=col.data,
                     validity=None,
                     offsets=col.offsets[c0:c1 + 1],
                     children=col.children)
        chars, lens = sub.to_padded_chars()
        malformed, vals, seen, rcnt, rvals = engine(chars, lens)
        mal_parts.append(np.asarray(malformed))
        val_parts.append([np.asarray(v) for v in vals])
        seen_parts.append([np.asarray(s) for s in seen])
        rcnt_parts.append([np.asarray(x) for x in rcnt])
        rval_parts.append([np.asarray(x) for x in rvals])
        char_parts.append(np.asarray(chars))
        len_parts.append(np.asarray(lens))

    malformed = np.concatenate(mal_parts)
    fvals = [np.concatenate([p[k] for p in val_parts])
             for k in range(len(fields))]
    fseen = [np.concatenate([p[k] for p in seen_parts])
             for k in range(len(fields))]
    rcnts = [np.concatenate([p[r] for p in rcnt_parts])
             for r in range(len(rep_idx))]
    # occurrence-capacity overflow: the whole column falls back to the
    # host oracle (the router treats None as "host path")
    if any((c > cap).any() for c in rcnts):
        return None

    required_missing = np.zeros(rows, bool)
    for k, f in enumerate(fields):
        if f.required:
            required_missing |= ~fseen[k]

    def concat_string_parts(parts):
        """Per-chunk string columns -> one column (char matrices have
        differing widths, so spans resolve chunk-wise)."""
        if len(parts) == 1:
            return parts[0]
        from spark_rapids_tpu.columns.table import Table
        from spark_rapids_tpu.ops.copying import concat_tables
        return concat_tables([Table([p]) for p in parts]).columns[0]

    def span_column(k, keep, default=None, default_rows=None):
        """LEN capture k -> string/binary column of payload spans;
        default splices into `default_rows` (unseen, non-null)."""
        parts = []
        off = 0
        for ci, ch in enumerate(char_parts):
            n = ch.shape[0]
            parts.append(_finalize_string(
                ch, len_parts[ci], val_parts[ci][k],
                seen_parts[ci][k], ~keep[off:off + n],
                default_rows=None if default_rows is None
                else default_rows[off:off + n],
                default=default))
            off += n
        return concat_string_parts(parts)

    def occurrence_layout(r):
        """Flat occurrence layout for repeated field r: counts keep
        their raw values even for rows that later turn null — the
        parent struct validity hides those lists, and a stable layout
        lets spans/values resolve before rownull exists."""
        cnts = rcnts[r].astype(np.int64)
        offsets = np.concatenate([[0], np.cumsum(cnts)]) \
            .astype(np.int32)
        total = int(offsets[-1])
        row_ids = np.repeat(np.arange(rows), cnts)
        k_of = (np.arange(total)
                - np.repeat(offsets[:-1].astype(np.int64), cnts))
        return offsets, total, row_ids, k_of

    def occurrence_strings(r, row_ids, k_of):
        """LEN occurrence spans -> flat string/binary column
        (chunk-relative spans resolve per chunk)."""
        parts = []
        off = 0
        for ci, ch in enumerate(char_parts):
            n = ch.shape[0]
            sel = (row_ids >= off) & (row_ids < off + n)
            rid = row_ids[sel] - off
            bank = rval_parts[ci][r]
            packs = bank[rid, k_of[sel]]
            starts = (packs >> np.uint64(32)).astype(np.int64)
            slens = (packs & np.uint64(0xFFFFFFFF)).astype(np.int64)
            Lc = ch.shape[1]
            from spark_rapids_tpu.columns.strbuild import \
                build_string_column
            parts.append(build_string_column(
                ch.reshape(-1), rid * Lc + starts, slens))
            off += n
        return concat_string_parts(parts)

    # nested messages first: a malformed/required-missing submessage
    # (single or any repeated occurrence) nulls the WHOLE parent row
    # (host _decode_message raises through)
    sub_cols: dict = {}
    rep_msg: dict = {}
    sub_bad = np.zeros(rows, bool)
    for k, f in enumerate(fields):
        if not f.is_message:
            continue
        if f.repeated:
            r = rep_idx.index(k)
            offsets, total, row_ids, k_of = occurrence_layout(r)
            texts = occurrence_strings(r, row_ids, k_of)
            sub = decode_protobuf_to_struct_device(texts, f.children) \
                if total else None
            if total and sub is None:
                return None    # nested occurrence-capacity overflow
            if sub is not None:
                occ_valid = (np.ones(total, bool)
                             if sub.validity is None
                             else np.asarray(sub.validity)
                             .astype(bool))
                bad_rows = np.unique(row_ids[~occ_valid])
                sub_bad[bad_rows] = True
            rep_msg[k] = (sub, offsets)
            continue
        child_bytes = span_column(k, fseen[k])
        sub = decode_protobuf_to_struct_device(child_bytes, f.children)
        if sub is None:
            # a nested repeated field overflowed its occurrence
            # capacity: the whole column takes the host path
            return None
        sub_valid = (np.ones(rows, bool) if sub.validity is None
                     else np.asarray(sub.validity).astype(bool))
        sub_bad |= fseen[k] & ~sub_valid
        sub_cols[k] = sub

    rownull = in_null | malformed | required_missing | sub_bad

    def repeated_column(k, f):
        """Occurrence bank -> LIST column (host _build_column repeated
        shape: the parent struct's validity hides null rows' lists)."""
        r = rep_idx.index(k)
        offsets, total, row_ids, k_of = occurrence_layout(r)
        if f.dtype.is_string:
            child = occurrence_strings(r, row_ids, k_of)
        else:
            bank = np.concatenate([p[r] for p in rval_parts])
            flat = bank[row_ids, k_of] if total else \
                np.zeros(0, np.uint64)
            vals_np = _convert_scalar_values(f, flat)
            child = Column.from_numpy(vals_np, dtype=f.dtype)
        return Column.make_list(offsets, child)

    def repeated_message_column(k, f):
        """LIST<STRUCT> from the recursed occurrence decode."""
        sub, offsets = rep_msg[k]
        if sub is None:    # zero occurrences anywhere
            # _build_column on the repeated field itself yields the
            # correctly-typed 0-row STRUCT list child
            from spark_rapids_tpu.ops.protobuf import _build_column
            empty = _build_column(f, [None], 1).children[0]
            return Column.make_list(offsets, empty)
        return Column.make_list(offsets, sub)

    children = []
    for k, f in enumerate(fields):
        if f.repeated and f.is_message:
            children.append(repeated_message_column(k, f))
        elif f.repeated:
            children.append(repeated_column(k, f))
        elif f.is_message:
            sub = sub_cols[k]
            keep = fseen[k] & ~rownull
            children.append(Column(
                sub.dtype, rows,
                validity=None if keep.all()
                else jnp.asarray(keep.astype(np.uint8)),
                children=sub.children))
        elif f.dtype.is_string:
            children.append(span_column(
                k, fseen[k] & ~rownull, default=f.default,
                default_rows=~fseen[k] & ~rownull))
        else:
            children.append(
                _finalize_numeric(f, fvals[k], fseen[k], rownull))

    validity = None if not rownull.any() else jnp.asarray(
        (~rownull).astype(np.uint8))
    return Column.make_struct(rows, children, validity=validity)


def use_device(col: Column, fields) -> bool:
    if os.environ.get("SPARK_RAPIDS_TPU_FORCE_DEVICE_PROTOBUF") == "1":
        return supported_schema(fields)
    # accelerator-gated like raw_map_device (ADVICE r4): on the
    # single-core CPU backend the host decoder beats the masked scan
    if jax.default_backend() == "cpu":
        return False
    min_rows = int(os.environ.get(
        "SPARK_RAPIDS_TPU_PROTOBUF_DEVICE_MIN", "256"))
    return col.length >= min_rows and supported_schema(fields)
