"""decimal128 arithmetic with Spark precision-38 semantics (reference
decimal_utils.cu/.hpp, DecimalUtils.java): each op returns (overflow BOOL8
column, result DECIMAL128 column at the requested scale).

Scales follow cudf convention: negative scale = digits after the point.

The reference computes through a 256-bit chunked integer type on device.
Here the math runs on host arbitrary-precision integers at the eager
boundary — bit-exact by construction, including the Spark legacy
cast_interim_result double-rounding (SPARK-40129) — with the (rows, 4)
limb columns as the device format.  A limb-vectorized device path is a
later optimization.
"""

from __future__ import annotations

from fractions import Fraction
from typing import Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind

MAX_38 = 10**38 - 1


def _to_ints(col: Column) -> Tuple[np.ndarray, np.ndarray]:
    """(object array of python unscaled ints, valid mask) — via the
    Column decimal128 codec (single source of the limb layout)."""
    vals = np.array([0 if v is None else v for v in col.to_pylist()],
                    object)
    mask = (np.ones(col.length, bool) if col.validity is None
            else np.asarray(col.validity).astype(bool))
    return vals, mask


def _from_ints(vals, mask, scale: int) -> Column:
    pyvals = [int(v) if m else None for v, m in zip(vals, mask)]
    col = Column.from_pylist(pyvals, dtypes.decimal128(scale))
    if col.validity is None and not mask.all():
        col = Column(col.dtype, col.length, data=col.data,
                     validity=jnp.asarray(mask.astype(np.uint8)))
    return col


def _bool_col(vals: np.ndarray, mask: np.ndarray) -> Column:
    validity = None if mask.all() else jnp.asarray(mask.astype(np.uint8))
    return Column(dtypes.BOOL8, len(vals),
                  data=jnp.asarray(vals.astype(np.uint8)),
                  validity=validity)


def _div_round_half_up(x: int, y: int) -> int:
    """round-half-away-from-zero of x/y (divide_and_round,
    decimal_utils.cu)."""
    if y == 0:
        raise ZeroDivisionError
    sign = -1 if (x < 0) != (y < 0) else 1
    ax, ay = abs(x), abs(y)
    return sign * ((2 * ax + ay) // (2 * ay))


def _precision10(x: int) -> int:
    return len(str(abs(x))) if x != 0 else 1


def _check_both(a: Column, b: Column):
    if a.dtype.kind != Kind.DECIMAL128 or b.dtype.kind != Kind.DECIMAL128:
        raise ValueError("decimal128 columns required")
    if a.length != b.length:
        raise ValueError("column lengths must match")




def _use_device() -> bool:
    """Route to the device limb kernels (ops/decimal_device.py) on
    accelerator backends — same gating pattern as the device join and
    group-by fast paths (override with
    SPARK_RAPIDS_TPU_FORCE_DEVICE_DECIMAL=1, disable with =0)."""
    import os

    import jax

    force = os.environ.get("SPARK_RAPIDS_TPU_FORCE_DEVICE_DECIMAL")
    if force == "1":
        return True
    if force == "0":
        return False
    return jax.default_backend() != "cpu"


def multiply_decimal128(a: Column, b: Column, product_scale: int,
                        cast_interim_result: bool = False):
    """(overflow, product) (decimal_utils.cu dec128_multiplier incl. the
    SPARK-40129 legacy interim rounding when cast_interim_result)."""
    _check_both(a, b)
    if not cast_interim_result and _use_device():
        from spark_rapids_tpu.ops.decimal_device import multiply128_device
        return multiply128_device(a, b, product_scale)
    av, am = _to_ints(a)
    bv, bm = _to_ints(b)
    mask = am & bm
    n = a.length
    out = np.zeros(n, object)
    ovf = np.zeros(n, bool)
    for i in range(n):
        if not mask[i]:
            continue
        product = int(av[i]) * int(bv[i])
        mult_scale = a.dtype.scale + b.dtype.scale
        if cast_interim_result:
            first_div_precision = _precision10(product) - 38
            if first_div_precision > 0:
                product = _div_round_half_up(product,
                                             10**first_div_precision)
                mult_scale += first_div_precision
        exponent = product_scale - mult_scale
        if exponent < 0:
            if _precision10(product) - exponent > 38:
                ovf[i] = True
                continue
            product *= 10 ** (-exponent)
        elif exponent > 0:
            product = _div_round_half_up(product, 10**exponent)
        if abs(product) > MAX_38:
            ovf[i] = True
        else:
            out[i] = product
    return _bool_col(ovf, mask), _from_ints(out, mask, product_scale)


def divide_decimal128(a: Column, b: Column, quotient_scale: int,
                      integer_divide: bool = False):
    """(overflow, quotient) at quotient_scale; HALF_UP rounding
    (dec128_divider)."""
    _check_both(a, b)
    if _use_device():
        from spark_rapids_tpu.ops.decimal_device import divide128_device
        return divide128_device(a, b, quotient_scale, integer_divide)
    av, am = _to_ints(a)
    bv, bm = _to_ints(b)
    mask = am & bm
    n = a.length
    out = np.zeros(n, object)
    ovf = np.zeros(n, bool)
    for i in range(n):
        if not mask[i]:
            continue
        if int(bv[i]) == 0:
            ovf[i] = True  # division by zero flagged as overflow
            continue
        # value = av*10^as / (bv*10^bs); unscaled at qs:
        shift = a.dtype.scale - b.dtype.scale - quotient_scale
        x, y = int(av[i]), int(bv[i])
        if integer_divide:
            # truncating division AT the target scale
            # (decimal_utils.cu dec128_divider is_int_div path)
            if shift >= 0:
                num, den = x * 10**shift, y
            else:
                num, den = x, y * 10**(-shift)
            q = abs(num) // abs(den)
            q = q if (x < 0) == (y < 0) else -q
            if q > 2**63 - 1 or q < -2**63:
                ovf[i] = True  # Spark integral div result bounds
                continue
        else:
            if shift >= 0:
                q = _div_round_half_up(x * 10**shift, y)
            else:
                q = _div_round_half_up(x, y * 10**(-shift))
        if abs(q) > MAX_38:
            ovf[i] = True
        else:
            out[i] = q
    return _bool_col(ovf, mask), _from_ints(out, mask, quotient_scale)


def integer_divide_decimal128(a: Column, b: Column, quotient_scale: int):
    return divide_decimal128(a, b, quotient_scale, integer_divide=True)


def remainder_decimal128(a: Column, b: Column, remainder_scale: int):
    """(overflow, a % b) with C/Java truncated-division remainder."""
    _check_both(a, b)
    if _use_device():
        from spark_rapids_tpu.ops.decimal_device import \
            remainder128_device
        return remainder128_device(a, b, remainder_scale)
    av, am = _to_ints(a)
    bv, bm = _to_ints(b)
    mask = am & bm
    n = a.length
    out = np.zeros(n, object)
    ovf = np.zeros(n, bool)
    for i in range(n):
        if not mask[i]:
            continue
        if int(bv[i]) == 0:
            ovf[i] = True
            continue
        # align both to the finer scale, take truncated remainder, rescale
        s = min(a.dtype.scale, b.dtype.scale)
        x = int(av[i]) * 10 ** (a.dtype.scale - s)
        y = int(bv[i]) * 10 ** (b.dtype.scale - s)
        r = abs(x) % abs(y)
        r = r if x >= 0 else -r
        shift = remainder_scale - s
        if shift < 0:
            r *= 10 ** (-shift)
        elif shift > 0:
            r = _div_round_half_up(r, 10**shift)
        if abs(r) > MAX_38:
            ovf[i] = True
        else:
            out[i] = r
    return _bool_col(ovf, mask), _from_ints(out, mask, remainder_scale)


def _add_sub(a: Column, b: Column, out_scale: int, sub: bool):
    _check_both(a, b)
    if _use_device():
        from spark_rapids_tpu.ops.decimal_device import (add128_device,
                                                         sub128_device)
        return (sub128_device if sub else add128_device)(a, b, out_scale)
    av, am = _to_ints(a)
    bv, bm = _to_ints(b)
    mask = am & bm
    n = a.length
    out = np.zeros(n, object)
    ovf = np.zeros(n, bool)
    s = min(a.dtype.scale, b.dtype.scale)
    for i in range(n):
        if not mask[i]:
            continue
        x = int(av[i]) * 10 ** (a.dtype.scale - s)
        y = int(bv[i]) * 10 ** (b.dtype.scale - s)
        v = x - y if sub else x + y
        shift = out_scale - s
        if shift < 0:
            v *= 10 ** (-shift)
        elif shift > 0:
            v = _div_round_half_up(v, 10**shift)
        if abs(v) > MAX_38:
            ovf[i] = True
        else:
            out[i] = v
    return _bool_col(ovf, mask), _from_ints(out, mask, out_scale)


def add_decimal128(a: Column, b: Column, out_scale: int):
    return _add_sub(a, b, out_scale, False)


def sub_decimal128(a: Column, b: Column, out_scale: int):
    return _add_sub(a, b, out_scale, True)


def floating_point_to_decimal(col: Column, output_scale: int,
                              precision: int):
    """(decimal column, first failed row index or -1): f64/f32 -> decimal
    rejecting values that don't fit `precision` digits
    (decimal_utils.hpp:77 floating_point_to_decimal)."""
    if col.dtype.kind not in (Kind.FLOAT32, Kind.FLOAT64):
        raise ValueError("floating point column required")
    host = col.to_numpy().astype(np.float64)
    mask = (np.ones(col.length, bool) if col.validity is None
            else np.asarray(col.validity).astype(bool))
    n = col.length
    out = np.zeros(n, object)
    ok = mask.copy()
    first_fail = -1
    for i in range(n):
        if not mask[i]:
            continue
        v = host[i]
        if not np.isfinite(v):
            ok[i] = False
            first_fail = i if first_fail < 0 else first_fail
            continue
        # exact double value scaled, then HALF_UP (decimal_utils.cu
        # scaled_round) — no double-arithmetic rounding error
        frac = Fraction(v) * 10 ** (-output_scale)
        unscaled = _div_round_half_up(frac.numerator, frac.denominator)
        if _precision10(int(unscaled)) > precision:
            ok[i] = False
            first_fail = i if first_fail < 0 else first_fail
            continue
        out[i] = int(unscaled)
    return _from_ints(out, ok, output_scale), first_fail
