"""StringUtils catch-all surface (reference StringUtilsJni.cpp —
randomUUIDs export — plus StringUtils.java).  The scattered string
helpers live in their own modules; this facade mirrors the reference's
single entry class so binding layers have one place to route
(VERDICT r3: "no catch-all surface")."""

from __future__ import annotations

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.ops.strings_misc import (  # noqa: F401
    REPLACE,
    REPORT,
    convert,
    decode_to_utf8,
    is_convert_overflow,
    list_slice,
    literal_range_pattern,
)
from spark_rapids_tpu.ops.substring_index import substring_index  # noqa: F401
from spark_rapids_tpu.ops.uuid_gen import random_uuids  # noqa: F401
