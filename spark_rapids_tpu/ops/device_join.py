"""Fully-jittable fixed-capacity inner join.

The eager joins in ops/joins.py (reference join_primitives.hpp) produce
exact variable-size index pairs at the eager boundary.  This module is
the *device* counterpart for use INSIDE jit/shard_map — the piece a
distributed join needs so the whole partition→exchange→join step
compiles to one XLA program: static shapes, a caller-chosen pair
capacity, and a true pair count so overflow is detectable (the same
fixed-capacity-plus-true-count contract as parallel/exchange.py).

TPU-first shape: both sides sort by key (total-order integer ranks —
callers canonicalize floats/strings first, as ops/joins does), the
right side's run for every left row comes from two vectorized
searchsorteds, and pair slot j reverse-maps to its (left row, offset
within run) with another searchsorted — no data-dependent loops, no
dynamic shapes, O(P log N) work for P = capacity.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp


class JoinPairs(NamedTuple):
    left_indices: jnp.ndarray   # (capacity,) int32 into the left table
    right_indices: jnp.ndarray  # (capacity,) int32 into the right table
    valid: jnp.ndarray          # (capacity,) bool — slot holds a pair
    total: jnp.ndarray          # () int64 TRUE pair count (may exceed
    #                               capacity: caller must retry bigger)


def inner_join_device(left_keys: jnp.ndarray, right_keys: jnp.ndarray,
                      capacity: int,
                      left_valid: jnp.ndarray | None = None,
                      right_valid: jnp.ndarray | None = None
                      ) -> JoinPairs:
    """Jittable inner join on integer key arrays (join_primitives.hpp
    sort_merge_inner_join contract, device-resident).  Rows with
    valid=False never match (NULL-inequality semantics; encode
    null-equals by mapping nulls to a shared sentinel key AND a
    dedicated validity column upstream, as ops/joins._key_ids does)."""
    nl = left_keys.shape[0]
    nr = right_keys.shape[0]
    lk = left_keys.astype(jnp.int64)
    rk = right_keys.astype(jnp.int64)
    if left_valid is None:
        left_valid = jnp.ones(nl, jnp.bool_)
    if right_valid is None:
        right_valid = jnp.ones(nr, jnp.bool_)

    if nl == 0 or nr == 0:
        z = jnp.zeros(capacity, jnp.int32)
        return JoinPairs(z, z, jnp.zeros(capacity, jnp.bool_),
                         jnp.int64(0))

    # sort right by (invalid, key): invalid rows go last and are excluded
    # from every searched run by searching only the valid prefix
    # (lexsort's primary key is the LAST entry).  Invalid keys map to
    # INT64_MAX so rk_sorted stays globally ascending — searchsorted
    # requires it; the n_valid_r clip below breaks the tie when valid
    # keys legitimately equal INT64_MAX.
    from jax import lax

    r_sortkey = jnp.where(right_valid, rk, jnp.int64(2**63 - 1))
    # one lax.sort delivers the sorted keys AND the permutation: keys
    # (invalid-last, key, iota-for-stability); rk_sorted stays globally
    # ascending because invalid keys are already INT64_MAX
    _, rk_sorted, r_order = lax.sort(
        ((~right_valid).astype(jnp.int32), r_sortkey,
         lax.iota(jnp.int32, nr)), num_keys=3)
    n_valid_r = jnp.sum(right_valid.astype(jnp.int32))

    # run bounds for each left key within the valid prefix
    lo = jnp.searchsorted(rk_sorted, lk, side="left")
    hi = jnp.searchsorted(rk_sorted, lk, side="right")
    lo = jnp.minimum(lo, n_valid_r)
    hi = jnp.minimum(hi, n_valid_r)
    # pair accounting is int64: two 64k-row sides sharing one key are
    # 2^32 pairs, which would wrap int32 and defeat overflow detection
    counts = jnp.where(left_valid, hi - lo, 0).astype(jnp.int64)

    offs = jnp.cumsum(counts) - counts          # exclusive prefix sum
    total = offs[-1] + counts[-1]

    # reverse map: pair slot j -> left row i with offs[i] <= j < offs[i+1]
    j = jnp.arange(capacity, dtype=jnp.int64)
    i = jnp.searchsorted(offs, j, side="right").astype(jnp.int32) - 1
    i = jnp.clip(i, 0, nl - 1)
    k = j - offs[i]
    valid = (j < total) & (k < counts[i])
    r_pos = jnp.clip(lo[i] + k, 0, nr - 1)
    right_idx = r_order[r_pos].astype(jnp.int32)
    return JoinPairs(jnp.where(valid, i, 0).astype(jnp.int32),
                     jnp.where(valid, right_idx, 0),
                     valid, total)
