"""Spark get_json_object (reference get_json_object.cu + json_parser.cuh,
JSONUtils.getJsonObject:64-106).

Path instructions: $ root, .name / ['name'], [index], [*] wildcard; arrays
flatten implicitly under named access (Spark evaluatePath).  The tolerant
parser accepts single-quoted strings and unescaped control characters
(json_parser.cuh Spark options).  Output: unescaped text for a single
string scalar, raw literal for other scalars, compact normalized JSON for
objects/arrays, a JSON array of results for multiple wildcard matches,
null for no match / invalid JSON / invalid path.

The multi-path API mirrors the reference's memory-budgeted batch entry
(get_json_object.hpp:9-14): paths are processed in chunks whose estimated
scratch fits the budget — the same chunking contract, applied host-side.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple, Union

from spark_rapids_tpu.columns.column import Column

MAX_PATH_DEPTH = 16  # get_json_object.hpp:2


# ----------------------------------------------------------- path parsing

class Named:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name


class Index:
    __slots__ = ("index",)

    def __init__(self, index):
        self.index = index


class Wildcard:
    pass


def parse_path(path: str) -> Optional[List]:
    """JSON path -> instruction list; None if malformed."""
    if not path or path[0] != "$":
        return None
    out: List = []
    i = 1
    n = len(path)
    while i < n:
        c = path[i]
        if c == ".":
            i += 1
            if i < n and path[i] == "*":
                out.append(Wildcard())
                i += 1
                continue
            j = i
            while j < n and path[j] not in ".[":
                j += 1
            if j == i:
                return None
            out.append(Named(path[i:j]))
            i = j
        elif c == "[":
            j = path.find("]", i)
            if j < 0:
                return None
            body = path[i + 1: j].strip()
            if body == "*":
                out.append(Wildcard())
            elif len(body) >= 2 and body[0] == "'" and body[-1] == "'":
                out.append(Named(body[1:-1]))
            elif body.isdigit():
                out.append(Index(int(body)))
            else:
                return None
            i = j + 1
        else:
            return None
    if len(out) > MAX_PATH_DEPTH:
        return None
    return out


# ------------------------------------------------------- tolerant parser

class _Invalid(Exception):
    pass


_WS = " \t\n\r"
_ESCAPES = {'"': '"', "'": "'", "\\": "\\", "/": "/", "b": "\b",
            "f": "\f", "n": "\n", "r": "\r", "t": "\t"}


class _Parser:
    def __init__(self, s: str, allow_leading_zeros: bool = False):
        self.s = s
        self.i = 0
        self.n = len(s)
        self.allow_leading_zeros = allow_leading_zeros

    def ws(self):
        while self.i < self.n and self.s[self.i] in _WS:
            self.i += 1

    def parse(self):
        self.ws()
        v = self.value()
        self.ws()
        if self.i != self.n:
            raise _Invalid()
        return v

    def value(self):
        if self.i >= self.n:
            raise _Invalid()
        c = self.s[self.i]
        if c == "{":
            return self.obj()
        if c == "[":
            return self.arr()
        if c in "\"'":
            return ("str", self.string(c))
        if c == "t" and self.s[self.i:self.i + 4] == "true":
            self.i += 4
            return ("lit", "true")
        if c == "f" and self.s[self.i:self.i + 5] == "false":
            self.i += 5
            return ("lit", "false")
        if c == "n" and self.s[self.i:self.i + 4] == "null":
            self.i += 4
            return ("lit", "null")
        return ("num", self.number())

    def obj(self):
        self.i += 1
        items = []
        self.ws()
        if self.i < self.n and self.s[self.i] == "}":
            self.i += 1
            return ("obj", items)
        while True:
            self.ws()
            if self.i >= self.n or self.s[self.i] not in "\"'":
                raise _Invalid()
            k = self.string(self.s[self.i])
            self.ws()
            if self.i >= self.n or self.s[self.i] != ":":
                raise _Invalid()
            self.i += 1
            self.ws()
            items.append((k, self.value()))
            self.ws()
            if self.i < self.n and self.s[self.i] == ",":
                self.i += 1
                continue
            if self.i < self.n and self.s[self.i] == "}":
                self.i += 1
                return ("obj", items)
            raise _Invalid()

    def arr(self):
        self.i += 1
        items = []
        self.ws()
        if self.i < self.n and self.s[self.i] == "]":
            self.i += 1
            return ("arr", items)
        while True:
            self.ws()
            items.append(self.value())
            self.ws()
            if self.i < self.n and self.s[self.i] == ",":
                self.i += 1
                continue
            if self.i < self.n and self.s[self.i] == "]":
                self.i += 1
                return ("arr", items)
            raise _Invalid()

    def string(self, quote):
        self.i += 1
        out = []
        while True:
            if self.i >= self.n:
                raise _Invalid()
            c = self.s[self.i]
            if c == quote:
                self.i += 1
                return "".join(out)
            if c == "\\":
                self.i += 1
                if self.i >= self.n:
                    raise _Invalid()
                e = self.s[self.i]
                if e == "u":
                    hexs = self.s[self.i + 1: self.i + 5]
                    # strict 4 hex digits (int() would tolerate ' 041',
                    # '0x..', '1_2' — Java and the device DFA reject)
                    if len(hexs) < 4 or not all(
                            c in "0123456789abcdefABCDEF" for c in hexs):
                        raise _Invalid()
                    cp = int(hexs, 16)
                    self.i += 5
                    # combine surrogate pairs (json.dumps ensure_ascii
                    # writes emoji as 😀); lone surrogates are
                    # unencodable in UTF-8 -> U+FFFD like Java's replace
                    if 0xD800 <= cp <= 0xDBFF and \
                            self.s[self.i: self.i + 2] == "\\u":
                        hex2 = self.s[self.i + 2: self.i + 6]
                        if len(hex2) == 4 and all(
                                c in "0123456789abcdefABCDEF"
                                for c in hex2):
                            lo = int(hex2, 16)
                        else:
                            lo = -1
                        if 0xDC00 <= lo <= 0xDFFF:
                            cp = 0x10000 + ((cp - 0xD800) << 10) \
                                + (lo - 0xDC00)
                            self.i += 6
                        else:
                            cp = 0xFFFD
                    elif 0xD800 <= cp <= 0xDFFF:
                        cp = 0xFFFD
                    out.append(chr(cp))
                    continue
                if e not in _ESCAPES:
                    raise _Invalid()
                out.append(_ESCAPES[e])
                self.i += 1
                continue
            # unescaped control chars allowed (Spark option)
            out.append(c)
            self.i += 1

    def number(self):
        start = self.i
        if self.i < self.n and self.s[self.i] == "-":
            self.i += 1
        digits = 0
        first_digit_i = self.i
        while self.i < self.n and self.s[self.i].isdigit():
            self.i += 1
            digits += 1
        if digits == 0:
            raise _Invalid()
        if digits > 1 and self.s[first_digit_i] == "0" \
                and not self.allow_leading_zeros:
            # invalid JSON numbers for get_json_object; from_json can
            # opt in via Spark's allowNumericLeadingZeros
            raise _Invalid()
        if self.i < self.n and self.s[self.i] == ".":
            self.i += 1
            while self.i < self.n and self.s[self.i].isdigit():
                self.i += 1
        if self.i < self.n and self.s[self.i] in "eE":
            self.i += 1
            if self.i < self.n and self.s[self.i] in "+-":
                self.i += 1
            ed = 0
            while self.i < self.n and self.s[self.i].isdigit():
                self.i += 1
                ed += 1
            if ed == 0:
                raise _Invalid()
        return self.s[start: self.i]


# ------------------------------------------------------------ evaluation

def _escape(s: str) -> str:
    out = ['"']
    for c in s:
        if c == '"':
            out.append('\\"')
        elif c == "\\":
            out.append("\\\\")
        elif c == "\n":
            out.append("\\n")
        elif c == "\r":
            out.append("\\r")
        elif c == "\t":
            out.append("\\t")
        elif ord(c) < 0x20:
            out.append(f"\\u{ord(c):04x}")
        else:
            out.append(c)
    out.append('"')
    return "".join(out)


def _normalize_number(text: str) -> str:
    """Spark-normalized number rendering (get_json_object writes
    numbers through Java double formatting when fractional/exponential;
    GetJsonObjectTest getJsonObjectTest_Number_Normalization):
    integer tokens stay verbatim (arbitrary precision, -0 -> 0);
    float tokens render as Java Double.toString, overflowing to the
    JSON STRING "Infinity"/"-Infinity"."""
    if not any(c in text for c in ".eE"):
        return "0" if text in ("-0", "0") else text
    from spark_rapids_tpu.ops.cast_string import _java_double_repr
    v = float(text)
    if v in (float("inf"), float("-inf")):
        return _escape("Infinity" if v > 0 else "-Infinity")
    return _java_double_repr(v, False)


def _render_json(v, normalize_numbers: bool = True) -> str:
    """normalize_numbers=True is get_json_object's Java-normalized
    rendering; the from_json family passes False to keep number tokens
    verbatim (from_json_to_raw_map.cu copies raw token substrings)."""
    kind = v[0]
    if kind == "str":
        return _escape(v[1])
    if kind == "num":
        return _normalize_number(v[1]) if normalize_numbers else v[1]
    if kind == "lit":
        return v[1]
    if kind == "obj":
        return "{" + ",".join(
            f"{_escape(k)}:{_render_json(x, normalize_numbers)}"
            for k, x in v[1]) + "}"
    return "[" + ",".join(_render_json(x, normalize_numbers)
                          for x in v[1]) + "]"


def _eval(v, path: List) -> List:
    if not path:
        return [v]
    ins = path[0]
    kind = v[0]
    if isinstance(ins, Named):
        if kind == "obj":
            out = []
            for k, child in v[1]:
                if k == ins.name:
                    out.extend(_eval(child, path[1:]))
            return out
        if kind == "arr":  # implicit array flattening under named access
            out = []
            for el in v[1]:
                out.extend(_eval(el, path))
            return out
        return []
    if isinstance(ins, Index):
        if kind == "arr" and 0 <= ins.index < len(v[1]):
            return _eval(v[1][ins.index], path[1:])
        return []
    if isinstance(ins, Wildcard):
        if kind == "arr":
            out = []
            for el in v[1]:
                out.extend(_eval(el, path[1:]))
            return out
        return []
    return []


def _run_one(doc: Optional[str], path: Optional[List]) -> Optional[str]:
    if doc is None or path is None:
        return None
    try:
        v = _Parser(doc).parse()
    except _Invalid:
        return None
    matches = _eval(v, path)
    if not matches:
        return None
    if len(matches) == 1:
        m = matches[0]
        if m[0] == "str":
            return m[1]
        return _render_json(m)
    return "[" + ",".join(_render_json(m) for m in matches) + "]"


def get_json_object_host(col: Column, path: str) -> Column:
    """Host evaluator (the oracle for the device engine's fallback rows)."""
    assert col.dtype.is_string
    instructions = parse_path(path)
    vals = col.to_pylist()
    return Column.from_strings([_run_one(v, instructions) for v in vals])


# rows at or above this count route through the device scan; tiny columns
# stay host-side where compile cost would dominate (override via env)
DEVICE_MIN_ROWS = int(os.environ.get("SPARK_RAPIDS_TPU_JSON_MIN_ROWS", 32))

# rows at or above this count earn a measured engine pick (ISSUE 9);
# below it the static default is cheaper than timing anything
JSON_CALIBRATE_MIN_ROWS = 1 << 14

# sampled rows each calibration candidate runs over
JSON_SAMPLE_ROWS = 1 << 14


class _EngineDeclined(RuntimeError):
    """A decline-capable engine refused the calibration sample."""


def route_json_engine(op: str, col: Column, engines, default: str,
                      extra: str = "") -> str:
    """Measured engine pick for a JSON string-column op (ISSUE 9).

    ``engines`` maps path name -> fn(col); candidates time a sampled
    slice of ``col`` under the shared calibrator
    (perf/calibrate.pick_path), keyed by (op, doc-shape digest,
    backend).  Every engine is byte-identical by contract (per-row host
    fallback), so the pick is SPEED only.  Small columns return
    ``default`` untimed; SPARK_RAPIDS_TPU_PATH_<OP> pins a path."""
    from spark_rapids_tpu.perf import calibrate

    pin = calibrate.pinned_path(op)
    if pin is not None and pin in engines:
        return pin
    rows = col.length
    if rows < JSON_CALIBRATE_MIN_ROWS or len(engines) <= 1:
        return default
    import numpy as np
    nbytes = int(np.asarray(col.offsets)[-1]) if col.offsets is not None \
        else 0
    mean_len = max(nbytes // max(rows, 1), 1)
    digest = (f"{extra}|rb{rows.bit_length()}"
              f"|lb{mean_len.bit_length()}")
    if rows > JSON_SAMPLE_ROWS:
        from spark_rapids_tpu.ops.copying import slice_column
        sub = slice_column(col, 0, JSON_SAMPLE_ROWS)
    else:
        sub = col

    def _ran(fn):
        # decline-capable device engines answer None for shapes they
        # refuse; timing that as a near-instant success would crown a
        # verdict whose production calls all fall back — surface the
        # decline as a calibration error so the engine is excluded
        out = fn(sub)
        if out is None:
            raise _EngineDeclined(f"engine declined {rows}-row sample")
        return out

    candidates = {name: (lambda fn=fn: _ran(fn))
                  for name, fn in engines.items()}
    path = calibrate.pick_path(op, digest, candidates, default=default)
    return path if path in engines else default


def get_json_object(col: Column, path: str) -> Column:
    """One strings column of extraction results (JSONUtils.getJsonObject).

    Engine choice is a measurement, not a backend gate (ISSUE 9): the
    batch-parallel structural-index tokenizer (ops/json_tokenizer), the
    per-row device scan (ops/json_device) and this host evaluator are
    byte-identical candidates; the calibrator picks per (path shape,
    doc shape, backend).  Wildcard paths stay on the scan/host pair
    (multi-match rendering is out of the tokenizer's scope)."""
    from spark_rapids_tpu import observability as _obs

    mode = os.environ.get("SPARK_RAPIDS_TPU_JSON", "auto")

    def _device_scan(c):
        from spark_rapids_tpu.ops.json_device import \
            get_json_object_device
        return get_json_object_device(c, path)

    engines = {
        "host": lambda c: get_json_object_host(c, path),
        "device_scan": _device_scan,
    }
    if mode == "host" or (mode != "device"
                          and col.length < DEVICE_MIN_ROWS):
        engine = "host"
    elif mode == "device":
        engine = "device_scan"
    else:
        from spark_rapids_tpu.ops import json_tokenizer as JT
        instructions = parse_path(path)
        tok_ok = bool(instructions) and not any(
            isinstance(i, Wildcard) for i in instructions)
        if tok_ok:
            engines["tokenizer"] = \
                lambda c: JT.get_json_object_tokenized(c, path)
        # static default below the calibration floor = the pre-ISSUE-9
        # routing (device scan); above it the measurement decides
        # tok_ok is part of the digest: wildcard and non-wildcard paths
        # offer different candidate sets and must not share a verdict
        engine = route_json_engine(
            "json.get_object", col, engines, "device_scan",
            extra=f"steps{len(instructions or ())}t{int(tok_ok)}")
    _obs.record_kernel_path("get_json_object", engine, col.length)
    return engines[engine](col)


def get_json_object_multiple_paths(col: Column, paths: Sequence[str],
                                   memory_budget_bytes: int = -1,
                                   parallel_override: int = -1
                                   ) -> List[Column]:
    """One output column per path (get_json_object.hpp:9 multi-path batch).
    The budget/parallel knobs shape chunking in the reference kernel; the
    host evaluator parses each document once per chunk of paths.  Large
    columns route through the device engine (padded matrix built once,
    shared across paths), same rule as get_json_object."""
    assert col.dtype.is_string
    mode = os.environ.get("SPARK_RAPIDS_TPU_JSON", "auto")
    if mode != "host" and (mode == "device"
                           or col.length >= DEVICE_MIN_ROWS):
        from spark_rapids_tpu import observability as _obs
        from spark_rapids_tpu.ops.json_device import \
            get_json_object_multiple_paths_device

        engines = {
            "device_scan": lambda c: \
                get_json_object_multiple_paths_device(
                    c, paths, memory_budget_bytes, parallel_override),
        }
        parsed = [parse_path(p) for p in paths]
        tok_ok = mode != "device" and all(
            p is None or (p and not any(isinstance(i, Wildcard)
                                        for i in p))
            for p in parsed) and any(p is not None for p in parsed)
        if tok_ok:
            from spark_rapids_tpu.ops import json_tokenizer as JT
            engines["tokenizer"] = lambda c: \
                JT.get_json_object_multiple_paths_tokenized(c, paths)
        # the path SET is part of the digest, not just its size: two
        # 2-path batches with very different step depths must not share
        # a cached verdict for the file-cache TTL
        import hashlib
        ph = hashlib.md5("|".join(paths).encode()).hexdigest()[:8]
        engine = route_json_engine(
            "json.get_object", col, engines, "device_scan",
            extra=f"multi{len(paths)}p{ph}t{int(tok_ok)}") \
            if mode != "device" else "device_scan"
        if engine not in engines:
            engine = "device_scan"
        _obs.record_kernel_path("get_json_object", engine, col.length)
        return engines[engine](col)
    parsed_paths = [parse_path(p) for p in paths]
    vals = col.to_pylist()
    if parallel_override > 0:
        chunk = max(1, parallel_override)
    elif memory_budget_bytes > 0:
        # reference heuristic: scratch ~ max row size per path
        max_row = max((len(v) for v in vals if v is not None), default=1)
        chunk = max(1, memory_budget_bytes // max(max_row, 1))
    else:
        chunk = len(paths) or 1
    # parse every document once per chunk of paths (the budget bounds how
    # long the parsed trees stay alive, as the reference's scratch does)
    outs: List[Column] = []
    for c0 in range(0, len(parsed_paths), chunk):
        trees = []
        for v in vals:
            if v is None:
                trees.append(None)
            else:
                try:
                    trees.append(_Parser(v).parse())
                except _Invalid:
                    trees.append(None)
        for path in parsed_paths[c0:c0 + chunk]:
            if path is None:
                outs.append(Column.from_strings([None] * len(vals)))
                continue
            row_out = []
            for t in trees:
                if t is None:
                    row_out.append(None)
                    continue
                matches = _eval(t, path)
                if not matches:
                    row_out.append(None)
                elif len(matches) == 1:
                    m = matches[0]
                    row_out.append(m[1] if m[0] == "str"
                                   else _render_json(m))
                else:
                    row_out.append(
                        "[" + ",".join(_render_json(m)
                                       for m in matches) + "]")
            outs.append(Column.from_strings(row_out))
    return outs
