"""Iceberg partition transforms (reference src/main/cpp/src/iceberg/:
iceberg_bucket.cu, iceberg_truncate.cu, iceberg_datetime_util.cu;
IcebergBucket.java etc.) — bucket (STANDARD murmur3_32 seed 0, NOT the
Spark variant: ints promote to longs and hash as 8 LE bytes, decimals
hash their minimal big-endian two's-complement unscaled bytes), truncate
(positive-mod for integrals/decimals, leading codepoints for strings),
and year/month/day/hour datetime transforms."""

from __future__ import annotations

from typing import Union

import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.ops.hash import (_MM_C1, _MM_C2, _MM_C3, _mm_fmix,
                                       _mm_update, _rotl32, _split_u64,
                                       _dec128_min_be_bytes, _pad_chars,
                                       _chars_to_u32_blocks)
from spark_rapids_tpu.ops.datetime_ops import _days_to_ymd

_U8 = jnp.uint8
_U32 = jnp.uint32
_U64 = jnp.uint64
_I32 = jnp.int32
_I64 = jnp.int64

MICROS_PER_HOUR = 3_600_000_000
MICROS_PER_DAY = 86_400_000_000


def _std_murmur_varbytes(chars: jnp.ndarray, lens: jnp.ndarray
                         ) -> jnp.ndarray:
    """STANDARD murmur3_32 (seed 0) over per-row byte strings — unlike
    Spark's variant, the tail partial block is combined little-endian and
    mixed once without the h-rotation (iceberg_bucket.cu hash_bytes via
    cuco MurmurHash3_32)."""
    chars = _pad_chars(chars, 4)
    blocks = _chars_to_u32_blocks(chars)
    nblocks = (lens // 4).astype(_I32)
    rows = chars.shape[0]
    h = jnp.zeros(rows, _U32)

    def body(hc, xs):
        i, blk = xs
        h2 = _mm_update(hc, blk)
        return jnp.where(i < nblocks, h2, hc), None

    nb = blocks.shape[1]
    h, _ = lax.scan(body, h,
                    (jnp.arange(nb, dtype=_I32), blocks.T))
    # standard tail: combine remaining 1-3 bytes LE, single k1 mix
    p = chars.shape[1]
    tail = jnp.zeros(rows, _U32)
    for j in range(3):
        idx = nblocks * 4 + j
        byte = jnp.take_along_axis(
            chars, jnp.clip(idx, 0, p - 1)[:, None], axis=1)[:, 0]
        tail = tail | jnp.where(idx < lens,
                                byte.astype(_U32) << _U32(8 * j), _U32(0))
    k1 = tail * _MM_C1
    k1 = _rotl32(k1, 15)
    k1 = k1 * _MM_C2
    h = jnp.where(lens % 4 != 0, h ^ k1, h)
    h = h ^ lens.astype(_U32)
    return _mm_fmix(h)


def _std_murmur_u64(v: jnp.ndarray) -> jnp.ndarray:
    """Standard murmur3_32 of 8 LE bytes (Iceberg hashLong)."""
    lo, hi = _split_u64(v.astype(_U64))
    h = jnp.zeros(v.shape, _U32)
    h = _mm_update(h, lo)
    h = _mm_update(h, hi)
    h = h ^ _U32(8)
    return _mm_fmix(h)


def bucket(col: Column, num_buckets: int) -> Column:
    """Iceberg bucket transform: (hash & MAX_INT) % N, null-preserving."""
    kind = col.dtype.kind
    if kind in (Kind.INT32, Kind.INT64, Kind.TIMESTAMP_DAYS,
                Kind.TIMESTAMP_MICROS):
        h = _std_murmur_u64(col.data.astype(_I64))
    elif kind == Kind.STRING:
        chars, lens = col.to_padded_chars()
        h = _std_murmur_varbytes(chars, lens)
    elif kind in (Kind.DECIMAL32, Kind.DECIMAL64):
        # minimal big-endian two's complement of the unscaled value
        from spark_rapids_tpu.ops.hash import _fixed_width_blocks
        v = col.data.astype(_I64)
        limbs = jnp.stack([
            (v & _I64(0xFFFFFFFF)).astype(_I32),
            ((v >> _I64(32)) & _I64(0xFFFFFFFF)).astype(_I32),
            jnp.where(v < 0, _I32(-1), _I32(0)),
            jnp.where(v < 0, _I32(-1), _I32(0))], axis=1)
        be, length = _dec128_min_be_bytes(limbs)
        h = _std_murmur_varbytes(be, length)
    elif kind == Kind.DECIMAL128:
        be, length = _dec128_min_be_bytes(col.data)
        h = _std_murmur_varbytes(be, length)
    else:
        raise NotImplementedError(f"iceberg bucket of {kind}")
    b = (h & _U32(0x7FFFFFFF)) % _U32(num_buckets)
    return Column(dtypes.INT32, col.length, data=b.astype(_I32),
                  validity=col.validity)


def truncate(col: Column, width: int) -> Column:
    """Iceberg truncate transform (iceberg_truncate.cu:48-61 examples:
    truncate(10, 5)=0, truncate(10, 15)=10, truncate(10, -5)=-10)."""
    kind = col.dtype.kind
    if kind in (Kind.INT32, Kind.INT64, Kind.DECIMAL32, Kind.DECIMAL64):
        v = col.data.astype(_I64)
        w = _I64(width)
        out = v - (((v % w) + w) % w)
        return Column(col.dtype, col.length,
                      data=out.astype(col.dtype.np_dtype),
                      validity=col.validity)
    if kind == Kind.STRING:
        # first `width` CODEPOINTS (not bytes): keep bytes whose position
        # in codepoints is < width
        out = []
        mask = (np.ones(col.length, bool) if col.validity is None
                else np.asarray(col.validity).astype(bool))
        for i, s in enumerate(col.to_pylist()):
            out.append(s[:width] if mask[i] and s is not None else None)
        return Column.from_strings(out)
    raise NotImplementedError(f"iceberg truncate of {kind}")


def year(col: Column) -> Column:
    """Years since 1970 (iceberg_datetime_util.cu)."""
    days = _col_days(col)
    y, _, _ = _days_to_ymd(days)
    return Column(dtypes.INT32, col.length,
                  data=(y - 1970).astype(_I32), validity=col.validity)


def month(col: Column) -> Column:
    days = _col_days(col)
    y, m, _ = _days_to_ymd(days)
    return Column(dtypes.INT32, col.length,
                  data=((y - 1970) * 12 + m - 1).astype(_I32),
                  validity=col.validity)


def day(col: Column) -> Column:
    days = _col_days(col)
    return Column(dtypes.INT32, col.length, data=days.astype(_I32),
                  validity=col.validity)


def hour(col: Column) -> Column:
    assert col.dtype.kind == Kind.TIMESTAMP_MICROS
    h = col.data.astype(_I64) // _I64(MICROS_PER_HOUR)
    return Column(dtypes.INT32, col.length, data=h.astype(_I32),
                  validity=col.validity)


def _col_days(col: Column) -> jnp.ndarray:
    if col.dtype.kind == Kind.TIMESTAMP_DAYS:
        return col.data.astype(_I64)
    if col.dtype.kind == Kind.TIMESTAMP_MICROS:
        return col.data.astype(_I64) // _I64(MICROS_PER_DAY)
    raise NotImplementedError(f"datetime transform of {col.dtype.kind}")
