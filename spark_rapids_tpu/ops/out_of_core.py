"""Out-of-core hash join and group-by (ISSUE 18 tentpole b): when the
build side exceeds ``SPARK_RAPIDS_TPU_DEVICE_BUDGET_BYTES``, partition
both sides by the existing xxhash64 join group ids
(ops/hash_join.key_hashes over the join word encoding), spill build
partitions through the tiered store (memory/spill.py), and stream
them back one partition at a time — each partition running through
the UNCHANGED in-memory kernels, so the result is byte-identical to
the single-pass answer.

Why partitioning preserves bit-exactness (the contracts these wrappers
lean on, both asserted by tests/test_spill.py):

* join — ``hash_inner_join`` returns pairs grouped by left index
  ascending, right ascending within a left row.  A key hashes to ONE
  partition, so every match of a left row lives in that row's
  partition; concatenating per-partition pairs (mapped back to global
  indices) and re-sorting by (left, right) reproduces the oracle
  order exactly, and the pair SET is trivially equal.
* group-by — same-key rows land in the same partition, so every group
  is COMPLETE within its partition: per-partition aggregates are the
  FINAL aggregates, computed by ``groupby_aggregate`` over the same
  rows in the same relative order (stable mask partitioning), hence
  bit-identical — including float sums, whose accumulation sequence
  is unchanged.  Output rows are re-ordered to the in-memory group
  order (sorted-key order, the position-independent contract of
  ``_group_ids``) by running the group-id machinery once over the
  merged one-row-per-group output keys.

The DISABLED path — no device budget configured — is one cached env
read and a direct call into the in-memory operator (<1us, gated by
scripts/spill_smoke.py).
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.memory import spill as spill_mod
from spark_rapids_tpu.ops import joins
from spark_rapids_tpu.ops.copying import gather_table

_MAX_PARTS = 64


def _partition_count(build_bytes: int, budget: int,
                     parts: Optional[int]) -> int:
    """Power-of-two partition count sized so one build partition fits
    the budget (expectation under a uniform hash), clamped to
    [2, 64]; ``SPARK_RAPIDS_TPU_SPILL_PARTITIONS`` / ``parts``
    overrides."""
    if parts is None:
        parts = spill_mod._env_int("SPARK_RAPIDS_TPU_SPILL_PARTITIONS")
    if parts is not None and parts > 0:
        n = 1 << max(int(parts) - 1, 0).bit_length()
        return max(2, min(_MAX_PARTS, n))
    need = max(2, -(-build_bytes // max(budget, 1)))
    return min(_MAX_PARTS, 1 << (need - 1).bit_length())


def _partition_ids(words, nparts: int) -> np.ndarray:
    """Per-row partition id from the SAME xxhash64 group ids the join
    engines key on — both join sides therefore agree by
    construction."""
    from spark_rapids_tpu.ops.hash_join import key_hashes
    if not words:
        return np.zeros(0, np.int64)
    h = np.asarray(key_hashes(words))
    return (h.view(np.uint64) & np.uint64(nparts - 1)).astype(np.int64)


def _spill_partitions(store, tables: List[Table], stage: str,
                      task_id=None) -> List:
    """Register every partition as spillable and push them all down a
    tier: the caller is ABOUT to exceed its device budget, and the
    streamed-back working set re-enters one partition at a time."""
    handles = []
    for i, t in enumerate(tables):
        src = t  # recompute-from-source: the gathered partition table
        h = store.register(
            list(t.columns), name=f"{stage}-p{i}", task_id=task_id,
            stage=stage, recompute=lambda t=src: list(t.columns))
        handles.append(h)
    for h in handles:
        h.spill()
    return handles


def out_of_core_hash_join(left_keys: Table, right_keys: Table,
                          compare_nulls: str = joins.NULL_EQUAL, *,
                          budget: Optional[int] = None,
                          parts: Optional[int] = None,
                          store: Optional[spill_mod.SpillStore] = None,
                          task_id: Optional[int] = None
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``joins.hash_inner_join`` that degrades to partitioned
    out-of-core execution (never to shedding) when the build side
    exceeds the device budget.  Same return contract: (left_indices,
    right_indices) grouped by left ascending, right ascending
    within."""
    from spark_rapids_tpu.ops.hash_join import join_key_words
    if budget is None:
        budget = spill_mod.device_budget_bytes()
    if budget is None:
        return joins.hash_inner_join(left_keys, right_keys,
                                     compare_nulls)
    build_bytes = spill_mod.columns_nbytes(right_keys.columns)
    if build_bytes <= budget:
        return joins.hash_inner_join(left_keys, right_keys,
                                     compare_nulls)
    try:
        lwords, rwords, _vl, _vr, _extra = join_key_words(
            left_keys, right_keys, compare_nulls)
    except ValueError:
        # no device word encoding for these keys -> no hash to
        # partition on; the host rank path runs in one pass
        return joins.hash_inner_join(left_keys, right_keys,
                                     compare_nulls)
    nparts = _partition_count(build_bytes, budget, parts)
    lpid = _partition_ids(lwords, nparts)
    rpid = _partition_ids(rwords, nparts)

    # global row indices per partition (stable: original order kept)
    lidx = [np.nonzero(lpid == p)[0] for p in range(nparts)]
    ridx = [np.nonzero(rpid == p)[0] for p in range(nparts)]
    rparts = [gather_table(right_keys,
                           jnp.asarray(ri.astype(np.int32)))
              for ri in ridx]
    if store is None:
        store = spill_mod.ensure_store()
    handles = _spill_partitions(store, rparts, "ooc_join", task_id)

    out_l: List[np.ndarray] = []
    out_r: List[np.ndarray] = []
    try:
        for p in range(nparts):
            if len(lidx[p]) == 0 or len(ridx[p]) == 0:
                continue
            lpart = gather_table(
                left_keys, jnp.asarray(lidx[p].astype(np.int32)))
            # pinned while the kernel runs: a concurrent
            # ensure_headroom must not re-spill the partition out
            # from under the join
            with handles[p].pin() as rcols:
                rpart = Table(rcols, right_keys.names)
                # the UNCHANGED in-memory kernel, per partition
                li, ri = joins.hash_inner_join(lpart, rpart,
                                               compare_nulls)
                out_l.append(lidx[p][np.asarray(li)])
                out_r.append(ridx[p][np.asarray(ri)])
            handles[p].close()
    finally:
        for h in handles:
            h.close()
    if not out_l:
        return jnp.zeros(0, jnp.int32), jnp.zeros(0, jnp.int32)
    gl = np.concatenate(out_l)
    gr = np.concatenate(out_r)
    order = np.lexsort((gr, gl))
    return (jnp.asarray(gl[order].astype(np.int32)),
            jnp.asarray(gr[order].astype(np.int32)))


def out_of_core_groupby(keys: Table, values: Sequence, aggs: Sequence[str],
                        *, budget: Optional[int] = None,
                        parts: Optional[int] = None,
                        store: Optional[spill_mod.SpillStore] = None,
                        task_id: Optional[int] = None) -> Table:
    """``groupby.groupby_aggregate`` that partitions by the key hash
    and streams partitions through the spill store when the input
    exceeds the device budget.  Groups are complete per partition, so
    per-partition aggregates are final and bit-identical; rows are
    re-ordered to the in-memory (sorted-key) group order."""
    from spark_rapids_tpu.ops import groupby
    from spark_rapids_tpu.ops.hash_join import join_key_words
    if budget is None:
        budget = spill_mod.device_budget_bytes()
    input_cols = list(keys.columns) + list(values)
    if budget is None:
        return groupby.groupby_aggregate(keys, values, aggs)
    total_bytes = spill_mod.columns_nbytes(input_cols)
    if total_bytes <= budget:
        return groupby.groupby_aggregate(keys, values, aggs)
    try:
        kwords, _rw, _vl, _vr, _extra = join_key_words(
            keys, keys, joins.NULL_EQUAL)
    except ValueError:
        return groupby.groupby_aggregate(keys, values, aggs)
    nparts = _partition_count(total_bytes, budget, parts)
    pid = _partition_ids(kwords, nparts)
    nkeys = len(keys.columns)

    whole = Table(input_cols)
    pidx = [np.nonzero(pid == p)[0] for p in range(nparts)]
    ptables = [gather_table(whole, jnp.asarray(ix.astype(np.int32)))
               for ix in pidx if len(ix)]
    if store is None:
        store = spill_mod.ensure_store()
    handles = _spill_partitions(store, ptables, "ooc_agg", task_id)

    partials: List[Table] = []
    try:
        for h in handles:
            # pinned while the kernel runs (see out_of_core_hash_join)
            with h.pin() as cols:
                pkeys = Table(cols[:nkeys], keys.names)
                pvals = cols[nkeys:]
                # the UNCHANGED in-memory kernel, per partition
                partials.append(
                    groupby.groupby_aggregate(pkeys, pvals, aggs))
            h.close()
    finally:
        for h in handles:
            h.close()
    if not partials:
        return groupby.groupby_aggregate(keys, values, aggs)
    if len(partials) == 1:
        merged = partials[0]
    else:
        from spark_rapids_tpu.ops.copying import concat_tables
        merged = concat_tables(partials)
    # one row per group across all partials; the group-id machinery
    # (position-independent, sorted-key order) yields each row's
    # global position in the in-memory output
    out_keys = Table(list(merged.columns)[:nkeys], keys.names)
    ids, _first, ngroups = groupby._group_ids(out_keys)
    order = np.argsort(np.asarray(ids), kind="stable")
    out = gather_table(merged, jnp.asarray(order.astype(np.int32)))
    names = None
    if keys.names is not None:
        names = list(keys.names) + [f"agg{i}"
                                    for i in range(len(values))]
    return Table(list(out.columns), names)
