"""Join primitives (reference join_primitives.hpp/.cu, JoinPrimitives.java):
sort_merge_inner_join / hash_inner_join -> (left_indices, right_indices)
gather maps, plus the index transforms make_left_outer / make_full_outer /
make_semi / make_anti / get_matched_rows and conditional pair filtering.

TPU-first design (SURVEY.md §7.4): sort-based equality matching — TPUs
have no device hash tables, but argsort/segment ops vectorize well.  Keys
are reduced to per-column total-order rank arrays (floats via the raw-bit
total-order transform, strings via host ordinal ranking for now), combined
lexicographically, and matched by group: both sides' rows are bucketed by
canonical key id, and the inner join emits the per-group cross products.
Pair expansion sizes are data-dependent, so the expansion happens at the
eager boundary (host offsets + device gathers) — the budgeted-chunk
device path is future work.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.utils import floats, native

_I32 = jnp.int32

NULL_EQUAL = "EQUAL"
NULL_UNEQUAL = "UNEQUAL"


def _mask_of(col: Column) -> np.ndarray:
    return (np.ones(col.length, bool) if col.validity is None
            else np.asarray(col.validity).astype(bool))


def _string_buf(col: Column) -> np.ndarray:
    return (np.asarray(col.data) if col.data is not None
            else np.zeros(0, np.uint8))


_STRING_RANK_WORDS_BUDGET = 256 << 20   # packed-word matrix byte cap


def _string_ranks(chars: np.ndarray, offsets: np.ndarray) -> np.ndarray:
    """Dense lexicographic ranks of an Arrow string buffer — native C++
    kernel when available (utils/native.py), packed-word vectorized
    ranking otherwise (ISSUE 9 satellite: the per-row
    ``chars[o[i]:o[i+1]].tobytes()`` python loop was a big slice of the
    11.2s host join)."""
    offsets = np.asarray(offsets, dtype=np.int64)
    ranks = native.rank_strings(chars, offsets)
    if ranks is not None:
        return ranks
    n = len(offsets) - 1
    if n <= 0:
        return np.zeros(0, np.int64)
    lens = np.diff(offsets)
    maxlen = int(lens.max()) if n else 0
    k = max(1, (maxlen + 7) // 8)
    idx_dt = np.int32 if len(chars) < 2**31 else np.int64
    # budget the whole transient, not just the u8 word matrix: the
    # (n, k*8) gather-index matrix below is idx_dt-sized and dominates
    if n * k * 8 * (1 + np.dtype(idx_dt).itemsize) > \
            _STRING_RANK_WORDS_BUDGET:
        # pathological width: the dense matrices would dwarf the
        # data; keep the exact per-row path for this rare shape
        vals = np.array([chars[offsets[i]:offsets[i + 1]].tobytes()
                         for i in range(n)], dtype=object)
        _, inv = np.unique(vals, return_inverse=True)
        return inv.astype(np.int64)
    # big-endian packed u64 words: zero pad preserves byte order, the
    # length column restores shorter-before-longer on equal prefixes
    # (and keeps "a" != "a\x00" injective)
    padded = np.zeros((n, k * 8), np.uint8)
    if len(chars):
        width = np.arange(k * 8, dtype=idx_dt)[None, :]
        idx = offsets[:-1, None].astype(idx_dt) + width
        valid = width < lens[:, None]
        np.minimum(idx, idx_dt(len(chars) - 1), out=idx)
        padded = chars[idx] * valid
    words = np.ascontiguousarray(padded).view(
        np.dtype(">u8")).astype(np.uint64).reshape(n, k)
    cols = [words[:, i] for i in range(k)]
    cols.append(lens.astype(np.uint64))
    ids, _, _ = group_ids_from_ranks(cols)
    return ids.astype(np.int64)


def _column_rank_host(col: Column) -> Tuple[np.ndarray, np.ndarray]:
    """(rank int64 array, null mask) — ranks order rows like the column's
    natural ordering; nulls get rank -1."""
    kind = col.dtype.kind
    mask = _mask_of(col)
    if kind == Kind.STRING:
        rank = _string_ranks(_string_buf(col), np.asarray(col.offsets))
    elif kind == Kind.DECIMAL128:
        _, inv = np.unique(_raw_values(col), return_inverse=True)
        rank = inv.astype(np.int64)
    elif kind == Kind.FLOAT64:
        rank = np.asarray(floats.total_order_key(col.data))
    elif kind == Kind.FLOAT32:
        import jax.numpy as _j
        from jax import lax
        bits = np.asarray(lax.bitcast_convert_type(col.data, _j.uint32))
        flipped = np.where(bits >> 31 != 0, ~bits,
                           bits | np.uint32(1 << 31)).astype(np.int64)
        rank = flipped
    else:
        rank = np.asarray(col.to_numpy()).astype(np.int64, copy=False)
    rank = np.where(mask, rank, 0)
    return rank, mask


def group_ids_from_ranks(rank_cols):
    """(ids, first_index_per_group, ngroups) from per-column rank arrays.
    Single column uses the fast 1-D np.unique; multi-column avoids the
    slow np.unique(axis=0) structured path via lexsort + adjacent-diff."""
    n = len(rank_cols[0]) if rank_cols else 0
    if n == 0:
        return (np.zeros(0, np.int64), np.zeros(0, np.int64), 0)
    if len(rank_cols) == 1:
        uniq, first_idx, ids = np.unique(
            rank_cols[0], return_index=True, return_inverse=True)
        return ids.astype(np.int64), first_idx, len(uniq)
    order = np.lexsort(tuple(reversed(rank_cols)))
    diff = np.zeros(n, bool)
    for c in rank_cols:
        cs = c[order]
        diff[1:] |= cs[1:] != cs[:-1]
    gid_sorted = np.cumsum(diff)  # 0-based after subtracting below
    ids = np.empty(n, np.int64)
    ids[order] = gid_sorted
    ngroups = int(gid_sorted[-1]) + 1
    # stable lexsort: the first sorted element of each group is its
    # earliest original occurrence (np.unique return_index semantics)
    starts = np.concatenate([[0], np.nonzero(diff)[0]])
    first_idx = order[starts]
    return ids, first_idx, ngroups


def _key_ids(left: Table, right: Table, compare_nulls: str):
    """Canonical group id per row of left and right (equal keys <=> equal
    id), plus per-row key-validity (any null key under UNEQUAL = no
    match)."""
    nl, nr = left.num_rows, right.num_rows
    cols = list(zip(left.columns, right.columns))
    ranks = []
    valid_l = np.ones(nl, bool)
    valid_r = np.ones(nr, bool)
    for lc, rc in cols:
        if lc.dtype.kind != rc.dtype.kind:
            raise ValueError("join key dtypes must match")
        if lc.dtype.kind == Kind.STRING:
            # joint ranking over the concatenated Arrow buffers (native
            # C++ rank kernel when available); int64 offsets so the
            # combined buffers may exceed 2^31 bytes
            lm, rm = _mask_of(lc), _mask_of(rc)
            lchars, rchars = _string_buf(lc), _string_buf(rc)
            loffs = np.asarray(lc.offsets).astype(np.int64)
            roffs = np.asarray(rc.offsets).astype(np.int64)
            chars = np.concatenate([lchars, rchars])
            offsets = np.concatenate([loffs, roffs[1:] + len(lchars)])
            inv = _string_ranks(chars, offsets)
            lr, rr = inv[:nl], inv[nl:]
        elif lc.dtype.kind == Kind.DECIMAL128:
            lm, rm = _mask_of(lc), _mask_of(rc)
            lvals, rvals = _raw_values(lc), _raw_values(rc)
            _, inv = np.unique(np.concatenate([lvals, rvals]),
                               return_inverse=True)
            lr, rr = inv[:nl].astype(np.int64), inv[nl:].astype(np.int64)
        else:
            lr, lm = _column_rank_host(lc)
            rr, rm = _column_rank_host(rc)
        # null encoding WITHOUT sentinel values (a sentinel collides with
        # legal ranks like INT64_MIN): the mask itself becomes an extra
        # key column, and null rows zero their value column
        ranks.append((lm.astype(np.int64), rm.astype(np.int64)))
        ranks.append((np.where(lm, lr, np.int64(0)),
                      np.where(rm, rr, np.int64(0))))
        if compare_nulls == NULL_UNEQUAL:
            valid_l &= lm
            valid_r &= rm
    combined = [np.concatenate([a, b]) for a, b in ranks]
    if combined and len(combined[0]):
        ids, _, _ = group_ids_from_ranks(combined)
    else:
        ids = np.zeros(nl + nr, np.int64)
    return ids[:nl], ids[nl:], valid_l, valid_r


def _raw_values(col: Column) -> np.ndarray:
    kind = col.dtype.kind
    if kind == Kind.DECIMAL128:
        limbs = np.asarray(col.data).astype(np.uint32).astype(object)
        vals = (limbs[:, 0] + (limbs[:, 1] << 32) + (limbs[:, 2] << 64)
                + (limbs[:, 3] << 96))
        return np.where(vals >= (1 << 127), vals - (1 << 128), vals)
    raise AssertionError


def _device_key_kind_ok(c: Column) -> bool:
    """Can this column be a device join/group-by key?  Fixed-width and
    decimal128 always; strings up to the word-sort width cap."""
    kind = c.dtype.kind
    if kind in _DEVICE_RANK_KINDS or kind == Kind.DECIMAL128:
        return True
    if kind == Kind.STRING:
        return c.max_string_length() <= DEVICE_STR_KEY_MAX_LEN
    return False


# dtypes whose rank is a pure device transform (no host readback):
# everything fixed-width except decimal128 (multi-word device encoding
# via _decimal_words) and strings (packed-word device encoding via
# _string_words, host native-rank fallback beyond the width cap)
_DEVICE_RANK_KINDS = frozenset({
    Kind.BOOL8, Kind.INT8, Kind.INT16, Kind.INT32, Kind.INT64,
    Kind.UINT8, Kind.UINT16, Kind.UINT32, Kind.UINT64,
    Kind.FLOAT32, Kind.FLOAT64, Kind.TIMESTAMP_DAYS,
    Kind.TIMESTAMP_MICROS, Kind.DECIMAL32, Kind.DECIMAL64})


def _device_rank(col: Column) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(int64 equality-rank, bool mask) computed entirely on device.
    Ranks are injective per value (sufficient for equality joins);
    float ranks also order correctly (total-order bit transform)."""
    from jax import lax

    kind = col.dtype.kind
    if kind == Kind.FLOAT64:
        r = floats.total_order_key(col.data)   # data carries raw bits
    elif kind == Kind.FLOAT32:
        bits = lax.bitcast_convert_type(col.data, jnp.uint32)
        r = jnp.where(bits >> 31 != 0, ~bits,
                      bits | jnp.uint32(1 << 31)).astype(jnp.int64)
    else:
        r = col.data.astype(jnp.int64)  # uint64 wraps but stays injective
    mask = (jnp.ones(col.length, jnp.bool_) if col.validity is None
            else jnp.asarray(col.validity).astype(jnp.bool_))
    return r, mask


# Longest string key that still goes through the device word-sort path
# (comparator width = ceil(maxlen/8)+1 columns per key; beyond this the
# host rank path wins)
DEVICE_STR_KEY_MAX_LEN = 256


def _string_words(col: Column, pad_to: int) -> List[jnp.ndarray]:
    """Exact string equality keys as packed big-endian u64 word columns
    plus the byte length (padding zeros alone would conflate "a" and
    "a\\x00" — the length column restores injectivity).  Entirely on
    device; the joint pad width makes both join sides comparable."""
    chars, lens = col.to_padded_chars(pad_to=max(pad_to, 1))
    rows, L = chars.shape
    k = (L + 7) // 8
    padded = jnp.concatenate(
        [chars, jnp.zeros((rows, k * 8 - L), jnp.uint8)], axis=1)
    bytes_ = padded.reshape(rows, k, 8).astype(jnp.uint64)
    shifts = jnp.asarray(
        np.arange(56, -8, -8, dtype=np.uint64))      # big-endian
    words = (bytes_ << shifts[None, None, :]).sum(
        axis=2, dtype=jnp.uint64)
    out = [words[:, i].astype(jnp.int64) for i in range(k)]
    out.append(lens.astype(jnp.int64))
    return out


def _decimal_words(col: Column) -> List[jnp.ndarray]:
    """decimal128 equality keys: the (n, 4) int32 limb matrix packed
    into two u64 word columns (equality-injective; order irrelevant for
    join/group-by ids)."""
    limbs = col.data.astype(jnp.uint32).astype(jnp.uint64)
    lo = limbs[:, 0] | (limbs[:, 1] << jnp.uint64(32))
    hi = limbs[:, 2] | (limbs[:, 3] << jnp.uint64(32))
    return [lo.astype(jnp.int64), hi.astype(jnp.int64)]


def _device_equality_cols(col: Column, pad_to: int = 0
                          ) -> Optional[List[jnp.ndarray]]:
    """Device int64 equality-key columns for one column, or None when
    the kind has no device path.  Multi-column encodings (strings,
    decimal128) are fine: the sorted-gid core takes any column list."""
    kind = col.dtype.kind
    if kind in _DEVICE_RANK_KINDS:
        r, _ = _device_rank(col)
        return [r]
    if kind == Kind.STRING:
        return _string_words(col, pad_to)
    if kind == Kind.DECIMAL128:
        return _decimal_words(col)
    return None


def _device_key_columns(columns) -> list:
    """int64 equality-key columns for the sorted-gid core.  Nullable
    columns (validity present — a static pytree property) contribute a
    mask column before their value columns: the sentinel-free null
    encoding shared by joins and group-by (a sentinel value would
    collide with legal ranks like INT64_MIN).  All-valid columns skip
    the mask, keeping the sort comparator as narrow as possible —
    comparator width is what drives XLA sort compile/runtime cost."""
    cols = []
    for c in columns:
        pad = c.max_string_length() if c.dtype.kind == Kind.STRING \
            else 0
        vals = _device_equality_cols(c, pad)
        if vals is None:
            raise ValueError(f"no device key path for {c.dtype}")
        if c.validity is not None:
            m = c.validity.astype(jnp.bool_)
            cols.append(m.astype(jnp.int64))
            cols.extend(jnp.where(m, v, jnp.int64(0)) for v in vals)
        else:
            cols.extend(vals)
    return cols


def _sorted_gid_core(cols):
    """(order, gid_sorted): stable sort over the key columns plus
    adjacent-diff group numbering.  Shared device core for join key ids
    and group-by ids.  Uses lax.sort directly: the iota as the final
    sort key gives deterministic (stable) ordering, and the co-sorted
    key columns come back from the same sort — no post-sort gathers."""
    from jax import lax

    n = cols[0].shape[0]
    iota = lax.iota(jnp.int32, n)
    sorted_all = lax.sort(tuple(cols) + (iota,), num_keys=len(cols) + 1)
    order = sorted_all[-1]
    diff = jnp.zeros(n, jnp.bool_)
    for cs in sorted_all[:-1]:
        diff = diff.at[1:].set(diff[1:] | (cs[1:] != cs[:-1]))
    gid_sorted = jnp.cumsum(diff.astype(jnp.int64))
    return order, gid_sorted


def _sort_merge_inner_join_device(left: Table, right: Table,
                                  compare_nulls: str):
    """Device fast path: ranks, joint ids, run search, and pair
    expansion are one XLA program each; only the true pair count crosses
    to the host (to size the output)."""
    from spark_rapids_tpu.ops.device_join import inner_join_device

    nl, nr = left.num_rows, right.num_rows
    if nl == 0 or nr == 0 or not left.columns:
        return (jnp.zeros(0, jnp.int32), jnp.zeros(0, jnp.int32))

    lid, rid, lval, rval = _device_ids(left, right, compare_nulls)
    total = int(_device_join_total(lid, rid, lval, rval))
    if total == 0:
        return (jnp.zeros(0, jnp.int32), jnp.zeros(0, jnp.int32))
    cap = 1 << (total - 1).bit_length()   # pow2-bucketed: few recompiles
    pairs = _device_join_pairs(lid, rid, lval, rval, cap)
    # with capacity >= total the first `total` slots are exactly the
    # valid pairs, in (left row, right sorted-run) order — identical to
    # the host path's layout
    return pairs.left_indices[:total], pairs.right_indices[:total]


# module-level jitted helpers: jax.jit caches on function identity, so
# these compile once per (shape, static arg) instead of once per call
from functools import partial as _partial  # noqa: E402


@jax.jit
def _ids_from_cols_jit(cols):
    order, gid_sorted = _sorted_gid_core(list(cols))
    n = cols[0].shape[0]
    return jnp.zeros(n, jnp.int64).at[order].set(gid_sorted)


def _col_mask(c: Column) -> jnp.ndarray:
    return (jnp.ones(c.length, jnp.bool_) if c.validity is None
            else c.validity.astype(jnp.bool_))


def _device_ids(left: Table, right: Table, compare_nulls: str):
    """Per-row equality ids over the joined key columns.  Eager key
    prep (string pad widths are data-dependent) + one jitted sorted-gid
    program.  The join core only needs an injective int64 key (it sorts
    + searchsorts), so a single all-valid fixed-width key column IS its
    own id — no sort at all; multi-column encodings (strings as packed
    words + length, decimal128 as limb words) and nullable keys pay for
    the sorted-gid pass."""
    nl, nr = left.num_rows, right.num_rows
    key_cols = []
    vl = jnp.ones(nl, jnp.bool_)
    vr = jnp.ones(nr, jnp.bool_)
    for lc, rc in zip(left.columns, right.columns):
        pad = (max(lc.max_string_length(), rc.max_string_length())
               if lc.dtype.kind == Kind.STRING else 0)
        lvals = _device_equality_cols(lc, pad)
        rvals = _device_equality_cols(rc, pad)
        nullable = lc.validity is not None or rc.validity is not None
        if nullable or compare_nulls == NULL_UNEQUAL:
            lm, rm = _col_mask(lc), _col_mask(rc)
        if nullable:
            key_cols.append(jnp.concatenate([lm, rm]).astype(jnp.int64))
            key_cols.extend(
                jnp.concatenate([jnp.where(lm, lv, jnp.int64(0)),
                                 jnp.where(rm, rv, jnp.int64(0))])
                for lv, rv in zip(lvals, rvals))
        else:
            key_cols.extend(jnp.concatenate([lv, rv])
                            for lv, rv in zip(lvals, rvals))
        if compare_nulls == NULL_UNEQUAL:
            vl &= lm
            vr &= rm
    if len(key_cols) == 1:
        ids = key_cols[0]
    else:
        ids = _ids_from_cols_jit(tuple(key_cols))
    return ids[:nl], ids[nl:], vl, vr


@jax.jit
def _device_join_total(lid, rid, lval, rval):
    """Count-only half of inner_join_device: sort + two searchsorteds
    (no reverse map, no pair expansion)."""
    r_sortkey = jnp.where(rval, rid, jnp.int64(2**63 - 1))
    rk_sorted = jnp.sort(r_sortkey)
    n_valid_r = jnp.sum(rval.astype(jnp.int32))
    lo = jnp.minimum(jnp.searchsorted(rk_sorted, lid, side="left"),
                     n_valid_r)
    hi = jnp.minimum(jnp.searchsorted(rk_sorted, lid, side="right"),
                     n_valid_r)
    counts = jnp.where(lval, hi - lo, 0).astype(jnp.int64)
    return jnp.sum(counts)


@_partial(jax.jit, static_argnames=("capacity",))
def _device_join_pairs(lid, rid, lval, rval, capacity: int):
    from spark_rapids_tpu.ops.device_join import inner_join_device

    return inner_join_device(lid, rid, capacity, lval, rval)


# rows (max side) at or above this count earn a measured path pick;
# below it the static default is cheaper than timing anything
JOIN_CALIBRATE_MIN_ROWS = 1 << 15

JOIN_PATHS = ("host_rank", "host_hash", "device_sort", "device_hash")


def _host_hash_inner_join(left_keys: Table, right_keys: Table,
                          compare_nulls: str):
    from spark_rapids_tpu.ops import hash_join as HJ
    lwords, rwords, vl, vr, _extra = HJ.join_key_words(
        left_keys, right_keys, compare_nulls)
    li, ri = HJ.host_hash_join(
        [np.asarray(w) for w in lwords], [np.asarray(w) for w in rwords],
        np.asarray(vl), np.asarray(vr))
    return jnp.asarray(li), jnp.asarray(ri)


def _device_hash_inner_join(left_keys: Table, right_keys: Table,
                            compare_nulls: str):
    from spark_rapids_tpu.ops import hash_join as HJ
    lwords, rwords, vl, vr, extra = HJ.join_key_words(
        left_keys, right_keys, compare_nulls)
    return HJ.device_hash_join(lwords, rwords, vl, vr, extra)


def _join_engines():
    """Name -> engine map, resolved lazily (the host rank oracle is
    defined below this router in file order).  Dict order is the
    calibration measurement order: expected-fast engines first, the
    rank oracle LAST, so a slow oracle that trips the calibration
    budget can only lose to already-measured candidates, never win by
    starving them (perf/calibrate.pick_path's budget discipline)."""
    return {
        "host_hash": _host_hash_inner_join,
        "device_sort": _sort_merge_inner_join_device,
        "device_hash": _device_hash_inner_join,
        "host_rank": _sort_merge_inner_join_host,
    }


def _join_sample(table: Table, rows: int) -> Table:
    if table.num_rows <= rows:
        return table
    from spark_rapids_tpu.ops.copying import slice_table
    return slice_table(table, 0, rows)


def sort_merge_inner_join(left_keys: Table, right_keys: Table,
                          compare_nulls: str = NULL_EQUAL
                          ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(left_indices, right_indices) gather maps of matching row pairs
    (join_primitives.hpp:64).  Pair order: grouped by key, row-order
    within group — identical across every engine.

    Engine choice is a MEASUREMENT, not a backend gate (ISSUE 9): for
    large inputs the per-(schema digest, backend) calibrator times the
    host rank oracle, the numpy bucket hash join, and the two device
    engines on a sample (left side capped, right side full — the build
    side's cache behavior is what separates the engines) and caches
    the verdict.  Small inputs keep the static default (device on
    accelerators, host elsewhere); operators can pin a path with
    SPARK_RAPIDS_TPU_PATH_JOIN_INNER=<engine> or force the legacy
    device gate with SPARK_RAPIDS_TPU_FORCE_DEVICE_JOIN=1."""
    import os

    from spark_rapids_tpu import observability as _obs

    nl, nr = left_keys.num_rows, right_keys.num_rows
    rows = max(nl, nr)
    # both sides must have a device key encoding AND per-column kinds
    # must match (a mismatch falls through to the host path's
    # ValueError); very long string keys rank better on the host
    device_ok = (
        len(left_keys.columns) == len(right_keys.columns)
        and all(lc.dtype.kind == rc.dtype.kind
                and _device_key_kind_ok(lc) and _device_key_kind_ok(rc)
                for lc, rc in zip(left_keys.columns, right_keys.columns)))
    on_accel = jax.default_backend() != "cpu"
    force_device = os.environ.get(
        "SPARK_RAPIDS_TPU_FORCE_DEVICE_JOIN") == "1"

    engines = _join_engines()
    path = None
    if not device_ok or not left_keys.columns:
        path = "host_rank"
    elif force_device:
        path = "device_sort"
    else:
        from spark_rapids_tpu.perf import calibrate
        pin = calibrate.pinned_path("join.inner")
        if pin is not None and pin in engines:
            path = pin
        elif rows < JOIN_CALIBRATE_MIN_ROWS:
            path = "device_sort" if on_accel else "host_rank"
        else:
            from spark_rapids_tpu.perf.jit_cache import schema_digest
            # BOTH sides' schemas and size classes key the verdict
            # (calibrate.operands_digest): the winning engine flips
            # with how much of the build side stays cache-resident,
            # and a probe side that changed size class must not reuse
            # a verdict measured at another scale
            nulls = [lc.validity is not None or rc.validity is not None
                     for lc, rc in zip(left_keys.columns,
                                       right_keys.columns)]
            digest = calibrate.operands_digest(
                [(schema_digest([c.dtype for c in left_keys.columns],
                                nulls), nl),
                 (schema_digest([c.dtype for c in right_keys.columns],
                                nulls), nr)],
                extra=f"join:{compare_nulls}")
            # the build side is bounded too: its size CLASS stays in
            # the digest above, but timing 4 engines x 2 runs over an
            # unbounded build side would stall the first query for
            # minutes (and trip the lifeguard deadline) — a 2^20-row
            # build is enough to separate the engines
            sl = _join_sample(left_keys, 1 << 18)
            sr = _join_sample(right_keys, 1 << 20)
            candidates = {
                name: (lambda fn=fn: fn(sl, sr, compare_nulls))
                for name, fn in engines.items()}
            path = calibrate.pick_path(
                "join.inner", digest, candidates,
                default="device_sort" if on_accel else "host_hash")
            if path not in engines:
                path = "host_rank"
    _obs.record_kernel_path("join.inner", path, rows)
    return engines[path](left_keys, right_keys, compare_nulls)


def _sort_merge_inner_join_host(left_keys: Table, right_keys: Table,
                                compare_nulls: str = NULL_EQUAL
                                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Host rank path (all dtypes incl. strings/decimal128/nested) —
    also the executable oracle for the device path's differential
    tests."""
    lid, rid, lval, rval = _key_ids(left_keys, right_keys, compare_nulls)
    nl = left_keys.num_rows
    # bucket right rows by id
    order_r = np.argsort(rid, kind="stable")
    rid_sorted = rid[order_r]
    # for each left row, locate its id-run in the sorted right side
    starts = np.searchsorted(rid_sorted, lid, side="left")
    ends = np.searchsorted(rid_sorted, lid, side="right")
    counts = ends - starts
    lrows = np.arange(nl)
    if compare_nulls == NULL_UNEQUAL:
        counts = np.where(lval, counts, 0)
    # drop right rows that are invalid under UNEQUAL: since any null key
    # made the whole row invalid, exclude them from the runs
    if compare_nulls == NULL_UNEQUAL and not rval.all():
        keep = rval[order_r]
        # recompute runs against only valid rows
        order_r = order_r[keep]
        rid_sorted = rid[order_r]
        starts = np.searchsorted(rid_sorted, lid, side="left")
        ends = np.searchsorted(rid_sorted, lid, side="right")
        counts = np.where(lval, ends - starts, 0)
    total = int(counts.sum())
    left_out = np.repeat(lrows, counts)
    offs = np.zeros(nl + 1, np.int64)
    np.cumsum(counts, out=offs[1:])
    pos = np.arange(total) - offs[left_out]
    right_out = order_r[starts[left_out] + pos]
    return (jnp.asarray(left_out.astype(np.int32)),
            jnp.asarray(right_out.astype(np.int32)))


def hash_inner_join(left_keys: Table, right_keys: Table,
                    compare_nulls: str = NULL_EQUAL):
    """Same contract as the reference hash_inner_join
    (join_primitives.hpp:87).  Since ISSUE 9 the shared router really
    does own hash-keyed engines (ops/hash_join.py: xxhash64 group ids
    over the word encoding, bucket-table host core / fixed-capacity
    device core), so both entries converge on the calibrated pick."""
    return sort_merge_inner_join(left_keys, right_keys, compare_nulls)


def filter_join_pairs(left_indices: jnp.ndarray,
                      right_indices: jnp.ndarray,
                      predicate: jnp.ndarray):
    """Keep pairs where predicate (bool per pair) holds
    (join_primitives.hpp conditional filtering — the AST predicate is
    evaluated by the caller over gathered pair columns)."""
    keep = np.asarray(predicate).astype(bool)
    li = np.asarray(left_indices)[keep]
    ri = np.asarray(right_indices)[keep]
    return jnp.asarray(li), jnp.asarray(ri)


def make_left_outer(left_indices, right_indices, left_num_rows: int):
    """Add unmatched left rows with right index -1 (null sentinel,
    join_primitives.hpp:145)."""
    li = np.asarray(left_indices)
    ri = np.asarray(right_indices)
    matched = np.zeros(left_num_rows, bool)
    matched[li] = True
    missing = np.nonzero(~matched)[0].astype(li.dtype)
    out_l = np.concatenate([li, missing])
    out_r = np.concatenate([ri, np.full(missing.shape, -1, ri.dtype)])
    return jnp.asarray(out_l), jnp.asarray(out_r)


def make_full_outer(left_indices, right_indices, left_num_rows: int,
                    right_num_rows: int):
    """Unmatched rows from both sides with -1 sentinels
    (join_primitives.hpp:169)."""
    li = np.asarray(left_indices)
    ri = np.asarray(right_indices)
    lmatched = np.zeros(left_num_rows, bool)
    lmatched[li] = True
    rmatched = np.zeros(right_num_rows, bool)
    rmatched[ri] = True
    lmiss = np.nonzero(~lmatched)[0].astype(li.dtype)
    rmiss = np.nonzero(~rmatched)[0].astype(ri.dtype)
    out_l = np.concatenate([li, lmiss, np.full(rmiss.shape, -1, li.dtype)])
    out_r = np.concatenate([ri, np.full(lmiss.shape, -1, ri.dtype), rmiss])
    return jnp.asarray(out_l), jnp.asarray(out_r)


def make_semi(left_indices, left_num_rows: int):
    """Distinct left rows with >=1 match (join_primitives.hpp:194)."""
    li = np.asarray(left_indices)
    matched = np.zeros(left_num_rows, bool)
    matched[li] = True
    return jnp.asarray(np.nonzero(matched)[0].astype(np.int32))


def make_anti(left_indices, left_num_rows: int):
    """Left rows with no match (join_primitives.hpp:213)."""
    li = np.asarray(left_indices)
    matched = np.zeros(left_num_rows, bool)
    matched[li] = True
    return jnp.asarray(np.nonzero(~matched)[0].astype(np.int32))


def get_matched_rows(indices, num_rows: int) -> Column:
    """BOOL8 column marking rows present in the gather map
    (join_primitives.hpp:237)."""
    idx = np.asarray(indices)
    matched = np.zeros(num_rows, bool)
    matched[idx[idx >= 0]] = True
    return Column(dtypes.BOOL8, num_rows,
                  data=jnp.asarray(matched.astype(np.uint8)))
