"""Device decimal128 arithmetic on (rows, 4) int32 limb columns.

The exact host big-int path (ops/decimal_utils.py) is the semantic
reference (reference decimal_utils.cu dec128_multiplier/dec128_adder);
this module runs the same math as vectorized 32-bit limb arithmetic so
large columns never leave the device:

- products via 4x4 schoolbook partial products accumulated in uint64
  (a 256-bit intermediate, like the reference's __int128 chunks);
- rescaling by 10^k as k vectorized divmod-by-10 sweeps (k is static —
  scales are column metadata — so the sweep unrolls at trace time);
- HALF_UP decided by the most significant dropped digit, identical to
  _div_round_half_up;
- overflow = |result| > 10^38-1, reported per row exactly like the host
  path's overflow column.

All helpers operate on uint32 limb matrices little-endian (limb 0 =
least significant), rows vectorized, and are jit-safe.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind

_U32 = jnp.uint32
_U64 = jnp.uint64
_MASK32 = jnp.uint64(0xFFFFFFFF)

MAX_38 = 10**38 - 1
_MAX38_LIMBS = tuple((MAX_38 >> (32 * k)) & 0xFFFFFFFF for k in range(4))


def _mag_sign(limbs_i32: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(rows,4) int32 two's-complement -> ((rows,4) u32 magnitude,
    (rows,) bool negative)."""
    x = limbs_i32
    neg = x[:, 3] < 0
    u = jax.lax.bitcast_convert_type(x, _U32)
    flipped = jnp.where(neg[:, None], ~u, u)
    # +1 with ripple carry for the negate
    carry = neg.astype(_U64)
    out = []
    for k in range(4):
        t = flipped[:, k].astype(_U64) + carry
        out.append((t & _MASK32).astype(_U32))
        carry = t >> jnp.uint64(32)
    return jnp.stack(out, axis=1), neg


def _apply_sign(mag4: jnp.ndarray, neg: jnp.ndarray) -> jnp.ndarray:
    """(rows,4) u32 magnitude + sign -> (rows,4) int32 two's complement."""
    flipped = jnp.where(neg[:, None], ~mag4, mag4)
    carry = neg.astype(_U64)
    out = []
    for k in range(4):
        t = flipped[:, k].astype(_U64) + carry
        out.append((t & _MASK32).astype(_U32))
        carry = t >> jnp.uint64(32)
    return jax.lax.bitcast_convert_type(jnp.stack(out, axis=1),
                                        jnp.int32)


def _mul_4x4(a4: jnp.ndarray, b4: jnp.ndarray) -> jnp.ndarray:
    """(rows,4) u32 x (rows,4) u32 -> (rows,8) u32 full 256-bit product
    (schoolbook partial products in u64; max term 2^64-1 exactly)."""
    rows = a4.shape[0]
    acc = [jnp.zeros(rows, _U64) for _ in range(8)]
    for i in range(4):
        carry = jnp.zeros(rows, _U64)
        ai = a4[:, i].astype(_U64)
        for j in range(4):
            t = acc[i + j] + ai * b4[:, j].astype(_U64) + carry
            acc[i + j] = t & _MASK32
            carry = t >> jnp.uint64(32)
        acc[i + 4] = acc[i + 4] + carry
    return jnp.stack([a.astype(_U32) for a in acc], axis=1)


def _divmod10(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(rows,L) u32 // 10 with remainder, one high-to-low sweep
    (each step value < 10*2^32, fits u64)."""
    L = x.shape[1]
    r = jnp.zeros(x.shape[0], _U64)
    q = [None] * L
    for k in range(L - 1, -1, -1):
        cur = (r << jnp.uint64(32)) | x[:, k].astype(_U64)
        q[k] = (cur // jnp.uint64(10)).astype(_U32)
        r = cur % jnp.uint64(10)
    return jnp.stack(q, axis=1), r


def _mul10(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(rows,L) u32 * 10; returns (product, overflowed-beyond-L-limbs)."""
    L = x.shape[1]
    carry = jnp.zeros(x.shape[0], _U64)
    out = []
    for k in range(L):
        t = x[:, k].astype(_U64) * jnp.uint64(10) + carry
        out.append((t & _MASK32).astype(_U32))
        carry = t >> jnp.uint64(32)
    return jnp.stack(out, axis=1), carry != 0


def _add_one(x: jnp.ndarray, inc: jnp.ndarray) -> jnp.ndarray:
    """x + inc (inc bool per row), ripple carry."""
    carry = inc.astype(_U64)
    out = []
    for k in range(x.shape[1]):
        t = x[:, k].astype(_U64) + carry
        out.append((t & _MASK32).astype(_U32))
        carry = t >> jnp.uint64(32)
    return jnp.stack(out, axis=1)


def _rescale_down(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """x // 10^k with HALF_UP (k static; the most significant dropped
    digit alone decides the rounding, as in _div_round_half_up)."""
    if k <= 0:
        return x
    for _ in range(k - 1):
        x, _ = _divmod10(x)
    x, r = _divmod10(x)
    return _add_one(x, r >= 5)


def _scale_up(x: jnp.ndarray, k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x * 10^k (k static); returns (result, overflowed)."""
    ovf = jnp.zeros(x.shape[0], jnp.bool_)
    for _ in range(k):
        x, o = _mul10(x)
        ovf = ovf | o
    return x, ovf


def _exceeds_max38(x: jnp.ndarray) -> jnp.ndarray:
    """(rows,L) u32 magnitude > 10^38-1 (per row)."""
    high_nonzero = jnp.zeros(x.shape[0], jnp.bool_)
    for k in range(4, x.shape[1]):
        high_nonzero = high_nonzero | (x[:, k] != 0)
    # lexicographic compare of the low 4 limbs against MAX_38
    gt = jnp.zeros(x.shape[0], jnp.bool_)
    eq = jnp.ones(x.shape[0], jnp.bool_)
    for k in range(3, -1, -1):
        lim = _U32(_MAX38_LIMBS[k])
        gt = gt | (eq & (x[:, k] > lim))
        eq = eq & (x[:, k] == lim)
    return high_nonzero | gt


def _widen(x4: jnp.ndarray, limbs: int) -> jnp.ndarray:
    pad = jnp.zeros((x4.shape[0], limbs - x4.shape[1]), _U32)
    return jnp.concatenate([x4, pad], axis=1)


@partial(jax.jit, static_argnames=("a_scale", "b_scale", "product_scale"))
def _multiply_core(a_limbs, b_limbs, a_scale: int, b_scale: int,
                   product_scale: int):
    amag, aneg = _mag_sign(a_limbs)
    bmag, bneg = _mag_sign(b_limbs)
    p = _mul_4x4(amag, bmag)                       # (rows, 8)
    neg = aneg ^ bneg
    exponent = product_scale - (a_scale + b_scale)
    ovf = jnp.zeros(p.shape[0], jnp.bool_)
    if exponent > 0:
        p = _rescale_down(p, exponent)
    elif exponent < 0:
        if exponent <= -38:
            # host-path parity: the precision pre-check flags even a
            # ZERO product when precision10(0)=1 minus exponent exceeds
            # 38 (decimal_utils.multiply_decimal128); the magnitude
            # check below can never catch 0 * 10^k
            is_zero = jnp.ones(p.shape[0], jnp.bool_)
            for k in range(p.shape[1]):
                is_zero = is_zero & (p[:, k] == 0)
            ovf = ovf | is_zero
        p, o = _scale_up(p, -exponent)
        ovf = ovf | o
    ovf = ovf | _exceeds_max38(p)
    return ovf, _apply_sign(p[:, :4], neg)


@partial(jax.jit, static_argnames=("a_scale", "b_scale", "out_scale",
                                   "sub"))
def _add_sub_core(a_limbs, b_limbs, a_scale: int, b_scale: int,
                  out_scale: int, sub: bool):
    s = min(a_scale, b_scale)
    amag, aneg = _mag_sign(a_limbs)
    bmag, bneg = _mag_sign(b_limbs)
    if sub:
        bneg = ~bneg
    # limb budget sized to the STATIC upscale so a legitimately-huge
    # intermediate (big scale gap, later divided back down) stays exact:
    # 10^k < 2^(4k), plus one limb of headroom for the add
    max_shift = max(a_scale - s, b_scale - s)
    wide = _limbs_for_shift(max_shift)
    x, oa = _scale_up(_widen(amag, wide), a_scale - s)
    y, ob = _scale_up(_widen(bmag, wide), b_scale - s)
    x7 = _apply_sign_wide(x, aneg)
    y7 = _apply_sign_wide(y, bneg)
    v = _add_wide(x7, y7)
    vneg = (jax.lax.bitcast_convert_type(v[:, -1:], jnp.int32)
            [:, 0] < 0)
    vmag = _negate_if(v, vneg)
    shift = out_scale - s
    ovf = oa | ob
    if shift < 0:
        vmag, o = _scale_up(vmag, -shift)
        ovf = ovf | o
    elif shift > 0:
        vmag = _rescale_down(vmag, shift)
    ovf = ovf | _exceeds_max38(vmag)
    return ovf, _apply_sign(vmag[:, :4], vneg)


def _apply_sign_wide(mag: jnp.ndarray, neg: jnp.ndarray) -> jnp.ndarray:
    flipped = jnp.where(neg[:, None], ~mag, mag)
    return _add_one(flipped, neg)


def _negate_if(x: jnp.ndarray, neg: jnp.ndarray) -> jnp.ndarray:
    flipped = jnp.where(neg[:, None], ~x, x)
    return _add_one(flipped, neg)


def _add_wide(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    carry = jnp.zeros(x.shape[0], _U64)
    out = []
    for k in range(x.shape[1]):
        t = x[:, k].astype(_U64) + y[:, k].astype(_U64) + carry
        out.append((t & _MASK32).astype(_U32))
        carry = t >> jnp.uint64(32)
    return jnp.stack(out, axis=1)


# ------------------------------------------------------- Column wrappers

def _check(a: Column, b: Column):
    if (a.dtype.kind != Kind.DECIMAL128
            or b.dtype.kind != Kind.DECIMAL128):
        raise ValueError("decimal128 columns required")
    if a.length != b.length:
        raise ValueError("length mismatch")


def _wrap(ovf, limbs, a: Column, b: Column, out_scale: int):
    from spark_rapids_tpu.ops.arithmetic import _combined_validity

    mask = _combined_validity(a, b)  # device-side; None = all valid
    ovf_col = Column(dtypes.BOOL8, a.length,
                     data=ovf.astype(jnp.uint8), validity=mask)
    out = Column(dtypes.decimal128(out_scale), a.length, data=limbs,
                 validity=mask)
    return ovf_col, out


def multiply128_device(a: Column, b: Column, product_scale: int):
    """Device counterpart of decimal_utils.multiply_decimal128 (without
    the SPARK-40129 interim cast — the host path covers that legacy
    mode)."""
    _check(a, b)
    ovf, limbs = _multiply_core(a.data, b.data, a.dtype.scale,
                                b.dtype.scale, product_scale)
    return _wrap(ovf, limbs, a, b, product_scale)


def add128_device(a: Column, b: Column, out_scale: int):
    _check(a, b)
    ovf, limbs = _add_sub_core(a.data, b.data, a.dtype.scale,
                               b.dtype.scale, out_scale, False)
    return _wrap(ovf, limbs, a, b, out_scale)


def sub128_device(a: Column, b: Column, out_scale: int):
    _check(a, b)
    ovf, limbs = _add_sub_core(a.data, b.data, a.dtype.scale,
                               b.dtype.scale, out_scale, True)
    return _wrap(ovf, limbs, a, b, out_scale)


# --------------------------------------------------- division / remainder

def _shl1_inject(x: jnp.ndarray, bit: jnp.ndarray) -> jnp.ndarray:
    """(rows,L) u32 << 1 with `bit` (rows,) injected at bit 0."""
    hi = x >> _U32(31)
    shifted = x << _U32(1)
    carry_in = jnp.concatenate(
        [bit.astype(_U32)[:, None], hi[:, :-1]], axis=1)
    return shifted | carry_in


def _ge_limbs(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """x >= y, limbwise lexicographic from the top (per row)."""
    gt = jnp.zeros(x.shape[0], jnp.bool_)
    eq = jnp.ones(x.shape[0], jnp.bool_)
    for k in range(x.shape[1] - 1, -1, -1):
        gt = gt | (eq & (x[:, k] > y[:, k]))
        eq = eq & (x[:, k] == y[:, k])
    return gt | eq


def _sub_limbs(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """x - y (x >= y assumed), ripple borrow."""
    borrow = jnp.zeros(x.shape[0], _U64)
    out = []
    for k in range(x.shape[1]):
        t = (x[:, k].astype(_U64) | (jnp.uint64(1) << jnp.uint64(32))) \
            - y[:, k].astype(_U64) - borrow
        out.append((t & _MASK32).astype(_U32))
        borrow = jnp.uint64(1) - (t >> jnp.uint64(32))
    return jnp.stack(out, axis=1)


def _long_divide(num: jnp.ndarray, den: jnp.ndarray,
                 num_bits: int | None = None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Restoring binary long division, vectorized over rows.
    num (rows,N) u32 / den (rows,N) u32 -> (quotient, remainder), both
    (rows,N).  Caller guarantees den != 0 (zero rows are masked out and
    flagged upstream).  `num_bits` statically bounds the numerator's
    bit-length — the loop runs num_bits iterations, not 32*N, which is
    the difference between ~280 and ~480 rounds for deep scale gaps."""
    rows, N = num.shape
    total_bits = min(num_bits, N * 32) if num_bits else N * 32

    def body(i, st):
        rem, q = st
        k = total_bits - 1 - i
        limb = jax.lax.dynamic_index_in_dim(
            num, k // 32, axis=1, keepdims=False)
        bit = (limb >> (k % 32).astype(_U32)) & _U32(1)
        rem = _shl1_inject(rem, bit)
        ge = _ge_limbs(rem, den)
        rem = jnp.where(ge[:, None], _sub_limbs(rem, den), rem)
        qlimb = jax.lax.dynamic_index_in_dim(
            q, k // 32, axis=1, keepdims=False)
        qlimb = qlimb | (ge.astype(_U32) << (k % 32).astype(_U32))
        q = jax.lax.dynamic_update_index_in_dim(
            q, qlimb, k // 32, axis=1)
        return rem, q

    rem0 = jnp.zeros((rows, N), _U32)
    q0 = jnp.zeros((rows, N), _U32)
    rem, q = jax.lax.fori_loop(0, total_bits, body, (rem0, q0))
    return q, rem


def _limbs_for_shift(shift: int) -> int:
    return 4 + (abs(shift) * 4 + 31) // 32 + 1


def _bits_for_shift(shift: int) -> int:
    """Static bit bound for a 128-bit magnitude scaled up by 10^shift
    (10^k < 2^(4k))."""
    return 128 + 4 * max(shift, 0) + 1


def _is_zero_mag(mag: jnp.ndarray) -> jnp.ndarray:
    """(rows,) bool: every limb zero."""
    z = jnp.ones(mag.shape[0], jnp.bool_)
    for k in range(mag.shape[1]):
        z = z & (mag[:, k] == 0)
    return z


def _replace_zero_den(den: jnp.ndarray,
                      div_zero: jnp.ndarray) -> jnp.ndarray:
    """Zero divisors (flagged upstream) divide by 1 so the long
    division stays well-defined; their values are unspecified."""
    one = jnp.concatenate(
        [jnp.ones((den.shape[0], 1), _U32),
         jnp.zeros((den.shape[0], den.shape[1] - 1), _U32)], axis=1)
    return jnp.where(div_zero[:, None], one, den)


@partial(jax.jit, static_argnames=("a_scale", "b_scale",
                                   "quotient_scale", "integer_divide"))
def _divide_core(a_limbs, b_limbs, a_scale: int, b_scale: int,
                 quotient_scale: int, integer_divide: bool):
    shift = a_scale - b_scale - quotient_scale
    num_bits = _bits_for_shift(shift)
    wide = max((num_bits + 31) // 32,
               (_bits_for_shift(-shift) + 31) // 32)
    amag, aneg = _mag_sign(a_limbs)
    bmag, bneg = _mag_sign(b_limbs)
    div_zero = _is_zero_mag(bmag)
    num = _widen(amag, wide)
    den = _widen(bmag, wide)
    ovf = jnp.zeros(a_limbs.shape[0], jnp.bool_)
    if shift >= 0:
        num, o = _scale_up(num, shift)
    else:
        den, o = _scale_up(den, -shift)
    ovf = ovf | o
    den = _replace_zero_den(den, div_zero)
    q, rem = _long_divide(num, den, num_bits=num_bits)
    neg = aneg ^ bneg
    if not integer_divide:
        # HALF_UP on the magnitude: round up when 2*rem >= den
        rem2, c = _mul_by_2(rem)
        up = (_ge_limbs(rem2, den) | c) & ~div_zero
        q = _add_one(q, up)
    ovf = ovf | div_zero | _exceeds_max38(q)
    if integer_divide:
        # Spark integral division bounds the result to int64
        # (dec128_divider is_int_div path)
        int64_ovf = jnp.zeros(q.shape[0], jnp.bool_)
        for k in range(2, q.shape[1]):
            int64_ovf = int64_ovf | (q[:, k] != 0)
        hi = q[:, 1]
        # |q| must be <= 2^63-1 (or 2^63 when negative)
        too_big = (hi > _U32(0x7FFFFFFF)) | int64_ovf
        exactly_min = (hi == _U32(0x80000000)) & (q[:, 0] == 0) \
            & ~int64_ovf
        ovf = ovf | jnp.where(neg, too_big & ~exactly_min, too_big)
    return ovf, _apply_sign(q[:, :4], neg)


def _mul_by_2(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    carry_out = x[:, -1] >> _U32(31) != 0
    return _shl1_inject(x, jnp.zeros(x.shape[0], jnp.bool_)), carry_out


@partial(jax.jit, static_argnames=("a_scale", "b_scale",
                                   "remainder_scale"))
def _remainder_core(a_limbs, b_limbs, a_scale: int, b_scale: int,
                    remainder_scale: int):
    s = min(a_scale, b_scale)
    # width is driven by the ALIGNMENT upscales only; the remainder is
    # re-widened after the division if the output rescale needs it
    num_bits = _bits_for_shift(a_scale - s)
    wide = max((num_bits + 31) // 32,
               (_bits_for_shift(b_scale - s) + 31) // 32)
    amag, aneg = _mag_sign(a_limbs)
    bmag, _ = _mag_sign(b_limbs)
    div_zero = _is_zero_mag(bmag)
    x, oa = _scale_up(_widen(amag, wide), a_scale - s)
    y, ob = _scale_up(_widen(bmag, wide), b_scale - s)
    y = _replace_zero_den(y, div_zero)
    _, rem = _long_divide(x, y, num_bits=num_bits)
    shift = remainder_scale - s
    ovf = oa | ob
    if shift < 0:
        need = (_bits_for_shift(b_scale - s) + 4 * (-shift) + 31) \
            // 32 + 1
        if need > rem.shape[1]:
            rem = _widen(rem, need)
        rem, o = _scale_up(rem, -shift)
        ovf = ovf | o
    elif shift > 0:
        rem = _rescale_down(rem, shift)
    ovf = ovf | div_zero | _exceeds_max38(rem)
    return ovf, _apply_sign(rem[:, :4], aneg)   # sign follows the dividend


def divide128_device(a: Column, b: Column, quotient_scale: int,
                     integer_divide: bool = False):
    """Device counterpart of decimal_utils.divide_decimal128
    (dec128_divider): restoring binary long division on u32 limbs,
    HALF_UP (or truncation for integral division with int64 bounds);
    division by zero flags overflow."""
    _check(a, b)
    ovf, limbs = _divide_core(a.data, b.data, a.dtype.scale,
                              b.dtype.scale, quotient_scale,
                              integer_divide)
    return _wrap(ovf, limbs, a, b, quotient_scale)


def integer_divide128_device(a: Column, b: Column, quotient_scale: int):
    return divide128_device(a, b, quotient_scale, integer_divide=True)


def remainder128_device(a: Column, b: Column, remainder_scale: int):
    """Device counterpart of decimal_utils.remainder_decimal128:
    truncated-division remainder with the dividend's sign."""
    _check(a, b)
    ovf, limbs = _remainder_core(a.data, b.data, a.dtype.scale,
                                 b.dtype.scale, remainder_scale)
    return _wrap(ovf, limbs, a, b, remainder_scale)
