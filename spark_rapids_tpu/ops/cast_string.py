"""CAST(string AS int/long/float/double) with Spark semantics.

Reference: src/main/cpp/src/cast_string.cu (string_to_integer_kernel
:163-250 — whitespace/C0 stripping, optional +/- sign, digits with
per-step overflow detection, non-ANSI truncation at '.', trailing
whitespace tolerance) and cast_string_to_float.cu (sign, digits, decimal
point, e/E exponent, case-insensitive inf/infinity/nan).

TPU-first design: the per-row character march becomes a vectorized DFA —
one lax.scan over the padded char axis carrying (state, value, sign, ...)
lanes for every row simultaneously.  ANSI mode surfaces the first failing
row as CastException (exception_with_row_index.hpp analog) at the eager
boundary.

Float conversion routes through host strtod (correctly rounded — what
the reference's 128-bit path guarantees); validation rules match the
device DFA.
"""

from __future__ import annotations

import re
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType, Kind
from spark_rapids_tpu.ops.exceptions import CastException

_I32 = jnp.int32
_I64 = jnp.int64
_U8 = jnp.uint8

# DFA states for integer parsing
_S_LEAD = 0      # skipping leading whitespace / expecting sign or digit
_S_DIGITS = 1    # consuming digits
_S_TRUNC = 2     # after '.', consuming (and ignoring) fraction digits
_S_TRAIL = 3     # consuming trailing whitespace
_S_INVALID = 4


def _is_ws(c):
    return (c <= _U8(0x1F)) | (c == _U8(0x20))


def _int_limits(dt: DType) -> Tuple[int, int]:
    info = np.iinfo(dt.np_dtype)
    return int(info.min), int(info.max)


def string_to_integer(col: Column, dtype: DType, ansi_mode: bool = False,
                      strip: bool = True) -> Column:
    """Spark CAST(string AS integral) (CastStrings.toInteger:39)."""
    assert col.dtype.is_string
    rows = col.length
    if rows == 0:
        return Column(dtype, 0, data=jnp.zeros(0, dtype.np_dtype))
    chars, lens = col.to_padded_chars()
    p = chars.shape[1]
    minval, maxval = _int_limits(dtype)
    signed_target = np.dtype(dtype.np_dtype).kind == "i"

    state0 = jnp.where(lens > 0, _S_LEAD, _S_INVALID).astype(_I32)
    carry0 = (
        state0,
        jnp.zeros(rows, _I64),                   # value
        jnp.ones(rows, _I64),                    # sign
        jnp.zeros(rows, jnp.bool_),              # seen_digit
    )

    def step(carry, xs):
        i, c = xs
        state, value, sign, seen_digit = carry
        in_range = i < lens
        ws = _is_ws(c)
        digit = (c >= _U8(48)) & (c <= _U8(57))
        dval = (c - _U8(48)).astype(_I64)

        # --- LEAD: optional whitespace*, then sign?, then first digit.
        # "sign consumed" is encoded by switching to DIGITS with
        # seen_digit=False; ending there (bare sign) is invalid.
        lead = state == _S_LEAD
        if signed_target:
            is_sign = (c == _U8(43)) | (c == _U8(45))
        else:  # reference consumes signs only for signed types
            is_sign = jnp.zeros_like(ws)
        dot = c == _U8(46)
        stay_ws = (lead & ws) if strip else jnp.zeros_like(ws)
        take_sign = lead & is_sign
        new_sign = jnp.where(take_sign & (c == _U8(45)),
                             jnp.int64(-1), sign)
        next_state = state
        next_state = jnp.where(lead & stay_ws, _S_LEAD, next_state)
        next_state = jnp.where(take_sign, _S_DIGITS, next_state)
        next_state = jnp.where(lead & digit, _S_DIGITS, next_state)
        # '.' as the first body char truncates to 0 in non-ANSI mode
        # (cast_string.cu: the char loop treats '.' identically wherever
        # it appears, so "." / "+.5" are VALID zeros)
        next_state = jnp.where(lead & dot & ~stay_ws,
                               _S_INVALID if ansi_mode else _S_TRUNC,
                               next_state)
        next_state = jnp.where(
            lead & ~stay_ws & ~take_sign & ~digit & ~dot, _S_INVALID,
            next_state)

        # --- DIGITS
        in_digits = (state == _S_DIGITS) | (lead & digit)
        adding = new_sign > 0
        # value accumulation with overflow checks (cast_string.cu:122-150)
        ovf_mul = jnp.where(adding, value > maxval // 10,
                            value < -((-minval) // 10))
        val10 = value * 10
        first = ~seen_digit
        base = jnp.where(first, jnp.int64(0), val10)
        ovf_mul = jnp.where(first, False, ovf_mul)
        ovf_add = jnp.where(adding, base > maxval - dval,
                            base < minval + dval)
        new_value = jnp.where(adding, base + dval, base - dval)
        overflow = in_digits & digit & in_range & (ovf_mul | ovf_add)

        take_digit = in_digits & digit & in_range
        value = jnp.where(take_digit, new_value, value)
        seen_digit = seen_digit | take_digit

        next_state = jnp.where(in_digits & digit, _S_DIGITS, next_state)
        # '.' truncates in non-ANSI mode (only valid after >=1 digit? the
        # reference allows '.' anywhere in digits run; digits before are
        # kept) — in ANSI mode '.' is invalid
        if not ansi_mode:
            next_state = jnp.where((state == _S_DIGITS) & dot, _S_TRUNC,
                                   next_state)
        else:
            next_state = jnp.where((state == _S_DIGITS) & dot, _S_INVALID,
                                   next_state)
        trail_ok = (seen_digit | take_digit) if strip else \
            jnp.zeros_like(seen_digit)
        next_state = jnp.where(
            (state == _S_DIGITS) & ws & trail_ok, _S_TRAIL, next_state)
        next_state = jnp.where(
            (state == _S_DIGITS) & ~digit & ~dot & ~(ws & trail_ok),
            _S_INVALID, next_state)

        # --- TRUNC: digits ignored; whitespace moves to TRAIL (strip);
        # anything else invalid
        in_trunc = state == _S_TRUNC
        next_state = jnp.where(in_trunc & digit, _S_TRUNC, next_state)
        next_state = jnp.where(in_trunc & ws & jnp.bool_(strip), _S_TRAIL,
                               next_state)
        next_state = jnp.where(in_trunc & ~digit & ~ws, _S_INVALID,
                               next_state)
        next_state = jnp.where(in_trunc & ws & ~jnp.bool_(strip),
                               _S_INVALID, next_state)

        # --- TRAIL: only whitespace allowed
        in_trail = state == _S_TRAIL
        next_state = jnp.where(in_trail & ~ws, _S_INVALID, next_state)

        next_state = jnp.where(overflow, _S_INVALID, next_state)
        next_state = jnp.where(in_range, next_state, state)
        sign = jnp.where(in_range, new_sign, sign)
        return (next_state, value, sign, seen_digit), None

    (state, value, sign, seen_digit), _ = lax.scan(
        step, carry0,
        (jnp.arange(p, dtype=_I32), chars.T))

    # valid end states: digits seen, or truncated-at-dot (possibly with
    # trailing ws); LEAD (only ws/sign) and INVALID are not
    valid = (((state == _S_DIGITS) & seen_digit)
             | (state == _S_TRUNC) | (state == _S_TRAIL))
    base_valid = col.valid_mask()
    out_valid = base_valid & valid
    result = value.astype(dtype.np_dtype)

    if ansi_mode:
        bad = np.asarray(base_valid & ~valid)
        if bad.any():
            row = int(np.argmax(bad))
            raise CastException(row, col.to_pylist()[row])
        return Column(dtype, rows, data=result, validity=col.validity)
    return Column(dtype, rows, data=result,
                  validity=out_valid.astype(jnp.uint8))


# ----------------------------------------------------------------- float


def _match_word(chars, lens, start, word: bytes):
    """Rows where chars[start:start+len(word)] case-insensitively equals
    word and the string ends there (or only whitespace follows is NOT
    allowed here — caller handles)."""
    p = chars.shape[1]
    ok = lens - start == len(word)
    for j, wc in enumerate(word):
        idx = jnp.clip(start + j, 0, p - 1)
        c = jnp.take_along_axis(chars, idx[:, None], axis=1)[:, 0]
        lower = jnp.where((c >= _U8(65)) & (c <= _U8(90)), c + _U8(32), c)
        ok = ok & (lower == _U8(wc))
    return ok


def _float_parse_one(s: bytes, np_dt):
    """(value, ok) for one stripped-input row — the libc-exact oracle
    shared by the host loop and the device path's fallback rows."""
    t = s.strip(b" \t\r\n\x0b\x0c\x00\x01\x02\x03\x04\x05\x06\x07\x08"
                b"\x0e\x0f\x10\x11\x12\x13\x14\x15\x16\x17\x18\x19"
                b"\x1a\x1b\x1c\x1d\x1e\x1f")
    if not t:
        return 0.0, False
    body = t
    sign = 1.0
    had_sign = body[:1] in (b"+", b"-")
    if had_sign:
        if body[:1] == b"-":
            sign = -1.0
        body = body[1:]
    low = body.lower()
    if low in (b"inf", b"infinity"):
        return sign * np.inf, True
    if low == b"nan":
        # Spark rejects signed NaN ("+naN"/"-nAn" -> null,
        # castToFloatNanTest) but accepts signed Infinity
        return (np.nan, True) if not had_sign else (0.0, False)
    if b"_" in t:  # python float() extension Java/Spark don't have
        return 0.0, False
    try:
        v = float(t)
    except ValueError:
        return 0.0, False
    return np_dt(v), True


def _float_host_rows(col: Column, idx: np.ndarray, is_f32: bool):
    """(bits u64, ok bool) for the selected rows via the host oracle
    (used by ops/stod_device.py for its fallback rows)."""
    chars_host = np.asarray(col.data).tobytes() if col.data is not None \
        else b""
    offs = np.asarray(col.offsets)
    np_dt = np.float32 if is_f32 else np.float64
    bits = np.zeros(len(idx), np.uint64)
    ok = np.zeros(len(idx), bool)
    for k, i in enumerate(idx):
        v, good = _float_parse_one(chars_host[offs[i]:offs[i + 1]],
                                   np_dt)
        ok[k] = good
        if good:
            if is_f32:
                bits[k] = np.float32(v).view(np.uint32)
            else:
                bits[k] = np.float64(v).view(np.uint64)
    return bits, ok


def string_to_float(col: Column, dtype: DType = dtypes.FLOAT64,
                    ansi_mode: bool = False) -> Column:
    """Spark CAST(string AS float/double) (CastStrings.toFloat:66,
    cast_string_to_float.cu).  Columns above the routing threshold run
    the vectorized Eisel-Lemire device path (ops/stod_device.py) with
    per-row host fallback; this host loop is the differential oracle
    (SPARK_RAPIDS_TPU_STOD=host|device overrides)."""
    assert col.dtype.is_string
    from spark_rapids_tpu.ops import stod_device

    if stod_device.use_device(col):
        return stod_device.string_to_float_device(col, dtype, ansi_mode)
    rows = col.length
    np_dt = np.float32 if dtype.kind == Kind.FLOAT32 else np.float64
    if rows == 0:
        data = np.zeros(0, np_dt)
        if dtype.kind == Kind.FLOAT64:
            data = data.view(np.uint64)
        return Column(dtype, 0, data=jnp.asarray(data))

    # Host-vectorized parse: validation mirrors the device DFA rules but
    # float conversion wants libc exactness; strings are already host-
    # resident at the shim boundary in the eager path.
    chars_host = np.asarray(col.data).tobytes() if col.data is not None \
        else b""
    offs = np.asarray(col.offsets)
    base_valid = np.asarray(col.valid_mask())
    out = np.zeros(rows, np_dt)
    valid = np.zeros(rows, bool)
    for i in range(rows):
        if not base_valid[i]:
            continue
        v, ok = _float_parse_one(chars_host[offs[i]:offs[i + 1]], np_dt)
        if ok:
            out[i] = v
            valid[i] = True

    if ansi_mode:
        bad = base_valid & ~valid
        if bad.any():
            row = int(np.argmax(bad))
            raise CastException(row, col.to_pylist()[row])
        validity = col.validity
    else:
        validity = jnp.asarray(valid.astype(np.uint8))
    data = out.view(np.uint64) if dtype.kind == Kind.FLOAT64 else out
    return Column(dtype, rows, data=jnp.asarray(data), validity=validity)


# ----------------------------------------------------------- float → str


def _java_double_repr(v: float, is_f32: bool) -> str:
    """Java Double.toString / Float.toString formatting: shortest decimal
    that round-trips, plain notation for 1e-3 <= |v| < 1e7, otherwise
    E-notation with one leading digit (ftos_converter.cuh semantics)."""
    if np.isnan(v):
        return "NaN"
    if np.isinf(v):
        return "Infinity" if v > 0 else "-Infinity"
    if v == 0.0:
        return "-0.0" if np.signbit(v) else "0.0"
    neg = v < 0
    a = -v if neg else v
    if is_f32:
        digits = np.format_float_scientific(
            np.float32(a), unique=True, trim="-").replace("e+0", "e+") \
            .replace("e-0", "e-")
    else:
        digits = np.format_float_scientific(a, unique=True, trim="-")
    # parse "d.ddde[+-]xx"
    mant, _, exp_s = digits.partition("e")
    exp = int(exp_s)
    mant = mant.replace(".", "")
    if -3 <= exp < 7:
        if exp >= 0:
            int_part = mant[:exp + 1].ljust(exp + 1, "0")
            frac = mant[exp + 1:] or "0"
            body = f"{int_part}.{frac}"
        else:
            body = "0." + "0" * (-exp - 1) + mant
    else:
        frac = mant[1:] or "0"
        body = f"{mant[0]}.{frac}E{exp}"
    return ("-" if neg else "") + body


def float_to_string(col: Column) -> Column:
    """Spark-compatible float->string (CastStrings.fromFloat:103).
    Columns above the routing threshold run the vectorized device Ryu
    digit engine (ops/ftos_device.py, the ftos_converter.cuh analog);
    this host path is the differential oracle (SPARK_RAPIDS_TPU_FTOS=
    host|device overrides)."""
    assert col.dtype.kind in (Kind.FLOAT32, Kind.FLOAT64)
    from spark_rapids_tpu.ops import ftos_device

    if ftos_device.use_device(col):
        return ftos_device.float_to_string_device(col)
    host = col.to_numpy()
    is_f32 = col.dtype.kind == Kind.FLOAT32
    mask = np.asarray(col.valid_mask())
    vals = [
        _java_double_repr(float(host[i]), is_f32) if mask[i] else None
        for i in range(col.length)
    ]
    return Column.from_strings(vals)


# ------------------------------------------------------ string -> decimal

_DEC_RE_STRIP = re.compile(
    r"^[\x00-\x1f ]*([+-]?)(\d*)(?:\.(\d*))?(?:[eE]([+-]?\d+))?"
    r"[\x00-\x1f ]*$")
_DEC_RE_NOSTRIP = re.compile(
    r"^([+-]?)(\d*)(?:\.(\d*))?(?:[eE]([+-]?\d+))?$")


def string_to_decimal(col: Column, precision: int, scale: int,
                      ansi_mode: bool = False,
                      strip: bool = True) -> Column:
    """Spark CAST(string AS DECIMAL(precision, scale))
    (cast_string.hpp:97 string_to_decimal; CastStrings.toDecimal):
    optional sign/decimal point/exponent, HALF_UP rounding to the target
    scale, null (or ANSI row error) when invalid or when the value does
    not fit `precision` digits.  Output type by precision: decimal32
    (<=9), decimal64 (<=18), else decimal128 — cudf scale convention
    (negative = fractional digits)."""
    assert col.dtype.is_string
    vals = col.to_pylist()
    rx = _DEC_RE_STRIP if strip else _DEC_RE_NOSTRIP
    out = []
    for s in vals:
        if s is None:
            out.append(None)
            continue
        m = rx.match(s)
        if not m:
            out.append(None)
            continue
        sign_s, ipart, fpart, exp_s = m.groups()
        ipart = ipart or ""
        fpart = fpart or ""
        if not ipart and not fpart:
            out.append(None)
            continue
        digits = int((ipart + fpart) or "0")
        exp10 = (int(exp_s) if exp_s else 0) - len(fpart)
        # unscaled at target scale: value * 10^{-scale}
        shift = exp10 - scale
        # bound the power before computing it exactly: a hostile
        # exponent ("1e2147483647") must not allocate a gigabyte int
        ndig = len(str(abs(digits))) if digits else 0
        if digits == 0:
            shift = 0
        elif shift > precision:
            out.append(None)  # unscaled >= 10^shift > 10^precision
            continue
        elif shift < -(ndig + 1):
            digits, shift = 0, 0  # |value| < 0.1 -> rounds to 0
        if shift >= 0:
            unscaled = digits * 10**shift
        else:
            d = 10 ** (-shift)
            unscaled = (2 * digits + d) // (2 * d)  # HALF_UP (positive)
        if sign_s == "-":
            unscaled = -unscaled
        if abs(unscaled) >= 10**precision:
            out.append(None)  # doesn't fit the requested precision
            continue
        out.append(unscaled)
    base_valid = np.asarray(col.valid_mask())
    computed = np.array([v is not None for v in out])
    if ansi_mode:
        bad = base_valid & ~computed
        if bad.any():
            row = int(np.argmax(bad))
            raise CastException(row, vals[row])
    if precision <= 9:
        dt = dtypes.decimal32(scale)
    elif precision <= 18:
        dt = dtypes.decimal64(scale)
    else:
        dt = dtypes.decimal128(scale)
    return Column.from_pylist(out, dt)


# ------------------------------------------- integer <-> string with base

def string_to_integers_with_base(col: Column, base: int,
                                 ansi_mode: bool = False,
                                 dtype: DType = dtypes.UINT64) -> Column:
    """CastStrings.toIntegersWithBase(:134) — the string leg of Spark
    conv(): trim ASCII spaces, optional '-', longest valid-digit prefix
    in `base`; no digits -> 0 (still a valid row), negatives wrap to
    unsigned, overflow clamps to 2^64-1.  Matches baseDec2Hex/baseHex2Dec
    test vectors (CastStringsTest.java:430-560)."""
    from spark_rapids_tpu.ops.strings_misc import parse_base_prefix

    assert col.dtype.is_string
    if not (2 <= base <= 36):
        raise ValueError(f"unsupported base {base}")
    np_dt = np.dtype(dtype.np_dtype)
    bits = np_dt.itemsize * 8
    signed = np_dt.kind == "i"
    out = []
    for s in col.to_pylist():
        if s is None:
            out.append(None)
            continue
        t = s.lstrip(" \t\n\r\f\v")
        if not t:
            # rows matching ^\s*$ are NULL (CastStringJni.cpp:234-240),
            # unlike no-digit junk which yields 0
            out.append(None)
            continue
        val, overflow = parse_base_prefix(t, base)
        if overflow and ansi_mode:
            raise CastException(len(out), s)
        val &= (1 << bits) - 1
        if signed and val >= 1 << (bits - 1):
            val -= 1 << bits
        out.append(val)
    return Column.from_pylist(out, dtype)


def integers_with_base_to_string(col: Column, base: int) -> Column:
    """CastStrings.fromIntegersWithBase(:158): base 10 renders the value
    as-is (signed for signed dtypes); base 16 renders the two's-complement
    bits of the column's width, uppercase, no leading zeros
    ([123,-1] int32 -> ['7B','FFFFFFFF'])."""
    if base not in (10, 16):
        raise ValueError("only base 10 and 16 are supported")
    np_dt = np.dtype(col.dtype.np_dtype)
    bits = np_dt.itemsize * 8
    out = []
    for v in col.to_pylist():
        if v is None:
            out.append(None)
        elif base == 10:
            out.append(str(v))
        else:
            out.append(format(int(v) & ((1 << bits) - 1), "X"))
    return Column.from_strings(out)
