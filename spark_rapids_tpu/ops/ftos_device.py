"""Device float->string digit engine (reference: ftos_converter.cuh,
1,493 LoC of device Ryu; CastStrings.fromFloat:103).

Vectorized shortest-round-trip decimal conversion (the published Ryu
algorithm) in lane-per-row jnp u64 arithmetic:

  * the float decomposes into (mantissa, exponent); three scaled
    candidates vm < vr < vp bracket the value's rounding interval
  * one 128-bit multiply per candidate by a precomputed power-of-5
    (or inverse) table entry converts to the decimal domain; the table
    is generated at import with exact Python big-int arithmetic
  * a masked fixed-trip loop strips digits while the whole interval
    agrees, with the tie/trailing-zero refinements that make the result
    exactly the shortest representation that round-trips
  * digits + decimal exponent render into Java's Double.toString /
    Float.toString layout (plain for 1e-3 <= |v| < 1e7, else E-notation)
    as one byte matrix -> offsets/chars string column

The host path (cast_string._java_double_repr) is the differential
oracle; tests fuzz random bit patterns incl. subnormals and boundary
mantissas.  128-bit products are composed from 32-bit limbs so every
lane op stays in native u64.
"""

from __future__ import annotations

import os
from functools import partial as _partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind

_U64 = jnp.uint64
_U32 = jnp.uint32
_I32 = jnp.int32

DEVICE_MIN_ROWS = int(os.environ.get("SPARK_RAPIDS_TPU_FTOS_MIN_ROWS",
                                     32))


def use_device(col: Column) -> bool:
    mode = os.environ.get("SPARK_RAPIDS_TPU_FTOS", "auto")
    if mode == "host":
        return False
    return mode == "device" or col.length >= DEVICE_MIN_ROWS


# ------------------------------------------------------------- tables
# Exact big-int generation (ryu d2s_full_table shapes): for e2 >= 0 the
# inverse table INV[q] ~ 2^j / 5^q rounded up; for e2 < 0 the table
# P5[i] = top bits of 5^i.  125-bit significands, split into hi/lo u64.

_B_INV = 125   # bits kept of 2^j/5^q  (double)
_B_POW = 125   # bits kept of 5^i      (double)
_FB_INV = 59   # float tables are single u64 entries
_FB_POW = 61


def _pow5bits(e: int) -> int:
    return ((e * 1217359) >> 19) + 1


def _gen_double_tables():
    inv = np.zeros((292, 2), np.uint64)
    for q in range(292):
        j = _pow5bits(q) - 1 + _B_INV
        v = (1 << j) // (5 ** q) + 1
        inv[q, 0] = v & ((1 << 64) - 1)
        inv[q, 1] = v >> 64
    p5 = np.zeros((326, 2), np.uint64)
    for i in range(326):
        shift = _pow5bits(i) - _B_POW
        v = (5 ** i) >> shift if shift >= 0 else (5 ** i) << -shift
        p5[i, 0] = v & ((1 << 64) - 1)
        p5[i, 1] = v >> 64
    return inv, p5


def _gen_float_tables():
    inv = np.zeros(31, np.uint64)
    for q in range(31):
        j = _pow5bits(q) - 1 + _FB_INV
        inv[q] = (1 << j) // (5 ** q) + 1
    # i = -e2 - q reaches 48 at the deepest f32 subnormal (e2 = -151,
    # with the corrected q = log10Pow5(151) - 1)
    p5 = np.zeros(49, np.uint64)
    for i in range(49):
        shift = _pow5bits(i) - _FB_POW
        p5[i] = (5 ** i) >> shift if shift >= 0 else (5 ** i) << -shift
    return inv, p5


_D_INV, _D_POW5 = _gen_double_tables()
_F_INV, _F_POW5 = _gen_float_tables()

_POW10_U64 = np.array([10 ** k for k in range(20)], np.uint64)


def _log10_pow2(e):
    # floor(log10(2^e)) for 0 <= e <= 1650
    return (e * 78913) >> 18


def _log10_pow5(e):
    # floor(log10(5^e)) for 0 <= e <= 2620
    return (e * 732923) >> 20


# (table generation and jit cores share _pow5bits: the bit-count
# formula must never desynchronize between them)


# --------------------------------------------------- 128-bit primitives


from spark_rapids_tpu.utils.u64math import umul128 as _umul128  # noqa: E402


def _mul_shift64(m, mul_lo, mul_hi, j):
    """floor((m * (mul_hi:mul_lo)) / 2^j) for 64 < j < 128+64, result
    fitting u64 (ryu mulShift64)."""
    b0_lo, b0_hi = _umul128(m, mul_lo)
    b2_lo, b2_hi = _umul128(m, mul_hi)
    s_mid = b0_hi + b2_lo
    carry = (s_mid < b0_hi).astype(_U64)
    s_hi = b2_hi + carry
    jj = (j - _U64(64)) & _U64(63)
    # j - 64 in (0, 64): combine mid and hi
    return (s_mid >> jj) | jnp.where(
        jj == 0, _U64(0), s_hi << ((_U64(64) - jj) & _U64(63)))


def _pow5_factor_ge(value, p):
    """value divisible by 5^p?  (p <= 23 suffices for doubles)."""
    v = value
    count = jnp.zeros_like(value, dtype=_I32)
    for _ in range(24):
        div = v // _U64(5)
        is_mult = div * _U64(5) == v
        take = is_mult & (count < 24)
        v = jnp.where(take, div, v)
        count = count + take.astype(_I32)
    return count >= p


def _multiple_of_pow2(value, p):
    mask = jnp.where(p >= 64, _U64(0xFFFFFFFFFFFFFFFF),
                     (_U64(1) << (p.astype(_U64) & _U64(63))) - _U64(1))
    return (value & mask) == _U64(0)


# --------------------------------------------------------- core (f64)


@jax.jit
def _d2d(bits: jnp.ndarray):
    """Shortest-decimal core for f64 raw bits (sign handled by caller).
    Returns (digits u64, e10 int32) for finite nonzero inputs."""
    mant = bits & _U64((1 << 52) - 1)
    expo = ((bits >> _U64(52)) & _U64(0x7FF)).astype(_I32)
    is_sub = expo == 0
    m2 = jnp.where(is_sub, mant, mant | _U64(1 << 52))
    e2 = jnp.where(is_sub, 1, expo) - 1075 - 2
    accept = (m2 & _U64(1)) == _U64(0)          # even mantissa
    mm_shift = ((mant != _U64(0)) | (expo <= 1)).astype(_U64)
    mv = m2 * _U64(4)
    mp = mv + _U64(2)
    mm = mv - _U64(1) - mm_shift

    # ---- decimal-domain candidates, both e2 branches computed & merged
    pos = e2 >= 0
    e2p = jnp.maximum(e2, 0)
    q_pos = jnp.maximum(_log10_pow2(e2p) - (e2p > 3), 0)
    k_pos = _B_INV + _pow5bits(q_pos) - 1
    i_pos = -e2p + q_pos + k_pos
    inv = jnp.asarray(_D_INV)
    q_idx = jnp.clip(q_pos, 0, inv.shape[0] - 1)
    vr_p = _mul_shift64(mv, inv[q_idx, 0], inv[q_idx, 1],
                        i_pos.astype(_U64))
    vp_p = _mul_shift64(mp, inv[q_idx, 0], inv[q_idx, 1],
                        i_pos.astype(_U64))
    vm_p = _mul_shift64(mm, inv[q_idx, 0], inv[q_idx, 1],
                        i_pos.astype(_U64))
    e10_p = q_pos
    qp_small = q_pos <= 21
    mv5 = _pow5_factor_ge(mv, q_pos)
    vr_t_p = qp_small & mv5 & ((mv % _U64(5)) == _U64(0))
    vm_t_p = qp_small & _pow5_factor_ge(mm, q_pos) \
        & ((mv % _U64(5)) != _U64(0)) & accept
    vp_adj_p = qp_small & _pow5_factor_ge(mp, q_pos) \
        & ((mv % _U64(5)) != _U64(0)) & ~accept

    e2n = jnp.minimum(e2, 0)
    nq = jnp.maximum(_log10_pow5(-e2n) - ((-e2n) > 1), 0)
    e10_n = nq + e2n
    i_neg = jnp.maximum(-e2n - nq, 0)
    k_neg = _pow5bits(i_neg) - _B_POW
    j_neg = nq - k_neg
    p5 = jnp.asarray(_D_POW5)
    i_idx = jnp.clip(i_neg, 0, p5.shape[0] - 1)
    vr_n = _mul_shift64(mv, p5[i_idx, 0], p5[i_idx, 1],
                        j_neg.astype(_U64))
    vp_n = _mul_shift64(mp, p5[i_idx, 0], p5[i_idx, 1],
                        j_neg.astype(_U64))
    vm_n = _mul_shift64(mm, p5[i_idx, 0], p5[i_idx, 1],
                        j_neg.astype(_U64))
    nq_u = nq.astype(_U64)
    vr_t_n = (nq <= 1) | ((nq < 63) & _multiple_of_pow2(mv, nq_u))
    # (q<=1: mv=4m2 has >=2 factors of 2 -> vr trailing if q<=1 and...)
    vr_t_n = jnp.where(nq <= 1, jnp.ones_like(vr_t_n), vr_t_n)
    vm_t_n = jnp.where(
        nq <= 1, accept & (mm_shift == _U64(1)),
        (nq < 63) & _multiple_of_pow2(mm, nq_u))
    # ryu: for q<=1, vp trailing-adjust when !acceptBounds
    vp_adj_n = (nq <= 1) & ~accept

    vr = jnp.where(pos, vr_p, vr_n)
    vp = jnp.where(pos, vp_p, vp_n)
    vm = jnp.where(pos, vm_p, vm_n)
    e10 = jnp.where(pos, e10_p, e10_n)
    vr_trail = jnp.where(pos, vr_t_p, vr_t_n)
    vm_trail = jnp.where(pos, vm_t_p, vm_t_n)
    vp_dec = jnp.where(pos, vp_adj_p, vp_adj_n)
    vp = vp - vp_dec.astype(_U64)

    # ---- digit stripping (masked fixed-trip loops) ------------------
    def strip_body(_, st):
        vr, vp, vm, last, removed, vm_t, vr_t = st
        cond = (vp // _U64(10)) > (vm // _U64(10))
        vm_t = jnp.where(cond, vm_t & ((vm % _U64(10)) == _U64(0)), vm_t)
        vr_t = jnp.where(cond, vr_t & (last == _U64(0)), vr_t)
        last = jnp.where(cond, vr % _U64(10), last)
        vr = jnp.where(cond, vr // _U64(10), vr)
        vp = jnp.where(cond, vp // _U64(10), vp)
        vm = jnp.where(cond, vm // _U64(10), vm)
        removed = removed + cond.astype(_I32)
        return vr, vp, vm, last, removed, vm_t, vr_t

    last0 = jnp.zeros_like(vr)
    rem0 = jnp.zeros_like(vr, dtype=_I32)
    vr, vp, vm, last, removed, vm_trail, vr_trail = jax.lax.fori_loop(
        0, 19, strip_body,
        (vr, vp, vm, last0, rem0, vm_trail, vr_trail))

    def strip_vm_body(_, st):
        vr, vp, vm, last, removed, vr_t = st
        cond = (vm % _U64(10)) == _U64(0)
        vr_t = jnp.where(cond, vr_t & (last == _U64(0)), vr_t)
        last = jnp.where(cond, vr % _U64(10), last)
        vr = jnp.where(cond, vr // _U64(10), vr)
        vp = jnp.where(cond, vp // _U64(10), vp)
        vm = jnp.where(cond, vm // _U64(10), vm)
        removed = removed + cond.astype(_I32)
        return vr, vp, vm, last, removed, vr_t

    def run_vm_strip(st):
        return jax.lax.fori_loop(0, 19, strip_vm_body, st)

    vr2, vp2, vm2, last2, removed2, vr_trail2 = run_vm_strip(
        (vr, vp, vm, last, removed, vr_trail))
    use2 = vm_trail
    vr = jnp.where(use2, vr2, vr)
    vm = jnp.where(use2, vm2, vm)
    last = jnp.where(use2, last2, last)
    removed = jnp.where(use2, removed2, removed)
    vr_trail = jnp.where(use2, vr_trail2, vr_trail)

    # round-even on exact ties
    tie = vr_trail & (last == _U64(5)) & ((vr % _U64(2)) == _U64(0))
    last = jnp.where(tie, _U64(4), last)
    need_up = ((vr == vm) & (~accept | ~vm_trail)) | (last >= _U64(5))
    out = vr + need_up.astype(_U64)
    return out, (e10 + removed).astype(_I32)


@jax.jit
def _f2d(bits32: jnp.ndarray):
    """Shortest-decimal core for f32 raw bits."""
    b = bits32.astype(_U64)
    mant = b & _U64((1 << 23) - 1)
    expo = ((b >> _U64(23)) & _U64(0xFF)).astype(_I32)
    is_sub = expo == 0
    m2 = jnp.where(is_sub, mant, mant | _U64(1 << 23))
    e2 = jnp.where(is_sub, 1, expo) - 150 - 2
    accept = (m2 & _U64(1)) == _U64(0)
    mm_shift = ((mant != _U64(0)) | (expo <= 1)).astype(_U64)
    mv = m2 * _U64(4)
    mp = mv + _U64(2)
    mm = mv - _U64(1) - mm_shift

    def mul_shift32(m, factor, shift):
        # m < 2^26, factor < 2^64, shift in (32, 96)
        f_hi = factor >> _U64(32)
        f_lo = factor & _U64(0xFFFFFFFF)
        hi = m * f_hi
        lo = m * f_lo
        s = hi + (lo >> _U64(32))
        return s >> ((shift - _U64(32)) & _U64(63))

    # d2d-style q (one smaller than the naive log10): guarantees the
    # strip loop removes >= 1 digit whenever q >= 1, so no separate
    # last-removed-digit patch is needed (same argument as _d2d)
    pos = e2 >= 0
    e2p = jnp.maximum(e2, 0)
    q_pos = jnp.maximum(_log10_pow2(e2p) - (e2p > 3), 0)
    k_pos = _FB_INV + _pow5bits(q_pos) - 1
    i_pos = (-e2p + q_pos + k_pos).astype(_U64)
    finv = jnp.asarray(_F_INV)
    q_idx = jnp.clip(q_pos, 0, finv.shape[0] - 1)
    vr_p = mul_shift32(mv, finv[q_idx], i_pos)
    vp_p = mul_shift32(mp, finv[q_idx], i_pos)
    vm_p = mul_shift32(mm, finv[q_idx], i_pos)
    e10_p = q_pos
    # mv < 2^26 so 5^q | mv is only possible for q <= 11
    qp_small = q_pos <= 11
    vr_t_p = qp_small & ((mv % _U64(5)) == _U64(0)) \
        & _pow5_factor_ge(mv, q_pos)
    vm_t_p = qp_small & _pow5_factor_ge(mm, q_pos) \
        & ((mv % _U64(5)) != _U64(0)) & accept
    vp_adj_p = qp_small & _pow5_factor_ge(mp, q_pos) \
        & ((mv % _U64(5)) != _U64(0)) & ~accept

    e2n = jnp.minimum(e2, 0)
    nq = jnp.maximum(_log10_pow5(-e2n) - ((-e2n) > 1), 0)
    e10_n = nq + e2n
    i_neg = jnp.maximum(-e2n - nq, 0)
    k_neg = _pow5bits(i_neg) - _FB_POW
    j_neg = (nq - k_neg).astype(_U64)
    fp5 = jnp.asarray(_F_POW5)
    i_idx = jnp.clip(i_neg, 0, fp5.shape[0] - 1)
    vr_n = mul_shift32(mv, fp5[i_idx], j_neg)
    vp_n = mul_shift32(mp, fp5[i_idx], j_neg)
    vm_n = mul_shift32(mm, fp5[i_idx], j_neg)
    nq_u = nq.astype(_U64)
    # vr = mv*5^i/2^q is an integer (no nonzero digit dropped by the
    # scaling) iff 2^q divides mv
    vr_t_n = (nq <= 1) | _multiple_of_pow2(mv, nq_u)
    vm_t_n = jnp.where(nq <= 1, accept & (mm_shift == _U64(1)),
                       _multiple_of_pow2(mm, nq_u))
    vp_adj_n = (nq <= 1) & ~accept

    vr = jnp.where(pos, vr_p, vr_n)
    vp = jnp.where(pos, vp_p, vp_n)
    vm = jnp.where(pos, vm_p, vm_n)
    e10 = jnp.where(pos, e10_p, e10_n)
    vr_trail = jnp.where(pos, vr_t_p, vr_t_n)
    vm_trail = jnp.where(pos, vm_t_p, vm_t_n)
    vp = vp - jnp.where(pos, vp_adj_p, vp_adj_n).astype(_U64)

    def strip_body(_, st):
        vr, vp, vm, last, removed, vm_t, vr_t = st
        cond = (vp // _U64(10)) > (vm // _U64(10))
        vm_t = jnp.where(cond, vm_t & ((vm % _U64(10)) == _U64(0)), vm_t)
        vr_t = jnp.where(cond, vr_t & (last == _U64(0)), vr_t)
        last = jnp.where(cond, vr % _U64(10), last)
        vr = jnp.where(cond, vr // _U64(10), vr)
        vp = jnp.where(cond, vp // _U64(10), vp)
        vm = jnp.where(cond, vm // _U64(10), vm)
        removed = removed + cond.astype(_I32)
        return vr, vp, vm, last, removed, vm_t, vr_t

    last0 = jnp.zeros_like(vr)
    rem0 = jnp.zeros_like(vr, dtype=_I32)
    vr, vp, vm, last, removed, vm_trail, vr_trail = jax.lax.fori_loop(
        0, 11, strip_body,
        (vr, vp, vm, last0, rem0, vm_trail, vr_trail))

    def strip_vm_body(_, st):
        vr, vp, vm, last, removed, vr_t = st
        cond = (vm % _U64(10)) == _U64(0)
        vr_t = jnp.where(cond, vr_t & (last == _U64(0)), vr_t)
        last = jnp.where(cond, vr % _U64(10), last)
        vr = jnp.where(cond, vr // _U64(10), vr)
        vp = jnp.where(cond, vp // _U64(10), vp)
        vm = jnp.where(cond, vm // _U64(10), vm)
        removed = removed + cond.astype(_I32)
        return vr, vp, vm, last, removed, vr_t

    vr2, vp2, vm2, last2, removed2, vr_trail2 = jax.lax.fori_loop(
        0, 11, strip_vm_body, (vr, vp, vm, last, removed, vr_trail))
    use2 = vm_trail
    vr = jnp.where(use2, vr2, vr)
    vm = jnp.where(use2, vm2, vm)
    last = jnp.where(use2, last2, last)
    removed = jnp.where(use2, removed2, removed)
    vr_trail = jnp.where(use2, vr_trail2, vr_trail)

    tie = vr_trail & (last == _U64(5)) & ((vr % _U64(2)) == _U64(0))
    last = jnp.where(tie, _U64(4), last)
    need_up = ((vr == vm) & (~accept | ~vm_trail)) | (last >= _U64(5))
    out = vr + need_up.astype(_U64)
    return out, (e10 + removed).astype(_I32)


# ------------------------------------------------------------- layout

_MAXW = 32          # widest Java rendering fits comfortably
_NAN = np.frombuffer(b"NaN", np.uint8)
_INF = np.frombuffer(b"Infinity", np.uint8)


@_partial(jax.jit, static_argnames=("is_f32",))
def _render(bits64: jnp.ndarray, is_f32: bool):
    """(bytes (rows, _MAXW) u8, lengths (rows,) int32) in Java
    Double/Float.toString layout."""
    rows = bits64.shape[0]
    if is_f32:
        sign = (bits64 >> _U64(31)) & _U64(1)
        expfield = (bits64 >> _U64(23)) & _U64(0xFF)
        mantfield = bits64 & _U64((1 << 23) - 1)
        is_nan = (expfield == _U64(0xFF)) & (mantfield != _U64(0))
        is_inf = (expfield == _U64(0xFF)) & (mantfield == _U64(0))
        is_zero = (expfield == _U64(0)) & (mantfield == _U64(0))
        digits, e10 = _f2d(bits64)
    else:
        sign = (bits64 >> _U64(63)) & _U64(1)
        expfield = (bits64 >> _U64(52)) & _U64(0x7FF)
        mantfield = bits64 & _U64((1 << 52) - 1)
        is_nan = (expfield == _U64(0x7FF)) & (mantfield != _U64(0))
        is_inf = (expfield == _U64(0x7FF)) & (mantfield == _U64(0))
        is_zero = (expfield == _U64(0)) & (mantfield == _U64(0))
        digits, e10 = _d2d(bits64)

    neg = sign == _U64(1)
    # digit count and most-significant-first digit bytes
    p10 = jnp.asarray(_POW10_U64)
    ndig = jnp.sum((digits[:, None] >= p10[None, :]).astype(_I32),
                   axis=1)
    ndig = jnp.maximum(ndig, 1)
    # extract up to 17 digits LSB-first
    ND = 17
    def dig_body(k, st):
        v, out = st
        out = out.at[:, k].set((v % _U64(10)).astype(jnp.uint8))
        return v // _U64(10), out
    _, dlsb = jax.lax.fori_loop(
        0, ND, dig_body,
        (digits, jnp.zeros((rows, ND), jnp.uint8)))
    # digit i (0 = most significant) = dlsb[ndig-1-i]
    sci_exp = e10 + ndig - 1
    plain = (sci_exp >= -3) & (sci_exp < 7)

    j = jnp.arange(_MAXW, dtype=_I32)[None, :]
    nd = ndig[:, None]
    sneg = neg[:, None]
    sgn_off = sneg.astype(_I32)

    def digit_at(i):
        idx = jnp.clip(nd - 1 - i, 0, ND - 1)
        return jnp.take_along_axis(dlsb, idx.astype(_I32), axis=1)

    # ---------- plain notation -------------------------------------
    se = sci_exp[:, None]
    int_digits = jnp.where(se >= 0, se + 1, 1)       # digits before '.'
    # frac digits: max(ndig - int_digits, 1) when se >= 0; for se < 0
    # frac = leading zeros + all digits
    lead_zeros = jnp.where(se < 0, -se - 1, 0)
    frac_digits = jnp.where(se >= 0,
                            jnp.maximum(nd - int_digits, 1),
                            lead_zeros + nd)
    plain_len = sgn_off + jnp.where(se >= 0, int_digits, 1) \
        + 1 + frac_digits
    # byte at position j (after sign): integer part, '.', fraction
    pj = j - sgn_off
    in_int = (pj >= 0) & (pj < jnp.where(se >= 0, int_digits, 1))
    int_digit = jnp.where(
        se >= 0,
        jnp.where(pj < nd, digit_at(pj), jnp.zeros_like(pj, jnp.uint8)),
        jnp.zeros_like(pj, jnp.uint8))          # "0." case
    dot_pos = jnp.where(se >= 0, int_digits, 1)
    in_dot = pj == dot_pos
    fj = pj - dot_pos - 1                       # index into fraction
    in_frac = (fj >= 0) & (fj < frac_digits)
    frac_digit = jnp.where(
        se >= 0,
        jnp.where(fj < nd - int_digits, digit_at(int_digits + fj),
                  jnp.zeros_like(fj, jnp.uint8)),
        jnp.where(fj < lead_zeros, jnp.zeros_like(fj, jnp.uint8),
                  digit_at(fj - lead_zeros)))
    plain_b = jnp.where(
        in_int, int_digit + jnp.uint8(48),
        jnp.where(in_dot, jnp.uint8(46),
                  jnp.where(in_frac, frac_digit + jnp.uint8(48),
                            jnp.uint8(0))))

    # ---------- E notation -----------------------------------------
    # d.dddE[-]xx ; fraction = remaining digits or "0"
    efrac = jnp.maximum(nd - 1, 1)
    eneg = se < 0
    ae = jnp.abs(se)
    exp_digits = jnp.where(ae >= 100, 3, jnp.where(ae >= 10, 2, 1))
    sci_len = sgn_off + 1 + 1 + efrac + 1 + eneg.astype(_I32) \
        + exp_digits
    in_d0 = pj == 0
    in_dot_s = pj == 1
    sfj = pj - 2
    in_sfrac = (sfj >= 0) & (sfj < efrac)
    sfrac_digit = jnp.where(sfj < nd - 1, digit_at(1 + sfj),
                            jnp.zeros_like(sfj, jnp.uint8))
    epos = 2 + efrac
    in_e = pj == epos
    in_esign = (pj == epos + 1) & eneg
    edig_start = epos + 1 + eneg.astype(_I32)
    ej = pj - edig_start
    in_edig = (ej >= 0) & (ej < exp_digits)
    # exponent digits MSB first
    div = jnp.where(ej == exp_digits - 1, 1,
                    jnp.where(ej == exp_digits - 2, 10, 100))
    edigit = (ae // div) % 10
    sci_b = jnp.where(
        in_d0, digit_at(jnp.zeros_like(pj)) + jnp.uint8(48),
        jnp.where(in_dot_s, jnp.uint8(46),
                  jnp.where(in_sfrac, sfrac_digit + jnp.uint8(48),
                            jnp.where(in_e, jnp.uint8(69),
                                      jnp.where(in_esign, jnp.uint8(45),
                                                jnp.where(in_edig,
                                                          edigit.astype(jnp.uint8) + jnp.uint8(48),
                                                          jnp.uint8(0)))))))

    # body already leaves position 0 free on negative rows (pj = j - 1)
    body = jnp.where(plain[:, None], plain_b, sci_b)
    body = jnp.where(sneg & (j == 0), jnp.uint8(45), body)
    length = jnp.where(plain, plain_len[:, 0], sci_len[:, 0])
    out = jnp.where(j < length[:, None], body, jnp.uint8(0))

    # ---------- specials -------------------------------------------
    nan_b = jnp.zeros(_MAXW, jnp.uint8).at[:3].set(jnp.asarray(_NAN))
    inf_b = jnp.zeros(_MAXW, jnp.uint8).at[:8].set(jnp.asarray(_INF))
    ninf_b = jnp.zeros(_MAXW, jnp.uint8).at[0].set(jnp.uint8(45)) \
        .at[1:9].set(jnp.asarray(_INF))
    zero_b = jnp.zeros(_MAXW, jnp.uint8).at[:3].set(
        jnp.asarray(np.frombuffer(b"0.0", np.uint8)))
    nzero_b = jnp.zeros(_MAXW, jnp.uint8).at[:4].set(
        jnp.asarray(np.frombuffer(b"-0.0", np.uint8)))

    out = jnp.where(is_nan[:, None], nan_b[None, :], out)
    length = jnp.where(is_nan, 3, length)
    out = jnp.where((is_inf & ~neg)[:, None], inf_b[None, :], out)
    length = jnp.where(is_inf & ~neg, 8, length)
    out = jnp.where((is_inf & neg)[:, None], ninf_b[None, :], out)
    length = jnp.where(is_inf & neg, 9, length)
    out = jnp.where((is_zero & ~neg)[:, None], zero_b[None, :], out)
    length = jnp.where(is_zero & ~neg, 3, length)
    out = jnp.where((is_zero & neg)[:, None], nzero_b[None, :], out)
    length = jnp.where(is_zero & neg, 4, length)
    return out, length.astype(_I32)


def float_to_string_device(col: Column) -> Column:
    """Device path of cast_string.float_to_string (same output)."""
    assert col.dtype.kind in (Kind.FLOAT32, Kind.FLOAT64)
    rows = col.length
    if rows == 0:
        return Column.from_strings([])
    is_f32 = col.dtype.kind == Kind.FLOAT32
    if is_f32:
        from jax import lax

        bits = lax.bitcast_convert_type(col.data, jnp.uint32) \
            .astype(_U64)
    else:
        bits = col.data.astype(_U64)   # FLOAT64 data carries raw bits
    mat, lens = _render(bits, is_f32)
    lens_np = np.asarray(lens)
    mask = np.asarray(col.valid_mask()).astype(bool)
    lens_np = np.where(mask, lens_np, 0)
    offs = np.zeros(rows + 1, np.int32)
    np.cumsum(lens_np, out=offs[1:])
    total = int(offs[-1])
    offs_j = jnp.asarray(offs)
    if total:
        i_flat = jnp.arange(total, dtype=_I32)
        r = jnp.searchsorted(offs_j, i_flat, side="right") \
            .astype(_I32) - 1
        cpos = i_flat - offs_j[r]
        data = mat[r, cpos]
    else:
        data = jnp.zeros(0, jnp.uint8)
    v = None if mask.all() else jnp.asarray(mask.astype(np.uint8))
    return Column(dtypes.STRING, rows, data=data, validity=v,
                  offsets=offs_j)
