"""Misc utilities (reference utilities.hpp:3-12: bitmask_bitwise_or,
spark-numeric type traits)."""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from spark_rapids_tpu.columns.dtypes import DType, Kind


def bitmask_bitwise_or(masks: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """OR N equal-length packed bitmask buffers (utilities.hpp
    bitmask_bitwise_or) — used to combine validity across columns."""
    if not masks:
        raise ValueError("need at least one mask")
    out = masks[0]
    for m in masks[1:]:
        if m.shape != out.shape:
            raise ValueError("mask length mismatch")
        out = out | m
    return out


def is_spark_numeric(dt: DType) -> bool:
    """spark-numeric type trait (utilities.hpp): integrals, floats and
    decimals."""
    return dt.kind in (Kind.INT8, Kind.INT16, Kind.INT32, Kind.INT64,
                       Kind.FLOAT32, Kind.FLOAT64, Kind.DECIMAL32,
                       Kind.DECIMAL64, Kind.DECIMAL128)
