"""CASE WHEN fast path (reference case_when.cu/case_when.hpp,
CaseWhen.java): N boolean WHEN columns -> index of the first true branch
per row (num_columns = ELSE) for a subsequent gather."""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column

_I32 = jnp.int32


def select_first_true_index(bool_cols: Sequence[Column]) -> Column:
    """(rows,) INT32: index of the first WHEN column whose value is true
    (null counts as false); len(bool_cols) if none match (the ELSE
    branch)."""
    if not bool_cols:
        raise ValueError("need at least one boolean column")
    n = len(bool_cols)
    rows = bool_cols[0].length
    result = jnp.full((rows,), n, _I32)
    for i in range(n - 1, -1, -1):
        c = bool_cols[i]
        t = c.data.astype(jnp.bool_)
        if c.validity is not None:
            t = t & c.validity.astype(jnp.bool_)
        result = jnp.where(t, _I32(i), result)
    return Column(dtypes.INT32, rows, data=result)
