"""random_uuids column generator (reference uuid.cu/uuid.hpp:2): a
strings column of version-4 variant-2 UUIDs.

TPU design: bits come from jax.random (threefry) — two u32 words per
half, formatted via vectorized nibble-to-hex byte assembly on device."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column

_U8 = jnp.uint8
_U32 = jnp.uint32
_U64 = jnp.uint64
_I32 = jnp.int32

_UUID_LEN = 36
_DASH_POS = (8, 13, 18, 23)


def random_uuids(rows: int, seed: int = 0) -> Column:
    """STRING column of random UUIDs (xxxxxxxx-xxxx-4xxx-yxxx-xxxxxxxxxxxx,
    y in 8..b)."""
    key = jax.random.PRNGKey(seed)
    bits = jax.random.bits(key, (rows, 4), dtype=jnp.uint32)
    msb = bits[:, 0].astype(_U64) << _U64(32) | bits[:, 1].astype(_U64)
    lsb = bits[:, 2].astype(_U64) << _U64(32) | bits[:, 3].astype(_U64)
    # version 4 + IETF variant
    msb = (msb & _U64(0xFFFFFFFFFFFF0FFF)) | _U64(0x4000)
    lsb = (lsb & _U64(0x3FFFFFFFFFFFFFFF)) | _U64(0x8000000000000000)

    # 32 hex nibbles, most significant first
    nib_idx = jnp.arange(32, dtype=_I32)
    src = jnp.where(nib_idx < 16, msb[:, None], lsb[:, None])
    shift = (15 - (nib_idx % 16)).astype(_U64) * _U64(4)
    nibbles = ((src >> shift[None, :]) & _U64(0xF)).astype(_U8)
    hex_bytes = jnp.where(nibbles < 10, nibbles + _U8(48),
                          nibbles + _U8(87))  # '0'..'9', 'a'..'f'

    # interleave dashes: output position -> nibble index
    out_map = []
    nib = 0
    for pos in range(_UUID_LEN):
        if pos in _DASH_POS:
            out_map.append(-1)
        else:
            out_map.append(nib)
            nib += 1
    out_map_arr = jnp.asarray(out_map, _I32)
    gathered = jnp.where(
        out_map_arr[None, :] >= 0,
        jnp.take_along_axis(
            hex_bytes,
            jnp.clip(out_map_arr, 0, 31)[None, :].repeat(rows, 0),
            axis=1),
        _U8(45))  # '-'
    data = gathered.reshape(-1)
    offsets = jnp.arange(rows + 1, dtype=_I32) * _I32(_UUID_LEN)
    return Column(dtypes.STRING, rows, data=data, offsets=offsets)
