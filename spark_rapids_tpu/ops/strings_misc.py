"""Assorted string/conversion kernels: number_converter conv(),
GBK charset decode, list_slice, regex fast-path literal_range_pattern
(reference number_converter.cu, charset_decode.cu, list_slice.cu,
regex_rewrite_utils.cu)."""

from __future__ import annotations

import functools

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.ops.exceptions import ExceptionWithRowIndex

_DIGITS = "0123456789abcdefghijklmnopqrstuvwxyz"


# ------------------------------------------------------- number_converter

def parse_base_prefix(t: str, base: int) -> Tuple[int, bool]:
    """Optional '-' then the longest valid-digit prefix of t in `base`,
    accumulated as unsigned 64-bit: overflow clamps to 2^64-1 (and stays
    clamped under negation), negatives wrap.  Shared by conv()
    (number_converter.cu) and CastStrings.toIntegersWithBase."""
    neg = t[:1] == "-"
    if neg:
        t = t[1:]
    val = 0
    overflow = False
    for ch in t:
        d = _DIGITS.find(ch.lower())
        if d < 0 or d >= base:
            break
        if not overflow:
            val = val * base + d
            if val >= 1 << 64:
                overflow = True
    if overflow:
        val = (1 << 64) - 1
    elif neg:
        val = ((1 << 64) - val) & ((1 << 64) - 1)
    return val, overflow


def _conv_one(s: Optional[str], from_base: int, to_base: int
              ) -> Tuple[Optional[str], bool]:
    """Spark conv() single value; returns (result, overflowed).
    Semantics (number_converter.cu / Spark NumberConverter): ASCII spaces
    trimmed (only 0x20), optional '-', longest valid-digit prefix parsed
    as UNSIGNED 64-bit; zero digits still render "0"; overflow clamps to
    2^64-1 (stays clamped under negation); from_base must be 2..36
    (positive only); to_base<0 renders signed."""
    if s is None:
        return None, False
    if not (2 <= from_base <= 36 and 2 <= abs(to_base) <= 36):
        return None, False
    t = s.strip(" ")
    if not t:
        return None, False
    val, overflow = parse_base_prefix(t, from_base)
    tb = abs(to_base)
    if to_base < 0:
        # signed rendering
        sval = val - (1 << 64) if val >= (1 << 63) else val
        sign = "-" if sval < 0 else ""
        mag = abs(sval)
    else:
        sign = ""
        mag = val
    if mag == 0:
        return "0", overflow
    out = []
    while mag:
        out.append(_DIGITS[mag % tb].upper())
        mag //= tb
    return sign + "".join(reversed(out)), overflow


def convert(col_or_str: Union[Column, str], from_base: int, to_base: int,
            rows: Optional[int] = None) -> Column:
    """Spark conv() (NumberConverter.java convert*)."""
    if isinstance(col_or_str, Column):
        vals = col_or_str.to_pylist()
    else:
        vals = [col_or_str] * (rows if rows is not None else 1)
    return Column.from_strings(
        [_conv_one(v, from_base, to_base)[0] for v in vals])


def is_convert_overflow(col_or_str: Union[Column, str], from_base: int,
                        to_base: int, rows: Optional[int] = None) -> Column:
    """BOOL8: conv() would overflow uint64 (ANSI pre-check,
    number_converter.hpp is_convert_overflow)."""
    if isinstance(col_or_str, Column):
        vals = col_or_str.to_pylist()
    else:
        vals = [col_or_str] * (rows if rows is not None else 1)
    res = [_conv_one(v, from_base, to_base) for v in vals]
    return Column.from_pylist(
        [ovf if v0 is not None else None
         for (_, ovf), v0 in zip(res, vals)],
        dtypes.BOOL8)


# --------------------------------------------------------- charset decode

REPLACE = "REPLACE"
REPORT = "REPORT"


_GBK_SENTINEL = 0x110000


@functools.lru_cache(maxsize=1)
def _gbk_table() -> np.ndarray:
    """64K GBK-code -> Unicode-codepoint table, generated from the
    stdlib codec (the reference vendors a codegen'd
    gbk_to_unicode_table.inc — charset_decode.cu:51-141; here the table
    is regenerated at first use, same idea).  Unmapped codes hold a
    sentinel."""
    t = np.full(65536, _GBK_SENTINEL, np.uint32)
    t[:0x80] = np.arange(0x80)          # single-byte ASCII plane
    for lead in range(0x81, 0xFF):
        row = bytes(b"".join(bytes([lead, tr])
                             for tr in range(0x40, 0xFF)))
        for tr in range(0x40, 0xFF):
            pair = row[2 * (tr - 0x40): 2 * (tr - 0x40) + 2]
            try:
                u = pair.decode("gbk")
            except UnicodeDecodeError:
                continue
            if len(u) == 1:
                t[(lead << 8) | tr] = ord(u)
    return t


def decode_to_utf8(col: Column, charset: str = "GBK",
                   on_error: str = REPLACE) -> Column:
    """GBK -> UTF-8 decode (charset_decode.cu two-pass table decode;
    CharsetDecode.java:55-79).  REPLACE substitutes U+FFFD; REPORT
    raises with the first malformed row.

    Vectorized two-pass design mirroring the reference kernel: a
    char-step loop advances every row's cursor simultaneously (1 byte
    for ASCII, 2 for a mapped pair, 1 + U+FFFD otherwise — the stdlib
    codec's error-consumption rule, differentially tested), then one
    vectorized UTF-8 byte-emission pass builds the output buffer.  No
    per-row Python."""
    assert col.dtype.is_string
    if charset.upper() != "GBK":
        raise ValueError("only GBK is supported")
    rows = col.length
    if rows == 0:
        return Column.from_strings([])
    table = _gbk_table()
    chars = np.asarray(col.to_padded_chars()[0])
    lens = np.asarray(col.string_lengths())
    mask = (np.ones(rows, bool) if col.validity is None
            else np.asarray(col.validity).astype(bool))
    lens = np.where(mask, lens, 0)
    R, L = chars.shape

    cur = np.zeros(R, np.int64)
    outn = np.zeros(R, np.int64)
    out_cp = np.zeros((R, max(L, 1)), np.uint32)
    malformed = np.zeros(R, bool)
    rows_idx = np.arange(R)
    while True:
        active = cur < lens
        if not active.any():
            break
        b = chars[rows_idx, np.minimum(cur, L - 1)].astype(np.int64)
        t = chars[rows_idx, np.minimum(cur + 1, L - 1)].astype(np.int64)
        has_t = cur + 1 < lens
        is_ascii = b < 0x80
        code = np.where(has_t, (b << 8) | t, 0)
        u = table[code]
        pair_ok = ~is_ascii & has_t & (u != _GBK_SENTINEL)
        emit = np.where(is_ascii, b,
                        np.where(pair_ok, u, 0xFFFD)).astype(np.uint32)
        bad = active & ~is_ascii & ~pair_ok
        malformed |= bad
        act_i = np.nonzero(active)[0]
        out_cp[act_i, outn[act_i]] = emit[act_i]
        outn += active
        cur += np.where(active, np.where(pair_ok, 2, 1), 0)

    if on_error == REPORT and (malformed & mask).any():
        i = int(np.nonzero(malformed & mask)[0][0])
        raise ExceptionWithRowIndex(i, "malformed GBK bytes")

    # pass 2: vectorized UTF-8 emission (GBK maps inside the BMP: <=3B)
    keep = np.arange(out_cp.shape[1])[None, :] < outn[:, None]
    flat = out_cp[keep].astype(np.uint32)          # row-major order
    nb = np.where(flat < 0x80, 1, np.where(flat < 0x800, 2, 3)) \
        .astype(np.int64)
    boff = np.concatenate([[0], np.cumsum(nb)])
    total = int(boff[-1])
    buf = np.zeros(total, np.uint8)
    b0 = np.where(nb == 1, flat,
                  np.where(nb == 2, 0xC0 | (flat >> 6),
                           0xE0 | (flat >> 12)))
    buf[boff[:-1]] = b0
    m2 = nb >= 2
    buf[boff[:-1][m2] + 1] = np.where(
        nb[m2] == 2, 0x80 | (flat[m2] & 0x3F),
        0x80 | ((flat[m2] >> 6) & 0x3F))
    m3 = nb == 3
    buf[boff[:-1][m3] + 2] = 0x80 | (flat[m3] & 0x3F)

    cp_row = np.repeat(rows_idx, outn)
    row_bytes = np.bincount(cp_row, weights=nb, minlength=R) \
        .astype(np.int64)
    offs = np.concatenate([[0], np.cumsum(row_bytes)]) \
        .astype(np.int32)
    import jax.numpy as jnp
    return Column(
        dtypes.STRING, rows, data=jnp.asarray(buf),
        validity=None if mask.all() else
        jnp.asarray(mask.astype(np.uint8)),
        offsets=jnp.asarray(offs))


# -------------------------------------------------------------- list_slice

def list_slice(col: Column, start: Union[int, Column],
               length: Union[int, Column, None] = None,
               check_start_length: bool = True) -> Column:
    """Spark slice(list, start, length) — 1-based start, negative counts
    from the end (list_slice.hpp 4 overloads via scalar/column combos)."""
    assert col.dtype.kind == Kind.LIST
    rows = col.length
    offs = np.asarray(col.offsets)
    starts = (start.to_pylist() if isinstance(start, Column)
              else [start] * rows)
    length_is_col = isinstance(length, Column)
    lens = (length.to_pylist() if length_is_col else [length] * rows)
    mask = (np.ones(rows, bool) if col.validity is None
            else np.asarray(col.validity).astype(bool))
    child = col.children[0]
    take: List[int] = []
    new_offs = np.zeros(rows + 1, np.int32)
    out_valid = np.zeros(rows, np.uint8)
    for i in range(rows):
        # a null entry in a start/length COLUMN nulls the row
        # (list_slice.cu:100-101); a scalar length of None means
        # "slice to the end"
        null_len = length_is_col and lens[i] is None
        if not mask[i] or starts[i] is None or null_len:
            new_offs[i + 1] = len(take)
            continue
        st = int(starts[i])
        if check_start_length and st == 0:
            raise ExceptionWithRowIndex(
                i, "Unexpected value for start in function slice: SQL "
                   "array indices start at 1.")
        ln_req = lens[i]
        if ln_req is not None and int(ln_req) < 0 and check_start_length:
            raise ExceptionWithRowIndex(
                i, "Unexpected value for length in function slice: "
                   "length must be greater than or equal to 0.")
        n = int(offs[i + 1] - offs[i])
        if st > 0:
            begin = st - 1
        else:
            begin = n + st
        if begin < 0 or begin >= n:
            sliced: List[int] = []
        else:
            count = n - begin if ln_req is None else min(int(ln_req),
                                                         n - begin)
            sliced = list(range(int(offs[i]) + begin,
                                int(offs[i]) + begin + count))
        take.extend(sliced)
        new_offs[i + 1] = len(take)
        out_valid[i] = 1
    from spark_rapids_tpu.ops.copying import gather
    new_child = gather(child, jnp.asarray(np.array(take, np.int32)))
    validity = None if out_valid.all() else jnp.asarray(out_valid)
    return Column(dtypes.LIST, rows, validity=validity,
                  offsets=jnp.asarray(new_offs), children=(new_child,))


# ------------------------------------------------- regex fast-path search

def literal_range_pattern(col: Column, literal: str, range_len: int,
                          start: int, end: int) -> Column:
    """BOOL8: row contains `literal` followed by `range_len` codepoints
    each within [start, end] (regex_rewrite_utils.cu literal_range
    fast path for trivial regexes like 'lit[a-b]{n}')."""
    assert col.dtype.is_string
    vals = col.to_pylist()
    out: List[Optional[bool]] = []
    for s in vals:
        if s is None:
            out.append(None)
            continue
        found = False
        m = len(literal)
        for i in range(len(s) - m - range_len + 1):
            if s[i:i + m] != literal:
                continue
            ok = all(start <= ord(s[i + m + j]) <= end
                     for j in range(range_len))
            if ok:
                found = True
                break
        out.append(found)
    return Column.from_pylist(out, dtypes.BOOL8)
