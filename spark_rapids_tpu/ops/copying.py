"""Table/column copying primitives: slice, gather, concat — the building
blocks shuffle split/assemble and joins compose (reference analogs:
cudf::slice/gather/concatenate as used by shuffle_split.cu /
shuffle_assemble.cu)."""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.columns.table import Table


def gather(col: Column, idx: jnp.ndarray) -> Column:
    """New column with rows col[idx[i]].  idx must be in range; device op."""
    n = int(idx.shape[0])
    validity = None
    if col.validity is not None:
        validity = col.validity[idx]
    kind = col.dtype.kind
    if kind == Kind.STRUCT:
        return Column(col.dtype, n, validity=validity,
                      children=tuple(gather(ch, idx) for ch in col.children))
    if kind in (Kind.STRING, Kind.LIST):
        # variable width: rebuild offsets from gathered lengths, then move
        # payload via a flattened gather (host-synced sizes; eager op)
        offs = np.asarray(col.offsets)
        hidx = np.asarray(idx)
        lens = offs[hidx + 1] - offs[hidx]
        new_offs = np.zeros(n + 1, np.int32)
        np.cumsum(lens, out=new_offs[1:])
        total = int(new_offs[-1])
        src = np.zeros(total, np.int64)
        for i in range(n):  # host loop over rows: acceptable for eager path
            src[new_offs[i]:new_offs[i + 1]] = np.arange(
                offs[hidx[i]], offs[hidx[i] + 1])
        src_j = jnp.asarray(src)
        if kind == Kind.STRING:
            data = col.data[src_j] if total else jnp.zeros(0, jnp.uint8)
            return Column(col.dtype, n, data=data, validity=validity,
                          offsets=jnp.asarray(new_offs))
        child = gather(col.children[0], src_j)
        return Column(col.dtype, n, validity=validity,
                      offsets=jnp.asarray(new_offs), children=(child,))
    data = col.data[idx] if col.data is not None else None
    return Column(col.dtype, n, data=data, validity=validity)


def gather_table(table: Table, idx: jnp.ndarray) -> Table:
    return Table([gather(c, idx) for c in table.columns], table.names)


def slice_column(col: Column, start: int, end: int) -> Column:
    """Zero-rebase slice [start, end) (cudf::slice semantics, materialized)."""
    n = end - start
    validity = col.validity[start:end] if col.validity is not None else None
    kind = col.dtype.kind
    if kind == Kind.STRUCT:
        return Column(col.dtype, n, validity=validity,
                      children=tuple(slice_column(ch, start, end)
                                     for ch in col.children))
    if kind in (Kind.STRING, Kind.LIST):
        offs = np.asarray(col.offsets)
        c0, c1 = int(offs[start]), int(offs[end])
        new_offs = jnp.asarray((offs[start:end + 1] - c0).astype(np.int32))
        if kind == Kind.STRING:
            return Column(col.dtype, n, data=col.data[c0:c1],
                          validity=validity, offsets=new_offs)
        child = slice_column(col.children[0], c0, c1)
        return Column(col.dtype, n, validity=validity, offsets=new_offs,
                      children=(child,))
    data = col.data[start:end] if col.data is not None else None
    return Column(col.dtype, n, data=data, validity=validity)


def slice_table(table: Table, start: int, end: int) -> Table:
    return Table([slice_column(c, start, end) for c in table.columns],
                 table.names)


def split_table(table: Table, splits: Sequence[int]) -> List[Table]:
    """Split at row indices (cudf::split): [0,s0), [s0,s1), ... [sn,rows)."""
    bounds = [0] + list(splits) + [table.num_rows]
    return [slice_table(table, bounds[i], bounds[i + 1])
            for i in range(len(bounds) - 1)]


def concat_columns(cols: Sequence[Column]) -> Column:
    first = cols[0]
    rows = sum(c.length for c in cols)
    kind = first.dtype.kind
    if any(c.validity is not None for c in cols):
        validity = jnp.concatenate([
            c.validity if c.validity is not None
            else jnp.ones((c.length,), jnp.uint8) for c in cols])
    else:
        validity = None
    if kind == Kind.STRUCT:
        children = tuple(
            concat_columns([c.children[i] for c in cols])
            for i in range(len(first.children)))
        return Column(first.dtype, rows, validity=validity,
                      children=children)
    if kind in (Kind.STRING, Kind.LIST):
        sizes = [int(np.asarray(c.offsets[-1])) for c in cols]
        parts = [cols[0].offsets]
        base = sizes[0]
        for c, sz in zip(cols[1:], sizes[1:]):
            parts.append(c.offsets[1:] + base)
            base += sz
        offsets = jnp.concatenate(parts)
        if kind == Kind.STRING:
            data = jnp.concatenate([c.data for c in cols])
            return Column(first.dtype, rows, data=data, validity=validity,
                          offsets=offsets)
        child = concat_columns([c.children[0] for c in cols])
        return Column(first.dtype, rows, validity=validity, offsets=offsets,
                      children=(child,))
    data = jnp.concatenate([c.data for c in cols])
    return Column(first.dtype, rows, data=data, validity=validity)


def concat_tables(tables: Sequence[Table]) -> Table:
    if not tables:
        raise ValueError("need at least one table")
    ncols = tables[0].num_columns
    return Table([concat_columns([t.columns[i] for t in tables])
                  for i in range(ncols)], tables[0].names)
