"""Histogram build + percentile (reference histogram.cu/.hpp,
Histogram.java): Spark percentile() over (value, frequency) histograms.

create_histogram_if_valid: (values, frequencies) -> LIST<STRUCT<value,
freq>> per input row (validating freq >= 0); percentile_from_histogram:
for each histogram row, Spark percentile interpolation at the requested
percentages."""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.ops.exceptions import ExceptionWithRowIndex
from spark_rapids_tpu.utils import floats

_I32 = jnp.int32
_I64 = jnp.int64


def create_histogram_if_valid(values: Column, frequencies: Column,
                              output_as_lists: bool = True) -> Column:
    """Per input row i: a one-element histogram [{value_i, freq_i}], or an
    empty list (lists mode) / null struct row (struct mode) when the value
    is null or freq <= 0.  Null or negative frequencies raise
    (histogram.cu:374-440 contract)."""
    rows = values.length
    freqs = np.asarray(frequencies.to_numpy()).astype(np.int64)
    fmask = (np.ones(frequencies.length, bool)
             if frequencies.validity is None
             else np.asarray(frequencies.validity).astype(bool))
    if not fmask.all():
        raise ExceptionWithRowIndex(int(np.argmax(~fmask)),
                                    "frequency must not be null")
    neg = freqs < 0
    if neg.any():
        raise ExceptionWithRowIndex(int(np.argmax(neg)),
                                    "frequency must not be negative")
    vmask = (np.ones(rows, bool) if values.validity is None
             else np.asarray(values.validity).astype(bool))
    keep = vmask & (freqs > 0)
    if not output_as_lists:
        freq_col = Column(dtypes.INT64, rows, data=jnp.asarray(freqs),
                          validity=jnp.asarray(keep.astype(np.uint8)))
        return Column.make_struct(rows, [values, freq_col],
                                  validity=keep.astype(np.uint8))
    # lists mode: element stream keeps only valid pairs; each input row's
    # list holds 0 or 1 element
    keep_idx = jnp.asarray(np.nonzero(keep)[0].astype(np.int32))
    from spark_rapids_tpu.ops.copying import gather
    kept_vals = gather(values, keep_idx)
    kept_freqs = Column(dtypes.INT64, int(keep.sum()),
                        data=jnp.asarray(freqs[keep]))
    st = Column.make_struct(kept_vals.length, [kept_vals, kept_freqs])
    offsets = np.zeros(rows + 1, np.int32)
    np.cumsum(keep.astype(np.int32), out=offsets[1:])
    return Column(dtypes.LIST, rows, offsets=jnp.asarray(offsets),
                  children=(st,))


def percentile_from_histogram(histogram: Column,
                              percentages: Sequence[float],
                              output_as_list: bool = True) -> Column:
    """Spark percentile(): sort each histogram by value, walk cumulative
    frequencies, linear-interpolate at p*(total-1)
    (histogram.hpp percentile_from_histogram)."""
    assert histogram.dtype.kind == "list"
    st = histogram.children[0]
    vals_col, freq_col = st.children
    offs = np.asarray(histogram.offsets)
    vals = np.asarray(vals_col.to_numpy()).astype(np.float64)
    freqs = np.asarray(freq_col.to_numpy()).astype(np.int64)
    rows = histogram.length
    out: List = []
    hmask = (np.ones(rows, bool) if histogram.validity is None
             else np.asarray(histogram.validity).astype(bool))
    for i in range(rows):
        if not hmask[i]:
            out.append(None)
            continue
        v = vals[offs[i]:offs[i + 1]]
        f = freqs[offs[i]:offs[i + 1]]
        if len(v) == 0:
            out.append(None)
            continue
        order = np.argsort(v, kind="stable")
        v, f = v[order], f[order]
        cum = np.cumsum(f)
        total = cum[-1]
        row_out = []
        for p in percentages:
            pos = p * (total - 1)
            lo = int(np.floor(pos))
            hi = int(np.ceil(pos))
            # index of first cumulative count > lo / > hi
            li = int(np.searchsorted(cum, lo + 1, side="left"))
            hi_i = int(np.searchsorted(cum, hi + 1, side="left"))
            vlo, vhi = v[li], v[hi_i]
            row_out.append(vlo + (pos - lo) * (vhi - vlo))
        out.append(row_out)
    if output_as_list:
        flat = [x for row in out if row is not None for x in row]
        child = Column.from_pylist(flat, dtypes.FLOAT64)
        offsets = np.zeros(rows + 1, np.int32)
        acc = 0
        for i, row in enumerate(out):
            acc += 0 if row is None else len(row)
            offsets[i + 1] = acc
        validity = None if all(r is not None for r in out) else \
            jnp.asarray(np.array([r is not None for r in out], np.uint8))
        return Column(dtypes.LIST, rows, validity=validity,
                      offsets=jnp.asarray(offsets), children=(child,))
    return Column.from_pylist(
        [row[0] if row else None for row in out], dtypes.FLOAT64)
