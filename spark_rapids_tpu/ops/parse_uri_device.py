"""Device parse_url: vectorized java.net.URI split + validation.

Reference: src/main/cpp/src/parse_uri.cu:1-1075 (thread-per-row
validation/extraction kernels behind ParseURI.java).  The TPU design
does the whole column in ONE jitted pass of positional vector ops — no
per-row loops, no scan:

  * component boundaries (fragment '#', scheme ':', query '?',
    authority '//', path '/') are first/last-position reductions over
    the padded char matrix;
  * per-component character validation is a 256-entry class-table
    lookup plus prefix-sum range counts (bad chars in [lo,hi) == 0),
    with '%'-escape legality as a shifted-window hex check;
  * the authority classifier (userinfo, port, IPv4 exact-octet,
    RFC-1034 hostname label rules, registry fallback) is positional
    arithmetic on dot/colon/at positions.

Rows the engine cannot fully decide on device are FLAGGED and routed
per-row to the host oracle (ops/parse_uri.py _URI — the java.net.URI
mini-parser): any byte >= 0x80 (codepoint-level rules) and IPv6
literals ('[' authorities).  This is the json_device fallback
discipline: device for the overwhelming common case, host for the tail,
bit-identical results either way (tests/test_parse_uri_device.py
differential).
"""

from __future__ import annotations

import functools
import os
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column

_I32 = jnp.int32
_U8 = jnp.uint8
_B = jnp.bool_

DEVICE_ROW_CHUNK = 1 << 17


# ------------------------------------------------------- char classes
# Sets are 4x32-bit ASCII bitmask quads tested with shift+mask — XLA:CPU
# lowers 256-entry table gathers to scalar loops (measured 10x slower),
# while the quad test is pure SIMD compares/shifts.
def _quad(chars_ok: str):
    m = [0, 0, 0, 0]
    for ch in chars_ok:
        o = ord(ch)
        assert o < 128
        m[o >> 5] |= 1 << (o & 31)
    return tuple(m)


@functools.lru_cache(maxsize=1)
def _quads():
    from spark_rapids_tpu.ops import parse_uri as PU
    alpha = ("abcdefghijklmnopqrstuvwxyz"
             "ABCDEFGHIJKLMNOPQRSTUVWXYZ")
    digit = "0123456789"
    return {
        "path": _quad("".join(PU._PATH_OK)),
        "query": _quad("".join(PU._QUERY_OK)),
        "user": _quad("".join(PU._USER_OK)),
        "reg": _quad("".join(PU._USER_OK | {"[", "]"})),
        "scheme": _quad(alpha + digit + "+.-"),
        "alpha": _quad(alpha),
        "digit": _quad(digit),
        "alnum": _quad(alpha + digit),
        "hex": _quad(PU._HEX),
    }


def _cls(chars: jnp.ndarray, quad) -> jnp.ndarray:
    """Membership test against an ASCII bitmask quad (any shape)."""
    _U32 = jnp.uint32
    w = (chars >> _U8(5)).astype(_I32)
    bit = (chars & _U8(31)).astype(_U32)
    sel = jnp.where(w == 0, _U32(quad[0]),
                    jnp.where(w == 1, _U32(quad[1]),
                              jnp.where(w == 2, _U32(quad[2]),
                                        jnp.where(w == 3,
                                                  _U32(quad[3]),
                                                  _U32(0)))))
    return ((sel >> bit) & _U32(1)).astype(_B)


# ------------------------------------------------------------- helpers
def _first(mask, lo, hi, L):
    """First index in [lo,hi) where mask; (pos, found).  argmax-on-bool:
    two 1-byte reductions beat the where(i32)+min formulation ~2x on the
    single-core CPU backend."""
    idx = jnp.arange(L, dtype=_I32)[None, :]
    m = mask & (idx >= lo[:, None]) & (idx < hi[:, None])
    p = jnp.argmax(m, axis=1).astype(_I32)
    found = jnp.any(m, axis=1)
    return jnp.where(found, p, L), found


def _last(mask, lo, hi, L):
    idx = jnp.arange(L, dtype=_I32)[None, :]
    inr = (idx >= lo[:, None]) & (idx < hi[:, None])
    cand = jnp.where(mask & inr, idx, -1)
    p = jnp.max(cand, axis=1)
    return p, p >= 0


def _count_in(mask, lo, hi, idx):
    """Count of True in columns [lo,hi) per row — a masked reduction
    (XLA:CPU lowers cumsum+gather range counts ~5x slower)."""
    inr = (idx >= lo[:, None]) & (idx < hi[:, None])
    return jnp.sum((mask & inr).astype(_I32), axis=1)


def _char_at(chars, pos, L, fill=0):
    p = jnp.clip(pos, 0, L - 1)
    c = jnp.take_along_axis(chars, p[:, None], axis=1)[:, 0]
    return jnp.where((pos >= 0) & (pos < L), c, _U8(fill))


def _analyze(chars: jnp.ndarray, lens: jnp.ndarray):
    """All component spans + validity for every row, one pass.

    Returns dict of (R,) arrays; spans are [start, end) char positions,
    has_* False means the component is null (java returns null)."""
    Q = _quads()
    R, L = chars.shape
    idx = jnp.arange(L, dtype=_I32)[None, :]
    in_row = idx < lens[:, None]

    def is_(b):
        return chars == _U8(ord(b))

    fallback = jnp.any((chars >= _U8(0x80)) & in_row, axis=1)

    # escape legality: every '%' needs two hex chars after it, inside
    # the row (component boundary tightening handled per-range below)
    hx = _cls(chars, Q["hex"])
    hx1 = jnp.concatenate([hx[:, 1:], jnp.zeros((R, 1), _B)], axis=1)
    hx2 = jnp.concatenate([hx[:, 2:], jnp.zeros((R, 2), _B)], axis=1)
    pct = is_("%")
    esc_bad = pct & ~(hx1 & hx2)

    # per-component bad-char masks, computed once per class
    bad_m = {k: ~(_cls(chars, Q[k]) | pct) & in_row
             for k in ("path", "query", "user", "reg")}

    # bad class char OR broken escape: one fused reduction per call
    badesc_m = {k: bad_m[k] | esc_bad for k in bad_m}

    def comp_ok(clsname, lo, hi):
        """Chars in [lo,hi) all legal for the component: class mask
        (plus '%' heads), escapes valid and fully inside [lo,hi)."""
        cnt_bad = _count_in(badesc_m[clsname], lo, hi, idx)
        # '%' within 2 chars of the component end cannot complete
        tail_pct = _count_in(pct, jnp.maximum(hi - 2, lo), hi, idx)
        return (cnt_bad == 0) & (tail_pct == 0)

    invalid = jnp.zeros(R, _B)

    # ---- fragment ---------------------------------------------------
    hpos, has_frag = _first(is_("#"), jnp.zeros(R, _I32), lens, L)
    len0 = jnp.where(has_frag, hpos, lens)
    invalid |= has_frag & ~comp_ok("query", hpos + 1, lens)

    # ---- scheme -----------------------------------------------------
    c0, has_c = _first(is_(":"), jnp.zeros(R, _I32), len0, L)
    sch_chars_ok = (_count_in(
        ~_cls(chars, Q["scheme"]) & in_row,
        jnp.ones(R, _I32), c0, idx) == 0)
    first_alpha = _cls(_char_at(chars, jnp.zeros(R, _I32), L),
                       Q["alpha"])
    has_scheme = has_c & (c0 >= 1) & first_alpha & sch_chars_ok
    invalid |= has_c & (c0 == 0)            # rest startswith ':'
    pos_s = jnp.where(has_scheme, c0 + 1, 0)

    # ---- opaque vs hierarchical ------------------------------------
    first_rest = _char_at(chars, pos_s, L)
    rest_empty = pos_s >= len0
    opaque = has_scheme & ~(~rest_empty & (first_rest == ord("/")))
    invalid |= opaque & rest_empty                       # empty ssp
    invalid |= opaque & ~comp_ok("query", pos_s, len0)

    hier = ~opaque

    # ---- query ------------------------------------------------------
    q0, has_q0 = _first(is_("?"), pos_s, len0, L)
    has_q = hier & has_q0
    invalid |= has_q & ~comp_ok("query", q0 + 1, len0)
    e0 = jnp.where(has_q, q0, len0)

    # ---- authority / path ------------------------------------------
    second = _char_at(chars, pos_s + 1, L)
    has_auth = (hier & (first_rest == ord("/")) & (second == ord("/"))
                & (pos_s + 1 < e0))
    a0 = pos_s + 2
    p0, p_found = _first(is_("/"), a0, e0, L)
    auth_end = jnp.where(has_auth, jnp.where(p_found, p0, e0), a0)
    path_lo = jnp.where(has_auth,
                        jnp.where(p_found, p0, e0),   # "" when no '/'
                        pos_s)
    path_hi = e0
    has_path = hier
    invalid |= hier & ~comp_ok("path", path_lo, path_hi)

    # ---- authority classification ----------------------------------
    auth_present = has_auth & (a0 < auth_end)
    atp, has_at = _last(is_("@"), a0, auth_end, L)
    has_at &= auth_present
    invalid |= has_at & ~comp_ok("user", a0, atp)
    hp0 = jnp.where(has_at, atp + 1, a0)
    hp1 = auth_end

    fallback |= auth_present & (_char_at(chars, hp0, L) == ord("["))

    cpos, has_col = _last(is_(":"), hp0, hp1, L)
    has_col &= auth_present
    dig_m = _cls(chars, Q["digit"]) & in_row
    port_len = jnp.maximum(hp1 - (cpos + 1), 0)
    port_digits = _count_in(dig_m, cpos + 1, hp1, idx) == port_len
    server_port_ok = ~has_col | port_digits
    h_end = jnp.where(has_col & port_digits, cpos, hp1)

    # IPv4: exactly 3 dots, 4 all-digit octets of 1-3 chars, each <=255
    dot = is_(".")
    d1, f1 = _first(dot, hp0, h_end, L)
    d2, f2 = _first(dot, d1 + 1, h_end, L)
    d3, f3 = _first(dot, d2 + 1, h_end, L)
    _d4, f4 = _first(dot, d3 + 1, h_end, L)
    three_dots = f1 & f2 & f3 & ~f4

    def octet(a, b):
        n = b - a
        c0_ = _char_at(chars, a, L)
        c1_ = _char_at(chars, a + 1, L)
        c2_ = _char_at(chars, a + 2, L)
        dcount = _count_in(dig_m, a, b, idx)
        all_dig = dcount == n
        v0 = (c0_ - ord("0")).astype(_I32)
        v1 = (c1_ - ord("0")).astype(_I32)
        v2 = (c2_ - ord("0")).astype(_I32)
        val = jnp.where(n == 1, v0,
                        jnp.where(n == 2, v0 * 10 + v1,
                                  v0 * 100 + v1 * 10 + v2))
        ok = (n >= 1) & (n <= 3) & all_dig & (val <= 255)
        return ok

    ipv4_ok = (three_dots & server_port_ok
               & octet(hp0, d1) & octet(d1 + 1, d2)
               & octet(d2 + 1, d3) & octet(d3 + 1, h_end))

    # hostname (RFC-1034 labels): chars alnum/-/., first char alnum,
    # every '.' preceded by alnum and followed by alnum-or-end, last
    # char alnum or '.'
    alnum_m = _cls(chars, Q["alnum"])
    hn_class = alnum_m | dot | is_("-")
    hn_all = _count_in(~hn_class & in_row, hp0, h_end, idx) == 0
    first_an = _cls(_char_at(chars, hp0, L), Q["alnum"])
    prev_alnum = jnp.concatenate(
        [jnp.zeros((R, 1), _B), alnum_m[:, :-1]], axis=1)
    next_alnum = jnp.concatenate(
        [alnum_m[:, 1:], jnp.zeros((R, 1), _B)], axis=1)
    at_end = idx == (h_end[:, None] - 1)
    dot_bad = dot & ~(prev_alnum & (next_alnum | at_end))
    inr_h = (idx >= hp0[:, None]) & (idx < h_end[:, None])
    dots_ok = ~jnp.any(dot_bad & inr_h, axis=1)
    last_c = _char_at(chars, h_end - 1, L)
    last_ok = _cls(last_c, Q["alnum"]) | (last_c == ord("."))
    hostname_ok = ((h_end > hp0) & hn_all & first_an & dots_ok
                   & last_ok & server_port_ok)

    is_server = auth_present & server_port_ok & (ipv4_ok | hostname_ok)
    has_host = is_server
    host_lo, host_hi = hp0, h_end

    # registry authority: valid chars required, host stays null.
    # server-parse failure with non-digit port validates the WHOLE
    # hostport (host + ':' + port); plain hostname/ipv4 failure
    # validates only the host part (port was stripped) — ops/parse_uri
    # _parse_authority.
    reg_hi = jnp.where(server_port_ok, h_end, hp1)
    registry = auth_present & ~is_server
    invalid |= registry & ~comp_ok("reg", hp0, reg_hi)

    return {
        "invalid": invalid, "fallback": fallback,
        "has_scheme": has_scheme,
        "scheme_lo": jnp.zeros(R, _I32), "scheme_hi": c0,
        "opaque": opaque,
        "has_q": has_q, "q_lo": q0 + 1, "q_hi": len0,
        "has_path": has_path, "path_lo": path_lo, "path_hi": path_hi,
        "has_host": has_host, "host_lo": host_lo, "host_hi": host_hi,
    }


_analyze_jit = jax.jit(_analyze)

# chunk-analysis memo: parse_url workloads typically extract several
# components of the same column (protocol+host+query+path); the engine
# computes all spans in one pass, so later extractors reuse it.  Keys
# hold a STRONG reference to the column, which both bounds staleness
# (identity can't be recycled while cached) and caps memory via FIFO.
from collections import OrderedDict

_ANALYSIS_CACHE: "OrderedDict" = OrderedDict()
_ANALYSIS_CACHE_MAX = 8
# byte budget as well as entry count: one 8KB-row chunk's char matrix
# alone can be ~1GB, so entry count alone cannot bound memory
_ANALYSIS_CACHE_BYTES = int(os.environ.get(
    "SPARK_RAPIDS_TPU_PARSE_URI_CACHE_BYTES", str(256 << 20)))


def _fallback_uris(col: Column, b0: int, fb_rows, chars, lens_np):
    """{local_row: parsed URI or None} for the chunk's fallback rows
    (VERDICT r4 weak #6: these used to re-parse for EVERY component
    extractor).  The dict lives INSIDE the chunk's _ANALYSIS_CACHE
    entry, so one cache/one guard/one eviction budget governs both
    the span analysis and the fallback parses."""
    from spark_rapids_tpu.ops import parse_uri as PU
    ent = _ANALYSIS_CACHE.get((id(col), b0))
    uris = ent[5] if ent is not None and ent[0] is col else {}
    for i in fb_rows:
        if i not in uris:
            s = bytes(chars[i, :lens_np[i]]).decode(
                "utf-8", errors="replace")
            uris[i] = PU._parse(s)
    return uris


def _analyzed_chunk(col: Column, b0: int, b1: int):
    key = (id(col), b0)
    ent = _ANALYSIS_CACHE.get(key)
    if ent is not None and ent[0] is col:
        return ent[1], ent[2], ent[3]
    sub = Column(col.dtype, b1 - b0, data=col.data, validity=None,
                 offsets=col.offsets[b0:b1 + 1])
    chars_j, lens_j = sub.to_padded_chars()
    res = _analyze_jit(chars_j, lens_j)
    res_np = {k: np.asarray(v) for k, v in res.items()}
    chars = np.asarray(chars_j)
    lens_np = np.asarray(lens_j)
    nbytes = (chars.nbytes + lens_np.nbytes
              + sum(v.nbytes for v in res_np.values()))
    _ANALYSIS_CACHE[key] = (col, res_np, chars, lens_np, nbytes,
                            {})   # lazily-filled fallback URI parses
    total = sum(e[4] for e in _ANALYSIS_CACHE.values())
    while _ANALYSIS_CACHE and (
            len(_ANALYSIS_CACHE) > _ANALYSIS_CACHE_MAX
            or total > _ANALYSIS_CACHE_BYTES):
        _k, evicted = _ANALYSIS_CACHE.popitem(last=False)
        total -= evicted[4]
    return res_np, chars, lens_np


# ------------------------------------------------ span materialization
def spans_to_strings(chars: np.ndarray, starts: np.ndarray,
                     ends: np.ndarray, valid: np.ndarray,
                     host_patch=None) -> Column:
    """Gather [start,end) per row from the padded matrix into a STRING
    column; invalid rows are null (shared builder: columns/strbuild)."""
    from spark_rapids_tpu.columns.strbuild import build_string_column
    L = chars.shape[1]
    rows_idx = np.arange(len(starts))
    return build_string_column(
        chars.reshape(-1), rows_idx * L + starts,
        np.maximum(ends - starts, 0), valid, host_patch)


def _component(res, what):
    """(valid, lo, hi) numpy views for an extractor."""
    inv = np.asarray(res["invalid"])
    if what == "protocol":
        has = np.asarray(res["has_scheme"])
        lo, hi = np.asarray(res["scheme_lo"]), np.asarray(
            res["scheme_hi"])
    elif what == "host":
        has = np.asarray(res["has_host"])
        lo, hi = np.asarray(res["host_lo"]), np.asarray(res["host_hi"])
    elif what == "query":
        has = np.asarray(res["has_q"])
        lo, hi = np.asarray(res["q_lo"]), np.asarray(res["q_hi"])
    elif what == "path":
        has = np.asarray(res["has_path"])
        lo, hi = np.asarray(res["path_lo"]), np.asarray(res["path_hi"])
    else:
        raise ValueError(what)
    return has & ~inv, lo, hi


def extract_device(col: Column, what: str, ansi_mode: bool,
                   key: Optional[str] = None) -> Column:
    """Device-first extraction with per-row host fallback."""
    from spark_rapids_tpu.ops.exceptions import ExceptionWithRowIndex

    rows = col.length
    parts: List[Column] = []
    for b0 in range(0, rows, DEVICE_ROW_CHUNK):
        b1 = min(rows, b0 + DEVICE_ROW_CHUNK)
        res, chars, lens_np = _analyzed_chunk(col, b0, b1)
        fb = res["fallback"]
        inv = res["invalid"]

        in_null = np.zeros(b1 - b0, bool)
        if col.validity is not None:
            in_null = ~np.asarray(
                col.validity[b0:b1]).astype(bool)

        if what == "query_key":
            valid, lo, hi = _component(res, "query")
            qvals = _materialize_query_key(
                chars, lo, hi, valid & ~in_null & ~fb, key)
        else:
            valid, lo, hi = _component(res, what)

        # per-row host fallback (non-ASCII / IPv6):
        # host_vals[i] = (uri_parses, component_value)
        fb_rows = np.nonzero(fb & ~in_null)[0]
        host_vals = {}
        if fb_rows.size:
            uris = _fallback_uris(col, b0, fb_rows, chars, lens_np)
            for i in fb_rows:
                uri = uris[i]
                if uri is None:
                    host_vals[i] = (False, None)
                    continue
                if what == "protocol":
                    v = uri.scheme
                elif what == "host":
                    v = uri.host
                elif what == "query":
                    v = uri.raw_query
                elif what == "path":
                    v = uri.raw_path
                else:
                    v = _host_query_key(uri.raw_query, key)
                host_vals[i] = (True, v)

        row_invalid = np.array(inv & ~fb)   # writable copy
        for i, (parses, _v) in host_vals.items():
            if not parses:
                row_invalid[i] = True
        if ansi_mode:
            bad = np.nonzero(row_invalid & ~in_null)[0]
            if bad.size:
                i = int(bad[0]) + b0
                raise ExceptionWithRowIndex(
                    i, "invalid URI at row %d" % i)

        if what == "query_key":
            vals = qvals
            for i, (_parses, v) in host_vals.items():
                vals[i] = v
            parts.append(Column.from_strings(vals))
        else:
            # device spans, host rows spliced in by the shared builder
            patch = {i: v for i, (_p, v) in host_vals.items()} \
                if host_vals else None
            parts.append(spans_to_strings(
                chars, lo, hi, valid & ~in_null & ~fb, patch))

    if len(parts) == 1:
        return parts[0]
    from spark_rapids_tpu.columns.table import Table
    from spark_rapids_tpu.ops.copying import concat_tables
    return concat_tables([Table([p]) for p in parts]).columns[0]


def _host_query_key(q: Optional[str], key: Optional[str]):
    from spark_rapids_tpu.ops.parse_uri import match_query_key
    return match_query_key(q, key)


def _materialize_query_key(chars: np.ndarray, lo: np.ndarray,
                           hi: np.ndarray, valid: np.ndarray,
                           key: str) -> List[Optional[str]]:
    """parse_url(..., QUERY, key) over the device-extracted query spans
    (pair matching delegates to the single matcher in ops/parse_uri)."""
    from spark_rapids_tpu.ops.parse_uri import match_query_key

    out: List[Optional[str]] = [None] * len(lo)
    for i in range(len(lo)):
        if not valid[i]:
            continue
        v = match_query_key(bytes(chars[i, lo[i]:hi[i]]), key)
        if v is not None:
            out[i] = v.decode("utf-8", errors="replace")
    return out


def use_device(col: Column) -> bool:
    """NOT accelerator-gated, unlike from_json/protobuf (ADVICE r4):
    parse_uri's host path is a per-row Python parse (ops/parse_uri.py),
    so the vectorized scan wins even on the CPU backend; the raw-map /
    from_json host paths are batch builders, which is why those ops
    gate on jax.default_backend()."""
    if os.environ.get("SPARK_RAPIDS_TPU_FORCE_DEVICE_PARSE_URI") == "1":
        return True
    min_rows = int(os.environ.get(
        "SPARK_RAPIDS_TPU_PARSE_URI_DEVICE_MIN", "512"))
    return col.length >= min_rows
