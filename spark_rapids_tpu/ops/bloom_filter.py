"""Spark BloomFilter create/put/probe/merge (reference bloom_filter.cu/
bloom_filter.hpp, BloomFilter.java) — byte-compatible with Spark's
serialized sketch formats:

  V1: [version=1, numHashes, numLongs] big-endian + longs big-endian
      (BloomFilterImpl hash loop: combined = h1 + i*h2, i in 1..n, int32)
  V2: [version=2, numHashes, seed, numLongs] + longs
      (BloomFilterImplV2: combined int64 = h1*INT32_MAX (+= h2 per probe))

Internally the bitset lives as uint32 words with the reference's
big-endian swizzle (word index ^ 1, bit index ^ 0x18,
bloom_filter.cu gpu_bit_to_word_mask) so the word buffer's little-endian
byte image equals Spark's big-endian long array.

TPU design: a put of N rows with K hashes computes the (N, K) bit
positions in one vectorized pass, scatters into a boolean bit array
(duplicate-safe set-to-True), packs to words, and ORs into the filter —
no atomics needed."""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.ops.hash import _Murmur32, _split_u64

_I32 = jnp.int32
_I64 = jnp.int64
_U32 = jnp.uint32
_U64 = jnp.uint64

INT32_MAX = 2147483647


@dataclass
class BloomFilter:
    version: int
    num_hashes: int
    seed: int                 # 0 for v1 (not serialized)
    words: jnp.ndarray        # (num_longs*2,) uint32, swizzled layout

    @property
    def num_longs(self) -> int:
        return int(self.words.shape[0]) // 2

    @property
    def num_bits(self) -> int:
        return self.num_longs * 64


def create(num_hashes: int, num_longs: int, version: int = 2,
           seed: int = 0) -> BloomFilter:
    if version not in (1, 2):
        raise ValueError("bloom filter version must be 1 or 2")
    return BloomFilter(version, num_hashes, seed if version == 2 else 0,
                       jnp.zeros(num_longs * 2, _U32))


def _hash_pair(col: Column, seed: int):
    """(h1, h2) int32 murmur hashes of an INT64 column
    (bloom_filter.cu:95-100)."""
    v = col.data.astype(_I64)
    lo, hi = _split_u64(v.astype(_U64))
    h1u = _Murmur32.hash_blocks(
        jnp.full(v.shape, np.uint32(seed & 0xFFFFFFFF), _U32), [lo, hi], 8)
    h2u = _Murmur32.hash_blocks(h1u, [lo, hi], 8)
    return h1u.astype(_I32), h2u.astype(_I32)


def _bit_positions(bf: BloomFilter, col: Column) -> jnp.ndarray:
    """(rows, num_hashes) int64 bit positions."""
    h1, h2 = _hash_pair(col, bf.seed if bf.version == 2 else 0)
    k = bf.num_hashes
    if bf.version == 1:
        idx = jnp.arange(1, k + 1, dtype=_I32)[None, :]
        combined = h1[:, None] + idx * h2[:, None]       # int32 wrap
        pos = jnp.where(combined < 0, ~combined, combined).astype(_I64)
    else:
        steps = jnp.arange(1, k + 1, dtype=_I64)[None, :]
        combined = (h1.astype(_I64) * _I64(INT32_MAX))[:, None] \
            + steps * h2.astype(_I64)[:, None]           # int64 wrap
        pos = jnp.where(combined < 0, ~combined, combined)
    return pos % _I64(bf.num_bits)


def _word_and_bit(pos: jnp.ndarray):
    """gpu_bit_to_word_mask (bloom_filter.cu): big-endian swizzle."""
    word = (pos // 32) ^ _I64(1)
    bit = (pos % 32).astype(_I32) ^ _I32(0x18)
    return word, bit


def put(bf: BloomFilter, col: Column) -> BloomFilter:
    """Insert all valid rows of an INT64 column; returns the updated
    filter (functional — jax arrays are immutable)."""
    if col.length == 0:
        return bf
    pos = _bit_positions(bf, col)
    word, bit = _word_and_bit(pos)
    flat = (word * 32 + bit.astype(_I64)).reshape(-1)
    if col.validity is not None:
        keep = jnp.broadcast_to(col.validity.astype(jnp.bool_)[:, None],
                                pos.shape).reshape(-1)
        flat = jnp.where(keep, flat, jnp.int64(bf.num_bits))  # dropped
    bits = jnp.zeros(bf.num_bits + 1, jnp.bool_).at[flat].set(
        True, mode="drop")[: bf.num_bits]
    packed = (bits.reshape(-1, 32).astype(_U32)
              << jnp.arange(32, dtype=_U32)[None, :]).sum(
        axis=1, dtype=_U32)
    return BloomFilter(bf.version, bf.num_hashes, bf.seed,
                       bf.words | packed)


def probe(bf: BloomFilter, col: Column) -> Column:
    """BOOL8 column: row possibly in the filter (bloom_filter.hpp probe)."""
    if col.length == 0:
        return Column(dtypes.BOOL8, 0, data=jnp.zeros(0, jnp.uint8))
    pos = _bit_positions(bf, col)
    word, bit = _word_and_bit(pos)
    w = bf.words[jnp.clip(word, 0, bf.words.shape[0] - 1)]
    hit = (w >> bit.astype(_U32)) & _U32(1)
    found = jnp.all(hit != 0, axis=1)
    return Column(dtypes.BOOL8, col.length,
                  data=found.astype(jnp.uint8), validity=col.validity)


def merge(filters: Sequence[BloomFilter]) -> BloomFilter:
    """OR-combine filters built with identical parameters
    (bloom_filter.hpp merge)."""
    first = filters[0]
    words = first.words
    for f in filters[1:]:
        if (f.version, f.num_hashes, f.seed, f.num_longs) != \
                (first.version, first.num_hashes, first.seed,
                 first.num_longs):
            raise ValueError("incompatible bloom filters")
        words = words | f.words
    return BloomFilter(first.version, first.num_hashes, first.seed, words)


def serialize(bf: BloomFilter) -> bytes:
    """Spark sketch bytes (BE header + BE longs; the swizzled LE word
    image IS the BE long image)."""
    if bf.version == 1:
        header = struct.pack(">iii", 1, bf.num_hashes, bf.num_longs)
    else:
        header = struct.pack(">iiii", 2, bf.num_hashes, bf.seed,
                             bf.num_longs)
    return header + np.asarray(bf.words).astype("<u4").tobytes()


def deserialize(data: bytes) -> BloomFilter:
    version = struct.unpack(">i", data[:4])[0]
    if version == 1:
        _, num_hashes, num_longs = struct.unpack(">iii", data[:12])
        seed, off = 0, 12
    elif version == 2:
        _, num_hashes, seed, num_longs = struct.unpack(">iiii", data[:16])
        off = 16
    else:
        raise ValueError(f"unsupported bloom filter version {version}")
    words = np.frombuffer(data, "<u4", num_longs * 2, off)
    return BloomFilter(version, num_hashes, seed, jnp.asarray(words))
