"""Map column utilities (reference map_utils.hpp / map.hpp /
map_zip_with_utils.hpp, Map.java / MapUtils.java / GpuMapZipWithUtils):
maps are LIST<STRUCT<key, value>> columns with Spark semantics."""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.ops.copying import gather
from spark_rapids_tpu.ops.exceptions import ExceptionWithRowIndex
from spark_rapids_tpu.ops.joins import _column_rank_host


def _entries(col: Column) -> Tuple[Column, Column, Column]:
    assert col.dtype.kind == Kind.LIST
    st = col.children[0]
    assert st.dtype.kind == Kind.STRUCT and len(st.children) == 2
    return st, st.children[0], st.children[1]


def is_valid_map(col: Column, throw_on_null_key: bool = False) -> bool:
    """True when every entry struct is non-null and every key is non-null
    (map_utils.hpp:58)."""
    st, keys, _ = _entries(col)
    if st.validity is not None and not np.asarray(st.validity).all():
        return False
    if keys.validity is not None and not np.asarray(keys.validity).all():
        if throw_on_null_key:
            bad = int(np.argmin(np.asarray(keys.validity)))
            raise ExceptionWithRowIndex(bad, "null map key")
        return False
    return True


def map_from_entries(col: Column, throw_on_null_key: bool = True
                     ) -> Column:
    """LIST<STRUCT<K,V>> -> valid Spark map: null keys throw (or drop),
    duplicate keys keep the LAST occurrence (Spark LAST_WIN policy),
    entry order of first occurrence preserved (map_utils.hpp:97)."""
    st, keys, vals = _entries(col)
    offs = np.asarray(col.offsets)
    key_ranks, key_mask = _column_rank_host(keys)
    st_mask = (np.ones(st.length, bool) if st.validity is None
               else np.asarray(st.validity).astype(bool))
    row_mask = (np.ones(col.length, bool) if col.validity is None
                else np.asarray(col.validity).astype(bool))
    if throw_on_null_key and not key_mask.all():
        # only entries under valid rows AND valid structs count
        for row in range(col.length):
            if not row_mask[row]:
                continue
            for e in range(offs[row], offs[row + 1]):
                if st_mask[e] and not key_mask[e]:
                    raise ExceptionWithRowIndex(row, "null map key")
    take = []
    new_offs = np.zeros(col.length + 1, np.int32)
    for row in range(col.length):
        seen = {}
        order = []
        if row_mask[row]:
            for e in range(offs[row], offs[row + 1]):
                if not st_mask[e] or not key_mask[e]:
                    continue  # drop null entries/keys (non-throw mode)
                k = key_ranks[e]
                if k not in seen:
                    order.append(k)
                seen[k] = e           # last occurrence wins
        take.extend(seen[k] for k in order)
        new_offs[row + 1] = len(take)
    idx = jnp.asarray(np.array(take, np.int32))
    new_st = Column.make_struct(len(take),
                                [gather(keys, idx), gather(vals, idx)])
    return Column(dtypes.LIST, col.length, validity=col.validity,
                  offsets=jnp.asarray(new_offs), children=(new_st,))


def sort_map_column(col: Column, descending: bool = False) -> Column:
    """Sort each map's entries by key (map.hpp:39 sort_map_column)."""
    st, keys, vals = _entries(col)
    offs = np.asarray(col.offsets)
    key_ranks, _ = _column_rank_host(keys)
    take = []
    for row in range(col.length):
        es = list(range(offs[row], offs[row + 1]))
        es.sort(key=lambda e: key_ranks[e], reverse=descending)
        take.extend(es)
    idx = jnp.asarray(np.array(take, np.int32))
    new_st = Column.make_struct(
        len(take), [gather(keys, idx), gather(vals, idx)],
        validity=None if st.validity is None
        else np.asarray(st.validity)[np.array(take, np.int64)]
        if len(take) else None)
    return Column(dtypes.LIST, col.length, validity=col.validity,
                  offsets=col.offsets, children=(new_st,))


def map_zip(keys_list: Column, a_vals: Column, b_vals: Column) -> Column:
    """Zip aligned LIST columns into LIST<STRUCT<key, a, b>> — the
    map_zip_with building block (map_zip_with_utils.hpp:60); the three
    lists must share offsets."""
    for c in (keys_list, a_vals, b_vals):
        assert c.dtype.kind == Kind.LIST
    ko = np.asarray(keys_list.offsets)
    if not (np.array_equal(ko, np.asarray(a_vals.offsets))
            and np.array_equal(ko, np.asarray(b_vals.offsets))):
        raise ValueError("map_zip requires aligned list offsets")
    st = Column.make_struct(
        keys_list.children[0].length,
        [keys_list.children[0], a_vals.children[0], b_vals.children[0]])
    return Column(dtypes.LIST, keys_list.length,
                  validity=keys_list.validity, offsets=keys_list.offsets,
                  children=(st,))


def map_zip_full(col1: Column, col2: Column) -> Column:
    """Spark map_zip_with key alignment (map_zip_with_utils.cu:356-420
    map_zip; GpuMapZipWithUtils.mapZip): per row, take the distinct
    union of both maps' keys (col1's keys in first-appearance order,
    then col2's new keys), and for each key build STRUCT<value1, value2>
    where a side's value is null when that map lacks the key.  Result
    row validity is the AND of the input validities."""
    from spark_rapids_tpu.ops.copying import concat_columns

    st1, k1, v1 = _entries(col1)
    st2, k2, v2 = _entries(col2)
    assert col1.length == col2.length
    all_keys = concat_columns([k1, k2])
    ranks, _ = _column_rank_host(all_keys)
    r1, r2 = ranks[:k1.length], ranks[k1.length:]
    o1 = np.asarray(col1.offsets)
    o2 = np.asarray(col2.offsets)
    m1 = (np.ones(col1.length, bool) if col1.validity is None
          else np.asarray(col1.validity).astype(bool))
    m2 = (np.ones(col2.length, bool) if col2.validity is None
          else np.asarray(col2.validity).astype(bool))
    row_mask = m1 & m2
    key_take = []          # index into the concatenated key column
    take1, take2 = [], []  # value gathers; -1 = absent
    new_offs = np.zeros(col1.length + 1, np.int32)
    for row in range(col1.length):
        if row_mask[row]:
            pos = {}   # rank -> output slot
            for e in range(o1[row], o1[row + 1]):
                if r1[e] not in pos:
                    pos[r1[e]] = len(key_take)
                    key_take.append(e)
                    take1.append(e)
                    take2.append(-1)
                else:
                    take1[pos[r1[e]]] = e  # duplicate key: last wins
            for e in range(o2[row], o2[row + 1]):
                if r2[e] not in pos:
                    pos[r2[e]] = len(key_take)
                    key_take.append(k1.length + e)
                    take1.append(-1)
                    take2.append(e)
                else:
                    take2[pos[r2[e]]] = e
        new_offs[row + 1] = len(key_take)

    def _all_null_like(src: Column, n: int) -> Column:
        """n all-null rows shaped like src (src may be zero-length)."""
        if src.dtype.kind == Kind.LIST:
            return Column(src.dtype, n,
                          validity=jnp.zeros(n, jnp.uint8),
                          offsets=jnp.zeros(n + 1, jnp.int32),
                          children=src.children)
        if src.dtype.kind == Kind.STRUCT:
            return Column.make_struct(
                n, [_all_null_like(c, n) for c in src.children],
                validity=np.zeros(n, np.uint8))
        return Column.from_pylist([None] * n, src.dtype)

    def _gather_opt(src: Column, take) -> Column:
        t = np.array(take, np.int64)
        present = t >= 0
        if src.length == 0:
            # one side contributed no entries at all: every take is -1
            return _all_null_like(src, len(t))
        g = gather(src, jnp.asarray(np.where(present, t, 0).astype(
            np.int32)))
        base = (present if g.validity is None
                else np.asarray(g.validity).astype(bool) & present)
        return Column(g.dtype, g.length,
                      data=g.data, validity=jnp.asarray(
                          base.astype(np.uint8)),
                      offsets=g.offsets, children=g.children)

    # key_take never holds -1, so a plain gather over the concatenation
    # already built for ranking is enough
    keys_out = gather(all_keys, jnp.asarray(
        np.array(key_take, np.int32)))
    pair = Column.make_struct(len(key_take),
                              [_gather_opt(v1, take1),
                               _gather_opt(v2, take2)])
    st = Column.make_struct(len(key_take), [keys_out, pair])
    return Column(dtypes.LIST, col1.length,
                  validity=jnp.asarray(row_mask.astype(np.uint8)),
                  offsets=jnp.asarray(new_offs), children=(st,))
