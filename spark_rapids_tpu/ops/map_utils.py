"""Map column utilities (reference map_utils.hpp / map.hpp /
map_zip_with_utils.hpp, Map.java / MapUtils.java / GpuMapZipWithUtils):
maps are LIST<STRUCT<key, value>> columns with Spark semantics."""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.ops.copying import gather
from spark_rapids_tpu.ops.exceptions import ExceptionWithRowIndex
from spark_rapids_tpu.ops.joins import _column_rank_host


def _entries(col: Column) -> Tuple[Column, Column, Column]:
    assert col.dtype.kind == Kind.LIST
    st = col.children[0]
    assert st.dtype.kind == Kind.STRUCT and len(st.children) == 2
    return st, st.children[0], st.children[1]


def is_valid_map(col: Column, throw_on_null_key: bool = False) -> bool:
    """True when every entry struct is non-null and every key is non-null
    (map_utils.hpp:58)."""
    st, keys, _ = _entries(col)
    if st.validity is not None and not np.asarray(st.validity).all():
        return False
    if keys.validity is not None and not np.asarray(keys.validity).all():
        if throw_on_null_key:
            bad = int(np.argmin(np.asarray(keys.validity)))
            raise ExceptionWithRowIndex(bad, "null map key")
        return False
    return True


def map_from_entries(col: Column, throw_on_null_key: bool = True
                     ) -> Column:
    """LIST<STRUCT<K,V>> -> valid Spark map: null keys throw (or drop),
    duplicate keys keep the LAST occurrence (Spark LAST_WIN policy),
    entry order of first occurrence preserved (map_utils.hpp:97)."""
    st, keys, vals = _entries(col)
    offs = np.asarray(col.offsets)
    key_ranks, key_mask = _column_rank_host(keys)
    st_mask = (np.ones(st.length, bool) if st.validity is None
               else np.asarray(st.validity).astype(bool))
    row_mask = (np.ones(col.length, bool) if col.validity is None
                else np.asarray(col.validity).astype(bool))
    if throw_on_null_key and not key_mask.all():
        # only entries under valid rows AND valid structs count
        for row in range(col.length):
            if not row_mask[row]:
                continue
            for e in range(offs[row], offs[row + 1]):
                if st_mask[e] and not key_mask[e]:
                    raise ExceptionWithRowIndex(row, "null map key")
    take = []
    new_offs = np.zeros(col.length + 1, np.int32)
    for row in range(col.length):
        seen = {}
        order = []
        if row_mask[row]:
            for e in range(offs[row], offs[row + 1]):
                if not st_mask[e] or not key_mask[e]:
                    continue  # drop null entries/keys (non-throw mode)
                k = key_ranks[e]
                if k not in seen:
                    order.append(k)
                seen[k] = e           # last occurrence wins
        take.extend(seen[k] for k in order)
        new_offs[row + 1] = len(take)
    idx = jnp.asarray(np.array(take, np.int32))
    new_st = Column.make_struct(len(take),
                                [gather(keys, idx), gather(vals, idx)])
    return Column(dtypes.LIST, col.length, validity=col.validity,
                  offsets=jnp.asarray(new_offs), children=(new_st,))


def sort_map_column(col: Column, descending: bool = False) -> Column:
    """Sort each map's entries by key (map.hpp:39 sort_map_column)."""
    st, keys, vals = _entries(col)
    offs = np.asarray(col.offsets)
    key_ranks, _ = _column_rank_host(keys)
    take = []
    for row in range(col.length):
        es = list(range(offs[row], offs[row + 1]))
        es.sort(key=lambda e: key_ranks[e], reverse=descending)
        take.extend(es)
    idx = jnp.asarray(np.array(take, np.int32))
    new_st = Column.make_struct(
        len(take), [gather(keys, idx), gather(vals, idx)],
        validity=None if st.validity is None
        else np.asarray(st.validity)[np.array(take, np.int64)]
        if len(take) else None)
    return Column(dtypes.LIST, col.length, validity=col.validity,
                  offsets=col.offsets, children=(new_st,))


def map_zip(keys_list: Column, a_vals: Column, b_vals: Column) -> Column:
    """Zip aligned LIST columns into LIST<STRUCT<key, a, b>> — the
    map_zip_with building block (map_zip_with_utils.hpp:60); the three
    lists must share offsets."""
    for c in (keys_list, a_vals, b_vals):
        assert c.dtype.kind == Kind.LIST
    ko = np.asarray(keys_list.offsets)
    if not (np.array_equal(ko, np.asarray(a_vals.offsets))
            and np.array_equal(ko, np.asarray(b_vals.offsets))):
        raise ValueError("map_zip requires aligned list offsets")
    st = Column.make_struct(
        keys_list.children[0].length,
        [keys_list.children[0], a_vals.children[0], b_vals.children[0]])
    return Column(dtypes.LIST, keys_list.length,
                  validity=keys_list.validity, offsets=keys_list.offsets,
                  children=(st,))
