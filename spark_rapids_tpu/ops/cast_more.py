"""Remaining cast/format kernels (reference cast_string.hpp:36-72,
cast_decimal_to_string.cu, cast_long_to_binary_string.cu, hex.cu,
format_float.cu, cast_string_to_datetime.cu /
parse_timestamp_with_format): bin(), hex(), decimal->string,
format_number(), and Spark string->date/timestamp parsing."""

from __future__ import annotations

import datetime
import re
from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType, Kind
from spark_rapids_tpu.ops.exceptions import CastException

_I64 = jnp.int64
_U64 = jnp.uint64
_U8 = jnp.uint8
_I32 = jnp.int32


def long_to_binary_string(col: Column) -> Column:
    """Spark bin(): unsigned 64-bit binary, no leading zeros
    (cast_string.hpp long_to_binary_string).  Fully on device: 64
    bit-lanes -> '0'/'1' bytes, compacted by leading-zero count."""
    assert col.dtype.kind == Kind.INT64
    u = col.data.astype(_U64)
    shifts = jnp.arange(63, -1, -1, dtype=_U64)
    bits = ((u[:, None] >> shifts[None, :]) & _U64(1)).astype(_U8)
    digits = bits + _U8(48)
    nbits = 64 - jnp.sum(jnp.cumsum(bits, axis=1) == 0, axis=1)
    nbits = jnp.maximum(nbits, 1).astype(_I32)  # 0 -> "0"
    lens_host = np.asarray(nbits)
    mask = np.asarray(col.valid_mask())
    lens_host = np.where(mask, lens_host, 0)
    offsets = np.zeros(col.length + 1, np.int32)
    np.cumsum(lens_host, out=offsets[1:])
    total = int(offsets[-1])
    offs_j = jnp.asarray(offsets)
    i = jnp.arange(total, dtype=_I32)
    r = jnp.searchsorted(offs_j, i, side="right").astype(_I32) - 1
    pos = i - offs_j[r]
    src_col = 64 - nbits[r] + pos
    data = digits[r, src_col] if total else jnp.zeros(0, jnp.uint8)
    return Column(dtypes.STRING, col.length, data=data,
                  validity=col.validity, offsets=offs_j)


def bytes_to_hex(col: Column) -> Column:
    """hex() of a binary (LIST<UINT8>) or string column: two uppercase
    hex digits per byte (cast_string.hpp bytes_to_hex)."""
    if col.dtype.kind == Kind.LIST:
        chars = np.asarray(col.children[0].to_numpy())
        offs = np.asarray(col.offsets)
    elif col.dtype.is_string:
        chars = (np.asarray(col.data) if col.data is not None
                 else np.zeros(0, np.uint8))
        offs = np.asarray(col.offsets)
    else:
        raise ValueError("binary or string column required")
    mask = np.asarray(col.valid_mask())
    out = []
    blob = chars.tobytes()
    for i in range(col.length):
        out.append(blob[offs[i]:offs[i + 1]].hex().upper()
                   if mask[i] else None)
    return Column.from_strings(out)


def long_to_hex_string(col: Column) -> Column:
    """hex() of an INT64 column (unsigned, no leading zeros)."""
    assert col.dtype.kind == Kind.INT64
    host = col.to_numpy().astype(np.uint64)
    mask = np.asarray(col.valid_mask())
    return Column.from_strings(
        [format(int(host[i]), "X") if mask[i] else None
         for i in range(col.length)])


def decimal_to_non_ansi_string(col: Column) -> Column:
    """decimal -> string, non-ANSI Spark formatting
    (cast_decimal_to_string.cu): scale digits after the point, leading
    0 for |v| < 1, no trailing-zero trimming."""
    if not col.dtype.is_decimal:
        raise ValueError("decimal column required")
    scale = -col.dtype.scale  # digits after the point
    unscaled = col.to_pylist()
    out: List[Optional[str]] = []
    for v in unscaled:
        if v is None:
            out.append(None)
            continue
        v = int(v)
        neg = v < 0
        digits = str(abs(v))
        if scale <= 0:
            body = digits + "0" * (-scale)
        else:
            digits = digits.rjust(scale + 1, "0")
            body = f"{digits[:-scale]}.{digits[-scale:]}"
        out.append(("-" if neg else "") + body)
    return Column.from_strings(out)


def format_number(col: Column, digits: int) -> Column:
    """Spark format_number(x, d): thousands separators + d decimal places
    HALF_EVEN (format_float.cu / cast_string.hpp format_float)."""
    from spark_rapids_tpu.utils import floats as fl
    kind = col.dtype.kind
    mask = np.asarray(col.valid_mask())
    host = col.to_numpy()
    out: List[Optional[str]] = []
    for i in range(col.length):
        if not mask[i]:
            out.append(None)
            continue
        v = float(host[i]) if kind in (Kind.FLOAT32, Kind.FLOAT64) else \
            int(host[i])
        if isinstance(v, float) and (np.isnan(v) or np.isinf(v)):
            out.append("NaN" if np.isnan(v) else
                       ("∞" if v > 0 else "-∞"))
            continue
        out.append(f"{v:,.{max(digits, 0)}f}")
    return Column.from_strings(out)


# ------------------------------------------------ string -> date/timestamp

_DATE_RE = re.compile(
    r"^\s*([+-]?\d{1,7})(?:-(\d{1,2})(?:-(\d{1,2})(.*))?)?\s*$")
_TIME_RE = re.compile(
    r"^[T ](\d{1,2}):(\d{1,2})(?::(\d{1,2})(?:\.(\d{1,9}))?)?"
    r"(Z|[+-]\d{1,2}(?::\d{1,2})?)?\s*$")


from spark_rapids_tpu.ops.datetime_ops import civil_days_scalar as \
    _days_from_civil


def _valid_ymd(y, m, d) -> bool:
    if not (1 <= m <= 12 and 1 <= d <= 31):
        return False
    if 1 <= y <= 9999:
        try:
            datetime.date(y, m, d)
            return True
        except ValueError:
            return False
    # proleptic years outside datetime.date's range: manual day-in-month
    dim = [31, 29 if (y % 4 == 0 and (y % 100 != 0 or y % 400 == 0))
           else 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31][m - 1]
    return d <= dim


def parse_strings_to_date(col: Column, ansi_mode: bool = False) -> Column:
    """Spark CAST(string AS DATE) (cast_string.hpp parse_strings_to_date):
    accepts yyyy, yyyy-[M]M, yyyy-[M]M-[d]d (trailing time part ignored
    when it starts with T or space)."""
    assert col.dtype.is_string
    vals = col.to_pylist()
    out = np.zeros(col.length, np.int32)
    valid = np.zeros(col.length, bool)
    for i, s in enumerate(vals):
        if s is None:
            continue
        m = _DATE_RE.match(s)
        if not m:
            continue
        y = int(m.group(1))
        mo = int(m.group(2)) if m.group(2) else 1
        d = int(m.group(3)) if m.group(3) else 1
        rest = m.group(4) or ""
        if rest and not (rest.startswith("T") or rest.startswith(" ")):
            continue
        if not _valid_ymd(y, mo, d):
            continue
        out[i] = _days_from_civil(y, mo, d)
        valid[i] = True
    base_valid = np.asarray(col.valid_mask())
    if ansi_mode:
        bad = base_valid & ~valid
        if bad.any():
            row = int(np.argmax(bad))
            raise CastException(row, vals[row])
        validity = col.validity
    else:
        validity = jnp.asarray((valid & base_valid).astype(np.uint8))
    return Column(dtypes.TIMESTAMP_DAYS, col.length,
                  data=jnp.asarray(out), validity=validity)


def parse_timestamp_strings(col: Column, default_tz_offset_sec: int = 0,
                            ansi_mode: bool = False) -> Column:
    """Spark CAST(string AS TIMESTAMP) (cast_string.hpp
    parse_timestamp_strings): date part + optional time-of-day with
    fractional seconds and optional Z/±hh[:mm] zone; zoneless values use
    default_tz_offset_sec."""
    assert col.dtype.is_string
    vals = col.to_pylist()
    out = np.zeros(col.length, np.int64)
    valid = np.zeros(col.length, bool)
    for i, s in enumerate(vals):
        if s is None:
            continue
        m = _DATE_RE.match(s)
        if not m:
            continue
        y = int(m.group(1))
        mo = int(m.group(2)) if m.group(2) else 1
        d = int(m.group(3)) if m.group(3) else 1
        if not _valid_ymd(y, mo, d):
            continue
        rest = m.group(4) or ""
        hh = mm = ss = frac_us = 0
        off = default_tz_offset_sec
        if rest:
            t = _TIME_RE.match(rest)
            if not t:
                continue
            hh = int(t.group(1))
            mm = int(t.group(2))
            ss = int(t.group(3)) if t.group(3) else 0
            if t.group(4):
                frac_us = int(t.group(4)[:6].ljust(6, "0"))
            if t.group(5):
                z = t.group(5)
                if z == "Z":
                    off = 0
                else:
                    sign = -1 if z[0] == "-" else 1
                    parts = z[1:].split(":")
                    off = sign * (int(parts[0]) * 3600
                                  + (int(parts[1]) * 60
                                     if len(parts) > 1 else 0))
            if not (hh < 24 and mm < 60 and ss < 60):
                continue
        days = _days_from_civil(y, mo, d)
        micros = ((days * 86400 + hh * 3600 + mm * 60 + ss - off)
                  * 1_000_000 + frac_us)
        out[i] = micros
        valid[i] = True
    base_valid = np.asarray(col.valid_mask())
    if ansi_mode:
        bad = base_valid & ~valid
        if bad.any():
            row = int(np.argmax(bad))
            raise CastException(row, vals[row])
        validity = col.validity
    else:
        validity = jnp.asarray((valid & base_valid).astype(np.uint8))
    return Column(dtypes.TIMESTAMP_MICROS, col.length,
                  data=jnp.asarray(out), validity=validity)


_FORMAT_TOKENS = [
    ("yyyy", r"(?P<y>\d{4})"), ("MM", r"(?P<M>\d{2})"),
    ("dd", r"(?P<d>\d{2})"), ("HH", r"(?P<H>\d{2})"),
    ("mm", r"(?P<m>\d{2})"), ("ss", r"(?P<s>\d{2})"),
    ("SSSSSS", r"(?P<f6>\d{6})"), ("SSS", r"(?P<f3>\d{3})"),
]


def parse_timestamp_strings_with_format(col: Column, fmt: str,
                                        ansi_mode: bool = False) -> Column:
    """to_timestamp(str, fmt) with the common Java SimpleDateFormat tokens
    (cast_string.hpp parse_timestamp_strings_with_format)."""
    assert col.dtype.is_string
    pattern = ""
    i = 0
    while i < len(fmt):
        for tok, rx in _FORMAT_TOKENS:
            if fmt.startswith(tok, i):
                pattern += rx
                i += len(tok)
                break
        else:
            pattern += re.escape(fmt[i])
            i += 1
    rx = re.compile("^" + pattern + "$")
    vals = col.to_pylist()
    out = np.zeros(col.length, np.int64)
    valid = np.zeros(col.length, bool)
    for i, s in enumerate(vals):
        if s is None:
            continue
        m = rx.match(s.strip())
        if not m:
            continue
        g = m.groupdict()
        y = int(g.get("y") or 1970)
        mo = int(g.get("M") or 1)
        d = int(g.get("d") or 1)
        if not _valid_ymd(y, mo, d):
            continue
        hh = int(g.get("H") or 0)
        mm = int(g.get("m") or 0)
        ss = int(g.get("s") or 0)
        if not (hh < 24 and mm < 60 and ss < 60):
            continue
        frac = int(g.get("f6") or 0) + int(g.get("f3") or 0) * 1000
        days = _days_from_civil(y, mo, d)
        out[i] = (days * 86400 + hh * 3600 + mm * 60 + ss) * 1_000_000 \
            + frac
        valid[i] = True
    base_valid = np.asarray(col.valid_mask())
    if ansi_mode:
        bad = base_valid & ~valid
        if bad.any():
            row = int(np.argmax(bad))
            raise CastException(row, vals[row])
        validity = col.validity
    else:
        validity = jnp.asarray((valid & base_valid).astype(np.uint8))
    return Column(dtypes.TIMESTAMP_MICROS, col.length,
                  data=jnp.asarray(out), validity=validity)
