"""GPU protobuf decoder equivalent (reference protobuf/ 4,956 LoC:
protobuf.hpp:26-67 nested_field_descriptor schema, wire-type parsing
kernels, Protobuf.java / ProtobufSchemaDescriptor.java): decode a binary
column of serialized protobuf messages into a struct column given a
schema descriptor.

Descriptor model mirrors the reference: each field = (field_number,
parent, wire_type, output dtype, encoding DEFAULT/FIXED/ZIGZAG, repeated,
required, default).  Unknown fields are skipped by wire type; missing
optional fields take their default (or null); missing required fields
null the row (proto2); nesting depth is capped at 10
(protobuf.hpp MAX_NESTING_DEPTH)."""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType, Kind

MAX_NESTING_DEPTH = 10

# encodings (protobuf.hpp proto_encoding)
DEFAULT = 0
FIXED = 1
ZIGZAG = 2

# wire types
VARINT = 0
I64BIT = 1
LEN = 2
I32BIT = 5


@dataclass
class Field:
    field_number: int
    dtype: DType                       # output column type
    encoding: int = DEFAULT
    repeated: bool = False
    required: bool = False
    default: Any = None
    name: Optional[str] = None
    children: Sequence["Field"] = field(default_factory=tuple)  # message

    @property
    def is_message(self) -> bool:
        return len(self.children) > 0


class _Malformed(Exception):
    pass


def _read_varint(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        if pos >= len(buf) or shift > 63:
            raise _Malformed()
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result & ((1 << 64) - 1), pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _skip(buf: bytes, pos: int, wire: int) -> int:
    if wire == VARINT:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire == I64BIT:
        pos += 8
    elif wire == I32BIT:
        pos += 4
    elif wire == LEN:
        n, pos = _read_varint(buf, pos)
        pos += n
    else:
        raise _Malformed()
    if pos > len(buf):
        raise _Malformed()  # truncated field payload
    return pos


def _decode_scalar(f: Field, buf: bytes, pos: int, wire: int):
    kind = f.dtype.kind
    if wire == VARINT:
        v, pos = _read_varint(buf, pos)
        if f.encoding == ZIGZAG:
            v = _zigzag(v)
        elif kind in (Kind.INT32, Kind.INT64):
            if v >= 1 << 63:
                v -= 1 << 64   # two's complement
        if kind == Kind.BOOL8:
            v = bool(v)
        elif kind == Kind.INT32:
            v = ((v + 2**31) % 2**32) - 2**31
        return v, pos
    if wire == I64BIT:
        raw = buf[pos:pos + 8]
        if len(raw) < 8:
            raise _Malformed()
        pos += 8
        if kind == Kind.FLOAT64:
            return struct.unpack("<d", raw)[0], pos
        return struct.unpack("<q", raw)[0], pos
    if wire == I32BIT:
        raw = buf[pos:pos + 4]
        if len(raw) < 4:
            raise _Malformed()
        pos += 4
        if kind == Kind.FLOAT32:
            return struct.unpack("<f", raw)[0], pos
        return struct.unpack("<i", raw)[0], pos
    if wire == LEN:
        n, pos = _read_varint(buf, pos)
        raw = buf[pos:pos + n]
        if len(raw) < n:
            raise _Malformed()
        pos += n
        if kind == Kind.STRING:
            return raw.decode("utf-8", errors="replace"), pos
        raise _Malformed()
    raise _Malformed()


def _expected_wire(f: Field) -> int:
    kind = f.dtype.kind
    if f.is_message or kind == Kind.STRING:
        return LEN
    if f.encoding == FIXED:
        return I64BIT if kind in (Kind.INT64, Kind.FLOAT64) else I32BIT
    if kind == Kind.FLOAT64:
        return I64BIT
    if kind == Kind.FLOAT32:
        return I32BIT
    return VARINT


def _decode_message(buf: bytes, fields: Sequence[Field],
                    depth: int) -> dict:
    if depth > MAX_NESTING_DEPTH:
        raise _Malformed()
    by_num = {f.field_number: f for f in fields}
    out: dict = {}
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        wire = tag & 7
        num = tag >> 3
        f = by_num.get(num)
        if f is None:
            pos = _skip(buf, pos, wire)
            continue
        if f.is_message:
            if wire != LEN:
                raise _Malformed()
            n, pos = _read_varint(buf, pos)
            sub = _decode_message(buf[pos:pos + n], f.children, depth + 1)
            pos += n
            if f.repeated:
                out.setdefault(num, []).append(sub)
            else:
                out[num] = sub
            continue
        exp = _expected_wire(f)
        if f.repeated and wire == LEN and exp != LEN:
            # packed repeated scalars
            n, pos = _read_varint(buf, pos)
            end = pos + n
            vals = out.setdefault(num, [])
            while pos < end:
                v, pos = _decode_scalar(f, buf, pos, exp)
                vals.append(v)
            continue
        if wire != exp:
            pos = _skip(buf, pos, wire)  # tolerate mismatched wire type
            continue
        v, pos = _decode_scalar(f, buf, pos, wire)
        if f.repeated:
            out.setdefault(num, []).append(v)
        else:
            out[num] = v  # last value wins (proto3)
    # nested required enforcement propagates up as malformed
    # (reference maybe_check_required_fields nulls the top row)
    for f in fields:
        if f.required and f.field_number not in out:
            raise _Malformed()
    return out


def _build_column(f: Field, values: List, rows: int) -> Column:
    """values: one decoded python value (or None) per row."""
    if f.repeated:
        child_vals = []
        offsets = np.zeros(rows + 1, np.int32)
        for i, v in enumerate(values):
            if v is None:
                v = []
            child_vals.extend(v)
            offsets[i + 1] = len(child_vals)
        inner = Field(f.field_number, f.dtype, f.encoding, False,
                      f.required, f.default, f.name, f.children)
        child = _build_column(inner, child_vals, len(child_vals))
        return Column(dtypes.LIST, rows, offsets=jnp.asarray(offsets),
                      children=(child,))
    if f.is_message:
        validity = np.array([v is not None for v in values], np.uint8)
        children = []
        for ch in f.children:
            ch_vals = [None if v is None else v.get(ch.field_number,
                                                    ch.default)
                       for v in values]
            children.append(_build_column(ch, ch_vals, rows))
        return Column.make_struct(
            rows, children,
            validity=None if validity.all() else validity)
    if f.dtype.is_string:
        return Column.from_strings(values)
    return Column.from_pylist(values, f.dtype)


def decode_protobuf_to_struct(col: Column,
                              fields: Sequence[Field]) -> Column:
    """Binary (LIST<UINT8> or STRING) column of serialized messages ->
    STRUCT column (protobuf.hpp:64 decode_protobuf_to_struct).  Malformed
    rows and rows missing required fields are null.

    Flat scalar schemas route to the vectorized device engine
    (ops/protobuf_device.py, the masked-scan counterpart of the
    reference's protobuf_kernels.cu); everything else — and small
    columns — runs this host path, which doubles as the differential
    oracle (tests/test_protobuf_device.py)."""
    from spark_rapids_tpu.ops import protobuf_device as PD
    if PD.use_device(col, fields):
        out = PD.decode_protobuf_to_struct_device(col, fields)
        if out is not None:
            return out
    rows = col.length
    if col.dtype.kind == Kind.LIST or col.dtype.is_string:
        chars = (np.asarray(col.children[0].data) if
                 col.dtype.kind == Kind.LIST else np.asarray(col.data))
        offs = np.asarray(col.offsets)
    else:
        raise ValueError("binary column required")
    raw = chars.tobytes() if chars is not None and chars.size else b""
    mask = (np.ones(rows, bool) if col.validity is None
            else np.asarray(col.validity).astype(bool))
    decoded: List[Optional[dict]] = []
    for i in range(rows):
        if not mask[i]:
            decoded.append(None)
            continue
        try:
            msg = _decode_message(raw[offs[i]:offs[i + 1]], fields, 0)
        except _Malformed:
            decoded.append(None)
            continue
        decoded.append(msg)
    root = Field(0, dtypes.STRUCT, children=tuple(fields))
    return _build_column(root, decoded, rows)
