"""Batch-parallel JSON structural-index tokenizer (ISSUE 9 tentpole).

The per-row ``lax.scan`` DFA in ops/json_device.py marches every
document one character per scan step — fine on a VPU, catastrophic on
the CPU backend, which is why ``from_json``/raw-map grew hard
``jax.default_backend() != "cpu"`` gates and get_json_object crawled
at 120k rows/s.  This module is the simdjson-shaped alternative: a
handful of *whole-buffer* vectorized passes over the flat Arrow chars
buffer build a structural index for every row simultaneously —

  stage 1  escape parity (backslash run length before each byte, row
           bounded) and in-string parity (cumsum of unescaped quotes);
  stage 2  structural token extraction in one pass ({ } [ ] : , and
           string-open quotes; a string is ONE token carrying its
           close position, paired per row by quote ordinal);
  stage 3  per-token container links: depth from a signed cumsum, and
           for each nesting level a segmented running-max of open
           positions — parent/match links in O(depth) passes, not
           O(tokens);
  stage 4  grammar validation as PURE LOCAL RULES over (previous
           token, gap class, current token, container kind) — the
           classic observation that, once brackets are matched by
           level, JSON's grammar is regular in the token stream;
  stage 5  primitive gaps (the byte runs between tokens) classified
           and validated by a fixed-window vectorized number/literal
           DFA, plus prefix sums for O(1) span-safety range queries
           (whitespace outside strings, escapes, control chars, float
           tokens) used by the verbatim renderers.

Consumers (get_json_object, from_json struct fields, raw map) share
one tokenize pass and emit byte spans into the ORIGINAL buffer;
anything outside the proven-fast shape — single-quoted strings,
documents deeper than MAX_DEPTH, overlong primitives, escape-bearing
keys, >MAX_PAIRS raw-map objects, multi-match paths — flags its row to
the host oracle (ops/json_path), never the whole column.  The host
tree-builder remains the semantics oracle; the differential corpus in
tests/test_device_join_paths.py pins byte-identical output.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

MAX_DEPTH = 16          # container nesting tracked; deeper rows -> host
MAX_PAIRS = 64          # raw-map pairs handled natively per row
_PRIM_W = 26            # primitive window; longer tokens -> host
ROW_CHUNK = 1 << 15     # rows per tokenize pass: bounds temporaries
#                         AND keeps each pass's working set inside the
#                         cache hierarchy (measured ~25% over 2^17 on
#                         the 2-core eval box), while giving the chunk
#                         thread pool enough pieces to balance

# token type codes
T_OBJ, T_CLOSE_OBJ, T_ARR, T_CLOSE_ARR, T_COLON, T_COMMA, T_STR = \
    range(7)

_TYPE_LUT = np.full(256, -1, np.int8)
_TYPE_LUT[ord("{")] = T_OBJ
_TYPE_LUT[ord("}")] = T_CLOSE_OBJ
_TYPE_LUT[ord("[")] = T_ARR
_TYPE_LUT[ord("]")] = T_CLOSE_ARR
_TYPE_LUT[ord(":")] = T_COLON
_TYPE_LUT[ord(",")] = T_COMMA
_TYPE_LUT[ord('"')] = T_STR

_IS_WS = np.zeros(256, bool)
for _c in (32, 9, 10, 13):
    _IS_WS[_c] = True

# structural token chars EXCLUDING the quote (stage 2 fuses the
# type-lookup + quote-exclusion tests into one gather)
_IS_STRUCT_NONQ = _TYPE_LUT >= 0
_IS_STRUCT_NONQ[ord('"')] = False

_ESC_OK = np.zeros(256, bool)
for _c in b"\"'\\/bfnrtu":
    _ESC_OK[_c] = True

_IS_HEX = np.zeros(256, bool)
for _c in b"0123456789abcdefABCDEF":
    _IS_HEX[_c] = True


class Tokens:
    """Structural index for one row chunk (attribute bag)."""
    __slots__ = (
        "chars", "offs", "lens", "R", "N",
        "host", "valid",
        "tpos", "ttype", "tok_offs", "row_of", "str_end",
        "depth_at", "parent", "close_of",
        "gap_end", "gap_next",
        "gap_runs", "gap_first", "gap_last",
        "lead_runs", "lead_first", "lead_last",
        "prim_ok", "prim_float", "prim_negz", "prim_lit",
        "lead_ok", "lead_float", "lead_negz",
        "wsout_cum", "esc_cum", "ctrlstr_cum",
        "gapbad_cum", "_wsout_mask",
    )


def _build_grammar_lut() -> np.ndarray:
    """Token-local grammar as ONE boolean lookup table over the packed
    (token type, previous token, token before that, gap class, context)
    code — the ~45 vectorized boolean passes this replaces were a
    bandwidth bill at a million tokens per chunk.  gap class: 0 = empty
    gap before token, 1 = exactly one valid primitive, 2 = anything
    else.  context: 0 = object, 1 = array, 2 = root."""
    lut = np.zeros(7 * 8 * 8 * 3 * 3, bool)
    for tt in range(7):
        for prev in range(-1, 7):
            for pprev in range(-1, 7):
                for gapc in range(3):
                    for ctxc in range(3):
                        ctx_obj, ctx_arr, ctx_root = (
                            ctxc == 0, ctxc == 1, ctxc == 2)
                        gb_e, gb_p = gapc == 0, gapc == 1
                        is_value_prev = (
                            (prev == T_STR
                             and (pprev == T_COLON if ctx_obj else True)
                             and (pprev == -1 if ctx_root else True))
                            or prev in (T_CLOSE_OBJ, T_CLOSE_ARR))
                        prim_pos_prev = (
                            (prev == T_COLON and ctx_obj)
                            or prev == T_ARR
                            or (prev == T_COMMA and ctx_arr))
                        if tt == T_COLON:
                            ok = (gb_e and ctx_obj and prev == T_STR
                                  and pprev in (T_OBJ, T_COMMA))
                        elif tt == T_COMMA:
                            ok = (ctx_obj or ctx_arr) and (
                                (gb_e and is_value_prev)
                                or (gb_p and prim_pos_prev))
                        elif tt in (T_CLOSE_OBJ, T_CLOSE_ARR):
                            match = prev == (T_OBJ if tt == T_CLOSE_OBJ
                                             else T_ARR)
                            ok = ((gb_e and match)
                                  or (gb_e and is_value_prev)
                                  or (gb_p and prim_pos_prev))
                        elif tt == T_STR:
                            ok = gb_e and (
                                (ctx_obj and prev in (T_OBJ, T_COMMA,
                                                      T_COLON))
                                or (ctx_arr and prev in (T_ARR,
                                                         T_COMMA))
                                or (ctx_root and prev == -1))
                        else:          # T_OBJ / T_ARR open
                            ok = gb_e and (
                                (ctx_obj and prev == T_COLON)
                                or (ctx_arr and prev in (T_ARR,
                                                         T_COMMA))
                                or (ctx_root and prev == -1))
                        lut[tt + 7 * (prev + 1) + 56 * (pprev + 1)
                            + 448 * gapc + 1344 * ctxc] = ok
    return lut


_GRAMMAR_LUT = _build_grammar_lut()


def _build_prim_table(allow_leading_zeros: bool) -> np.ndarray:
    """(states, 256) DFA transition table for JSON primitives.  Number
    states: 0 start, 1 '-', 2 zero, 3 int digits, 4 '.', 5 frac
    digits, 6 e, 7 e-sign, 8 exp digits; literal spines 10..17
    (t-rue / f-alse / n-ull share a padded track); 9 rejects.  The
    tolerant host grammar allows an empty fraction ("12.", "12.e5"):
    state 4 is accepting and may take the exponent."""
    R = 9
    tbl = np.full((23, 256), R, np.uint8)
    dig = [ord(c) for c in "0123456789"]
    tbl[0, ord("-")] = 1
    for d in dig:
        tbl[0, d] = tbl[1, d] = 3
        tbl[3, d] = 3
        tbl[4, d] = tbl[5, d] = 5
        tbl[6, d] = tbl[7, d] = tbl[8, d] = 8
    tbl[0, ord("0")] = tbl[1, ord("0")] = 2
    if allow_leading_zeros:
        for d in dig:
            tbl[2, d] = 3
    for s in (2, 3):
        tbl[s, ord(".")] = 4
    for s in (2, 3, 4, 5):
        tbl[s, ord("e")] = tbl[s, ord("E")] = 6
    tbl[6, ord("+")] = tbl[6, ord("-")] = 7
    # literal spines: true -> 10..13(acc), false -> 14..18(acc),
    # null -> 19..22(acc); final states have no outgoing edges
    for word, base in ((b"true", 10), (b"false", 14), (b"null", 19)):
        prev = 0
        for i, b in enumerate(word):
            tbl[prev, b] = base + i
            prev = base + i
    return tbl


_PRIM_TBL = _build_prim_table(False)
_PRIM_TBL_LZ = _build_prim_table(True)
# accepting states: number-accepting + the three literal finals
_PRIM_ACCEPT = np.zeros(23, bool)
for _s in (2, 3, 4, 5, 8, 13, 18, 22):
    _PRIM_ACCEPT[_s] = True
_PRIM_IS_LIT = np.zeros(23, bool)
for _s in (13, 18, 22):
    _PRIM_IS_LIT[_s] = True


def _prim_check(chars: np.ndarray, first: np.ndarray, last: np.ndarray,
                sel: np.ndarray, allow_leading_zeros: bool):
    """Vectorized primitive validation over [first, last] byte spans
    (sel = which entries to check).  Returns (ok, is_float, is_negzero,
    is_literal, overlong) — ``ok`` is True for true/false/null or a
    strict JSON number (modulo the leading-zero knob).  Work is
    COMPRESSED to the selected entries and the table-driven DFA runs
    only to the longest selected span."""
    n = len(first)
    zeros = np.zeros(n, bool)
    idxs = np.nonzero(sel)[0]
    if len(idxs) == 0:
        return zeros, zeros.copy(), zeros.copy(), zeros.copy(), \
            zeros.copy()
    f = first[idxs]
    length = last[idxs] - f + 1
    over_c = length > _PRIM_W
    w = int(min(_PRIM_W, length.max() if len(length) else 0))
    m = len(idxs)
    win_idx = f[:, None] + np.arange(w, dtype=np.int64)[None, :]
    np.clip(win_idx, 0, max(len(chars) - 1, 0), out=win_idx)
    win = (chars[win_idx] if len(chars) else np.zeros((m, w), np.uint8))
    inlen = np.arange(w)[None, :] < np.minimum(length, w)[:, None]
    win = win * inlen
    # fast path: plain digit runs (the overwhelmingly common case) —
    # one all-digits test instead of w DFA steps
    isdig = ((win >= ord("0")) & (win <= ord("9"))) | ~inlen
    plain = isdig.all(axis=1) & ~over_c & (length >= 1)
    if not allow_leading_zeros:
        plain &= (length == 1) | (win[:, 0] != ord("0"))
    slow = np.nonzero(~plain)[0]
    st = np.zeros(m, np.uint8)
    if len(slow):
        tbl = _PRIM_TBL_LZ if allow_leading_zeros else _PRIM_TBL
        ss = np.zeros(len(slow), np.uint8)
        sw = win[slow]
        sl = inlen[slow]
        for i in range(w):
            act = sl[:, i]
            if not act.any():
                break
            ss = np.where(act, tbl[ss, sw[:, i]], ss)
        st[slow] = ss
    acc = (plain | _PRIM_ACCEPT[st]) & ~over_c
    lit_c = acc & _PRIM_IS_LIT[st] & ~plain
    num_ok = acc & ~(_PRIM_IS_LIT[st] & ~plain)
    isf_c = num_ok & (((win == ord(".")) | (win == ord("e"))
                       | (win == ord("E"))).any(axis=1))
    negz_c = num_ok & (length == 2) & (win[:, 0] == ord("-")) \
        & (win[:, 1] == ord("0")) if w >= 2 else num_ok & False

    def scatter(vals):
        out = zeros.copy()
        out[idxs] = vals
        return out

    return (scatter(acc), scatter(isf_c), scatter(negz_c),
            scatter(lit_c), scatter(over_c))


def _cum(mask: np.ndarray) -> np.ndarray:
    """Prefix-exclusive cumsum, length N+1: sum over [a, b) is
    cum[b] - cum[a].  int32 when the total fits (these arrays are the
    tokenizer's bandwidth bill — chunking keeps N < 2^31)."""
    out = np.zeros(len(mask) + 1,
                   np.int32 if len(mask) < 2**31 else np.int64)
    np.cumsum(mask, out=out[1:])
    return out


def _cum_opt(mask: np.ndarray) -> Optional[np.ndarray]:
    """_cum, or None when the mask is empty — the all-zero prefix sums
    (escapes, control chars) are the common case and the consumers'
    range queries short-circuit on None."""
    return _cum(mask) if mask.any() else None


def _rsum_pos(cum: Optional[np.ndarray], a: np.ndarray, b: np.ndarray
              ) -> np.ndarray:
    """cum[b] - cum[a] > 0 with the None (all-zero) short-circuit."""
    if cum is None:
        return np.zeros(np.shape(a), bool)
    return cum[b] - cum[a] > 0


def tokenize(chars: np.ndarray, offs: np.ndarray,
             allow_leading_zeros: bool = False) -> Tokens:
    """Build the structural index for rows offs[0]..offs[-1] of a flat
    char buffer.  ``chars``/``offs`` are chunk-local (offs[0] == 0)."""
    t = Tokens()
    R = len(offs) - 1
    N = int(offs[-1])
    t.chars, t.offs, t.R, t.N = chars, offs, R, N
    lens = np.diff(offs)
    t.lens = lens
    host = np.zeros(R, bool)
    valid = np.ones(R, bool)

    if N == 0:
        t.host = host
        t.valid = np.zeros(R, bool)      # all rows empty -> invalid
        t.tpos = np.zeros(0, np.int64)
        t.ttype = np.zeros(0, np.int8)
        t.tok_offs = np.zeros(R + 1, np.int64)
        t.row_of = np.zeros(0, np.int64)
        t.str_end = np.zeros(0, np.int64)
        t.depth_at = np.zeros(0, np.int64)
        t.parent = np.zeros(0, np.int64)
        t.close_of = np.zeros(0, np.int64)
        t.gap_end = np.zeros(0, np.int64)
        t.gap_next = np.zeros(0, np.int64)
        for f in ("gap_runs", "gap_first", "gap_last"):
            setattr(t, f, np.zeros(0, np.int64))
        t.lead_runs = np.zeros(R, np.int64)
        t.lead_first = np.zeros(R, np.int64)
        t.lead_last = np.zeros(R, np.int64)
        for f in ("prim_ok", "prim_float", "prim_negz", "prim_lit"):
            setattr(t, f, np.zeros(0, bool))
        for f in ("lead_ok", "lead_float", "lead_negz"):
            setattr(t, f, np.zeros(R, bool))
        t.wsout_cum = t.esc_cum = t.ctrlstr_cum = None
        t.gapbad_cum = None
        t._wsout_mask = None
        return t

    i32 = np.int32 if N < 2**31 else np.int64
    offs_n = offs.astype(i32, copy=False)
    idx = np.arange(N, dtype=i32)

    # byte -> row map, built lazily: the unconditional consumer (quote
    # pairing) uses the cheaper searchsorted form, so the full N-sized
    # repeat only materializes for host-gated shapes (escapes, odd
    # rows, control chars)
    _rob = [None]

    def row_of_b():
        if _rob[0] is None:
            _rob[0] = np.repeat(np.arange(R, dtype=i32), lens)
        return _rob[0]

    # ---- stage 1: escape parity + in-string parity -------------------
    bs = chars == ord("\\")
    has_bs = bool(bs.any())
    if has_bs:
        rstart = np.repeat(offs_n[:-1], lens)
        non_bs_last = np.maximum.accumulate(np.where(~bs, idx, -1))
        prev_non_bs = np.empty(N, i32)
        prev_non_bs[0] = -1
        prev_non_bs[1:] = non_bs_last[:-1]
        run_before = idx - 1 - np.maximum(prev_non_bs, rstart - 1)
        escaped = (run_before & 1) == 1
        sq = (chars == ord('"')) & ~escaped
    else:
        escaped = None
        sq = chars == ord('"')

    # every consumer needs quote COUNTS only mod 2, so the prefix sum
    # is a 1-byte XOR-accumulate, not an i32 cumsum (4x less traffic
    # on the tokenizer's widest pass): qpar[i] = parity of unescaped
    # quotes before byte i
    qpar = np.zeros(N + 1, bool)
    np.logical_xor.accumulate(sq, out=qpar[1:])

    ws = _IS_WS[chars]

    # row gates: odd quote count, single quote outside a string, or a
    # backslash outside a string (tolerant grammar the parity pass
    # cannot track) -> host oracle
    odd_q = qpar[offs_n[1:]] ^ qpar[offs_n[:-1]]
    host |= odd_q
    # per-row parity rebase only matters once an odd row has shifted
    # the global parity — the common all-even chunk skips the repeat
    if bool(odd_q.any()):
        in_str = qpar[:N] ^ np.repeat(qpar[offs_n[:-1]], lens)
    else:
        in_str = qpar[:N]

    def any_per_row(mask: np.ndarray) -> np.ndarray:
        if not mask.any():
            return np.zeros(R, bool)
        return np.bincount(row_of_b()[mask], minlength=R) > 0

    nis = ~in_str
    squote = chars == ord("'")
    if squote.any():
        host |= any_per_row(squote & nis)
    # control chars outside strings that are not whitespace are invalid
    ctrl = chars < 0x20
    if ctrl.any():
        valid &= ~any_per_row(ctrl & ~ws & nis)

    # escape validity (tolerant set + \uXXXX needs 4 in-row hex)
    intro = (bs & ~escaped & in_str) if has_bs else bs
    if has_bs:
        rend = np.repeat(offs_n[1:], lens)
        host |= any_per_row(bs & ~in_str & ~escaped)
        nxt = np.empty(N, np.uint8)
        nxt[:-1] = chars[1:]
        nxt[-1] = 0
        bad = intro & (~_ESC_OK[nxt] | (idx + 1 >= rend))
        isu = intro & (nxt == ord("u"))
        if isu.any():
            for k in range(2, 6):
                pos = np.minimum(idx + k, N - 1)
                bad |= isu & ((idx + k >= rend) | ~_IS_HEX[chars[pos]])
        valid &= ~any_per_row(bad)

    # ---- stage 2: token extraction ----------------------------------
    open_q = sq & nis
    tok_mask = (nis & _IS_STRUCT_NONQ[chars]) | open_q
    # per-row token counts by segment reduction — tok_offs needs no
    # full-buffer cumsum (reduceat quirk: empty segments echo the next
    # element, zeroed after)
    seg = np.minimum(offs_n[:-1], max(N - 1, 0))
    ntok = np.add.reduceat(tok_mask, seg).astype(i32, copy=False)
    ntok[lens == 0] = 0
    tok_offs = np.zeros(R + 1, i32)
    np.cumsum(ntok, out=tok_offs[1:])
    T = int(tok_offs[-1])
    tpos = np.nonzero(tok_mask)[0].astype(i32, copy=False)
    ttype = _TYPE_LUT[chars[tpos]]
    row_of = np.repeat(np.arange(R, dtype=i32), ntok)
    t.tpos, t.ttype, t.tok_offs, t.row_of = tpos, ttype, tok_offs, \
        row_of

    # string close pairing: within a row unescaped quotes strictly
    # alternate open/close (in-row ordinal parity), so an open's close
    # is simply the NEXT quote of the same row — no per-side cumsums.
    # Odd (host-gated) rows leave their last open unpaired (-1).
    str_end = np.full(T, -1, i32)
    is_str_tok = ttype == T_STR
    qpos = np.nonzero(sq)[0].astype(i32, copy=False)
    if len(qpos):
        qrow = (np.searchsorted(offs_n, qpos, side="right")
                .astype(i32, copy=False) - 1)
        ends = np.full(len(qpos), -1, i32)
        same = qrow[1:] == qrow[:-1]
        ends[:-1][same] = qpos[1:][same]
        # in-row quote ordinal parity == global ordinal parity XOR
        # the parity of quotes before the row start
        is_open_q = (((np.arange(len(qpos), dtype=i32) & 1) == 1)
                     == qpar[offs_n[:-1]][qrow])
        str_end[is_str_tok] = ends[is_open_q]
    t.str_end = str_end

    # ---- stage 3: depth + container links ---------------------------
    is_open = (ttype == T_OBJ) | (ttype == T_ARR)
    is_close = (ttype == T_CLOSE_OBJ) | (ttype == T_CLOSE_ARR)
    delta = np.zeros(T, np.int8)
    delta[is_open] = 1
    delta[is_close] = -1
    dcum = np.empty(T, i32)
    np.cumsum(delta, out=dcum)
    first_ti = tok_offs[:-1]
    d_base_row = np.where(first_ti > 0,
                          dcum[np.maximum(first_ti - 1, 0)], 0)
    if d_base_row.any():     # some earlier row left depth unbalanced
        depth_after = dcum - np.repeat(d_base_row, ntok)
    else:                    # common case: every row closed at 0
        depth_after = dcum
    depth_at = depth_after - delta
    t.depth_at = depth_at

    valid &= ~any_per_row_tok(depth_at < 0, row_of, R)
    has_tok = ntok > 0
    if T:
        last_idx = np.maximum(tok_offs[1:] - 1, 0)
        valid &= ~(has_tok & (depth_after[last_idx] != 0))
    maxd = int(depth_at.max()) + 1 if T else 0
    if maxd > MAX_DEPTH:
        host |= any_per_row_tok(depth_at >= MAX_DEPTH, row_of, R)
        maxd = MAX_DEPTH

    tok_idx = np.arange(T, dtype=i32)
    parent = np.full(T, -1, i32)
    open_of_close = np.full(T, -1, i32)
    first_tok = np.repeat(first_ti, ntok)    # row's first token index
    opos = np.nonzero(is_open)[0]
    odepth = depth_at[opos]
    for d in range(max(maxd, 1)):
        md_idx = opos[odepth == d]
        if len(md_idx) == 0:
            continue         # no containers at this level -> no level
        md = np.zeros(T, bool)
        md[md_idx] = True
        lastopen = np.maximum.accumulate(np.where(md, tok_idx, -1))
        lastopen = np.where(lastopen >= first_tok, lastopen, -1)
        # parent of tokens sitting INSIDE level d+1 containers
        sel = depth_at == d + 1
        parent[sel] = lastopen[sel]
        # the container a close at depth_at d+1... closes: same link
        selc = is_close & (depth_at == d + 1)
        open_of_close[selc] = lastopen[selc]
    # closes at depth_at >= 1 map via the loop above; a close token's
    # own container is what it closes
    t.parent = parent
    close_of = np.full(T, -1, i32)
    cpos = np.nonzero(is_close)[0]
    if len(cpos):
        oc = open_of_close[cpos]
        bad_close = (oc < 0) | (ttype[np.maximum(oc, 0)]
                                != np.where(ttype[cpos] == T_CLOSE_OBJ,
                                            T_OBJ, T_ARR))
        valid &= ~any_per_row_tok(bad_close, row_of[cpos], R)
        okc = oc >= 0
        close_of[oc[okc]] = cpos[okc].astype(i32)
    t.close_of = close_of

    # ---- stage 4/5: gaps, primitives, grammar -----------------------
    # token span end (bytes): structural = pos+1, string = close+1
    span_end = np.where(is_str_tok & (str_end >= 0), str_end,
                        tpos) + 1
    next_start = np.empty(T, i32)
    if T:
        next_start[:-1] = tpos[1:]
        next_start[-1] = N
        last_of_row = tok_offs[1:] - 1
        next_start[last_of_row[has_tok]] = offs_n[1:][has_tok]
    t.gap_end = span_end
    t.gap_next = next_start

    nws = ~ws
    nws_prev = np.zeros(N, bool)
    nws_prev[1:] = nws[:-1]
    at_rstart = np.zeros(N, bool)
    at_rstart[offs_n[:-1][lens > 0]] = True
    # a non-ws RUN starts where non-ws follows ws or a row boundary;
    # run starts right after a token byte are handled by gap_info's
    # explicit nws[gs] term (the byte before a gap is always non-ws)
    edge = nws & ~(nws_prev & ~at_rstart)
    ecum = _cum(edge)

    # prev non-ws byte position; a gap's first content byte resolves
    # through the run-start positions (epos) with O(1) gathers
    ln = np.maximum.accumulate(np.where(nws, idx, -1))
    epos = np.nonzero(edge)[0].astype(i32, copy=False)

    def gap_info(gs, ge):
        """(runs, first, last) of non-ws content in [gs, ge) —
        compressed to byte-nonempty, then content-bearing, gaps (most
        gaps are empty or pure whitespace; the gathers only pay for
        the rest).  ``first`` is the start of the run containing
        ``last`` — identical to the gap's first content byte in the
        only case consumers read it (runs == 1)."""
        n_ = len(gs)
        runs = np.zeros(n_, i32)
        first = np.zeros(n_, i32)
        last = np.full(n_, -1, i32)
        nz = np.nonzero(ge > gs)[0]
        if len(nz):
            # content exists iff the last non-ws byte before the gap
            # end falls inside the gap — ln answers it, no second
            # full-buffer prefix sum
            nz = nz[ln[ge[nz] - 1] >= gs[nz]]
        if len(nz):
            g0 = gs[nz]
            g1 = ge[nz]
            runs[nz] = ecum[g1] - ecum[np.minimum(g0 + 1, g1)] \
                + nws[g0]
            lst = ln[g1 - 1]
            # run start of the run holding ``last``: g0 itself when the
            # gap opens mid-run (the byte before a gap is a token, so
            # no edge bit), else the (ecum[last+1] - 1)-th run start
            # overall — O(1) gathers, no binary search
            nxt = epos[np.maximum(ecum[lst + 1] - 1, 0)] \
                if len(epos) else g0
            first[nz] = np.where(nws[g0], g0, nxt)
            last[nz] = lst
        return runs, first, last

    gap_runs, gap_first, gap_last = gap_info(span_end, next_start) \
        if T else (np.zeros(0, i32),) * 3
    t.gap_runs, t.gap_first, t.gap_last = gap_runs, gap_first, gap_last

    if T:
        first_pos = tpos[np.minimum(first_ti, T - 1)]
        lead_end = np.where(has_tok, first_pos, offs_n[1:])
    else:
        lead_end = offs_n[1:].astype(i32, copy=False)
    lead_runs, lead_first, lead_last = gap_info(
        offs_n[:-1].astype(i32, copy=False), lead_end)
    t.lead_runs, t.lead_first, t.lead_last = lead_runs, lead_first, \
        lead_last

    # primitive validation
    psel = gap_runs == 1
    p_ok, p_f, p_nz, p_lit, p_over = _prim_check(
        chars, gap_first, gap_last, psel, allow_leading_zeros)
    lsel = lead_runs == 1
    l_ok, l_f, l_nz, _l_lit, l_over = _prim_check(
        chars, lead_first, lead_last, lsel, allow_leading_zeros)
    t.prim_ok, t.prim_float, t.prim_negz, t.prim_lit = p_ok, p_f, \
        p_nz, p_lit
    t.lead_ok, t.lead_float, t.lead_negz = l_ok, l_f, l_nz
    host |= any_per_row_tok(p_over, row_of, R)
    host |= l_over

    # multiple runs in any gap, or an invalid single-run primitive,
    # invalidate the row (the host parser would reject mid-document)
    valid &= ~any_per_row_tok((gap_runs > 1) | (psel & ~p_ok),
                              row_of, R)
    valid &= ~((lead_runs > 1) | (lsel & ~l_ok & has_tok))
    # zero-token rows: exactly one valid primitive run = root scalar
    no_tok = ~has_tok
    valid &= ~(no_tok & ~(l_ok & (lead_runs == 1)))

    # ---- grammar local rules (packed-code LUT) ----------------------
    if T:
        prev_t = np.full(T, -1, np.int8)        # -1 = virtual row start
        same_row = np.zeros(T, bool)
        same_row[1:] = row_of[1:] == row_of[:-1]
        prev_t[1:] = np.where(same_row[1:], ttype[:-1], np.int8(-1))
        pprev_t = np.full(T, -1, np.int8)
        if T > 2:
            same2 = row_of[2:] == row_of[:-2]
            pprev_t[2:] = np.where(same2, ttype[:-2], np.int8(-1))

        # gap BEFORE each token: row-leading for first token, else the
        # gap after the previous token (scatter fixes first tokens)
        gap_b = np.empty(T, i32)
        gap_b[0] = 0
        gap_b[1:] = gap_runs[:-1]
        gb_prim = np.zeros(T, bool)
        gb_prim[1:] = p_ok[:-1]
        ft = first_ti[has_tok]
        gap_b[ft] = lead_runs[has_tok]
        gb_prim[ft] = l_ok[has_tok]

        ptype = np.full(T, -1, np.int8)
        pp = parent >= 0
        ptype[pp] = ttype[parent[pp]]

        gapc = np.where(gap_b == 0, np.int16(0),
                        np.where((gap_b == 1) & gb_prim, np.int16(1),
                                 np.int16(2)))
        ctxc = np.where(parent < 0, np.int16(2),
                        np.where(ptype == T_OBJ, np.int16(0),
                                 np.int16(1)))
        code = (ttype.astype(np.int16)
                + 7 * (prev_t.astype(np.int16) + 1)
                + 56 * (pprev_t.astype(np.int16) + 1)
                + 448 * gapc + 1344 * ctxc)
        ok_tok = _GRAMMAR_LUT[code]
        # unterminated string (no close in row)
        ok_tok &= ~(is_str_tok & (str_end < 0))

        valid &= ~any_per_row_tok(~ok_tok, row_of, R)

        # trailing gap after the last token must be pure whitespace
        last_idx = tok_offs[1:] - 1
        trail_bad = np.zeros(R, bool)
        trail_bad[has_tok] = gap_runs[last_idx[has_tok]] > 0
        valid &= ~trail_bad

    # ---- span-safety prefix sums ------------------------------------
    # wsout is the expensive common one: defer until a consumer
    # actually range-queries a container span
    t._wsout_mask = ws & nis
    t.wsout_cum = False
    t.esc_cum = _cum_opt(intro) if has_bs else None
    t.ctrlstr_cum = _cum_opt(ctrl & in_str) if ctrl.any() else None
    # per-token cumsum of "render-unsafe primitive gap follows token"
    gap_bad = (p_f | p_nz) if T else np.zeros(0, bool)
    t.gapbad_cum = _cum_opt(gap_bad)

    t.host = host
    t.valid = valid & ~host
    return t


def any_per_row_tok(mask: np.ndarray, row_of: np.ndarray, R: int
                    ) -> np.ndarray:
    if len(mask) == 0 or not mask.any():
        return np.zeros(R, bool)
    return np.bincount(row_of[mask], minlength=R) > 0


def _wsout(t: Tokens) -> Optional[np.ndarray]:
    """Lazily-built whitespace-outside-strings prefix sum (None when
    the chunk has none)."""
    if t.wsout_cum is False:
        t.wsout_cum = _cum_opt(t._wsout_mask)
        t._wsout_mask = None
    return t.wsout_cum


# ======================================================================
# Consumers: get_json_object / raw map / from_json structs over one
# shared structural index.  Each returns per-row verbatim byte spans
# into the ORIGINAL buffer; rows the index cannot render byte-exactly
# (escapes to rewrite, floats to normalize, multi-match paths, the
# tokenizer's own host gates) are flagged to the host oracle in
# ops/json_path — per row, never whole-column.
# ======================================================================

# statistics from the most recent tokenizer-path evaluation
last_stats = {"rows": 0, "fallback_rows": 0, "token_rows": 0}


def _chunks(col):
    """Yield (b0, b1, chars, offs) chunk-local views of a string
    column: offs[0] == 0, chars is the chunk's slice of the flat
    buffer."""
    offs_all = np.asarray(col.offsets).astype(np.int64)
    chars_all = (np.asarray(col.data) if col.data is not None
                 else np.zeros(0, np.uint8))
    for b0 in range(0, col.length, ROW_CHUNK):
        b1 = min(col.length, b0 + ROW_CHUNK)
        lo, hi = offs_all[b0], offs_all[b1]
        yield b0, b1, chars_all[lo:hi], offs_all[b0:b1 + 1] - lo


def _in_valid(col, b0, b1):
    if col.validity is None:
        return np.ones(b1 - b0, bool)
    return np.asarray(col.validity).astype(bool)[b0:b1]


def _tok_index(t: Tokens):
    """Shared per-chunk derived arrays: key tokens, escaped-key rows,
    and a row-indexed root token (-1 for token-less rows)."""
    T = len(t.ttype)
    nxt_same = np.zeros(T, bool)
    if T:
        nxt_same[:-1] = t.row_of[1:] == t.row_of[:-1]
    is_key = np.zeros(T, bool)
    if T:
        is_key[:-1] = ((t.ttype[:-1] == T_STR) & nxt_same[:-1]
                       & (t.ttype[1:] == T_COLON))
    key_esc = np.zeros(T, bool)
    if T and t.esc_cum is not None:
        s0 = np.minimum(t.tpos + 1, t.N)
        s1 = np.clip(t.str_end, 0, t.N)
        key_esc = (t.ttype == T_STR) & (t.str_end >= 0) & \
            _rsum_pos(t.esc_cum, s0, np.maximum(s1, s0))
    has_tok = np.diff(t.tok_offs) > 0
    root = np.where(has_tok, t.tok_offs[:-1], -1)
    esc_key_row = any_per_row_tok(is_key & key_esc, t.row_of, t.R)
    return is_key, key_esc, root, esc_key_row


def _key_name_eq(t: Tokens, is_key: np.ndarray, key_esc: np.ndarray,
                 name: bytes) -> np.ndarray:
    """Per-token: an escape-free key whose raw bytes equal ``name``."""
    L = len(name)
    eq = np.zeros(len(t.ttype), bool)
    cand = np.nonzero(is_key)[0]        # compressed: all tests run
    if len(cand):                       # over the key tokens only
        ok = ~key_esc[cand] & (t.str_end[cand] - t.tpos[cand] - 1 == L)
        cand = cand[ok]
    if len(cand) and t.N:
        base = t.tpos[cand] + 1
        keep = np.ones(len(cand), bool)
        for k, b in enumerate(name):
            keep &= t.chars[np.minimum(base + k, t.N - 1)] == b
        cand = cand[keep]
    eq[cand] = True
    return eq


def _value_after(t: Tokens, x: np.ndarray, have: np.ndarray):
    """The JSON value following token ``x`` (a colon, '[' or comma):
    (vtok, vgap) — vtok >= 0 when the value is the next token (string
    or container open), vgap >= 0 when it is the primitive occupying
    x's trailing gap (vgap == x).  Grammar-valid rows guarantee
    exactly one of the two."""
    T = len(t.ttype)
    xs = np.clip(x, 0, max(T - 1, 0))
    g = np.where(have & (T > 0), t.gap_runs[xs], 0)
    vgap = np.where(have & (g == 1), x, -1)
    nxt = np.clip(x + 1, 0, max(T - 1, 0))
    tok_ok = have & (g == 0) & (x + 1 < T)
    # '[' directly followed by ']' is an empty array, not an element
    close_next = tok_ok & ((t.ttype[nxt] == T_CLOSE_OBJ)
                           | (t.ttype[nxt] == T_CLOSE_ARR))
    vtok = np.where(tok_ok & ~close_next, x + 1, -1)
    return vtok, vgap


def _span_unsafe(t: Tokens, a, b, sel, *, check_float: bool,
                 tok_a=None, tok_b=None):
    """Rows whose [a, b) byte span cannot be copied verbatim: any
    whitespace outside strings, escape intro, or control char inside a
    string — plus (get_json_object only) any float / negative-zero
    primitive gap among tokens [tok_a, tok_b)."""
    if not sel.any():
        # nothing selected: skip the range queries AND the lazy wsout
        # prefix-sum build (the common all-scalar-result chunk)
        return np.zeros(np.shape(sel), bool)
    a = np.clip(a, 0, t.N)
    b = np.clip(b, 0, t.N)
    bad = sel & (_rsum_pos(_wsout(t), a, b)
                 | _rsum_pos(t.esc_cum, a, b)
                 | _rsum_pos(t.ctrlstr_cum, a, b))
    if check_float and tok_a is not None:
        T = len(t.ttype)
        ta = np.clip(tok_a, 0, T)
        tb = np.clip(tok_b, 0, T)
        bad |= sel & _rsum_pos(t.gapbad_cum, ta, tb)
    return bad


def _container_span(t: Tokens, vtok: np.ndarray, sel: np.ndarray):
    """(start, end, close_tok) byte span of container tokens."""
    T = len(t.ttype)
    v = np.clip(vtok, 0, max(T - 1, 0))
    close = np.where(sel, t.close_of[v], -1)
    cc = np.clip(close, 0, max(T - 1, 0))
    start = np.where(sel, t.tpos[v], 0)
    end = np.where(sel & (close >= 0), t.tpos[cc] + 1, 0)
    return start, end, close


def _gather_bytes(chars: np.ndarray, starts: np.ndarray,
                  lens: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(flat bytes, int32 offsets) concatenating per-row spans of a
    flat u8 buffer — one repeat + arange, no per-row loop."""
    lens = np.maximum(lens, 0)
    offs = np.zeros(len(lens) + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    total = int(offs[-1])
    if not total:
        return np.zeros(0, np.uint8), offs.astype(np.int32)
    adj = starts - offs[:-1]
    pos = np.repeat(adj, lens) + np.arange(total, dtype=np.int64)
    return chars[np.clip(pos, 0, len(chars) - 1)], offs.astype(np.int32)


def _eval_path_chunk(t: Tokens, instructions, in_valid: np.ndarray):
    """Evaluate a wildcard-free JSON path over one chunk's structural
    index.  Returns (to_host, starts, lens, validity): verbatim spans
    for rows the index fully resolves, to_host for the rest."""
    from spark_rapids_tpu.ops.json_path import Index, Named

    R = t.R
    T = len(t.ttype)
    is_key, key_esc, root, esc_key_row = _tok_index(t)

    to_host = t.host.copy()
    alive = in_valid & t.valid & ~to_host
    # current value: a token (cur >= 0) or the row-leading primitive
    cur = np.where(alive, root, -1)
    prim_gap = np.full(R, -1, np.int64)     # token whose gap holds it
    # token-less root scalars need no tracking: every instruction on a
    # scalar evaluates to no-match (the empty path is engine-gated)

    for step in instructions:
        if isinstance(step, Named):
            ctype = t.ttype[np.clip(cur, 0, max(T - 1, 0))] \
                if T else np.zeros(R, np.int8)
            on_obj = (cur >= 0) & (ctype == T_OBJ)
            # Named on an array implicitly flattens (multi-match) and
            # escaped keys may unescape to the target — host decides
            to_host |= (cur >= 0) & (ctype == T_ARR)
            to_host |= on_obj & esc_key_row
            eq = _key_name_eq(t, is_key, key_esc,
                              step.name.encode("utf-8"))
            sel_idx = np.nonzero(eq)[0]       # compressed: the parent
            if len(sel_idx):                  # test touches only the
                rows_s = t.row_of[sel_idx]    # name-matched keys
                keep = t.parent[sel_idx] == cur[rows_s]
                sel_idx = sel_idx[keep]
                rows_s = rows_s[keep]
            cnt = (np.bincount(rows_s, minlength=R)
                   if len(sel_idx) else np.zeros(R, np.int64))
            to_host |= on_obj & (cnt > 1)     # duplicate-key multi-match
            hit = np.full(R, -1, np.int64)
            if len(sel_idx):
                hit[rows_s] = sel_idx
            have = on_obj & ~to_host & (cnt == 1)
            cur, prim_gap = _value_after(t, hit + 1, have)
        elif isinstance(step, Index):
            ctype = t.ttype[np.clip(cur, 0, max(T - 1, 0))] \
                if T else np.zeros(R, np.int8)
            on_arr = (cur >= 0) & (ctype == T_ARR)
            if step.index == 0:
                x = np.where(on_arr, cur, -1)
            else:
                x = np.full(R, -1, np.int64)
                if T:
                    cidx = np.nonzero(t.ttype == T_COMMA)[0]
                    if len(cidx):
                        rows_c = t.row_of[cidx]
                        keep = t.parent[cidx] == cur[rows_c]
                        cidx = cidx[keep]
                        rows_c = rows_c[keep]
                        # in-row rank of each kept comma (rows_c is
                        # sorted; exclusive per-row counts rebase)
                        cstart = np.zeros(R, np.int64)
                        if len(rows_c):
                            np.cumsum(np.bincount(
                                rows_c, minlength=R)[:-1],
                                out=cstart[1:])
                        rank = (np.arange(len(cidx))
                                - cstart[rows_c])
                        pick = rank == step.index - 1
                        x[rows_c[pick]] = cidx[pick]
            have = on_arr & (x >= 0) & ~to_host
            cur, prim_gap = _value_after(t, x, have)
        else:                                 # Wildcard: caller gates
            raise AssertionError("wildcard paths never reach the "
                                 "tokenizer engine")

    # ---- render the final value -------------------------------------
    starts = np.zeros(R, np.int64)
    lens = np.zeros(R, np.int64)
    validity = np.zeros(R, bool)

    vv = np.clip(cur, 0, max(T - 1, 0))
    vt = t.ttype[vv] if T else np.zeros(R, np.int8)
    is_str = (cur >= 0) & (vt == T_STR)
    is_cont = (cur >= 0) & ((vt == T_OBJ) | (vt == T_ARR))

    if T:
        s0 = t.tpos[vv] + 1
        s1 = np.clip(t.str_end[vv], 0, t.N)
        to_host |= is_str & _rsum_pos(t.esc_cum, np.minimum(s0, t.N),
                                      s1)
        ok_str = is_str & ~to_host
        starts = np.where(ok_str, s0, starts)
        lens = np.where(ok_str, s1 - s0, lens)
        validity |= ok_str

        ca, cb, _cl = _container_span(t, cur, is_cont)
        to_host |= _span_unsafe(
            t, ca, cb, is_cont, check_float=True,
            tok_a=cur, tok_b=np.where(is_cont, t.close_of[vv], 0))
        ok_cont = is_cont & ~to_host
        starts = np.where(ok_cont, ca, starts)
        lens = np.where(ok_cont, cb - ca, lens)
        validity |= ok_cont

    # primitive result: verbatim only for exact ints / literals
    # (floats take Java Double formatting, "-0" renders "0" — host)
    sel = prim_gap >= 0
    if sel.any():
        g = np.clip(prim_gap, 0, max(T - 1, 0))
        to_host |= sel & (t.prim_float[g] | t.prim_negz[g])
        okp = sel & t.prim_ok[g] & ~to_host
        starts = np.where(okp, t.gap_first[g], starts)
        lens = np.where(okp, t.gap_last[g] - t.gap_first[g] + 1, lens)
        validity |= okp

    to_host &= in_valid
    validity &= in_valid & ~to_host
    return to_host, starts, lens, validity


def get_json_object_tokenized(col, path: str):
    """Structural-index get_json_object; None when the path shape is
    out of the tokenizer's scope (wildcards, malformed, empty)."""
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.ops import json_path as JP

    instructions = JP.parse_path(path)
    if instructions is None:
        return Column.from_strings([None] * col.length)
    if not instructions or any(
            isinstance(i, JP.Wildcard) for i in instructions):
        return None
    return _run_tokenized_paths(col, [instructions])[0]


def get_json_object_multiple_paths_tokenized(col, paths):
    """One output column per path over ONE shared tokenize pass; None
    when any path needs a different engine (caller falls back whole)."""
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.ops import json_path as JP

    parsed = [JP.parse_path(p) for p in paths]
    todo = [p for p in parsed if p is not None]
    if any(not p or any(isinstance(i, JP.Wildcard) for i in p)
           for p in todo):
        return None
    outs = iter(_run_tokenized_paths(col, todo))
    return [Column.from_strings([None] * col.length) if p is None
            else next(outs) for p in parsed]


def _pool_workers() -> int:
    """Chunk-level parallelism: the tokenize passes are numpy C loops
    that release the GIL, so a small thread pool scales near-linearly
    on multi-core hosts.  SPARK_RAPIDS_TPU_JSON_TOKENIZER_THREADS=1
    forces serial."""
    import os
    try:
        w = int(os.environ.get(
            "SPARK_RAPIDS_TPU_JSON_TOKENIZER_THREADS",
            min(4, os.cpu_count() or 1)))
    except ValueError:
        w = 1
    return max(1, w)


def _map_chunks(col, work):
    """[work(b0, b1, chars, offs) for each chunk], in chunk order,
    fanned over the tokenizer thread pool when it pays."""
    chunks = list(_chunks(col))
    workers = _pool_workers()
    if len(chunks) <= 1 or workers <= 1:
        return [work(*c) for c in chunks]
    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(max_workers=workers) as ex:
        return list(ex.map(lambda c: work(*c), chunks))


def _run_tokenized_paths(col, instruction_lists):
    """Shared driver: tokenize each chunk once, evaluate every path,
    patch host rows through the oracle, assemble string columns."""
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.columns.strbuild import build_string_column
    from spark_rapids_tpu.ops import json_path as JP

    P = len(instruction_lists)

    def work(b0, b1, chars, offs):
        t = tokenize(chars, offs)
        iv = _in_valid(col, b0, b1)
        host_docs: Dict[int, str] = {}
        results = []
        for ins in instruction_lists:
            results.append(_eval_path_chunk(t, ins, iv))
        host_rows = np.zeros(t.R, bool)
        for to_host, _s, _l, _v in results:
            host_rows |= to_host
        if host_rows.any():
            for i in np.nonzero(host_rows)[0]:
                host_docs[int(i)] = bytes(
                    chars[offs[i]:offs[i + 1]]).decode(
                        "utf-8", errors="replace")
        cols = []
        n_tok = 0
        for pi, (to_host, starts, lens, validity) in enumerate(results):
            patch = {int(i): JP._run_one(host_docs[int(i)],
                                         instruction_lists[pi])
                     for i in np.nonzero(to_host)[0]}
            n_tok += int(validity.sum())
            cols.append(build_string_column(
                chars, starts, lens, validity, patch))
        return cols, len(host_docs), n_tok

    parts: List[List[Column]] = [[] for _ in range(P)]
    n_host = 0
    n_tok = 0
    for cols, h, k in _map_chunks(col, work):
        for pi, c in enumerate(cols):
            parts[pi].append(c)
        n_host += h
        n_tok += k
    global last_stats
    last_stats = {"rows": int(col.length), "fallback_rows": n_host,
                  "token_rows": n_tok}
    return [_concat_parts(p, col.length) for p in parts]


def _concat_parts(parts, rows: int):
    from spark_rapids_tpu.columns.column import Column
    if not parts:
        return Column.from_strings([None] * rows)
    if len(parts) == 1:
        return parts[0]
    from spark_rapids_tpu.columns.table import Table
    from spark_rapids_tpu.ops.copying import concat_tables
    return concat_tables([Table([p]) for p in parts]).columns[0]


# ======================================================================
# from_json consumers: raw map + flat structs over the same index
# ======================================================================

def _top_level_keys(t: Tokens):
    """(kidx, rows_k) of escape-free top-level object keys, plus the
    per-row root-object mask and the escaped-top-key host gate."""
    is_key, key_esc, root, _esc_row = _tok_index(t)
    T = len(t.ttype)
    has_tok = np.diff(t.tok_offs) > 0
    root_c = np.clip(root, 0, max(T - 1, 0))
    is_obj = has_tok & (t.ttype[root_c] == T_OBJ) if T \
        else np.zeros(t.R, bool)
    kidx = np.nonzero(is_key)[0]
    rows_k = t.row_of[kidx] if len(kidx) else kidx
    if len(kidx):
        keep = t.parent[kidx] == t.tok_offs[:-1][rows_k]
        kidx = kidx[keep]
        rows_k = rows_k[keep]
    esc_top = (any_per_row_tok(key_esc[kidx], rows_k, t.R)
               if len(kidx) else np.zeros(t.R, bool))
    return kidx, rows_k, is_obj, esc_top


def _dup_key_rows(t: Tokens, kidx: np.ndarray, rows_k: np.ndarray
                  ) -> np.ndarray:
    """Rows whose top-level keys are not provably distinct.  A sampled
    byte hash (length + first/middle/last chars) keeps this to a few
    compressed gathers: identical keys always collide (detected), and
    a false collision merely routes the row to the host oracle."""
    if len(kidx) < 2:
        return np.zeros(t.R, bool)
    s0 = t.tpos[kidx] + 1
    klen = t.str_end[kidx] - s0
    cap = max(t.N - 1, 0)
    h = (klen.astype(np.int64)
         + 131 * t.chars[np.minimum(s0, cap)].astype(np.int64)
         + 257 * t.chars[np.minimum(s0 + klen // 2,
                                    cap)].astype(np.int64)
         + 65537 * t.chars[np.minimum(s0 + np.maximum(klen - 1, 0),
                                      cap)].astype(np.int64))
    order = np.lexsort((h, rows_k))
    ro = rows_k[order]
    ho = h[order]
    dup = (ro[1:] == ro[:-1]) & (ho[1:] == ho[:-1])
    if not dup.any():
        return np.zeros(t.R, bool)
    bad = np.zeros(t.R, bool)
    bad[ro[1:][dup]] = True
    return bad


def _value_spans(t: Tokens, x: np.ndarray, have: np.ndarray,
                 *, null_is_none: bool):
    """Verbatim (starts, lens, got, is_null, unsafe) for the value
    following token ``x`` (a colon or comma): strings render their
    unescaped content, containers their exact byte span, primitives
    their gap bytes (numbers VERBATIM — the from_json family never
    normalizes).  ``unsafe`` rows need the host oracle."""
    T = len(t.ttype)
    K = len(x)
    vtok, vgap = _value_after(t, x, have)
    starts = np.zeros(K, np.int64)
    lens = np.zeros(K, np.int64)
    got = np.zeros(K, bool)
    is_null = np.zeros(K, bool)
    unsafe = np.zeros(K, bool)

    vv = np.clip(vtok, 0, max(T - 1, 0))
    vt = t.ttype[vv] if T else np.zeros(K, np.int8)
    is_str = (vtok >= 0) & (vt == T_STR)
    is_cont = (vtok >= 0) & ((vt == T_OBJ) | (vt == T_ARR))
    if T:
        s0 = t.tpos[vv] + 1
        s1 = np.clip(t.str_end[vv], 0, t.N)
        unsafe |= is_str & _rsum_pos(t.esc_cum, np.minimum(s0, t.N),
                                     np.maximum(s1, np.minimum(s0, t.N)))
        ok_str = is_str & ~unsafe
        starts = np.where(ok_str, s0, starts)
        lens = np.where(ok_str, s1 - s0, lens)
        got |= ok_str

        ca, cb, _cl = _container_span(t, vtok, is_cont)
        unsafe |= _span_unsafe(t, ca, cb, is_cont, check_float=False)
        ok_cont = is_cont & ~unsafe
        starts = np.where(ok_cont, ca, starts)
        lens = np.where(ok_cont, cb - ca, lens)
        got |= ok_cont

    sel = vgap >= 0
    if sel.any():
        g = np.clip(vgap, 0, max(T - 1, 0))
        gf = t.gap_first[g]
        gl = t.gap_last[g]
        okp = sel & t.prim_ok[g]
        if null_is_none:
            cap = max(t.N - 1, 0)
            isn = okp & t.prim_lit[g] & (gl - gf == 3) & \
                (t.chars[np.minimum(gf, cap)] == ord("n"))
            is_null |= isn
            okp = okp & ~isn
        starts = np.where(okp, gf, starts)
        lens = np.where(okp, gl - gf + 1, lens)
        got |= okp
    return starts, lens, got, is_null, unsafe


def from_json_to_raw_map_tokenized(col, allow_leading_zeros=False):
    """Structural-index from_json raw map: MAP<STRING,STRING> rows with
    keys in first-seen order and values rendered exactly as the host
    tree-builder would (string content unescaped, numbers and nested
    containers verbatim).  Rows out of the proven shape (escaped or
    duplicate top-level keys, >MAX_PAIRS, render-unsafe spans, the
    tokenizer's own host gates) fall back to the host oracle per row."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columns import dtypes
    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.columns.strbuild import build_string_column
    from spark_rapids_tpu.ops.json_utils import (_parse_rows,
                                                 _value_as_raw_string)

    def work(b0, b1, chars, offs):
        t = tokenize(chars, offs, allow_leading_zeros)
        iv = _in_valid(col, b0, b1)
        R = t.R
        kidx, rows_k, is_obj, esc_top = _top_level_keys(t)
        to_host = t.host.copy()
        to_host |= esc_top
        to_host |= _dup_key_rows(t, kidx, rows_k)
        pair_cnt = (np.bincount(rows_k, minlength=R)
                    if len(kidx) else np.zeros(R, np.int64))
        to_host |= pair_cnt > MAX_PAIRS

        # values after each key's colon
        have = np.ones(len(kidx), bool)
        vs, vl, got, _nul, unsafe = _value_spans(
            t, kidx + 1, have, null_is_none=False)
        to_host |= any_per_row_tok(unsafe | ~got, rows_k, t.R) \
            if len(kidx) else np.zeros(R, bool)
        to_host &= iv

        row_ok = iv & t.valid & is_obj & ~to_host
        # host parses: row -> list[(key, value)] | None
        host_pairs = {}
        if to_host.any():
            rows = [None] * R
            for i in np.nonzero(to_host)[0]:
                rows[i] = bytes(chars[offs[i]:offs[i + 1]]).decode(
                    "utf-8", errors="replace")
            sub = Column.from_strings(rows)
            for i, tree in enumerate(_parse_rows(sub,
                                                 allow_leading_zeros)):
                if not to_host[i]:
                    continue
                if tree is None or tree[0] != "obj":
                    host_pairs[i] = None
                    continue
                seen, order = {}, []
                for k, v in tree[1]:
                    if k not in seen:
                        order.append(k)
                    seen[k] = _value_as_raw_string(v)
                host_pairs[i] = [(k, seen[k]) for k in order]

        counts = np.zeros(R, np.int64)
        keep_k = row_ok[rows_k] if len(kidx) else np.zeros(0, bool)
        rows_kk = rows_k[keep_k]
        counts[np.nonzero(row_ok)[0]] = pair_cnt[row_ok]
        valid_row = row_ok.copy()
        for i, pairs in host_pairs.items():
            if pairs is None:
                continue
            counts[i] = len(pairs)
            valid_row[i] = True
        roffs = np.zeros(R + 1, np.int64)
        np.cumsum(counts, out=roffs[1:])

        # flat positions for tokenizer pairs (rows_kk sorted): in-row
        # ordinal via exclusive per-row counts — no binary search
        kstart = np.zeros(R, np.int64)
        if len(rows_kk):
            np.cumsum(np.bincount(rows_kk, minlength=R)[:-1],
                      out=kstart[1:])
        flat = roffs[rows_kk] + (np.arange(len(rows_kk))
                                 - kstart[rows_kk])
        total = int(roffs[-1])
        kst = np.zeros(total, np.int64)
        kln = np.zeros(total, np.int64)
        vst = np.zeros(total, np.int64)
        vln = np.zeros(total, np.int64)
        kst[flat] = t.tpos[kidx[keep_k]] + 1
        kln[flat] = t.str_end[kidx[keep_k]] - t.tpos[kidx[keep_k]] - 1
        vst[flat] = vs[keep_k]
        vln[flat] = vl[keep_k]
        patch_k, patch_v = {}, {}
        for i, pairs in host_pairs.items():
            if pairs is None:
                continue
            base = int(roffs[i])
            for j, (k, v) in enumerate(pairs):
                patch_k[base + j] = k
                patch_v[base + j] = v
        kcol = build_string_column(chars, kst, kln, None, patch_k)
        vcol = build_string_column(chars, vst, vln, None, patch_v)
        return (counts, valid_row, kcol, vcol, int(to_host.sum()),
                int(row_ok.sum()))

    outs = _map_chunks(col, work)
    rows = col.length
    counts = np.concatenate([o[0] for o in outs]) if outs else \
        np.zeros(0, np.int64)
    valid_row = np.concatenate([o[1] for o in outs]) if outs else \
        np.zeros(0, bool)
    kcol = _concat_parts([o[2] for o in outs], 0)
    vcol = _concat_parts([o[3] for o in outs], 0)
    global last_stats
    last_stats = {"rows": rows,
                  "fallback_rows": sum(o[4] for o in outs),
                  "token_rows": sum(o[5] for o in outs)}
    offs = np.zeros(rows + 1, np.int32)
    np.cumsum(counts, out=offs[1:])
    st = Column.make_struct(int(offs[-1]), [kcol, vcol])
    return Column(dtypes.LIST, rows,
                  validity=None if valid_row.all() else
                  jnp.asarray(valid_row.astype(np.uint8)),
                  offsets=jnp.asarray(offs), children=(st,))


def from_json_to_structs_tokenized(col, fields,
                                   allow_leading_zeros=False):
    """Structural-index from_json to a flat STRUCT: one shared tokenize
    pass, per-field top-level key lookup (duplicate keys: LAST wins,
    natively — dict semantics), values rendered verbatim and converted
    through the same convert_from_strings the host path uses.  None
    when the schema has non-leaf fields (caller falls back)."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columns.column import Column
    from spark_rapids_tpu.columns.dtypes import DType
    from spark_rapids_tpu.columns.strbuild import build_string_column
    from spark_rapids_tpu.ops.json_utils import (_parse_rows,
                                                 _value_as_raw_string,
                                                 convert_from_strings)

    if not all(isinstance(spec, DType) for _n, spec in fields):
        return None
    F = len(fields)

    def work(b0, b1, chars, offs):
        t = tokenize(chars, offs, allow_leading_zeros)
        iv = _in_valid(col, b0, b1)
        R = t.R
        kidx, rows_k, is_obj, esc_top = _top_level_keys(t)
        is_key, key_esc, _root, _e = _tok_index(t)
        to_host = t.host.copy()
        to_host |= esc_top

        field_spans = []
        for name, _spec in fields:
            nm = name.encode("utf-8")
            eq = _key_name_eq(t, is_key, key_esc, nm)
            sel_idx = np.nonzero(eq)[0]
            if len(sel_idx):
                rows_s = t.row_of[sel_idx]
                keep = t.parent[sel_idx] == t.tok_offs[:-1][rows_s]
                sel_idx = sel_idx[keep]
                rows_s = rows_s[keep]
            hit = np.full(R, -1, np.int64)
            if len(sel_idx):
                hit[rows_s] = sel_idx          # dup keys: last wins
            have = hit >= 0
            vs, vl, got, isn, unsafe = _value_spans(
                t, hit + 1, have, null_is_none=True)
            to_host |= have & unsafe
            field_spans.append((vs, vl, got, isn, have))
        to_host &= iv

        row_ok = iv & t.valid & is_obj & ~to_host
        host_trees = {}
        if to_host.any():
            rows = [None] * R
            for i in np.nonzero(to_host)[0]:
                rows[i] = bytes(chars[offs[i]:offs[i + 1]]).decode(
                    "utf-8", errors="replace")
            sub = Column.from_strings(rows)
            for i, tree in enumerate(_parse_rows(sub,
                                                 allow_leading_zeros)):
                if to_host[i]:
                    host_trees[i] = tree

        valid_row = row_ok.copy()
        for i, tree in host_trees.items():
            valid_row[i] = tree is not None and tree[0] == "obj"

        cols = []
        for fi, (vs, vl, got, isn, have) in enumerate(field_spans):
            fvalid = row_ok & got & ~isn
            patch = {}
            name = fields[fi][0]
            for i, tree in host_trees.items():
                if tree is None or tree[0] != "obj":
                    continue
                d = dict(tree[1])
                v = d.get(name)
                patch[i] = (None if v is None or v == ("lit", "null")
                            else _value_as_raw_string(v))
            cols.append(build_string_column(chars, vs, vl, fvalid,
                                            patch))
        return cols, valid_row, int(to_host.sum()), int(row_ok.sum())

    outs = _map_chunks(col, work)
    rows = col.length
    valid_row = np.concatenate([o[1] for o in outs]) if outs else \
        np.zeros(0, bool)
    global last_stats
    last_stats = {"rows": rows,
                  "fallback_rows": sum(o[2] for o in outs),
                  "token_rows": sum(o[3] for o in outs)}
    children = []
    for fi, (_name, spec) in enumerate(fields):
        raw = _concat_parts([o[0][fi] for o in outs], rows)
        children.append(convert_from_strings(raw, spec))
    return Column.make_struct(
        rows, children,
        validity=None if valid_row.all()
        else valid_row.astype(np.uint8))
