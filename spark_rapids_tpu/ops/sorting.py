"""Multi-key sort (the libcudf sort slice of the substrate the reference
leans on for sort_merge joins and ORDER BY; SURVEY.md §7.1): stable
lexicographic ordering with Spark null placement, floats ordered by the
total-order transform (NaN largest, -0.0 < 0.0)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.ops.copying import gather_table
from spark_rapids_tpu.ops.joins import _column_rank_host

ASC = True
DESC = False


def order_by(keys: Table,
             ascending: Optional[Sequence[bool]] = None,
             nulls_first: Optional[Sequence[bool]] = None) -> jnp.ndarray:
    """Stable argsort over the key columns (leftmost key most
    significant).  Returns an int32 gather map.  Spark defaults: ASC with
    nulls first; DESC places nulls last unless overridden."""
    n = keys.num_columns
    asc = list(ascending) if ascending is not None else [True] * n
    if nulls_first is None:
        nf = [a for a in asc]  # Spark: ASC->nulls first, DESC->nulls last
    else:
        nf = list(nulls_first)
    if not (len(asc) == len(nf) == n):
        raise ValueError("ascending/nulls_first must match key count")
    if n == 0:
        return jnp.arange(keys.num_rows, dtype=jnp.int32)
    sort_keys: List[np.ndarray] = []
    for col, a, f in zip(keys.columns, asc, nf):
        rank, mask = _column_rank_host(col)
        # descending via bitwise NOT (order-reversing, no INT64_MIN
        # negation overflow); nulls ordered by a dedicated mask key so no
        # sentinel can collide with a legal rank value
        key = rank if a else ~rank
        null_key = np.where(mask, 1, 0) if f else np.where(mask, 0, 1)
        sort_keys.append(null_key.astype(np.int64))
        sort_keys.append(np.where(mask, key, np.int64(0)))
    # np.lexsort: last key is primary -> reverse
    order = np.lexsort(tuple(reversed(sort_keys)))
    return jnp.asarray(order.astype(np.int32))


def sort_table(table: Table, key_indices: Sequence[int],
               ascending: Optional[Sequence[bool]] = None,
               nulls_first: Optional[Sequence[bool]] = None) -> Table:
    keys = Table([table.columns[i] for i in key_indices])
    order = order_by(keys, ascending, nulls_first)
    return gather_table(table, order)
