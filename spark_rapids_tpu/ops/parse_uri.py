"""Spark parse_url (reference parse_uri.cu/.hpp, ParseURI.java): extract
protocol/host/query/query-by-key/path with java.net.URI validation
semantics — invalid URIs yield null (non-ANSI) or ExceptionWithRowIndex
(ANSI), matching ParseURITest's java.net.URI oracle.

Columns above a size threshold route to the vectorized device engine
(ops/parse_uri_device.py, one jitted pass over the padded char matrix);
the per-row _URI parser here is the semantic oracle and handles the
device engine's fallback rows (non-ASCII, IPv6) plus small columns."""

from __future__ import annotations

import re
from typing import List, Optional, Union

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.ops.exceptions import ExceptionWithRowIndex

_SCHEME_RE = re.compile(r"^[A-Za-z][A-Za-z0-9+.\-]*$")
_HEX = "0123456789abcdefABCDEF"
# RFC 2396 unreserved + punct allowed by java.net.URI per component
_PATH_OK = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"
               "0123456789-_.!~*'():@&=+$,;/")
_QUERY_OK = _PATH_OK | set("?[]")  # java allows ? and [] in query/fragment
_USER_OK = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"
               "0123456789-_.!~*'():&=+$,;")
_HOSTNAME_RE = re.compile(
    r"^(?:[A-Za-z0-9]|[A-Za-z0-9][A-Za-z0-9\-]*[A-Za-z0-9])"
    r"(?:\.(?:[A-Za-z0-9]|[A-Za-z0-9][A-Za-z0-9\-]*[A-Za-z0-9]))*\.?$")
_IPV4_RE = re.compile(r"^(\d{1,3})\.(\d{1,3})\.(\d{1,3})\.(\d{1,3})$")
_IPV6_CHUNK = re.compile(r"^[0-9A-Fa-f]{1,4}$")


class _Invalid(Exception):
    pass


def _check_escapes(s: str, allowed: set) -> None:
    i = 0
    while i < len(s):
        c = s[i]
        if c == "%":
            if i + 2 >= len(s) + 1 or len(s) - i < 3 or \
                    s[i + 1] not in _HEX or s[i + 2] not in _HEX:
                raise _Invalid()
            i += 3
            continue
        if ord(c) >= 0x80:
            # java.net.URI allows non-US-ASCII "other" chars; C1 controls
            # and unicode spaces are rejected
            if 0x80 <= ord(c) <= 0x9F or c.isspace():
                raise _Invalid()
            i += 1
            continue
        if c not in allowed:
            raise _Invalid()
        i += 1


def _valid_ipv6(h: str) -> bool:
    if not (h.startswith("[") and h.endswith("]")):
        return False
    body = h[1:-1]
    if body.count("::") > 1:
        return False
    if "%" in body:  # scope id
        body = body.split("%", 1)[0]
    parts = body.split(":")
    if "" in parts:
        if "::" not in body:
            return False
        parts = [p for p in parts if p]
        if len(parts) > 7:
            return False
    elif len(parts) != 8 and "." not in parts[-1]:
        return False
    for i, p in enumerate(parts):
        if "." in p:
            if i != len(parts) - 1 or not _IPV4_RE.match(p):
                return False
            if any(int(x) > 255 for x in _IPV4_RE.match(p).groups()):
                return False
        elif p and not _IPV6_CHUNK.match(p):
            return False
    return True


class _URI:
    """Mini java.net.URI: scheme/host/rawQuery/rawPath with validation."""

    def __init__(self, s: str):
        self.scheme: Optional[str] = None
        self.host: Optional[str] = None
        self.raw_query: Optional[str] = None
        self.raw_path: Optional[str] = None
        rest = s
        # fragment
        frag = None
        if "#" in rest:
            rest, frag = rest.split("#", 1)
            _check_escapes(frag, _QUERY_OK)
        # scheme
        m = re.match(r"^([A-Za-z][A-Za-z0-9+.\-]*):", rest)
        if m:
            self.scheme = m.group(1)
            rest = rest[m.end():]
        elif rest.startswith(":"):
            raise _Invalid()
        # query (only for hierarchical URIs)
        if self.scheme is not None and not rest.startswith("/") \
                and not rest.startswith("//"):
            # opaque URI: ssp must be non-empty and not start with /
            if not rest:
                raise _Invalid()
            _check_escapes(rest, _QUERY_OK)
            return
        if "?" in rest:
            rest, q = rest.split("?", 1)
            _check_escapes(q, _QUERY_OK)
            self.raw_query = q
        # authority
        if rest.startswith("//"):
            auth = rest[2:]
            slash = auth.find("/")
            if slash >= 0:
                rest = auth[slash:]
                auth = auth[:slash]
            else:
                rest = ""
            self._parse_authority(auth)
        if rest:
            _check_escapes(rest, _PATH_OK)
        self.raw_path = rest

    def _parse_authority(self, auth: str):
        if not auth:
            return
        host = auth
        if "@" in auth:
            user, host = auth.rsplit("@", 1)
            _check_escapes(user, _USER_OK)
        # port
        if host.startswith("["):
            close = host.find("]")
            if close < 0:
                raise _Invalid()
            hostpart = host[:close + 1]
            portpart = host[close + 1:]
            if portpart and not re.match(r"^:\d*$", portpart):
                raise _Invalid()
            if not _valid_ipv6(hostpart):
                raise _Invalid()
            self.host = hostpart
            return
        portpart = None
        if ":" in host:
            host, portpart = host.rsplit(":", 1)
            if portpart and not portpart.isdigit():
                # server-based parse fails; registry authority: host null
                _check_escapes(host + ":" + portpart, _USER_OK | {"[", "]"})
                return
        m4 = _IPV4_RE.match(host)
        if m4 and all(int(x) <= 255 for x in m4.groups()):
            self.host = host
            return
        if _HOSTNAME_RE.match(host):
            self.host = host
            return
        # registry-based authority: URI valid but host is null; chars must
        # still be legal
        _check_escapes(host, _USER_OK | {"[", "]"})


def match_query_key(query, key):
    """parse_url(..., 'QUERY', key) pair matching: value of the FIRST
    '&'-delimited 'key=value' pair, else None.  THE single definition —
    the host extractor, the device engine's fallback rows, and the
    device materializer (parse_uri_device) all call this, so a
    semantics change lands everywhere at once.  Accepts str or bytes
    queries (key is always str)."""
    if query is None or key is None:
        return None
    if isinstance(query, bytes):
        sep, eq, k = b"&", b"=", key.encode()
    else:
        sep, eq, k = "&", "=", key
    for pair in query.split(sep):
        i = pair.find(eq)
        if i >= 0 and pair[:i] == k:
            return pair[i + 1:]
    return None


def _parse(s: Optional[str]) -> Optional[_URI]:
    if s is None:
        return None
    try:
        return _URI(s)
    except _Invalid:
        return None


def _extract(col: Column, what: str, ansi_mode: bool,
             keys: Optional[List[Optional[str]]] = None,
             scalar_key: Optional[str] = None) -> Column:
    assert col.dtype.is_string
    from spark_rapids_tpu.ops import parse_uri_device as PD
    if PD.use_device(col) and (what != "query_key"
                               or scalar_key is not None):
        return PD.extract_device(col, what, ansi_mode, scalar_key)
    vals = col.to_pylist()
    out: List[Optional[str]] = []
    for i, s in enumerate(vals):
        uri = _parse(s)
        if uri is None:
            if ansi_mode and s is not None:
                raise ExceptionWithRowIndex(i, f"invalid URI: {s!r}")
            out.append(None)
            continue
        if what == "protocol":
            out.append(uri.scheme)
        elif what == "host":
            out.append(uri.host)
        elif what == "query":
            out.append(uri.raw_query)
        elif what == "path":
            out.append(uri.raw_path)
        elif what == "query_key":
            out.append(match_query_key(uri.raw_query, keys[i]))
        else:
            raise ValueError(what)
    return Column.from_strings(out)


def parse_uri_to_protocol(col: Column, ansi_mode: bool = False) -> Column:
    return _extract(col, "protocol", ansi_mode)


def parse_uri_to_host(col: Column, ansi_mode: bool = False) -> Column:
    return _extract(col, "host", ansi_mode)


def parse_uri_to_query(col: Column, ansi_mode: bool = False) -> Column:
    return _extract(col, "query", ansi_mode)


def parse_uri_to_path(col: Column, ansi_mode: bool = False) -> Column:
    return _extract(col, "path", ansi_mode)


def parse_uri_to_query_with_key(col: Column,
                                key: Union[str, Column],
                                ansi_mode: bool = False) -> Column:
    if isinstance(key, Column):
        keys = key.to_pylist()
        return _extract(col, "query_key", ansi_mode, keys)
    return _extract(col, "query_key", ansi_mode,
                    [key] * col.length, scalar_key=key)
