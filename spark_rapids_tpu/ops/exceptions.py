"""Op-level exceptions (reference exception_with_row_index.hpp:4-12 /
ExceptionWithRowIndex.java, CastException.java): ANSI-mode errors carry the
first failing row index across the op boundary."""


class ExceptionWithRowIndex(RuntimeError):
    def __init__(self, row_index: int, msg: str = ""):
        super().__init__(msg or f"error at row {row_index}")
        self.row_index = int(row_index)


class CastException(ExceptionWithRowIndex):
    def __init__(self, row_index: int, string_with_error: str = ""):
        super().__init__(row_index,
                         f"Error casting data on row {row_index}: "
                         f"{string_with_error!r}")
        self.string_with_error = string_with_error
