"""Device from_json (flat schemas): typed extraction over the JSON
pushdown scan.

Reference: src/main/cpp/src/from_json_to_structs.cu:1-959 (typed
extraction kernels behind JSONUtils.fromJSONToStructs).  The TPU design
reuses the SAME compiled scan as get_json_object (json_device.py — one
lax.scan over the padded char axis) once per schema field with path
$.<name>, then diverges from get_json_object only in rendering rules:

  * number tokens are copied VERBATIM (from_json does no Java double
    normalization — from_json_to_raw_map.cu copies raw substrings), so
    fractional/negative numbers stay on device;
  * a matched literal `null` nulls the field (get_json_object renders
    the text "null");
  * leaf typing goes through convert_from_strings, whose int/float
    paths are the existing device cast engines (stod_device /
    cast_string DFA).

Per-row host fallback (json_device discipline): rows the scan flags
(deep nesting, invalid UTF-8 …), rows with duplicate keys (from_json is
last-wins; the scan captures one match), string values with escapes,
and nested values whose verbatim span may not equal the re-rendered
text (whitespace / single quotes / control chars).  The host parser
(json_utils._parse_rows) stays the oracle; each fallback row is parsed
ONCE and shared across all schema fields.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType

_WS = (0x20, 0x09, 0x0A, 0x0D)


def _root_is_object(chars: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """First non-whitespace char is '{' (from_json nulls non-object
    rows regardless of field matches)."""
    R, L = chars.shape
    idx = np.arange(L)[None, :]
    ws = np.zeros((R, L), bool)
    for w in _WS:
        ws |= chars == w
    nonws = ~ws & (idx < lens[:, None])
    first = np.argmax(nonws, axis=1)
    any_nonws = nonws.any(axis=1)
    return any_nonws & (chars[np.arange(R), first] == ord("{"))


def _field_strings(col: Column, name: str, padded, host_trees,
                   chars_np: np.ndarray):
    """One schema field -> (raw string column (pre-typing), scan-valid
    mask): device spans with per-row host fallback."""
    from spark_rapids_tpu.ops import json_device as JD
    from spark_rapids_tpu.ops import json_path as JP
    from spark_rapids_tpu.ops.json_utils import _value_as_raw_string

    rows = col.length
    (valid, mcount, mstart, mend, mkind, mfloat, mneg, f_ws, f_sq,
     f_escun, f_ctrl, f_anyesc, f_float, f_negz, fb) = \
        JD._scan_column(col, [JP.Named(name)], padded=padded)

    in_valid = (np.ones(rows, bool) if col.validity is None
                else np.asarray(col.validity).astype(bool)[:rows])

    is_str = mkind == JD._K_STR
    is_lit = mkind == JD._K_LIT
    is_nested = (mkind == JD._K_OBJ) | (mkind == JD._K_ARR)
    # from_json renders numbers verbatim: f_float / f_negz are safe
    nested_unsafe = f_ws | f_sq | f_escun | f_ctrl
    fast_ok = np.where(is_str, ~f_anyesc,
                       np.where(is_nested, ~nested_unsafe, True))
    need_host = in_valid & (fb | (valid & (
        (mcount > 1) | ((mcount == 1) & ~fast_ok))))
    dev_copy = in_valid & ~need_host & valid & (mcount == 1)

    offs = np.asarray(col.offsets)
    span_start = offs[:-1] + np.where(is_str, mstart + 1, mstart)
    span_len = np.where(is_str, mend - mstart - 2, mend - mstart)
    span_len = np.where(dev_copy, np.maximum(span_len, 0), 0)

    # matched literal `null` -> field null (first span char is 'n')
    all_chars = np.asarray(col.data)
    lit_first = all_chars[np.clip(span_start, 0,
                                  max(len(all_chars) - 1, 0))] \
        if len(all_chars) else np.zeros(rows, np.uint8)
    is_null_lit = dev_copy & is_lit & (lit_first == ord("n"))
    dev_copy = dev_copy & ~is_null_lit
    span_len = np.where(dev_copy, span_len, 0)

    # host fallback rows: parse once, share the tree across fields
    fb_idx = np.nonzero(need_host)[0]
    fb_vals = {}
    for i in fb_idx:
        if i not in host_trees:
            doc = bytes(all_chars[offs[i]:offs[i + 1]]).decode(
                "utf-8", errors="replace")
            try:
                host_trees[i] = JP._Parser(doc).parse()
            except JP._Invalid:
                host_trees[i] = None
        tree = host_trees[i]
        if tree is None or tree[0] != "obj":
            fb_vals[i] = None
            continue
        got = dict(tree[1]).get(name)
        fb_vals[i] = (None if got is None or got == ("lit", "null")
                      else _value_as_raw_string(got))

    # assemble device spans; fallback rows splice into the byte buffer
    # (shared builder — never a whole-column Python round-trip)
    from spark_rapids_tpu.columns.strbuild import build_string_column
    out = build_string_column(np.asarray(all_chars), span_start,
                              span_len, dev_copy,
                              fb_vals if fb_vals else None)
    return out, valid


def from_json_to_structs_device(
        col: Column, fields: Sequence[Tuple[str, DType]],
        allow_leading_zeros: bool = False) -> Optional[Column]:
    """Flat-schema device from_json; None when the host path must run
    (nested schemas, leading-zero tolerance, empty input)."""
    if allow_leading_zeros or col.length == 0 or not fields:
        return None
    if not all(isinstance(spec, DType) for _n, spec in fields):
        return None   # nested schema: host builder

    from spark_rapids_tpu.ops import json_device as JD
    from spark_rapids_tpu.ops.json_utils import convert_from_strings

    padded = JD._padded_with_terminator(col)
    chars_np = np.asarray(padded[0])
    lens_np = np.asarray(padded[1])
    rows = col.length

    host_trees = {}
    raw_cols = []
    row_valid = None
    for name, spec in fields:
        raw, valid = _field_strings(col, name, padded, host_trees,
                                    chars_np)
        row_valid = valid if row_valid is None else row_valid
        raw_cols.append(convert_from_strings(raw, spec))

    # struct-level validity: tolerant-JSON valid AND root is an object;
    # rows the scan couldn't judge (fb) take the host parse's verdict
    root_obj = _root_is_object(chars_np, lens_np)
    in_valid = (np.ones(rows, bool) if col.validity is None
                else np.asarray(col.validity).astype(bool)[:rows])
    struct_valid = in_valid & row_valid & root_obj

    # fallback rows that parsed as valid objects must flip validity on
    for i, tree in host_trees.items():
        struct_valid[i] = in_valid[i] and tree is not None \
            and tree[0] == "obj"

    return Column.make_struct(
        rows, raw_cols,
        validity=None if struct_valid.all() else
        struct_valid.astype(np.uint8))
