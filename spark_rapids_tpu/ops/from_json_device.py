"""Device from_json (flat schemas): typed extraction over the JSON
pushdown scan.

Reference: src/main/cpp/src/from_json_to_structs.cu:1-959 (typed
extraction kernels behind JSONUtils.fromJSONToStructs).  The TPU design
reuses the SAME compiled scan as get_json_object (json_device.py — one
lax.scan over the padded char axis) once per schema field with path
$.<name>, then diverges from get_json_object only in rendering rules:

  * number tokens are copied VERBATIM (from_json does no Java double
    normalization — from_json_to_raw_map.cu copies raw substrings), so
    fractional/negative numbers stay on device;
  * a matched literal `null` nulls the field (get_json_object renders
    the text "null");
  * leaf typing goes through convert_from_strings, whose int/float
    paths are the existing device cast engines (stod_device /
    cast_string DFA).

Per-row host fallback (json_device discipline): rows the scan flags
(deep nesting, invalid UTF-8 …), rows with duplicate keys (from_json is
last-wins; the scan captures one match), string values with escapes,
and nested values whose verbatim span may not equal the re-rendered
text (whitespace / single quotes / control chars).  The host parser
(json_utils._parse_rows) stays the oracle; each fallback row is parsed
ONCE and shared across all schema fields.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType

_WS = (0x20, 0x09, 0x0A, 0x0D)


def _root_is_object(chars: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """First non-whitespace char is '{' (from_json nulls non-object
    rows regardless of field matches)."""
    R, L = chars.shape
    idx = np.arange(L)[None, :]
    ws = np.zeros((R, L), bool)
    for w in _WS:
        ws |= chars == w
    nonws = ~ws & (idx < lens[:, None])
    first = np.argmax(nonws, axis=1)
    any_nonws = nonws.any(axis=1)
    return any_nonws & (chars[np.arange(R), first] == ord("{"))


def _host_bufs(col):
    """One device->host materialization of (offsets, chars) shared by
    every fallback loop over a column (hoisted: per-row np.asarray
    would pay one full readback per fallback row)."""
    return np.asarray(col.offsets), np.asarray(col.data)


def _host_tree(bufs, i: int, host_trees, allow_lz: bool = False):
    """Parse row i once (tolerant JSON), shared across all schema
    nodes; None for invalid documents."""
    from spark_rapids_tpu.ops import json_path as JP
    if i not in host_trees:
        offs, all_chars = bufs
        doc = bytes(all_chars[offs[i]:offs[i + 1]]).decode(
            "utf-8", errors="replace")
        try:
            host_trees[i] = JP._Parser(doc, allow_lz).parse()
        except JP._Invalid:
            host_trees[i] = None
    return host_trees[i]


def _tree_nav(tree, steps):
    """Navigate a host parse tree along struct field names (duplicate
    keys last-wins via dict()); None when missing or off-path."""
    cur = tree
    for name in steps:
        if cur is None or cur[0] != "obj":
            return None
        cur = dict(cur[1]).get(name)
    return cur


def _field_strings(col: Column, steps, padded, host_trees,
                   allow_lz: bool = False):
    """One leaf at struct path `steps` -> (raw string column
    (pre-typing), doc-valid mask): device spans with per-row host
    fallback.  `steps` is a list of struct field names; [] matches the
    root value (used when recursing into list elements)."""
    from spark_rapids_tpu.ops import json_device as JD
    from spark_rapids_tpu.ops import json_path as JP
    from spark_rapids_tpu.ops.json_utils import _value_as_raw_string

    rows = col.length
    (valid, mcount, mstart, mend, mkind, mfloat, mneg, f_ws, f_sq,
     f_escun, f_ctrl, f_anyesc, f_float, f_negz, fb) = \
        JD._scan_column(col, [JP.Named(n) for n in steps],
                        padded=padded, allow_leading_zeros=allow_lz)

    in_valid = (np.ones(rows, bool) if col.validity is None
                else np.asarray(col.validity).astype(bool)[:rows])

    is_str = mkind == JD._K_STR
    is_lit = mkind == JD._K_LIT
    is_nested = (mkind == JD._K_OBJ) | (mkind == JD._K_ARR)
    # from_json renders numbers verbatim: f_float / f_negz are safe
    nested_unsafe = f_ws | f_sq | f_escun | f_ctrl
    fast_ok = np.where(is_str, ~f_anyesc,
                       np.where(is_nested, ~nested_unsafe, True))
    need_host = in_valid & (fb | (valid & (
        (mcount > 1) | ((mcount == 1) & ~fast_ok))))
    dev_copy = in_valid & ~need_host & valid & (mcount == 1)

    offs = np.asarray(col.offsets)
    span_start = offs[:-1] + np.where(is_str, mstart + 1, mstart)
    span_len = np.where(is_str, mend - mstart - 2, mend - mstart)
    span_len = np.where(dev_copy, np.maximum(span_len, 0), 0)

    # matched literal `null` -> field null (first span char is 'n')
    all_chars = np.asarray(col.data)
    lit_first = all_chars[np.clip(span_start, 0,
                                  max(len(all_chars) - 1, 0))] \
        if len(all_chars) else np.zeros(rows, np.uint8)
    is_null_lit = dev_copy & is_lit & (lit_first == ord("n"))
    dev_copy = dev_copy & ~is_null_lit
    span_len = np.where(dev_copy, span_len, 0)

    # host fallback rows: parse once, share the tree across fields
    fb_idx = np.nonzero(need_host)[0]
    fb_vals = {}
    bufs = (offs, all_chars)   # already host-materialized above
    for i in fb_idx:
        tree = _host_tree(bufs, i, host_trees, allow_lz)
        got = _tree_nav(tree, steps)
        fb_vals[i] = (None if got is None or got == ("lit", "null")
                      else _value_as_raw_string(got))

    # assemble device spans; fallback rows splice into the byte buffer
    # (shared builder — never a whole-column Python round-trip)
    from spark_rapids_tpu.columns.strbuild import build_string_column
    out = build_string_column(np.asarray(all_chars), span_start,
                              span_len, dev_copy,
                              fb_vals if fb_vals else None)
    return out, valid


def _presence(col: Column, steps, want_kind, padded, host_trees,
              host_tag: str, allow_lz: bool = False):
    """Bool array: value at struct path `steps` exists and has the
    scan kind `want_kind` (K_OBJ for struct nodes, K_ARR for lists);
    rows the scan can't judge resolve via the host tree."""
    from spark_rapids_tpu.ops import json_device as JD
    from spark_rapids_tpu.ops import json_path as JP

    rows = col.length
    (valid, mcount, mstart, mend, mkind, _mf, _mn, _fw, _fsq, _fe,
     _fc, _fa, _ff, _fz, fb) = JD._scan_column(
        col, [JP.Named(n) for n in steps], padded=padded,
        allow_leading_zeros=allow_lz)
    in_valid = (np.ones(rows, bool) if col.validity is None
                else np.asarray(col.validity).astype(bool)[:rows])
    need_host = in_valid & (fb | (valid & (mcount > 1)))
    present = (in_valid & ~need_host & valid & (mcount == 1)
               & (mkind == want_kind))
    host_idx = np.nonzero(need_host)[0]
    bufs = _host_bufs(col) if len(host_idx) else None
    for i in host_idx:
        got = _tree_nav(_host_tree(bufs, i, host_trees, allow_lz),
                        steps)
        present[i] = got is not None and got[0] == host_tag
    return present, valid


def _list_column(col: Column, steps, elem_spec, padded, host_trees,
                 allow_lz: bool = False):
    """LIST node at struct path `steps`: the array's verbatim span is
    located by the scan, top-level elements are split with one
    vectorized pass over the padded matrix (backslash-parity string
    masking + bracket-depth cumsum — the TPU re-design of the
    reference's per-thread nesting walk, from_json_to_structs.cu),
    and the element texts become a CHILD string column the schema
    recursion re-enters with an empty path.  Rows the split cannot
    judge (single-quote strings, empty elements, multi-match) fall
    back per-row to the host parser."""
    from spark_rapids_tpu.ops import json_device as JD
    from spark_rapids_tpu.ops import json_path as JP
    from spark_rapids_tpu.ops.json_utils import _render_json
    from spark_rapids_tpu.columns.strbuild import build_string_column

    rows = col.length
    (valid, mcount, mstart, mend, mkind, _mf, _mn, _fw, f_sq, _fe,
     _fc, _fa, _ff, _fz, fb) = JD._scan_column(
        col, [JP.Named(n) for n in steps], padded=padded,
        allow_leading_zeros=allow_lz)
    chars = np.asarray(padded[0])
    lens = np.asarray(padded[1])
    R, L = chars.shape
    in_valid = (np.ones(rows, bool) if col.validity is None
                else np.asarray(col.validity).astype(bool)[:rows])
    is_arr = mkind == JD._K_ARR
    # single-quote (tolerant) strings break the double-quote parity
    # masking below: host those rows
    need_host = in_valid & (fb | (valid & ((mcount > 1) | f_sq)))
    dev = in_valid & ~need_host & valid & (mcount == 1) & is_arr

    idx = np.arange(L)[None, :]
    # string masking: a quote is real unless preceded by an odd run of
    # backslashes (vectorized run length via maximum.accumulate)
    is_bs = chars == ord("\\")
    last_nonbs = np.maximum.accumulate(
        np.where(~is_bs, idx, -1), axis=1)
    runlen = idx - last_nonbs
    prev_run = np.concatenate(
        [np.zeros((R, 1), runlen.dtype), runlen[:, :-1]], axis=1)
    quote = (chars == ord('"')) & ((prev_run % 2) == 0)
    inside = (np.cumsum(quote, axis=1) % 2) == 1
    open_b = ((chars == ord("{")) | (chars == ord("["))) & ~inside
    close_b = ((chars == ord("}")) | (chars == ord("]"))) & ~inside
    depth = np.cumsum(open_b.astype(np.int32)
                      - close_b.astype(np.int32), axis=1)
    s = np.where(dev, mstart, 0).astype(np.int64)
    e = np.where(dev, mend, 1).astype(np.int64)
    depth_at_s = np.take_along_axis(depth, s[:, None], 1)[:, 0]
    in_span = (idx > s[:, None]) & (idx < (e - 1)[:, None])
    top = in_span & ~inside & (depth == depth_at_s[:, None])
    comma_top = top & (chars == ord(","))

    ws = np.zeros((R, L), bool)
    for w in _WS:
        ws |= chars == w
    has_content = (in_span & ~ws).any(axis=1) & dev
    ncommas = comma_top.sum(axis=1)
    cnt = np.where(has_content, ncommas + 1, 0).astype(np.int64)

    max_cnt = int(cnt.max()) if rows else 0
    if max_cnt > 0:
        width = max(max_cnt, 1)
        cpos = np.sort(np.where(comma_top, idx, L + 1),
                       axis=1)[:, :width].astype(np.int64)
        karr = np.arange(max_cnt)[None, :]
        cp_shift = np.concatenate(
            [np.zeros((R, 1), np.int64), cpos[:, :max_cnt - 1]]
            if max_cnt > 1 else [np.zeros((R, 1), np.int64)], axis=1)
        start_m = np.where(karr == 0, (s + 1)[:, None], cp_shift + 1)
        end_m = np.where(karr < (cnt - 1)[:, None], cpos[:, :max_cnt],
                         (e - 1)[:, None])
        elem_ok = karr < cnt[:, None]
        # whitespace-only elements ("[1,,2]", trailing commas): not
        # verbatim-splittable -> host verdict for the whole row
        nws_cum = np.cumsum((~ws) & (idx < lens[:, None]), axis=1)

        def _cum_at(pos):
            p = np.clip(pos - 1, 0, L - 1)
            v = np.take_along_axis(nws_cum, p, axis=1)
            return np.where(pos > 0, v, 0)

        empty_elem = (elem_ok & ((_cum_at(end_m) - _cum_at(start_m))
                                 <= 0)).any(axis=1) & has_content
        if empty_elem.any():
            need_host |= empty_elem
            dev &= ~empty_elem
            cnt = np.where(empty_elem, 0, cnt)
    else:
        start_m = np.zeros((rows, 1), np.int64)
        end_m = np.zeros((rows, 1), np.int64)

    # host rows: element texts re-rendered from the parse tree
    host_elems = {}
    host_idx = np.nonzero(need_host)[0]
    bufs = _host_bufs(col) if len(host_idx) else None
    for i in host_idx:
        got = _tree_nav(_host_tree(bufs, i, host_trees, allow_lz),
                        steps)
        if got is None or got[0] != "arr":
            host_elems[i] = None
        else:
            host_elems[i] = [_render_json(it, normalize_numbers=False)
                             for it in got[1]]

    present = dev.copy()
    for i, elems in host_elems.items():
        if elems is not None:
            present[i] = True
            cnt[i] = len(elems)

    offsets = np.concatenate([[0], np.cumsum(cnt)]).astype(np.int32)
    total = int(offsets[-1])
    row_ids = np.repeat(np.arange(rows), cnt)
    k_of = np.arange(total) - np.repeat(offsets[:-1].astype(np.int64),
                                        cnt)
    if total:
        k_idx = np.minimum(k_of, start_m.shape[1] - 1).astype(np.int64)
        child_start = (start_m[row_ids, k_idx]
                       + row_ids.astype(np.int64) * L)
        child_len = end_m[row_ids, k_idx] - start_m[row_ids, k_idx]
        dev_child = dev[row_ids]
    else:
        child_start = np.zeros(0, np.int64)
        child_len = np.zeros(0, np.int64)
        dev_child = np.zeros(0, bool)
    host_patch = {}
    for i, elems in host_elems.items():
        if elems is not None:
            base = int(offsets[i])
            for j, text in enumerate(elems):
                host_patch[base + j] = text
    if total:
        child_texts = build_string_column(
            chars.reshape(-1), child_start, child_len, dev_child,
            host_patch if host_patch else None)
        elem_col, _ = _node_column(child_texts, [], elem_spec,
                                   None, {}, allow_lz)
    else:
        # all arrays empty/null: typed empty child via the host
        # builder (the scan cannot run on zero rows)
        from spark_rapids_tpu.ops.json_utils import _build_json_column
        elem_col = _build_json_column([], elem_spec)
    out = Column.make_list(
        offsets, elem_col,
        validity=None if present.all() else present.astype(np.uint8))
    return out, valid


def _node_column(col: Column, steps, spec, padded, host_trees,
                 allow_lz: bool = False):
    """Schema recursion: leaf DType | ("struct", fields) |
    ("list", spec) at struct path `steps` (json_utils.hpp:10-23
    parallel-schema-vector analog: one scan per node, all rows at
    once)."""
    from spark_rapids_tpu.ops import json_device as JD
    from spark_rapids_tpu.ops.json_utils import convert_from_strings

    if padded is None:
        padded = JD._padded_with_terminator(col)
    if isinstance(spec, DType):
        raw, valid = _field_strings(col, steps, padded, host_trees,
                                    allow_lz)
        return convert_from_strings(raw, spec), valid
    tag, arg = spec
    if tag == "struct":
        present, valid = _presence(col, steps, JD._K_OBJ, padded,
                                   host_trees, "obj", allow_lz)
        children = []
        for name, child_spec in arg:
            ch, _ = _node_column(col, list(steps) + [name], child_spec,
                                 padded, host_trees, allow_lz)
            children.append(ch)
        out = Column.make_struct(
            col.length, children,
            validity=None if present.all()
            else present.astype(np.uint8))
        return out, valid
    if tag == "list":
        return _list_column(col, steps, arg, padded, host_trees,
                            allow_lz)
    raise ValueError(f"unknown schema node {tag!r}")


def from_json_to_structs_device(
        col: Column, fields: Sequence[Tuple[str, DType]],
        allow_leading_zeros: bool = False) -> Optional[Column]:
    """Device from_json for flat AND nested schemas; None only for
    empty input (the host builder owns the zero-row shape).  Nested
    struct fields compose scan paths; list nodes split elements with a
    vectorized pass and recurse on the derived child column
    (from_json_to_structs.cu:1-959 re-designed for the one-scan TPU
    engine).  allow_leading_zeros compiles a tolerant-number scan
    variant (Spark allowNumericLeadingZeros)."""
    if col.length == 0 or not fields:
        return None

    from spark_rapids_tpu.ops import json_device as JD

    padded = JD._padded_with_terminator(col)
    chars_np = np.asarray(padded[0])
    lens_np = np.asarray(padded[1])
    rows = col.length

    host_trees = {}
    raw_cols = []
    row_valid = None
    for name, spec in fields:
        child, valid = _node_column(col, [name], spec, padded,
                                    host_trees, allow_leading_zeros)
        row_valid = valid if row_valid is None else row_valid
        raw_cols.append(child)

    # struct-level validity: tolerant-JSON valid AND root is an object;
    # rows the scan couldn't judge (fb) take the host parse's verdict
    root_obj = _root_is_object(chars_np, lens_np)
    in_valid = (np.ones(rows, bool) if col.validity is None
                else np.asarray(col.validity).astype(bool)[:rows])
    struct_valid = in_valid & row_valid & root_obj

    # fallback rows that parsed as valid objects must flip validity on
    for i, tree in host_trees.items():
        struct_valid[i] = in_valid[i] and tree is not None \
            and tree[0] == "obj"

    return Column.make_struct(
        rows, raw_cols,
        validity=None if struct_valid.all() else
        struct_valid.astype(np.uint8))
