"""ORC writer->reader timezone rectification.

Reference: timezones.hpp:24-31 / timezones.cu convert_orc_timezones
(device port of org.apache.orc.impl.SerializationUtils
.convertBetweenTimezones), with the timezone tables built host-side the
way OrcTimezoneInfo.java builds them from java.util.TimeZone.

java.util.TimeZone (sun.util.calendar.ZoneInfo) lookup semantics — which
differ from java.time.ZoneRules and which the device table reproduces
(get_transition_index, timezones.cu:256-289):

  * BEFORE the first historical transition: the zone's RAW offset (not
    the pre-1900 LMT offset ZoneRules would report);
  * between transitions: the offset set by the latest transition <= t;
  * AFTER the last transition: the RAW offset again (recurring DST
    rules would apply here, but DST zones are rejected up front exactly
    like GpuTimeZoneDB.convertOrcTimezones:582-586).

The conversion itself is three offset lookups per timestamp
(SerializationUtils.convertBetweenTimezones), floor-dividing the
microsecond timestamp to milliseconds so negative sub-millisecond
values don't round toward zero (timezones.cu:322-329).  All lookups are
vectorized searchsorted on device.
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.utils import tzdb

# ORC supports timestamps from year 0001 on (OrcTimezoneInfo.java:67)
MIN_SUPPORTED_ORC_UTC_MILLIS = -62135596800000  # 0001-01-01T00:00:00Z

_FIXED_RE = re.compile(r"^([+-])(\d{2}):?(\d{2})(?::?(\d{2}))?$")


class OrcTimezoneInfo:
    """rawOffset (ms) + historical transition table (ms), mirroring
    OrcTimezoneInfo.java:46-59.  transitions is None for fixed zones."""

    __slots__ = ("raw_offset", "transitions", "offsets")

    def __init__(self, raw_offset: int,
                 transitions: Optional[np.ndarray],
                 offsets: Optional[np.ndarray]):
        self.raw_offset = raw_offset
        self.transitions = transitions
        self.offsets = offsets


_info_cache: Dict[str, OrcTimezoneInfo] = {}


def _parse_fixed_offset(zone_id: str) -> Optional[int]:
    """Offset millis for '+05:30'-style ids (valid ZoneIds that
    java.util.TimeZone would silently map to GMT; the reference derives
    the offset from ZoneRules instead, OrcTimezoneInfo.java:131-139)."""
    if zone_id == "Z":          # java ZoneId accepts bare 'Z' for UTC
        return 0
    zid = zone_id
    if zid.upper().startswith(("UTC+", "UTC-", "GMT+", "GMT-")):
        zid = zid[3:]
    m = _FIXED_RE.match(zid)
    if not m:
        return None
    sign = 1 if m.group(1) == "+" else -1
    h, mn = int(m.group(2)), int(m.group(3))
    s = int(m.group(4) or 0)
    if h > 18 or mn > 59 or s > 59:
        raise ValueError(f"invalid offset zone id {zone_id!r}")
    return sign * ((h * 3600 + mn * 60 + s) * 1000)


def _split_posix_std(footer: str) -> Tuple[Optional[str], str]:
    """Split a POSIX TZ footer into (std offset spec or None, rest after
    the offset).  Shared scanner for DST detection and raw-offset
    extraction so the two can't drift apart."""
    if not footer:
        return None, ""
    i = 0
    if footer.startswith("<"):        # <quoted> std designation
        close = footer.find(">")
        i = close + 1 if close >= 0 else len(footer)
    while i < len(footer) and footer[i] not in "+-0123456789":
        i += 1
    j = i
    if j < len(footer) and footer[j] in "+-":
        j += 1
    while j < len(footer) and (footer[j].isdigit() or footer[j] == ":"):
        j += 1
    spec = footer[i:j]
    if not spec or not any(ch.isdigit() for ch in spec):
        return None, footer[j:]
    return spec, footer[j:]


def _footer_has_dst(footer: str) -> bool:
    """POSIX TZ footer contains a DST designation (e.g. 'PST8PDT,M3...')?
    The std name + offset is followed by a dst name when the zone keeps
    observing DST — java.util.TimeZone.useDaylightTime equivalent."""
    _, rest = _split_posix_std(footer)
    return bool(rest.split(",")[0])


def has_daylight_saving_time(zone_id: str) -> bool:
    """GpuTimeZoneDB.isDST analog: the zone observes DST going forward
    (recurring rule in the TZif footer).  TZif v1 files carry no footer;
    for those, recent DST flags in the transition table are the signal —
    without this, a v1-only tzdata would silently convert DST zones with
    raw-offset semantics (data corruption) instead of raising."""
    if _parse_fixed_offset(zone_id) is not None or zone_id in (
            "UTC", "GMT", "Z"):
        return False
    rec = tzdb.get_zone_info(zone_id)
    if _footer_has_dst(rec.footer):
        return True
    if not rec.footer and len(rec.trans) > 1:
        horizon = int(rec.trans[-1]) - 15 * 365 * 86400
        recent = rec.trans >= horizon
        if bool((np.asarray(rec.isdst)[recent] != 0).any()):
            return True
    return False


def _raw_offset_ms(rec: "tzdb.ZoneInfoRecord") -> int:
    """java.util.TimeZone.getRawOffset: the current STANDARD offset.
    From the footer's std offset when present (authoritative for the
    recurring era), else the last non-DST offset in the table."""
    spec, _ = _split_posix_std(rec.footer)
    if spec is not None:
        neg = spec.startswith("-")
        parts = [int(x) for x in spec.lstrip("+-").split(":")]
        while len(parts) < 3:
            parts.append(0)
        secs = parts[0] * 3600 + parts[1] * 60 + parts[2]
        # POSIX TZ offsets are west-positive: UTC offset = -spec
        return (secs if neg else -secs) * 1000
    std = [(int(t), int(o)) for t, o, d in
           zip(rec.trans, rec.offs, rec.isdst) if not d]
    if std:
        return std[-1][1] * 1000
    return int(rec.offs[-1]) * 1000 if len(rec.offs) else 0


def get_orc_timezone_info(zone_id: str) -> OrcTimezoneInfo:
    """OrcTimezoneInfo.get analog (cached); ValueError on unknown ids
    (no silent GMT fallback — OrcTimezoneInfo.java:107-116)."""
    if zone_id in _info_cache:
        return _info_cache[zone_id]
    fixed = _parse_fixed_offset(zone_id)
    if fixed is not None:
        info = OrcTimezoneInfo(fixed, None, None)
    else:
        rec = tzdb.get_zone_info(zone_id)   # raises ValueError if unknown
        trans_s = rec.trans[1:]             # drop the -inf sentinel row
        offs_s = rec.offs[1:]
        trans_ms = trans_s * 1000
        offs_ms = offs_s * 1000
        keep = trans_ms >= MIN_SUPPORTED_ORC_UTC_MILLIS
        trans_ms, offs_ms = trans_ms[keep], offs_ms[keep]
        raw = _raw_offset_ms(rec)
        if trans_ms.size == 0:
            info = OrcTimezoneInfo(raw, None, None)
        else:
            info = OrcTimezoneInfo(raw, trans_ms.astype(np.int64),
                                   offs_ms.astype(np.int64))
    _info_cache[zone_id] = info
    return info


def _offset_lookup(t_ms: jnp.ndarray, info: OrcTimezoneInfo
                   ) -> jnp.ndarray:
    """Vectorized get_transition_index (timezones.cu:256-289): offset in
    effect at each t_ms under java.util.TimeZone semantics."""
    raw = jnp.int64(info.raw_offset)
    if info.transitions is None:
        return jnp.full(t_ms.shape, raw, jnp.int64)
    trans = jnp.asarray(info.transitions)
    offs = jnp.asarray(info.offsets)
    n = int(info.transitions.shape[0])
    idx = jnp.searchsorted(trans, t_ms, side="right").astype(jnp.int32)
    at = offs[jnp.clip(idx - 1, 0, n - 1)]
    out = jnp.where(idx == 0, raw, at)          # before the table
    out = jnp.where(idx == n, raw, out)         # after the table
    return out


def convert_orc_timezones(col: Column, writer_tz: str,
                          reader_tz: str) -> Column:
    """Rectify ORC timestamps written under writer_tz for a reader in
    reader_tz (GpuTimeZoneDB.convertOrcTimezones:578-604 →
    timezones.cu convert_timestamp_between_timezones).

    Raises NotImplementedError for DST zones, matching the reference's
    UnsupportedOperationException guard (GpuTimeZoneDB.java:582-586)."""
    assert col.dtype.kind == Kind.TIMESTAMP_MICROS
    if has_daylight_saving_time(writer_tz) or \
            has_daylight_saving_time(reader_tz):
        raise NotImplementedError(
            "Daylight Saving Time is not supported now.")
    w = get_orc_timezone_info(writer_tz)
    r = get_orc_timezone_info(reader_tz)

    us = col.data.astype(jnp.int64)
    ms = jnp.floor_divide(us, jnp.int64(1000))
    w_off = _offset_lookup(ms, w)
    r_off = _offset_lookup(ms, r)
    adjusted_ms = ms + (w_off - r_off)
    r_adj = _offset_lookup(adjusted_ms, r)
    final = us + (w_off - r_adj) * jnp.int64(1000)
    return Column(col.dtype, col.length, data=final,
                  validity=col.validity)
