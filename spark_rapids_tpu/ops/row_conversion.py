"""Row <-> columnar conversion in the JCUDF row format.

Reference: src/main/cpp/src/row_conversion.cu (format spec in
RowConversion.java:67-137 javadoc and compute_column_information
row_conversion.cu:1367-1405):

  * fixed-width section: columns in order, each aligned to its byte size
    (strings/lists store a 4-byte-aligned (offset-in-row, length) uint32
    pair); then validity — one bit per column (1 = valid), byte-aligned;
    then variable-width payloads; row length rounded up to 8 bytes
    (JCUDF_ROW_ALIGNMENT).
  * output is a LIST<INT8> column: row i = bytes[offsets[i]:offsets[i+1]].

TPU-first design: the reference uses square shared-memory tiles with
memcpy_async to balance row/column coalescing (row_conversion.cu:109-126).
On TPU the same job is done by XLA fusion: each column's bytes are computed
with integer shifts ((rows, size) uint8 lanes), padding/validity are more
lanes, and one concatenate builds the (rows, row_bytes) matrix — a single
fused HBM-bandwidth-bound kernel with 8x128-friendly shapes.  FLOAT64
columns already carry uint64 raw bits (columns/column.py) so no f64
bitcasts are ever needed; float32 bitcasts to u32 lanes (TPU-supported).

Variable-width rows are assembled per-row padded then compacted by a
gather keyed on searchsorted(row_offsets) — vectorized, no per-row loops.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType, Kind
from spark_rapids_tpu.columns.table import Table

JCUDF_ROW_ALIGNMENT = 8

_U8 = jnp.uint8
_U32 = jnp.uint32
_U64 = jnp.uint64
_I32 = jnp.int32


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _col_byte_size(dt: DType) -> int:
    if dt.is_string:
        return 8  # (offset, length) uint32 pair
    if dt.kind == Kind.DECIMAL128:
        return 16
    return dt.size_bytes


def _col_alignment(dt: DType) -> int:
    return 4 if dt.is_string else _col_byte_size(dt)


def compute_layout(schema: Sequence[DType]):
    """Per-column start offsets + fixed-section/validity sizes.
    Mirrors compute_column_information (row_conversion.cu:1367)."""
    starts: List[int] = []
    size = 0
    for dt in schema:
        size = _round_up(size, _col_alignment(dt))
        starts.append(size)
        size += _col_byte_size(dt)
    validity_offset = size
    size += (len(schema) + 7) // 8
    return starts, validity_offset, size  # size = fixed + validity bytes


def _value_bytes(col: Column) -> jnp.ndarray:
    """(rows, size) uint8 little-endian bytes of a fixed-width column."""
    kind = col.dtype.kind
    d = col.data
    if kind == Kind.FLOAT32:
        u = lax.bitcast_convert_type(d, _U32)
        n = 4
    elif kind == Kind.FLOAT64:
        u = d.astype(_U64)  # already raw bits
        n = 8
    elif kind == Kind.DECIMAL128:
        # (rows, 4) int32 limbs -> 16 LE bytes
        u = d.astype(_U32)
        k = jnp.arange(16, dtype=_I32)
        return ((u[:, k // 4] >> ((8 * (k % 4)).astype(_U32)))
                & _U32(0xFF)).astype(_U8)
    else:
        n = col.dtype.size_bytes
        u = d.astype(jnp.int64).astype(_U64) if n == 8 else \
            d.astype(_I32).astype(_U32)
    shifts = (8 * jnp.arange(n, dtype=_I32)).astype(u.dtype)
    return ((u[:, None] >> shifts[None, :]) & u.dtype.type(0xFF)).astype(_U8)


def _bytes_to_values(raw: jnp.ndarray, dt: DType) -> jnp.ndarray:
    """(rows, size) uint8 LE bytes -> (rows,) natural-dtype values (or
    (rows,4) int32 limbs for decimal128)."""
    kind = dt.kind
    if kind == Kind.DECIMAL128:
        b = raw.astype(_U32)
        limbs = (b[:, 0::4] | (b[:, 1::4] << _U32(8))
                 | (b[:, 2::4] << _U32(16)) | (b[:, 3::4] << _U32(24)))
        return limbs.astype(jnp.int32)
    n = raw.shape[1]
    if n == 8:
        u = jnp.zeros(raw.shape[:1], _U64)
        for k in range(8):
            u = u | (raw[:, k].astype(_U64) << _U64(8 * k))
        if kind == Kind.FLOAT64 or dt.np_dtype == np.dtype(np.uint64):
            return u  # raw-bits / unsigned representation
        return u.astype(jnp.int64)
    u = jnp.zeros(raw.shape[:1], _U32)
    for k in range(n):
        u = u | (raw[:, k].astype(_U32) << _U32(8 * k))
    if kind == Kind.FLOAT32:
        return lax.bitcast_convert_type(u, jnp.float32)
    if n < 4 and dt.np_dtype.kind == "i":  # sign-extend from the top
        u = u << _U32(8 * (4 - n))
        s = u.astype(jnp.int32) >> _I32(8 * (4 - n))
        return s.astype(dt.np_dtype)
    return u.astype(jnp.int32) if dt.np_dtype == np.dtype(np.int32) else \
        u.astype(dt.np_dtype)


def _validity_bytes(cols: Sequence[Column]) -> jnp.ndarray:
    """(rows, ceil(ncols/8)) uint8; bit c%8 of byte c//8 set = col c valid."""
    nbytes = (len(cols) + 7) // 8
    return jnp.stack([_validity_byte_vector(cols, b) for b in range(nbytes)],
                     axis=1)


def _validity_byte_vector(cols: Sequence[Column], b: int) -> jnp.ndarray:
    """(rows,) uint8 validity byte b (bit i = col 8b+i valid)."""
    rows = cols[0].length
    byte = jnp.zeros((rows,), _U8)
    for i in range(8):
        c = b * 8 + i
        if c >= len(cols):
            break
        if cols[c].validity is None:
            byte = byte | _U8(1 << i)
        else:
            byte = byte | ((cols[c].validity != 0).astype(_U8) << _U8(i))
    return byte


def field_word_slots(dt: DType, st: int):
    """[(word_index, shift_bits, nbits)] for the value pieces of one
    fixed-width field at byte offset `st` — THE single source of the
    JCUDF word layout.  Consumed by build_plan (assembly: piece arrays
    zip with these coordinates) and by the Pallas from-rows extraction
    plan (row_assembly_pallas.build_extract_plan), so the two
    directions cannot drift."""
    w = st // 4
    size = _col_byte_size(dt)
    if dt.kind == Kind.DECIMAL128:
        return [(w + k, 0, 32) for k in range(4)]
    if size == 8:
        return [(w, 0, 32), (w + 1, 0, 32)]
    if size == 4:
        return [(w, 0, 32)]
    return [(w, (st % 4) * 8, size * 8)]


def build_plan(cols: Sequence[Column], starts: Sequence[int],
               validity_offset: int, n_words: int):
    """(inputs, plan): one (rows,) array per word contribution in its
    native width (u8/u16/u32; 8-byte columns split into u32 lo/hi —
    (rows, 2) u32 bitcasts are not tile-safe on this backend, see
    docs/tpu_design.md §2), and the (word_index, left_shift_bits) each
    lands at.  Word coordinates come from field_word_slots (the shared
    layout source); this function supplies the matching piece arrays.
    Consumed by the default stack assembly below and by the Pallas
    tile kernel (ops/row_assembly_pallas.py)."""
    inputs = []
    plan = []

    def add(arrs, slots):
        assert len(arrs) == len(slots)
        for arr, (word, shift, _nbits) in zip(arrs, slots):
            inputs.append(arr)
            plan.append((word, shift))

    for c, st in zip(cols, starts):
        kind = c.dtype.kind
        d = c.data
        slots = field_word_slots(c.dtype, st)
        if kind == Kind.FLOAT32:
            arrs = [lax.bitcast_convert_type(d, _U32)]
        elif kind == Kind.DECIMAL128:
            u = lax.bitcast_convert_type(d, _U32)
            arrs = [u[:, k] for k in range(4)]
        elif _col_byte_size(c.dtype) == 8:
            u = (d if d.dtype == jnp.uint64
                 else d.astype(jnp.int64).astype(_U64))
            arrs = [(u & _U64(0xFFFFFFFF)).astype(_U32),
                    (u >> _U64(32)).astype(_U32)]
        elif _col_byte_size(c.dtype) == 4:
            arrs = [lax.bitcast_convert_type(d.astype(_I32), _U32)]
        else:
            size = _col_byte_size(c.dtype)
            native = jnp.uint8 if size == 1 else jnp.uint16
            arrs = [d if d.dtype == native
                    else lax.bitcast_convert_type(
                        d.astype(jnp.int16 if size == 2 else jnp.int8),
                        native)]
        add(arrs, slots)

    for b in range((len(cols) + 7) // 8):
        off = validity_offset + b
        inputs.append(_validity_byte_vector(cols, b))
        plan.append((off // 4, (off % 4) * 8))

    assert all(w < n_words for w, _ in plan)
    return inputs, plan


def _assemble_fixed_words(cols, starts, validity_offset,
                          row_size) -> jnp.ndarray:
    """Word-oriented row assembly: compose each 4-byte word of the row
    from (rows,) u32 vectors (full-lane friendly) and stack them into the
    (rows, W) matrix.  Avoids the 16x lane padding of narrow (rows, k)
    uint8 pieces; measured ~59 GB/s of output on one v5e chip.  The
    single-pass Pallas tile kernel (row_assembly_pallas.py, env opt-in
    in convert_to_rows) consumes the same build_plan.  Returns flat
    packed u32 LE words."""
    rows = cols[0].length
    n_words = row_size // 4
    inputs, plan = build_plan(cols, starts, validity_offset, n_words)
    contribs = {}
    for arr, (w, sh) in zip(inputs, plan):
        u = arr if arr.dtype == _U32 else arr.astype(_U32)
        if sh:
            u = u << _U32(sh)
        contribs.setdefault(w, []).append(u)
    zeros = None
    words = []
    for w in range(n_words):
        if w in contribs:
            acc = contribs[w][0]
            for u in contribs[w][1:]:
                acc = acc | u
            words.append(acc)
        else:
            if zeros is None:
                zeros = jnp.zeros((rows,), _U32)
            words.append(zeros)
    mat = jnp.stack(words, axis=1)         # (rows, W) directly
    return mat.reshape(-1)                  # packed u32 LE words


def convert_to_rows(table: Table) -> Column:
    """Table -> LIST<INT8> column of JCUDF rows (RowConversion.convertToRows,
    RowConversionJni.cpp).  Fixed-width and string columns."""
    cols = table.columns
    if not cols:
        raise ValueError("cannot convert empty table")
    rows = table.num_rows
    schema = [c.dtype for c in cols]
    starts, validity_offset, fixed_size = compute_layout(schema)

    str_cols = [c for c in cols if c.dtype.is_string]
    if not str_cols:
        row_size = _round_up(fixed_size, JCUDF_ROW_ALIGNMENT)
        if os.environ.get("SPARK_RAPIDS_TPU_PALLAS_ROWCONV") == "1":
            # single-pass Pallas tile kernel (opt-in until profiled on
            # real hardware); interpret mode on the CPU backend
            from spark_rapids_tpu.ops.row_assembly_pallas import \
                assemble_fixed_words_pallas
            data = assemble_fixed_words_pallas(
                cols, starts, validity_offset, row_size,
                interpret=jax.default_backend() == "cpu")
        else:
            data = _assemble_fixed_words(cols, starts, validity_offset,
                                         row_size)
        offsets = jnp.arange(rows + 1, dtype=_I32) * _I32(row_size)
        return Column.make_list_from_parts(offsets, data,
                                           nbytes=rows * row_size)

    # variable-width path
    str_lens = [c.string_lengths() for c in str_cols]
    var_total = sum(str_lens)
    row_sizes = ((jnp.full((rows,), fixed_size, _I32) + var_total
                  + _I32(JCUDF_ROW_ALIGNMENT - 1))
                 // JCUDF_ROW_ALIGNMENT * JCUDF_ROW_ALIGNMENT)
    offsets = jnp.concatenate([jnp.zeros((1,), _I32),
                               jnp.cumsum(row_sizes).astype(_I32)])
    # per-row (offset-in-row, length) pairs for each string column
    var_starts = []
    off = jnp.full((rows,), fixed_size, _I32)
    for lens in str_lens:
        var_starts.append(off)
        off = off + lens
    max_row = int(np.asarray(row_sizes).max()) if rows else 0
    mat = _assemble_fixed(cols, starts, validity_offset, max_row,
                          list(zip(var_starts, str_lens)), fixed_size)
    # paste string payloads into the padded matrix
    use_pallas_paste = (
        os.environ.get("SPARK_RAPIDS_TPU_PALLAS_ROWCONV") == "1"
        and rows > 0)
    for c, vstart, lens in zip(str_cols, var_starts, str_lens):
        pad = max(1, c.max_string_length())
        chars, _ = c.to_padded_chars(pad_to=pad)
        if use_pallas_paste:
            # VMEM tile gather (row_assembly_pallas.py) instead of a
            # whole-matrix HBM scatter; interpret mode on CPU
            from spark_rapids_tpu.ops.row_assembly_pallas import \
                paste_strings_pallas
            mat = paste_strings_pallas(
                mat, chars, vstart, lens,
                interpret=jax.default_backend() == "cpu")
            continue
        # scatter chars into mat[r, vstart[r]+j]
        j = jnp.arange(pad, dtype=_I32)
        dest = vstart[:, None] + j[None, :]
        m = j[None, :] < lens[:, None]
        mat = _masked_row_scatter(mat, dest, chars, m)
    flat = _compact(mat, offsets, row_sizes)
    return Column.make_list_from_parts(offsets, flat)


def _assemble_fixed(cols, starts, validity_offset, row_size,
                    var_pairs, fixed_size) -> jnp.ndarray:
    """(rows, row_size) uint8 with fixed-width values, validity, padding."""
    rows = cols[0].length
    pieces = []
    pos = 0
    vp = 0
    for c, st in zip(cols, starts):
        if st > pos:
            pieces.append(jnp.zeros((rows, st - pos), _U8))
        if c.dtype.is_string:
            vstart, lens = var_pairs[vp]
            vp += 1
            pair = jnp.stack([vstart.astype(_U32), lens.astype(_U32)], 1)
            shifts = (8 * jnp.arange(4, dtype=_I32)).astype(_U32)
            b = ((pair[:, :, None] >> shifts[None, None, :])
                 & _U32(0xFF)).astype(_U8).reshape(rows, 8)
            pieces.append(b)
            pos = st + 8
        else:
            vb = _value_bytes(c)
            pieces.append(vb)
            pos = st + vb.shape[1]
    if validity_offset > pos:
        pieces.append(jnp.zeros((rows, validity_offset - pos), _U8))
    pieces.append(_validity_bytes(cols))
    pos = fixed_size
    if row_size > pos:
        pieces.append(jnp.zeros((rows, row_size - pos), _U8))
    return jnp.concatenate(pieces, axis=1)


def _masked_row_scatter(mat, dest, src, mask):
    """mat[r, dest[r,j]] = src[r,j] where mask — via one-hot-free gather:
    build an index map from output position back to source position."""
    rows, width = mat.shape
    pad = dest.shape[1]
    # scatter via jnp at: vectorized scatter is fine on TPU through XLA
    r = jnp.broadcast_to(jnp.arange(rows, dtype=_I32)[:, None], dest.shape)
    dest_c = jnp.where(mask, dest, width)  # out-of-range drops
    return mat.at[r.reshape(-1), dest_c.reshape(-1)].set(
        src.reshape(-1), mode="drop")


def _compact(mat: jnp.ndarray, offsets: jnp.ndarray,
             row_sizes: jnp.ndarray) -> jnp.ndarray:
    """(rows, maxP) padded matrix -> flat uint8 using per-row sizes."""
    total = int(np.asarray(offsets)[-1])
    i = jnp.arange(total, dtype=_I32)
    r = jnp.searchsorted(offsets, i, side="right").astype(_I32) - 1
    p = i - offsets[r]
    return mat[r, p]


def convert_from_rows(list_col: Column, schema: Sequence[DType]) -> Table:
    """LIST<INT8> of JCUDF rows -> Table (RowConversion.convertFromRows)."""
    from spark_rapids_tpu.columns import bytesview

    rows = list_col.length
    starts, validity_offset, fixed_size = compute_layout(schema)
    if (os.environ.get("SPARK_RAPIDS_TPU_PALLAS_ROWCONV") == "1"
            and rows > 0
            and not any(dt.is_string for dt in schema)
            and list_col.children[0].data.dtype == jnp.uint32):
        # single-pass tile disassembly (one HBM read of the row matrix
        # feeds all column extractions); interpret mode on CPU.  The
        # kernel needs uniform contiguous rows — any other buffer
        # shape falls through to the per-row gather path below.
        row_size = _round_up(fixed_size, JCUDF_ROW_ALIGNMENT)
        if int(list_col.children[0].data.size) == rows * (row_size // 4):
            from spark_rapids_tpu.ops.row_assembly_pallas import \
                convert_from_rows_pallas
            return convert_from_rows_pallas(
                list_col, schema,
                interpret=jax.default_backend() == "cpu")
    child = list_col.children[0]
    data = child.data  # flat byte buffer (u8 or packed u32 words)
    offs = list_col.offsets
    out_cols: List[Column] = []
    nbytes_total = child.length

    def gather_bytes(col_start: int, size: int) -> jnp.ndarray:
        idx = offs[:-1][:, None] + col_start + jnp.arange(size, dtype=_I32)
        idx = jnp.clip(idx, 0, max(nbytes_total - 1, 0))
        return bytesview.byte_gather(data, idx)

    for ci, dt in enumerate(schema):
        raw = gather_bytes(starts[ci], _col_byte_size(dt))
        vbyte = gather_bytes(validity_offset + ci // 8, 1)[:, 0]
        valid = ((vbyte >> _U8(ci % 8)) & _U8(1)).astype(jnp.uint8)
        if dt.is_string:
            pair = _bytes_to_values(raw[:, 0:4], dtypes.INT32), \
                _bytes_to_values(raw[:, 4:8], dtypes.INT32)
            in_row_off, lens = pair
            str_offsets = jnp.concatenate(
                [jnp.zeros((1,), _I32), jnp.cumsum(lens).astype(_I32)])
            pad = int(np.asarray(lens).max()) if rows else 0
            pad = max(pad, 1)
            j = jnp.arange(pad, dtype=_I32)
            src = offs[:-1][:, None] + in_row_off[:, None] + j[None, :]
            src = jnp.clip(src, 0, max(nbytes_total - 1, 0))
            chars2d = jnp.where(j[None, :] < lens[:, None],
                                bytesview.byte_gather(data, src), _U8(0))
            flat = _compact(chars2d, str_offsets, lens)
            out_cols.append(Column(dtypes.STRING, rows, data=flat,
                                   validity=valid, offsets=str_offsets))
        else:
            vals = _bytes_to_values(raw, dt)
            out_cols.append(Column(dt, rows, data=vals, validity=valid))
    return Table(out_cols)
