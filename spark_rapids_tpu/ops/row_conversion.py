"""Row <-> columnar conversion in the JCUDF row format.

Reference: src/main/cpp/src/row_conversion.cu (format spec in
RowConversion.java:67-137 javadoc and compute_column_information
row_conversion.cu:1367-1405):

  * fixed-width section: columns in order, each aligned to its byte size
    (strings/lists store a 4-byte-aligned (offset-in-row, length) uint32
    pair); then validity — one bit per column (1 = valid), byte-aligned;
    then variable-width payloads; row length rounded up to 8 bytes
    (JCUDF_ROW_ALIGNMENT).
  * output is a LIST<INT8> column: row i = bytes[offsets[i]:offsets[i+1]].

TPU-first design: the reference uses square shared-memory tiles with
memcpy_async to balance row/column coalescing (row_conversion.cu:109-126).
On TPU the same job is done by XLA fusion: each row word is an OR of
shifted (rows,) column vectors fused into one concat write
(_assemble_fixed_words).  Validity packs ALL columns in one vectorized
packbits-style scatter-add instead of a per-byte python loop — that
loop was the historical compile blow-up; with it gone a 212-column
schema lowers+compiles in about a second.  The **width-grouped** class
machinery (_grouped_fixed_bytes: columns of equal byte width stacked
into one (rows, n_cols_of_width) matrix per width class, one byte-lane
expansion each) builds the variable-width fixed section; for the
fixed-width word path the measured truth on this backend is that
per-column fusion beats materialized class matrices by 4-20x, so the
word path keeps per-column pieces and the class path stays for
byte-matrix consumers.  FLOAT64 columns already carry uint64 raw bits
(columns/column.py) so no f64 bitcasts are ever needed; float32
bitcasts to u32 lanes (TPU-supported).

The eager graph is additionally routed through the process-wide kernel
compile cache (spark_rapids_tpu/perf/jit_cache.py): fixed-width
conversions compile once per (schema digest, power-of-two row bucket)
and every later batch in the same bucket reuses the executable with
zero XLA compilation.  SPARK_RAPIDS_TPU_JIT_CACHE=0 falls back to the
uncached (still width-grouped) graph.

Variable-width rows are assembled per-row padded then compacted by a
gather keyed on searchsorted(row_offsets) — vectorized, no per-row loops.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType, Kind
from spark_rapids_tpu.columns.table import Table

JCUDF_ROW_ALIGNMENT = 8

_U8 = jnp.uint8
_U32 = jnp.uint32
_U64 = jnp.uint64
_I32 = jnp.int32


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _col_byte_size(dt: DType) -> int:
    if dt.is_string:
        return 8  # (offset, length) uint32 pair
    if dt.kind == Kind.DECIMAL128:
        return 16
    return dt.size_bytes


def _col_alignment(dt: DType) -> int:
    return 4 if dt.is_string else _col_byte_size(dt)


def compute_layout(schema: Sequence[DType]):
    """Per-column start offsets + fixed-section/validity sizes.
    Mirrors compute_column_information (row_conversion.cu:1367)."""
    starts: List[int] = []
    size = 0
    for dt in schema:
        size = _round_up(size, _col_alignment(dt))
        starts.append(size)
        size += _col_byte_size(dt)
    validity_offset = size
    size += (len(schema) + 7) // 8
    return starts, validity_offset, size  # size = fixed + validity bytes


# --------------------------------------------------- width-grouped assembly


def _to_unsigned(mat: jnp.ndarray) -> jnp.ndarray:
    """Same-width unsigned view of an integer/float matrix (bitcast —
    never a value conversion)."""
    dt = mat.dtype
    if dt in (jnp.uint8, jnp.uint16, jnp.uint32, jnp.uint64):
        return mat
    if dt == jnp.float32:
        return lax.bitcast_convert_type(mat, _U32)
    if dt == jnp.float64:
        return lax.bitcast_convert_type(mat, _U64)
    target = {1: jnp.uint8, 2: jnp.uint16, 4: _U32, 8: _U64}[dt.itemsize]
    return lax.bitcast_convert_type(mat, target)


def _le_byte_matrix(mat: jnp.ndarray, w: int) -> jnp.ndarray:
    """(rows, m) unsigned width-w matrix -> (rows, m*w) uint8
    little-endian byte lanes — one shift/mask over the whole class."""
    if w == 1:
        return mat.astype(_U8)
    shifts = (8 * jnp.arange(w, dtype=_I32)).astype(mat.dtype)
    b = ((mat[:, :, None] >> shifts[None, None, :])
         & mat.dtype.type(0xFF)).astype(_U8)
    return b.reshape(mat.shape[0], mat.shape[1] * w)


def _validity_bytes(cols: Sequence[Column]) -> jnp.ndarray:
    """(rows, ceil(ncols/8)) uint8; bit c%8 of byte c//8 set = col c
    valid.  Vectorized packbits: always-valid columns fold into one
    host-side constant byte vector; the nullable columns stack into a
    single (rows, m) matrix, scale by their bit weights, and scatter-add
    into the byte lanes in one op — no per-byte python loop."""
    rows = cols[0].length
    nbytes = (len(cols) + 7) // 8
    base = np.zeros((nbytes,), np.uint8)
    arrs, byte_idx, weights = [], [], []
    for ci, c in enumerate(cols):
        if c.validity is None:
            base[ci // 8] |= np.uint8(1 << (ci % 8))
        else:
            arrs.append((c.validity != 0).astype(_U8))
            byte_idx.append(ci // 8)
            weights.append(1 << (ci % 8))
    out = jnp.broadcast_to(jnp.asarray(base)[None, :], (rows, nbytes))
    if arrs:
        vm = jnp.stack(arrs, axis=1) * \
            jnp.asarray(np.array(weights, np.uint8))[None, :]
        acc = jnp.zeros((rows, nbytes), _U8).at[
            :, jnp.asarray(np.array(byte_idx, np.int32))].add(vm)
        out = out | acc
    return out


def _validity_byte_vector(cols: Sequence[Column], b: int) -> jnp.ndarray:
    """(rows,) uint8 validity byte b (bit i = col 8b+i valid).  Kept for
    callers that want one byte; packs all bytes vectorized and slices —
    use _validity_bytes directly when you need more than one."""
    return _validity_bytes(cols)[:, b]


def _grouped_fixed_bytes(cols: Sequence[Column], starts: Sequence[int],
                         validity_offset: int, out_width: int,
                         var_pairs: Optional[Sequence[Tuple]] = None
                         ) -> jnp.ndarray:
    """(rows, out_width) uint8 fixed section via width-grouped assembly.

    Columns are grouped by native buffer dtype; each group becomes one
    stacked matrix and one byte-lane expansion (O(width classes) heavy
    ops).  Per-column byte runs are then cheap static slices of their
    class byte matrix, concatenated in layout order with zero-fill for
    alignment gaps — compile-light data movement, no per-column math.
    String columns contribute their (offset-in-row, length) u32 pairs
    from ``var_pairs``; DECIMAL128 contributes its four u32 limbs."""
    rows = cols[0].length
    groups: dict = {}          # key -> {"w": int, "arrs": [...]}
    placement = []             # per column: (key, first_piece, n_pieces)
    vp = 0
    for c, st in zip(cols, starts):
        if c.dtype.is_string:
            vstart, lens = var_pairs[vp]
            vp += 1
            g = groups.setdefault("u32", {"w": 4, "arrs": []})
            placement.append(("u32", len(g["arrs"]), 2))
            g["arrs"].extend([vstart.astype(_U32), lens.astype(_U32)])
        elif c.dtype.kind == Kind.DECIMAL128:
            g = groups.setdefault("dec128", {"w": 4, "arrs": []})
            placement.append(("dec128", len(g["arrs"]), 4))
            g["arrs"].append(c.data)   # (rows, 4) int32 limbs
        else:
            key = str(c.data.dtype)
            g = groups.setdefault(
                key, {"w": c.data.dtype.itemsize, "arrs": []})
            placement.append((key, len(g["arrs"]), 1))
            g["arrs"].append(c.data)

    class_bytes = {}
    for key, g in groups.items():
        if key == "dec128":
            mat = jnp.concatenate(g["arrs"], axis=1)   # (rows, 4k) i32
        else:
            mat = jnp.stack(g["arrs"], axis=1)
        class_bytes[key] = _le_byte_matrix(_to_unsigned(mat), g["w"])

    pieces = []
    pos = 0
    for (key, p0, np_), c, st in zip(placement, cols, starts):
        if st > pos:
            pieces.append(jnp.zeros((rows, st - pos), _U8))
        w = groups[key]["w"]
        if key == "dec128":
            # placement counts (rows,4) limb matrices; 16 bytes each
            pieces.append(class_bytes[key][:, p0 * 16:(p0 + 1) * 16])
            pos = st + 16
        else:
            pieces.append(class_bytes[key][:, p0 * w:(p0 + np_) * w])
            pos = st + np_ * w
    if validity_offset > pos:
        pieces.append(jnp.zeros((rows, validity_offset - pos), _U8))
    pieces.append(_validity_bytes(cols))
    pos = validity_offset + (len(cols) + 7) // 8
    if out_width > pos:
        pieces.append(jnp.zeros((rows, out_width - pos), _U8))
    return jnp.concatenate(pieces, axis=1)


def _assemble_fixed_words(cols, starts, validity_offset,
                          row_size) -> jnp.ndarray:
    """Word-oriented row assembly: compose each 4-byte word of the row
    from (rows,) u32 vectors and stack them into the (rows, W) matrix.
    XLA fuses every per-column bitcast/shift straight into the single
    concat write, so the data moves HBM->HBM exactly once — measured
    4-20x faster than materializing per-width-class matrices on this
    backend (class matrices force extra full-size passes that defeat
    the fusion).  The graph stays O(columns) in op COUNT but each op is
    trivial data movement; the historical compile blow-up came from the
    per-byte python validity stacking, which _validity_bytes now packs
    in one vectorized scatter-add (a 212-column schema lowers+compiles
    in ~1 s).  Recompiles across batch sizes are absorbed by the
    compile cache (perf/jit_cache.py row bucketing); the single-pass
    Pallas tile kernel (row_assembly_pallas.py, env opt-in in
    convert_to_rows) consumes the same build_plan.  Returns flat packed
    u32 LE words."""
    rows = cols[0].length
    n_words = row_size // 4
    inputs, plan = build_plan(cols, starts, validity_offset, n_words)
    contribs = {}
    for arr, (w, sh) in zip(inputs, plan):
        u = arr if arr.dtype == _U32 else arr.astype(_U32)
        if sh:
            u = u << _U32(sh)
        contribs.setdefault(w, []).append(u)
    zeros = None
    words = []
    for w in range(n_words):
        if w in contribs:
            acc = contribs[w][0]
            for u in contribs[w][1:]:
                acc = acc | u
            words.append(acc)
        else:
            if zeros is None:
                zeros = jnp.zeros((rows,), _U32)
            words.append(zeros)
    mat = jnp.stack(words, axis=1)         # (rows, W) directly
    return mat.reshape(-1)                  # packed u32 LE words


def field_word_slots(dt: DType, st: int):
    """[(word_index, shift_bits, nbits)] for the value pieces of one
    fixed-width field at byte offset `st` — THE single source of the
    JCUDF word layout.  Consumed by build_plan (assembly: piece arrays
    zip with these coordinates) and by the Pallas from-rows extraction
    plan (row_assembly_pallas.build_extract_plan), so the two
    directions cannot drift."""
    w = st // 4
    size = _col_byte_size(dt)
    if dt.kind == Kind.DECIMAL128:
        return [(w + k, 0, 32) for k in range(4)]
    if size == 8:
        return [(w, 0, 32), (w + 1, 0, 32)]
    if size == 4:
        return [(w, 0, 32)]
    return [(w, (st % 4) * 8, size * 8)]


def build_plan(cols: Sequence[Column], starts: Sequence[int],
               validity_offset: int, n_words: int):
    """(inputs, plan): one (rows,) array per word contribution in its
    native width (u8/u16/u32; 8-byte columns split into u32 lo/hi —
    (rows, 2) u32 bitcasts are not tile-safe on this backend, see
    docs/tpu_design.md §2), and the (word_index, left_shift_bits) each
    lands at.  Word coordinates come from field_word_slots (the shared
    layout source); this function supplies the matching piece arrays.
    Consumed by the Pallas tile kernel (ops/row_assembly_pallas.py)."""
    inputs = []
    plan = []

    def add(arrs, slots):
        assert len(arrs) == len(slots)
        for arr, (word, shift, _nbits) in zip(arrs, slots):
            inputs.append(arr)
            plan.append((word, shift))

    for c, st in zip(cols, starts):
        kind = c.dtype.kind
        d = c.data
        slots = field_word_slots(c.dtype, st)
        if kind == Kind.FLOAT32:
            arrs = [lax.bitcast_convert_type(d, _U32)]
        elif kind == Kind.DECIMAL128:
            u = lax.bitcast_convert_type(d, _U32)
            arrs = [u[:, k] for k in range(4)]
        elif _col_byte_size(c.dtype) == 8:
            u = (d if d.dtype == jnp.uint64
                 else d.astype(jnp.int64).astype(_U64))
            arrs = [(u & _U64(0xFFFFFFFF)).astype(_U32),
                    (u >> _U64(32)).astype(_U32)]
        elif _col_byte_size(c.dtype) == 4:
            arrs = [lax.bitcast_convert_type(d.astype(_I32), _U32)]
        else:
            size = _col_byte_size(c.dtype)
            native = jnp.uint8 if size == 1 else jnp.uint16
            arrs = [d if d.dtype == native
                    else lax.bitcast_convert_type(
                        d.astype(jnp.int16 if size == 2 else jnp.int8),
                        native)]
        add(arrs, slots)

    # validity: packed once vectorized, sliced per byte
    packed = _validity_bytes(cols)
    for b in range((len(cols) + 7) // 8):
        off = validity_offset + b
        inputs.append(packed[:, b])
        plan.append((off // 4, (off % 4) * 8))

    assert all(w < n_words for w, _ in plan)
    return inputs, plan


# -------------------------------------------------------------- to-rows


def _is_traced(cols: Sequence[Column]) -> bool:
    return any(isinstance(c.data, jax.core.Tracer) for c in cols
               if c.data is not None)


def _to_rows_fixed_cached(cols, schema, starts, validity_offset,
                          row_size, rows) -> jnp.ndarray:
    """Fixed-width to-rows through the process compile cache: operands
    pad to the power-of-two row bucket, the width-grouped kernel
    compiles once per (schema digest, bucket) with the padded operands
    donated (TPU), and the padded tail rows are sliced off."""
    from spark_rapids_tpu.perf import jit_cache as _jc

    nullable = tuple(c.validity is not None for c in cols)
    digest = _jc.schema_digest(schema, nullable,
                               extra=f"to_rows:{row_size}")
    bucket = _jc.bucket_rows(rows)
    datas = tuple(_jc.pad_axis0(c.data, bucket) for c in cols)
    valids = tuple(None if c.validity is None
                   else _jc.pad_axis0(c.validity, bucket) for c in cols)
    schema_t = tuple(schema)
    starts_t = tuple(starts)

    def kernel(datas, valids):
        kcols = [Column(dt, bucket, data=d, validity=v)
                 for dt, d, v in zip(schema_t, datas, valids)]
        return _assemble_fixed_words(kcols, starts_t, validity_offset,
                                     row_size)

    words = _jc.CACHE.cached_call(
        "row_conversion.to_rows", digest, kernel, (datas, valids),
        bucket=bucket, donate_argnums=(0,))
    return words[: rows * (row_size // 4)]


def convert_to_rows(table: Table) -> Column:
    """Table -> LIST<INT8> column of JCUDF rows (RowConversion.convertToRows,
    RowConversionJni.cpp).  Fixed-width and string columns."""
    from spark_rapids_tpu.perf import jit_cache as _jc

    cols = table.columns
    if not cols:
        raise ValueError("cannot convert empty table")
    rows = table.num_rows
    schema = [c.dtype for c in cols]
    starts, validity_offset, fixed_size = compute_layout(schema)

    str_cols = [c for c in cols if c.dtype.is_string]
    if not str_cols:
        row_size = _round_up(fixed_size, JCUDF_ROW_ALIGNMENT)
        if os.environ.get("SPARK_RAPIDS_TPU_PALLAS_ROWCONV") == "1":
            # single-pass Pallas tile kernel (opt-in until profiled on
            # real hardware); interpret mode on the CPU backend.  The
            # wrapper consults the compile cache itself.
            from spark_rapids_tpu.ops.row_assembly_pallas import \
                assemble_fixed_words_pallas
            data = assemble_fixed_words_pallas(
                cols, starts, validity_offset, row_size,
                interpret=jax.default_backend() == "cpu")
        elif _jc.cache_enabled() and rows > 0 and not _is_traced(cols):
            data = _to_rows_fixed_cached(cols, schema, starts,
                                         validity_offset, row_size, rows)
        else:
            data = _assemble_fixed_words(cols, starts, validity_offset,
                                         row_size)
        offsets = jnp.arange(rows + 1, dtype=_I32) * _I32(row_size)
        return Column.make_list_from_parts(offsets, data,
                                           nbytes=rows * row_size)

    # variable-width path
    str_lens = [c.string_lengths() for c in str_cols]
    var_total = sum(str_lens)
    row_sizes = ((jnp.full((rows,), fixed_size, _I32) + var_total
                  + _I32(JCUDF_ROW_ALIGNMENT - 1))
                 // JCUDF_ROW_ALIGNMENT * JCUDF_ROW_ALIGNMENT)
    offsets = jnp.concatenate([jnp.zeros((1,), _I32),
                               jnp.cumsum(row_sizes).astype(_I32)])
    # per-row (offset-in-row, length) pairs for each string column
    var_starts = []
    off = jnp.full((rows,), fixed_size, _I32)
    for lens in str_lens:
        var_starts.append(off)
        off = off + lens
    max_row = int(np.asarray(row_sizes).max()) if rows else 0
    mat = _grouped_fixed_bytes(cols, starts, validity_offset, max_row,
                               var_pairs=list(zip(var_starts, str_lens)))
    # paste string payloads into the padded matrix
    use_pallas_paste = (
        os.environ.get("SPARK_RAPIDS_TPU_PALLAS_ROWCONV") == "1"
        and rows > 0)
    for c, vstart, lens in zip(str_cols, var_starts, str_lens):
        pad = max(1, c.max_string_length())
        chars, _ = c.to_padded_chars(pad_to=pad)
        if use_pallas_paste:
            # VMEM tile gather (row_assembly_pallas.py) instead of a
            # whole-matrix HBM scatter; interpret mode on CPU
            from spark_rapids_tpu.ops.row_assembly_pallas import \
                paste_strings_pallas
            mat = paste_strings_pallas(
                mat, chars, vstart, lens,
                interpret=jax.default_backend() == "cpu")
            continue
        # scatter chars into mat[r, vstart[r]+j]
        j = jnp.arange(pad, dtype=_I32)
        dest = vstart[:, None] + j[None, :]
        m = j[None, :] < lens[:, None]
        mat = _masked_row_scatter(mat, dest, chars, m)
    flat = _compact(mat, offsets, row_sizes)
    return Column.make_list_from_parts(offsets, flat)


def _masked_row_scatter(mat, dest, src, mask):
    """mat[r, dest[r,j]] = src[r,j] where mask — via one-hot-free gather:
    build an index map from output position back to source position."""
    rows, width = mat.shape
    pad = dest.shape[1]
    # scatter via jnp at: vectorized scatter is fine on TPU through XLA
    r = jnp.broadcast_to(jnp.arange(rows, dtype=_I32)[:, None], dest.shape)
    dest_c = jnp.where(mask, dest, width)  # out-of-range drops
    return mat.at[r.reshape(-1), dest_c.reshape(-1)].set(
        src.reshape(-1), mode="drop")


def _compact(mat: jnp.ndarray, offsets: jnp.ndarray,
             row_sizes: jnp.ndarray) -> jnp.ndarray:
    """(rows, maxP) padded matrix -> flat uint8 using per-row sizes."""
    total = int(np.asarray(offsets)[-1])
    i = jnp.arange(total, dtype=_I32)
    r = jnp.searchsorted(offsets, i, side="right").astype(_I32) - 1
    p = i - offsets[r]
    return mat[r, p]


# ------------------------------------------------------------ from-rows


def _bytes_to_values(raw: jnp.ndarray, dt: DType) -> jnp.ndarray:
    """(rows, size) uint8 LE bytes -> (rows,) natural-dtype values (or
    (rows,4) int32 limbs for decimal128)."""
    kind = dt.kind
    if kind == Kind.DECIMAL128:
        b = raw.astype(_U32)
        limbs = (b[:, 0::4] | (b[:, 1::4] << _U32(8))
                 | (b[:, 2::4] << _U32(16)) | (b[:, 3::4] << _U32(24)))
        return limbs.astype(jnp.int32)
    n = raw.shape[1]
    if n == 8:
        u = jnp.zeros(raw.shape[:1], _U64)
        for k in range(8):
            u = u | (raw[:, k].astype(_U64) << _U64(8 * k))
        if kind == Kind.FLOAT64 or dt.np_dtype == np.dtype(np.uint64):
            return u  # raw-bits / unsigned representation
        return u.astype(jnp.int64)
    u = jnp.zeros(raw.shape[:1], _U32)
    for k in range(n):
        u = u | (raw[:, k].astype(_U32) << _U32(8 * k))
    if kind == Kind.FLOAT32:
        return lax.bitcast_convert_type(u, jnp.float32)
    if n < 4 and dt.np_dtype.kind == "i":  # sign-extend from the top
        u = u << _U32(8 * (4 - n))
        s = u.astype(jnp.int32) >> _I32(8 * (4 - n))
        return s.astype(dt.np_dtype)
    return u.astype(jnp.int32) if dt.np_dtype == np.dtype(np.int32) else \
        u.astype(dt.np_dtype)


def _gather_fixed_region(data, offs, fixed_size: int, nbytes_total: int):
    """ONE clipped gather of every row's fixed+validity section —
    (rows, fixed_size) uint8.  The retired path gathered per column
    (O(columns) gathers, each with its own (rows, size) index matrix);
    all column decodes now slice this single region."""
    from spark_rapids_tpu.columns import bytesview

    idx = offs[:-1][:, None] + jnp.arange(fixed_size, dtype=_I32)[None, :]
    idx = jnp.clip(idx, 0, max(nbytes_total - 1, 0))
    return bytesview.byte_gather(data, idx)


def _decode_validity(region: jnp.ndarray, schema, validity_offset: int):
    """(rows, ncols) uint8 validity bits in one vectorized op."""
    n = len(schema)
    bidx = np.array([validity_offset + ci // 8 for ci in range(n)],
                    np.int32)
    shifts = np.array([ci % 8 for ci in range(n)], np.uint8)
    return ((region[:, bidx] >> jnp.asarray(shifts)[None, :])
            & _U8(1)).astype(jnp.uint8)


# uniformity verdicts memoized per offsets array: the host readback +
# O(rows) scan below would otherwise run on EVERY eager from-rows call
# (a synchronous ~70ms tunnel RTT on the TPU relay).  Keyed by id()
# with a weakref guard — the finalizer drops the entry when the array
# dies, so a recycled id can never resurrect a stale verdict.
_UNIFORM_VERDICTS: dict = {}


def _uniform_row_offsets(offs, rows: int, row_size: int,
                         nbytes_total: int) -> bool:
    """True when the list column holds exactly rows x row_size uniform
    rows (what fixed-width convert_to_rows produces) — the shape the
    bucketed from-rows kernel requires."""
    import weakref

    if int(nbytes_total) != rows * row_size:
        return False
    key = id(offs)
    ent = _UNIFORM_VERDICTS.get(key)
    if ent is not None:
        ref, rs, verdict = ent
        if ref() is offs and rs == row_size:
            return verdict
    o = np.asarray(offs)
    verdict = bool(o[0] == 0 and np.all(np.diff(o) == row_size))
    try:
        ref = weakref.ref(offs,
                          lambda _r: _UNIFORM_VERDICTS.pop(key, None))
    except TypeError:
        return verdict
    if len(_UNIFORM_VERDICTS) > 512:
        _UNIFORM_VERDICTS.clear()
    _UNIFORM_VERDICTS[key] = (ref, row_size, verdict)
    return verdict


def _from_rows_fixed_cached(list_col: Column, schema, starts,
                            validity_offset: int, fixed_size: int,
                            row_size: int) -> Table:
    """Fixed-width from-rows through the compile cache: the flat row
    buffer pads to bucket * row_size, offsets pad edge-replicated, and
    the single-gather decode kernel compiles once per (schema digest,
    bucket, buffer packing)."""
    from spark_rapids_tpu.perf import jit_cache as _jc

    rows = list_col.length
    child = list_col.children[0]
    data, offs = child.data, list_col.offsets
    packed = data.dtype == _U32
    bucket = _jc.bucket_rows(rows)
    unit = row_size // 4 if packed else row_size
    data_p = _jc.pad_axis0(data, bucket * unit)
    offs_p = (offs if bucket == rows
              else jnp.pad(offs, (0, bucket - rows), mode="edge"))
    digest = _jc.schema_digest(
        schema, extra=f"from_rows:{row_size}:{'u32' if packed else 'u8'}")
    schema_t = tuple(schema)
    starts_t = tuple(starts)
    total_bytes = bucket * row_size

    def kernel(data_p, offs_p):
        region = _gather_fixed_region(data_p, offs_p, fixed_size,
                                      total_bytes)
        valid_all = _decode_validity(region, schema_t, validity_offset)
        vals = tuple(
            _bytes_to_values(region[:, st:st + _col_byte_size(dt)], dt)
            for dt, st in zip(schema_t, starts_t))
        return vals, valid_all

    vals, valid_all = _jc.CACHE.cached_call(
        "row_conversion.from_rows", digest, kernel, (data_p, offs_p),
        bucket=bucket, donate_argnums=(0,))
    out_cols = [Column(dt, rows, data=v[:rows],
                       validity=valid_all[:rows, ci])
                for ci, (dt, v) in enumerate(zip(schema, vals))]
    return Table(out_cols)


def convert_from_rows(list_col: Column, schema: Sequence[DType]) -> Table:
    """LIST<INT8> of JCUDF rows -> Table (RowConversion.convertFromRows)."""
    from spark_rapids_tpu.columns import bytesview
    from spark_rapids_tpu.perf import jit_cache as _jc

    rows = list_col.length
    starts, validity_offset, fixed_size = compute_layout(schema)
    has_strings = any(dt.is_string for dt in schema)
    row_size = _round_up(fixed_size, JCUDF_ROW_ALIGNMENT)
    child = list_col.children[0]
    data = child.data  # flat byte buffer (u8 or packed u32 words)
    offs = list_col.offsets
    nbytes_total = child.length
    traced = isinstance(data, jax.core.Tracer) or \
        isinstance(offs, jax.core.Tracer)

    if (os.environ.get("SPARK_RAPIDS_TPU_PALLAS_ROWCONV") == "1"
            and rows > 0
            and not has_strings
            and data.dtype == jnp.uint32):
        # single-pass tile disassembly (one HBM read of the row matrix
        # feeds all column extractions); interpret mode on CPU.  The
        # kernel needs uniform contiguous rows — any other buffer
        # shape falls through to the gather path below.
        if int(data.size) == rows * (row_size // 4):
            from spark_rapids_tpu.ops.row_assembly_pallas import \
                convert_from_rows_pallas
            return convert_from_rows_pallas(
                list_col, schema,
                interpret=jax.default_backend() == "cpu")

    if (_jc.cache_enabled() and rows > 0 and not has_strings
            and not traced
            and _uniform_row_offsets(offs, rows, row_size, nbytes_total)):
        return _from_rows_fixed_cached(list_col, schema, starts,
                                       validity_offset, fixed_size,
                                       row_size)

    # eager width-grouped decode: one region gather + static slices
    region = _gather_fixed_region(data, offs, fixed_size, nbytes_total)
    valid_all = _decode_validity(region, schema, validity_offset)
    out_cols: List[Column] = []
    for ci, dt in enumerate(schema):
        st = starts[ci]
        valid = valid_all[:, ci]
        if dt.is_string:
            in_row_off = _bytes_to_values(region[:, st:st + 4],
                                          dtypes.INT32)
            lens = _bytes_to_values(region[:, st + 4:st + 8],
                                    dtypes.INT32)
            str_offsets = jnp.concatenate(
                [jnp.zeros((1,), _I32), jnp.cumsum(lens).astype(_I32)])
            pad = int(np.asarray(lens).max()) if rows else 0
            pad = max(pad, 1)
            j = jnp.arange(pad, dtype=_I32)
            src = offs[:-1][:, None] + in_row_off[:, None] + j[None, :]
            src = jnp.clip(src, 0, max(nbytes_total - 1, 0))
            chars2d = jnp.where(j[None, :] < lens[:, None],
                                bytesview.byte_gather(data, src), _U8(0))
            flat = _compact(chars2d, str_offsets, lens)
            out_cols.append(Column(dtypes.STRING, rows, data=flat,
                                   validity=valid, offsets=str_offsets))
        else:
            vals = _bytes_to_values(
                region[:, st:st + _col_byte_size(dt)], dt)
            out_cols.append(Column(dt, rows, data=vals, validity=valid))
    return Table(out_cols)
