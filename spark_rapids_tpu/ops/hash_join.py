"""Hash-keyed inner join engines (ISSUE 9 tentpole).

The general join used to run a double-argsort rank core: every key
column of BOTH sides was jointly ranked (np.unique / lax.sort over the
concatenated sides) before the run search could even start — O((nl+nr)
log(nl+nr)) comparator work for what is an equality-only problem.
This module replaces that core with the classic hash-join shape:

  * keys reduce to the existing device word encoding
    (ops/joins._device_equality_cols: fixed-width ranks, packed string
    words + length, decimal128 limb words, sentinel-free null masks);
  * one xxhash64 pass over the word columns assigns a 64-bit group id
    per row (ops/hash.py mixing primitives — the short-input xxhash64
    schedule, extended past 32 bytes by chaining 8-byte updates), AOT
    compiled through perf/jit_cache with power-of-two row buckets and
    operand donation;
  * only the RIGHT side is organized (bucket table / sort) — the probe
    is a gather, so the big side never pays comparator work;
  * candidate pairs are verified by exact word comparison — hash
    quality affects SPEED only, never correctness.

Three engines share that skeleton:

``host`` (numpy)
    A direct-address bucket table: ``slot = hash & (m-1)`` with m a
    power of two at load factor <= 1/4, right rows counting-sorted by
    slot, probes resolved with O(1) gathers — no binary search (the
    cache-hostile searchsorted is what made the old host path crawl at
    0.9M rows/s).  When the single key column is an integer rank whose
    value span fits a small table, the identity function IS a perfect
    hash: ``slot = key - min`` with zero collisions and no verify pass
    (``direct`` sub-path).

``device`` (XLA)
    The same hash ids drive ops/device_join.inner_join_device (sort +
    searchsorted run expansion) inside ONE compiled program per
    (schema digest, row buckets, capacity): fixed-capacity pair slots
    with a true count, equality verification fused into the program,
    and the pair capacity doubling under the SAME
    exchange.with_capacity_retry discipline the shuffle uses.

Pair order is identical across engines and to the host rank oracle:
grouped by left row (ascending), right indices ascending within each
group — the differential tests in tests/test_device_join_paths.py
pin this byte-for-byte.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.table import Table

_I32 = jnp.int32
_I64 = jnp.int64
_U64 = jnp.uint64

JOIN_HASH_SEED = 42

# perfect-hash (direct-address) table budget: the key span must fit a
# table no larger than this many slots AND no larger than a small
# multiple of the data (a sparse 2^40 keyspace must not allocate 2^40
# counters)
DIRECT_MAX_SLOTS = 1 << 23
DIRECT_SPAN_FACTOR = 4


# --------------------------------------------------------------- key prep

def join_key_words(left: Table, right: Table, compare_nulls: str):
    """Per-side device word columns + validity for the join keys.

    Mirrors ops/joins._device_ids exactly: nullable key columns (on
    EITHER side — pytree symmetry) contribute a mask word followed by
    their zeroed value words (sentinel-free null encoding), and
    NULL_UNEQUAL rows with any null key become invalid.  Returns
    (lwords, rwords, lvalid, rvalid, digest_extra) with words as int64
    jnp arrays; raises ValueError when a key kind has no device word
    encoding (caller falls back to the host rank path)."""
    from spark_rapids_tpu.ops import joins as J

    nl, nr = left.num_rows, right.num_rows
    lwords: List[jnp.ndarray] = []
    rwords: List[jnp.ndarray] = []
    vl = jnp.ones(nl, jnp.bool_)
    vr = jnp.ones(nr, jnp.bool_)
    shape = []
    for lc, rc in zip(left.columns, right.columns):
        if lc.dtype.kind != rc.dtype.kind:
            raise ValueError("join key dtypes must match")
        from spark_rapids_tpu.columns.dtypes import Kind
        pad = (max(lc.max_string_length(), rc.max_string_length())
               if lc.dtype.kind == Kind.STRING else 0)
        lvals = J._device_equality_cols(lc, pad)
        rvals = J._device_equality_cols(rc, pad)
        if lvals is None or rvals is None:
            raise ValueError(f"no device key path for {lc.dtype}")
        nullable = lc.validity is not None or rc.validity is not None
        if nullable or compare_nulls == J.NULL_UNEQUAL:
            lm, rm = J._col_mask(lc), J._col_mask(rc)
        if nullable:
            lwords.append(lm.astype(jnp.int64))
            rwords.append(rm.astype(jnp.int64))
            lwords.extend(jnp.where(lm, v, jnp.int64(0)) for v in lvals)
            rwords.extend(jnp.where(rm, v, jnp.int64(0)) for v in rvals)
        else:
            lwords.extend(lvals)
            rwords.extend(rvals)
        if compare_nulls == J.NULL_UNEQUAL:
            vl = vl & lm
            vr = vr & rm
        shape.append(f"{lc.dtype.kind}:{len(lvals)}:{int(nullable)}")
    extra = f"{compare_nulls}|{';'.join(shape)}"
    return lwords, rwords, vl, vr, extra


# ------------------------------------------------------------- key hashes

def _hash_words_program(*words):
    """xxhash64 of the concatenated 8-byte words, one lane per row —
    the short-input schedule from ops/hash.py (seed + P5 + length, an
    _xx_update8 per word, avalanche finalize), chained past the 32-byte
    stripe threshold.  Internal group ids only: NOT the Spark row-hash
    contract (ops/hash.xxhash64 keeps that)."""
    from spark_rapids_tpu.ops.hash import (_XXP5, _xx_finalize,
                                           _xx_update8)
    rows = words[0].shape[0]
    h = jnp.full((rows,), np.uint64(JOIN_HASH_SEED), _U64)
    h = h + _XXP5 + _U64(8 * len(words))
    for w in words:
        h = _xx_update8(h, lax.bitcast_convert_type(w, _U64))
    return _xx_finalize(h).astype(_I64)


def key_hashes(words: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """(rows,) int64 xxhash64 group ids for a word-column list, AOT
    compiled through the process jit cache under power-of-two row
    buckets (zero recompiles on same-bucket batches) with operand
    donation on backends that honor it."""
    from spark_rapids_tpu.perf.jit_cache import (CACHE, bucket_rows,
                                                 pad_axis0)
    rows = int(words[0].shape[0])
    if rows == 0:
        return jnp.zeros(0, _I64)
    if not CACHE.enabled():
        return jax.jit(_hash_words_program)(*words)[:rows]
    bucket = bucket_rows(rows)
    padded = tuple(pad_axis0(w.astype(_I64), bucket) for w in words)
    out = CACHE.cached_call(
        "join.keyhash", f"w{len(words)}", _hash_words_program, padded,
        bucket=bucket,
        donate_argnums=tuple(range(len(padded))))
    return out[:rows]


# ------------------------------------------------------------ host engine

def _expand_runs(starts: np.ndarray, counts: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """(left_out, positions) for per-left-row candidate runs: left row
    i contributes counts[i] consecutive positions starts[i]..  One
    np.repeat of the fused (start - exclusive_offset) adjustment plus
    an arange keeps the temporaries to two total-sized arrays."""
    nl = len(counts)
    total = int(counts.sum())
    idx_dtype = np.int32 if total < 2**31 and nl < 2**31 else np.int64
    left_out = np.repeat(np.arange(nl, dtype=idx_dtype), counts)
    ends = np.cumsum(counts, dtype=np.int64)
    adj = starts.astype(np.int64) - (ends - counts)
    pos = np.repeat(adj, counts) + np.arange(total, dtype=np.int64)
    return left_out, pos


def _host_join_from_slots(lslot, rslot, m, lcount_mask, verify,
                          rcounts=None) -> Tuple[np.ndarray, np.ndarray]:
    """Shared bucket-table core: build over right slots, probe with
    left slots, expand runs, then ``verify(left_out, cand)`` filters
    candidate pairs to true matches (None skips the pass — perfect
    hash).  ``rcounts`` is the caller's already-computed
    ``np.bincount(rslot, minlength=m)`` when it has one.  Returns
    (left_out, right_out_in_filtered_space)."""
    nr = len(rslot)
    order_r = np.argsort(rslot, kind="stable")
    if order_r.dtype != np.int32 and nr < 2**31:
        order_r = order_r.astype(np.int32)
    bcount = (np.bincount(rslot, minlength=m) if rcounts is None
              else rcounts)
    bstart = np.zeros(m + 1, np.int64)
    np.cumsum(bcount, out=bstart[1:])
    if nr < 2**31:
        bcount = bcount.astype(np.int32)
        bstart32 = bstart[:-1].astype(np.int32)
    else:  # pragma: no cover - >2^31-row build side
        bstart32 = bstart[:-1]
    starts = bstart32[lslot]
    counts = bcount[lslot]
    if lcount_mask is not None:
        counts = np.where(lcount_mask, counts, 0)
    left_out, pos = _expand_runs(starts, counts)
    cand = order_r[pos]
    if verify is not None:
        eq = verify(left_out, cand)
        if not eq.all():
            left_out = left_out[eq]
            cand = cand[eq]
    return left_out, cand


def host_hash_join(lwords, rwords, lvalid, rvalid
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """numpy bucket-table hash join over host word columns.

    lwords/rwords: list of (rows,) int64 numpy arrays (the device word
    encoding pulled to host — zero-copy on the CPU backend).
    lvalid/rvalid: bool masks (NULL_UNEQUAL exclusion).  Returns int32
    (left_indices, right_indices) in oracle order."""
    nl = len(lwords[0]) if lwords else 0
    nr = len(rwords[0]) if rwords else 0
    empty = (np.zeros(0, np.int32), np.zeros(0, np.int32))
    if nl == 0 or nr == 0 or not lwords:
        return empty

    ridx = None
    if not rvalid.all():
        ridx = np.nonzero(rvalid)[0].astype(np.int32)
        rwords = [w[ridx] for w in rwords]
        nr = len(ridx)
        if nr == 0:
            return empty
    lmask = None if lvalid.all() else lvalid

    # ---- perfect-hash fast path: one integer word, small value span
    if len(lwords) == 1:
        lo = int(rwords[0].min())
        hi = int(rwords[0].max())
        span = hi - lo + 1
        if span <= min(DIRECT_MAX_SLOTS,
                       max(1 << 16, DIRECT_SPAN_FACTOR * (nl + nr))):
            lk = lwords[0]
            rk0 = rwords[0] - lo if lo else rwords[0]
            bcount = np.bincount(rk0, minlength=span)
            if int(bcount.max()) <= 1:
                # unique build keys (the PK-FK join): the probe is ONE
                # gather through a dense lookup — no run expansion, no
                # sort, the fewest full-size passes this box's memory
                # bus allows
                lookup = np.full(span, -1, np.int32)
                lookup[rk0] = np.arange(nr, dtype=np.int32)
                if int(lk.min()) >= lo and int(lk.max()) <= hi:
                    cand = lookup[lk - lo if lo else lk]
                else:
                    inr = (lk >= lo) & (lk <= hi)
                    cand = lookup[np.where(inr, lk - lo, 0)]
                    cand = np.where(inr, cand, np.int32(-1))
                ok = cand >= 0
                if lmask is not None:
                    ok &= lmask
                if ok.all():
                    left_out = np.arange(nl, dtype=np.int32)
                    right_out = cand
                else:
                    left_out = np.nonzero(ok)[0].astype(np.int32,
                                                        copy=False)
                    right_out = cand[left_out]
                if ridx is not None:
                    right_out = ridx[right_out]
                return left_out, right_out
            inr = (lk >= lo) & (lk <= hi)
            if lmask is not None:
                inr &= lmask
            lslot = np.where(inr, lk - lo, 0)
            left_out, cand = _host_join_from_slots(
                lslot, rk0, span, inr, None, rcounts=bcount)
            right_out = cand if ridx is None else ridx[cand]
            return (left_out.astype(np.int32, copy=False),
                    right_out.astype(np.int32, copy=False))

    # ---- general path: xxhash64 bucket table + exact verify
    lh = np.asarray(key_hashes([jnp.asarray(w) for w in lwords])) \
        .view(np.uint64)
    rh = np.asarray(key_hashes([jnp.asarray(w) for w in rwords])) \
        .view(np.uint64)
    m = 1 << min(max(4, int(nr - 1).bit_length() + 2), 26)
    mask = np.uint64(m - 1)
    lslot = (lh & mask).astype(np.int64)
    rslot = (rh & mask).astype(np.int64)

    def verify(left_out, cand):
        eq = np.ones(len(left_out), bool)
        for lw, rw in zip(lwords, rwords):
            eq &= lw[left_out] == rw[cand]
        return eq

    left_out, cand = _host_join_from_slots(lslot, rslot, m, lmask,
                                           verify)
    right_out = cand if ridx is None else ridx[cand]
    return (left_out.astype(np.int32, copy=False),
            right_out.astype(np.int32, copy=False))


# ---------------------------------------------------------- device engine

@functools.lru_cache(maxsize=64)
def _device_step_factory(k: int, nlb: int, nrb: int, digest: str):
    """Capacity-parameterized factory for the fused hash-join program,
    memoized so repeated same-shape joins present the SAME factory
    object to with_capacity_retry (one jit-cache owner, steady-state
    cache hits)."""
    from spark_rapids_tpu.perf.jit_cache import CACHE, pad_axis0

    def make_step(capacity: int):
        def program(lh, rh, lv, rv, *words):
            from spark_rapids_tpu.ops.device_join import \
                inner_join_device
            lws, rws = words[:k], words[k:]
            pairs = inner_join_device(lh, rh, capacity, lv, rv)
            eq = pairs.valid
            for i in range(k):
                eq = eq & (lws[i][pairs.left_indices]
                           == rws[i][pairs.right_indices])
            overflow = pairs.total > capacity
            return (pairs.left_indices, pairs.right_indices, eq,
                    pairs.total, overflow)

        program_jit = jax.jit(program)   # cache-disabled fallback

        def run(lh, rh, lv, rv, lwords, rwords):
            # pad fresh per attempt: donated buffers must be throwaway
            # (a doubled-capacity retry re-reads the same logical args)
            args = (pad_axis0(lh, nlb), pad_axis0(rh, nrb),
                    pad_axis0(lv, nlb), pad_axis0(rv, nrb),
                    *[pad_axis0(w, nlb) for w in lwords],
                    *[pad_axis0(w, nrb) for w in rwords])
            if not CACHE.enabled():
                return program_jit(*args)
            return CACHE.cached_call(
                "join.hash_pairs",
                f"{digest}|k{k}|r{nrb}|c{capacity}", program, args,
                bucket=nlb,
                donate_argnums=tuple(range(len(args))))

        return run

    return make_step


# pair-capacity memo per (digest, bucket) shape: a steady workload
# whose joins fan out (dup keys, null-equal clusters) must not re-learn
# the budget by doubling from scratch on every batch
_LEARNED_CAPACITY: dict = {}


def device_hash_join(lwords, rwords, lvalid, rvalid, digest_extra: str,
                     initial_capacity: Optional[int] = None
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Device-resident hash join: xxhash64 ids + fixed-capacity pair
    expansion (ops/device_join) + fused equality verify, AOT through
    the jit cache, capacity learned by the exchange retry driver.
    Returns int32 (left_indices, right_indices) in oracle order."""
    from spark_rapids_tpu.parallel.exchange import with_capacity_retry
    from spark_rapids_tpu.perf.jit_cache import bucket_rows

    nl = int(lwords[0].shape[0]) if lwords else 0
    nr = int(rwords[0].shape[0]) if rwords else 0
    if nl == 0 or nr == 0 or not lwords:
        return (jnp.zeros(0, _I32), jnp.zeros(0, _I32))
    lh = key_hashes(lwords)
    rh = key_hashes(rwords)
    nlb, nrb = bucket_rows(nl), bucket_rows(nr)
    k = len(lwords)
    cap_key = (digest_extra, k, nlb, nrb)
    cap0 = (int(initial_capacity) if initial_capacity
            else max(1 << max(4, nl.bit_length()),
                     _LEARNED_CAPACITY.get(cap_key, 0)))
    make_step = _device_step_factory(k, nlb, nrb, digest_extra)
    run = with_capacity_retry(make_step, cap0, overflow_index=-1,
                              max_doublings=20)
    (li, ri, eq, total, _of), cap_used = run(
        lh, rh, lvalid.astype(jnp.bool_), rvalid.astype(jnp.bool_),
        [w.astype(_I64) for w in lwords],
        [w.astype(_I64) for w in rwords])
    if len(_LEARNED_CAPACITY) > 256:     # bounded memo
        _LEARNED_CAPACITY.clear()
    _LEARNED_CAPACITY[cap_key] = int(cap_used)
    # eager compaction: collisions are ~never, so eq usually equals the
    # valid prefix and the nonzero is one pass over a bitmask
    eqn = np.asarray(eq)
    tot = int(total)
    if tot and bool(eqn[:tot].all()):
        return li[:tot], ri[:tot]
    keep = np.nonzero(eqn)[0]
    return (jnp.asarray(np.asarray(li)[keep]),
            jnp.asarray(np.asarray(ri)[keep]))
