"""from_json family (reference from_json_to_raw_map.cu,
from_json_to_structs.cu, json_utils.hpp helpers; JSONUtils.java:159-188):
Spark from_json to MAP<STRING,STRING> and to typed structs, plus the
remove_quotes / concat_json helpers, all over the tolerant parser in
ops/json_path.py."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType, Kind
from spark_rapids_tpu.ops.json_path import _Invalid, _Parser, _render_json
from spark_rapids_tpu.ops import cast_string


def _parse_rows(col: Column, allow_leading_zeros: bool = False):
    for v in col.to_pylist():
        if v is None:
            yield None
            continue
        try:
            yield _Parser(v, allow_leading_zeros).parse()
        except _Invalid:
            yield None


def _value_as_raw_string(v) -> str:
    """Raw-map value rendering: string scalars unescaped, everything
    else as JSON text with number tokens VERBATIM — the reference's
    from_json_to_raw_map copies raw token substrings, no Double
    normalization (from_json_to_raw_map.cu)."""
    if v[0] == "str":
        return v[1]
    return _render_json(v, normalize_numbers=False)


def from_json_to_raw_map(col: Column,
                         allow_leading_zeros: bool = False) -> Column:
    """JSON object rows -> MAP<STRING,STRING>
    (JSONUtils.extractRawMapFromJsonString:159).  Non-object / invalid
    rows are null; duplicate keys keep the last value.

    Engine choice is a measurement, not a backend gate (ISSUE 9): the
    structural-index tokenizer (ops/json_tokenizer), the device
    multi-capture scan (ops/raw_map_device.py) and this host
    tree-builder are byte-identical candidates; the calibrator picks
    per (doc shape, backend).  The tree-builder stays the oracle and
    handles every engine's fallback rows."""
    import os

    import jax

    from spark_rapids_tpu import observability as _obs
    from spark_rapids_tpu.ops import json_tokenizer as JT
    from spark_rapids_tpu.ops import raw_map_device as RM
    from spark_rapids_tpu.ops.json_path import route_json_engine
    min_rows = int(os.environ.get(
        "SPARK_RAPIDS_TPU_RAW_MAP_DEVICE_MIN", "256"))
    force = os.environ.get(
        "SPARK_RAPIDS_TPU_FORCE_DEVICE_RAW_MAP") == "1"
    on_accel = jax.default_backend() != "cpu"
    if force:
        engine = "device_scan"
    elif col.length < min_rows:
        engine = "host"
    else:
        engines = {
            "host": lambda c: _raw_map_host(c, allow_leading_zeros),
            "device_scan": lambda c:
                RM.from_json_to_raw_map_device(c, allow_leading_zeros),
            "tokenizer": lambda c:
                JT.from_json_to_raw_map_tokenized(c,
                                                  allow_leading_zeros),
        }
        # static default below the calibration floor = the pre-ISSUE-9
        # routing (accel scan / host); above it the measurement decides
        engine = route_json_engine(
            "json.raw_map", col, engines,
            "device_scan" if on_accel else "host")
    # record the path AFTER fallback resolution: a device scan that
    # declines the shape (returns None) really ran on the host, and the
    # counter is sold as routing evidence
    if engine == "tokenizer":
        _obs.record_kernel_path("from_json_raw_map", "tokenizer",
                                col.length)
        return JT.from_json_to_raw_map_tokenized(col,
                                                 allow_leading_zeros)
    if engine == "device_scan":
        out = RM.from_json_to_raw_map_device(col, allow_leading_zeros)
        if out is not None:
            _obs.record_kernel_path("from_json_raw_map", "device_scan",
                                    col.length)
            return out
    _obs.record_kernel_path("from_json_raw_map", "host", col.length)
    return _raw_map_host(col, allow_leading_zeros)


def _raw_map_host(col: Column,
                  allow_leading_zeros: bool = False) -> Column:
    """The host tree-builder — the oracle every raw-map engine falls
    back to per row."""
    assert col.dtype.is_string
    rows = col.length
    keys: List[str] = []
    vals: List[str] = []
    new_offs = np.zeros(rows + 1, np.int32)
    validity = np.zeros(rows, np.uint8)
    for i, tree in enumerate(_parse_rows(col, allow_leading_zeros)):
        if tree is None or tree[0] != "obj":
            new_offs[i + 1] = len(keys)
            continue
        validity[i] = 1
        seen = {}
        order = []
        for k, v in tree[1]:
            if k not in seen:
                order.append(k)
            seen[k] = _value_as_raw_string(v)
        for k in order:
            keys.append(k)
            vals.append(seen[k])
        new_offs[i + 1] = len(keys)
    st = Column.make_struct(len(keys), [Column.from_strings(keys),
                                        Column.from_strings(vals)])
    return Column(dtypes.LIST, rows,
                  validity=None if validity.all() else
                  jnp.asarray(validity),
                  offsets=jnp.asarray(new_offs), children=(st,))


def from_json_to_structs(col: Column,
                         fields: Sequence[Tuple[str, DType]]) -> Column:
    """JSON object rows -> STRUCT column with the requested fields
    (JSONUtils.fromJSONToStructs:188; schema as parallel vectors in the
    reference json_utils.hpp:10-23).  Missing/mistyped fields are null;
    invalid rows null the whole struct.

    A flat schema is just a one-level nested schema: delegate so the
    device routing gate and null/leniency rules live in exactly one
    place (from_json_to_structs_nested)."""
    return from_json_to_structs_nested(col, ("struct", list(fields)))


def convert_from_strings(col: Column, dtype: DType) -> Column:
    """String column -> typed column with Spark cast semantics
    (json_utils.hpp:67 convert_from_strings)."""
    if dtype.is_string:
        return col
    if dtype.kind == Kind.BOOL8:
        # vectorized 'true'/'false' compare over the padded matrix
        chars, lens = col.to_padded_chars(pad_to=max(
            5, int(col.max_string_length()) or 1))
        chars = np.asarray(chars)
        lens = np.asarray(lens)
        def _eq(word):
            w = np.frombuffer(word.encode(), np.uint8)
            return (lens == len(w)) & (
                chars[:, :len(w)] == w[None, :]).all(axis=1)
        is_t = _eq("true")
        is_f = _eq("false")
        valid = (is_t | is_f)
        if col.validity is not None:
            valid &= np.asarray(col.validity).astype(bool)
        return Column.from_numpy(
            is_t.astype(np.uint8),
            validity=None if valid.all() else valid.astype(np.uint8),
            dtype=dtype)
    if dtype.kind in (Kind.INT8, Kind.INT16, Kind.INT32, Kind.INT64):
        return cast_string.string_to_integer(col, dtype)
    if dtype.kind in (Kind.FLOAT32, Kind.FLOAT64):
        return cast_string.string_to_float(col, dtype)
    raise NotImplementedError(f"from_json field type {dtype.kind}")


def remove_quotes(col: Column, nullify_if_not_quoted: bool = False
                  ) -> Column:
    """Strip one pair of surrounding double quotes (json_utils.hpp:84)."""
    assert col.dtype.is_string
    out = []
    for v in col.to_pylist():
        if v is None:
            out.append(None)
        elif len(v) >= 2 and v[0] == '"' and v[-1] == '"':
            out.append(v[1:-1])
        else:
            out.append(None if nullify_if_not_quoted else v)
    return Column.from_strings(out)


def concat_json(col: Column) -> Tuple[bytes, str, Column]:
    """Join all rows into one JSON-lines buffer with a chosen delimiter
    (json_utils.hpp:110 concat_json): returns (buffer, delimiter,
    is_valid-and-non-empty BOOL8 column).  Null/empty/whitespace rows are
    replaced by empty entries."""
    assert col.dtype.is_string
    candidates = "\n\r\x01\x02\x03"
    vals = col.to_pylist()
    joined_src = "".join(v for v in vals if v)
    delim = next((c for c in candidates if c not in joined_src), None)
    if delim is None:
        raise ValueError("no usable delimiter byte found")
    parts = []
    valid = np.zeros(col.length, np.uint8)
    for i, v in enumerate(vals):
        if v is None or not v.strip():
            parts.append("")
        else:
            parts.append(v)
            valid[i] = 1
    buffer = (delim.join(parts) + delim).encode()
    return buffer, delim, Column(dtypes.BOOL8, col.length,
                                 data=jnp.asarray(valid))


# ----------------------------------------- nested from_json schemas

def _build_json_column(values, spec) -> Column:
    """Recursive column builder from parsed JSON value trees.

    spec: a leaf DType, ("struct", [(name, spec), ...]), or
    ("list", spec) — mirroring the reference's nested schema vectors
    (json_utils.hpp:10-23, JSONUtils.fromJSONToStructs).  Mistyped
    values null the row at that level (Spark from_json leniency)."""
    if isinstance(spec, DType):
        raw = [None if v is None or v == ("lit", "null")
               else _value_as_raw_string(v) for v in values]
        return convert_from_strings(Column.from_strings(raw), spec)
    tag, arg = spec
    n = len(values)
    if tag == "struct":
        validity = np.array(
            [v is not None and v[0] == "obj" for v in values], np.uint8)
        # one dict per row (duplicate keys: last wins), not per field
        dicts = [dict(v[1]) if v is not None and v[0] == "obj" else None
                 for v in values]
        children = []
        for name, child_spec in arg:
            sub = []
            for d in dicts:
                got = None if d is None else d.get(name)
                sub.append(None if got == ("lit", "null") else got)
            children.append(_build_json_column(sub, child_spec))
        return Column.make_struct(n, children,
                                  validity=None if validity.all()
                                  else validity)
    if tag == "list":
        validity = np.array(
            [v is not None and v[0] == "arr" for v in values], np.uint8)
        offs = np.zeros(n + 1, np.int32)
        flat = []
        for i, v in enumerate(values):
            if validity[i]:
                flat.extend(None if it == ("lit", "null") else it
                            for it in v[1])
            offs[i + 1] = len(flat)
        return Column.make_list(offs, _build_json_column(flat, arg),
                                validity=None if validity.all()
                                else validity)
    raise ValueError(f"unknown schema node {tag!r}")


def from_json_to_structs_nested(col: Column, schema,
                                allow_leading_zeros: bool = False
                                ) -> Column:
    """JSON rows -> arbitrarily nested STRUCT/LIST column
    (JSONUtils.fromJSONToStructs:188 with a nested Schema).  `schema`
    must be a ("struct", ...) node; invalid JSON rows are null.

    Nested schemas route to the device engine too (r5): struct fields
    compose scan paths, list nodes split elements vectorized and
    recurse (ops/from_json_device.py).  Since ISSUE 9 the engine
    choice is a measurement (host tree-builder / device scan / the
    structural-index tokenizer for FLAT schemas), calibrated per
    (schema shape, doc shape, backend); the tree-builder stays the
    oracle and the per-row fallback."""
    assert col.dtype.is_string
    if not (isinstance(schema, tuple) and schema[0] == "struct"):
        raise ValueError("top-level schema must be a struct")
    import os

    import jax

    from spark_rapids_tpu import observability as _obs
    from spark_rapids_tpu.ops import from_json_device as FJ
    from spark_rapids_tpu.ops import json_tokenizer as JT
    from spark_rapids_tpu.ops.json_path import route_json_engine
    min_rows = int(os.environ.get(
        "SPARK_RAPIDS_TPU_FROM_JSON_DEVICE_MIN", "256"))
    force = os.environ.get(
        "SPARK_RAPIDS_TPU_FORCE_DEVICE_FROM_JSON") == "1"
    on_accel = jax.default_backend() != "cpu"
    fields = list(schema[1])
    flat = all(isinstance(spec, DType) for _n, spec in fields)

    def _host(c):
        return _build_json_column(
            list(_parse_rows(c, allow_leading_zeros)), schema)

    if force:
        engine = "device_scan"
    elif col.length < min_rows:
        engine = "host"
    else:
        engines = {
            "host": _host,
            "device_scan": lambda c: FJ.from_json_to_structs_device(
                c, fields, allow_leading_zeros),
        }
        if flat:
            engines["tokenizer"] = \
                lambda c: JT.from_json_to_structs_tokenized(
                    c, fields, allow_leading_zeros)
        engine = route_json_engine(
            "json.from_json", col, engines,
            "device_scan" if on_accel else "host",
            extra=f"f{len(fields)}|flat{int(flat)}")
    # record the path AFTER fallback resolution: an engine that
    # declines the shape (returns None) really ran on the host
    if engine == "tokenizer" and flat:
        out = JT.from_json_to_structs_tokenized(col, fields,
                                                allow_leading_zeros)
        if out is not None:
            _obs.record_kernel_path("from_json_structs", "tokenizer",
                                    col.length)
            return out
    if engine == "device_scan":
        out = FJ.from_json_to_structs_device(
            col, fields, allow_leading_zeros)
        if out is not None:
            _obs.record_kernel_path("from_json_structs", "device_scan",
                                    col.length)
            return out
    _obs.record_kernel_path("from_json_structs", "host", col.length)
    return _host(col)
