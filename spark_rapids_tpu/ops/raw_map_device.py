"""Device from_json raw map: multi-capture JSON scan on TPU.

Reference: src/main/cpp/src/from_json_to_raw_map.cu:1-894 (kernels
behind JSONUtils.extractRawMapFromJsonString — every top-level key and
value of a JSON object row into MAP<STRING,STRING>).

Unlike get_json_object/from_json-to-structs (one capture register per
row), raw map needs EVERY depth-1 pair, so this is a dedicated
lax.scan: each row carries a token-mode DFA plus a pair cursor, and
key/value spans land in (rows, MAX_PAIRS) registers via one-hot
pair-index writes (the json_device stack-lane discipline — scatter
lowers catastrophically inside TPU scans, masked one-hot writes don't).

Device scope (everything else flags the row to the host oracle,
json_utils.from_json_to_raw_map): flat objects of plain double-quoted
keys and primitive values — strings without escapes, numbers without
leading zeros, true/false/null.  Nested values, escapes, single quotes,
control characters, >MAX_PAIRS pairs, and potential duplicate keys
(detected post-scan by span length + content probes) all fall back
per-row.  Rows whose first non-whitespace byte is not '{' are null
directly (the host nulls every non-object row, valid JSON or not).

Duplicate-key note: raw map keeps the FIRST position but the LAST value
of a duplicated key; rather than cross-compare 32x32 spans on device,
potential duplicates route to the host (false positives only cost a
fallback, never correctness).
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column

_I32 = jnp.int32
_U8 = jnp.uint8
_B = jnp.bool_

MAX_PAIRS = 32
DEVICE_ROW_CHUNK = 1 << 15   # bounds the (rows, MAX_PAIRS) registers

# DFA modes
_M_PRE = 0        # before '{' (only ws allowed)
_M_KEY_OR_END = 1  # after '{': key quote or '}'
_M_KEY = 2        # inside key string
_M_COLON = 3      # after key: ws then ':'
_M_VAL_START = 4  # ws then a value first-char
_M_VAL_PRIM = 5   # inside number/true/false/null token
_M_VAL_STR = 6    # inside string value
_M_AFTER_VAL = 7  # ws then ',' or '}'
_M_KEY_REQ = 8    # after ',': key quote required
_M_END = 9        # after final '}': only ws allowed


def _scan_raw_map(chars: jnp.ndarray, lens: jnp.ndarray):
    """Returns (is_obj, ok, npairs, ks, ke, vs, ve, val_is_str):
    spans are row-relative; ok=False rows need the host oracle."""
    R, L = chars.shape
    pair_lane = jnp.arange(MAX_PAIRS, dtype=_I32)[None, :]

    ws = (ord(" "), ord("\t"), ord("\n"), ord("\r"))

    def step(carry, j_and_c):
        (mode, fb, npairs, last_nonws, ks, ke, vs, ve, vstr) = carry
        j, c = j_and_c
        in_row = j < lens
        is_ws = jnp.zeros(R, _B)
        for w in ws:
            is_ws |= c == w
        bad_ctrl = (c < 0x20) & ~is_ws
        high = c >= 0x80

        def put(reg, val, active):
            onehot = (pair_lane == npairs[:, None]) & active[:, None]
            val = jnp.broadcast_to(val, (R,))   # j-scalars and (R,)
            return jnp.where(onehot, val[:, None], reg)

        m = lambda v: mode == v  # noqa: E731
        act = in_row & ~fb

        # --- _M_PRE: ws* then '{' (anything else: null-row marker,
        # encoded as fb=False + is_obj False computed at the end)
        to_obj = act & m(_M_PRE) & (c == ord("{"))
        pre_other = act & m(_M_PRE) & ~is_ws & (c != ord("{"))

        # --- key start / object end
        kq = act & (m(_M_KEY_OR_END) | m(_M_KEY_REQ)) & (c == ord('"'))
        obj_end_early = act & m(_M_KEY_OR_END) & (c == ord("}"))
        key_bad = act & (m(_M_KEY_OR_END) | m(_M_KEY_REQ)) & ~is_ws \
            & (c != ord('"')) & ~(m(_M_KEY_OR_END) & (c == ord("}")))

        # --- inside key
        key_end = act & m(_M_KEY) & (c == ord('"'))
        key_esc = act & m(_M_KEY) & ((c == ord("\\")) | bad_ctrl | high)

        # --- colon
        colon = act & m(_M_COLON) & (c == ord(":"))
        colon_bad = act & m(_M_COLON) & ~is_ws & (c != ord(":"))

        # --- value start
        vschar = act & m(_M_VAL_START) & ~is_ws
        v_str = vschar & (c == ord('"'))
        v_nest = vschar & ((c == ord("{")) | (c == ord("[")))
        v_prim_ok = vschar & (
            ((c >= ord("0")) & (c <= ord("9"))) | (c == ord("-"))
            | (c == ord("t")) | (c == ord("f")) | (c == ord("n")))
        v_bad = vschar & ~v_str & ~v_nest & ~v_prim_ok

        # --- inside string value
        vs_end = act & m(_M_VAL_STR) & (c == ord('"'))
        vs_esc = act & m(_M_VAL_STR) & ((c == ord("\\")) | bad_ctrl
                                        | high)

        # --- inside primitive value: ends at ws, ',' or '}'
        vp_delim = act & m(_M_VAL_PRIM) & (
            is_ws | (c == ord(",")) | (c == ord("}")))
        vp_bad = act & m(_M_VAL_PRIM) & (
            bad_ctrl | (c == ord("[")) | (c == ord("{"))
            | (c == ord('"')))

        # --- after value
        more = (act & m(_M_AFTER_VAL) & (c == ord(","))) | \
            (vp_delim & (c == ord(",")))
        obj_end = (act & m(_M_AFTER_VAL) & (c == ord("}"))) | \
            (vp_delim & (c == ord("}"))) | obj_end_early
        after_bad = act & m(_M_AFTER_VAL) & ~is_ws \
            & (c != ord(",")) & (c != ord("}"))

        # --- after '}': only trailing ws
        end_bad = act & m(_M_END) & ~is_ws

        new_fb = fb | (act & (
            key_esc | vs_esc | colon_bad | v_bad | v_nest | vp_bad
            | after_bad | end_bad | key_bad
            | (kq & (npairs >= MAX_PAIRS))))

        # span writes (one-hot at the current pair index)
        ks = put(ks, j + 1, kq)
        ke = put(ke, j, key_end)
        vs = put(vs, jnp.where(v_str, j + 1, j), v_str | v_prim_ok)
        # string value end: at closing quote; primitive end: last
        # non-ws position + 1 (handled via last_nonws below)
        ve = put(ve, j, vs_end)
        ve = put(ve, last_nonws + 1, vp_delim)
        vstr = jnp.where(
            (pair_lane == npairs[:, None]) & (v_str | v_prim_ok)[:, None],
            v_str[:, None], vstr)

        npairs_new = npairs + (vs_end | vp_delim).astype(_I32)
        last_nonws_new = jnp.where(act & m(_M_VAL_PRIM) & ~is_ws
                                   & ~vp_delim, j, last_nonws)

        mode_new = jnp.where(
            to_obj, _M_KEY_OR_END,
            jnp.where(kq, _M_KEY,
            jnp.where(key_end, _M_COLON,
            jnp.where(colon, _M_VAL_START,
            jnp.where(v_str, _M_VAL_STR,
            jnp.where(v_prim_ok & ~v_str, _M_VAL_PRIM,
            jnp.where(vs_end, _M_AFTER_VAL,
            jnp.where(vp_delim & ~more & ~(vp_delim & (c == ord("}"))),
                      _M_AFTER_VAL,
            jnp.where(more, _M_KEY_REQ,
            jnp.where(obj_end, _M_END, mode))))))))))
        mode_new = jnp.where(act, mode_new, mode)
        # a non-'{' first char parks the row in _M_PRE permanently
        mode_new = jnp.where(pre_other, _M_PRE, mode_new)
        fb_keep = jnp.where(pre_other, fb, new_fb)  # null row, not fb

        return ((mode_new, fb_keep, npairs_new, last_nonws_new,
                 ks, ke, vs, ve, vstr), None)

    z_pairs = jnp.zeros((R, MAX_PAIRS), _I32)
    carry0 = (jnp.full(R, _M_PRE, _I32), jnp.zeros(R, _B),
              jnp.zeros(R, _I32), jnp.zeros(R, _I32),
              z_pairs, z_pairs, z_pairs, z_pairs,
              jnp.zeros((R, MAX_PAIRS), _B))
    js = jnp.arange(L, dtype=_I32)
    (mode, fb, npairs, _ln, ks, ke, vs, ve, vstr), _ = lax.scan(
        step, carry0, (js, chars.T))

    # structural completion: mode must be _M_END (or _M_PRE for
    # non-object rows); unterminated rows are invalid -> null (host
    # agrees: invalid JSON nulls the row), EXCEPT fb rows (host decides)
    is_obj = mode == _M_END
    ok = ~fb
    return is_obj, ok, npairs, ks, ke, vs, ve, vstr


_scan_raw_map_jit = jax.jit(_scan_raw_map)


_NUM_W = 26   # validation window: longer primitives fall back


def _primitive_token_ok(chars: np.ndarray, vs, ve, pvalid
                        ) -> np.ndarray:
    """Per-pair primitive validation: exact true/false/null, or the
    strict JSON number grammar run as a small unrolled DFA over a
    fixed window (anything else — NaN, hex, overlong — host decides).
    Returns (R, MAX_PAIRS) ok mask (True where not a primitive)."""
    R = chars.shape[0]
    L = chars.shape[1]
    tok_len = np.where(pvalid, ve - vs, 0)
    win_idx = vs[:, :, None] + np.arange(_NUM_W)[None, None, :]
    win = chars[np.arange(R)[:, None, None],
                np.minimum(win_idx, L - 1)]
    inlen = np.arange(_NUM_W)[None, None, :] < tok_len[:, :, None]
    win = np.where(inlen, win, 0)

    def is_word(w: bytes):
        m = tok_len == len(w)
        for i, b in enumerate(w):
            m = m & (win[:, :, i] == b)
        return m

    word_ok = is_word(b"true") | is_word(b"false") | is_word(b"null")

    # number DFA states: 0 start, 1 after '-', 2 int digits,
    # 3 after '.', 4 frac digits, 5 after e, 6 after e-sign,
    # 7 exp digits, 8 reject
    state = np.zeros(tok_len.shape, np.int8)
    for i in range(_NUM_W):
        c = win[:, :, i]
        active = inlen[:, :, i]
        dig = (c >= ord("0")) & (c <= ord("9"))
        new = np.full_like(state, 8)
        new = np.where((state == 0) & (c == ord("-")), 1, new)
        new = np.where(((state == 0) | (state == 1) | (state == 2))
                       & dig, 2, new)
        new = np.where((state == 2) & (c == ord(".")), 3, new)
        new = np.where(((state == 3) | (state == 4)) & dig, 4, new)
        new = np.where(((state == 2) | (state == 4))
                       & ((c == ord("e")) | (c == ord("E"))), 5, new)
        new = np.where((state == 5)
                       & ((c == ord("+")) | (c == ord("-"))), 6, new)
        new = np.where(((state == 5) | (state == 6) | (state == 7))
                       & dig, 7, new)
        state = np.where(active, new, state)
    num_ok = ((state == 2) | (state == 4) | (state == 7)) \
        & (tok_len <= _NUM_W)

    return ~pvalid | word_ok | num_ok


def _dup_key_suspects(chars: np.ndarray, ks, ke, npairs) -> np.ndarray:
    """Rows that MIGHT contain duplicate keys (probe: length + first/
    last byte); false positives just fall back to host."""
    R = chars.shape[0]
    lane = np.arange(MAX_PAIRS)[None, :]
    valid = lane < npairs[:, None]
    klen = np.where(valid, ke - ks, -lane)          # unique when empty
    first = np.where(valid, chars[np.arange(R)[:, None],
                                  np.minimum(ks, chars.shape[1] - 1)],
                     0)
    last = np.where(valid, chars[np.arange(R)[:, None],
                                 np.minimum(np.maximum(ke - 1, 0),
                                            chars.shape[1] - 1)], 0)
    probe = (klen.astype(np.int64) << 32) | \
        (first.astype(np.int64) << 16) | last.astype(np.int64)
    srt = np.sort(np.where(valid, probe, lane - 100_000), axis=1)
    dup = (srt[:, 1:] == srt[:, :-1]).any(axis=1)
    return dup & (npairs > 1)


def _flat_string_build(chars: np.ndarray, starts: np.ndarray,
                       lens: np.ndarray,
                       host_patch) -> Column:
    """STRING column from flat spans (starts encode row*width+col)
    into the padded matrix (shared builder: columns/strbuild)."""
    from spark_rapids_tpu.columns.strbuild import build_string_column
    return build_string_column(chars.reshape(-1), starts, lens,
                               None, host_patch or None)


def from_json_to_raw_map_device(col: Column,
                                allow_leading_zeros: bool = False
                                ) -> Optional[Column]:
    """Device raw-map extraction; None -> caller must run the host
    path entirely (the router in json_utils handles that)."""
    if col.length == 0:
        return None

    from spark_rapids_tpu.ops.json_utils import (_parse_rows,
                                                 _value_as_raw_string)

    rows = col.length
    in_valid = (np.ones(rows, bool) if col.validity is None
                else np.asarray(col.validity).astype(bool)[:rows])

    counts = np.zeros(rows, np.int64)
    validity = np.zeros(rows, np.uint8)
    key_parts: List[Column] = []
    val_parts: List[Column] = []

    for b0 in range(0, rows, DEVICE_ROW_CHUNK):
        b1 = min(rows, b0 + DEVICE_ROW_CHUNK)
        R = b1 - b0
        sub = Column(col.dtype, R, data=col.data, validity=None,
                     offsets=col.offsets[b0:b1 + 1])
        chars_j, lens_j = sub.to_padded_chars()
        is_obj, ok, npairs, ks, ke, vs, ve, vstr = \
            _scan_raw_map_jit(chars_j, lens_j)
        chars = np.asarray(chars_j)
        lens_np = np.asarray(lens_j)
        is_obj = np.asarray(is_obj)
        ok = np.asarray(ok)
        npairs = np.asarray(npairs)
        ks, ke = np.asarray(ks), np.asarray(ke)
        vs, ve = np.asarray(vs), np.asarray(ve)
        vstr = np.asarray(vstr)

        # Spark leading-zero number rule: the scan is agnostic, so
        # rows with a primitive value '0<digit>...' fall back to the
        # host parser (which owns the allow_leading_zeros knob)
        lane = np.arange(MAX_PAIRS)[None, :]
        pvalid = (lane < npairs[:, None]) & ~vstr
        rr = np.arange(R)[:, None]
        c0 = chars[rr, np.minimum(vs, chars.shape[1] - 1)]
        c1 = chars[rr, np.minimum(vs + 1, chars.shape[1] - 1)]
        neg = c0 == ord("-")
        d0 = np.where(neg, c1, c0)
        lead_zero = pvalid & (d0 == ord("0")) & \
            ((ve - vs) > (1 + neg.astype(np.int64)))
        ok = ok & ~lead_zero.any(axis=1)
        ok = ok & _primitive_token_ok(chars, vs, ve, pvalid).all(axis=1)
        ok = ok & ~_dup_key_suspects(chars, ks, ke, npairs)

        # host fallback rows: parse once per row, spark semantics
        host_rows = np.nonzero(in_valid[b0:b1] & ~ok)[0]
        host_maps = {}
        if host_rows.size:
            sub_host = Column.from_strings(
                [bytes(chars[i, :lens_np[i]]) for i in host_rows])
            for hi, tree in zip(host_rows,
                                _parse_rows(sub_host,
                                            allow_leading_zeros)):
                if tree is None or tree[0] != "obj":
                    host_maps[hi] = None
                    continue
                seen = {}
                order = []
                for k, v in tree[1]:
                    if k not in seen:
                        order.append(k)
                    seen[k] = _value_as_raw_string(v)
                host_maps[hi] = [(k, seen[k]) for k in order]

        dev_ok = in_valid[b0:b1] & ok & is_obj
        c_counts = np.where(dev_ok, npairs, 0)
        for hi, pairs in host_maps.items():
            if pairs is not None:
                c_counts[hi] = len(pairs)
        counts[b0:b1] = c_counts
        validity[b0:b1] = (dev_ok | np.asarray(
            [host_maps.get(i) is not None for i in range(R)])) \
            .astype(np.uint8) if host_maps else dev_ok.astype(np.uint8)

        # flat pair stream (row-major): device spans + host patches
        lane_valid = (lane < npairs[:, None]) & dev_ok[:, None]
        pair_base = np.concatenate([[0], np.cumsum(c_counts)])
        total_pairs = int(pair_base[-1])
        k_start = np.zeros(total_pairs, np.int64)
        k_len = np.zeros(total_pairs, np.int64)
        v_start = np.zeros(total_pairs, np.int64)
        v_len = np.zeros(total_pairs, np.int64)
        fp_row, fp_lane = np.nonzero(lane_valid)
        gidx = pair_base[fp_row] + fp_lane
        L = chars.shape[1]
        k_start[gidx] = fp_row * L + ks[fp_row, fp_lane]
        k_len[gidx] = (ke - ks)[fp_row, fp_lane]
        v_start[gidx] = fp_row * L + vs[fp_row, fp_lane]
        v_len[gidx] = (ve - vs)[fp_row, fp_lane]
        key_patch, val_patch = {}, {}
        for hi, pairs in host_maps.items():
            if pairs is None:
                continue
            for p, (k, v) in enumerate(pairs):
                key_patch[int(pair_base[hi]) + p] = k
                val_patch[int(pair_base[hi]) + p] = v

        key_parts.append(_flat_string_build(chars, k_start, k_len,
                                            key_patch))
        val_parts.append(_flat_string_build(chars, v_start, v_len,
                                            val_patch))

    if len(key_parts) == 1:
        keys_col, vals_col = key_parts[0], val_parts[0]
    else:
        from spark_rapids_tpu.columns.table import Table
        from spark_rapids_tpu.ops.copying import concat_tables
        keys_col = concat_tables([Table([p]) for p in key_parts]) \
            .columns[0]
        vals_col = concat_tables([Table([p]) for p in val_parts]) \
            .columns[0]

    offs = np.concatenate([[0], np.cumsum(counts)]).astype(np.int32)
    st = Column.make_struct(keys_col.length, [keys_col, vals_col])
    return Column(dtypes.LIST, rows,
                  validity=None if validity.all() else
                  jnp.asarray(validity),
                  offsets=jnp.asarray(offs), children=(st,))
