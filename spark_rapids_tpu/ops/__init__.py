"""Columnar op kernels — the L3 equivalent of the reference's
src/main/cpp/src/*.cu free functions.  All ops are stateless, take
Column/Table values, and return new Columns."""

from spark_rapids_tpu.ops.hash import (  # noqa: F401
    murmur3_32,
    xxhash64,
    hive_hash,
    DEFAULT_XXHASH64_SEED,
)
from spark_rapids_tpu.ops.sha import (  # noqa: F401
    sha224_nulls_preserved,
    sha256_nulls_preserved,
    sha384_nulls_preserved,
    sha512_nulls_preserved,
    host_crc32,
)
