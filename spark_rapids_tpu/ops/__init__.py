"""Columnar op kernels — the L3 equivalent of the reference's
src/main/cpp/src/*.cu free functions.  All ops are stateless, take
Column/Table values, and return new Columns."""

from spark_rapids_tpu.ops.hash import (  # noqa: F401
    murmur3_32,
    xxhash64,
    hive_hash,
    DEFAULT_XXHASH64_SEED,
)
from spark_rapids_tpu.ops.sha import (  # noqa: F401
    sha224_nulls_preserved,
    sha256_nulls_preserved,
    sha384_nulls_preserved,
    sha512_nulls_preserved,
    host_crc32,
)
from spark_rapids_tpu.ops.cast_string import (  # noqa: F401
    string_to_integer,
    string_to_float,
    float_to_string,
)
from spark_rapids_tpu.ops.arithmetic import (  # noqa: F401
    multiply,
    round_column,
    HALF_UP,
    HALF_EVEN,
)
from spark_rapids_tpu.ops.aggregation64 import (  # noqa: F401
    extract_chunk32_from_64bit,
    assemble64_from_sum,
)
from spark_rapids_tpu.ops.case_when import (  # noqa: F401
    select_first_true_index,
)
from spark_rapids_tpu.ops.copying import (  # noqa: F401
    gather,
    gather_table,
    slice_table,
    split_table,
    concat_tables,
)
from spark_rapids_tpu.ops.substring_index import substring_index  # noqa: F401
from spark_rapids_tpu.ops.zorder import (  # noqa: F401
    interleave_bits,
    hilbert_index,
)
from spark_rapids_tpu.ops import bloom_filter  # noqa: F401
from spark_rapids_tpu.ops.exceptions import (  # noqa: F401
    ExceptionWithRowIndex,
    CastException,
)
from spark_rapids_tpu.ops.joins import (  # noqa: F401
    sort_merge_inner_join,
    hash_inner_join,
    filter_join_pairs,
    make_left_outer,
    make_full_outer,
    make_semi,
    make_anti,
    get_matched_rows,
)
from spark_rapids_tpu.ops.groupby import groupby_aggregate  # noqa: F401
from spark_rapids_tpu.ops import hllpp  # noqa: F401
from spark_rapids_tpu.ops.histogram import (  # noqa: F401
    create_histogram_if_valid,
    percentile_from_histogram,
)
from spark_rapids_tpu.ops import decimal_utils  # noqa: F401
from spark_rapids_tpu.ops import datetime_ops  # noqa: F401
from spark_rapids_tpu.ops.json_path import (  # noqa: F401
    get_json_object,
    get_json_object_multiple_paths,
)
from spark_rapids_tpu.ops import parse_uri  # noqa: F401
from spark_rapids_tpu.ops.strings_misc import (  # noqa: F401
    convert,
    is_convert_overflow,
    decode_to_utf8,
    list_slice,
    literal_range_pattern,
)
from spark_rapids_tpu.ops import map_utils  # noqa: F401
from spark_rapids_tpu.ops import json_utils  # noqa: F401
from spark_rapids_tpu.ops import iceberg  # noqa: F401
from spark_rapids_tpu.ops import protobuf  # noqa: F401
from spark_rapids_tpu.ops.uuid_gen import random_uuids  # noqa: F401
from spark_rapids_tpu.ops.sorting import order_by, sort_table  # noqa: F401
from spark_rapids_tpu.ops.cast_more import (  # noqa: F401
    long_to_binary_string,
    bytes_to_hex,
    long_to_hex_string,
    decimal_to_non_ansi_string,
    format_number,
    parse_strings_to_date,
    parse_timestamp_strings,
    parse_timestamp_strings_with_format,
)

# ---------------------------------------------------------------------
# Sidecar instrumentation: every public op entry point gets the
# maybe_inject + op_range bracket AT THE OP LAYER (reference: NVTX
# ranges live in each kernel entry, nvtx_ranges.hpp), so models/, tests
# and the shim all hit the same tracing/fault-injection surface.
from spark_rapids_tpu.utils.tracing import instrument as _instrument

_TRACED = {
    "spark_rapids_tpu.ops.hash": ["murmur3_32", "xxhash64", "hive_hash"],
    "spark_rapids_tpu.ops.sha": [
        "sha224_nulls_preserved", "sha256_nulls_preserved",
        "sha384_nulls_preserved", "sha512_nulls_preserved", "host_crc32"],
    "spark_rapids_tpu.ops.cast_string": [
        "string_to_integer", "string_to_float", "float_to_string"],
    "spark_rapids_tpu.ops.arithmetic": ["multiply", "round_column"],
    "spark_rapids_tpu.ops.aggregation64": [
        "extract_chunk32_from_64bit", "assemble64_from_sum"],
    "spark_rapids_tpu.ops.case_when": ["select_first_true_index"],
    "spark_rapids_tpu.ops.copying": [
        "gather", "gather_table", "slice_table", "split_table",
        "concat_tables"],
    "spark_rapids_tpu.ops.substring_index": ["substring_index"],
    "spark_rapids_tpu.ops.zorder": ["interleave_bits", "hilbert_index"],
    "spark_rapids_tpu.ops.joins": [
        "sort_merge_inner_join", "hash_inner_join", "filter_join_pairs",
        "make_left_outer", "make_full_outer", "make_semi", "make_anti",
        "get_matched_rows"],
    "spark_rapids_tpu.ops.groupby": ["groupby_aggregate"],
    "spark_rapids_tpu.ops.histogram": [
        "create_histogram_if_valid", "percentile_from_histogram"],
    "spark_rapids_tpu.ops.json_path": [
        "get_json_object", "get_json_object_multiple_paths"],
    "spark_rapids_tpu.ops.strings_misc": [
        "convert", "is_convert_overflow", "decode_to_utf8", "list_slice",
        "literal_range_pattern"],
    "spark_rapids_tpu.ops.uuid_gen": ["random_uuids"],
    "spark_rapids_tpu.ops.sorting": ["order_by", "sort_table"],
    "spark_rapids_tpu.ops.row_conversion": [
        "convert_to_rows", "convert_from_rows"],
    "spark_rapids_tpu.ops.cast_more": [
        "long_to_binary_string", "bytes_to_hex", "long_to_hex_string",
        "decimal_to_non_ansi_string", "format_number",
        "parse_strings_to_date", "parse_timestamp_strings",
        "parse_timestamp_strings_with_format"],
}

from spark_rapids_tpu.ops import row_conversion as _rc  # noqa: F401,E402

for _m, _names in _TRACED.items():
    _instrument(_m, _names)
# re-export the wrapped bindings at the package level too
import sys as _sys  # noqa: E402

_pkg = _sys.modules[__name__]
for _m, _names in _TRACED.items():
    for _n in _names:
        if hasattr(_pkg, _n):
            setattr(_pkg, _n, getattr(_sys.modules[_m], _n))
del _sys, _pkg, _m, _names, _n
