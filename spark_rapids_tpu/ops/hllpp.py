"""HyperLogLogPlusPlus (approx_count_distinct) sketches — Spark-compatible
(reference hyper_log_log_plus_plus.cu/.hpp, HyperLogLogPlusPlusHostUDF):

  * hash = xxhash64(column, seed 42) (hyper_log_log_plus_plus.cu:59)
  * register index = hash >>> (64 - p); register value =
    countl_zero((hash << p) | w_padding) + 1 (:190-212)
  * sketch = 2^p 6-bit registers packed 10 per int64, stored as a STRUCT
    of ceil-ish (2^p/10 + 1) INT64 columns (:373-382)
  * estimate: harmonic mean + empirical bias correction in the mid
    zone + HLL++ linear-counting decision with the paper's
    per-precision thresholds (estimate_fn :852-875 delegates to the
    cuco finalizer).  The bias table (ops/hllpp_bias.npz) is measured
    by scripts/gen_hllpp_bias.py with this repo's own register
    pipeline — the reference's table lives inside its cuco dependency,
    so the paper's measurement is reproduced rather than vendored;
    values can differ from Spark's table within estimator noise.

TPU design: register maxima via segment_max over (group, register) ids;
countl_zero as vectorized binary steps; packing as shift-OR reductions —
all device ops.
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.ops import hash as H

_I32 = jnp.int32
_I64 = jnp.int64
_U64 = jnp.uint64

REGISTER_VALUE_BITS = 6
REGISTERS_PER_LONG = 10
MASK = (1 << REGISTER_VALUE_BITS) - 1
MAX_PRECISION = 18
SEED = 42


def _check_precision(precision: int) -> int:
    if precision < 4:
        raise ValueError(
            "HyperLogLogPlusPlus requires precision bigger than 4.")
    return min(precision, MAX_PRECISION)


from spark_rapids_tpu.utils.u64math import clz64 as _clz64  # noqa: E402


def _registers_for(col: Column, precision: int):
    """(per-row register index, per-row register value, valid mask)."""
    hashes = H.xxhash64([col], SEED).data.astype(_U64)
    idx = (hashes >> _U64(64 - precision)).astype(_I32)
    w_padding = _U64(1 << (precision - 1))
    w = (hashes << _U64(precision)) | w_padding
    val = _clz64(w) + 1
    return idx, val, col.valid_mask()


def _num_long_cols(precision: int) -> int:
    return (1 << precision) // REGISTERS_PER_LONG + 1


def _pack_registers(regs: jnp.ndarray, precision: int) -> List[jnp.ndarray]:
    """(ngroups, 2^p) int32 register values -> list of (ngroups,) int64
    packed columns (10x6 bits per long)."""
    ngroups, m = regs.shape
    ncols = _num_long_cols(precision)
    pad = ncols * REGISTERS_PER_LONG - m
    if pad:
        regs = jnp.pad(regs, ((0, 0), (0, pad)))
    r3 = regs.reshape(ngroups, ncols, REGISTERS_PER_LONG).astype(_I64)
    shifts = (REGISTER_VALUE_BITS
              * jnp.arange(REGISTERS_PER_LONG, dtype=_I64))[None, None, :]
    packed = (r3 << shifts).sum(axis=2)
    return [packed[:, j] for j in range(ncols)]


def _unpack_registers(longs: Sequence[jnp.ndarray],
                      precision: int) -> jnp.ndarray:
    """Inverse of _pack_registers: -> (ngroups, 2^p) int32."""
    m = 1 << precision
    cols = []
    for j, lg in enumerate(longs):
        for k in range(REGISTERS_PER_LONG):
            reg_idx = j * REGISTERS_PER_LONG + k
            if reg_idx >= m:
                break
            cols.append(((lg >> _I64(REGISTER_VALUE_BITS * k))
                         & _I64(MASK)).astype(_I32))
    return jnp.stack(cols, axis=1)


def _sketch_struct(longs: List[jnp.ndarray]) -> Column:
    n = int(longs[0].shape[0])
    children = [Column(dtypes.INT64, n, data=lg) for lg in longs]
    return Column.make_struct(n, children)


def group_hllpp(col: Column, group_ids: jnp.ndarray, num_groups: int,
                precision: int) -> Column:
    """Per-group sketches as a STRUCT<INT64...> column
    (group_hyper_log_log_plus_plus)."""
    precision = _check_precision(precision)
    m = 1 << precision
    idx, val, valid = _registers_for(col, precision)
    flat = group_ids.astype(_I64) * m + idx.astype(_I64)
    flat = jnp.where(valid, flat, jnp.int64(num_groups) * m)  # dropped
    maxes = jax.ops.segment_max(jnp.where(valid, val, 0), flat,
                                num_groups * m + 1)
    regs = maxes[: num_groups * m].reshape(num_groups, m)
    regs = jnp.maximum(regs, 0)  # segment_max of empty segments -> -inf
    return _sketch_struct(_pack_registers(regs, precision))


def reduce_hllpp(col: Column, precision: int) -> Column:
    """Whole-column sketch (1-row struct; reduce_hyper_log_log_plus_plus)."""
    return group_hllpp(col, jnp.zeros(col.length, _I32), 1, precision)


def merge_sketches(sketch_col: Column, group_ids: jnp.ndarray,
                   num_groups: int, precision: int) -> Column:
    """Merge sketch rows by group (group_merge_hyper_log_log_plus_plus):
    per-register max."""
    precision = _check_precision(precision)
    if len(sketch_col.children) != _num_long_cols(precision):
        raise ValueError("The num of long columns in input is incorrect.")
    regs = _unpack_registers([c.data for c in sketch_col.children],
                             precision)
    m = 1 << precision
    rows = sketch_col.length
    flat = (group_ids.astype(_I64)[:, None] * m
            + jnp.arange(m, dtype=_I64)[None, :]).reshape(-1)
    merged = jax.ops.segment_max(regs.reshape(-1), flat, num_groups * m)
    merged = jnp.maximum(merged.reshape(num_groups, m), 0)
    return _sketch_struct(_pack_registers(merged, precision))


def reduce_merge_hllpp(sketch_col: Column, precision: int) -> Column:
    return merge_sketches(sketch_col, jnp.zeros(sketch_col.length, _I32),
                          1, precision)


_BIAS_CACHE = {}


def _bias_table(precision: int):
    """(raw_estimate knots, bias knots) jnp arrays for jnp.interp."""
    if precision not in _BIAS_CACHE:
        import os

        path = os.path.join(os.path.dirname(__file__),
                            "hllpp_bias.npz")
        data = np.load(path)
        _BIAS_CACHE[precision] = (
            jnp.asarray(data[f"raw_p{precision}"]),
            jnp.asarray(data[f"bias_p{precision}"]))
    return _BIAS_CACHE[precision]


def estimate_from_hll_sketches(sketch_col: Column,
                               precision: int) -> Column:
    """INT64 estimates per sketch row (estimate_fn; HLL++ with linear
    counting for the small range)."""
    precision = _check_precision(precision)
    regs = _unpack_registers([c.data for c in sketch_col.children],
                             precision)
    m = 1 << precision
    if m == 16:
        alpha = 0.673
    elif m == 32:
        alpha = 0.697
    elif m == 64:
        alpha = 0.709
    else:
        alpha = 0.7213 / (1.0 + 1.079 / m)
    inv = 1.0 / (2.0 ** regs.astype(jnp.float64))
    s = inv.sum(axis=1)
    zeroes = (regs == 0).sum(axis=1).astype(jnp.float64)
    raw = alpha * m * m / s
    # empirical bias correction in the mid zone (raw <= 5m), paper
    # order: correct raw first, then the linear-counting decision.
    # ALGORITHM parity with Spark's HyperLogLogPlusPlusHelper: the
    # bias at a raw estimate is the MEAN OF THE K=6 NEAREST knots'
    # biases (Spark's kNN average over rawEstimateData/biasData), not
    # a linear interpolation.  Table values: ops/hllpp_bias.npz,
    # measured with this repo's own register pipeline
    # (scripts/gen_hllpp_bias.py) since the reference's table lives in
    # its cuco dependency and Spark's in its source constants — the
    # small/large ranges below are table-free and exact; mid-range
    # estimates can differ from Spark within measurement noise.
    raw_knots, bias_knots = _bias_table(precision)
    k = 6
    nk = raw_knots.shape[0]
    idx = jnp.searchsorted(raw_knots, raw)
    # nearest-k knots BY DISTANCE: with sorted knots they form a
    # contiguous window; among the k+1 candidate windows ending near
    # idx, pick the one whose FARTHEST member is closest (Spark's
    # estimateBias slides the window by exactly this criterion)
    best_lo = None
    best_far = None
    for shift in range(k + 1):
        lo = jnp.clip(idx - k + shift, 0, max(nk - k, 0))
        far = jnp.maximum(jnp.abs(raw - raw_knots[lo]),
                          jnp.abs(raw_knots[lo + k - 1] - raw))
        if best_lo is None:
            best_lo, best_far = lo, far
        else:
            take = far < best_far
            best_lo = jnp.where(take, lo, best_lo)
            best_far = jnp.where(take, far, best_far)
    window = best_lo[:, None] + jnp.arange(k)[None, :]
    bias = bias_knots[jnp.clip(window, 0, nk - 1)].mean(axis=1)
    corrected = raw - bias
    e = jnp.where(raw <= 5.0 * m, corrected, raw)
    linear = m * jnp.log(m / jnp.maximum(zeroes, 1))
    # HLL++ linear-counting threshold per precision (paper appendix;
    # what the cuco finalizer uses), p=4..18
    thresholds = {4: 10, 5: 20, 6: 40, 7: 80, 8: 220, 9: 400, 10: 900,
                  11: 1800, 12: 3100, 13: 6500, 14: 11500, 15: 20000,
                  16: 50000, 17: 120000, 18: 350000}
    thr = thresholds[precision]
    est = jnp.where((zeroes > 0) & (linear <= thr), linear, e)
    return Column(dtypes.INT64, sketch_col.length,
                  data=jnp.round(est).astype(_I64))
