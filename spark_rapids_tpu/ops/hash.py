"""Spark-exact hash kernels: murmur3_32, xxhash64, hive_hash.

Reference semantics (studied from /root/reference/src/main/cpp/src/hash/):
  * murmur_hash.cuh:95-119  — Spark murmur3: 4-byte blocks, Spark's
    sign-extending tail handling, h ^= len, fmix32.  Floats normalize NaNs
    only (murmur_hash.cuh:164-173); small ints sign-extend to 4 bytes;
    decimal32/64 hash as 8-byte long; decimal128 hashes the minimal
    big-endian two's-complement byte string (hash.cuh:64-107).
  * xxhash64.cu:43-199 — Spark xxhash64 (seed 42): 32-byte stripes with 4
    lanes, then 8/4/1-byte tails; floats normalize NaNs AND -0.0
    (xxhash64.cu:230-239); same widening/decimal rules as murmur.
  * hive_hash.cu — h = 31*h + elem_hash fold, null elem contributes 0;
    int→identity, long→(v>>>32)^v, float/double→bits, string→Java
    String.hashCode over bytes, timestamp special (hive_hash.cu:136-152).
  * Row semantics (murmur_hash.cu:64-165, xxhash64.cu:273+): seed chains
    serially across columns; a null element returns the incoming seed
    unchanged.  Nested columns flatten per-row to leaf elements, folded
    serially with the same chaining; lists of structs are rejected
    (murmur_hash.cu:167-187).

TPU-first design: no per-row scalar loops.  Every element hash is a
closed-form function of a fixed number of 4/8-byte little-endian blocks,
computed vectorized over all rows on the VPU.  Variable-length bytes
(strings, decimal128) use a lax.scan over the padded block axis with per-row
active masks — O(max_len/4) vector steps regardless of row count.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.utils import floats

DEFAULT_XXHASH64_SEED = 42

_U32 = jnp.uint32
_U64 = jnp.uint64
_I32 = jnp.int32
_I64 = jnp.int64

# ----------------------------------------------------------------- helpers


def _cols(table_or_cols) -> List[Column]:
    if isinstance(table_or_cols, Table):
        return list(table_or_cols.columns)
    if isinstance(table_or_cols, Column):
        return [table_or_cols]
    return list(table_or_cols)


def _rotl32(x, r: int):
    return (x << _U32(r)) | (x >> _U32(32 - r))


def _rotl64(x, r: int):
    return (x << _U64(r)) | (x >> _U64(64 - r))


def _bitcast_u32(x) -> jnp.ndarray:
    return lax.bitcast_convert_type(x, jnp.uint32)


def _bitcast_u64(x) -> jnp.ndarray:
    return lax.bitcast_convert_type(x, jnp.uint64)


def _split_u64(v: jnp.ndarray):
    """uint64 -> (lo, hi) uint32 little-endian blocks."""
    return (v & _U64(0xFFFFFFFF)).astype(_U32), (v >> _U64(32)).astype(_U32)


def _normalize_nans_f32_bits(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(jnp.isnan(x), _U32(0x7FC00000), _bitcast_u32(x))


def _normalize_nans_f64_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """FLOAT64 columns carry raw bits (see columns/column.py), so NaN/zero
    normalization is pure integer work — no f64 lowering needed on TPU."""
    return jnp.where(floats.is_nan_bits(bits), _U64(floats.F64_QNAN), bits)


def _normalize_nans_zeros_f32_bits(x: jnp.ndarray) -> jnp.ndarray:
    bits = jnp.where(x == 0.0, _U32(0), _bitcast_u32(x))
    return jnp.where(jnp.isnan(x), _U32(0x7FC00000), bits)


def _normalize_nans_zeros_f64_bits(bits: jnp.ndarray) -> jnp.ndarray:
    bits = jnp.where(bits == _U64(floats.F64_SIGN), _U64(0), bits)
    return jnp.where(floats.is_nan_bits(bits), _U64(floats.F64_QNAN), bits)


def _chars_to_u32_blocks(chars: jnp.ndarray) -> jnp.ndarray:
    """(rows, P) uint8 (P % 4 == 0) -> (rows, P//4) uint32 little-endian."""
    rows, p = chars.shape
    b = chars.reshape(rows, p // 4, 4).astype(_U32)
    return (b[..., 0] | (b[..., 1] << _U32(8)) | (b[..., 2] << _U32(16))
            | (b[..., 3] << _U32(24)))


def _pad_chars(chars: jnp.ndarray, multiple: int) -> jnp.ndarray:
    p = chars.shape[1]
    target = max(((p + multiple - 1) // multiple) * multiple, multiple)
    if target != p:
        chars = jnp.pad(chars, ((0, 0), (0, target - p)))
    return chars


def _dec128_min_be_bytes(limbs: jnp.ndarray):
    """(rows, 4) int32 LE limbs -> ((rows, 16) uint8 big-endian minimal
    two's-complement bytes left-justified, (rows,) int32 byte length).

    Java BigDecimal.unscaledValue().toByteArray() semantics per reference
    hash.cuh:64-107: strip leading sign bytes, keep >=1 byte, re-add one if
    the top bit would flip the sign.
    """
    u = limbs.astype(_U32)
    k = jnp.arange(16, dtype=_I32)
    le = (u[:, k // 4] >> (8 * (k % 4)).astype(_U32)) & _U32(0xFF)  # (r,16)
    neg = limbs[:, 3] < 0
    zero = jnp.where(neg, _U32(0xFF), _U32(0))
    neq = le != zero[:, None]
    last_sig = jnp.max(jnp.where(neq, k[None, :], -1), axis=1)
    length = jnp.maximum(last_sig + 1, 1)
    top = jnp.take_along_axis(le, (length - 1)[:, None], axis=1)[:, 0]
    need_sign_byte = (length < 16) & (neg != ((top & _U32(0x80)) != 0))
    length = (length + need_sign_byte).astype(_I32)
    j = jnp.arange(16, dtype=_I32)
    src = length[:, None] - 1 - j[None, :]
    be = jnp.where(src >= 0,
                   jnp.take_along_axis(le, jnp.clip(src, 0, 15), axis=1),
                   _U32(0))
    return be.astype(jnp.uint8), length


# ------------------------------------------------------------ murmur3_32

_MM_C1 = _U32(0xCC9E2D51)
_MM_C2 = _U32(0x1B873593)
_MM_C3 = _U32(0xE6546B64)


def _mm_update(h, k1):
    k1 = k1 * _MM_C1
    k1 = _rotl32(k1, 15)
    k1 = k1 * _MM_C2
    h = h ^ k1
    h = _rotl32(h, 13)
    return h * _U32(5) + _MM_C3


def _mm_fmix(h):
    h ^= h >> _U32(16)
    h = h * _U32(0x85EBCA6B)
    h ^= h >> _U32(13)
    h = h * _U32(0xC2B2AE35)
    h ^= h >> _U32(16)
    return h


class _Murmur32:
    """Vectorized Spark murmur3_32 element hashers over a (rows,) seed."""

    htype = _U32
    out_dtype = dtypes.INT32

    @staticmethod
    def seed_array(rows: int, seed: int) -> jnp.ndarray:
        return jnp.full((rows,), np.uint32(seed & 0xFFFFFFFF), _U32)

    @staticmethod
    def finish(h: jnp.ndarray) -> jnp.ndarray:
        return h.astype(_I32)

    @staticmethod
    def hash_blocks(h, blocks: Sequence[jnp.ndarray], nbytes: int):
        for b in blocks:
            h = _mm_update(h, b)
        h = h ^ _U32(nbytes)
        return _mm_fmix(h)

    @staticmethod
    def hash_varbytes(h0, chars: jnp.ndarray, lens: jnp.ndarray):
        """Spark murmur over per-row byte strings.

        chars: (rows, P) uint8 zero-padded; lens: (rows,) int32.
        Full 4-byte blocks vector-scanned; Spark's nonstandard tail
        (murmur_hash.cuh:72-93) sign-extends each trailing byte.
        """
        chars = _pad_chars(chars, 4)
        blocks = _chars_to_u32_blocks(chars)          # (rows, nb)
        nblocks = (lens // 4).astype(_I32)

        def body(h, xs):
            i, blk = xs
            h2 = _mm_update(h, blk)
            return jnp.where(i < nblocks, h2, h), None

        nb = blocks.shape[1]
        h, _ = lax.scan(body, h0,
                        (jnp.arange(nb, dtype=_I32), blocks.T))
        # tail: up to 3 sign-extended bytes
        p = chars.shape[1]
        for j in range(3):
            idx = nblocks * 4 + j
            byte = jnp.take_along_axis(
                chars, jnp.clip(idx, 0, p - 1)[:, None], axis=1)[:, 0]
            sbyte = byte.astype(jnp.int8).astype(_I32).astype(_U32)
            h2 = _mm_update(h, sbyte)
            h = jnp.where(idx < lens, h2, h)
        h = h ^ lens.astype(_U32)
        return _mm_fmix(h)


# -------------------------------------------------------------- xxhash64

_XXP1 = _U64(0x9E3779B185EBCA87)
_XXP2 = _U64(0xC2B2AE3D27D4EB4F)
_XXP3 = _U64(0x165667B19E3779F9)
_XXP4 = _U64(0x85EBCA77C2B2AE63)
_XXP5 = _U64(0x27D4EB2F165667C5)


def _xx_round(v, k):
    v = v + k * _XXP2
    v = _rotl64(v, 31)
    return v * _XXP1


def _xx_merge(h, v):
    v = v * _XXP2
    v = _rotl64(v, 31)
    v = v * _XXP1
    h = h ^ v
    return h * _XXP1 + _XXP4


def _xx_update8(h, k64):
    k1 = _xx_round(_U64(0), k64)
    h = h ^ k1
    return _rotl64(h, 27) * _XXP1 + _XXP4


def _u64_lo32(k32) -> jnp.ndarray:
    """Zero-extend a u32 block to u64 — with an explicit low-32 mask.

    The mask is semantically a no-op on a real u32, but it is
    load-bearing under jit: XLA's algebraic simplifier collapses
    convert chains like i16->i32->u32->u64 into one i16->u64 convert,
    turning the intermediate unsigned truncation into a 64-bit SIGN
    extension (observed miscompiling xxhash64 of negative narrow ints
    on this backend's CPU pipeline).  The mask pins the zero-extension
    whatever the converts collapse to."""
    return k32.astype(_U64) & _U64(0xFFFFFFFF)


def _xx_update4(h, k32):
    h = h ^ (_u64_lo32(k32) * _XXP1)
    return _rotl64(h, 23) * _XXP2 + _XXP3


def _xx_update1(h, byte):
    h = h ^ (byte.astype(_U64) * _XXP5)
    return _rotl64(h, 11) * _XXP1


def _xx_finalize(h):
    h ^= h >> _U64(33)
    h = h * _XXP2
    h ^= h >> _U64(29)
    h = h * _XXP3
    h ^= h >> _U64(32)
    return h


class _XXHash64:
    htype = _U64
    out_dtype = dtypes.INT64

    @staticmethod
    def seed_array(rows: int, seed: int) -> jnp.ndarray:
        return jnp.full((rows,), np.uint64(seed & 0xFFFFFFFFFFFFFFFF), _U64)

    @staticmethod
    def finish(h: jnp.ndarray) -> jnp.ndarray:
        return h.astype(_I64)

    @staticmethod
    def hash_blocks(h, blocks: Sequence[jnp.ndarray], nbytes: int):
        """Fixed-size (< 32 bytes here: 4, 8 or 16) element hash.
        blocks are uint32 little-endian."""
        assert nbytes < 32 and nbytes % 4 == 0
        h = h + _XXP5
        h = h + _U64(nbytes)
        i = 0
        rem = nbytes
        while rem >= 8:
            k64 = _u64_lo32(blocks[i]) | (blocks[i + 1].astype(_U64)
                                          << _U64(32))
            h = _xx_update8(h, k64)
            i += 2
            rem -= 8
        if rem >= 4:
            h = _xx_update4(h, blocks[i])
            rem -= 4
        return _xx_finalize(h)

    @staticmethod
    def hash_varbytes(h0, chars: jnp.ndarray, lens: jnp.ndarray):
        """Spark xxhash64 over per-row byte strings (xxhash64.cu:113-183)."""
        chars = _pad_chars(chars, 32)
        rows, p = chars.shape
        b32 = _chars_to_u32_blocks(chars)                       # (rows, p/4)
        b64 = (b32[:, 0::2].astype(_U64)
               | (b32[:, 1::2].astype(_U64) << _U64(32)))       # (rows, p/8)
        lens64 = lens.astype(_U64)
        nstripes = jnp.where(lens >= 32, lens // 32, 0).astype(_I32)

        # 32-byte stripes: 4 pipelined lanes
        v_init = jnp.stack([
            jnp.broadcast_to(h0 + _XXP1 + _XXP2, h0.shape),
            jnp.broadcast_to(h0 + _XXP2, h0.shape),
            h0,
            jnp.broadcast_to(h0 - _XXP1, h0.shape),
        ])

        n_stripe_steps = p // 32

        def stripe_body(v, xs):
            s, k4 = xs          # k4: (4, rows) uint64
            active = s < nstripes
            v_new = jnp.stack([_xx_round(v[l], k4[l]) for l in range(4)])
            return jnp.where(active[None, :], v_new, v), None

        stripes = b64.T.reshape(n_stripe_steps, 4, rows)
        v, _ = lax.scan(stripe_body, v_init,
                        (jnp.arange(n_stripe_steps, dtype=_I32), stripes))

        merged = (_rotl64(v[0], 1) + _rotl64(v[1], 7) + _rotl64(v[2], 12)
                  + _rotl64(v[3], 18))
        for l in range(4):
            merged = _xx_merge(merged, v[l])
        h = jnp.where(nstripes > 0, merged, h0 + _XXP5)
        h = h + lens64

        # tail after the stripes: up to three 8-byte chunks
        off8 = nstripes * 4  # stripe end in 8-byte block units
        rem = lens - nstripes * 32
        n8 = rem // 8
        nb64 = b64.shape[1]
        for t in range(3):
            idx = off8 + t
            k64 = jnp.take_along_axis(
                b64, jnp.clip(idx, 0, nb64 - 1)[:, None], axis=1)[:, 0]
            h = jnp.where(t < n8, _xx_update8(h, k64), h)
        # one 4-byte chunk
        off4 = (nstripes * 32 + n8 * 8) // 4
        rem4 = rem - n8 * 8
        nb32 = b32.shape[1]
        k32 = jnp.take_along_axis(
            b32, jnp.clip(off4, 0, nb32 - 1)[:, None], axis=1)[:, 0]
        h = jnp.where(rem4 >= 4, _xx_update4(h, k32), h)
        # up to 3 single bytes (zero-extended, unlike murmur)
        offb = nstripes * 32 + n8 * 8 + jnp.where(rem4 >= 4, 4, 0)
        for t in range(3):
            idx = offb + t
            byte = jnp.take_along_axis(
                chars, jnp.clip(idx, 0, p - 1)[:, None], axis=1)[:, 0]
            h = jnp.where(idx < lens, _xx_update1(h, byte), h)
        return _xx_finalize(h)


# ------------------------------------------------- element hash dispatch


def _fixed_width_blocks(col: Column, algo) -> tuple:
    """Return (blocks, nbytes) little-endian uint32 block decomposition of a
    fixed-width column under Spark hashing rules."""
    kind = col.dtype.kind
    d = col.data
    norm_f32 = (_normalize_nans_f32_bits if algo is _Murmur32
                else _normalize_nans_zeros_f32_bits)
    norm_f64 = (_normalize_nans_f64_bits if algo is _Murmur32
                else _normalize_nans_zeros_f64_bits)
    if kind in (Kind.BOOL8, Kind.INT8, Kind.UINT8, Kind.INT16, Kind.UINT16):
        if kind == Kind.BOOL8:
            w = d.astype(_U32)  # bool widens as 0/1
        elif kind in (Kind.INT8, Kind.INT16):
            w = d.astype(_I32).astype(_U32)  # sign-extend
        else:
            w = d.astype(_U32)
        return [w], 4
    if kind in (Kind.INT32, Kind.TIMESTAMP_DAYS):
        return [d.astype(_I32).astype(_U32)], 4
    if kind == Kind.UINT32:
        return [d.astype(_U32)], 4
    if kind == Kind.FLOAT32:
        return [norm_f32(d)], 4
    if kind in (Kind.INT64, Kind.TIMESTAMP_MICROS, Kind.UINT64):
        lo, hi = _split_u64(d.astype(_I64).astype(_U64))
        return [lo, hi], 8
    if kind == Kind.FLOAT64:
        lo, hi = _split_u64(norm_f64(d.astype(_U64)))  # d is raw bits
        return [lo, hi], 8
    if kind in (Kind.DECIMAL32, Kind.DECIMAL64):
        # hashed as an 8-byte long of the unscaled value
        lo, hi = _split_u64(d.astype(_I64).astype(_U64))
        return [lo, hi], 8
    raise NotImplementedError(f"hash of {kind}")


def _resolve_str_pad(col: Column, max_str_len: Optional[int]) -> int:
    """Padded char width for a string column.  max_str_len is an upper
    bound that must dominate the data: truncating the char matrix while
    keeping true lengths would produce silently wrong hashes (and e.g.
    shuffle-partition misrouting), so a too-small value is an error.
    Under jit the offsets are tracers and validation would need a host
    sync, so the bound is trusted there."""
    if max_str_len is None:
        return max(1, col.max_string_length())
    if not isinstance(col.offsets, jax.core.Tracer):
        actual = col.max_string_length()
        if max_str_len < actual:
            raise ValueError(
                f"max_str_len={max_str_len} is smaller than the column's "
                f"longest string ({actual} bytes); refusing to truncate")
    return max(1, max_str_len)


def _hash_element_column(algo, h, col: Column,
                         max_str_len: Optional[int]) -> jnp.ndarray:
    """h' for every row: element hash seeded by h; null rows keep h."""
    kind = col.dtype.kind
    if kind == Kind.STRING:
        pad = _resolve_str_pad(col, max_str_len)
        chars, lens = col.to_padded_chars(pad_to=pad)
        h2 = algo.hash_varbytes(h, chars, lens)
    elif kind == Kind.DECIMAL128:
        be, length = _dec128_min_be_bytes(col.data)
        h2 = algo.hash_varbytes(h, be, length)
    elif kind == Kind.STRUCT:
        h2 = h
        for child in col.children:
            h2 = _hash_element_column(algo, h2, child, max_str_len)
    elif kind == Kind.LIST:
        return _hash_list_column(algo, h, col, max_str_len)
    else:
        blocks, nbytes = _fixed_width_blocks(col, algo)
        h2 = algo.hash_blocks(h, blocks, nbytes)
    if col.validity is not None:
        h2 = jnp.where(col.validity.astype(jnp.bool_), h2, h)
    return h2


def _flatten_list_offsets(col: Column):
    """Descend through nested LIST levels composing offsets; returns
    (leaf_column, start_idx (rows,), count (rows,)) for each top row."""
    assert col.dtype.kind == Kind.LIST
    start = col.offsets[:-1]
    end = col.offsets[1:]
    cur = col.children[0]
    while cur.dtype.kind == Kind.LIST:
        if cur.children[0].dtype.kind == Kind.STRUCT:
            raise ValueError(
                "Cannot compute hash of a table with a LIST of STRUCT "
                "columns.")
        start = cur.offsets[start]
        end = cur.offsets[end]
        cur = cur.children[0]
    if cur.dtype.kind == Kind.STRUCT:
        raise ValueError(
            "Cannot compute hash of a table with a LIST of STRUCT columns.")
    return cur, start, (end - start).astype(_I32)


def _hash_list_column(algo, h, col: Column, max_str_len: Optional[int]):
    """Seed-chained fold over each row's (flattened) leaf elements.

    Vectorized as a masked scan over element position: O(max_row_elems)
    vector steps.  Null elements are skipped (seed passes through), matching
    murmur_hash.cu:135-144.
    """
    leaf, start, count = _flatten_list_offsets(col)
    rows = col.length
    if rows == 0:
        return h
    max_count = int(np.asarray(count).max()) if not isinstance(
        count, jax.core.Tracer) else None
    if max_count is None:
        raise ValueError(
            "hashing LIST columns under jit requires concrete offsets; "
            "call eagerly or provide padded representation")
    if max_count == 0:
        h2 = h
    else:
        leaf_valid = (leaf.validity.astype(jnp.bool_)
                      if leaf.validity is not None else None)
        is_string = leaf.dtype.is_string
        if is_string:
            pad = _resolve_str_pad(leaf, max_str_len)
            leaf_chars_len = leaf.data.shape[0]
        else:
            blocks_all, nbytes = _fixed_width_blocks(leaf, algo)

        h2 = h
        nleaf = max(leaf.length, 1)
        for j in range(max_count):
            idx = jnp.clip(start + j, 0, nleaf - 1)
            active = j < count
            if leaf_valid is not None:
                active = active & leaf_valid[idx]
            if is_string:
                s0 = leaf.offsets[idx]
                lens = leaf.offsets[idx + 1] - s0
                cidx = s0[:, None] + jnp.arange(pad, dtype=_I32)
                in_r = cidx < leaf.offsets[idx + 1][:, None]
                cidx = jnp.clip(cidx, 0, max(leaf_chars_len - 1, 0))
                chars = jnp.where(
                    in_r,
                    leaf.data[cidx] if leaf_chars_len else
                    jnp.zeros_like(cidx, jnp.uint8),
                    jnp.uint8(0))
                hnew = algo.hash_varbytes(h2, chars, lens)
            else:
                blocks = [b[idx] for b in blocks_all]
                hnew = algo.hash_blocks(h2, blocks, nbytes)
            h2 = jnp.where(active, hnew, h2)
    if col.validity is not None:
        h2 = jnp.where(col.validity.astype(jnp.bool_), h2, h)
    return h2


def _hash_cacheable(cols: Sequence[Column]) -> bool:
    """Fixed-width non-nested schemas hash through the compile cache;
    strings/lists/structs/decimal128 need host-derived pads or concrete
    offsets, and tracer inputs mean we are already inside someone
    else's jit (the models' step builders) — both stay eager."""
    for c in cols:
        if c.dtype.kind in (Kind.STRING, Kind.LIST, Kind.STRUCT,
                            Kind.DECIMAL128):
            return False
        if isinstance(c.data, jax.core.Tracer):
            return False
        if c.validity is not None and \
                isinstance(c.validity, jax.core.Tracer):
            return False
    return True


def _run_row_hash_cached(algo, cols: Sequence[Column], seed: int,
                         rows: int) -> Column:
    """Row hash through perf/jit_cache: one executable per (algo,
    schema digest, row bucket).  The seed travels as a traced scalar so
    re-seeding never recompiles; padded tail rows hash to garbage and
    are sliced off."""
    from spark_rapids_tpu.perf import jit_cache as _jc

    name = ("hash.murmur3_32" if algo is _Murmur32 else "hash.xxhash64")
    nullable = tuple(c.validity is not None for c in cols)
    schema_t = tuple(c.dtype for c in cols)
    digest = _jc.schema_digest(schema_t, nullable, extra=name)
    bucket = _jc.bucket_rows(rows)
    datas = tuple(_jc.pad_axis0(c.data, bucket) for c in cols)
    valids = tuple(None if c.validity is None
                   else _jc.pad_axis0(c.validity, bucket) for c in cols)
    if algo.htype == _U32:
        seed_arr = jnp.asarray(np.uint32(seed & 0xFFFFFFFF))
    else:
        seed_arr = jnp.asarray(np.uint64(seed & 0xFFFFFFFFFFFFFFFF))

    def kernel(datas, valids, seed_arr):
        kcols = [Column(dt, bucket, data=d, validity=v)
                 for dt, d, v in zip(schema_t, datas, valids)]
        h = jnp.broadcast_to(seed_arr, (bucket,))
        for c in kcols:
            h = _hash_element_column(algo, h, c, None)
        return algo.finish(h)

    out = _jc.CACHE.cached_call(name, digest, kernel,
                                (datas, valids, seed_arr),
                                bucket=bucket, donate_argnums=(0,))
    return Column(algo.out_dtype, rows, data=out[:rows])


def _run_row_hash(algo, table_or_cols, seed: int,
                  max_str_len: Optional[int]) -> Column:
    cols = _cols(table_or_cols)
    if not cols:
        raise ValueError("need at least one column to hash")
    rows = cols[0].length
    from spark_rapids_tpu.perf import jit_cache as _jc
    if _jc.cache_enabled() and rows > 0 and _hash_cacheable(cols):
        return _run_row_hash_cached(algo, cols, seed, rows)
    h = algo.seed_array(rows, seed)
    for c in cols:
        h = _hash_element_column(algo, h, c, max_str_len)
    return Column(algo.out_dtype, rows, data=algo.finish(h))


# ----------------------------------------------------------- public API


def murmur3_32(table_or_cols, seed: int = 42,
               max_str_len: Optional[int] = None) -> Column:
    """Spark-exact murmur3_32 row hash (reference hash.hpp murmur_hash3_32,
    Hash.java:44 murmurHash32). Returns a non-null INT32 column."""
    return _run_row_hash(_Murmur32, table_or_cols, seed, max_str_len)


def xxhash64(table_or_cols, seed: int = DEFAULT_XXHASH64_SEED,
             max_str_len: Optional[int] = None) -> Column:
    """Spark-exact xxhash64 row hash (reference hash.hpp xx_hash_64,
    Hash.java xxhash64). Returns a non-null INT64 column."""
    return _run_row_hash(_XXHash64, table_or_cols, seed, max_str_len)


# ------------------------------------------------------------- hive hash

_HIVE_FACTOR = _I32(31)


def _hive_element(col: Column, max_str_len: Optional[int]) -> jnp.ndarray:
    """(rows,) int32 hive hash of each element; nulls -> 0
    (hive_hash.cu:42-152)."""
    kind = col.dtype.kind
    d = col.data
    if kind == Kind.BOOL8:
        hv = d.astype(_I32)
    elif kind in (Kind.INT8, Kind.INT16, Kind.INT32, Kind.TIMESTAMP_DAYS):
        hv = d.astype(_I32)
    elif kind == Kind.INT64:
        u = d.astype(_U64)
        hv = ((u >> _U64(32)) ^ u).astype(_U32).astype(_I32)
    elif kind == Kind.FLOAT32:
        # Java floatToIntBits semantics: NaNs canonicalize (hive_hash.cu:114)
        hv = _normalize_nans_f32_bits(d).astype(_I32)
    elif kind == Kind.FLOAT64:
        u = _normalize_nans_f64_bits(d.astype(_U64))  # d is raw bits
        hv = ((u >> _U64(32)) ^ u).astype(_U32).astype(_I32)
    elif kind == Kind.TIMESTAMP_MICROS:
        v = d.astype(_I64)
        ts = lax.div(v, _I64(1000000))          # C-style trunc division
        tns = lax.rem(v, _I64(1000000)) * _I64(1000)
        res = (ts << _I64(30)) | tns
        u = res.astype(_U64)
        hv = ((u >> _U64(32)) ^ u).astype(_U32).astype(_I32)
    elif kind == Kind.STRING:
        pad = _resolve_str_pad(col, max_str_len)
        chars, lens = col.to_padded_chars(pad_to=pad)
        sb = chars.astype(jnp.int8).astype(_I32)

        def body(hacc, xs):
            i, byte = xs
            h2 = hacc * _HIVE_FACTOR + byte
            return jnp.where(i < lens, h2, hacc), None

        p = chars.shape[1]
        hv, _ = lax.scan(body, jnp.zeros((col.length,), _I32),
                         (jnp.arange(p, dtype=_I32), sb.T))
    elif kind == Kind.STRUCT:
        hv = jnp.zeros((col.length,), _I32)
        for child in col.children:
            hv = hv * _HIVE_FACTOR + _hive_element(child, max_str_len)
    elif kind == Kind.LIST:
        # Hive hashes each direct element independently from HIVE_INIT_HASH
        # and folds those hashes (hive_hash.cu col_stack_frame recursion) —
        # nested lists/structs recurse, null elements contribute 0.
        child = col.children[0]
        start = col.offsets[:-1]
        count = (col.offsets[1:] - start).astype(_I32)
        if isinstance(count, jax.core.Tracer):
            raise ValueError(
                "hive_hash of LIST columns under jit requires concrete "
                "offsets; call eagerly")
        max_count = int(np.asarray(count).max()) if col.length else 0
        child_h = (_hive_element(child, max_str_len) if child.length
                   else jnp.zeros((1,), _I32))
        nchild = max(child.length, 1)
        hv = jnp.zeros((col.length,), _I32)
        for j in range(max_count):
            idx = jnp.clip(start + j, 0, nchild - 1)
            h2 = hv * _HIVE_FACTOR + child_h[idx]
            hv = jnp.where(j < count, h2, hv)
    else:
        raise NotImplementedError(f"hive hash of {kind}")
    if col.validity is not None:
        hv = jnp.where(col.validity.astype(jnp.bool_), hv, _I32(0))
    return hv


def hive_hash(table_or_cols, max_str_len: Optional[int] = None) -> Column:
    """Hive-compatible row hash (reference hash.hpp hive_hash): row hash is
    a 31-factor fold of element hashes, null elements contribute 0."""
    cols = _cols(table_or_cols)
    if not cols:
        raise ValueError("need at least one column to hash")
    rows = cols[0].length
    h = jnp.zeros((rows,), _I32)
    for c in cols:
        h = h * _HIVE_FACTOR + _hive_element(c, max_str_len)
    return Column(dtypes.INT32, rows, data=h)
