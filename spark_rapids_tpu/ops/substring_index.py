"""Spark substring_index(str, delim, count) (reference
substring_index.cu/.hpp, GpuSubstringIndexUtils.java).

count > 0: prefix up to (not including) the count-th delimiter occurrence
from the left; count < 0: suffix after the |count|-th occurrence from the
right; count == 0 or empty delimiter: empty string; fewer occurrences
than |count|: whole string.

TPU design: single-byte delimiter matches are a fully vectorized
sliding-window equality over the padded char matrix with a cumulative
match count.  Multi-byte delimiters additionally need non-overlapping
match suppression, which currently runs as a host pass over the match
matrix (directional: left-to-right for count>0, right-to-left for
count<0 to match Spark's indexOf/lastIndexOf semantics)."""

from __future__ import annotations

from typing import Union

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column

_I32 = jnp.int32
_U8 = jnp.uint8


def substring_index(col: Column, delimiter: Union[str, bytes],
                    count: int) -> Column:
    assert col.dtype.is_string
    rows = col.length
    delim = delimiter.encode("utf-8") if isinstance(delimiter, str) \
        else bytes(delimiter)
    d = len(delim)
    mask_host = (np.ones(rows, bool) if col.validity is None
                 else np.asarray(col.validity).astype(bool))
    if rows == 0 or count == 0 or d == 0:
        return Column.from_strings(
            ["" if mask_host[i] else None for i in range(rows)])

    chars, lens = col.to_padded_chars()
    p = chars.shape[1]
    if p < d:
        # no row can contain the delimiter: whole strings
        keep_len = lens
    else:
        # match[i, j]: delim starts at byte j (non-overlapping scan not
        # needed — Spark counts overlapping occurrences left-to-right is
        # moot for distinct delimiters; the reference scans forward past
        # each full match, so suppress overlaps within d bytes)
        m = jnp.ones((rows, p - d + 1), jnp.bool_)
        for k, b in enumerate(delim):
            m = m & (chars[:, k:p - d + 1 + k] == _U8(b))
        valid_start = jnp.arange(p - d + 1, dtype=_I32)[None, :] <= \
            (lens - d)[:, None]
        m = m & valid_start
        # suppress overlapping matches. Direction matters for
        # self-overlapping delimiters: Spark scans with indexOf from the
        # left for count>0 but lastIndexOf from the right for count<0
        # (substring_index.cu rfind loop)
        if d > 1:
            # greedy non-overlap suppression, vectorized across rows:
            # one sweep over positions with a per-row "suppressed
            # until" cursor (directional per Spark indexOf/lastIndexOf)
            mh = np.asarray(m)
            P = mh.shape[1]
            kept = np.zeros_like(mh)
            if count > 0:
                until = np.zeros(rows, np.int64)
                for j in range(P):
                    k = mh[:, j] & (j >= until)
                    kept[:, j] = k
                    until = np.where(k, j + d, until)
            else:
                until = np.full(rows, P, np.int64)
                for j in range(P - 1, -1, -1):
                    k = mh[:, j] & (j < until)
                    kept[:, j] = k
                    until = np.where(k, j - d + 1, until)
            m = jnp.asarray(kept)
        cum = jnp.cumsum(m.astype(_I32), axis=1)
        total = cum[:, -1] if p >= d else jnp.zeros(rows, _I32)
        if count > 0:
            # cut before the count-th occurrence
            hit = (m & (cum == count))
            # position of that occurrence (or len if fewer)
            pos = jnp.where(
                hit.any(axis=1),
                jnp.argmax(hit, axis=1).astype(_I32), lens)
            keep_len = jnp.minimum(pos, lens)
        else:
            k = -count
            # keep everything after the (total-k+1)-th occurrence
            target = total - k + 1
            hit = (m & (cum == jnp.maximum(target, 1)[:, None]))
            start = jnp.where(
                (total >= k) & hit.any(axis=1),
                jnp.argmax(hit, axis=1).astype(_I32) + d, 0)
            keep_len = lens - start
            # gather suffix: build shifted char matrix
            idx = start[:, None] + jnp.arange(p, dtype=_I32)[None, :]
            in_r = idx < lens[:, None]
            idx = jnp.clip(idx, 0, p - 1)
            chars = jnp.where(in_r, jnp.take_along_axis(chars, idx, axis=1),
                              _U8(0))

    # rebuild string column from per-row prefixes of `chars` — numpy
    # flat gather (the jnp 2D fancy gather lowers to a scalar loop on
    # the CPU backend; this was the pathological path flagged in r1)
    keep_host = np.asarray(keep_len)
    keep_host = np.where(mask_host, np.maximum(keep_host, 0), 0)
    new_offs = np.concatenate(
        [[0], np.cumsum(keep_host)]).astype(np.int32)
    total_chars = int(new_offs[-1])
    if total_chars:
        chars_np = np.asarray(chars)
        i_flat = np.arange(total_chars)
        r = np.searchsorted(new_offs, i_flat, side="right") - 1
        cpos = i_flat - new_offs[r]
        data = jnp.asarray(chars_np[r, cpos])
    else:
        data = jnp.zeros(0, jnp.uint8)
    return Column(dtypes.STRING, rows, data=data,
                  validity=col.validity,
                  offsets=jnp.asarray(new_offs))
