"""Overflow-safe 64-bit SUM building blocks (reference
aggregation64_utils.hpp/.cu, Aggregation64Utils.java): split int64 values
into 32-bit chunks, sum the chunks as int64 (no overflow for < 2^32 rows),
then reassemble with carry propagation and overflow detection."""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType

_I64 = jnp.int64
_U64 = jnp.uint64


def extract_chunk32_from_64bit(col: Column, dtype: DType,
                               chunk_idx: int) -> Column:
    """Chunk 0 = least-significant 32 bits (as UINT32-valued numbers),
    chunk 1 = most-significant (signed).  Output in `dtype` (UINT32/INT32
    per the reference, but any integer dtype wide enough works)."""
    if chunk_idx not in (0, 1):
        raise ValueError("chunk_idx must be 0 or 1")
    v = col.data.astype(_I64)
    if chunk_idx == 0:
        chunk = (v.astype(_U64) & _U64(0xFFFFFFFF)).astype(_I64)
    else:
        chunk = v >> _I64(32)  # arithmetic: keeps sign
    return Column(dtype, col.length,
                  data=chunk.astype(dtype.np_dtype),
                  validity=col.validity)


def assemble64_from_sum(low_sums: Column, high_sums: Column,
                        output_dtype: DType = dtypes.INT64):
    """(overflow BOOL8 column, value column): value = low + (high << 32)
    where low's upper bits carry into high (aggregation64_utils.hpp:52).
    Overflow when the true sum does not fit in 64 bits signed."""
    low = low_sums.data.astype(_I64)
    high = high_sums.data.astype(_I64)
    carry = low >> _I64(32)           # arithmetic shift: signed carry
    low32 = low.astype(_U64) & _U64(0xFFFFFFFF)
    total_high = high + carry         # sum of high chunks + carry
    # the final value uses total_high's low 32 bits as bits 32..63
    value = (low32 | (total_high.astype(_U64) << _U64(32))).astype(_I64)
    # overflow iff total_high isn't a sign extension of value's bit 63:
    # total_high must equal value >> 32 (arithmetic)
    overflow = total_high != (value >> _I64(32))
    validity = None
    if low_sums.validity is not None or high_sums.validity is not None:
        validity = (low_sums.valid_mask()
                    & high_sums.valid_mask()).astype(jnp.uint8)
    ovf_col = Column(dtypes.BOOL8, low_sums.length,
                     data=overflow.astype(jnp.uint8), validity=validity)
    val_col = Column(output_dtype, low_sums.length,
                     data=value.astype(output_dtype.np_dtype),
                     validity=validity)
    return ovf_col, val_col
