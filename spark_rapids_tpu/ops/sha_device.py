"""Device SHA-2: lane-per-row vectorized SHA-224/256/384/512.

Reference: src/main/cpp/src/hash/sha.cpp delegates to cudf's device SHA
(one thread per row); here every row is a vector lane and the block loop
is a lax.scan — the same shape as ops/hash.py's xxhash64 block scan.

Message padding (0x80, zero fill, 8/16-byte big-endian bit length) is
materialized as a (rows, max_blocks*B) byte matrix with closed-form
selects, then packed big-endian into 32/64-bit schedule words.  Rows
with fewer blocks than max_blocks mask their state updates off once
their block count is reached, so mixed-length columns hash correctly in
one pass.  Output is the lowercase hex digest as a strings column,
matching hashlib/cudf byte-for-byte (tests/test_sha_device.py runs the
hashlib differential).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column

_U8 = jnp.uint8
_U32 = jnp.uint32
_U64 = jnp.uint64
_I32 = jnp.int32

_K256 = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
    0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
    0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
    0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
    0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
    0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
    0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
    0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
    0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], np.uint32)

_IV256 = np.array([0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19],
                  np.uint32)
_IV224 = np.array([0xc1059ed8, 0x367cd507, 0x3070dd17, 0xf70e5939,
                   0xffc00b31, 0x68581511, 0x64f98fa7, 0xbefa4fa4],
                  np.uint32)

_K512 = np.array([
    0x428a2f98d728ae22, 0x7137449123ef65cd, 0xb5c0fbcfec4d3b2f,
    0xe9b5dba58189dbbc, 0x3956c25bf348b538, 0x59f111f1b605d019,
    0x923f82a4af194f9b, 0xab1c5ed5da6d8118, 0xd807aa98a3030242,
    0x12835b0145706fbe, 0x243185be4ee4b28c, 0x550c7dc3d5ffb4e2,
    0x72be5d74f27b896f, 0x80deb1fe3b1696b1, 0x9bdc06a725c71235,
    0xc19bf174cf692694, 0xe49b69c19ef14ad2, 0xefbe4786384f25e3,
    0x0fc19dc68b8cd5b5, 0x240ca1cc77ac9c65, 0x2de92c6f592b0275,
    0x4a7484aa6ea6e483, 0x5cb0a9dcbd41fbd4, 0x76f988da831153b5,
    0x983e5152ee66dfab, 0xa831c66d2db43210, 0xb00327c898fb213f,
    0xbf597fc7beef0ee4, 0xc6e00bf33da88fc2, 0xd5a79147930aa725,
    0x06ca6351e003826f, 0x142929670a0e6e70, 0x27b70a8546d22ffc,
    0x2e1b21385c26c926, 0x4d2c6dfc5ac42aed, 0x53380d139d95b3df,
    0x650a73548baf63de, 0x766a0abb3c77b2a8, 0x81c2c92e47edaee6,
    0x92722c851482353b, 0xa2bfe8a14cf10364, 0xa81a664bbc423001,
    0xc24b8b70d0f89791, 0xc76c51a30654be30, 0xd192e819d6ef5218,
    0xd69906245565a910, 0xf40e35855771202a, 0x106aa07032bbd1b8,
    0x19a4c116b8d2d0c8, 0x1e376c085141ab53, 0x2748774cdf8eeb99,
    0x34b0bcb5e19b48a8, 0x391c0cb3c5c95a63, 0x4ed8aa4ae3418acb,
    0x5b9cca4f7763e373, 0x682e6ff3d6b2b8a3, 0x748f82ee5defb2fc,
    0x78a5636f43172f60, 0x84c87814a1f0ab72, 0x8cc702081a6439ec,
    0x90befffa23631e28, 0xa4506cebde82bde9, 0xbef9a3f7b2c67915,
    0xc67178f2e372532b, 0xca273eceea26619c, 0xd186b8c721c0c207,
    0xeada7dd6cde0eb1e, 0xf57d4f7fee6ed178, 0x06f067aa72176fba,
    0x0a637dc5a2c898a6, 0x113f9804bef90dae, 0x1b710b35131c471b,
    0x28db77f523047d84, 0x32caab7b40c72493, 0x3c9ebe0a15c9bebc,
    0x431d67c49c100d4c, 0x4cc5d4becb3e42b6, 0x597f299cfc657e2a,
    0x5fcb6fab3ad6faec, 0x6c44198c4a475817], np.uint64)

_IV512 = np.array([0x6a09e667f3bcc908, 0xbb67ae8584caa73b,
                   0x3c6ef372fe94f82b, 0xa54ff53a5f1d36f1,
                   0x510e527fade682d1, 0x9b05688c2b3e6c1f,
                   0x1f83d9abfb41bd6b, 0x5be0cd19137e2179], np.uint64)
_IV384 = np.array([0xcbbb9d5dc1059ed8, 0x629a292a367cd507,
                   0x9159015a3070dd17, 0x152fecd8f70e5939,
                   0x67332667ffc00b31, 0x8eb44a8768581511,
                   0xdb0c2e0d64f98fa7, 0x47b5481dbefa4fa4], np.uint64)


def _padded_message(chars: jnp.ndarray, lens: jnp.ndarray,
                    block_bytes: int, len_bytes: int
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(rows, nblocks*B) padded message matrix + (rows,) block counts."""
    rows, L = chars.shape
    maxblocks = max((L + len_bytes + 1 + block_bytes - 1) // block_bytes,
                    1)
    total = maxblocks * block_bytes
    nblk = (lens + len_bytes + 1 + block_bytes - 1) // block_bytes
    blk_end = nblk * block_bytes                     # (rows,)
    j = jnp.arange(total, dtype=_I32)[None, :]
    body = jnp.concatenate(
        [chars, jnp.zeros((rows, total - L), _U8)], axis=1)
    msg = jnp.where(j < lens[:, None], body, _U8(0))
    msg = jnp.where(j == lens[:, None], _U8(0x80), msg)
    # big-endian bit length in the trailing len_bytes of the last block
    bitlen = (lens.astype(_U64) * _U64(8))
    lpos = j - (blk_end[:, None] - len_bytes)        # 0..len_bytes-1
    in_len = (lpos >= 0) & (j < blk_end[:, None])
    shift = ((len_bytes - 1 - lpos).astype(_U64) * _U64(8))
    # shifts >= 64 are undefined in XLA (hardware may mask the amount):
    # for the 16-byte SHA-384/512 length field only the low 8 bytes can
    # be nonzero for a 64-bit bit length — force the rest to 0
    shift_ok = in_len & (shift < _U64(64))
    lbyte = jnp.where(
        shift_ok,
        (bitlen[:, None] >> jnp.where(shift_ok, shift, _U64(0)))
        & _U64(0xFF), _U64(0)).astype(_U8)
    msg = jnp.where(in_len & (j >= lens[:, None] + 1), lbyte, msg)
    return msg, nblk


def _rotr32(x, n):
    return (x >> _U32(n)) | (x << _U32(32 - n))


def _rotr64(x, n):
    return (x >> _U64(n)) | (x << _U64(64 - n))


def _sha2_core(chars, lens, iv, *, bits64: bool):
    """Shared SHA-256/512 compression: outer scan over message blocks,
    inner scan over rounds with a 16-word sliding schedule window (a
    fully-unrolled round graph makes LLVM compile time explode; the
    two-level scan keeps the body ~20 ops)."""
    rows = chars.shape[0]
    if bits64:
        B, LB, NR, dt = 128, 16, 80, _U64
        K = jnp.asarray(_K512)
        r1, r2, r3 = (1, 8, 7), (19, 61, 6), (14, 18, 41)
        r0 = (28, 34, 39)
        rot, width = _rotr64, 64
    else:
        B, LB, NR, dt = 64, 8, 64, _U32
        K = jnp.asarray(_K256)
        r1, r2, r3 = (7, 18, 3), (17, 19, 10), (6, 11, 25)
        r0 = (2, 13, 22)
        rot, width = _rotr32, 32
    msg, nblk = _padded_message(chars, lens, B, LB)
    maxblocks = msg.shape[1] // B
    nbw = B // 16                                  # bytes per word
    w8 = msg.reshape(rows, maxblocks, 16, nbw).astype(dt)
    words = jnp.zeros(w8.shape[:3], dt)
    for k in range(nbw):
        words = words | (w8[..., k] << dt(8 * (nbw - 1 - k)))
    words = jnp.moveaxis(words, 1, 0)              # (blocks, rows, 16)
    state0 = tuple(jnp.full(rows, iv[i], dt) for i in range(8))
    ts = jnp.arange(NR, dtype=_I32)

    def block(carry, wblk):
        state, b = carry
        win0 = jnp.zeros((rows, 16), dt)
        # first 16 w's come from the block; later ones from the window
        w_in = jnp.concatenate(
            [wblk.T, jnp.zeros((NR - 16, rows), dt)], axis=0)

        def rnd(c, xs):
            (a, bb, cc, d, e, f, g, h, win) = c
            k_t, w0_t, t = xs
            wm16, wm15 = win[:, 0], win[:, 1]
            wm7, wm2 = win[:, 9], win[:, 14]
            s0 = rot(wm15, r1[0]) ^ rot(wm15, r1[1]) \
                ^ (wm15 >> dt(r1[2]))
            s1 = rot(wm2, r2[0]) ^ rot(wm2, r2[1]) \
                ^ (wm2 >> dt(r2[2]))
            w_t = jnp.where(t < 16, w0_t, wm16 + s0 + wm7 + s1)
            S1 = rot(e, r3[0]) ^ rot(e, r3[1]) ^ rot(e, r3[2])
            ch = (e & f) ^ (~e & g)
            t1 = h + S1 + ch + k_t + w_t
            S0 = rot(a, r0[0]) ^ rot(a, r0[1]) ^ rot(a, r0[2])
            maj = (a & bb) ^ (a & cc) ^ (bb & cc)
            t2 = S0 + maj
            win = jnp.concatenate([win[:, 1:], w_t[:, None]], axis=1)
            return (t1 + t2, a, bb, cc, d + t1, e, f, g, win), None

        init = state + (win0,)
        out, _ = lax.scan(rnd, init, (K, w_in, ts))
        upd = (b < nblk)
        new = tuple(jnp.where(upd, s + n, s)
                    for s, n in zip(state, out[:8]))
        return (new, b + 1), None

    (state, _), _ = lax.scan(block, (state0, jnp.zeros((), _I32)),
                             words)
    return state


_HEX = jnp.asarray(np.frombuffer(b"0123456789abcdef", np.uint8))


def _hex_column(state, word_bits: int, out_words: int,
                validity) -> Column:
    """8/6/4-word big-endian state -> lowercase hex strings column."""
    rows = state[0].shape[0]
    nbytes_per_word = word_bits // 8
    digest_bytes = out_words * nbytes_per_word
    cols = []
    for wi in range(out_words):
        wv = state[wi]
        for k in range(nbytes_per_word):
            shift = (nbytes_per_word - 1 - k) * 8
            byte = ((wv >> wv.dtype.type(shift))
                    & wv.dtype.type(0xFF)).astype(_I32)
            cols.append(_HEX[byte >> 4])
            cols.append(_HEX[byte & 0xF])
    hexmat = jnp.stack(cols, axis=1)          # (rows, digest_bytes*2)
    n = digest_bytes * 2
    if validity is None:
        data = hexmat.reshape(rows * n)
        offs = jnp.arange(rows + 1, dtype=_I32) * n
        return Column(dtypes.STRING, rows, data=data, offsets=offs)
    vmask = np.asarray(validity).astype(bool)[:rows]
    lens = np.where(vmask, n, 0).astype(np.int64)
    offs = np.zeros(rows + 1, np.int32)
    np.cumsum(lens, out=offs[1:])
    keep = jnp.asarray(np.repeat(vmask, n) if rows else
                       np.zeros(0, bool))
    data = hexmat.reshape(rows * n)[keep] if rows else \
        jnp.zeros(0, _U8)
    return Column(dtypes.STRING, rows, data=data,
                  validity=jnp.asarray(vmask.astype(np.uint8)),
                  offsets=jnp.asarray(offs))


def _le_bytes(vals, itemsize: int):
    """Little-endian byte planes of an unsigned integer array."""
    out = []
    for k in range(itemsize):
        out.append(((vals >> vals.dtype.type(8 * k))
                    & vals.dtype.type(0xFF)).astype(_U8))
    return out


def _col_bytes_matrix(col: Column):
    """(rows, L) byte matrix + lengths for string or fixed-width input
    (fixed-width rows hash their little-endian storage bytes, matching
    numpy .tobytes() and cudf's byte-wise SHA of the element).  Floats
    hash their IEEE-754 bit patterns (FLOAT64 data already carries raw
    uint64 bits per the Column convention; FLOAT32 is bit-cast here)."""
    from jax import lax as _lax

    if col.dtype.is_string:
        return col.to_padded_chars()
    rows = col.length
    kind = col.dtype.kind
    if kind == dtypes.Kind.DECIMAL128:
        # (rows, 4) int32 LE limbs -> 16 LE bytes, limb 0 first
        limbs = col.data.astype(jnp.uint32)
        planes = []
        for limb in range(4):
            planes.extend(_le_bytes(limbs[:, limb], 4))
        return (jnp.stack(planes, axis=1),
                jnp.full(rows, 16, _I32))
    data = col.data
    if kind == dtypes.Kind.FLOAT32:
        data = _lax.bitcast_convert_type(data, jnp.uint32)
    itemsize = np.dtype(col.dtype.np_dtype).itemsize
    vals = data.astype({1: jnp.uint8, 2: jnp.uint16,
                        4: jnp.uint32, 8: jnp.uint64}[itemsize])
    chars = jnp.stack(_le_bytes(vals, itemsize), axis=1)
    lens = jnp.full(rows, itemsize, _I32)
    return chars, lens


_SPECS = {224: (_IV224, False, 32, 7), 256: (_IV256, False, 32, 8),
          384: (_IV384, True, 64, 6), 512: (_IV512, True, 64, 8)}


@functools.partial(jax.jit, static_argnames=("bits",))
def _sha_jit(chars, lens, bits: int):
    iv, bits64, _, _ = _SPECS[bits]
    return _sha2_core(chars, lens, iv, bits64=bits64)


def _sha_device(col: Column, bits: int) -> Column:
    chars, lens = _col_bytes_matrix(col)
    _, _, word_bits, out_words = _SPECS[bits]
    state = _sha_jit(chars, lens, bits)
    return _hex_column(state, word_bits, out_words, col.validity)


def sha224_device(col: Column) -> Column:
    return _sha_device(col, 224)


def sha256_device(col: Column) -> Column:
    return _sha_device(col, 256)


def sha384_device(col: Column) -> Column:
    return _sha_device(col, 384)


def sha512_device(col: Column) -> Column:
    return _sha_device(col, 512)
