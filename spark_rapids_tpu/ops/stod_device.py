"""Device string->float conversion (reference cast_string_to_float.cu:
57-235 — device correctly-rounded strtod).

Two vectorized stages, with a per-row host fallback for the rare
ambiguous cases (the same device-first/host-oracle split as
ops/json_device.py):

  1. a lax.scan DFA over the padded char axis parses sign, mantissa
     (first 19 significant digits into one u64 lane), decimal point,
     exponent, and the Spark validity rules; inf/nan keywords are
     matched by direct padded-window compares before the scan.
  2. the Eisel-Lemire algorithm converts (w, q) -> IEEE bits in pure
     u64 integer ops: normalize w, one 64x64->128 multiply with a
     128-bit-truncated power-of-ten significand (table generated at
     import with exact big-int arithmetic), exponent bookkeeping, and
     round-half-even with explicit ambiguity detection.  Integer-only
     is the natural fit here: this backend carries f64 as raw bits.

Fallback rows (truncated >19-digit mantissas, results in the subnormal
range, products whose low bits make rounding ambiguous, possible
round-even ties) are converted by the host libc path — bit-exact by
construction, and rare (<<1% of random inputs).
"""

from __future__ import annotations

import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import DType, Kind

_U64 = jnp.uint64
_U8 = jnp.uint8
_I32 = jnp.int32

DEVICE_MIN_ROWS = int(os.environ.get("SPARK_RAPIDS_TPU_STOD_MIN_ROWS",
                                     32))

Q_MIN, Q_MAX = -342, 308


def use_device(col: Column) -> bool:
    mode = os.environ.get("SPARK_RAPIDS_TPU_STOD", "auto")
    if mode == "host":
        return False
    return mode == "device" or col.length >= DEVICE_MIN_ROWS


def _gen_pow10_hi() -> np.ndarray:
    """Top 64 bits of the normalized 128-bit significand of 10^q for
    q in [Q_MIN, Q_MAX] (truncated, as Eisel-Lemire expects)."""
    out = np.zeros(Q_MAX - Q_MIN + 1, np.uint64)
    for q in range(Q_MIN, Q_MAX + 1):
        if q >= 0:
            v = 10 ** q
            shift = v.bit_length() - 128
            t = v >> shift if shift >= 0 else v << -shift
        else:
            # floor(2^k / 10^-q) normalized to 128 bits
            d = 10 ** (-q)
            k = d.bit_length() + 127
            t = (1 << k) // d
            if t.bit_length() == 129:   # can land one bit high
                t >>= 1
        assert t.bit_length() == 128
        out[q - Q_MIN] = t >> 64
    return out


_POW10_HI = _gen_pow10_hi()


from spark_rapids_tpu.utils.u64math import clz64 as _clz64  # noqa: E402
from spark_rapids_tpu.utils.u64math import umul128 as _umul128  # noqa: E402


def _eisel_lemire(w, q, is_f32: bool):
    """(bits u64 without sign, ok bool).  ok=False rows need the host
    fallback; w==0 handled by the caller."""
    mant_bits = 23 if is_f32 else 52
    kept = mant_bits + 2                     # mantissa + round bit
    drop = 64 - kept - 1                     # shift when upperbit==0
    exp_bias = 127 if is_f32 else 1023
    exp_max = 255 if is_f32 else 2047

    in_range = (q >= Q_MIN) & (q <= Q_MAX)
    qc = jnp.clip(q, Q_MIN, Q_MAX)
    t_hi = jnp.asarray(_POW10_HI)[qc - Q_MIN]
    l = _clz64(w)
    wn = w << (l.astype(_U64) & _U64(63))
    lo, hi = _umul128(wn, t_hi)
    upper = (hi >> _U64(63)).astype(_I32)
    s = hi >> (upper + drop).astype(_U64)    # kept+1 bits incl. round
    # floor(q*log2(10)): exact for |q| <= 1650; +63 accounts for the
    # [2^63, 2^64) normalization of both operands
    powq = ((217706 * qc) >> 16) + 63
    e = powq + upper - l + exp_bias
    m = (s + (s & _U64(1))) >> _U64(1)       # round half up (ties fixed
    #                                          below / via fallback)
    carried = m >> _U64(mant_bits + 1) != 0
    m = jnp.where(carried, m >> _U64(1), m)
    e = e + carried.astype(_I32)

    # bits strictly below the round bit: drop of them when upperbit=0,
    # drop+1 when upperbit=1.  All-ones -> the truncated table may hide
    # a carry (ambiguous); all-zeros with round=1, kept-lsb=0 and a zero
    # low product word -> possible exact half (tie).  Both fall back.
    low_mask = (_U64(1) << (drop + upper).astype(_U64)) - _U64(1)
    ambiguous = (hi & low_mask) == low_mask
    tie = ((s & _U64(3)) == _U64(1)) & (lo == 0) \
        & ((hi & low_mask) == 0)
    subnormal = e <= 0
    overflow = e >= exp_max
    ok = in_range & ~ambiguous & ~tie & ~subnormal & ~overflow
    bits = (m & _U64((1 << mant_bits) - 1)) \
        | (jnp.clip(e, 0, exp_max).astype(_U64) << _U64(mant_bits))
    # out-of-range exponents resolve exactly: q too small -> 0,
    # q too large -> inf
    bits = jnp.where(q < Q_MIN, _U64(0), bits)
    bits = jnp.where(q > Q_MAX,
                     _U64(exp_max) << _U64(mant_bits), bits)
    ok = ok | (q < Q_MIN) | (q > Q_MAX)
    return bits, ok


# ------------------------------------------------------------- parsing


def _is_ws(c):
    return (c <= _U8(0x20)) & ((c <= _U8(0x1F)) | (c == _U8(0x20)))


def _lower(c):
    return jnp.where((c >= _U8(65)) & (c <= _U8(90)), c + _U8(32), c)


@jax.jit
def _parse_scan(chars, start, end):
    """Numeric grammar DFA over the char axis (python float grammar
    minus '_': [sign] (d+[.d*] | .d+) [eE [sign] d+]).  Returns
    mantissa/exponent lanes + flags."""
    rows, L = chars.shape
    S_SIGN, S_INT, S_FRAC, S_ESIGN, S_EXP, S_BAD = 0, 1, 2, 3, 4, 5

    def body(carry, j):
        (st, mant, nsig, frac_kept, int_drop, dropped_nz, exp, eneg,
         neg, saw_digit, saw_edigit) = carry
        c = chars[:, j]
        active = (j >= start) & (j < end)
        digit = (c >= _U8(48)) & (c <= _U8(57))
        d = (c - _U8(48)).astype(_U64)
        is_dot = c == _U8(46)
        is_e = (_lower(c) == _U8(101))
        is_sign = (c == _U8(43)) | (c == _U8(45))

        # transitions
        ns = st
        ns = jnp.where((st == S_SIGN) & is_sign, S_INT, ns)
        ns = jnp.where((st == S_SIGN) & digit, S_INT, ns)
        ns = jnp.where((st == S_SIGN) & is_dot, S_FRAC, ns)
        ns = jnp.where((st == S_INT) & is_dot, S_FRAC, ns)
        ns = jnp.where((st == S_INT) & is_e & saw_digit, S_ESIGN, ns)
        ns = jnp.where((st == S_FRAC) & is_e & saw_digit, S_ESIGN, ns)
        ns = jnp.where((st == S_ESIGN) & (is_sign | digit), S_EXP, ns)
        bad = ((st == S_SIGN) & ~(is_sign | digit | is_dot)) \
            | ((st == S_INT) & ~(digit | is_dot | (is_e & saw_digit))) \
            | ((st == S_FRAC) & ~(digit | (is_e & saw_digit))) \
            | ((st == S_ESIGN) & ~(is_sign | digit)) \
            | ((st == S_EXP) & ~digit)
        ns = jnp.where(bad, S_BAD, ns)
        ns = jnp.where(active, ns, st)

        in_mant = active & digit & ((st == S_SIGN) | (st == S_INT)
                                    | (st == S_FRAC))
        sig = in_mant & ((mant != _U64(0)) | (d != _U64(0)))
        keep = sig & (nsig < 19)
        mant = jnp.where(keep, mant * _U64(10) + d, mant)
        nsig = nsig + sig.astype(_I32)
        frac_kept = frac_kept + (keep & (st == S_FRAC)).astype(_I32)
        int_drop = int_drop + (sig & ~keep
                               & (st != S_FRAC)).astype(_I32)
        dropped_nz = dropped_nz | (sig & ~keep & (d != _U64(0)))
        # leading zeros in the fraction scale the exponent even though
        # they are not significant
        frac_kept = frac_kept + ((st == S_FRAC) & in_mant & ~sig
                                 ).astype(_I32)
        saw_digit = saw_digit | in_mant
        neg = neg | (active & (st == S_SIGN) & (c == _U8(45)))
        eneg = eneg | (active & (st == S_ESIGN) & (c == _U8(45)))
        in_exp = active & digit & ((st == S_ESIGN) | (st == S_EXP))
        exp = jnp.where(in_exp,
                        jnp.minimum(exp * 10 + d.astype(_I32), 100000),
                        exp)
        saw_edigit = saw_edigit | in_exp
        return (ns, mant, nsig, frac_kept, int_drop, dropped_nz, exp,
                eneg, neg, saw_digit, saw_edigit), None

    z64 = jnp.zeros(rows, _U64)
    zi = jnp.zeros(rows, _I32)
    zb = jnp.zeros(rows, jnp.bool_)
    init = (zi, z64, zi, zi, zi, zb, zi, zb, zb, zb, zb)
    (st, mant, nsig, frac_kept, int_drop, dropped_nz, exp, eneg, neg,
     saw_digit, saw_edigit), _ = jax.lax.scan(
        body, init, jnp.arange(L, dtype=_I32))
    # terminal validity: digits seen, not stuck in a bad/e-dangling state
    valid = saw_digit & (st != 5) \
        & ~((st == 3) | ((st == 4) & ~saw_edigit))
    q = jnp.where(eneg, -exp, exp) + int_drop - frac_kept
    return mant, q, neg, valid, nsig, dropped_nz


@jax.jit
def _strip_bounds(chars, lens):
    rows, L = chars.shape
    j = jnp.arange(L, dtype=_I32)[None, :]
    inrow = j < lens[:, None]
    ws = _is_ws(chars) | ~inrow
    nonws = ~ws
    any_nonws = nonws.any(axis=1)
    start = jnp.argmax(nonws, axis=1).astype(_I32)
    end = (L - jnp.argmax(nonws[:, ::-1], axis=1)).astype(_I32)
    return jnp.where(any_nonws, start, 0), \
        jnp.where(any_nonws, end, 0)


@jax.jit
def _keyword_scan(chars, start, end):
    """(is_inf, is_nan, kw_neg, kw_signed) after optional sign at
    start: 'inf'/'infinity'/'nan' case-insensitive."""
    rows, L = chars.shape

    def char_at(pos):
        p = jnp.clip(pos, 0, L - 1)
        return _lower(chars[jnp.arange(rows), p])

    c0 = char_at(start)
    signed = (c0 == _U8(43)) | (c0 == _U8(45))
    kw_neg = c0 == _U8(45)
    s = start + signed.astype(_I32)
    n = end - s

    def matches(word: bytes):
        m = n == len(word)
        for k, ch in enumerate(word):
            m = m & (char_at(s + k) == _U8(ch))
        return m

    is_inf = matches(b"inf") | matches(b"infinity")
    is_nan = matches(b"nan")
    return is_inf, is_nan, kw_neg, signed


@jax.jit
def _narrow_to_f32(bits64):
    """f64 bits -> f32 bits, round-half-even, in exact integer ops
    (the same narrowing the host path applies after its f64 parse, so
    both paths double-round identically).  Subnormal f32 results are
    flagged for the host fallback."""
    exp64 = ((bits64 >> _U64(52)) & _U64(0x7FF)).astype(_I32)
    mant = bits64 & _U64((1 << 52) - 1)
    sign = (bits64 >> _U64(63)) << _U64(31)
    is_special = exp64 == 0x7FF                    # inf / nan
    e32 = exp64 - 1023 + 127
    m53 = mant | _U64(1 << 52)
    dropped = m53 & _U64((1 << 29) - 1)
    m24 = m53 >> _U64(29)
    half = _U64(1 << 28)
    round_up = (dropped > half) | ((dropped == half)
                                   & ((m24 & _U64(1)) == _U64(1)))
    m24 = m24 + round_up.astype(_U64)
    carried = m24 >> _U64(24) != 0
    m24 = jnp.where(carried, m24 >> _U64(1), m24)
    e32 = e32 + carried.astype(_I32)
    overflow = (e32 >= 255) & ~is_special
    # f32-subnormal results AND f64-subnormal inputs go to the fallback:
    # the clip-to-1 + forced hidden bit below would fabricate a normal f32
    # for an exp64==0 input, so such rows must never take the device value.
    need_fb = ((e32 <= 0) & (exp64 != 0)) | ((exp64 == 0) & (mant != _U64(0)))
    out = (m24 & _U64((1 << 23) - 1)) \
        | (jnp.clip(e32, 1, 254).astype(_U64) << _U64(23))
    out = jnp.where(overflow, _U64(0xFF) << _U64(23), out)
    out = jnp.where(is_special,
                    (_U64(0xFF) << _U64(23))
                    | jnp.where(mant != 0, _U64(1 << 22), _U64(0)),
                    out)
    out = jnp.where((exp64 == 0) & (mant == _U64(0)), _U64(0), out)
    need_fb = need_fb & ~is_special
    return out | sign, need_fb


def string_to_float_device(col: Column, dtype: DType,
                           ansi_mode: bool = False) -> Column:
    """Device path of cast_string.string_to_float (same output)."""
    from spark_rapids_tpu.ops.cast_string import _float_host_rows

    assert col.dtype.is_string
    rows = col.length
    is_f32 = dtype.kind == Kind.FLOAT32
    chars, lens = col.to_padded_chars()
    if chars.shape[1] == 0:
        chars = jnp.zeros((rows, 1), jnp.uint8)
    start, end = _strip_bounds(chars, lens)
    empty = end <= start
    is_inf, is_nan, kw_neg, kw_signed = _keyword_scan(chars, start, end)
    mant, q, neg, valid, nsig, dropped_nz = _parse_scan(
        chars, start, end)

    bits, ok = _eisel_lemire(mant, q, False)
    need_fb = valid & ~ok & (mant != _U64(0))
    need_fb = need_fb | (valid & dropped_nz)

    bits = jnp.where(mant == _U64(0), _U64(0), bits)
    inf_bits = _U64(0x7FF) << _U64(52)
    nan_bits = inf_bits | (_U64(1) << _U64(51))
    bits = jnp.where(is_inf, inf_bits, bits)
    # Spark rejects signed NaN but accepts signed Infinity
    bits = jnp.where(is_nan & ~kw_signed, nan_bits, bits)
    out_valid = (valid | is_inf | (is_nan & ~kw_signed)) & ~empty
    bits = bits | (jnp.where(neg | (is_inf & kw_neg), _U64(1), _U64(0))
                   << _U64(63))
    if is_f32:
        bits, narrow_fb = _narrow_to_f32(bits)
        need_fb = need_fb | (valid & narrow_fb)

    bits_np = np.asarray(bits)
    valid_np = np.asarray(out_valid) \
        & np.asarray(col.valid_mask()).astype(bool)
    fb_np = np.asarray(need_fb) & valid_np

    if fb_np.any():
        fb_idx = np.nonzero(fb_np)[0]
        host_bits, host_ok = _float_host_rows(col, fb_idx, is_f32)
        bits_np = bits_np.copy()
        bits_np[fb_idx] = host_bits
        valid_np[fb_idx] = host_ok

    if is_f32:
        data = jnp.asarray(
            bits_np.astype(np.uint32).view(np.float32))
    else:
        data = jnp.asarray(bits_np)      # FLOAT64 carries raw bits
    if ansi_mode:
        from spark_rapids_tpu.ops.exceptions import CastException

        base = np.asarray(col.valid_mask()).astype(bool)
        bad = base & ~valid_np
        if bad.any():
            row = int(np.argmax(bad))
            raise CastException(row, col.to_pylist()[row])
        validity = col.validity
    else:
        validity = jnp.asarray(valid_np.astype(np.uint8))
    return Column(dtype, rows, data=data, validity=validity)
