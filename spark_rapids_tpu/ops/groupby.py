"""Hash group-by aggregation (the BASELINE.json config-2 workload:
"hash group-by aggregate on 1e7-row int64/float64 Table").

TPU-first design: no device hash tables — group ids come from key
canonicalization (shared with joins), and the aggregations run as
jax.ops.segment_* reductions on device, which XLA lowers to efficient
sorted-segment ops.  int64 SUM wraps on overflow (Java semantics); the
plan layer detects overflow with ops/aggregation64.py chunk sums, exactly
as the reference plugin orchestrates Aggregation64Utils around cudf sums.
Float MIN/MAX run on total-order keys so NaN ordering (largest) and
-0.0/0.0 bit patterns match Spark for both f32 and f64.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columns import dtypes
from spark_rapids_tpu.columns.column import Column
from spark_rapids_tpu.columns.dtypes import Kind
from spark_rapids_tpu.columns.table import Table
from spark_rapids_tpu.ops.copying import gather_table
from spark_rapids_tpu.ops.joins import _column_rank_host, \
    group_ids_from_ranks
from spark_rapids_tpu.utils import floats

SUM = "sum"
COUNT = "count"
MIN = "min"
MAX = "max"
MEAN = "mean"


@jax.jit
def _device_group_ids_jit(cols):
    """Device group ids over prepared key columns (shared sorted-gid
    core with the device join); first-occurrence index per group via
    segment_min.  Returns (ids int32, first_full (n,) int64 — slice
    [:ngroups] on the host, ngroups scalar)."""
    from spark_rapids_tpu.ops.joins import _sorted_gid_core

    n = cols[0].shape[0]
    order, gid_sorted = _sorted_gid_core(list(cols))
    ids = jnp.zeros(n, jnp.int64).at[order].set(gid_sorted)
    first_full = jax.ops.segment_min(jnp.arange(n, dtype=jnp.int64),
                                     ids, num_segments=n)
    return ids.astype(jnp.int32), first_full, gid_sorted[-1] + 1


def _group_ids_device(keys: Table):
    """Device branch of _group_ids (same return contract).  Key
    columns are prepared eagerly (string pad widths are
    data-dependent), the gid core is one jitted program."""
    from spark_rapids_tpu.ops.joins import _device_key_columns

    cols = _device_key_columns(keys.columns)
    ids, first_full, ng = _device_group_ids_jit(tuple(cols))
    ngroups = int(ng)
    return ids, first_full[:ngroups], ngroups


def _group_ids_host(keys: Table):
    """Host rank branch of _group_ids (all dtypes; also the executable
    oracle for the device branch's differential test)."""
    cols = []
    for c in keys.columns:
        rank, mask = _column_rank_host(c)
        # mask as its own key column: no sentinel value can collide with
        # a legal rank (e.g. -1 or INT64_MIN keys)
        cols.append(mask.astype(np.int64))
        cols.append(np.where(mask, rank, np.int64(0)))
    ids, first_idx, ngroups = group_ids_from_ranks(cols)
    return jnp.asarray(ids.astype(np.int32)), first_idx, ngroups


def _group_ids(keys: Table) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """(per-row group id (device), first-row index per group (host or
    device array), num_groups).  Nulls group together (Spark GROUP BY
    semantics).  Fixed-width keys compute ids on device on accelerator
    backends (only the group count crosses to the host);
    strings/decimal128 and the CPU backend use the host rank path."""
    import os

    from spark_rapids_tpu.ops.joins import _device_key_kind_ok

    if not keys.columns:
        return (jnp.zeros(keys.num_rows, np.int32),
                np.zeros(0, np.int64), 0)
    use_device = (jax.default_backend() != "cpu"
                  or os.environ.get(
                      "SPARK_RAPIDS_TPU_FORCE_DEVICE_GROUPBY") == "1")
    if (use_device and keys.num_rows > 0
            and all(_device_key_kind_ok(c) for c in keys.columns)):
        return _group_ids_device(keys)
    return _group_ids_host(keys)


def _value_f64(col: Column) -> jnp.ndarray:
    if col.dtype.kind == Kind.FLOAT64:
        return floats.bits_to_f64_compute(col.data)
    return col.data


def groupby_aggregate(keys: Table, values: Sequence[Column],
                      aggs: Sequence[str]) -> Table:
    """One output row per distinct key; columns = keys then one per
    (value, agg) pair.  Null values are excluded from aggregates
    (Spark semantics); count counts non-null values."""
    if len(values) != len(aggs):
        raise ValueError("values and aggs must align")
    ids, first_idx, ngroups = _group_ids(keys)
    out_keys = gather_table(keys, jnp.asarray(first_idx.astype(np.int32)))
    out_cols: List[Column] = list(out_keys.columns)
    for col, agg in zip(values, aggs):
        out_cols.append(_aggregate_one(col, agg, ids, ngroups))
    names = None
    if keys.names is not None:
        names = list(keys.names) + [f"agg{i}" for i in range(len(values))]
    return Table(out_cols, names)


def _aggregate_one(col: Column, agg: str, ids: jnp.ndarray,
                   ngroups: int) -> Column:
    kind = col.dtype.kind
    valid = col.valid_mask()
    counts = jax.ops.segment_sum(valid.astype(jnp.int64), ids, ngroups)
    if agg == COUNT:
        return Column(dtypes.INT64, ngroups, data=counts)
    is_float = kind in (Kind.FLOAT32, Kind.FLOAT64)
    x = _value_f64(col) if kind == Kind.FLOAT64 else col.data
    if agg in (SUM, MEAN):
        if is_float:
            xz = jnp.where(valid, x, 0.0)
            s = jax.ops.segment_sum(xz.astype(jnp.float64), ids, ngroups)
            if agg == MEAN:
                s = s / jnp.maximum(counts, 1).astype(jnp.float64)
            validity = (counts > 0).astype(jnp.uint8)
            if kind == Kind.FLOAT64 or agg == MEAN:
                return Column(dtypes.FLOAT64, ngroups,
                              data=floats.f64_compute_to_bits(s),
                              validity=validity)
            return Column(col.dtype, ngroups,
                          data=s.astype(jnp.float32), validity=validity)
        xz = jnp.where(valid, x.astype(jnp.int64), 0)
        s = jax.ops.segment_sum(xz, ids, ngroups)
        validity = (counts > 0).astype(jnp.uint8)
        if agg == MEAN:
            m = s.astype(jnp.float64) / jnp.maximum(counts, 1).astype(
                jnp.float64)
            return Column(dtypes.FLOAT64, ngroups,
                          data=floats.f64_compute_to_bits(m),
                          validity=validity)
        return Column(dtypes.INT64, ngroups, data=s, validity=validity)
    if agg in (MIN, MAX):
        validity = (counts > 0).astype(jnp.uint8)
        if kind == Kind.FLOAT64:
            # bit-exact via the total-order transform: min/max on keys
            key = floats.total_order_key(col.data)
            fill = jnp.int64(2**63 - 1) if agg == MIN else \
                jnp.int64(-2**63)
            kz = jnp.where(valid, key, fill)
            seg = jax.ops.segment_min if agg == MIN else \
                jax.ops.segment_max
            best = seg(kz, ids, ngroups)
            # invert the total-order transform back to raw bits
            shifted = (best + jnp.int64(2**63 - 1) + 1).astype(jnp.uint64)
            neg = (shifted >> jnp.uint64(63)) == 0
            bits = jnp.where(neg, ~shifted,
                             shifted ^ jnp.uint64(1 << 63))
            return Column(col.dtype, ngroups, data=bits,
                          validity=validity)
        if is_float:  # float32 via the 32-bit total-order transform
            from jax import lax
            bits = lax.bitcast_convert_type(x, jnp.uint32)
            negb = (bits >> jnp.uint32(31)) != 0
            flipped = jnp.where(negb, ~bits, bits | jnp.uint32(1 << 31))
            key = flipped.astype(jnp.int64)  # 0..2^32-1, NaN sorts largest
            fill = jnp.int64(2**32) if agg == MIN else jnp.int64(-1)
            kz = jnp.where(valid, key, fill)
            seg = jax.ops.segment_min if agg == MIN else \
                jax.ops.segment_max
            best = seg(kz, ids, ngroups)
            bu32 = jnp.clip(best, 0, 2**32 - 1).astype(jnp.uint32)
            neg_out = (bu32 >> jnp.uint32(31)) == 0
            outbits = jnp.where(neg_out, ~bu32,
                                bu32 ^ jnp.uint32(1 << 31))
            return Column(col.dtype, ngroups,
                          data=lax.bitcast_convert_type(outbits,
                                                        jnp.float32),
                          validity=validity)
        info = np.iinfo(col.dtype.np_dtype)
        fill = info.max if agg == MIN else info.min
        xz = jnp.where(valid, x, jnp.array(fill, x.dtype))
        seg = jax.ops.segment_min if agg == MIN else jax.ops.segment_max
        return Column(col.dtype, ngroups, data=seg(xz, ids, ngroups),
                      validity=validity)
    raise ValueError(f"unknown aggregation {agg}")
